"""Deterministic fuzz over the WHOLE L7 parser registry.

The parsers are the most attacker-facing code in the agent: every
byte of every payload a monitored network carries flows through
check()/parse(). The reference fuzzes its protocol_logs
(agent/src/flow_generator/protocol_logs/parser.rs check/parse trait
surface); this suite holds the in-tree registry to the same bar —
NO input may raise, whatever parser claims it, and every claimed
parse must return a well-formed L7Record. Coverage beyond the
HTTP-only fuzz in test_trace_context.py: all ~18 registered parsers,
cross-protocol confusion (one protocol's bytes mutated into
another's checker), truncation sweeps, and flag-byte flips on
protocol-plausible seeds."""

import random
import struct

from deepflow_tpu.agent.l7 import PARSERS, parse_payload

# protocol-plausible seeds: enough structure to get PAST check() so
# the fuzz exercises parse() bodies, not just the cheap gate
SEEDS = [
    b"GET /api/users?id=1 HTTP/1.1\r\nHost: svc\r\n"
    b"traceparent: 00-11111111111111111111111111111111-"
    b"2222222222222222-01\r\nContent-Length: 0\r\n\r\n",
    b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nok",
    # DNS query: id 1, rd, 1 question www.example.com A IN
    struct.pack(">HHHHHH", 1, 0x0100, 1, 0, 0, 0)
    + b"\x03www\x07example\x03com\x00" + struct.pack(">HH", 1, 1),
    # MySQL COM_QUERY
    struct.pack("<I", 20)[:3] + b"\x00" + b"\x03SELECT 1 FROM dual",
    # Redis inline + RESP
    b"*2\r\n$3\r\nGET\r\n$5\r\nk:123\r\n",
    b"+OK\r\n",
    # TLS ClientHello-ish record
    b"\x16\x03\x01\x00\x31" + b"\x01\x00\x00\x2d\x03\x03" + b"r" * 32
    + b"\x00" + b"\x00\x04\x13\x01\x13\x02" + b"\x01\x00",
    # HTTP/2 preface + SETTINGS
    b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
    + struct.pack(">I", 0)[1:] + b"\x04\x00" + struct.pack(">I", 0),
    # Kafka request header (api_key 0 Produce v7)
    struct.pack(">IhhI", 24, 0, 7, 9)
    + struct.pack(">h", 4) + b"cli1" + b"\x00" * 10,
    # PostgreSQL simple query
    b"Q" + struct.pack(">I", 13) + b"SELECT 1\x00",
    # MongoDB OP_MSG header
    struct.pack("<iiii", 38, 7, 0, 2013) + b"\x00"
    + b"\x15\x00\x00\x00\x02ping\x00\x02\x00\x00\x001\x00\x00",
    # Dubbo request
    b"\xda\xbb\xc2\x00" + struct.pack(">q", 1)
    + struct.pack(">i", 4) + b"\x22v2\x22",
    # MQTT CONNECT
    b"\x10\x10\x00\x04MQTT\x04\x02\x00\x3c\x00\x04cli1",
    # AMQP protocol header + frame
    b"AMQP\x00\x00\x09\x01",
    # NATS
    b"PUB subj 5\r\nhello\r\n",
    b"INFO {\"server_id\":\"x\"}\r\n",
    # OpenWire (WireFormatInfo-ish)
    struct.pack(">I", 20) + b"\x01ActiveMQ" + b"\x00" * 10,
    # FastCGI BEGIN_REQUEST
    b"\x01\x01\x00\x01\x00\x08\x00\x00\x00\x01\x00\x00\x00\x00\x00\x00",
    # SofaRPC request
    b"\x01\x00\x00\x01\x00" + struct.pack(">I", 1)
    + b"\x00\x00" + struct.pack(">h", 10) + b"\x00" * 14,
    # Oracle TNS connect
    struct.pack(">HHBB", 40, 0, 1, 0) + b"\x00" * 34,
]


def _assert_wellformed(rec):
    if rec is None:
        return
    assert isinstance(rec.proto, int)
    assert isinstance(rec.msg_type, int)
    assert isinstance(rec.status, int) and not isinstance(rec.status, bool)
    assert isinstance(rec.req_len, int) and rec.req_len >= 0
    assert isinstance(rec.resp_len, int) and rec.resp_len >= 0
    for f in ("req_type", "domain", "resource"):
        v = getattr(rec, f, "")
        assert v is None or isinstance(v, (str, bytes))


def _run_all(payload: bytes) -> None:
    for p in PARSERS:
        try:
            if p.check(payload):
                _assert_wellformed(p.parse(payload))
        except Exception as e:  # pragma: no cover - the failure itself
            raise AssertionError(
                f"{type(p).__name__} raised {type(e).__name__}: {e!r} "
                f"on {payload[:48]!r}...") from e
    _assert_wellformed(parse_payload(payload, proto=6,
                                     port_src=55555, port_dst=80))
    _assert_wellformed(parse_payload(payload, proto=17,
                                     port_src=53, port_dst=5353))


def test_seeds_reach_parse():
    """Sanity: the seeds are structured enough that a good share get
    PAST some parser's check — otherwise the fuzz only tests gates."""
    claimed = sum(1 for s in SEEDS
                  if any(p.check(s) for p in PARSERS))
    assert claimed >= len(SEEDS) * 2 // 3, claimed


def test_full_registry_never_raises_on_mutated_seeds():
    # budget sized by evidence: the ValueError('³00') int() crash
    # (Unicode-digit status line) needed ~8 flips to surface; 1-6
    # flips at 60 rounds missed it, 1-10 at 150 finds it reliably
    rng = random.Random(0xC0FFEE)
    for seed in SEEDS:
        for _ in range(150):
            buf = bytearray(seed)
            for _ in range(rng.randrange(1, 10)):
                buf[rng.randrange(len(buf))] = rng.randrange(256)
            _run_all(bytes(buf))


def test_full_registry_never_raises_on_truncations():
    for seed in SEEDS:
        for cut in range(0, min(len(seed), 48)):
            _run_all(seed[:cut])
        _run_all(seed + b"\x00" * 7)          # trailing garbage


def test_full_registry_never_raises_on_random_blobs():
    rng = random.Random(0xBADF00D)
    for _ in range(400):
        blob = bytes(rng.randrange(256)
                     for _ in range(rng.randrange(0, 300)))
        _run_all(blob)


def test_cross_protocol_confusion_never_raises():
    """One protocol's bytes spliced into another's framing: the
    classic mis-dispatch shape (a Redis banner inside a Kafka length
    prefix, HTTP inside TLS records, ...)."""
    rng = random.Random(0x5EED)
    for _ in range(200):
        a, b = rng.choice(SEEDS), rng.choice(SEEDS)
        cut_a = rng.randrange(0, len(a))
        cut_b = rng.randrange(0, len(b))
        _run_all(a[:cut_a] + b[cut_b:])
        _run_all(b[:8] + a)


def test_pathological_lengths_never_hang_or_raise():
    """Length fields set to extremes: huge claimed sizes, zero sizes,
    negative-as-unsigned. Parsers must neither raise nor allocate
    absurdly (the assert is on returning promptly and cleanly)."""
    cases = []
    for seed in SEEDS:
        if len(seed) >= 8:
            for val in (0, 0xFFFFFFFF, 0x7FFFFFFF, 1):
                buf = bytearray(seed)
                buf[:4] = struct.pack(">I", val)
                cases.append(bytes(buf))
                buf2 = bytearray(seed)
                buf2[:4] = struct.pack("<I", val)
                cases.append(bytes(buf2))
    for c in cases:
        _run_all(c)

"""SQL statement obfuscation: literals -> ?, normalized whitespace.

Reference: agent/src/flow_generator/protocol_logs/sql/sql_obfuscate.rs —
the agent ships obfuscated statements so log storage never carries bound
values (PII) and identical query shapes aggregate under one endpoint.
This is a single-pass tokenizer, not a SQL grammar: strings, numbers and
comments are recognized lexically, everything else passes through with
whitespace collapsed.
"""

from __future__ import annotations

_WS = b" \t\r\n"
_NUM_LEAD = b"0123456789"
_IDENT = (b"abcdefghijklmnopqrstuvwxyz"
          b"ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_$.")


def obfuscate_sql(stmt: bytes, max_len: int = 256) -> str:
    """Replace quoted strings and numeric literals with '?'.

    - 'single' / "double" / `backtick` quoted runs (with '' and \\'
      escapes) collapse to ?
    - numbers (ints, decimals, 0x..., exponent forms) collapse to ?,
      but identifiers keep trailing digits (tab1e2 stays)
    - -- line comments and /* block comments */ drop
    - whitespace runs collapse to one space
    """
    out = bytearray()
    i, n = 0, len(stmt)
    prev_ident = False
    while i < n and len(out) < max_len:
        c = stmt[i]
        if c in _WS:
            while i < n and stmt[i] in _WS:
                i += 1
            if out and out[-1:] != b" ":
                out += b" "
            prev_ident = False
            continue
        if c in (0x27, 0x22, 0x60):              # ' " `
            q = c
            i += 1
            while i < n:
                if stmt[i] == 0x5C and i + 1 < n:      # backslash escape
                    i += 2
                    continue
                if stmt[i] == q:
                    if i + 1 < n and stmt[i + 1] == q:  # '' doubling
                        i += 2
                        continue
                    i += 1
                    break
                i += 1
            out += b"?"
            prev_ident = False
            continue
        if stmt[i:i + 2] == b"--":
            while i < n and stmt[i] not in b"\r\n":
                i += 1
            continue
        if stmt[i:i + 2] == b"/*":
            end = stmt.find(b"*/", i + 2)
            i = n if end < 0 else end + 2
            continue
        if c in _NUM_LEAD and not prev_ident:
            i += 1
            if c == 0x30 and i < n and stmt[i] in b"xX":   # 0x...
                i += 1
                while i < n and stmt[i] in b"0123456789abcdefABCDEF":
                    i += 1
            else:
                while i < n and stmt[i] in b"0123456789.eE+-":
                    # stop +/- unless right after an exponent marker
                    if stmt[i] in b"+-" and stmt[i - 1] not in b"eE":
                        break
                    i += 1
            out += b"?"
            prev_ident = False
            continue
        out.append(c)
        prev_ident = c in _IDENT
        i += 1
    return out.decode("latin-1").strip()[:max_len]


def sql_verb(stmt: bytes) -> str:
    """Leading keyword (SELECT/INSERT/...) of a statement, uppercased."""
    s = stmt.lstrip()
    for i, ch in enumerate(s[:32]):
        if chr(ch) not in ("abcdefghijklmnopqrstuvwxyz"
                          "ABCDEFGHIJKLMNOPQRSTUVWXYZ"):
            return s[:i].decode("latin-1").upper()
    return s[:32].decode("latin-1").upper()

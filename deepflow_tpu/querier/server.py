"""Querier HTTP API (reference: server/querier/router/query.go).

POST /v1/query           body: db=<db>&sql=<sql>   (form or JSON)
GET  /api/v1/query?query=<promql>[&time=<epoch>]   (Prometheus shape)
GET  /api/v1/query_range?query=&start=&end=&step=  (Prometheus matrix)
GET  /v1/profile/flame[?app_service=&event_type=&start=&end=]
GET  /v1/profile/top[?...same...&limit=]
GET  /health

Stdlib ThreadingHTTPServer: the query path is read-only over immutable
segments, so handlers are safely concurrent with ingest.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from deepflow_tpu.querier.engine import QueryEngine
from deepflow_tpu.querier.profile import ProfileQuery
from deepflow_tpu.querier.promql import PromEngine
from deepflow_tpu.store.db import Store
from deepflow_tpu.store.dict_store import TagDictRegistry

DEFAULT_PORT = 20416   # reference querier listens on 20416


class QuerierServer:
    def __init__(self, store: Store, tag_dicts: TagDictRegistry,
                 port: int = DEFAULT_PORT, host: str = "127.0.0.1",
                 tagrecorder=None) -> None:
        self.engine = QueryEngine(store, tag_dicts, tagrecorder=tagrecorder)
        self.prom = PromEngine(store, tag_dicts)
        self.profile = ProfileQuery(store, tag_dicts)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:
                url = urllib.parse.urlparse(self.path)
                if url.path == "/health":
                    self._send(200, {"status": "ok"})
                    return
                if url.path == "/api/v1/query":
                    qs = urllib.parse.parse_qs(url.query)
                    try:
                        result = outer.prom.query(
                            qs["query"][0],
                            at=int(qs["time"][0]) if "time" in qs else None)
                        self._send(200, {"status": "success",
                                         "data": {"resultType": "vector",
                                                  "result": result}})
                    except Exception as e:
                        self._send(400, {"status": "error", "error": str(e)})
                    return
                if url.path == "/api/v1/query_range":
                    qs = urllib.parse.parse_qs(url.query)
                    try:
                        result = outer.prom.query_range(
                            qs["query"][0], start=int(float(qs["start"][0])),
                            end=int(float(qs["end"][0])),
                            step=int(float(qs["step"][0])))
                        self._send(200, {"status": "success",
                                         "data": {"resultType": "matrix",
                                                  "result": result}})
                    except Exception as e:
                        self._send(400, {"status": "error", "error": str(e)})
                    return
                if url.path in ("/v1/profile/flame", "/v1/profile/top"):
                    qs = urllib.parse.parse_qs(url.query)

                    def one(key):
                        return qs[key][0] if key in qs else None

                    try:
                        tr = None
                        if "start" in qs and "end" in qs:
                            tr = (int(qs["start"][0]), int(qs["end"][0]))
                        if url.path.endswith("flame"):
                            res = outer.profile.flame(
                                app_service=one("app_service"),
                                event_type=one("event_type"), time_range=tr)
                        else:
                            res = outer.profile.top_functions(
                                app_service=one("app_service"),
                                event_type=one("event_type"), time_range=tr,
                                limit=int(one("limit") or 50))
                        self._send(200, {"result": res})
                    except Exception as e:
                        self._send(400, {"error": str(e)})
                    return
                self._send(404, {"error": "not found"})

            def do_POST(self) -> None:
                url = urllib.parse.urlparse(self.path)
                if url.path != "/v1/query":
                    self._send(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(length).decode()
                    ctype = self.headers.get("Content-Type", "")
                    if "json" in ctype:
                        params = json.loads(raw or "{}")
                    else:
                        params = {k: v[0] for k, v in
                                  urllib.parse.parse_qs(raw).items()}
                    res = outer.engine.execute(params.get("sql", ""),
                                               db=params.get("db") or None)
                    self._send(200, {"result": res.as_dict()})
                except Exception as e:
                    self._send(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="querier-http", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2)

"""The doc-drift rule (ISSUE 14 satellite): the README's knob and
gauge tables must keep up with the code.

Two contracts rot silently today: `IngesterConfig` grows a knob nobody
documents (operators discover it by reading the dataclass), and
`tracing.GAUGE_HELP` grows a gauge whose README row never lands (the
/metrics HELP string exists, the operator-facing table lies by
omission). This rule closes both: every `IngesterConfig` field and
every `GAUGE_HELP` key must appear — as a word — somewhere in the
README the scan was given (`ProjectIndex.doc_text`; the runner loads
the repo README.md, fixtures pass their own). A knob or gauge added
without its doc row is a finding at the definition line, pragma-able
and SARIF-emitted like every other rule.

Scope is deliberately the two declared registries, not every dataclass
in the tree: these are the operator-facing surfaces the README already
tables; a generic "document everything" rule would be pragma'd into
uselessness on day one.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional, Set

from deepflow_tpu.analysis.core import (Checker, FileContext, Finding,
                                        ProjectIndex, register)

__all__ = ["DocDrift"]

_CONFIG_SUFFIX = "pipelines/ingester.py"
_CONFIG_CLASS = "IngesterConfig"
_GAUGE_SUFFIX = "runtime/tracing.py"
_GAUGE_TABLES = ("GAUGE_HELP", "GAUGE_HELP_PREFIXES")


def _doc_words(doc: str) -> Set[str]:
    """Identifier-shaped words in the doc — the membership test. A
    name inside backticks, a table row, or dotted prose
    (`IngesterConfig.prefetch_depth`) all tokenize to the bare word."""
    return set(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", doc))


@register
class DocDrift(Checker):
    """An operator-facing registry entry (IngesterConfig knob /
    GAUGE_HELP gauge) with no row in the README. The doc tables are a
    contract with operators the same way the exposition HELP strings
    are a contract with scrapers."""

    name = "doc-drift"
    description = ("IngesterConfig knob or tracing.GAUGE_HELP gauge "
                   "absent from the README knob/gauge tables — "
                   "document the new name or it never existed for "
                   "operators")

    def check(self, ctx: FileContext,
              index: ProjectIndex) -> Iterable[Finding]:
        if index.doc_text is None:
            return               # no doc in scope: fixture scans stay silent
        is_cfg = ctx.path.endswith(_CONFIG_SUFFIX)
        is_gauge = ctx.path.endswith(_GAUGE_SUFFIX)
        if not (is_cfg or is_gauge):
            return
        words = index.memo.get("doc_words")
        if words is None:
            words = _doc_words(index.doc_text)
            index.memo["doc_words"] = words
        if is_cfg:
            yield from self._check_config(ctx, words)
        if is_gauge:
            yield from self._check_gauges(ctx, words)

    def _check_config(self, ctx: FileContext,
                      words: Set[str]) -> Iterable[Finding]:
        for node in ctx.tree.body:
            if not (isinstance(node, ast.ClassDef)
                    and node.name == _CONFIG_CLASS):
                continue
            for item in node.body:
                if not (isinstance(item, ast.AnnAssign)
                        and isinstance(item.target, ast.Name)):
                    continue
                name = item.target.id
                if name.startswith("_") or name in words:
                    continue
                yield self.finding(
                    ctx, item,
                    f"{_CONFIG_CLASS}.{name} has no row in the README "
                    f"knob table — operators cannot discover a knob "
                    f"that is only a dataclass field")

    def _check_gauges(self, ctx: FileContext,
                      words: Set[str]) -> Iterable[Finding]:
        for node in ctx.tree.body:
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id in _GAUGE_TABLES):
                continue
            value = node.value
            if not isinstance(value, ast.Dict):
                continue
            for key in value.keys:
                if not (isinstance(key, ast.Constant)
                        and isinstance(key.value, str)):
                    continue
                # prefix families document their stem (the trailing _
                # never reads as part of the word)
                name = key.value.rstrip("_")
                if not name or name in words:
                    continue
                yield self.finding(
                    ctx, key,
                    f"gauge '{key.value}' (tracing.GAUGE_HELP) has no "
                    f"row in the README gauge tables — it scrapes "
                    f"with HELP text but operators reading the doc "
                    f"never learn it exists")

"""UniformSender: framed record batches -> ingester TCP firehose.

Reference: agent/src/sender/uniform_sender.rs — one sender per message
type, batching pb records under BaseHeader+FlowHeader frames with a
per-type sequence counter, reconnecting TCP. The framing/codec modules
are shared with the server side, so this is the thin socket half.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import List, Optional

from deepflow_tpu.wire.codec import pack_pb_records
from deepflow_tpu.wire.framing import (MESSAGE_FRAME_SIZE_MAX, FlowHeader,
                                       MessageType, encode_frame)

# keep payloads comfortably under the wire max
_BATCH_BYTES = MESSAGE_FRAME_SIZE_MAX - 4096


class UniformSender:
    """One message type, one connection, sequenced frames."""

    def __init__(self, msg_type: MessageType, addr: str, vtap_id: int = 0,
                 reconnect_interval: float = 2.0) -> None:
        self.msg_type = msg_type
        host, _, port = addr.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.vtap_id = vtap_id
        self.reconnect_interval = reconnect_interval
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        self._lock = threading.Lock()
        self._last_attempt = 0.0
        self.sent_frames = 0
        self.sent_records = 0
        self.dropped_records = 0

    def set_target(self, addr: str) -> None:
        """Re-point at a different ingester (controller rebalancing)."""
        host, _, port = addr.rpartition(":")
        with self._lock:
            if (host or "127.0.0.1", int(port)) == (self.host, self.port):
                return
            self.host, self.port = host or "127.0.0.1", int(port)
            self._close_locked()

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _connect_locked(self) -> bool:
        if self._sock is not None:
            return True
        now = time.time()
        if now - self._last_attempt < self.reconnect_interval:
            return False
        self._last_attempt = now
        try:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=5)
            return True
        except OSError:
            self._sock = None
            return False

    def send(self, records: List[bytes]) -> int:
        """Frame + send; returns records sent (drops on no connection —
        the reference's queues also shed under backpressure, observably)."""
        if not records:
            return 0
        sent = 0
        with self._lock:
            if not self._connect_locked():
                self.dropped_records += len(records)
                return 0
            batch: List[bytes] = []
            size = 0
            for rec in records + [None]:
                if rec is not None and size + len(rec) + 4 < _BATCH_BYTES:
                    batch.append(rec)
                    size += len(rec) + 4
                    continue
                if batch:
                    self._seq += 1
                    frame = encode_frame(
                        self.msg_type, pack_pb_records(batch),
                        FlowHeader(sequence=self._seq,
                                   vtap_id=self.vtap_id))
                    try:
                        self._sock.sendall(frame)
                        sent += len(batch)
                        self.sent_frames += 1
                    except OSError:
                        self._close_locked()
                        self.dropped_records += len(records) - sent
                        break
                batch, size = ([rec], len(rec) + 4) if rec is not None \
                    else ([], 0)
        self.sent_records += sent
        return sent

    def send_columns(self, cols, schema) -> int:
        """Send column arrays as planar COLUMNAR_FLOW payloads (the
        TPU-native wire mode: no per-row protobuf serialization on the
        agent, no varint walk on the server — wire/columnar_wire.py).
        Chunks rows so each frame stays under the wire max. Returns rows
        sent."""
        from deepflow_tpu.wire import columnar_wire

        n = len(next(iter(cols.values())))
        if n == 0:
            return 0
        rows_per_frame = max(1, (_BATCH_BYTES - columnar_wire.HEADER_LEN)
                             // schema.row_bytes())
        sent = 0
        for lo in range(0, n, rows_per_frame):
            hi = min(lo + rows_per_frame, n)
            chunk = {k: v[lo:hi] for k, v in cols.items()}
            if self.send_raw(columnar_wire.encode_columnar(chunk, schema)):
                sent += hi - lo
        return sent

    def send_raw_batch(self, payloads: List[bytes]) -> int:
        """Concatenate self-delimited payloads (packet-sequence blocks:
        each leads with its own u32 size) into as few raw frames as fit
        under the frame budget; returns payloads sent."""
        sent = 0
        batch: List[bytes] = []
        size = 0
        for p in payloads + [None]:
            if p is not None and size + len(p) < _BATCH_BYTES:
                batch.append(p)
                size += len(p)
                continue
            if batch and self.send_raw(b"".join(batch)):
                sent += len(batch)
            batch, size = (([p], len(p)) if p is not None else ([], 0))
        return sent

    def send_raw(self, payload: bytes) -> bool:
        """Frame one raw payload as-is (streams whose frame body is a
        single message — OTel exports, influx text — rather than a
        length-prefixed record batch)."""
        if len(payload) >= _BATCH_BYTES:
            self.dropped_records += 1
            return False
        with self._lock:
            if not self._connect_locked():
                self.dropped_records += 1
                return False
            self._seq += 1
            frame = encode_frame(self.msg_type, payload,
                                 FlowHeader(sequence=self._seq,
                                            vtap_id=self.vtap_id))
            try:
                self._sock.sendall(frame)
                self.sent_frames += 1
                self.sent_records += 1
                return True
            except OSError:
                self._close_locked()
                self.dropped_records += 1
                return False

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def counters(self) -> dict:
        return {"sent_frames": self.sent_frames,
                "sent_records": self.sent_records,
                "dropped_records": self.dropped_records}

"""Pallas VMEM-resident histogram vs the XLA scan path: identical
outputs on every shape the sketches use (interpret mode on CPU; the
real-chip perf comparison lives in benches/kernel_bench.py)."""

import numpy as np
import pytest

import jax.numpy as jnp

from deepflow_tpu.ops.mxu_hist import hist
from deepflow_tpu.ops.pallas_hist import hist_pallas


@pytest.mark.parametrize("width,d,n", [
    (1 << 16, 4, 50_000),       # CMS: depth 4, 2^16 counters
    (1 << 12, 4, 20_000),       # entropy buckets
    (1024 * 512, 1, 30_000),    # DDSketch flat (groups x buckets)
])
def test_matches_xla_path(width, d, n):
    rng = np.random.default_rng(width % 97)
    idx = jnp.asarray(rng.integers(0, width, (d, n), dtype=np.int32))
    w = jnp.asarray(rng.integers(0, 3000, n, dtype=np.int32))
    for weights in (None, w):
        a = hist(idx, width, weights, method="xla")
        b = hist_pallas(idx, width, weights, interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_saturation_and_padding():
    # weights above the plane range saturate identically; n not a
    # multiple of chunk exercises the zero-weight pad rows
    idx = jnp.asarray(np.zeros((2, 4097), np.int32))
    w = jnp.asarray(np.full(4097, 1 << 20, np.int32))
    a = hist(idx, 1 << 16, w, method="xla")
    b = hist_pallas(idx, 1 << 16, w, interpret=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the true sum (~2^28) exceeds f32's exact-integer range; both
    # paths round identically (checked above), value is approximate
    assert float(a[0, 0]) == pytest.approx(4097 * (256 ** 2 - 1),
                                           rel=1e-6)


def test_method_dispatch(monkeypatch):
    idx = jnp.asarray(np.random.default_rng(0).integers(
        0, 1 << 16, (4, 9000), dtype=np.int32))
    out_x = hist(idx, 1 << 16, method="xla")
    out_p = hist(idx, 1 << 16, method="pallas")   # interpret on CPU
    np.testing.assert_array_equal(np.asarray(out_x), np.asarray(out_p))
    # auto on CPU stays on the XLA path regardless of the env opt-in
    monkeypatch.setenv("DEEPFLOW_HIST_PALLAS", "1")
    out_a = hist(idx, 1 << 16, method="auto")
    np.testing.assert_array_equal(np.asarray(out_x), np.asarray(out_a))

"""DeepFlow-SQL parser: a small recursive-descent front end.

Supports the query shapes the reference querier serves from Grafana
(engine/clickhouse/clickhouse.go TransSelect/TransWhere/TransGroupBy):

    SELECT * | <expr> [AS alias], ... FROM <table>
      [WHERE <cond> [AND <cond>]...]
      [GROUP BY col, ...] [HAVING <cond> [AND ...]]
      [ORDER BY key [ASC|DESC], ...] [LIMIT n]
    SHOW DATABASES | SHOW TABLES [FROM db] |
    SHOW TAGS FROM <table> | SHOW METRICS FROM <table> |
    SHOW TAG <tag> VALUES FROM <table> [LIMIT n]

Expressions: columns, integer/float/string literals, aggregate calls
(Sum/Min/Max/Avg/Count, Percentile(col, p), PerSecond(expr) — the
reference's TransMetricFunc function set), and +,-,*,/ arithmetic over
them (derived metrics like Sum(retrans)/Sum(packet_tx)). Conditions:
=, !=, <, <=, >, >=, IN/NOT IN (...), LIKE/NOT LIKE ('%' and '_'
wildcards on dictionary-backed columns), REGEXP, combined with
AND/OR/NOT and parentheses (full boolean trees; time-range pruning
reads the top-level conjuncts). The reference's sqlparser fork
(querier/parse/parse.go) plays this role; a hand-rolled parser keeps
the dependency surface zero.

Time bucketing: `time(N)` (alias `interval(N)`) may appear in GROUP BY
and in the select list — the reference's TransGroupBy interval grouping
(engine/clickhouse/clickhouse.go:816-1088 lowers it to
toStartOfInterval); here it floors the table's time column to N-second
buckets so timeseries panels can be driven straight from SQL.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

AGG_FUNCS = {"sum", "min", "max", "avg", "count"}

_TOKEN = re.compile(r"""
    \s*(
        '(?:[^'\\]|\\.)*'        # string literal
      | [A-Za-z_][A-Za-z0-9_.]*  # ident (may be db.table)
      | \d+\.\d+ | \d+           # number
      | != | <= | >= | [(),=<>*+/-]
    )""", re.VERBOSE)


def tokenize(s: str) -> List[str]:
    out, pos = [], 0
    while pos < len(s):
        m = _TOKEN.match(s, pos)
        if not m:
            if s[pos:].strip() == "":
                break
            raise ValueError(f"bad token at: {s[pos:pos+20]!r}")
        out.append(m.group(1))
        pos = m.end()
    return out


# -- AST -------------------------------------------------------------------
@dataclass(frozen=True)
class Column:
    name: str


@dataclass(frozen=True)
class Literal:
    value: Union[int, float, str]


@dataclass(frozen=True)
class Agg:
    func: str                 # sum|min|max|avg|count|percentile
    arg: Optional["Expr"]     # None for Count(*)
    param: Optional[float] = None   # Percentile(col, p)'s p


@dataclass(frozen=True)
class IntervalRef:
    """PerSecond()'s divisor: the GROUP BY time-bucket width, or the
    query's WHERE time span (reference: engine/clickhouse metrics
    TransMetricFunc lowers PerSecond to value/interval)."""


@dataclass(frozen=True)
class BinOp:
    op: str                   # + - * /
    left: "Expr"
    right: "Expr"


@dataclass(frozen=True)
class TimeBucket:
    """time(N) / interval(N): the table's time column floored to
    N-second buckets. Output column name defaults to `time`."""
    seconds: int


@dataclass(frozen=True)
class QualifiedFunc:
    """A dotted function call — ``sketch.topk(10)``,
    ``sketch.cms_point(key)`` — the virtual-datasource surface (ISSUE
    7's sketch tables). The parser stays generic: it records the dotted
    name plus LITERAL arguments; the owning datasource interprets them
    (serving/tables.py for the ``sketch.*`` family)."""
    name: str
    args: Tuple[Union[int, float, str], ...] = ()


Expr = Union[Column, Literal, Agg, BinOp, TimeBucket, IntervalRef,
             QualifiedFunc]


@dataclass(frozen=True)
class Cond:
    column: str
    op: str         # = != < <= > >= in not_in like not_like regexp
    value: Union[int, float, str, Tuple]


@dataclass(frozen=True)
class BoolOp:
    """WHERE boolean tree node. Select.where is a top-level AND list;
    OR/NOT subtrees appear as BoolOp entries (so time-range pruning
    keeps working off the top-level conjuncts)."""
    op: str                   # "and" | "or" | "not"
    children: Tuple           # Cond | BoolOp


WhereNode = Union[Cond, BoolOp]


@dataclass(frozen=True)
class SelectItem:
    expr: Expr
    alias: Optional[str]


@dataclass(frozen=True)
class Select:
    items: List[SelectItem]
    table: str
    where: List[Cond] = field(default_factory=list)
    # column names, plus at most one TimeBucket for interval grouping
    group_by: List[Union[str, TimeBucket]] = field(default_factory=list)
    # [(alias/col, desc), ...] — primary key first
    order_by: List[Tuple[str, bool]] = field(default_factory=list)
    limit: Optional[int] = None
    # post-aggregation conditions on output column names/aliases
    having: List[Cond] = field(default_factory=list)
    offset: int = 0


@dataclass(frozen=True)
class Show:
    what: str                 # databases|tables|tags|metrics|tag_values
    table: Optional[str] = None
    tag: Optional[str] = None            # SHOW TAG <tag> VALUES FROM t
    limit: Optional[int] = None


@dataclass(frozen=True)
class JoinSelect:
    """The final SELECT of a WITH query: two CTE results joined on an
    equality conjunction (the reference's Grafana multi-metric panel
    shape, clickhouse_test.go:452)."""
    items: List[SelectItem]          # qualified Column("q1.x") refs
    left: str
    right: str
    join_type: str                   # left | inner
    on: List[Tuple[str, str]]        # (left col, right col) pairs
    order_by: List[Tuple[str, bool]] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0


@dataclass(frozen=True)
class With:
    ctes: List[Tuple[str, Select]]
    select: JoinSelect


Statement = Union[Select, Show, With]


def expr_columns(expr: Expr) -> set:
    """Column names referenced anywhere in an expression tree."""
    if isinstance(expr, Column):
        return {expr.name}
    if isinstance(expr, Agg):
        return expr_columns(expr.arg) if expr.arg is not None else set()
    if isinstance(expr, BinOp):
        return expr_columns(expr.left) | expr_columns(expr.right)
    return set()


class _Parser:
    def __init__(self, tokens: List[str]) -> None:
        self.toks = tokens
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> str:
        t = self.peek()
        if t is None:
            raise ValueError("unexpected end of query")
        self.i += 1
        return t

    def expect(self, word: str) -> None:
        t = self.next()
        if t.lower() != word.lower():
            raise ValueError(f"expected {word!r}, got {t!r}")

    def accept(self, word: str) -> bool:
        if (self.peek() or "").lower() == word.lower():
            self.i += 1
            return True
        return False

    # -- expressions -------------------------------------------------------
    def parse_expr(self) -> Expr:
        left = self.parse_term()
        while self.peek() in ("+", "-"):
            op = self.next()
            left = BinOp(op, left, self.parse_term())
        return left

    def parse_term(self) -> Expr:
        left = self.parse_atom()
        while self.peek() in ("*", "/"):
            op = self.next()
            left = BinOp(op, left, self.parse_atom())
        return left

    def parse_atom(self) -> Expr:
        t = self.next()
        if t == "(":
            e = self.parse_expr()
            self.expect(")")
            return e
        if t.startswith("'"):
            return Literal(t[1:-1])
        if re.fullmatch(r"\d+", t):
            return Literal(int(t))
        if re.fullmatch(r"\d+\.\d+", t):
            return Literal(float(t))
        if t.lower() in ("time", "interval") and self.peek() == "(":
            return self._time_bucket()
        if t.lower() == "percentile" and self.peek() == "(":
            self.next()
            arg = self.parse_expr()
            self.expect(",")
            p = self._value(self.next())
            self.expect(")")
            if not isinstance(p, (int, float)) or not 0 <= p <= 100:
                raise ValueError(f"Percentile needs 0..100, got {p!r}")
            return Agg("percentile", arg, float(p))
        if t.lower() == "persecond" and self.peek() == "(":
            # PerSecond(expr) = expr / the query interval (time-bucket
            # width under interval grouping, else the WHERE time span)
            self.next()
            arg = self.parse_expr()
            self.expect(")")
            return BinOp("/", arg, IntervalRef())
        if t.lower() in AGG_FUNCS and self.peek() == "(":
            self.next()
            if self.accept("*"):
                self.expect(")")
                return Agg(t.lower(), None)
            arg = self.parse_expr()
            self.expect(")")
            return Agg(t.lower(), arg)
        if not re.fullmatch(r"[A-Za-z_][A-Za-z0-9_.]*", t):
            raise ValueError(f"unexpected token {t!r}")
        if "." in t and self.peek() == "(":
            # dotted function call (sketch.topk(10)-style): literal
            # arguments only — the datasource that owns the namespace
            # validates names/arity (engine._select routes by table)
            self.next()
            args = []
            if not self.accept(")"):
                args.append(self._value(self.next()))
                while self.accept(","):
                    args.append(self._value(self.next()))
                self.expect(")")
            return QualifiedFunc(t.lower(), tuple(args))
        return Column(t)

    # -- clauses -----------------------------------------------------------
    def parse_select(self, stop_at_paren: bool = False) -> Select:
        items = []
        if self.accept("*"):
            # SELECT *: expanded to the table's columns by the engine
            # (which knows the schema); must be the only select item
            items.append(SelectItem(Column("*"), None))
        else:
            while True:
                e = self.parse_expr()
                alias = None
                if self.accept("as"):
                    alias = self.next()
                items.append(SelectItem(e, alias))
                if not self.accept(","):
                    break
        self.expect("from")
        table = self.next()
        where: List[Cond] = []
        group_by: List[str] = []
        order_by: List[Tuple[str, bool]] = []
        limit = None
        if self.accept("where"):
            where = self.parse_bool()
        if self.accept("group"):
            self.expect("by")
            group_by.append(self._group_item())
            while self.accept(","):
                group_by.append(self._group_item())
            if sum(isinstance(g, TimeBucket) for g in group_by) > 1:
                raise ValueError("at most one time()/interval() bucket "
                                 "per GROUP BY")
        having: List[Cond] = []
        if self.accept("having"):
            having.append(self.parse_cond())
            while self.accept("and"):
                having.append(self.parse_cond())
        order_by, limit, offset = self._order_limit_tail()
        if not stop_at_paren and self.peek() is not None:
            raise ValueError(f"trailing tokens at {self.peek()!r}")
        return Select(items, table, where, group_by, order_by, limit,
                      having, offset)

    def _time_bucket(self) -> TimeBucket:
        self.expect("(")
        t = self.next()
        if not re.fullmatch(r"\d+", t) or int(t) <= 0:
            raise ValueError(f"time() needs a positive interval in "
                             f"seconds, got {t!r}")
        self.expect(")")
        return TimeBucket(int(t))

    def _group_item(self) -> Union[str, TimeBucket]:
        t = self.next()
        if t.lower() in ("time", "interval") and self.peek() == "(":
            return self._time_bucket()
        return t

    def parse_with(self) -> "With":
        ctes: List[Tuple[str, Select]] = []
        seen = set()
        while True:
            name = self.next()
            if name in seen:
                raise ValueError(f"duplicate CTE name {name!r}")
            seen.add(name)
            self.expect("as")
            self.expect("(")
            self.expect("select")
            ctes.append((name, self.parse_select(stop_at_paren=True)))
            self.expect(")")
            if not self.accept(","):
                break
        self.expect("select")
        items = []
        while True:
            e = self.parse_expr()
            if not isinstance(e, Column) or "." not in e.name:
                raise ValueError("the joined SELECT takes qualified "
                                 "columns (query1.col [AS alias])")
            alias = self.next() if self.accept("as") else None
            items.append(SelectItem(e, alias))
            if not self.accept(","):
                break
        self.expect("from")
        left = self.next()
        join_type = "inner"
        if self.accept("left"):
            join_type = "left"
        elif self.accept("inner"):
            pass
        self.expect("join")
        right = self.next()
        self.expect("on")
        on: List[Tuple[str, str]] = []
        while True:
            a = self.next()
            self.expect("=")
            b = self.next()
            for side in (a, b):
                if "." not in side:
                    raise ValueError(f"ON needs qualified columns, "
                                     f"got {side!r}")
            # normalize so the left CTE's column comes first
            la, ca = a.split(".", 1)
            lb, cb = b.split(".", 1)
            if la == left and lb == right:
                on.append((ca, cb))
            elif la == right and lb == left:
                on.append((cb, ca))
            else:
                raise ValueError(f"ON references unknown query "
                                 f"names: {a} = {b}")
            if not self.accept("and"):
                break
        order_by, limit, offset = self._order_limit_tail()
        if self.peek() is not None:
            raise ValueError(f"trailing tokens at {self.peek()!r}")
        names = {n for n, _ in ctes}
        if left not in names or right not in names:
            raise ValueError(f"JOIN references undefined query "
                             f"({left}, {right})")
        return With(ctes, JoinSelect(items, left, right, join_type, on,
                                     order_by, limit, offset))

    def _order_limit_tail(self):
        """The shared `ORDER BY k [ASC|DESC], ... LIMIT n` clause tail
        (plain selects and joined WITH-selects parse it identically)."""
        order_by: List[Tuple[str, bool]] = []
        if self.accept("order"):
            self.expect("by")
            while True:
                key = self.next()
                desc = False
                if self.accept("desc"):
                    desc = True
                elif self.accept("asc"):
                    pass
                order_by.append((key, desc))
                if not self.accept(","):
                    break
        limit = None
        offset = 0
        if self.accept("limit"):
            limit = int(self.next())
            if self.accept("offset"):
                offset = int(self.next())
        return order_by, limit, offset

    def parse_bool(self) -> List[WhereNode]:
        """WHERE tree, precedence OR < AND < NOT < atom; returns the
        top-level AND conjunct list (time pruning reads it directly)."""
        node = self._bool_or()
        if isinstance(node, BoolOp) and node.op == "and":
            return list(node.children)
        return [node]

    def _bool_or(self) -> WhereNode:
        left = self._bool_and()
        branches = [left]
        while self.accept("or"):
            branches.append(self._bool_and())
        if len(branches) == 1:
            return left
        return BoolOp("or", tuple(branches))

    def _bool_and(self) -> WhereNode:
        left = self._bool_not()
        parts = [left]
        while self.accept("and"):
            parts.append(self._bool_not())
        if len(parts) == 1:
            return left
        # flatten nested ANDs so parse_bool's top-level list is maximal
        flat: List[WhereNode] = []
        for p in parts:
            if isinstance(p, BoolOp) and p.op == "and":
                flat.extend(p.children)
            else:
                flat.append(p)
        return BoolOp("and", tuple(flat))

    def _bool_not(self) -> WhereNode:
        if self.accept("not"):
            return BoolOp("not", (self._bool_not(),))
        if self.peek() == "(":
            # lookahead: '(' here is a boolean group, because a
            # condition atom always starts with a column name
            self.next()
            inner = self._bool_or()
            self.expect(")")
            return inner
        return self.parse_cond()

    def parse_cond(self) -> Cond:
        col = self.next()
        op = self.next().lower()
        negate = False
        if op == "not":
            negate = True
            op = self.next().lower()
            if op not in ("in", "like"):
                raise ValueError(f"bad operator NOT {op!r}")
        if op == "in":
            self.expect("(")
            vals = [self._value(self.next())]
            while self.accept(","):
                vals.append(self._value(self.next()))
            self.expect(")")
            return Cond(col, "not_in" if negate else "in", tuple(vals))
        if op == "like":
            v = self._value(self.next())
            if not isinstance(v, str):
                raise ValueError("LIKE needs a string pattern")
            return Cond(col, "not_like" if negate else "like", v)
        if op == "regexp":
            v = self._value(self.next())
            if not isinstance(v, str):
                raise ValueError("REGEXP needs a string pattern")
            return Cond(col, "regexp", v)
        if op not in ("=", "!=", "<", "<=", ">", ">="):
            raise ValueError(f"bad operator {op!r}")
        return Cond(col, op, self._value(self.next()))

    @staticmethod
    def _value(t: str) -> Union[int, float, str]:
        if t.startswith("'"):
            return t[1:-1]
        if re.fullmatch(r"\d+", t):
            return int(t)
        if re.fullmatch(r"\d+\.\d+", t):
            return float(t)
        raise ValueError(f"bad literal {t!r}")


def parse_sql(sql: str) -> Statement:
    toks = tokenize(sql)
    p = _Parser(toks)
    head = p.next().lower()
    if head == "select":
        return p.parse_select()
    if head == "with":
        return p.parse_with()
    if head == "show":
        what = p.next().lower()
        if what == "databases":
            return Show("databases")
        if what == "tables":
            table = None
            if p.accept("from"):
                table = p.next()
            return Show("tables", table)
        if what in ("tags", "metrics"):
            p.expect("from")
            return Show(what, p.next())
        if what == "tag":
            # show tag <name> values from <table> [limit n] — the
            # Grafana variable-dropdown query (clickhouse.go:53)
            tag = p.next()
            p.expect("values")
            p.expect("from")
            table = p.next()
            limit = None
            if p.accept("limit"):
                limit = int(p.next())
            if p.peek() is not None:
                raise ValueError(f"trailing tokens at {p.peek()!r}")
            return Show("tag_values", table, tag=tag, limit=limit)
        raise ValueError(f"SHOW {what} not supported")
    raise ValueError(f"unsupported statement {head!r}")

"""QingCloud client: the iaas RPC protocol from scratch.

Reference: server/controller/cloud/qingcloud/ — qingcloud.go:138-185:
every call is a GET against `/iaas/` whose SORTED query (values
url-escaped with '+' as %20) is signed as
base64(HMAC-SHA256(secret, "GET\\n/iaas/\\n" + query)), the signature
itself url-escaped and appended; offset/limit paging driven by
total_count (GetResponse:195-230). QingCloud's resource model quirk,
kept faithfully: VPCs are ROUTERS (vpc.go reads router_id/router_name
from DescribeRouters), subnets are VXNETS (network.go: vxnet_id, cidr
from the attached router's ip_network), zones are the region axis
(region.go DescribeZones), and instances carry their vxnets inline
(vm.go:175+). Fifth vendor, fifth signature dialect (sorted-query
HMAC-SHA256 with escaped-signature transport).

Emits the same normalized region/az/vpc/subnet/vm rows as the rest.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Sequence

from deepflow_tpu.controller.cloud import (ResourceBuilder,
                                           add_vm_public_addresses)
from deepflow_tpu.controller.model import Resource

PAGE_LIMIT = 100


def _escape(v: object) -> str:
    """quote with '+' normalized to %20 (qingcloud.go:174-176)."""
    return urllib.parse.quote(str(v), safe="").replace("+", "%20")


def signed_query(params: Dict[str, object], secret: str) -> str:
    """Sorted canonical query + the url-escaped base64 HMAC-SHA256
    signature over "GET\\n/iaas/\\n" + query."""
    parts = [f"{k}={_escape(v) if isinstance(v, str) else v}"
             for k, v in sorted(params.items())]
    qs = "&".join(parts)
    sts = "GET\n/iaas/\n" + qs
    sig = base64.b64encode(hmac.new(secret.encode(), sts.encode(),
                                    hashlib.sha256).digest()).decode()
    return f"{qs}&signature={urllib.parse.quote(sig, safe='')}"


class QingCloudPlatform:
    """Same duck type as the other vendor drivers; `url` is the API
    base (the reference's q.url), `/iaas/` appended per call."""

    def __init__(self, domain: str, secret_id: str, secret_key: str,
                 url: str = "https://api.qingcloud.com",
                 zones: Optional[Sequence[str]] = None) -> None:
        self.domain = domain
        self.secret_id = secret_id
        self.secret_key = secret_key
        self.url = url.rstrip("/")
        self.include_zones = tuple(zones) if zones else ()

    # -- wire --------------------------------------------------------------
    def _page(self, action: str, offset: int,
              extra: Dict[str, object]) -> dict:
        params: Dict[str, object] = {
            "access_key_id": self.secret_id,
            "action": action,
            "limit": PAGE_LIMIT,
            "offset": offset,
            "signature_method": "HmacSHA256",
            "signature_version": 1,
            "time_stamp": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime()),
            "version": 1,
        }
        # verbose=2 everywhere the reference sends it
        # (qingcloud.go:159-162's exclusion list)
        if action not in ("DescribeClusters",
                          "DescribeLoadBalancerListeners",
                          "DescribeRouters"):
            params["verbose"] = 2
        params.update(extra)
        q = signed_query(params, self.secret_key)
        with urllib.request.urlopen(f"{self.url}/iaas/?{q}",
                                    timeout=30) as r:
            return json.load(r)

    def get_response(self, action: str, result_key: str,
                     **extra) -> List[dict]:
        """offset/limit until total_count rows collected
        (GetResponse's loop); a missing result key is an API error."""
        out: List[dict] = []
        offset = 0
        for _ in range(1000):
            doc = self._page(action, offset, extra)
            if result_key not in doc:
                raise RuntimeError(
                    f"qingcloud {action}: ret_code="
                    f"{doc.get('ret_code')} {doc.get('message', '')}")
            rows = doc[result_key]
            out.extend(rows)
            total = int(doc.get("total_count", len(out)))
            if not rows or len(out) >= total:
                break
            offset += len(rows)
        return out

    # -- api ---------------------------------------------------------------
    def check_auth(self) -> None:
        self.get_response("DescribeZones", "zone_set")

    def get_cloud_data(self) -> List[Resource]:
        b = ResourceBuilder(self.domain)
        add = b.add

        region_id = add("region", "qingcloud", "qingcloud")
        zones = [z.get("zone_id", "") for z in
                 self.get_response("DescribeZones", "zone_set")
                 if z.get("status", "active") == "active"]
        zones = [z for z in zones if z]
        if self.include_zones:
            zones = [z for z in zones if z in self.include_zones]
        for zone in zones:
            add("az", zone, zone, region_id=region_id)
            # VPCs are routers (vpc.go:57-70)
            for rt in self.get_response("DescribeRouters",
                                        "router_set", zone=zone):
                rid_ = rt.get("router_id", "")
                if rid_:
                    add("vpc", rid_, rt.get("router_name") or rid_,
                        region_id=region_id,
                        cidr=rt.get("vpc_network", ""))
            # subnets are vxnets; cidr from the attached router
            # (network.go:59-86); unattached/self-managed skipped
            for vx in self.get_response("DescribeVxnets", "vxnet_set",
                                        zone=zone):
                vid = vx.get("vxnet_id", "")
                router = vx.get("router") or {}
                epc = b.get("vpc", router.get("router_id", ""))
                if not vid or not epc:
                    continue
                add("subnet", vid, vx.get("vxnet_name") or vid,
                    epc_id=epc, az=zone,
                    cidr=router.get("ip_network", ""))
            # instances carry their vxnets inline (vm.go:85-180)
            for vm in self.get_response("DescribeInstances",
                                        "instance_set", zone=zone,
                                        status="running"):
                iid = vm.get("instance_id", "")
                if not iid:
                    continue
                epc, ip = 0, ""
                pubs = []
                for vx in vm.get("vxnets") or ():
                    sub = b.get("subnet", vx.get("vxnet_id", ""))
                    if sub and not epc:
                        for row in b.rows():
                            if row.type == "subnet" and row.id == sub:
                                epc = row.attr("epc_id", 0)
                                break
                        ip = vx.get("private_ip", "")
                    # per-nic eip (vm.go:297: nic.eip.eip_addr)
                    eip = (vx.get("eip") or {}).get("eip_addr", "")
                    if eip:
                        pubs.append((eip, vx.get("nic_id", "")))
                vm_rid = add("vm", iid,
                             vm.get("instance_name") or iid,
                             epc_id=epc, vpc_id=epc, ip=ip, az=zone)
                add_vm_public_addresses(b, iid, vm_rid, epc, pubs)
        return b.rows()

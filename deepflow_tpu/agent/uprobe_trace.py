"""Encrypted-traffic tracing: OpenSSL / Go-TLS uprobe suite, in-tree.

Reference: the agent's only window into HTTPS (most real traffic) is a
set of uprobes ABOVE the TLS layer, where the application's plaintext
is visible:

- `agent/src/ebpf/kernel/openssl_bpf.c:1` — uprobe/uretprobe pairs on
  SSL_read/SSL_write. Entry stashes {buf, fd} keyed pid_tgid, where fd
  is dug out of the SSL struct by probing ssl->rbio (+0x10) then
  rbio->num at the per-libssl-version offsets 0x38/0x30/0x28, taking
  the first that looks like a real fd (>2). Exit reads the return
  value, drops <=0, and submits the plaintext through the same
  process_data path as the syscall records, tagged
  DATA_SOURCE_OPENSSL_UPROBE.
- `agent/src/ebpf/kernel/go_tls_bpf.c:1` — uprobes on
  crypto/tls.(*Conn).Read/Write. Go's ABI shifted in 1.17 from stack
  args to registers (receiver AX, slice ptr BX); the fd is reached by
  walking Conn.conn (net.Conn interface) -> net.conn.fd (*netFD) ->
  pfd.Sysfd with per-binary offsets pushed by userspace into
  proc_info_map. Exits attach at the function's RET instructions
  (uretprobes are unsafe under goroutine stack moves) and read the
  byte count from AX (register ABI) or the saved entry SP + 40 (stack
  ABI). Tagged DATA_SOURCE_GO_TLS_UPROBE.
- `agent/src/ebpf/user/ssl_tracer.c:1`, `user/go_tracer.c:1`,
  `user/symbol.c:184` — userspace resolution: find libssl / the Go
  binary, resolve symbol file offsets, disassemble for RET offsets,
  detect the Go version/ABI, fill proc_info_map.

This module is that suite rebuilt on the in-tree toolkit: programs
authored in the eBPF assembler (agent/bpf.py), records emitted in the
SAME 192-byte SOCK_DATA wire image as the socket_trace suite with the
source packed in the direction word's high half (socket_trace.py's
emit_record_tail), so everything upstream — perf stream, EbpfTracer,
L7 parsing, session/trace aggregation, tempo — consumes TLS-uprobe
records with zero changes; the l7 rows come out flagged is_tls.

Userspace: ELF section/symbol/program-header readers (extending
agent/profiler.py's symbol reader with sizes + vaddr->file-offset),
the x86-64 length decoder (agent/x86_decode.py) for RET discovery, Go
buildinfo version detection, and plan_ssl/plan_go/find_libssl turning
a process or binary into an attach PLAN (UprobeSpec list + proc_info
entries) consumed by perf_ring.attach_uprobe. Attach needs the uprobe
PMU (/sys/bus/event_source/devices/uprobe) — attach_available()
probes it and the suite degrades to verifier-load + fixture replay
where it's masked. THIS build container exposes it:
tests/test_attach_live.py attaches to a compiled stand-in libssl and
drives real in-kernel captures (plaintext + in-kernel trace chaining)
through the perf ring into EbpfTracer, un-skipped.

Goroutine-id keying (uprobe_base_bpf.c:1's get_current_goroutine):
register-ABI Go keeps the current g in R14, so the programs read
runtime.g.goid at the per-version offset userspace pushes in
proc_info (goid_off; 0 disables) and key the in-flight stash AND the
trace park/consume by (bit63 | tgid << 32 | goid & 0xffffffff)
instead of pid_tgid. A goroutine migrating OS threads between a
Read's entry and its RET now keeps its record and its trace chain —
the exact loss mode the pid_tgid fallback had. Bit 63 partitions goid
keys from the syscall suite's pid_tgid keys in the SHARED trace map
(a pid_tgid's high word is a tgid < 2^22, so its bit 63 is always
clear; without the partition a syscall park could be consumed by the
wrong source). Stack-ABI (pre-1.17) processes key too: g lives in
thread-local storage at %fs:-8 there, and the programs reach it as
*(task->thread.fsbase - 8) with the fsbase offset discovered from the
kernel's own BTF (agent/btf.py — the reference's kernel-adaption
offset tables, answered by the kernel itself); a kernel without BTF
pushes fsbase_off 0 and those processes fall back to pid_tgid keying
(unavailable, not faulted). With keying
enabled, a failed in-kernel goid read DROPS that call rather than
falling back — a fallback would be asymmetric across the enter/exit
pair and could pair an exit with a different call's stash
(_goid_rekey's docstring has the full argument). The stash/trace maps
are LRU: goid keys are monotonic — never naturally overwritten — so
entries abandoned between enter and exit (goroutine exits with a
parked id; panic unwinds past the RET) age out instead of filling a
plain hash map and stopping all parking process-wide.

Cross-source chaining (the reference's unified get_current_goroutine
key, uprobe_base_bpf.c:1): the SYSCALL suite builds the IDENTICAL
goid key for proc_info-managed Go tgids — read at syscall entry where
the inner pt_regs expose the user's R14, carried to the kretprobe in
the entry stash (socket_trace.build_enter; a goroutine cannot migrate
OS threads while blocked in a syscall, so the stash's pid_tgid key
stays valid and only the trace park/consume needs the goid). A
decrypted TLS read therefore chains into the same goroutine's
plaintext syscall egress across sources AND threads
(tests/test_attach_live_cross_source.py proves it live in-kernel).
One proc_info row — the maps alias each other — enables both.
"""

from __future__ import annotations

import os
import re
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from deepflow_tpu.agent.bpf import (BPF_ADD, BPF_ARSH, BPF_DW, BPF_SUB,
                                    BPF_JEQ, BPF_JGT, BPF_JNE, BPF_JSGT,
                                    BPF_JSLE, BPF_LSH,
                                    BPF_MAP_TYPE_LRU_HASH, BPF_OR,
                                    BPF_PROG_TYPE_KPROBE,
                                    BPF_RSH, BPF_W,
                                    FN_get_current_pid_tgid,
                                    FN_map_delete_elem,
                                    FN_map_lookup_elem,
                                    FN_get_current_task,
                                    FN_map_update_elem, FN_probe_read,
                                    R0, R1, R2, R3, R4, R6, R7, R8, R9,
                                    R10, Asm, Map, Program, available,
                                    load)
from deepflow_tpu.agent.socket_trace import (PAYLOAD_CAP,
                                             SOURCE_GO_TLS_UPROBE,
                                             SOURCE_OPENSSL_UPROBE,
                                             SocketTraceMaps, T_EGRESS,
                                             T_INGRESS, create_maps,
                                             emit_fs_g_load,
                                             emit_gokey_pack,
                                             emit_record_tail)
from deepflow_tpu.agent.socket_trace import (_FDSAVE, _IOVPAIR,  # noqa
                                             _KEY, _PT_AX, _PT_DI,
                                             _PT_SI, _SCRATCH)

# x86_64 pt_regs offsets beyond socket_trace's (uprobes see the USER
# registers directly — no syscall-wrapper inner-pt_regs hop); R14 is
# where register-ABI Go keeps the current g
_PT_BX, _PT_CX, _PT_SP, _PT_R14 = 40, 88, 152, 8

# OpenSSL fd recovery: ssl->rbio, then BIO->num at the offset each
# libssl generation uses (openssl_bpf.c:43-47 — constants because
# libssl ships without debug info)
SSL_RBIO_OFF = 0x10
RBIO_FD_OFFS = (0x38, 0x30, 0x28)      # 3.x, 1.1.1, 1.1.0

# Go struct-walk defaults (go_tracer.c:71-175 data_members table):
# tls.Conn.conn at +0, interface data at +8, net.conn.fd -> *netFD at
# +0, poll.FD.Sysfd at +16, runtime.g.goid at +152
GO_DEFAULT_INFO = {"reg_abi": 1, "conn_off": 0, "fd_off": 0,
                   "sysfd_off": 16}

# runtime.g.goid file: 152 bytes of fields precede goid (stack 16,
# stackguard0/1, _panic, _defer, m, sched gobuf 56, syscallsp/pc,
# stktopsp, param, atomicstatus+stackLock) from go 1.9 through 1.22
# (1.5-1.8 carried stkbar stack-barrier fields before goid — refused);
# 1.23 inserted syscallbp after syscallpc, shifting goid to 160
# (go_tracer.c's per-version data_members table role)
GOID_OFF_DEFAULT, GOID_OFF_GO123 = 152, 160

# fresh stack slots (below socket_trace's frame, which tops out at
# _IOVPAIR = -264 .. -249)
_GOSTASH = -288      # stash build area {buf, fd, sp} (24B, -288..-265)
_PIKEY = -296        # u32 tgid key for proc_info lookups
_PIOFFS = -312       # {conn_off, fd_off, sysfd_off, pad} copy (16B)
_GOIDVAL = -328      # probe_read target for runtime.g.goid (8B)
_FSBOFF = -332       # u32 fsbase_off copy (stack-ABI g via %fs:-8)
_GOIDOFF = -336      # u32 goid_off copy (0 = pid_tgid keying)


@dataclass
class UprobeMaps:
    """ssl_ctx / go_conn / proc_info plus the SHARED trace/conf/events
    maps. Sharing them with a SocketTraceSuite (pass its maps) gives
    one trace-id ALLOCATOR and one event stream across syscall and
    uprobe sources, and OpenSSL/stack-ABI records (pid_tgid-keyed)
    park/consume against syscall records directly. Goid-keyed records
    (register-ABI Go) park in the same map under bit63-partitioned
    keys — chained among themselves per-goroutine, not with the
    syscall suite's pid_tgid parks (see the module docstring's
    tradeoff note)."""

    ssl_ctx: Map         # pid_tgid -> {buf, fd}            (16B)
    go_conn: Map         # goid key -> {buf, fd, entry sp}  (24B)
    shared: SocketTraceMaps
    owns_shared: bool = False

    @property
    def trace(self) -> Map:
        return self.shared.trace

    @property
    def conf(self) -> Map:
        return self.shared.conf

    @property
    def events(self) -> Map:
        return self.shared.events

    @property
    def proc_info(self) -> Map:
        """ALIASES the socket-trace suite's map: one proc_info row
        enables goid keying for a tgid in both the syscall programs
        (trace key via the entry stash) and the TLS uprobe programs —
        which is what makes the two sources build the same key and
        chain."""
        return self.shared.proc_info

    def set_proc_info(self, tgid: int, reg_abi: bool, conn_off: int = 0,
                      fd_off: int = 0, sysfd_off: int = 16,
                      goid_off: int = 0,
                      fsbase_off: Optional[int] = None) -> None:
        self.shared.set_proc_info(tgid, reg_abi, conn_off, fd_off,
                                  sysfd_off, goid_off, fsbase_off)

    def close(self) -> None:
        for m in (self.ssl_ctx, self.go_conn):
            m.close()
        if self.owns_shared:
            self.shared.close()


def create_uprobe_maps(
        shared: Optional[SocketTraceMaps] = None) -> UprobeMaps:
    owns = shared is None
    if shared is None:
        shared = create_maps()
    made: List[Map] = []
    try:
        # ssl_ctx / go_conn are LRU: a stash whose exit never fires (a
        # panic unwinding past the RET uprobe; an undecodable-exit
        # function whose enters still run; goid keys that are never
        # naturally overwritten) must age out, not brick the map.
        # proc_info lives in the SHARED maps (plain HASH there — LRU
        # eviction would silently disable keying for a managed
        # process).
        for args in ((8192, 16, BPF_MAP_TYPE_LRU_HASH, 8),
                     (8192, 24, BPF_MAP_TYPE_LRU_HASH, 8)):
            made.append(Map(*args))
    except OSError:
        for m in made:
            m.close()
        if owns:
            shared.close()
        raise
    return UprobeMaps(*made, shared=shared, owns_shared=owns)


# -- kernel programs -------------------------------------------------------

def _clamp_len(a: Asm) -> None:
    """R8 (signed byte count, already checked > 0) -> (0, PAYLOAD_CAP]."""
    a.jmp_imm(BPF_JGT, R8, PAYLOAD_CAP, "clamp")
    a.jmp("len_ok")
    a.label("clamp").mov_imm(R8, PAYLOAD_CAP)
    a.label("len_ok")


def _goid_rekey(a: Asm) -> None:
    """Rewrite the _KEY slot from pid_tgid to (tgid<<32 | goid-slice).

    Contract on entry: R6=ctx (user pt_regs), R7=pid_tgid, _GOIDOFF
    holds the u32 goid offset (0 = keep pid_tgid), _KEY already holds
    pid_tgid. Clobbers R0-R3 and _GOIDVAL.

    Fault discipline (review r5): with keying ENABLED (goid_off != 0)
    any failed goid read — no g in R14, probe_read fault, goid 0 —
    jumps to the program's "done" label and DROPS the call, it does
    not fall back to pid_tgid. A fallback here would be asymmetric
    across the enter/exit pair: an enter that faulted would stash
    under pid_tgid(thread) where a LATER call's faulting exit on the
    same thread could find it and emit that other call's buffer as its
    own — wrong-payload confusion. Dropping keeps the guarantee
    loss-only. (goid reads fault only in exceptional states — the g
    page is always resident for a running goroutine — so the loss rate
    is negligible; the reference accepts the confusion instead by
    falling back to tid, common.h get_current_goroutine returning 0.)
    Only goid_off == 0 (keying disabled: stack ABI, unmanaged tgid)
    keeps the pid_tgid key, where enter and exit are symmetric by
    construction.

    Key shape: bit63 | tgid<<32 | (goid & 0xffffffff). Bit 63 is the
    source partition for the SHARED trace map: pid_tgid keys always
    have it clear (the high word is a tgid < pid_max = 2^22), so a
    goid key can never consume a syscall park or vice versa
    (uprobe_base_bpf.c keys its own map by tgid+goid; here one map
    serves both sources, so the partition carries the separation).
    Residual ambiguity: two goroutines in one tgid whose goids are
    congruent mod 2^32, BOTH with a call in flight — goids are
    monotonic, so that needs ~4 billion goroutine spawns between two
    concurrently-live calls; the LRU maps bound the damage to one
    wrong pairing even then.

    g location by ABI (reads _PIOFFS+0/reg_abi and _FSBOFF, which the
    callers' prologues copy from proc_info): register ABI has g in
    R14; stack ABI (go < 1.17) keeps it at %fs:-8, reached through
    task_struct->thread.fsbase at the BTF-discovered offset —
    fsbase_off 0 (no BTF) keeps the pid_tgid key for stack-ABI
    processes (keying unavailable, nothing attempted, not a drop)."""
    a.ldx_mem(BPF_W, R1, R10, _GOIDOFF)
    a.jmp_imm(BPF_JEQ, R1, 0, "gokey_done")        # keying disabled
    a.ldx_mem(BPF_DW, R1, R10, _PIOFFS + 0)        # reg_abi
    a.jmp_imm(BPF_JNE, R1, 0, "gk_reg")
    a.ldx_mem(BPF_W, R1, R10, _FSBOFF)
    a.jmp_imm(BPF_JEQ, R1, 0, "gokey_done")        # no BTF: fallback
    emit_fs_g_load(a, _FSBOFF, _GOIDVAL, "done")   # g -> R3
    a.jmp("gk_have")
    a.label("gk_reg")
    a.ldx_mem(BPF_DW, R3, R6, _PT_R14)             # current g
    a.label("gk_have")
    a.jmp_imm(BPF_JEQ, R3, 0, "done")              # no g: drop call
    a.ldx_mem(BPF_W, R1, R10, _GOIDOFF)
    a.alu_reg(BPF_ADD, R3, R1)                     # &g.goid
    a.st_imm(BPF_DW, R10, _GOIDVAL, 0)
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _GOIDVAL)
    a.mov_imm(R2, 8)
    a.call(FN_probe_read)
    a.jmp_imm(BPF_JNE, R0, 0, "done")              # faulted: drop call
    a.ldx_mem(BPF_DW, R1, R10, _GOIDVAL)
    a.jmp_imm(BPF_JEQ, R1, 0, "done")              # goid 0: drop call
    emit_gokey_pack(a)             # SHARED with the syscall suite —
    a.stx_mem(BPF_DW, R10, R1, _KEY)  # identical keys = cross-source
    a.label("gokey_done")             # chaining


def build_ssl_enter(maps: UprobeMaps) -> Asm:
    """uprobe on SSL_read/SSL_write entry (direction-agnostic): stash
    {buf, fd} keyed pid_tgid, fd recovered through the rbio walk."""
    a = Asm()
    a.mov_reg(R6, R1)
    a.call(FN_get_current_pid_tgid)
    a.stx_mem(BPF_DW, R10, R0, _KEY)
    a.ldx_mem(BPF_DW, R8, R6, _PT_DI)              # SSL*
    a.ldx_mem(BPF_DW, R1, R6, _PT_SI)              # buf
    a.stx_mem(BPF_DW, R10, R1, _GOSTASH + 0)
    # rbio = *(ssl + SSL_RBIO_OFF)
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _SCRATCH)
    a.mov_imm(R2, 8)
    a.mov_reg(R3, R8).alu_imm(BPF_ADD, R3, SSL_RBIO_OFF)
    a.call(FN_probe_read)
    a.ldx_mem(BPF_DW, R8, R10, _SCRATCH)           # rbio
    # fd candidates at the per-version offsets; first plausible (>2)
    # wins, the last one is taken as-is (openssl_bpf.c:48-59)
    for idx, off in enumerate(RBIO_FD_OFFS):
        a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _SCRATCH)
        a.mov_imm(R2, 4)
        a.mov_reg(R3, R8).alu_imm(BPF_ADD, R3, off)
        a.call(FN_probe_read)
        a.ldx_mem(BPF_W, R1, R10, _SCRATCH)        # zero-extended u32
        # sign-extend the s32 fd so "-1" doesn't read as 4 billion
        a.alu_imm(BPF_LSH, R1, 32).alu_imm(BPF_ARSH, R1, 32)
        if idx < len(RBIO_FD_OFFS) - 1:
            a.jmp_imm(BPF_JSGT, R1, 2, "fd_done")
    a.label("fd_done")
    a.stx_mem(BPF_DW, R10, R1, _GOSTASH + 8)       # fd
    a.ld_map_fd(R1, maps.ssl_ctx)
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
    a.mov_reg(R3, R10).alu_imm(BPF_ADD, R3, _GOSTASH)
    a.mov_imm(R4, 0)                               # BPF_ANY
    a.call(FN_map_update_elem)
    a.exit_imm(0)
    return a


def build_ssl_exit(maps: UprobeMaps, direction: int) -> Asm:
    """uretprobe on SSL_read (T_INGRESS) / SSL_write (T_EGRESS): ret
    <= 0 drops; otherwise the stashed plaintext buffer is captured and
    the record emitted with SOURCE_OPENSSL_UPROBE, running the same
    trace-id park/consume discipline as the syscall suite."""
    a = Asm()
    a.mov_reg(R6, R1)
    a.call(FN_get_current_pid_tgid)
    a.mov_reg(R7, R0)
    a.stx_mem(BPF_DW, R10, R7, _KEY)
    a.ld_map_fd(R1, maps.ssl_ctx)
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
    a.call(FN_map_lookup_elem)
    a.jmp_imm(BPF_JEQ, R0, 0, "done")
    a.ldx_mem(BPF_DW, R9, R0, 0)                   # buf
    a.ldx_mem(BPF_DW, R1, R0, 8)
    a.stx_mem(BPF_DW, R10, R1, _FDSAVE)            # fd
    a.ld_map_fd(R1, maps.ssl_ctx)                  # consume the stash
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
    a.call(FN_map_delete_elem)
    # uretprobe fires with the USER pt_regs at return: ax = SSL ret.
    # SSL_read/SSL_write return a C int — the 32-bit register write
    # zero-extends, so -1 arrives as 0x00000000FFFFFFFF; sign-extend
    # before the signed drop check or every failed/WANT_READ call
    # would emit a bogus 128-byte garbage record
    a.ldx_mem(BPF_DW, R8, R6, _PT_AX)
    a.alu_imm(BPF_LSH, R8, 32).alu_imm(BPF_ARSH, R8, 32)
    a.jmp_imm(BPF_JSLE, R8, 0, "done")             # error/WANT_READ
    _clamp_len(a)
    emit_record_tail(a, maps, direction, source=SOURCE_OPENSSL_UPROBE)
    a.label("done")
    a.exit_imm(0)
    return a


def build_go_tls_enter(maps: UprobeMaps) -> Asm:
    """uprobe on crypto/tls.(*Conn).Read/Write entry. Register ABI
    (go >= 1.17): receiver in AX, slice ptr in BX; stack ABI: receiver
    at sp+8, slice ptr at sp+16. The fd walk (Conn.conn iface ->
    net.conn.fd -> pfd.Sysfd) uses the per-binary offsets userspace
    pushed into proc_info — an unmanaged process (no entry) traces
    nothing, exactly the reference's proc_info_map gate."""
    a = Asm()
    a.mov_reg(R6, R1)
    a.call(FN_get_current_pid_tgid)
    a.mov_reg(R7, R0)
    a.stx_mem(BPF_DW, R10, R7, _KEY)
    a.mov_reg(R1, R7).alu_imm(BPF_RSH, R1, 32)
    a.stx_mem(BPF_W, R10, R1, _PIKEY)
    a.ld_map_fd(R1, maps.proc_info)
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _PIKEY)
    a.call(FN_map_lookup_elem)
    a.jmp_imm(BPF_JEQ, R0, 0, "done")
    # copy the offsets out before the next helper call invalidates R0
    a.ldx_mem(BPF_W, R1, R0, 0)                    # reg_abi
    a.stx_mem(BPF_DW, R10, R1, _PIOFFS + 0)
    a.ldx_mem(BPF_W, R1, R0, 4)                    # conn_off
    a.stx_mem(BPF_W, R10, R1, _PIOFFS + 8)
    a.ldx_mem(BPF_W, R1, R0, 8)                    # fd_off
    a.stx_mem(BPF_W, R10, R1, _PIOFFS + 12)
    a.ldx_mem(BPF_W, R1, R0, 12)                   # sysfd_off
    a.stx_mem(BPF_W, R10, R1, _SCRATCH)
    a.ldx_mem(BPF_W, R1, R0, 16)                   # goid_off
    a.stx_mem(BPF_W, R10, R1, _GOIDOFF)
    a.ldx_mem(BPF_W, R1, R0, 20)                   # fsbase_off
    a.stx_mem(BPF_W, R10, R1, _FSBOFF)
    _goid_rekey(a)                                 # stash keyed by goid
    a.ldx_mem(BPF_DW, R1, R6, _PT_SP)              # entry sp (exit's
    a.stx_mem(BPF_DW, R10, R1, _GOSTASH + 16)      # stack-ABI ret read)
    a.ldx_mem(BPF_DW, R1, R10, _PIOFFS + 0)
    a.jmp_imm(BPF_JEQ, R1, 0, "stack_abi")
    a.ldx_mem(BPF_DW, R8, R6, _PT_AX)              # receiver (Conn*)
    a.ldx_mem(BPF_DW, R1, R6, _PT_BX)              # slice data ptr
    a.stx_mem(BPF_DW, R10, R1, _GOSTASH + 0)
    a.jmp("walk")
    a.label("stack_abi")
    # {receiver, slice ptr} live at sp+8 in one contiguous 16B read
    a.ldx_mem(BPF_DW, R3, R10, _GOSTASH + 16)
    a.alu_imm(BPF_ADD, R3, 8)
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _IOVPAIR)
    a.mov_imm(R2, 16)
    a.call(FN_probe_read)
    a.ldx_mem(BPF_DW, R8, R10, _IOVPAIR + 0)       # receiver
    a.ldx_mem(BPF_DW, R1, R10, _IOVPAIR + 8)       # slice data ptr
    a.stx_mem(BPF_DW, R10, R1, _GOSTASH + 0)
    a.label("walk")
    # hop 1: iface data = *(conn + conn_off + 8) (interface layout:
    # {itab, data})
    a.ldx_mem(BPF_W, R3, R10, _PIOFFS + 8)
    a.alu_reg(BPF_ADD, R3, R8).alu_imm(BPF_ADD, R3, 8)
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _GOSTASH + 8)
    a.mov_imm(R2, 8)
    a.call(FN_probe_read)
    a.ldx_mem(BPF_DW, R8, R10, _GOSTASH + 8)
    a.jmp_imm(BPF_JEQ, R8, 0, "done")
    # hop 2: *netFD = *(data + fd_off)
    a.ldx_mem(BPF_W, R3, R10, _PIOFFS + 12)
    a.alu_reg(BPF_ADD, R3, R8)
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _GOSTASH + 8)
    a.mov_imm(R2, 8)
    a.call(FN_probe_read)
    a.ldx_mem(BPF_DW, R8, R10, _GOSTASH + 8)
    a.jmp_imm(BPF_JEQ, R8, 0, "done")
    # hop 3: Sysfd (s32) = *(netFD + sysfd_off)
    a.ldx_mem(BPF_W, R3, R10, _SCRATCH)
    a.alu_reg(BPF_ADD, R3, R8)
    a.st_imm(BPF_DW, R10, _GOSTASH + 8, 0)
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _GOSTASH + 8)
    a.mov_imm(R2, 4)
    a.call(FN_probe_read)
    a.ld_map_fd(R1, maps.go_conn)
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
    a.mov_reg(R3, R10).alu_imm(BPF_ADD, R3, _GOSTASH)
    a.mov_imm(R4, 0)                               # BPF_ANY
    a.call(FN_map_update_elem)
    a.label("done")
    a.exit_imm(0)
    return a


def build_go_tls_exit(maps: UprobeMaps, direction: int) -> Asm:
    """uprobe at the RET offsets of crypto/tls.(*Conn).Read/Write
    (symbol.c's resolve_func_ret_addr role is x86_decode.py here).
    Byte count from AX (register ABI) or saved-entry-sp+40 (stack
    ABI); <= 0 drops."""
    a = Asm()
    a.mov_reg(R6, R1)
    a.call(FN_get_current_pid_tgid)
    a.mov_reg(R7, R0)
    a.stx_mem(BPF_DW, R10, R7, _KEY)
    # proc_info FIRST (the enter gated on it too): reg_abi for the ret
    # read, goid_off so the stash lookup key matches the enter's
    a.mov_reg(R1, R7).alu_imm(BPF_RSH, R1, 32)
    a.stx_mem(BPF_W, R10, R1, _PIKEY)
    a.ld_map_fd(R1, maps.proc_info)
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _PIKEY)
    a.call(FN_map_lookup_elem)
    a.jmp_imm(BPF_JEQ, R0, 0, "done")
    a.ldx_mem(BPF_W, R1, R0, 0)                    # reg_abi
    a.stx_mem(BPF_DW, R10, R1, _PIOFFS + 0)
    a.ldx_mem(BPF_W, R1, R0, 16)                   # goid_off
    a.stx_mem(BPF_W, R10, R1, _GOIDOFF)
    a.ldx_mem(BPF_W, R1, R0, 20)                   # fsbase_off
    a.stx_mem(BPF_W, R10, R1, _FSBOFF)
    _goid_rekey(a)                                 # same key the enter built
    a.ld_map_fd(R1, maps.go_conn)
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
    a.call(FN_map_lookup_elem)
    a.jmp_imm(BPF_JEQ, R0, 0, "done")
    a.ldx_mem(BPF_DW, R9, R0, 0)                   # buf
    a.ldx_mem(BPF_DW, R1, R0, 8)
    a.stx_mem(BPF_DW, R10, R1, _FDSAVE)            # fd
    a.ldx_mem(BPF_DW, R1, R0, 16)
    a.stx_mem(BPF_DW, R10, R1, _GOSTASH + 16)      # entry sp
    a.ld_map_fd(R1, maps.go_conn)                  # consume the stash
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
    a.call(FN_map_delete_elem)
    a.ldx_mem(BPF_DW, R1, R10, _PIOFFS + 0)        # reg_abi
    a.jmp_imm(BPF_JEQ, R1, 0, "stack_ret")
    a.ldx_mem(BPF_DW, R8, R6, _PT_AX)              # n in AX
    a.jmp("have_ret")
    a.label("stack_ret")
    # stack ABI: (n int, err error) at entry-sp +40 (go_tls_bpf.c:81)
    a.ldx_mem(BPF_DW, R3, R10, _GOSTASH + 16)
    a.alu_imm(BPF_ADD, R3, 40)
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _SCRATCH)
    a.mov_imm(R2, 8)
    a.call(FN_probe_read)
    a.ldx_mem(BPF_DW, R8, R10, _SCRATCH)
    a.label("have_ret")
    a.jmp_imm(BPF_JSLE, R8, 0, "done")
    _clamp_len(a)
    emit_record_tail(a, maps, direction, source=SOURCE_GO_TLS_UPROBE)
    a.label("done")
    a.exit_imm(0)
    return a


class UprobeSuite:
    """The loaded TLS-uprobe program set. Construction runs every
    program through the kernel verifier (failure raises with the
    verifier log); pass a SocketTraceSuite's maps as `shared` so
    syscall and TLS records share one trace-id space."""

    def __init__(self,
                 shared: Optional[SocketTraceMaps] = None) -> None:
        self.maps = create_uprobe_maps(shared)
        loaded: List[Program] = []
        try:
            for builder in (lambda: build_ssl_enter(self.maps),
                            lambda: build_ssl_exit(self.maps, T_INGRESS),
                            lambda: build_ssl_exit(self.maps, T_EGRESS),
                            lambda: build_go_tls_enter(self.maps),
                            lambda: build_go_tls_exit(self.maps,
                                                      T_INGRESS),
                            lambda: build_go_tls_exit(self.maps,
                                                      T_EGRESS)):
                loaded.append(load(builder().assemble(),
                                   prog_type=BPF_PROG_TYPE_KPROBE))
        except OSError:
            for p in loaded:
                p.close()
            self.maps.close()
            raise
        (self.ssl_enter, self.ssl_exit_read, self.ssl_exit_write,
         self.go_enter, self.go_exit_read, self.go_exit_write) = loaded

    def programs(self) -> Dict[str, Program]:
        return {"ssl_enter": self.ssl_enter,
                "ssl_exit_read": self.ssl_exit_read,
                "ssl_exit_write": self.ssl_exit_write,
                "go_enter": self.go_enter,
                "go_exit_read": self.go_exit_read,
                "go_exit_write": self.go_exit_write}

    def close(self) -> None:
        for p in self.programs().values():
            p.close()
        self.maps.close()


# -- ELF plumbing (sections, sizes, vaddr->offset) -------------------------

def _read_elf(path: str) -> Optional[bytes]:
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return None
    if len(data) < 64 or data[:4] != b"\x7fELF" or data[4] != 2 \
            or data[5] != 1:
        return None
    return data


def elf_sections(path: str) -> Dict[str, Tuple[int, int, int]]:
    """section name -> (file offset, size, vaddr)."""
    data = _read_elf(path)
    if data is None:
        return {}
    e_shoff, = struct.unpack_from("<Q", data, 0x28)
    e_shentsize, e_shnum, e_shstrndx = struct.unpack_from(
        "<HHH", data, 0x3A)
    if e_shstrndx >= e_shnum:
        return {}
    stroff, strsz = struct.unpack_from(
        "<QQ", data, e_shoff + e_shstrndx * e_shentsize + 24)
    strtab = data[stroff:stroff + strsz]
    out: Dict[str, Tuple[int, int, int]] = {}
    for i in range(e_shnum):
        off = e_shoff + i * e_shentsize
        if off + 64 > len(data):
            break
        sh_name, = struct.unpack_from("<I", data, off)
        sh_addr, sh_offset, sh_size = struct.unpack_from(
            "<QQQ", data, off + 16)
        end = strtab.find(b"\0", sh_name)
        name = strtab[sh_name:end if end >= 0 else None].decode(
            "utf-8", "replace")
        if name:
            out[name] = (sh_offset, sh_size, sh_addr)
    return out


def elf_func_table(path: str) -> Dict[str, Tuple[int, int]]:
    """function name -> (vaddr, size) from .symtab + .dynsym STT_FUNC
    entries (profiler.elf_function_symbols returns addr->name for
    symbolization; probing additionally needs SIZES for the RET
    walk)."""
    data = _read_elf(path)
    if data is None:
        return {}
    e_shoff, = struct.unpack_from("<Q", data, 0x28)
    e_shentsize, e_shnum = struct.unpack_from("<HH", data, 0x3A)
    out: Dict[str, Tuple[int, int]] = {}
    for i in range(e_shnum):
        off = e_shoff + i * e_shentsize
        if off + 64 > len(data):
            break
        sh_type, = struct.unpack_from("<I", data, off + 4)
        if sh_type not in (2, 11):                 # SYMTAB / DYNSYM
            continue
        sh_offset, sh_size = struct.unpack_from("<QQ", data, off + 24)
        sh_link, = struct.unpack_from("<I", data, off + 40)
        sh_entsize, = struct.unpack_from("<Q", data, off + 56)
        if sh_entsize != 24 or sh_link >= e_shnum:
            continue
        stroff, strsz = struct.unpack_from(
            "<QQ", data, e_shoff + sh_link * e_shentsize + 24)
        strtab = data[stroff:stroff + strsz]
        for s in range(sh_offset,
                       min(sh_offset + sh_size, len(data)), 24):
            st_name, st_info = struct.unpack_from("<IB", data, s)
            if st_info & 0xF != 2:                 # STT_FUNC
                continue
            st_value, st_size = struct.unpack_from("<QQ", data, s + 8)
            if st_value == 0 or st_name >= len(strtab):
                continue
            end = strtab.find(b"\0", st_name)
            name = strtab[st_name:end if end >= 0 else None].decode(
                "utf-8", "replace")
            if name and name not in out:
                out[name] = (st_value, st_size)
    return out


def vaddr_to_offset(path: str, vaddr: int) -> Optional[int]:
    """Virtual address -> file offset via PT_LOAD program headers —
    uprobes attach at FILE offsets (symbol.c:170-181's
    resolve_bin_file role)."""
    data = _read_elf(path)
    if data is None:
        return None
    e_phoff, = struct.unpack_from("<Q", data, 0x20)
    e_phentsize, e_phnum = struct.unpack_from("<HH", data, 0x36)
    for i in range(e_phnum):
        off = e_phoff + i * e_phentsize
        if off + 56 > len(data):
            break
        p_type, = struct.unpack_from("<I", data, off)
        if p_type != 1:                            # PT_LOAD
            continue
        p_offset, p_vaddr, _p_paddr, p_filesz = struct.unpack_from(
            "<QQQQ", data, off + 8)
        if p_vaddr <= vaddr < p_vaddr + p_filesz:
            return vaddr - p_vaddr + p_offset
    return None


# -- Go binary inspection ---------------------------------------------------

_BUILDINFO_MAGIC = b"\xff Go buildinf:"


def go_version(path: str) -> Optional[str]:
    """Go toolchain version of a binary ("go1.20.4"), from the
    .go.buildinfo blob (go_tracer.c:418's go_version_offset read —
    the 1.18+ inline-string layout), falling back to scanning for the
    always-embedded runtime version string."""
    data = _read_elf(path)
    if data is None:
        return None
    secs = elf_sections(path)
    blob = None
    if ".go.buildinfo" in secs:
        off, size, _ = secs[".go.buildinfo"]
        blob = data[off:off + size]
    if blob is not None and blob[:14] == _BUILDINFO_MAGIC \
            and len(blob) > 33 and blob[15] & 2:
        # flags bit 1 = inline strings: varint length at +32
        n = blob[32]
        if n < 128 and 33 + n <= len(blob):
            v = blob[33:33 + n].decode("utf-8", "replace")
            if v.startswith("go"):
                return v
    # pointer-layout buildinfo (go < 1.18): the runtime always embeds
    # "go1.X.Y" — but ONLY trust the scan when the binary carries Go
    # structure (.go.buildinfo / .gopclntab / runtime symbols). A bare
    # byte match anywhere ('logo1.2' in libssl's docs) would misroute
    # a C library away from SSL attach with no error anywhere
    if not ({".go.buildinfo", ".gopclntab"} & set(secs)
            or ".note.go.buildid" in secs):
        return None
    m = re.search(rb"go1\.\d+(\.\d+)?", data)
    return m.group(0).decode() if m else None


def _go_release(version: Optional[str]) -> Optional[Tuple[int, int]]:
    """(major, minor) from a toolchain version string, tolerating
    prerelease suffixes ("go1.23rc1" -> (1, 23), "go1.24beta2" ->
    (1, 24)); None when unparseable. ONE parser for every
    version-gated decision below — two hand-rolled copies disagreed on
    the unparseable fallback once, which mis-keyed prerelease
    toolchains (review r5)."""
    if not version or not version.startswith("go"):
        return None
    m = re.match(r"go(\d+)\.(\d+)", version)
    if not m:
        return None
    return int(m.group(1)), int(m.group(2))


def go_register_abi(version: Optional[str]) -> bool:
    """regabi (args in AX/BX/...) landed on amd64 in go 1.17
    (go_tracer.c's is_register_based_call)."""
    rel = _go_release(version)
    return True if rel is None else rel >= (1, 17)   # modern default


def go_goid_offset(version: Optional[str]) -> int:
    """Offset of runtime.g.goid for this toolchain version, 0 when
    keying must be disabled: an UNPARSEABLE version — a guessed offset
    on the wrong layout would read atomicstatus/stackLock, collapsing
    every goroutine onto one key and cross-wiring their stashes,
    strictly worse than the pid_tgid fallback's bounded loss. The
    152-byte prefix held from go 1.9 through 1.22 (both ABIs — the
    regabi transition did not reorder runtime.g; 1.5-1.8 carried
    stack-barrier fields (stkbar/stkbarPos) before goid, so those
    versions are REFUSED rather than mis-probed), and stack-ABI
    binaries key too, with g reached via %fs:-8 instead of R14
    (fsbase_off). The reference resolves this from its per-version
    data_members table (go_tracer.c:71-175); the layout history is in
    GOID_OFF_DEFAULT's comment."""
    rel = _go_release(version)
    if rel is None or rel < (1, 9):
        return 0
    return GOID_OFF_GO123 if rel >= (1, 23) else GOID_OFF_DEFAULT


# -- attach planning --------------------------------------------------------

GO_TLS_SYMBOLS = {"crypto/tls.(*Conn).Read": T_INGRESS,
                  "crypto/tls.(*Conn).Write": T_EGRESS}
SSL_SYMBOLS = {"SSL_read": T_INGRESS, "SSL_write": T_EGRESS}


@dataclass
class UprobeSpec:
    """One attachment: program `role` at `path`+`offset` (file
    offset). `retprobe` uses the PMU's uretprobe flavor; RET-offset
    exits instead carry extra entries, one per RET."""

    path: str
    symbol: str
    offset: int
    role: str            # key into UprobeSuite.programs()
    retprobe: bool = False


@dataclass
class GoProcPlan:
    version: str
    reg_abi: bool
    goid_off: int = 0    # runtime.g.goid offset (0 = pid_tgid keying)
    specs: List[UprobeSpec] = field(default_factory=list)
    undecodable: List[str] = field(default_factory=list)


def plan_ssl(path: str) -> List[UprobeSpec]:
    """Attach plan for a libssl image: uprobe at SSL_read/SSL_write
    entry + uretprobe at their returns (ssl_tracer.c probe table)."""
    funcs = elf_func_table(path)
    specs: List[UprobeSpec] = []
    for sym, direction in SSL_SYMBOLS.items():
        if sym not in funcs:
            continue
        vaddr, _size = funcs[sym]
        off = vaddr_to_offset(path, vaddr)
        if off is None:
            continue
        exit_role = ("ssl_exit_read" if direction == T_INGRESS
                     else "ssl_exit_write")
        specs.append(UprobeSpec(path, sym, off, "ssl_enter"))
        specs.append(UprobeSpec(path, sym, off, exit_role,
                                retprobe=True))
    return specs


def find_libssl(pid: int) -> Optional[str]:
    """The libssl image a process has mapped (ssl_tracer.c's
    per-process library discovery over /proc/<pid>/maps)."""
    try:
        with open(f"/proc/{pid}/maps") as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 6 and "libssl" in parts[-1] \
                        and ".so" in parts[-1]:
                    return parts[-1]
    except OSError:
        pass
    return None


def plan_go(path: str) -> Optional[GoProcPlan]:
    """Attach plan for a Go binary: entry uprobes at
    crypto/tls.(*Conn).Read/Write plus exit uprobes at every RET of
    each (go_tracer.c + symbol.c:184). None = not a Go binary or no
    TLS symbols (pure-HTTP or stripped)."""
    from deepflow_tpu.agent.x86_decode import DecodeError, \
        find_ret_offsets
    version = go_version(path)
    if version is None:
        return None
    funcs = elf_func_table(path)
    plan = GoProcPlan(version=version,
                      reg_abi=go_register_abi(version),
                      goid_off=go_goid_offset(version))
    data = _read_elf(path) or b""
    for sym, direction in GO_TLS_SYMBOLS.items():
        if sym not in funcs:
            continue
        vaddr, size = funcs[sym]
        off = vaddr_to_offset(path, vaddr)
        if off is None or size == 0:
            continue
        exit_role = ("go_exit_read" if direction == T_INGRESS
                     else "go_exit_write")
        plan.specs.append(UprobeSpec(path, sym, off, "go_enter"))
        try:
            rets = find_ret_offsets(data[off:off + size])
        except DecodeError:
            # never probe a guessed boundary: skip this function's
            # exits entirely and record why (the enter stash simply
            # expires unconsumed — loss, not corruption)
            plan.undecodable.append(sym)
            continue
        for r in rets:
            plan.specs.append(UprobeSpec(path, sym, off + r, exit_role))
    return plan if plan.specs else None


# -- attach capability ------------------------------------------------------

class TlsUprobeSource:
    """Live TLS capture for one agent: suite + attachments + perf
    reader, pumping kernel SOCK_DATA records into an EbpfTracer. The
    runtime-facing face of this module (trident wires it when the
    capability probe passes and config asks for it); targets are
    binary paths (a libssl image or a Go binary) or pids (libssl
    discovered via /proc/<pid>/maps).

    Reference: the ssl/go tracer lifecycles in
    agent/src/ebpf/user/{ssl_tracer.c,go_tracer.c} — probe tables
    built per process, attached through tracer.c, records through the
    shared perf reader."""

    def __init__(self, shared: Optional[SocketTraceMaps] = None,
                 cpus: Optional[List[int]] = None) -> None:
        from deepflow_tpu.agent import perf_ring
        ok, why = attach_available()
        if not ok:
            raise OSError(95, f"uprobe attach unavailable: {why}")
        self.suite = UprobeSuite(shared)
        try:
            self.reader = perf_ring.BpfOutputReader(
                self.suite.maps.events, cpus=cpus)
        except OSError:
            self.suite.close()
            raise
        self._probes: List[object] = []
        self.targets: List[dict] = []
        # (kind, realpath) of images already probed: uprobes attach to
        # the INODE, so two pids mapping one libssl (nginx workers) or
        # a repeated enable call must not install duplicate probes —
        # every TLS call would fire both and emit doubled records that
        # corrupt session pairing downstream
        self._attached: set = set()
        self._http2_suite = None       # lazy, shares the events map
        self.records_pumped = 0

    def attach_ssl(self, path: str) -> int:
        """Attach the OpenSSL pair set to a libssl image; returns the
        probe count (0 = symbols not found or already attached)."""
        from deepflow_tpu.agent import perf_ring
        key = ("openssl", os.path.realpath(path))
        if key in self._attached:
            return 0
        progs = self.suite.programs()
        specs = plan_ssl(path)
        for s in specs:
            self._probes.append(perf_ring.attach_uprobe(
                progs[s.role], s.path, s.offset, s.retprobe))
        if specs:
            self._attached.add(key)
            self.targets.append({"kind": "openssl", "path": path,
                                 "probes": len(specs)})
        return len(specs)

    def _push_proc_info(self, plan: GoProcPlan, tgid: int) -> None:
        """ONE place turning a plan into a proc_info row — every field
        added to the row (reg_abi, walk offsets, goid_off, ...) must
        reach both the fresh-attach and already-attached paths."""
        self.suite.maps.set_proc_info(
            tgid, reg_abi=plan.reg_abi, goid_off=plan.goid_off,
            **{k: GO_DEFAULT_INFO[k]
               for k in ("conn_off", "fd_off", "sysfd_off")})

    def attach_go(self, path: str, tgid: Optional[int] = None) -> int:
        """Attach the Go-TLS set to a Go binary and push its ABI/offset
        proc_info (for `tgid`, or every current process running that
        binary when omitted). An already-probed binary only refreshes
        proc_info for the new tgid (no duplicate probes)."""
        from deepflow_tpu.agent import perf_ring
        key = ("go_tls", os.path.realpath(path))
        if key in self._attached:
            plan = plan_go(path)
            if plan is not None and tgid is not None:
                self._push_proc_info(plan, tgid)
                if self._http2_suite is not None:
                    # a NEW pid of an already-probed binary needs its
                    # http2_info row too, or its writeHeader probes
                    # fire into the prologue's map-miss exit and h2
                    # capture silently never happens for it
                    from deepflow_tpu.agent.http2_trace import \
                        GO_HTTP2_DEFAULT_INFO
                    self._http2_suite.maps.set_info(
                        tgid, reg_abi=plan.reg_abi,
                        **GO_HTTP2_DEFAULT_INFO)
            return 0
        plan = plan_go(path)
        if plan is None:
            return 0
        self._attached.add(key)
        progs = self.suite.programs()
        for s in plan.specs:
            self._probes.append(perf_ring.attach_uprobe(
                progs[s.role], s.path, s.offset, s.retprobe))
        tgids = [tgid] if tgid is not None else _pids_running(path)
        for t in tgids:
            self._push_proc_info(plan, t)
        self.targets.append({"kind": "go_tls", "path": path,
                             "version": plan.version,
                             "reg_abi": plan.reg_abi,
                             "goid_off": plan.goid_off,
                             "probes": len(plan.specs),
                             "tgids": tgids,
                             "undecodable": plan.undecodable})
        # http2 write-side header sites ride along when the binary has
        # them (reference: go_tracer.c attaches the http2 probe table
        # next to the tls one); events land in the SAME perf rings
        from deepflow_tpu.agent.http2_trace import (
            GO_HTTP2_DEFAULT_INFO, Http2Suite, plan_go_http2)
        h2_specs = plan_go_http2(path)
        if h2_specs:
            if self._http2_suite is None:
                self._http2_suite = Http2Suite(
                    shared=self.suite.maps.shared)
            progs2 = self._http2_suite.programs()
            for s in h2_specs:
                self._probes.append(perf_ring.attach_uprobe(
                    progs2[s.role], s.path, s.offset, s.retprobe))
            for t in tgids:
                # the REAL walk/stream offsets (go_tracer.c defaults),
                # not set_info's zero defaults — stream_off=0 would
                # leave header events keyed stream 0 while end markers
                # carry the real id, and no group would ever complete
                self._http2_suite.maps.set_info(
                    t, reg_abi=plan.reg_abi, **GO_HTTP2_DEFAULT_INFO)
            self.targets.append({"kind": "go_http2", "path": path,
                                 "probes": len(h2_specs),
                                 "tgids": tgids})
        return len(plan.specs)

    def attach_pid(self, pid: int) -> int:
        """Discover a pid's TLS surface: mapped libssl and/or a Go main
        binary; attach whatever is found."""
        n = 0
        lib = find_libssl(pid)
        if lib:
            n += self.attach_ssl(lib)
        try:
            exe = os.readlink(f"/proc/{pid}/exe")
        except OSError:
            exe = None
        if exe and go_version(exe):
            n += self.attach_go(exe, tgid=pid)
        return n

    def pump(self, feed) -> int:
        """Drain the perf rings into `feed(raw_record_bytes)` — e.g.
        an EbpfTracer.feed_raw, or a wrapper adding a resolver and
        routing merged l7 records (trident._pump_tls_uprobes). Returns
        records moved; the ONLY place records_pumped accrues."""
        n = self.reader.pump(feed)
        self.records_pumped += n
        return n

    def counters(self) -> dict:
        return {"targets": self.targets,
                "probes_attached": len(self._probes),
                "records_pumped": self.records_pumped,
                "ring_lost": self.reader.lost}

    def close(self) -> None:
        for p in self._probes:
            p.close()
        self._probes = []
        self.reader.close()
        if self._http2_suite is not None:
            self._http2_suite.close()
            self._http2_suite = None
        self.suite.close()


def _pids_running(path: str) -> List[int]:
    """Current pids whose main binary is `path`."""
    out: List[int] = []
    real = os.path.realpath(path)
    for d in os.listdir("/proc"):
        if not d.isdigit():
            continue
        try:
            if os.path.realpath(f"/proc/{d}/exe") == real:
                out.append(int(d))
        except OSError:
            continue
    return out


_UPROBE_PMU = "/sys/bus/event_source/devices/uprobe/type"
_ATTACH_CACHE: Optional[Tuple[bool, str]] = None


def attach_available() -> Tuple[bool, str]:
    """Could uprobes attach here? Needs the uprobe PMU (perf) or a
    writable tracefs uprobe_events — both typically masked in
    containers, in which case the suite stays verifier-loaded +
    replay-driven (the socket_trace degradation contract)."""
    global _ATTACH_CACHE
    if _ATTACH_CACHE is not None:
        return _ATTACH_CACHE
    if not available():
        _ATTACH_CACHE = (False, "bpf(2) unavailable")
    elif os.path.exists(_UPROBE_PMU):
        _ATTACH_CACHE = (True, "uprobe PMU")
    else:
        for tracefs in ("/sys/kernel/tracing",
                        "/sys/kernel/debug/tracing"):
            if os.access(os.path.join(tracefs, "uprobe_events"),
                         os.W_OK):
                _ATTACH_CACHE = (True, f"tracefs at {tracefs}")
                break
        else:
            _ATTACH_CACHE = (False,
                             "no uprobe PMU and no writable tracefs")
    return _ATTACH_CACHE

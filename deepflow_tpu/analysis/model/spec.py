"""The modeling vocabulary for deepflow-model (ISSUE 14).

A model is a set of PROCESSES (the producer, shard workers, the epoch
coordinator, the drain thread...) whose steps are guarded ATOMIC
actions over one global state dict — the same granularity the real
code's ledger-lock sections establish ("absorb + booking + enqueue are
ONE atomic step", pod.py). Nondeterminism is explicit: an effect may
return several successor states (a frame in flight when the connection
dies was either delivered or not), and the explorer tries them all.

Faults are actions too, tagged with the REAL fault-site string from
`runtime/faults.py` (``shard.device_error``, ``merge.stall``, ...), so
a counterexample schedule reads like a chaos spec and the conformance
layer can diff the model's fault alphabet against the registry.
Process-level events the registry cannot arm (a SIGKILL) still count
against the fault budget but carry a deliberately non-site-shaped
label, so a trace never names a chaos spec that would silently no-op. The
explorer bounds how many fault actions any single execution may take
(the "N shards, <= 2 concurrent faults" budget that keeps the state
space inside CI).

States are plain dicts of ints/strs/bools/tuples (tuples all the way
down — effects must never mutate, they rebuild). `freeze_state` is the
canonical hashable form; a model's `symmetry` hook canonicalizes
before freezing (sorting the per-shard tuple makes shard ids
interchangeable, which is sound exactly when every per-shard fact
lives inside that shard's own sub-state).
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Action", "Model", "freeze_state", "updated"]

State = Dict[str, object]


def freeze_state(state: State) -> tuple:
    """Canonical hashable form of a state dict. Values must already be
    immutable (ints/strs/bools/tuples/frozensets) — the models are
    written that way so freezing is a sorted-items walk, not a deep
    conversion pass."""
    return tuple(sorted(state.items()))


def updated(state: State, **changes) -> State:
    """Copy-with-changes — the one-liner every effect is built from."""
    out = dict(state)
    out.update(changes)
    return out


class Action:
    """One guarded atomic step of one process.

    - `guard(state) -> bool`: enabled?
    - `effect(state) -> state | [state, ...]`: successor(s); returning
      a list models nondeterministic outcomes of ONE step.
    - `fault`: the runtime/faults.py site string when this action IS a
      fault injection — or a non-site-shaped event name (``SIGKILL``)
      for process-level faults the registry cannot arm. Either way it
      counts against the explorer's fault budget and renders as
      `!! fault <label>` in schedules; None for protocol steps.
    - `process`: the owning process label, for schedule readability
      ("shard1", "coordinator", "drain").
    """

    __slots__ = ("name", "guard", "effect", "process", "fault")

    def __init__(self, name: str,
                 guard: Callable[[State], bool],
                 effect: Callable[[State], object],
                 process: str = "",
                 fault: Optional[str] = None) -> None:
        self.name = name
        self.guard = guard
        self.effect = effect
        self.process = process
        self.fault = fault

    def successors(self, state: State) -> List[State]:
        out = self.effect(state)
        return out if isinstance(out, list) else [out]

    def label(self) -> str:
        base = f"{self.process}.{self.name}" if self.process else self.name
        if self.fault is not None:
            return f"!! fault {self.fault} ({base})"
        return base


class Model:
    """One protocol: initial state, actions, invariants, liveness goal.

    - `invariants`: [(name, fn)] where fn(state) returns None when the
      state is fine and a MESSAGE when it is not — the message lands in
      the counterexample verbatim, so write it as the post-mortem line.
    - `done(state)`: terminal-OK predicate; a state with no enabled
      action that is not `done` is a deadlock.
    - `goal(state)`: the liveness target ("everything sent was
      delivered or counted; the epoch machinery is quiet"). The
      explorer reports a livelock when some reachable state cannot
      reach ANY goal state through non-fault actions — under weak
      fairness that is exactly a schedule that runs forever without
      ever resolving the ledger. None skips the liveness pass.
    - `symmetry(state) -> state`: canonical representative under the
      model's symmetry group (shard-id permutation); identity by
      default.
    """

    def __init__(self, name: str, init: State,
                 actions: Sequence[Action],
                 invariants: Sequence[Tuple[str, Callable[[State],
                                                          Optional[str]]]],
                 done: Callable[[State], bool],
                 goal: Optional[Callable[[State], bool]] = None,
                 symmetry: Optional[Callable[[State], State]] = None,
                 ) -> None:
        self.name = name
        self.init = init
        self.actions = list(actions)
        self.invariants = list(invariants)
        self.done = done
        self.goal = goal
        self.symmetry = symmetry

    def canon(self, state: State) -> tuple:
        if self.symmetry is not None:
            state = self.symmetry(state)
        return freeze_state(state)

    def enabled(self, state: State) -> Iterable[Action]:
        for a in self.actions:
            if a.guard(state):
                yield a

    def check_invariants(self, state: State) -> Optional[Tuple[str, str]]:
        """(invariant name, message) of the first violated invariant."""
        for name, fn in self.invariants:
            msg = fn(state)
            if msg is not None:
                return name, msg
        return None

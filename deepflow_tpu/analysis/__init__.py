"""deepflow-lint: AST invariant checks for the pipeline's disciplines.

Entry points: `df-ctl lint` (deepflow_tpu/cli.py), the `lint` debug
command (runtime/debug.py), and ci.sh's failing lint step against the
committed `.lint-baseline.json` + `.lint-twins.json`. See core.py for
the framework, checkers.py for the per-file rules, concurrency.py for
the whole-program lock/race rules, and twins.py for the host/device
twin registry behind the twin-drift gate.
"""

from deepflow_tpu.analysis.core import (Finding, all_rules,
                                        default_twin_store_path,
                                        findings_to_json,
                                        findings_to_sarif,
                                        format_findings, load_baseline,
                                        new_findings, run_lint,
                                        run_on_sources, save_baseline,
                                        scan_package)
from deepflow_tpu.analysis.twins import host_twin_of

__all__ = ["Finding", "all_rules", "default_twin_store_path",
           "findings_to_json", "findings_to_sarif", "format_findings",
           "host_twin_of", "load_baseline", "new_findings", "run_lint",
           "run_on_sources", "save_baseline", "scan_package"]

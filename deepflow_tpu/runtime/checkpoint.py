"""Sketch-state checkpointing — now a thin alias over the SnapshotBus.

ISSUE 7 refactored this module's ``SketchCheckpointer`` into the
pub/sub, versioned :class:`~deepflow_tpu.runtime.snapbus.SnapshotBus`:
one snapshot format now serves three consumers — querier reads
(``serving/``), degraded-mode restore, and restart replay. The name is
kept because "checkpointer" is what the restore/replay consumers still
see; new code (and anything that wants the pub/sub surface) should
import :mod:`deepflow_tpu.runtime.snapbus` directly.

The PR 4 promise is unchanged: atomic rolling npz snapshots of one
pytree state, restart loses <= 1 window, incompatible snapshots (config
changed) are refused, not misloaded — plus the ISSUE 7 durability fix
(fsync file-then-directory around the rename) and restored-step
attribution (``counters()["last_restored_step"]``).
"""

from __future__ import annotations

from deepflow_tpu.runtime.snapbus import SketchSnapshot, SnapshotBus

__all__ = ["SketchCheckpointer", "SketchSnapshot", "SnapshotBus"]

# the historical name: identical object, not a subclass — isinstance
# checks and counters stay interchangeable across the rename
SketchCheckpointer = SnapshotBus

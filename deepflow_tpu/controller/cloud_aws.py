"""AWS cloud platform client: SigV4-signed EC2 Query API gathering.

Reference: server/controller/cloud/aws/ (aws.go NewAws/CheckAuth +
region.go/vpc.go/network.go/vm.go/vinterface_and_ip.go) — the vendor
client that proves the cloud-platform interface against a real vendor
shape: signed requests, XML responses, NextToken pagination, region
fan-out. The reference links the AWS SDK; this is a from-scratch
implementation of the public contracts:

- AWS Signature Version 4 (the published HMAC-SHA256 canonical-request
  algorithm; validated against AWS's official test-vector in
  tests/test_cloud_aws.py);
- the EC2 Query API (Action=Describe* form POSTs, XML results,
  nextToken paging);
- normalization into this controller's Resource rows: region -> az ->
  vpc (epc) -> subnet -> host rows carrying private IPs, the same
  shapes the filereader/http platforms produce, so recorder/enrich
  downstream is identical.

Fixture-replayed in tests (zero egress here); `endpoint_template`
points the client at the recorder."""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Sequence

from deepflow_tpu.controller.cloud import ResourceBuilder
from deepflow_tpu.controller.model import Resource

EC2_API_VERSION = "2016-11-15"


# -- AWS Signature Version 4 (public algorithm) ----------------------------
def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_signature(secret_key: str, date: str, region: str,
                    service: str, string_to_sign: str) -> str:
    k = _hmac(("AWS4" + secret_key).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    return hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()


def sigv4_headers(method: str, url: str, body: bytes, access_key: str,
                  secret_key: str, region: str, service: str = "ec2",
                  now: Optional[datetime.datetime] = None,
                  extra_headers: Optional[Dict[str, str]] = None
                  ) -> Dict[str, str]:
    """Authorization + x-amz-date headers for one request, per the
    SigV4 spec (canonical request -> string to sign -> derived-key
    HMAC chain)."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    parsed = urllib.parse.urlparse(url)
    host = parsed.netloc
    path = parsed.path or "/"
    # canonical query: key-sorted, strictly percent-encoded
    q = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    cq = "&".join(f"{urllib.parse.quote(k, safe='-_.~')}="
                  f"{urllib.parse.quote(v, safe='-_.~')}"
                  for k, v in sorted(q))
    headers = {"host": host, "x-amz-date": amz_date,
               **{k.lower(): v for k, v in (extra_headers or {}).items()}}
    signed = ";".join(sorted(headers))
    ch = "".join(f"{k}:{headers[k].strip()}\n" for k in sorted(headers))
    payload_hash = hashlib.sha256(body).hexdigest()
    creq = "\n".join([method, urllib.parse.quote(path, safe="/-_.~"),
                      cq, ch, signed, payload_hash])
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    sig = sigv4_signature(secret_key, date, region, service, sts)
    out = {"x-amz-date": amz_date,
           "Authorization": (
               f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
               f"SignedHeaders={signed}, Signature={sig}")}
    for k, v in (extra_headers or {}).items():
        out[k] = v
    return out


# -- EC2 Query XML ---------------------------------------------------------
def _strip_ns(root: ET.Element) -> ET.Element:
    for el in root.iter():
        if "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    return root


def _items(el: Optional[ET.Element], path: str) -> List[ET.Element]:
    return [] if el is None else el.findall(path + "/item")


def _text(el: ET.Element, path: str, default: str = "") -> str:
    got = el.findtext(path)
    return got if got is not None else default


def _tag_name(el: ET.Element, fallback: str) -> str:
    for t in _items(el, "tagSet"):
        if _text(t, "key") == "Name":
            return _text(t, "value") or fallback
    return fallback


class AwsPlatform:
    """check_auth()/get_cloud_data() against the EC2 Query API.

    `regions`: explicit include list; empty = DescribeRegions fan-out
    (the reference's includeRegions/excludeRegions knob).
    `endpoint_template`: '{region}'-templated base URL — the real
    service default, or the fixture recorder under test."""

    def __init__(self, domain: str, access_key_id: str,
                 secret_access_key: str,
                 regions: Sequence[str] = (),
                 api_default_region: str = "us-east-1",
                 endpoint_template: str =
                 "https://ec2.{region}.amazonaws.com/",
                 timeout_s: float = 15.0) -> None:
        self.domain = domain
        self.access_key_id = access_key_id
        self.secret_access_key = secret_access_key
        self.include_regions = tuple(regions)
        self.api_default_region = api_default_region
        self.endpoint_template = endpoint_template
        self.timeout_s = timeout_s
        self.api_calls = 0

    # -- transport ---------------------------------------------------------
    def _call(self, region: str, action: str,
              params: Optional[Dict[str, str]] = None) -> ET.Element:
        url = self.endpoint_template.format(region=region)
        form = {"Action": action, "Version": EC2_API_VERSION,
                **(params or {})}
        body = urllib.parse.urlencode(sorted(form.items())).encode()
        headers = sigv4_headers(
            "POST", url, body, self.access_key_id,
            self.secret_access_key, region,
            extra_headers={"content-type":
                           "application/x-www-form-urlencoded"})
        req = urllib.request.Request(url, data=body, headers=headers)
        self.api_calls += 1
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return _strip_ns(ET.fromstring(resp.read()))

    def _paged(self, region: str, action: str, set_path: str,
               params: Optional[Dict[str, str]] = None
               ) -> List[ET.Element]:
        """Follow nextToken until exhausted (DescribeInstances pages)."""
        out: List[ET.Element] = []
        token: Optional[str] = None
        for _ in range(64):                      # hostile-loop bound
            p = dict(params or {})
            if token:
                p["NextToken"] = token
            root = self._call(region, action, p)
            out.extend(_items(root, set_path))
            token = root.findtext("nextToken")
            if not token:
                break
        return out

    # -- platform contract -------------------------------------------------
    def check_auth(self) -> None:
        """DescribeRegions doubles as the credential probe (aws.go
        CheckAuth): a signature or permission error raises here."""
        self._regions()

    def _regions(self) -> List[str]:
        root = self._call(self.api_default_region, "DescribeRegions")
        names = [_text(r, "regionName")
                 for r in _items(root, "regionInfo")]
        if self.include_regions:
            names = [n for n in names if n in self.include_regions]
        return names

    def get_cloud_data(self) -> List[Resource]:
        b = ResourceBuilder(self.domain)
        add = b.add

        for region in self._regions():
            region_id = add("region", region, region)
            azs = self._call(region, "DescribeAvailabilityZones")
            for az in _items(azs, "availabilityZoneInfo"):
                add("az", _text(az, "zoneName"), _text(az, "zoneName"),
                    region_id=region_id)
            for vpc in self._paged(region, "DescribeVpcs", "vpcSet"):
                vpc_id = _text(vpc, "vpcId")
                add("vpc", vpc_id, _tag_name(vpc, vpc_id),
                    region_id=region_id, cidr=_text(vpc, "cidrBlock"))
            for sn in self._paged(region, "DescribeSubnets", "subnetSet"):
                sn_id = _text(sn, "subnetId")
                epc = b.get("vpc", _text(sn, "vpcId"))
                add("subnet", sn_id, _tag_name(sn, sn_id),
                    epc_id=epc, cidr=_text(sn, "cidrBlock"),
                    az=_text(sn, "availabilityZone"))
            for rsv in self._paged(region, "DescribeInstances",
                                   "reservationSet"):
                for inst in _items(rsv, "instancesSet"):
                    iid = _text(inst, "instanceId")
                    epc = b.get("vpc", _text(inst, "vpcId"))
                    ip = _text(inst, "privateIpAddress")
                    # EC2 instances are VMs (reference aws.go GetVMs ->
                    # chost rows, VIF_DEVICE_TYPE_VM), not hypervisor
                    # hosts — the round-5 model carries both types
                    add("vm", iid, _tag_name(inst, iid),
                        epc_id=epc, vpc_id=epc, ip=ip,
                        az=_text(inst, "placement/availabilityZone"),
                        subnet=_text(inst, "subnetId"))
            # ENIs -> vinterface + lan/wan ip rows (reference
            # vinterface_and_ip.go: unattached ENIs skipped, private
            # addresses as LAN ips, the association's public ip as
            # the WAN ip)
            for eni in self._paged(region, "DescribeNetworkInterfaces",
                                   "networkInterfaceSet"):
                eid = _text(eni, "networkInterfaceId")
                inst = _text(eni, "attachment/instanceId")
                if not eid or not inst:
                    continue
                vif = add("vinterface", eid, eid,
                          mac=_text(eni, "macAddress"),
                          subnet_id=b.get("subnet",
                                          _text(eni, "subnetId")),
                          device_vm_id=b.get("vm", inst))
                for ip_e in _items(eni, "privateIpAddressesSet"):
                    ip = _text(ip_e, "privateIpAddress")
                    if ip:
                        add("lan_ip", f"{eid}/{ip}", ip,
                            vinterface_id=vif, ip=ip)
                    # EIPs on SECONDARY private ips nest under each
                    # address item (vinterface_and_ip.go walks them
                    # all; the eni-level association is the primary)
                    pub2 = _text(ip_e, "association/publicIp")
                    if pub2:
                        add("wan_ip", f"{eid}/{pub2}", pub2,
                            vinterface_id=vif, ip=pub2)
                pub = _text(eni, "association/publicIp")
                if pub:
                    add("wan_ip", f"{eid}/{pub}", pub,
                        vinterface_id=vif, ip=pub)
            # NAT gateways ride the SAME EC2 Query API (reference
            # nat_gateway.go DescribeNatGateways); their public
            # addresses land as nat-linked floating_ips
            for nat in self._paged(region, "DescribeNatGateways",
                                   "natGatewaySet"):
                nid = _text(nat, "natGatewayId")
                if not nid:
                    continue
                # deleted gateways linger in DescribeNatGateways for
                # ~1h (their public IPs may already be reassigned);
                # the reference keeps only available ones
                # (aws/nat_gateway.go:60)
                if _text(nat, "state") != "available":
                    continue
                epc = b.get("vpc", _text(nat, "vpcId"))
                nat_rid = add("nat_gateway", nid, _tag_name(nat, nid),
                              vpc_id=epc, region_id=region_id)
                for addr in _items(nat, "natGatewayAddressSet"):
                    ip = _text(addr, "publicIp")
                    if ip:
                        add("floating_ip", f"{nid}/{ip}", ip,
                            vpc_id=epc, ip=ip,
                            nat_gateway_id=nat_rid)
        return b.rows()

"""ISSUE 6: the accuracy observatory + occupancy profiler.

The contracts under test: the exact shadow samples DETERMINISTICALLY by
flow-key hash (same keys after any restart or re-chunking), its exact
answers agree with the device sketch within the theoretical bounds on a
seeded stream, the audit lane is BIT-INVISIBLE to the sketch path
(state identical with the audit on/off), the bound-violation alarm
trips and clears breaker-style, and the profiler's bounded ring exports
a schema-valid Chrome-trace/Perfetto timeline."""

import json

import numpy as np
import pytest

from deepflow_tpu.batch.schema import L4_SCHEMA
from deepflow_tpu.models import flow_suite
from deepflow_tpu.models.flow_suite import FlowSuiteConfig, FlowWindowOutput
from deepflow_tpu.replay.generator import SyntheticAgent
from deepflow_tpu.runtime.audit import ShadowAuditor
from deepflow_tpu.runtime.faults import default_faults
from deepflow_tpu.runtime.profiler import OccupancyProfiler, default_profiler
from deepflow_tpu.runtime.tpu_sketch import TpuSketchExporter
from deepflow_tpu.runtime.tracing import default_tracer


@pytest.fixture(autouse=True)
def _clean_faults():
    default_faults().disarm()
    yield
    default_faults().disarm()


def _stream(n=40000, pool=512, seed=0xC0FFEE):
    """Pooled Zipf stream: heavy hitters genuinely repeat, so exact
    top-K is well-defined (the recall-harness feed)."""
    return SyntheticAgent(seed=seed).l4_columns_pooled(n, pool=pool)


def _chunks(cols, rows=8000):
    n = len(next(iter(cols.values())))
    return [{k: v[i:i + rows] for k, v in cols.items()}
            for i in range(0, n, rows)]


def _exporter(audit_rate, **kw):
    kw.setdefault("wire", "lanes")
    return TpuSketchExporter(store=None, window_seconds=3600,
                             batch_rows=4096, audit_rate=audit_rate, **kw)


# ---------------------------------------------------- sampler determinism

def test_sampler_deterministic_across_restarts():
    """The flow-hash sample admits the SAME keys with the SAME exact
    counts regardless of process lifetime or chunking — a restarted
    auditor over a replayed stream rebuilds the identical shadow."""
    cfg = FlowSuiteConfig()
    cols = _stream(20000)
    a = ShadowAuditor(cfg, rate=0.25)
    b = ShadowAuditor(cfg, rate=0.25)       # the "restarted" process
    for c in _chunks(cols, rows=5000):
        a.absorb(c)
    for c in _chunks(cols, rows=1777):      # different chunking
        b.absorb(c)
    assert a._counts and a._counts == b._counts
    assert a._clients == b._clients
    np.testing.assert_array_equal(a._ent, b._ent)
    # and the sample is a sample, not everything
    total_keys = len(np.unique(np.concatenate(
        [np.atleast_1d(v) for v in [c["ip_src"] for c in [cols]]])))
    assert 0 < len(a._counts)
    assert a.sampled_rows_total < a.rows_seen_total


def test_sample_rate_scales_admission():
    cfg = FlowSuiteConfig()
    cols = _stream(20000, pool=2048)
    lo = ShadowAuditor(cfg, rate=1.0 / 16)
    hi = ShadowAuditor(cfg, rate=1.0)
    for c in _chunks(cols):
        lo.absorb(c)
        hi.absorb(c)
    assert hi.sampled_rows_total == hi.rows_seen_total == 20000
    # rate 1/16 admits roughly 1/16 of distinct keys (hash-uniform)
    frac = len(lo._counts) / len(hi._counts)
    assert 0.02 < frac < 0.2


# ------------------------------------------- exact shadow vs live sketch

def test_shadow_agrees_with_sketch_on_seeded_stream():
    """Full-rate shadow vs the device sketch: CMS error within e/width,
    HLL within its bound, entropy within the plug-in bound, top-K
    recall >= 0.9, no violation — on a seeded Zipf stream."""
    exp = _exporter(audit_rate=1.0)
    for c in _chunks(_stream()):
        exp.process([("l4_flow_log", 0, c)])
    exp.flush_window()
    snap = exp._audit.last_window
    assert snap is not None and snap["rows_match"]
    assert snap["cms_rel_error"] <= exp._audit.cms_eps_theory
    assert snap["hll_rel_error"] <= snap["hll_eps_bound"]
    assert snap["entropy_abs_error"] <= snap["entropy_bound"]
    assert snap["topk_recall"] >= 0.9
    assert not snap["violation"] and not exp._audit.alarm
    exp.close()


@pytest.mark.parametrize("wire,depth", [("lanes", 0), ("lanes", 2),
                                        ("dict", 2)])
def test_audit_is_bit_invisible_to_sketch_state(wire, depth):
    """The acceptance bar: sketch state with the audit on is IDENTICAL
    to the audit off, on both wires, with and without the feed."""
    import jax

    on = _exporter(1.0, wire=wire, prefetch_depth=depth)
    off = _exporter(0.0, wire=wire, prefetch_depth=depth)
    for c in _chunks(_stream(16000)):
        on.process([("l4_flow_log", 0, c)])
        off.process([("l4_flow_log", 0, c)])
    for e in (on, off):
        if e._feed is not None:
            assert e._feed.drain(30)
    for a, b in zip(jax.tree.leaves(on.state), jax.tree.leaves(off.state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert on._audit.rows_seen_total == on.rows_in == off.rows_in
    on.close()
    off.close()


def test_audit_conservation_through_degraded_mode():
    """Every processed row is observed by the audit exactly once —
    including rows that die on the device and rows absorbed by the
    degraded host fallback — and the degraded window is audited,
    tagged, and kept OUT of the alarm ladder."""
    f = default_faults()
    sites = f.arm_spec("tpu.device_error:count=2;seed=3")
    try:
        exp = _exporter(1.0)
        exp.degrade_after = 1
        sent = 0
        for c in _chunks(_stream(24000)):
            exp.process([("l4_flow_log", 0, c)])
            sent += len(next(iter(c.values())))
        assert exp.device_errors >= 1 and exp.degraded
        exp.flush_window()
        a = exp._audit
        assert a.rows_seen_total == exp.rows_in == sent
        assert a.degraded_windows >= 1
        assert a.last_window["degraded"]
        assert not a.alarm and a._violations == 0
    finally:
        for s in sites:
            f.disarm(s)
    exp.close()


def test_lossy_window_tagged_not_alarmed():
    """One device error inside a window: the loss is counted by the
    exporter and the window's audit snapshot carries lossy=True (its
    comparison is expected to disagree) without advancing the alarm."""
    f = default_faults()
    sites = f.arm_spec("tpu.device_error:count=1;seed=5")
    try:
        exp = _exporter(1.0)          # degrade_after=2: one error stays
        for c in _chunks(_stream(24000)):   # on the device lane
            exp.process([("l4_flow_log", 0, c)])
        assert exp.device_errors == 1 and not exp.degraded
        exp.flush_window()
        snap = exp._audit.last_window
        assert snap["lossy"] and exp._audit.lossy_windows == 1
        assert exp._audit._violations == 0
    finally:
        for s in sites:
            f.disarm(s)
    exp.close()


# --------------------------------------------------- alarm ladder (trip)

def _window_out(cfg, keys, counts, card, ent, rows):
    k = np.full(cfg.top_k, 0xFFFFFFFF, np.uint32)
    c = np.full(cfg.top_k, -1, np.int32)
    k[:len(keys)] = keys
    c[:len(counts)] = counts
    return FlowWindowOutput(
        topk_keys=k, topk_counts=c,
        service_cardinality=np.asarray([card], np.float32),
        entropies=np.asarray(ent, np.float32),
        rows=np.asarray(rows, np.int32))


def test_alarm_trips_on_consecutive_violations_and_clears():
    """Breaker-style: N consecutive bound violations trip the alarm
    (surfaced on /healthz via the exporter property), M consecutive
    in-bound windows clear it; a single bad window never trips."""
    from deepflow_tpu.utils.u32 import fold_columns_np

    cfg = FlowSuiteConfig()
    a = ShadowAuditor(cfg, rate=1.0, trip_windows=3, clear_windows=2,
                      min_sampled_rows=10)
    cols = _stream(4000, pool=64)

    def one_window(honest: bool):
        for c in _chunks(cols, rows=4000):
            a.absorb(c)
        keys = np.array(sorted(a._counts, key=a._counts.get,
                               reverse=True)[:cfg.top_k], np.uint64)
        exact = np.array([a._counts[int(k)] for k in keys], np.int64)
        dev = exact if honest else exact + 4000   # way past eps*N
        # honest sibling numbers so only the CMS verdict varies
        card = len(a._clients) / a.rate
        h = a._ent.astype(np.float64)
        tot = h.sum(axis=1, keepdims=True)
        p = h / np.maximum(tot, 1.0)
        with np.errstate(divide="ignore", invalid="ignore"):
            xlogx = np.where(p > 0, p * np.log(p), 0.0)
        ent = -xlogx.sum(axis=1) / np.log(a._buckets)
        return a.close_window(_window_out(
            cfg, keys.astype(np.uint32),
            np.minimum(dev, 2**31 - 1).astype(np.int32),
            card, ent, rows=4000))

    assert not one_window(honest=True)["violation"]
    assert one_window(honest=False)["violation"] and not a.alarm
    one_window(honest=False)
    assert not a.alarm                       # 2 consecutive: still armed
    one_window(honest=False)
    assert a.alarm and a.alarm_trips == 1    # 3rd consecutive: tripped
    one_window(honest=True)
    assert a.alarm                           # 1 healthy: not yet cleared
    one_window(honest=True)
    assert not a.alarm                       # 2 healthy: cleared


def test_alarm_surfaces_on_healthz():
    from deepflow_tpu.enrich.platform_data import PlatformDataManager
    from deepflow_tpu.pipelines import Ingester, IngesterConfig

    ing = Ingester(IngesterConfig(listen_port=0,
                                  tpu_sketch_window_s=3600),
                   platform=PlatformDataManager())
    try:
        assert ing.health()["accuracy_alarm"] is False
        assert ing.tpu_sketch._audit is not None     # on by default
        ing.tpu_sketch._audit.alarm = True
        h = ing.health()
        assert h["accuracy_alarm"] and not h["ok"]
    finally:
        ing.tpu_sketch._audit.alarm = False
        ing.close()


def test_shadow_key_cap_clips_and_tags():
    cfg = FlowSuiteConfig()
    a = ShadowAuditor(cfg, rate=1.0, max_keys=64)
    rng = np.random.default_rng(9)
    cols = {name: rng.integers(0, 1 << 20, 4000).astype(dt)
            for name, dt in L4_SCHEMA.columns}
    a.absorb(cols)
    assert a.evicted_keys > 0 and a._clipped
    assert len(a._counts) <= 64
    snap = a.close_window(None)
    assert snap["clipped"] and a.clipped_windows == 1


# -------------------------------------------------------------- profiler

def test_profiler_ring_overflow_bounded():
    p = OccupancyProfiler(ring=32)
    for i in range(100):
        p.record("device", f"s{i}", 0.001)
    c = p.counters()
    assert c["spans"] == 100 and c["dropped"] == 68
    t = p.to_chrome_trace()
    xs = [e for e in t["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 32                      # ring keeps the newest 32
    assert xs[-1]["name"] == "s99"


def test_profiler_busy_fraction_union_math():
    import time as _time

    p = OccupancyProfiler()
    now = _time.time()
    # two overlapping 1s intervals + one disjoint 1s interval over a
    # 10s window anchored at the earliest span start -> 2s covered
    p.record("device", "a", 1.0, t_end=now - 8.0)    # [-9, -8]
    p.record("device", "b", 1.0, t_end=now - 8.5)    # [-9.5, -8.5] overlap
    p.record("device", "c", 1.0, t_end=now - 2.0)    # [-3, -2]
    frac = p.busy_fraction("device", horizon_s=30.0, now=now)
    window = 9.5                                     # earliest start -> now
    assert abs(frac - 2.5 / window) < 0.02
    assert p.busy_fraction("feed", horizon_s=30.0, now=now) == 0.0
    # stall accumulation
    p.add_stall(0.5)
    p.add_stall(0.25)
    assert abs(p.gauges()["tpu_feed_stall_seconds"] - 0.75) < 1e-9


def test_chrome_trace_schema_valid():
    """The Perfetto/chrome://tracing JSON contract: a traceEvents array
    of complete ('X') events with numeric microsecond ts/dur, pid/tid,
    and per-track thread_name metadata — json-serializable as-is."""
    p = OccupancyProfiler()
    p.record("feed", "group[2]", 0.003, rows=2048)
    p.record("device", "update", 0.002, rows=2048)
    p.record("fence", "wait", 0.001)
    doc = json.loads(json.dumps(p.to_chrome_trace()))
    assert isinstance(doc["traceEvents"], list)
    assert doc["displayTimeUnit"] == "ms"
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {m["args"]["name"] for m in metas} == {"feed", "device",
                                                  "fence"}
    assert len(xs) == 3
    for e in xs:
        assert set(e) >= {"name", "cat", "ph", "ts", "dur", "pid", "tid"}
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert e["dur"] >= 0 and e["tid"] >= 1
    tids = {m["tid"] for m in metas}
    assert all(e["tid"] in tids for e in xs)


def test_feed_populates_device_track_and_stall():
    """The overlapped feed feeds the profiler: device intervals from
    dispatch->fence, feed group spans, and starvation time while the
    window sits empty."""
    prof = default_profiler()
    prof.reset()
    exp = _exporter(0.0, prefetch_depth=2, coalesce_batches=2)
    for c in _chunks(_stream(16000)):
        exp.process([("l4_flow_log", 0, c)])
    assert exp._feed.drain(30)
    tracks = {s[0] for s in prof._snapshot()}
    assert {"feed", "device", "fence"} <= tracks
    assert prof.busy_fraction("device") > 0
    exp.close()


# ------------------------------------------------ exposition + CLI + debug

def test_gauges_on_metrics_render():
    """/metrics carries the audit error gauges (HELP-documented, strict
    checker clean) and the profiler occupancy gauges every scrape."""
    from deepflow_tpu.runtime.promexpo import (render_metrics,
                                               validate_exposition)
    from deepflow_tpu.runtime.stats import StatsRegistry

    tr = default_tracer()
    tr.reset()
    tr.enable()
    try:
        reg = StatsRegistry()
        exp = _exporter(1.0, stats=reg)
        for c in _chunks(_stream(16000)):
            exp.process([("l4_flow_log", 0, c)])
        exp.flush_window()
        text = render_metrics(reg, tr)
        assert validate_exposition(text) == []
        for needle in ("deepflow_tpu_sketch_accuracy_windows",
                       "deepflow_trace_tpu_audit_cms_rel_error",
                       "deepflow_trace_tpu_audit_topk_recall",
                       "tpu_device_busy_fraction",
                       "tpu_feed_stall_seconds"):
            assert needle in text, f"{needle} absent"
        exp.close()
    finally:
        tr.disable()


def test_gauge_without_help_fails_strict_validation():
    from deepflow_tpu.runtime.promexpo import validate_exposition

    bad = "# TYPE mystery gauge\nmystery 1\n"
    assert any("lacks HELP" in p for p in validate_exposition(bad))
    ok = "# HELP mystery documented\n# TYPE mystery gauge\nmystery 1\n"
    assert validate_exposition(ok) == []
    # the format does not mandate HELP-before-TYPE: a third-party
    # exposition with the comments swapped is still valid
    swapped = "# TYPE mystery gauge\n# HELP mystery documented\nmystery 1\n"
    assert validate_exposition(swapped) == []


def test_entropy_gauge_advisory_at_sampled_rates():
    """Per-key admission makes the sampled shadow's entropy a CLUSTER
    sample: a heavy key hashed out of the sample is missing from every
    window deterministically. At rate < 1 the entropy gauge must never
    feed the alarm verdict (only CMS/HLL/recall can), or a healthy
    ingester would flip /healthz 503 during exactly the heavy-hitter
    event it exists to detect."""
    cfg = FlowSuiteConfig()
    a = ShadowAuditor(cfg, rate=0.5, min_sampled_rows=10)
    cols = _stream(8000, pool=64)
    for c in _chunks(cols, rows=8000):
        a.absorb(c)
    keys = np.array(sorted(a._counts, key=a._counts.get,
                           reverse=True)[:cfg.top_k], np.uint64)
    exact = np.array([a._counts[int(k)] for k in keys], np.int64)
    card = len(a._clients) / a.rate
    # device entropy wildly different from the shadow's: at full rate
    # this is a violation, at a sampled rate it must be advisory
    snap = a.close_window(_window_out(
        cfg, keys.astype(np.uint32),
        np.minimum(exact, 2**31 - 1).astype(np.int32),
        card, [0.0, 0.0, 0.0, 0.0], rows=8000))
    assert snap["entropy_abs_error"] > snap["entropy_bound"]
    assert not snap["violation"]


def test_trace_export_fits_one_datagram_at_cap():
    """A full ring exported at the cap must come back through the UDP
    debug protocol, not be replaced by the response-too-large error."""
    from deepflow_tpu.runtime.debug import DebugServer, debug_request
    from deepflow_tpu.runtime.stats import StatsRegistry

    prof = default_profiler()
    prof.reset()
    for i in range(1000):
        prof.record("device", f"update:lanes_x{i % 7}", 0.0123, rows=65536)
    srv = DebugServer(StatsRegistry(), port=0)
    srv.start()
    try:
        out = debug_request("trace-export", port=srv.port, limit=10_000,
                            timeout=10.0)
        assert out["ok"], out
        xs = [e for e in out["data"]["trace"]["traceEvents"]
              if e["ph"] == "X"]
        assert len(xs) == 350                  # server-side cap
    finally:
        srv.close()
        prof.reset()


def test_trace_export_debug_route_and_cli(tmp_path, capsys):
    """`df-ctl trace export` round-trip: debug route -> CLI -> a file
    that parses as a Chrome-trace document; `trace latency` renders the
    occupancy columns."""
    from deepflow_tpu.cli import main
    from deepflow_tpu.runtime.debug import DebugServer
    from deepflow_tpu.runtime.stats import StatsRegistry

    prof = default_profiler()
    prof.record("device", "update", 0.002, rows=1024)
    srv = DebugServer(StatsRegistry(), port=0)
    srv.start()
    try:
        out_path = tmp_path / "trace.json"
        rc = main(["--debug-port", str(srv.port), "trace", "export",
                   "--out", str(out_path)])
        assert rc == 0
        doc = json.loads(out_path.read_text())
        assert any(e["ph"] == "X" for e in doc["traceEvents"])
        capsys.readouterr()
        tr = default_tracer()
        tr.enable()
        tr.observe("kernel", 0.002)
        try:
            rc = main(["--debug-port", str(srv.port), "trace",
                       "latency"])
        finally:
            tr.disable()
        assert rc == 0
        text = capsys.readouterr().out
        assert "DEVICE_BUSY_FRAC" in text
        assert "FEED_OVERLAP_EFF" in text
    finally:
        srv.close()


# -------------------------------------------------- sharded (mesh) audit

def test_sharded_suite_inherits_audit(rng):
    """ShardedFlowSuite with an attached auditor: host batches are
    mirrored with per-shard attribution, and flush closes the audit
    window against the MERGED output — the path the future pod-merged
    sketch inherits."""
    from deepflow_tpu.parallel import ShardedFlowSuite, make_mesh

    cfg = FlowSuiteConfig(cms_log2_width=14, ring_size=512,
                          hll_groups=64, hll_precision=8)
    mesh = make_mesh()
    suite = ShardedFlowSuite(cfg, mesh)
    auditor = ShadowAuditor(cfg, rate=1.0, shards=suite.n_devices)
    suite.attach_auditor(auditor)
    state = suite.init()
    B, n_batches = 1 << 13, 6     # several batches: ring admission is
    cols = _stream(B * n_batches, pool=256)   # sampled 1/16 per batch,
    mask = np.ones(B, bool)       # a heavy key needs a few to land
    for i in range(n_batches):
        batch = {k: np.ascontiguousarray(
                     v[i * B:(i + 1) * B]).astype(np.uint32)
                 for k, v in cols.items()
                 if k in ("ip_src", "ip_dst", "port_src", "port_dst",
                          "proto", "packet_tx", "packet_rx")}
        dc, md = suite.put_batch(batch, mask)
        state = suite.update(state, dc, md)
    assert auditor.rows_seen_total == B * n_batches
    assert sum(auditor._shard_rows) == auditor.sampled_rows_total
    assert all(r > 0 for r in auditor._shard_rows)
    # masked (padding) rows are excluded from the shadow exactly like
    # the device excludes them — the shadow must not audit rows the
    # sketch never saw
    part = np.zeros(B, bool)
    part[:100] = True
    batch = {k: np.ascontiguousarray(v[:B]).astype(np.uint32)
             for k, v in cols.items()
             if k in ("ip_src", "ip_dst", "port_src", "port_dst",
                      "proto", "packet_tx", "packet_rx")}
    dc, md = suite.put_batch(batch, part)
    state = suite.update(state, dc, md)
    assert auditor.rows_seen_total == B * n_batches + 100
    state, out = suite.flush(state)
    snap = auditor.last_window
    assert snap is not None and snap["sampled_keys"] > 0
    assert snap["topk_recall"] >= 0.9
    assert snap["cms_rel_error"] <= auditor.cms_eps_theory

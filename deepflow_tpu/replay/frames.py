"""Synthetic raw-frame builders: eth/ipv4 tcp+udp, vlan, simple tunnels.

The replay analogue of the reference's packet-crafting test helpers
(agent/resources/test/ fixture style): hand-built frames that exercise
the batch packet decoder (agent/packet.py) without a capture device.
Used by examples, fixture tests, and the replay CLI.
"""

from __future__ import annotations

import struct

SYN = 0x02
ACK = 0x10
FIN = 0x01
RST = 0x04
PSH = 0x08


def ip4(a: int, b: int, c: int, d: int) -> int:
    """Dotted quad -> the u32 the decoder and schemas carry."""
    return (a << 24) | (b << 16) | (c << 8) | d


def _eth_ipv4(src: int, dst: int, proto: int, l4: bytes,
              vlan: bool = False) -> bytes:
    """eth(+optional 802.1Q) + ipv4(proto) + the given l4 bytes — the
    one header pack every builder shares."""
    eth = b"\x02" * 6 + b"\x04" * 6
    eth += (b"\x81\x00\x00\x01\x08\x00" if vlan else b"\x08\x00")
    ip = struct.pack(">BBHHHBBHII", 0x45, 0, 20 + len(l4), 0, 0, 64,
                     proto, 0, src, dst)
    return eth + ip + l4


def eth_ipv4_tcp(src: int, dst: int, sport: int, dport: int,
                 flags: int = ACK, payload: bytes = b"", seq: int = 0,
                 ack: int = 0, win: int = 8192,
                 vlan: bool = False) -> bytes:
    """One eth(+optional 802.1Q)/ipv4/tcp frame."""
    tcp = struct.pack(">HHIIBBHHH", sport, dport, seq, ack, 0x50, flags,
                      win, 0, 0) + payload
    return _eth_ipv4(src, dst, 6, tcp, vlan=vlan)


def eth_ipv4_udp(src: int, dst: int, sport: int, dport: int,
                 payload: bytes = b"") -> bytes:
    """One eth/ipv4/udp frame."""
    udp = struct.pack(">HHHH", sport, dport, 8 + len(payload), 0) + payload
    return _eth_ipv4(src, dst, 17, udp)


def eth_ipv6_tcp(src16: bytes, dst16: bytes, sport: int, dport: int,
                 flags: int = ACK, payload: bytes = b"",
                 seq: int = 0) -> bytes:
    """One eth/ipv6/tcp frame (fixed 40-byte v6 header, 16-byte
    addresses)."""
    tcp = struct.pack(">HHIIBBHHH", sport, dport, seq, 0, 0x50, flags,
                      8192, 0, 0) + payload
    ip6 = struct.pack(">IHBB", 0x60000000, len(tcp), 6, 64) \
        + src16 + dst16
    return b"\x02" * 6 + b"\x04" * 6 + b"\x86\xdd" + ip6 + tcp


def vxlan(outer_src: int, outer_dst: int, inner_frame: bytes,
          vni: int = 123) -> bytes:
    """Wrap an inner frame in vxlan/udp/ipv4 (decap tested in
    agent/packet.py)."""
    head = struct.pack(">BBHI", 0x08, 0, 0, vni << 8)
    return eth_ipv4_udp(outer_src, outer_dst, 5555, 4789,
                        head + inner_frame)


def gre_teb(outer_src: int, outer_dst: int, inner_frame: bytes,
            key: int | None = None) -> bytes:
    """Wrap an inner eth frame in GRE transparent-ethernet-bridging
    (proto 0x6558) over ipv4, with an optional GRE key."""
    if key is None:
        gre = struct.pack(">HH", 0, 0x6558)
    else:
        gre = struct.pack(">HHI", 0x2000, 0x6558, key)
    return _eth_ipv4(outer_src, outer_dst, 47, gre + inner_frame)


def erspan_i(outer_src: int, outer_dst: int, inner_frame: bytes) -> bytes:
    """ERSPAN type I: bare GRE proto 0x88BE (no S flag, no ERSPAN
    header) directly wrapping the inner eth frame."""
    return _eth_ipv4(outer_src, outer_dst, 47,
                     struct.pack(">HH", 0, 0x88BE) + inner_frame)


def erspan_ii(outer_src: int, outer_dst: int, inner_frame: bytes,
              span_id: int = 5) -> bytes:
    """ERSPAN type II: GRE (proto 0x88BE, S flag) + 8-byte ERSPAN
    header + inner eth frame."""
    gre = struct.pack(">HHI", 0x1000, 0x88BE, 7)        # S flag + seq
    ers = struct.pack(">HHI", (1 << 12), span_id, 0)    # ver 1 (type II)
    return _eth_ipv4(outer_src, outer_dst, 47, gre + ers + inner_frame)

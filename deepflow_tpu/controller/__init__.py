"""Controller: agent management, resource model, tag dictionaries.

Reference: server/controller/ — trisolaris (agent registration/config
push), genesis (agent-reported resources), recorder (cloud+genesis ->
MySQL resource model), tagrecorder (SmartEncoding dimension tables),
election (single master), monitor (agent liveness + ingester
rebalancing). The re-design keeps the same responsibilities with an
in-memory + JSON-persisted resource model, a file-lock election, and
HTTP (stdlib) in place of gRPC for the sync surface — the data-plane
wire stays the firehose.
"""

from deepflow_tpu.controller.cloud import (CloudManager, CloudTask,
                                           FileReaderPlatform, HttpPlatform,
                                           KubernetesGatherPlatform)
from deepflow_tpu.controller.model import ResourceModel
from deepflow_tpu.controller.recorder import Recorder
from deepflow_tpu.controller.registry import VTapRegistry
from deepflow_tpu.controller.server import ControllerServer

__all__ = ["ResourceModel", "Recorder", "VTapRegistry",
           "ControllerServer", "CloudManager", "CloudTask",
           "FileReaderPlatform", "HttpPlatform",
           "KubernetesGatherPlatform"]

"""Minimal x86-64 instruction-length decoder: walk code at instruction
granularity and find RET offsets.

Reference role: the agent attaches Go function EXIT probes as uprobes
on every RET instruction of the function body (uretprobes are unsafe
under goroutine stack moves), found by disassembling the function with
bddisasm — `agent/src/ebpf/user/symbol.c:184-232`
(resolve_func_ret_addr: NdDecodeEx loop collecting ND_INS_RETN/RETF).
This module is that capability in-tree: not a full disassembler, just
a length decoder complete enough to walk compiler-generated 64-bit
code (gcc/clang/Go output) so a RET byte inside an immediate or
displacement is never mistaken for an instruction boundary.

Coverage: legacy prefixes, REX, the one-byte map, the 0x0F two-byte
map, and the 0x0F38/0x0F3A three-byte maps (SSE/AVX-adjacent forms the
compilers emit), VEX (0xC4/0xC5). Unknown opcodes raise DecodeError —
a caller walking a function either gets boundaries it can trust or an
explicit failure (attaching a probe mid-instruction corrupts the
traced process; guessing is not an option).
"""

from __future__ import annotations

from typing import List

# one-byte opcodes with a ModRM byte
_MODRM_1B = set()
for _op in range(0x00, 0x40):
    # arithmetic blocks: 00-03, 08-0b, ... (the +4/+5 AL,imm forms and
    # 0x0f escape / segment pushes excluded below)
    if _op & 7 in (0, 1, 2, 3):
        _MODRM_1B.add(_op)
_MODRM_1B |= {0x62, 0x63, 0x69, 0x6B,
              0x80, 0x81, 0x82, 0x83, 0x84, 0x85, 0x86, 0x87,
              0x88, 0x89, 0x8A, 0x8B, 0x8C, 0x8D, 0x8E, 0x8F,
              0xC0, 0xC1, 0xC4, 0xC5, 0xC6, 0xC7,
              0xD0, 0xD1, 0xD2, 0xD3,
              0xD8, 0xD9, 0xDA, 0xDB, 0xDC, 0xDD, 0xDE, 0xDF,
              0xF6, 0xF7, 0xFE, 0xFF}

# one-byte opcodes: immediate size class
#   1 = imm8, 2 = imm16, 4 = imm32 (imm16 with 0x66), 8 = special
_IMM_1B = {
    0x04: 1, 0x0C: 1, 0x14: 1, 0x1C: 1, 0x24: 1, 0x2C: 1, 0x34: 1,
    0x3C: 1,                                    # <op> AL, imm8
    0x05: 4, 0x0D: 4, 0x15: 4, 0x1D: 4, 0x25: 4, 0x2D: 4, 0x35: 4,
    0x3D: 4,                                    # <op> eAX, imm32
    0x68: 4, 0x69: 4, 0x6A: 1, 0x6B: 1,
    0x80: 1, 0x81: 4, 0x82: 1, 0x83: 1,
    0xA8: 1, 0xA9: 4,
    0xC0: 1, 0xC1: 1, 0xC2: 2, 0xC6: 1, 0xC7: 4,
    0xCD: 1, 0xD4: 1, 0xD5: 1,
    0xE4: 1, 0xE5: 1, 0xE6: 1, 0xE7: 1,
    0xE8: 4, 0xE9: 4,
    0xEB: 1,
}
for _op in range(0x70, 0x80):                   # Jcc rel8
    _IMM_1B[_op] = 1
for _op in range(0xB0, 0xB8):                   # MOV r8, imm8
    _IMM_1B[_op] = 1
# B8-BF: MOV r, imm32 (imm64 with REX.W; imm16 with 0x66) — special
# A0-A3: MOV al/ax/eax/rax, moffs — 8-byte address in 64-bit mode
# E0-E3: LOOPcc/JCXZ rel8
for _op in (0xE0, 0xE1, 0xE2, 0xE3):
    _IMM_1B[_op] = 1

# two-byte (0F xx) opcodes WITHOUT ModRM
_NO_MODRM_2B = (set(range(0x80, 0x90))          # Jcc rel32
                | {0x05, 0x06, 0x07, 0x08, 0x09, 0x0B, 0x0E,
                   0x30, 0x31, 0x32, 0x33, 0x34, 0x35, 0x37,
                   0x77, 0xA0, 0xA1, 0xA2, 0xA8, 0xA9, 0xAA}
                | set(range(0xC8, 0xD0)))       # BSWAP
# two-byte opcodes with an imm8 after ModRM
_IMM8_2B = {0x70, 0x71, 0x72, 0x73, 0xA4, 0xAC, 0xBA, 0xC2, 0xC4,
            0xC5, 0xC6}

_PREFIXES = {0x26, 0x2E, 0x36, 0x3E, 0x64, 0x65, 0x66, 0x67,
             0xF0, 0xF2, 0xF3}


class DecodeError(ValueError):
    pass


def _modrm_len(code: bytes, i: int, addr32: bool) -> int:
    """Bytes consumed by ModRM + SIB + displacement starting at i."""
    if i >= len(code):
        raise DecodeError("truncated at ModRM")
    modrm = code[i]
    mod, rm = modrm >> 6, modrm & 7
    n = 1
    if mod == 3:
        return n
    if not addr32:          # 64-bit addressing (the normal case)
        if rm == 4:                              # SIB follows
            if i + 1 >= len(code):
                raise DecodeError("truncated at SIB")
            sib = code[i + 1]
            n += 1
            if mod == 0 and (sib & 7) == 5:
                n += 4                           # disp32 base
        if mod == 1:
            n += 1
        elif mod == 2:
            n += 4
        elif mod == 0 and rm == 5:
            n += 4                               # RIP-relative disp32
        return n
    # 0x67 16/32-bit addressing never appears in compiler output we
    # walk; refuse rather than silently mis-measure
    raise DecodeError("0x67 address-size override unsupported")


def insn_len(code: bytes, i: int = 0) -> int:
    """Length of the instruction starting at code[i]."""
    start = i
    osize16 = False
    rex_w = False
    addr32 = False
    # prefixes
    while i < len(code) and code[i] in _PREFIXES:
        if code[i] == 0x66:
            osize16 = True
        if code[i] == 0x67:
            addr32 = True
        i += 1
    if i < len(code) and 0x40 <= code[i] <= 0x4F:   # REX
        rex_w = bool(code[i] & 8)
        i += 1
    if i >= len(code):
        raise DecodeError("truncated in prefixes")
    op = code[i]
    i += 1

    if op in (0xC4, 0xC5):                      # VEX (not the LES/LDS
        # legacy forms — those don't exist in 64-bit mode)
        vex3 = op == 0xC4
        if i + (2 if vex3 else 1) > len(code):
            raise DecodeError("truncated in VEX")
        # the 3-byte form's first payload byte carries the opcode MAP
        # in its low 5 bits (1=0F, 2=0F38, 3=0F3A); the 2-byte form is
        # always map 1. The map decides the imm8: 0F3A instructions
        # ALWAYS carry one — measuring them short would desynchronize
        # the walk silently, the exact guess this module must refuse
        vmap = (code[i] & 0x1F) if vex3 else 1
        i += 2 if vex3 else 1
        if i >= len(code):
            raise DecodeError("truncated after VEX prefix")
        vop = code[i]
        i += 1
        if vmap == 1 and vop in _NO_MODRM_2B:
            return i - start                    # e.g. vzeroupper (77)
        i += _modrm_len(code, i, addr32)
        if vmap == 3:
            i += 1                              # 0F3A map: imm8 always
        elif vmap == 2:
            pass                                # 0F38 map: no imm
        elif vmap == 1:
            if vop in _IMM8_2B or vop in (0x4A, 0x4B, 0x44):
                i += 1
        else:
            raise DecodeError(f"unknown VEX map {vmap}")
        return i - start

    if op == 0x0F:
        if i >= len(code):
            raise DecodeError("truncated after 0F")
        op2 = code[i]
        i += 1
        if op2 in (0x38, 0x3A):                 # three-byte maps
            if i >= len(code):
                raise DecodeError("truncated after 0F38/3A")
            i += 1                              # the third opcode byte
            i += _modrm_len(code, i, addr32)
            if op2 == 0x3A:                     # 0F3A always carries imm8
                i += 1
            return i - start
        if 0x80 <= op2 <= 0x8F:                 # Jcc rel32
            return i - start + 4
        if op2 not in _NO_MODRM_2B:
            i += _modrm_len(code, i, addr32)
        if op2 in _IMM8_2B:
            i += 1
        return i - start

    if 0xD8 <= op <= 0xDF:                      # x87: ModRM only
        i += _modrm_len(code, i, addr32)
        return i - start

    if op in _MODRM_1B:
        i += _modrm_len(code, i, addr32)

    if 0xB8 <= op <= 0xBF:                      # MOV r, imm
        i += 8 if rex_w else (2 if osize16 else 4)
    elif 0xA0 <= op <= 0xA3:                    # MOV moffs (64-bit addr)
        i += 8
    elif op in _IMM_1B:
        n = _IMM_1B[op]
        if n == 4 and osize16:
            n = 2
        # group 3 TEST /0-/1 carries an immediate; F6/F7 handled below
        i += n
    elif op in (0xF6, 0xF7):
        # group 3: TEST (/0,/1) has an immediate, the rest don't —
        # the reg field of the ALREADY-CONSUMED ModRM decides
        modrm_at = start
        # re-find the modrm byte: prefixes + rex + opcode
        j = start
        while code[j] in _PREFIXES:
            j += 1
        if 0x40 <= code[j] <= 0x4F:
            j += 1
        j += 1                                  # the opcode itself
        reg = (code[j] >> 3) & 7
        if reg in (0, 1):
            i += 1 if op == 0xF6 else (2 if osize16 else 4)
    elif op in (0xC8,):                         # ENTER imm16, imm8
        i += 3
    elif op in (0x9A, 0xEA):
        raise DecodeError("far call/jmp invalid in 64-bit mode")

    return i - start


def find_ret_offsets(code: bytes) -> List[int]:
    """Offsets of RET instructions (C3 / C2 iw) at TRUE instruction
    boundaries within `code` (one function's bytes). Mirrors
    symbol.c:resolve_func_ret_addr; raises DecodeError on opcodes the
    walker doesn't know (caller treats the function as unprobeable
    rather than probing a guessed boundary)."""
    out: List[int] = []
    i = 0
    while i < len(code):
        op = code[i]
        # skip prefixes to identify the opcode for the RET test
        j = i
        while j < len(code) and code[j] in _PREFIXES:
            j += 1
        if j < len(code) and 0x40 <= code[j] <= 0x4F:
            j += 1
        if j < len(code) and code[j] in (0xC3, 0xC2):
            out.append(i)
        i += insn_len(code, i)
        del op
    return out

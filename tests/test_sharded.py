import numpy as np

import jax
import jax.numpy as jnp

from deepflow_tpu.models import FlowSuiteConfig, flow_suite
from deepflow_tpu.parallel import ShardedFlowSuite, make_mesh
from deepflow_tpu.replay import SyntheticAgent


def _batches(rng, n_batches=4, batch=4096):
    agent = SyntheticAgent()
    return [agent.l4_columns_pooled(batch) for _ in range(n_batches)]


def _to_device_cols(cols):
    keep = ("ip_src", "ip_dst", "port_src", "port_dst", "proto",
            "packet_tx", "packet_rx")
    return {k: jnp.asarray(cols[k].astype(np.uint32)) for k in keep}


def test_mesh_has_8_virtual_devices():
    assert len(jax.devices()) == 8
    mesh = make_mesh()
    assert mesh.shape["data"] == 8


def test_mesh_multi_axis_factoring():
    mesh = make_mesh(8, axes=("replica", "data"))
    assert mesh.shape["replica"] == 2 and mesh.shape["data"] == 4
    mesh = make_mesh(6, axes=("replica", "data"))
    assert mesh.shape["replica"] == 2 and mesh.shape["data"] == 3


def test_sharded_merge_equals_single_device(rng):
    """Linear sketches: 8-way sharded update + merge == single-device update."""
    cfg = FlowSuiteConfig(cms_log2_width=12, ring_size=256, hll_groups=64,
                          hll_precision=8, conservative=False)
    mesh = make_mesh()
    sharded = ShardedFlowSuite(cfg, mesh)
    state_d = sharded.init()

    single = flow_suite.init(cfg)
    batches = _batches(rng, n_batches=3)
    for cols in batches:
        dc = _to_device_cols(cols)
        mask = jnp.ones((len(cols["ip_src"]),), jnp.bool_)
        cd, md = sharded.put_batch(dc, mask)
        state_d = sharded.update(state_d, cd, md)
        single = jax.jit(
            lambda s, c, m: flow_suite.update(s, c, m, cfg))(single, dc, mask)

    state_d, out_sharded = sharded.flush(state_d)
    single, out_single = flow_suite.flush(single, cfg)

    np.testing.assert_array_equal(np.asarray(out_sharded.rows),
                                  np.asarray(out_single.rows))
    np.testing.assert_allclose(np.asarray(out_sharded.service_cardinality),
                               np.asarray(out_single.service_cardinality),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out_sharded.entropies),
                               np.asarray(out_single.entropies), atol=1e-5)
    # CMS totals identical (sum merge of a linear sketch)
    got = set(np.asarray(out_sharded.topk_keys)[:50].tolist())
    want = set(np.asarray(out_single.topk_keys)[:50].tolist())
    overlap = len(got & want) / 50
    assert overlap >= 0.9, overlap


def test_sharded_topk_recall_vs_exact(rng):
    cfg = FlowSuiteConfig(cms_log2_width=14, ring_size=1024, top_k=20,
                          hll_groups=64, hll_precision=8)
    mesh = make_mesh()
    sharded = ShardedFlowSuite(cfg, mesh)
    state = sharded.init()

    agent = SyntheticAgent()
    all_cols = []
    for _ in range(4):
        cols = agent.l4_columns_pooled(8192)
        all_cols.append(cols)
        dc = _to_device_cols(cols)
        mask = jnp.ones((8192,), jnp.bool_)
        cd, md = sharded.put_batch(dc, mask)
        state = sharded.update(state, cd, md)
    state, out = sharded.flush(state)

    # exact GROUP BY on the service flow key (numpy oracle)
    keys = np.concatenate([
        np.asarray(flow_suite.flow_key(_to_device_cols(c)))
        for c in all_cols
    ])
    uniq, counts = np.unique(keys, return_counts=True)
    want = set(uniq[np.argsort(counts)[::-1][:20]].tolist())
    got = set(np.asarray(out.topk_keys).tolist())
    recall = len(got & want) / 20
    assert recall >= 0.95, recall

    # after flush, state is clean
    state2, out2 = sharded.flush(state)
    assert int(np.asarray(out2.rows)) == 0


def _metric_batch(rng, n):
    from deepflow_tpu.models.metrics_suite import (ENTROPY_FEATURES,
                                                   GOLDEN_SIGNALS)
    cols = {}
    for f in ENTROPY_FEATURES:
        cols[f] = jnp.asarray(
            rng.integers(0, 500, n).astype(np.uint32))
    for s in GOLDEN_SIGNALS:
        cols[s] = jnp.asarray(
            rng.integers(0, 10_000, n).astype(np.uint32))
    return cols


def test_sharded_metrics_suite_equals_one_device(rng):
    """BASELINE.md config 5 invariant: the 8-device ShardedMetricsSuite
    (entropy psum merge + PCA grad psum) produces the same window outputs
    and the same replicated PCA basis as the 1-device run of the SAME
    distributed algorithm on the full batch."""
    from deepflow_tpu.models.metrics_suite import MetricsSuiteConfig
    from deepflow_tpu.parallel import ShardedMetricsSuite

    from deepflow_tpu.models import metrics_suite

    cfg = MetricsSuiteConfig(entropy_log2_buckets=8)
    wide = ShardedMetricsSuite(cfg, make_mesh(8))
    one = ShardedMetricsSuite(cfg, make_mesh(1))
    s8, s1 = wide.init(), one.init()
    plain = metrics_suite.init(cfg)   # the single-device suite itself

    n = 2048
    for _ in range(3):
        cols = _metric_batch(rng, n)
        mask = jnp.ones((n,), jnp.bool_)
        c8, m8 = wide.put_batch(cols, mask)
        c1, m1 = one.put_batch(cols, mask)
        s8 = wide.update(s8, c8, m8)
        s1 = one.update(s1, c1, m1)
        plain = jax.jit(lambda s, c, m: metrics_suite.update(s, c, m, cfg))(
            plain, cols, mask)

    last = _metric_batch(rng, n)
    mask = jnp.ones((n,), jnp.bool_)
    s8, out8 = wide.flush(s8, *wide.put_batch(last, mask))
    s1, out1 = one.flush(s1, *one.put_batch(last, mask))
    plain, outp = jax.jit(
        lambda s, c, m: metrics_suite.flush(s, c, m, cfg))(plain, last, mask)

    # the sharded suite IS MetricsSuite-over-a-mesh: plain single-device
    # update/flush match the 1-device mesh run
    np.testing.assert_array_equal(np.asarray(outp.entropies),
                                  np.asarray(out1.entropies))
    np.testing.assert_allclose(np.asarray(plain.pca.w),
                               np.asarray(s1.pca.w)[0],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(outp.anomaly_scores),
                               np.asarray(out1.anomaly_scores),
                               rtol=1e-5, atol=1e-6)

    # entropy histograms are integer adds: merged == single exactly
    np.testing.assert_array_equal(np.asarray(out8.entropies),
                                  np.asarray(out1.entropies))
    np.testing.assert_allclose(np.asarray(out8.z_scores),
                               np.asarray(out1.z_scores), rtol=1e-5)
    assert bool(np.asarray(out8.ddos_alarm)) == \
        bool(np.asarray(out1.ddos_alarm))
    # the psum'd Oja step keeps the basis replicated and equal to the
    # full-batch step (float tolerance: reduction order differs)
    w8 = np.asarray(jax.tree.map(lambda x: x, s8.pca.w))
    assert w8.shape[0] == 8
    for d in range(1, 8):
        np.testing.assert_allclose(w8[d], w8[0], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w8[0], np.asarray(s1.pca.w)[0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out8.anomaly_scores),
                               np.asarray(out1.anomaly_scores),
                               rtol=1e-4, atol=1e-5)
    # matrix-profile rings hold POST-psum window vectors: the merged
    # 8-way scores must equal the 1-device and plain-suite scores (the
    # psum-before-push invariant — a pre-merge push would diverge here)
    np.testing.assert_allclose(np.asarray(outp.mp_scores),
                               np.asarray(out1.mp_scores),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out8.mp_scores),
                               np.asarray(out1.mp_scores),
                               rtol=1e-4, atol=1e-5)
    r8 = np.asarray(s8.mp.ring)
    for d in range(1, 8):
        np.testing.assert_allclose(r8[d], r8[0], rtol=1e-5, atol=1e-6)


def test_sharded_app_suite_matches_single():
    """Sharded AppSuite == single-device AppSuite: the whole-state psum
    merge must reproduce the unsharded answer exactly."""
    import numpy as np

    import jax

    from deepflow_tpu.models import app_suite
    from deepflow_tpu.parallel.sharded import ShardedAppSuite

    mesh8 = make_mesh()
    cfg = app_suite.AppSuiteConfig(groups=16, dd_buckets=128,
                                   dd_alpha=0.05)
    rng = np.random.default_rng(21)
    n = 512
    cols = {
        "ip_dst": rng.integers(0, 1 << 16, n).astype(np.uint32),
        "port_dst": rng.integers(0, 1024, n).astype(np.uint32),
        "protocol": np.full(n, 6, np.uint32),
        "status": np.where(rng.random(n) < 0.2, 500, 200)
        .astype(np.uint32),
        "rrt_us": rng.integers(1, 100_000, n).astype(np.uint32),
    }
    mask = np.ones(n, np.bool_)

    import jax.numpy as jnp
    single = app_suite.update(
        app_suite.init(cfg), {k: jnp.asarray(v) for k, v in cols.items()},
        jnp.asarray(mask), cfg)
    _, single_out = app_suite.flush(single, cfg)

    suite = ShardedAppSuite(cfg, mesh8)
    state = suite.init()
    cols_d, mask_d = suite.put_batch(cols, mask)
    state = suite.update(state, cols_d, mask_d)
    state, out = suite.flush(state)
    np.testing.assert_allclose(np.asarray(out.requests),
                               np.asarray(single_out.requests))
    np.testing.assert_allclose(np.asarray(out.errors),
                               np.asarray(single_out.errors))
    np.testing.assert_allclose(np.asarray(out.rrt_quantiles),
                               np.asarray(single_out.rrt_quantiles))


def test_sharded_plane_update_equals_cols_update(rng):
    """The single-transfer (n_cols, B) plane form of the sharded
    update lands the IDENTICAL state as the cols-dict form — the
    multi-chip face of the full-row fused-transfer path."""
    from deepflow_tpu.batch.schema import SKETCH_L4_SCHEMA
    from deepflow_tpu.wire import columnar_wire

    cfg = FlowSuiteConfig(cms_log2_width=12, ring_size=256,
                          hll_groups=64, hll_precision=8,
                          conservative=False)
    mesh = make_mesh()
    sharded = ShardedFlowSuite(cfg, mesh)
    s_cols = sharded.init()
    s_plane = sharded.init()
    agent = SyntheticAgent()
    for _ in range(2):
        base = agent.l4_columns_pooled(4096)
        full = {}
        for name, dt in SKETCH_L4_SCHEMA.columns:
            col = base.get(name)
            full[name] = (np.asarray(col).astype(dt)
                          if col is not None
                          else np.zeros(4096, dt))
        payload = columnar_wire.encode_columnar(full, SKETCH_L4_SCHEMA)
        plane, bad = columnar_wire.decode_columnar_plane(
            payload, SKETCH_L4_SCHEMA)
        assert bad == 0
        mask = np.ones(4096, np.bool_)
        dc = {k: jnp.asarray(v) for k, v in full.items()}
        cd, md = sharded.put_batch(dc, jnp.asarray(mask))
        s_cols = sharded.update(s_cols, cd, md)
        pd_, md2 = sharded.put_plane(jnp.asarray(plane), mask)
        s_plane = sharded.update_plane(s_plane, pd_, md2)
    for a, b in zip(jax.tree_util.tree_leaves(s_cols),
                    jax.tree_util.tree_leaves(s_plane)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_dict_lane_matches_single_device(rng):
    """Dictionary lane on the mesh: replicated table + broadcast news
    (each record counted on exactly one shard) + batch-sharded hits
    must land the same merged additive state as the single-device dict
    path AND the packed path on the same records."""
    from deepflow_tpu.models import flow_dict
    from deepflow_tpu.models.flow_dict import FlowDictPacker

    cfg = FlowSuiteConfig(cms_log2_width=12, ring_size=256, hll_groups=64,
                          hll_precision=8)
    mesh = make_mesh()
    sharded = ShardedFlowSuite(cfg, mesh)
    state_d = sharded.init()
    dtable = sharded.init_dict(capacity=8192)

    single = flow_suite.init(cfg)
    sdict = flow_dict.init_dict(8192)

    packer = FlowDictPacker(capacity=8192, hits_batch=4096,
                            news_batch=512)
    wire = []
    batches = _batches(rng, n_batches=3, batch=4096)
    for cols in batches:
        wire.extend(packer.pack(
            {k: cols[k].astype(np.uint32)
             for k in ("ip_src", "ip_dst", "port_src", "port_dst",
                       "proto", "packet_tx", "packet_rx")}))
    wire.extend(packer.flush())

    for kind, plane, n in wire:
        nn = np.uint32(n)
        if kind == "news":
            state_d, dtable = sharded.update_news(
                state_d, dtable, jnp.asarray(plane), nn)
            single, sdict = flow_dict.update_news(
                single, sdict, jnp.asarray(plane), nn, cfg)
        else:
            state_d = sharded.update_hits(
                state_d, dtable, jnp.asarray(plane), nn)
            single = flow_dict.update_hits(
                single, sdict, jnp.asarray(plane), nn, cfg)

    # every table replica must equal the single-device table
    tables = np.asarray(dtable)
    for d in range(tables.shape[0]):
        np.testing.assert_array_equal(tables[d], np.asarray(sdict.table))
    # merged additive state == single-device dict state
    merged_counts = np.asarray(state_d.sketch.counts).sum(axis=0)
    np.testing.assert_array_equal(merged_counts,
                                  np.asarray(single.sketch.counts))
    np.testing.assert_array_equal(
        np.asarray(state_d.services.registers).max(axis=0),
        np.asarray(single.services.registers))
    np.testing.assert_array_equal(
        np.asarray(state_d.ent.hist).sum(axis=0),
        np.asarray(single.ent.hist))
    assert (int(np.asarray(state_d.rows_seen).sum())
            == int(single.rows_seen))

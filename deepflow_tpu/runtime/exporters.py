"""Exporter plugin surface: where analytics backends plug into the pipeline.

Re-designs the reference's exporter registry (server/ingester/flow_log/
exporters/exporters.go: `Exporter` interface {Start/Close/Put/IsExportData},
`NewExporters` registry, per-decoder put caches) with the widening SURVEY.md
§7 Phase 3 calls for: `Put` takes (stream, decoder_index, records) so L4, L7
and metric streams all export — the reference's interface was typed to
*L7FlowLog only (exporters.go:46), which its own L4 path couldn't use.

Exporters receive *decoded columnar chunks* (schema column dicts), not row
structs: by the time data leaves the decode stage it is already
structure-of-arrays, the form both the TPU path and any file/OTLP-style
writer want.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Protocol, Sequence

from deepflow_tpu.runtime.queues import OverwriteQueue
from deepflow_tpu.runtime.stats import StatsRegistry
from deepflow_tpu.runtime.tracing import default_tracer


class Exporter(Protocol):
    """The plugin contract (reference: exporters.go:35-48)."""

    def start(self) -> None: ...

    def close(self) -> None: ...

    def is_export_data(self, stream: str, cols: Dict[str, Any]) -> bool:
        """Cheap filter before enqueue (reference: IsExportData signal-source
        bit filter, otlp_exporter/exporter.go:120)."""
        ...

    def put(self, stream: str, decoder_index: int,
            cols: Dict[str, Any]) -> None:
        """Hand one decoded columnar chunk to the exporter. Must not
        block. Batch causality rides the flight recorder's thread-local
        batch id (tracing.Tracer.set_batch), not the signature — the
        contract predates the tracer and third-party exporters keep
        working unchanged."""
        ...


class Exporters:
    """Registry + fan-out. One instance sits after the decode stage."""

    def __init__(self, stats: Optional[StatsRegistry] = None) -> None:
        self._exporters: List[Exporter] = []
        self._started = False
        self.put_count = 0
        self.filtered_count = 0
        if stats is not None:
            stats.register("exporters", self.counters)

    def register(self, exporter: Exporter) -> None:
        if self._started:
            raise RuntimeError("register before start()")
        self._exporters.append(exporter)

    def start(self) -> None:
        self._started = True
        for e in self._exporters:
            e.start()

    def close(self) -> None:
        for e in self._exporters:
            e.close()
        self._started = False

    def put(self, stream: str, decoder_index: int,
            cols: Dict[str, Any]) -> None:
        for e in self._exporters:
            if e.is_export_data(stream, cols):
                e.put(stream, decoder_index, cols)
                self.put_count += 1
            else:
                self.filtered_count += 1

    def counters(self) -> dict:
        return {"put": self.put_count, "filtered": self.filtered_count,
                "n_exporters": len(self._exporters)}


class QueueWorkerExporter:
    """Base for exporters that buffer chunks and drain on worker threads.

    The reference OTLP exporter's shape (otlp_exporter/exporter.go:86):
    own OverwriteQueue (drop-oldest back-pressure, observable loss) + N
    workers + Countable stats. Subclasses implement `process(chunks)`.
    """

    def __init__(self, name: str, streams: Sequence[str],
                 queue_size: int = 1 << 16, n_workers: int = 1,
                 batch: int = 64,
                 stats: Optional[StatsRegistry] = None) -> None:
        self.name = name
        self.streams = frozenset(streams)
        self.queue = OverwriteQueue(f"exporter.{name}", queue_size)
        self.n_workers = n_workers
        self.batch = batch
        self._threads: List[threading.Thread] = []
        self.processed = 0
        self._tracer = default_tracer()
        self.queue.trace_dwell(self._tracer, f"queue.exporter.{name}")
        if stats is not None:
            stats.register(f"exporter.{name}", self.counters)

    # -- Exporter contract -------------------------------------------------
    def start(self) -> None:
        for i in range(self.n_workers):
            t = threading.Thread(target=self._run, name=f"{self.name}-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def close(self) -> None:
        self.queue.close()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    def is_export_data(self, stream: str, cols: Dict[str, Any]) -> bool:
        return stream in self.streams

    def put(self, stream: str, decoder_index: int,
            cols: Dict[str, Any]) -> None:
        # the enqueuing thread's batch id crosses the queue inside the
        # item: the worker re-pins it so kernel attribution downstream
        # anchors to the decoder's chunk (batch causality across the
        # thread hop). -1 when tracing is off — same tuple shape always,
        # so process() implementations never see two layouts.
        self.queue.put((stream, decoder_index, cols,
                        self._tracer.current_batch()
                        if self._tracer.enabled else -1))

    # -- subclass surface --------------------------------------------------
    def process(self, chunks: List[Any]) -> None:  # pragma: no cover
        raise NotImplementedError

    @staticmethod
    def coerce_to_schema(cols: Dict[str, Any], schema) -> Dict[str, Any]:
        """Project a decoded chunk onto a batching Schema: contiguous
        casts for present columns, zero-fill for absent ones, empty
        chunks come back empty (shared by the tpu_sketch and app_red
        sketch exporters, which would otherwise drift)."""
        import numpy as np
        n = len(next(iter(cols.values()))) if cols else 0
        return {
            name: np.ascontiguousarray(cols[name]).astype(dt, copy=False)
            if name in cols else np.zeros(n, dt)
            for name, dt in schema.columns
        }

    def _run(self) -> None:
        tracer = self._tracer
        while True:
            chunks = self.queue.gets(self.batch, timeout=0.2)
            if chunks:
                if tracer.enabled:
                    rows = sum(
                        len(next(iter(c[2].values()))) if c[2] else 0
                        for c in chunks)
                    tracer.set_batch(chunks[0][3])
                    with tracer.span("export", stream=self.name,
                                     batch_id=chunks[0][3], rows=rows):
                        self.process(chunks)
                else:
                    self.process(chunks)
                self.processed += len(chunks)
            elif self.queue.closed:
                return

    def counters(self) -> dict:
        c = self.queue.counters()
        c["processed"] = self.processed
        return c

"""Agent process entrypoint: `python -m deepflow_tpu.agent -f agent.yaml`.

Reference: agent/src/main.rs:102 — the binary reads a tiny bootstrap
yaml (controller address and little else; the full RuntimeConfig is
PUSHED by the controller after registration) and runs until signalled.
Same shape here: the yaml's keys are AgentConfig fields plus a
`capture:` block choosing the packet source; everything else arrives
through the sync loop (trident.py Agent.sync_once -> _apply_config).

Capture sources (agent/afpacket.py, agent/xdp.py, agent/pcap.py):
  capture: {engine: ring,  iface: eth0}     TPACKET_V3 mmap ring
  capture: {engine: xdp,   iface: eth0}     AF_XDP (XDP redirect into
                                            XSK rings; CONSUMES the
                                            queue's ingress — analyzer
                                            deployments)
  capture: {engine: raw,   iface: eth0}     batched raw socket
  capture: {engine: pcap,  path: x.pcap}    replay a capture file
  capture: {engine: none}                   control-plane only (eBPF or
                                            integration push feeds data)
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

import yaml

_CAPTURE_KEYS = ("engine", "iface", "path", "batch_size", "block_size",
                 "block_count", "poll_ms", "snaplen", "bpf", "queue",
                 "frame_count")
_BPF_KEYS = ("proto", "port", "sample_shift")


def load_bootstrap(path: str) -> tuple:
    """Parse the bootstrap yaml into (AgentConfig, capture dict).

    Unknown keys are an error, not a warning: a typo'd yaml silently
    running on defaults is how a fleet ends up capturing nothing
    (the reference validates pushed config the same way —
    config.rs RuntimeConfig::validate).
    """
    # deferred: importing trident pulls jax (seconds); main() registers
    # signal handlers before paying that, so TERM-during-startup exits
    # cleanly instead of through the default handler
    from deepflow_tpu.agent.trident import AgentConfig
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    capture = raw.pop("capture", {"engine": "none"}) or {"engine": "none"}
    unknown = set(capture) - set(_CAPTURE_KEYS)
    if unknown:
        raise ValueError(f"unknown capture keys: {sorted(unknown)}")
    engine = capture.get("engine", "none")
    if engine not in ("none", "raw", "ring", "xdp", "pcap"):
        raise ValueError(f"unknown capture engine {engine!r} "
                         "(none|raw|ring|xdp|pcap)")
    if engine == "pcap" and not capture.get("path"):
        raise ValueError("capture engine pcap requires path")
    if engine == "xdp" and not capture.get("iface"):
        raise ValueError("capture engine xdp requires iface")
    # per-engine knobs: reject mismatches here so --dry-run catches them
    if engine != "raw" and "snaplen" in capture:
        raise ValueError("snaplen applies to engine raw only; "
                         "the ring sizes frames via block_size")
    if engine != "ring" and ("block_size" in capture
                             or "block_count" in capture):
        raise ValueError("block_size/block_count apply to engine ring only")
    if engine != "xdp" and ("queue" in capture
                            or "frame_count" in capture):
        raise ValueError("queue/frame_count apply to engine xdp only")
    if "bpf" in capture:
        if engine not in ("raw", "ring"):
            # xdp has its own in-kernel program; socket filters don't
            # apply to XSK rings
            raise ValueError("bpf filters attach to live sockets "
                             "(engine raw or ring)")
        b = capture["bpf"] or {}
        unknown = set(b) - set(_BPF_KEYS)
        if unknown:
            raise ValueError(f"unknown bpf keys: {sorted(unknown)}")
        for k, hi in (("proto", 255), ("port", 65535),
                      ("sample_shift", 31)):
            v = b.get(k)
            if v is not None and (not isinstance(v, int)
                                  or not 0 <= v <= hi):
                raise ValueError(f"bpf {k} must be an int in "
                                 f"0..{hi}, got {v!r}")
    fields = AgentConfig.__dataclass_fields__
    unknown = set(raw) - set(fields)
    if unknown:
        raise ValueError(f"unknown agent config keys: {sorted(unknown)}")
    for k in ("so_plugins", "wasm_plugins", "local_macs"):
        if k in raw and isinstance(raw[k], list):
            raw[k] = tuple(raw[k])
    return AgentConfig(**raw), capture


def build_source(capture: dict):
    engine = capture.get("engine", "none")
    if engine == "none":
        return None
    if engine == "pcap":
        from deepflow_tpu.agent.pcap import PcapFrameSource
        if not os.path.exists(capture["path"]):
            # PcapFrameSource opens lazily (in the capture thread, where
            # the error would only be swallowed) — fail at startup
            raise OSError(f"pcap not found: {capture['path']}")
        return PcapFrameSource(capture["path"])
    kw = {}
    for k in ("batch_size", "poll_ms"):
        if k in capture:
            kw[k] = capture[k]
    filt = None
    if "bpf" in capture:
        # kernel-side filter on the capture socket (recv_engine BPF
        # injection): attached BEFORE the socket binds (prepare hook)
        # so no packet ever reaches userspace unfiltered
        from deepflow_tpu.agent.bpf import BpfFilter
        filt = BpfFilter(**(capture["bpf"] or {}))
        kw["prepare"] = filt.attach_socket
    try:
        if engine == "ring":
            from deepflow_tpu.agent.afpacket import TpacketV3Source
            for k in ("block_size", "block_count"):
                if k in capture:
                    kw[k] = capture[k]
            src = TpacketV3Source(capture.get("iface"), **kw)
        elif engine == "raw":
            from deepflow_tpu.agent.afpacket import AfPacketSource
            if "snaplen" in capture:
                kw["snaplen"] = capture["snaplen"]
            src = AfPacketSource(capture.get("iface"), **kw)
        elif engine == "xdp":
            from deepflow_tpu.agent.xdp import XdpSource
            for k in ("queue", "frame_count"):
                if k in capture:
                    kw[k] = capture[k]
            src = XdpSource(capture["iface"], **kw)
        else:
            raise ValueError(f"unknown capture engine {engine!r}")
    except BaseException:
        if filt is not None:
            filt.close()
        raise
    if filt is not None:
        src.bpf = filt          # counters + lifecycle ride the source
    return src


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="deepflow-tpu-agent",
        description="capture agent (managed when controller_url is set, "
                    "standalone otherwise)")
    ap.add_argument("-f", "--config", required=True,
                    help="bootstrap yaml (AgentConfig keys + capture:)")
    ap.add_argument("--dry-run", action="store_true",
                    help="validate the bootstrap config and exit")
    args = ap.parse_args(argv)

    # handlers FIRST: everything below pays the multi-second jax import
    # (load_bootstrap's AgentConfig pull included), and a TERM during
    # startup must reach the clean-close path, not the default handler —
    # k8s sends TERM whenever it feels like it
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())

    try:
        cfg, capture = load_bootstrap(args.config)
    except (OSError, ValueError, TypeError, yaml.YAMLError) as e:
        print(f"bad bootstrap config: {e}", file=sys.stderr)
        return 2
    if args.dry_run:
        print(f"config ok: controller={cfg.controller_url or 'standalone'} "
              f"ingester={cfg.ingester_addr} "
              f"capture={capture.get('engine', 'none')}")
        return 0

    # source BEFORE agent: a bad iface/pcap must fail through the clean
    # config-error path, not leave a half-started agent behind
    try:
        source = build_source(capture)
    except (OSError, ValueError, KeyError) as e:
        print(f"bad capture config: {e}", file=sys.stderr)
        return 2

    from deepflow_tpu.agent.trident import Agent
    agent = Agent(cfg)
    loop = None
    agent.start()
    if source is not None and not stop.is_set():
        from deepflow_tpu.agent.afpacket import CaptureLoop
        agent.attach_source(source)       # ebpf debug dump reads it
        loop = CaptureLoop(source, agent, stats=agent.stats)
        loop.start()
    stop.wait()
    if loop is not None:
        loop.close()
    agent.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

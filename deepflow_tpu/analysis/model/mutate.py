"""Self-test by mutation (ISSUE 14d): prove the checker can actually
see the bug classes it claims to guard.

Each protocol model ships a MUTANTS table — named single-transition
flips of exactly the shape a bad refactor would introduce (double-count
the late merge, skip the dedup seq check, drop the fsync-on-roll). The
harness builds each mutant, runs the same exhaustive check CI runs, and
demands a counterexample: a mutant that SURVIVES means the model (or
the explorer) has a blind spot, and the whole `df-ctl verify` verdict
is worth nothing — so ci.sh runs the kill sweep beside the clean sweep.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from deepflow_tpu.analysis.model import explore
from deepflow_tpu.analysis.model.explore import CheckResult

__all__ = ["model_for", "all_mutants", "kill_all", "KillReport"]


def _modules():
    from deepflow_tpu.analysis.model import (host_pod, pod_epoch,
                                             sender_ring, spill_drain)
    return {"pod": pod_epoch, "hostpod": host_pod,
            "spill": spill_drain, "sender": sender_ring}


def model_for(protocol: str, mutation: Optional[str] = None):
    """The (optionally mutated) Model for one protocol name."""
    mods = _modules()
    if protocol not in mods:
        raise ValueError(f"unknown protocol {protocol!r} "
                         f"(know: {', '.join(sorted(mods))})")
    mod = mods[protocol]
    if mutation is not None and mutation not in mod.MUTANTS:
        raise ValueError(
            f"unknown mutant {mutation!r} for {protocol} "
            f"(know: {', '.join(sorted(mod.MUTANTS))})")
    return mod.build(mutation)


def all_mutants() -> List[Tuple[str, str, str]]:
    """[(protocol, mutant name, what it should break), ...]"""
    out = []
    for proto, mod in sorted(_modules().items()):
        for name, why in sorted(mod.MUTANTS.items()):
            out.append((proto, name, why))
    return out


class KillReport:
    def __init__(self) -> None:
        # (protocol, mutant) -> CheckResult
        self.results: Dict[Tuple[str, str], CheckResult] = {}
        self.survivors: List[Tuple[str, str]] = []
        self.incomplete: List[Tuple[str, str]] = []

    @property
    def ok(self) -> bool:
        return not self.survivors and not self.incomplete


def kill_all(protocol: Optional[str] = None, max_faults: int = 2,
             budget_s: Optional[float] = None) -> KillReport:
    """Run every seeded mutant (of one protocol, or all) and collect
    the verdicts. A mutant is KILLED when the checker finds a
    counterexample; an incomplete sweep is NOT a kill. `budget_s` is
    the TOTAL wall clock for the whole sweep (the same contract as
    `df-ctl verify --budget-s`): each mutant gets whatever remains, so
    an overrun surfaces as INCOMPLETE instead of multiplying the
    budget by the mutant count."""
    import time
    deadline = None if budget_s is None else time.monotonic() + budget_s
    from deepflow_tpu.analysis.model import expand_protocol
    report = KillReport()
    for proto, name, _why in all_mutants():
        if protocol is not None and proto not in expand_protocol(protocol):
            continue
        remaining = None if deadline is None \
            else max(0.0, deadline - time.monotonic())
        res = explore.check(model_for(proto, name),
                            max_faults=max_faults, budget_s=remaining)
        report.results[(proto, name)] = res
        if not res.complete and res.violation is None:
            report.incomplete.append((proto, name))
        elif res.violation is None:
            report.survivors.append((proto, name))
    return report

"""Querier: SQL + PromQL query surface over the columnar store.

Reference: server/querier/ — HTTP /v1/query executes DeepFlow-SQL
(`show tags/metrics`, auto tag translation, derived metrics) by
translating to ClickHouse SQL (engine/clickhouse/clickhouse.go). Here the
translation target is the framework's own store: filters are vectorized
numpy masks, GROUP BY aggregation runs as a device segment-reduction
(store/rollup.group_reduce), and SmartEncoded hash columns translate back
to strings through the TagDict registry at result time.
"""

from deepflow_tpu.querier.engine import QueryEngine, QueryResult
from deepflow_tpu.querier.sql import parse_sql

__all__ = ["QueryEngine", "QueryResult", "parse_sql"]

"""AF_PACKET live capture source: the recv_engine for real interfaces.

Reference: agent/src/dispatcher/recv_engine/af_packet/ — a TPACKET_V2
mmap ring delivering raw frames to the dispatcher. Python's stdlib
exposes AF_PACKET/SOCK_RAW directly on Linux, so the capture source here
is a raw socket drained in batches: recv up to `batch_size` frames (or
until `poll_ms` passes with none), stamp kernel-adjacent timestamps, and
hand the batch to `Agent.feed` — the same (frames, timestamps_ns)
contract the pcap replay source and the synthetic generators speak.

The mmap ring's zero-copy advantage matters at line rate on many-core
hosts; this framework's hot path is the batched columnar decode + TPU
sketches, and a per-batch recv loop on one core sustains the agent's
design envelope (the flow map itself merges >1M pkts/s/core). Requires
CAP_NET_RAW (root), like every capture backend.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import List, Optional, Tuple

ETH_P_ALL = 0x0003


class AfPacketSource:
    """Batched live capture off one interface (or all, iface=None)."""

    def __init__(self, iface: Optional[str] = None,
                 batch_size: int = 4096, poll_ms: float = 50.0,
                 snaplen: int = 65535) -> None:
        if not hasattr(socket, "AF_PACKET"):
            raise OSError("AF_PACKET requires Linux")
        self.iface = iface
        self.batch_size = batch_size
        self.poll_ms = poll_ms
        self.snaplen = snaplen
        self._sock = socket.socket(socket.AF_PACKET, socket.SOCK_RAW,
                                   socket.htons(ETH_P_ALL))
        try:
            if iface:
                self._sock.bind((iface, 0))
            self._sock.settimeout(poll_ms / 1e3)
        except OSError:
            self._sock.close()     # no fd leak on bad interface names
            raise
        self.frames_captured = 0
        self.errors = 0

    def fileno(self) -> int:
        return self._sock.fileno()

    def read_batch(self) -> Tuple[List[bytes], List[int]]:
        """One capture batch: up to batch_size frames; returns as soon as
        the poll window passes with the batch non-empty (or empty on a
        quiet interface). Timestamps are host-clock ns at dequeue —
        within the 1s flow-tick resolution of everything downstream."""
        frames: List[bytes] = []
        stamps: List[int] = []
        deadline = time.monotonic() + self.poll_ms / 1e3
        while len(frames) < self.batch_size:
            try:
                data = self._sock.recv(self.snaplen)
            except socket.timeout:
                break
            except OSError:
                # a dead socket must be visible, not a quiet interface:
                # count it so CaptureLoop backs off and counters show it
                self.errors += 1
                break
            frames.append(data)
            stamps.append(time.time_ns())
            if time.monotonic() > deadline:
                break
        self.frames_captured += len(frames)
        return frames, stamps

    def close(self) -> None:
        self._sock.close()


class CaptureLoop:
    """Drives an AfPacketSource (or any .read_batch() source) into an
    Agent from a daemon thread — the dispatcher's recv loop."""

    def __init__(self, source, agent, stats=None) -> None:
        self.source = source
        self.agent = agent
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.batches = 0
        self.packets = 0
        if stats is not None:
            stats.register("capture", self.counters)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="capture-loop", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        import numpy as np
        errors_seen = 0
        while not self._stop.is_set():
            frames, stamps = self.source.read_batch()
            if not frames:
                # if the empty batch came from a socket error (not a
                # quiet interface), back off instead of busy-spinning
                errs = getattr(self.source, "errors", 0)
                if errs > errors_seen:
                    errors_seen = errs
                    self._stop.wait(0.2)
                continue
            self.batches += 1
            self.packets += self.agent.feed(
                frames, np.asarray(stamps, np.uint64))

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        self.source.close()

    def counters(self) -> dict:
        c = {"batches": self.batches, "packets": self.packets}
        for attr in ("frames_captured", "errors"):
            if hasattr(self.source, attr):
                c[f"capture_{attr}" if attr == "errors" else attr] = \
                    getattr(self.source, attr)
        return c

"""Batched flow generator: MetaPacket columns -> TaggedFlow output.

Reference: agent/src/flow_generator/flow_map.rs — a per-packet AHashMap
hot loop with a time wheel, TCP state machine (flow_state.rs) and perf
calculator (perf/tcp.rs), ticking TaggedFlows out every second. The
batch-columnar re-design splits that into:

1. per-batch: canonicalize 5-tuples (so both directions share a flow),
   segment-reduce per-direction byte/packet/flag/timestamp aggregates —
   one vectorized pass over the whole batch, device-friendly;
2. cross-batch: merge the per-flow partials into a COLUMNAR flow table —
   the accumulators are numpy arrays indexed by slot, so the merge is a
   handful of vectorized scatters (np.add.at / np.maximum.at). The only
   per-group Python is one dict lookup resolving the 5-tuple to its
   slot (plus allocation for first-seen flows);
3. tick(now): one vectorized pass over the table emits 1s interval
   deltas for active flows and closes flows on FIN/RST or timeout,
   deriving close_type and RTT (SYN->SYN/ACK) the way the reference's
   state machine does. `tick_columns` returns oriented wire-ready
   columns with zero per-flow Python; `tick` wraps them in FlowAcc
   objects for callers that want row views.

Retransmissions are estimated per direction by counting payload-carrying
packets whose sequence did not advance (reference counts true
retransmits from the seq window; this batched estimate matches it for
the common in-order capture case).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from deepflow_tpu.agent.packet import ACK, FIN, PROTO_TCP, RST, SYN
from deepflow_tpu.agent.tcp_perf import TcpPerf
from deepflow_tpu.store.rollup import group_reduce

# close types (reference: agent/src/common/enums.rs CloseType)
CLOSE_FORCED_REPORT = 0   # still active at tick
CLOSE_FIN = 1
CLOSE_RST = 2
CLOSE_TIMEOUT = 3

FLOW_TIMEOUT_NS = 120 * 1_000_000_000
_U64 = np.uint64
_BIG = np.int64(1 << 62)


@dataclass
class FlowAcc:
    """Row view of one emitted flow (compat shell over the columnar
    table; tick_columns is the zero-copy path)."""

    ip0: int
    ip1: int
    port0: int
    port1: int
    proto: int
    flow_id: int
    start_ns: int
    last_ns: int
    # per direction (0 = canonical ip0->ip1, 1 = reverse)
    bytes_: List[int] = field(default_factory=lambda: [0, 0])
    packets: List[int] = field(default_factory=lambda: [0, 0])
    flags: List[int] = field(default_factory=lambda: [0, 0])
    retrans: List[int] = field(default_factory=lambda: [0, 0])
    max_seq: List[int] = field(default_factory=lambda: [0, 0])
    syn_ns: int = 0           # first SYN (no ACK)
    synack_ns: int = 0        # first SYN+ACK
    initiator: int = -1       # direction index that sent the first SYN
    reported: bool = False    # has this flow appeared in a tick yet?

    @property
    def rtt_us(self) -> int:
        if self.syn_ns and self.synack_ns > self.syn_ns:
            return (self.synack_ns - self.syn_ns) // 1000
        return 0

    def close_type(self, now_ns: int) -> int:
        f = self.flags[0] | self.flags[1]
        if f & RST:
            return CLOSE_RST
        if (self.flags[0] & FIN) and (self.flags[1] & FIN):
            return CLOSE_FIN
        if now_ns - self.last_ns > FLOW_TIMEOUT_NS:
            return CLOSE_TIMEOUT
        return CLOSE_FORCED_REPORT


class FlowMap:
    """Cross-batch columnar flow table: batched ingest + 1s tick output."""

    def __init__(self, vtap_id: int = 0, capacity: int = 1024) -> None:
        self.vtap_id = vtap_id
        self._slot: Dict[Tuple[int, int, int, int, int], int] = {}
        self._free: List[int] = []
        self._next_flow_id = 1
        # opt-in per-packet context from inject() (flow_id/direction
        # gathers) — only the packet-sequence collector pays for it
        self.want_packet_context = False
        self.packets_in = 0
        self.invalid_packets = 0
        self.flows_created = 0
        self._alloc_cols(max(capacity, 16))
        self.perf = TcpPerf(self._cap)

    def _alloc_cols(self, cap: int) -> None:
        self._cap = cap
        z64 = lambda shape: np.zeros(shape, np.int64)  # noqa: E731
        self.c_key = z64((cap, 5))       # ip0 ip1 p0 p1 proto
        self.c_flow_id = np.zeros(cap, np.uint64)
        self.c_start = z64(cap)
        self.c_last = z64(cap)
        self.c_bytes = z64((cap, 2))
        self.c_pkts = z64((cap, 2))
        self.c_flags = z64((cap, 2))
        self.c_retrans = z64((cap, 2))
        self.c_max_seq = z64((cap, 2))
        self.c_syn = z64(cap)            # 0 = unset
        self.c_synack = z64(cap)
        self.c_tap_side = z64(cap)
        self.c_initiator = np.full(cap, -1, np.int8)
        self.c_reported = np.zeros(cap, np.bool_)
        self.c_live = np.zeros(cap, np.bool_)

    def _grow(self) -> None:
        old = {k: getattr(self, k) for k in (
            "c_key", "c_flow_id", "c_start", "c_last", "c_bytes", "c_pkts",
            "c_flags", "c_retrans", "c_max_seq", "c_syn", "c_synack",
            "c_tap_side", "c_initiator", "c_reported", "c_live")}
        n = self._cap
        self._alloc_cols(self._cap * 2)
        for k, v in old.items():
            getattr(self, k)[:n] = v
        self.perf.grow(self._cap)

    def _allocate(self, key: Tuple[int, int, int, int, int]) -> int:
        if self._free:
            s = self._free.pop()
        else:
            s = len(self._slot)
            while s >= self._cap or self.c_live[s]:
                if s >= self._cap:
                    self._grow()
                    continue
                s += 1
        self._slot[key] = s
        self.c_key[s] = key
        self.c_flow_id[s] = self._next_flow_id
        self._next_flow_id += 1
        self.c_start[s] = _BIG
        self.c_last[s] = 0
        self.c_bytes[s] = 0
        self.c_pkts[s] = 0
        self.c_flags[s] = 0
        self.c_retrans[s] = 0
        self.c_max_seq[s] = 0
        self.c_syn[s] = 0
        self.c_synack[s] = 0
        self.c_tap_side[s] = 0
        self.c_initiator[s] = -1
        self.c_reported[s] = False
        self.c_live[s] = True
        self.perf.reset_slot(s)
        self.flows_created += 1
        return s

    # -- ingest ------------------------------------------------------------
    def inject(self, pkt: Dict[str, np.ndarray]) -> Optional[dict]:
        """Fold one decoded packet batch into the flow table. Returns
        per-packet context for the VALID packets so per-packet
        consumers (the packet-sequence collector) reuse this pass's
        masking/orientation instead of recomputing it:
        {"cols": valid-filtered columns, "flow_id": [n] u64,
        "direction": [n] u32 — the flow's CANONICAL orientation bit
        (0 = packet travels lower-(ip,port)-first), stable for the
        flow's lifetime}."""
        valid = pkt["valid"]
        n = int(valid.sum())
        self.packets_in += len(valid)
        self.invalid_packets += len(valid) - n
        if n == 0:
            return None
        cols = {k: v[valid] for k, v in pkt.items()}

        # canonical orientation: lower (ip, port) first; dir=1 if reversed
        a = (cols["ip_src"].astype(_U64) << _U64(16)) | cols["port_src"]
        b = (cols["ip_dst"].astype(_U64) << _U64(16)) | cols["port_dst"]
        rev = a > b
        ip0 = np.where(rev, cols["ip_dst"], cols["ip_src"])
        ip1 = np.where(rev, cols["ip_src"], cols["ip_dst"])
        p0 = np.where(rev, cols["port_dst"], cols["port_src"])
        p1 = np.where(rev, cols["port_src"], cols["port_dst"])
        direction = rev.astype(np.uint32)

        ts = cols["timestamp_ns"].astype(np.int64)
        tap_side = cols.get("tap_side")
        if tap_side is None:
            tap_side = np.zeros(n, np.int64)
        flags = cols["tcp_flags"].astype(np.int64)
        is_syn = (flags & (SYN | ACK)) == SYN
        is_synack = (flags & (SYN | ACK)) == (SYN | ACK)
        has_payload = cols["payload_len"] > 0

        # per-(flow, direction) segment reduction — one device pass.
        # The 6-part key packs into 2 u64 words (ips | ports+proto+dir):
        # grouping cost is 2 radix-friendly i64 sorts, not a 48-byte
        # memcmp sort.
        k_ips = (ip0.astype(_U64) << _U64(32)) | ip1.astype(_U64)
        k_rest = ((p0.astype(_U64) << _U64(25))
                  | (p1.astype(_U64) << _U64(9))
                  | (cols["proto"].astype(_U64) << _U64(1))
                  | direction.astype(_U64))
        work = {
            "k_ips": k_ips, "k_rest": k_rest,
            "bytes": cols["pkt_len"], "pkts": np.ones(n, np.int64),
            "flags": flags, "ts_min": ts, "ts_max": ts,
            "syn_ts": np.where(is_syn, ts, _BIG),
            "synack_ts": np.where(is_synack, ts, _BIG),
            "seq_max": cols["tcp_seq"].astype(np.int64),
            "tap_side": tap_side.astype(np.int64),
            # payload packets whose seq never advances past the running max
            # are the batch-local retrans candidates; cross-batch handled
            # against the accumulator's max_seq at merge time
            "payload_pkts": has_payload.astype(np.int64),
        }
        red, inv = group_reduce(
            work, ["k_ips", "k_rest"],
            {"bytes": "sum", "pkts": "sum", "flags": "max",
             "ts_min": "min", "ts_max": "max", "syn_ts": "min",
             "synack_ts": "min", "seq_max": "max", "payload_pkts": "sum",
             "tap_side": "max"},
            return_inverse=True)
        # flags need OR, not max: OR-reduce per group on host, reusing the
        # group ids from the reduction (group count << packet count)
        red_flags = np.zeros(len(red["k_ips"]), np.int64)
        np.bitwise_or.at(red_flags, inv, flags)

        m = len(red["k_ips"])
        # unpack the key words back to tuple form for slot resolution
        rk_ips = red["k_ips"].astype(_U64)
        rk_rest = red["k_rest"].astype(_U64)
        r_ip0 = (rk_ips >> _U64(32)).astype(np.int64)
        r_ip1 = (rk_ips & _U64(0xFFFFFFFF)).astype(np.int64)
        r_p0 = (rk_rest >> _U64(25)).astype(np.int64)
        r_p1 = ((rk_rest >> _U64(9)) & _U64(0xFFFF)).astype(np.int64)
        r_proto = ((rk_rest >> _U64(1)) & _U64(0xFF)).astype(np.int64)
        # slot resolution: the ONLY per-group Python — one dict op each
        keys = list(zip(r_ip0.tolist(), r_ip1.tolist(), r_p0.tolist(),
                        r_p1.tolist(), r_proto.tolist()))
        get = self._slot.get
        slots = np.fromiter(
            (s if (s := get(k)) is not None else self._allocate(k)
             for k in keys), dtype=np.int64, count=m)
        d = (rk_rest & _U64(1)).astype(np.int64)

        # everything below is vectorized scatter over (slot, dir). A slot
        # can appear for both directions in one batch, so per-slot columns
        # use .at reductions; per-(slot, dir) targets are unique and can
        # assign directly.
        prev_pkts = self.c_pkts[slots, d]
        prev_max = self.c_max_seq[slots, d]
        seq = red["seq_max"]
        self.c_bytes[slots, d] += red["bytes"]
        self.c_pkts[slots, d] = prev_pkts + red["pkts"]
        self.c_flags[slots, d] |= red_flags
        # retrans estimate: payload packets that failed to move seq_max
        self.c_retrans[slots, d] += np.where(
            (prev_pkts > 0) & (prev_max > 0) & (seq <= prev_max),
            red["payload_pkts"], 0)
        self.c_max_seq[slots, d] = np.maximum(prev_max, seq)
        np.minimum.at(self.c_start, slots, red["ts_min"])
        np.maximum.at(self.c_last, slots, red["ts_max"])
        # capture-point side (dispatcher MAC orientation) — constant per
        # observation point, so max-merge is exact
        np.maximum.at(self.c_tap_side, slots, red["tap_side"])
        # handshake stamps: 0 means unset — lift touched slots to +inf
        # BEFORE the min-scatter (min against a 0 target would stick), and
        # lower the never-set ones back after
        touched = np.unique(slots)
        for col, cand in ((self.c_syn, red["syn_ts"]),
                          (self.c_synack, red["synack_ts"])):
            cur = col[touched]
            col[touched] = np.where(cur == 0, _BIG, cur)
            np.minimum.at(col, slots, cand)
            cur = col[touched]
            col[touched] = np.where(cur >= _BIG, 0, cur)
        # initiator: direction of the earliest SYN. Write candidates in
        # DESCENDING syn_ts order so the earliest lands last (last write
        # wins on duplicate fancy indices); only unset slots take it.
        cand = np.nonzero((red["syn_ts"] < _BIG)
                          & (self.c_initiator[slots] < 0))[0]
        if len(cand):
            order = cand[np.argsort(-red["syn_ts"][cand],
                                    kind="stable")]
            self.c_initiator[slots[order]] = d[order].astype(np.int8)

        # TCP perf engine: per-PACKET pass (SRT/ART/CIT need packet
        # ordering the per-(flow,dir) reduction above deliberately
        # discards). Runs after the handshake-stamp merge so in-batch
        # SYN/SYN_ACK timestamps are already resolved in c_syn/c_synack.
        all_slots = slots[inv]
        tcp = np.nonzero(cols["proto"] == PROTO_TCP)[0]
        if len(tcp):
            pkt_slots = all_slots[tcp]
            zeros = np.zeros(n, np.int64)
            self.perf.inject(
                pkt_slots, direction[tcp], ts[tcp], flags[tcp],
                cols["tcp_seq"][tcp].astype(np.int64),
                cols.get("tcp_ack", zeros)[tcp].astype(np.int64),
                cols["payload_len"][tcp].astype(np.int64),
                cols.get("tcp_win", zeros)[tcp].astype(np.int64),
                self.c_syn[pkt_slots], self.c_synack[pkt_slots])
        if not self.want_packet_context:
            return None          # default path: no per-packet gathers
        # the direction bit uses CANONICAL orientation (lower (ip,port)
        # first) — the only basis that is stable for a flow's whole
        # lifetime. An initiator-relative bit would flip mid-flow when
        # the SYN arrives after mid-stream capture started, leaving one
        # block with contradictory bits. The l4_flow_log row for the
        # same flow_id records which canonical side initiated.
        return {"cols": cols, "flow_id": self.c_flow_id[all_slots],
                "direction": direction.astype(np.uint32)}

    # -- tick output -------------------------------------------------------
    def tick_columns(self, now_ns: Optional[int] = None,
                     emit_active: bool = True) -> Dict[str, np.ndarray]:
        """One vectorized pass: closed flows are removed; active ones are
        reported as *interval deltas* and kept with their counters reset
        (the reference's 1s forced report reports per-interval traffic
        too — re-emitting cumulative totals would double-count downstream
        sums). Output columns are oriented client->server: the initiator
        (first SYN sender) is the client."""
        now_ns = int(time.time() * 1e9) if now_ns is None else now_ns
        live = self.c_live
        flags0, flags1 = self.c_flags[:, 0], self.c_flags[:, 1]
        ct = np.zeros(self._cap, np.uint32)
        ct[now_ns - self.c_last > FLOW_TIMEOUT_NS] = CLOSE_TIMEOUT
        ct[((flags0 & FIN) > 0) & ((flags1 & FIN) > 0)] = CLOSE_FIN
        ct[((flags0 | flags1) & RST) > 0] = CLOSE_RST
        closed = live & (ct != CLOSE_FORCED_REPORT)
        active = live & (ct == CLOSE_FORCED_REPORT) & \
            (self.c_pkts.sum(axis=1) > 0)
        emit = closed | (active if emit_active else False)
        idx = np.nonzero(emit)[0]

        cli = np.maximum(self.c_initiator[idx], 0).astype(np.int64)
        srv = 1 - cli
        ips = self.c_key[idx, 0:2]
        ports = self.c_key[idx, 2:4]
        r = np.arange(len(idx))
        syn, synack = self.c_syn[idx], self.c_synack[idx]
        out = {
            "ip_src": ips[r, cli].astype(np.uint32),
            "ip_dst": ips[r, srv].astype(np.uint32),
            "port_src": ports[r, cli].astype(np.uint32),
            "port_dst": ports[r, srv].astype(np.uint32),
            "proto": self.c_key[idx, 4].astype(np.uint32),
            "vtap_id": np.full(len(idx), self.vtap_id, np.uint32),
            "byte_tx": self.c_bytes[idx][r, cli].astype(np.uint64),
            "byte_rx": self.c_bytes[idx][r, srv].astype(np.uint64),
            "packet_tx": self.c_pkts[idx][r, cli].astype(np.uint64),
            "packet_rx": self.c_pkts[idx][r, srv].astype(np.uint64),
            "retrans": self.c_retrans[idx].sum(axis=1).astype(np.uint32),
            "retrans_tx": self.c_retrans[idx][r, cli].astype(np.uint32),
            "retrans_rx": self.c_retrans[idx][r, srv].astype(np.uint32),
            "close_type": ct[idx],
            "flow_id": self.c_flow_id[idx],
            "start_time": self.c_start[idx].astype(np.uint64),
            "duration": np.maximum(self.c_last[idx] - self.c_start[idx],
                                   0).astype(np.uint64),
            "tap_side": self.c_tap_side[idx].astype(np.uint32),
            "l3_epc_id": np.zeros(len(idx), np.int32),
            "is_new_flow": (~self.c_reported[idx]).astype(np.uint32),
        }
        # LogMessageStatus (l4_flow_log.go getStatus :857) computed HERE
        # so the planar columnar wire carries the same value the server
        # derives for protobuf streams (wire-mode must not change data)
        proto_tcp = out["proto"] == PROTO_TCP
        ctv = ct[idx]
        out["status"] = np.where(
            (ctv == CLOSE_FORCED_REPORT) | (ctv == CLOSE_FIN), 0,
            np.where(ctv == CLOSE_TIMEOUT, np.where(proto_tcp, 3, 0),
                     np.where(ctv == CLOSE_RST, 3, 2))).astype(np.uint32)
        # perf-engine window columns (rtt/srt/art/cit/zero-win/...);
        # the full-handshake rtt falls back to the SYN->SYN_ACK estimate
        # when the engine saw no handshake ACK (e.g. ack-less captures)
        perf = self.perf.report(idx, cli)
        est = np.where((syn > 0) & (synack > syn),
                       (synack - syn) // 1000, 0).astype(np.uint32)
        perf["rtt"] = np.where(perf["rtt"] > 0, perf["rtt"], est)
        out.update(perf)
        # reset interval counters on kept-active flows; free closed slots
        act_idx = np.nonzero(active)[0] if emit_active else \
            np.empty(0, np.int64)
        self.c_bytes[act_idx] = 0
        self.c_pkts[act_idx] = 0
        self.c_retrans[act_idx] = 0
        self.c_reported[act_idx] = True
        self.perf.window_reset(act_idx)
        for s in np.nonzero(closed)[0]:
            self.c_live[s] = False
            del self._slot[tuple(self.c_key[s].tolist())]
            self._free.append(int(s))
        return out

    def tick(self, now_ns: Optional[int] = None,
             emit_active: bool = True) -> List[FlowAcc]:
        """Row-view tick for callers that want per-flow objects (tests,
        ad-hoc inspection). Same semantics as tick_columns; the column
        path is the hot one."""
        now_ns = int(time.time() * 1e9) if now_ns is None else now_ns
        snap = self._row_views(now_ns)
        self.tick_columns(now_ns, emit_active=emit_active)
        out = []
        for f in snap:
            closed = f.close_type(now_ns) != CLOSE_FORCED_REPORT
            if closed or (emit_active and f.packets != [0, 0]):
                out.append(f)
        return out

    def _row_views(self, now_ns: int) -> List[FlowAcc]:
        out = []
        for s in np.nonzero(self.c_live)[0]:
            k = self.c_key[s]
            out.append(FlowAcc(
                int(k[0]), int(k[1]), int(k[2]), int(k[3]), int(k[4]),
                flow_id=int(self.c_flow_id[s]),
                start_ns=int(self.c_start[s]), last_ns=int(self.c_last[s]),
                bytes_=self.c_bytes[s].tolist(),
                packets=self.c_pkts[s].tolist(),
                flags=self.c_flags[s].tolist(),
                retrans=self.c_retrans[s].tolist(),
                max_seq=self.c_max_seq[s].tolist(),
                syn_ns=int(self.c_syn[s]), synack_ns=int(self.c_synack[s]),
                initiator=int(self.c_initiator[s]),
                reported=bool(self.c_reported[s])))
        return out

    def __len__(self) -> int:
        return len(self._slot)

    def counters(self) -> dict:
        return {"packets_in": self.packets_in,
                "invalid_packets": self.invalid_packets,
                "flows_created": self.flows_created,
                "active_flows": len(self._slot)}


def flows_to_columns(flows: List[FlowAcc], vtap_id: int,
                     now_ns: int) -> Dict[str, np.ndarray]:
    """TaggedFlow-equivalent columns from FlowAcc row views (compat for
    the tick() path; tick_columns emits these directly)."""
    n = len(flows)
    cols = {k: np.zeros(n, dt) for k, dt in (
        ("ip_src", np.uint32), ("ip_dst", np.uint32),
        ("port_src", np.uint32), ("port_dst", np.uint32),
        ("proto", np.uint32), ("vtap_id", np.uint32),
        ("byte_tx", np.uint64), ("byte_rx", np.uint64),
        ("packet_tx", np.uint64), ("packet_rx", np.uint64),
        ("retrans", np.uint32), ("rtt", np.uint32),
        ("close_type", np.uint32), ("flow_id", np.uint64),
        ("start_time", np.uint64), ("duration", np.uint64),
        ("tap_side", np.uint32), ("l3_epc_id", np.int32),
        ("is_new_flow", np.uint32))}
    for i, f in enumerate(flows):
        cli = f.initiator if f.initiator >= 0 else 0
        srv = 1 - cli
        ips = (f.ip0, f.ip1)
        ports = (f.port0, f.port1)
        cols["ip_src"][i] = ips[cli]
        cols["ip_dst"][i] = ips[srv]
        cols["port_src"][i] = ports[cli]
        cols["port_dst"][i] = ports[srv]
        cols["proto"][i] = f.proto
        cols["vtap_id"][i] = vtap_id
        cols["byte_tx"][i] = f.bytes_[cli]
        cols["byte_rx"][i] = f.bytes_[srv]
        cols["packet_tx"][i] = f.packets[cli]
        cols["packet_rx"][i] = f.packets[srv]
        cols["retrans"][i] = f.retrans[0] + f.retrans[1]
        cols["rtt"][i] = f.rtt_us
        cols["close_type"][i] = f.close_type(now_ns)
        cols["flow_id"][i] = f.flow_id
        cols["start_time"][i] = f.start_ns
        cols["duration"][i] = max(f.last_ns - f.start_ns, 0)
        cols["is_new_flow"][i] = 0 if f.reported else 1
    return cols

"""The sender retransmit ring / receiver dedup pair model
(agent/sender.py + runtime/receiver.py, PR 4).

One `UniformSender` talking to one receiver `VtapStatus` over a FIFO
connection that the ``sender.disconnect`` fault can kill at a frame
boundary — with the delivery of the in-flight frame left UNKNOWN (both
outcomes explored). The sender's ring holds every framed batch until
capacity evicts it; on reconnect the whole sent prefix re-sends
FLAGGED (`FLOW_HEADER_RETRANSMIT`), and the receiver suppresses a
flagged frame at `seq <= last_seq` as a duplicate — the at-least-once
ring plus the dedup belt is what makes delivery into `_dispatch`
exactly-once.

Transition <-> code map (gated by conform.py):

- ``send_new``   <-> ``UniformSender.send`` / ``_ring_push_locked``
                     (eviction: a sent entry is free, an unsent entry
                     is COUNTED ``retransmit_shed``)
- ``pump``       <-> ``UniformSender._pump_ring_locked``
- ``reconnect``  <-> ``UniformSender._transmit_locked`` (flag the sent
                     prefix, reset it, re-send everything)
- ``deliver``    <-> ``Receiver._dispatch`` + ``VtapStatus.observe``
                     (dup suppression / gap inference / agent-restart
                     reset)
- fault ``sender.disconnect`` <-> the chaos site in
  ``_pump_ring_locked``

Safety invariant (every reachable state): **exactly-once** — no
sequence number is ever dispatched twice (`multi` stays False). The
skip-dedup and reconnect-without-flag mutants both die here.

Liveness goal: the system quiesces with every frame ACCOUNTED —
dispatched, counted shed (never-sent eviction / close), inferred lost
by the receiver's sequence-gap ledger, or the documented residual of
evicting an already-sent frame whose delivery stayed unknowable. The
evict-unsent-silently mutant makes the goal unreachable: a frame
vanishes from every ledger at once.
"""

from __future__ import annotations

from typing import List, Optional

from deepflow_tpu.runtime.faults import FAULT_SENDER_DISCONNECT
from deepflow_tpu.analysis.model.spec import Action, Model, State, updated

__all__ = ["build", "MUTANTS", "CONFORMANCE"]

MAXF = 3      # frames the producer creates (seq 1..MAXF)
RING = 2      # retransmit ring capacity (frames)
CHCAP = 1     # frames in flight on the connection

CONFORMANCE = {
    "protocol": "sender",
    "ledgers": [
        {"src": "deepflow_tpu/agent/sender.py:UniformSender.counters",
         "counters": ["sent_records", "retransmit_shed",
                      "retransmitted_frames", "disconnects",
                      "ring_pending_frames"]},
        {"src": "deepflow_tpu/runtime/receiver.py:Receiver.counters",
         "counters": ["rx_duplicate", "seq_dropped"]},
    ],
    "fault_sites": ["sender.disconnect"],
    "twins": {
        "send_new": "deepflow_tpu/agent/sender.py:UniformSender.send",
        "evict":
            "deepflow_tpu/agent/sender.py:UniformSender._ring_push_locked",
        "pump":
            "deepflow_tpu/agent/sender.py:UniformSender._pump_ring_locked",
        "reconnect":
            "deepflow_tpu/agent/sender.py:UniformSender._transmit_locked",
        "observe": "deepflow_tpu/runtime/receiver.py:VtapStatus.observe",
        "dispatch": "deepflow_tpu/runtime/receiver.py:Receiver._dispatch",
    },
}


def build(mutation: Optional[str] = None) -> Model:
    m = mutation

    init: State = {
        "next_seq": 0,
        "ring": (),          # ((seq, retransmit_flag), ...) send order
        "prefix": 0,         # entries [0, prefix) already on the wire
        "conn": True,
        "chan": (),          # in-flight ((seq, flag), ...) FIFO
        "seen": False,       # receiver saw any frame yet
        "last": 0,           # receiver last_seq
        "disp": frozenset(), # seqs delivered into _dispatch
        "multi": False,      # GHOST: some seq dispatched twice
        "shed": 0,           # counted never-sent eviction
        "gap": 0,            # receiver-inferred upstream loss
        "dup": 0,            # suppressed retransmits (rx_duplicate)
        "evs": 0,            # GHOST: sent entries evicted, fate unknown
    }

    # -- sender ------------------------------------------------------------
    def send_g(s: State) -> bool:
        return s["next_seq"] < MAXF

    def send_e(s: State) -> State:
        seq = s["next_seq"] + 1
        ring, prefix = list(s["ring"]), s["prefix"]
        shed, evs = s["shed"], s["evs"]
        while len(ring) >= RING:
            ring.pop(0)
            if prefix > 0:
                prefix -= 1          # evicting a sent entry is free...
                evs += 1             # ...but its fate is now unknowable
            elif m != "evict-unsent-silently":
                shed += 1            # the ONLY counted sender-side loss
        ring.append((seq, False))
        return updated(s, next_seq=seq, ring=tuple(ring), prefix=prefix,
                       shed=shed, evs=evs)

    def pump_g(s: State) -> bool:
        return (s["conn"] and s["prefix"] < len(s["ring"])
                and len(s["chan"]) < CHCAP)

    def pump_e(s: State) -> State:
        entry = s["ring"][s["prefix"]]
        return updated(s, prefix=s["prefix"] + 1,
                       chan=s["chan"] + (entry,))

    def reconnect_g(s: State) -> bool:
        return not s["conn"]

    def reconnect_e(s: State) -> State:
        ring = s["ring"]
        if m != "reconnect-no-flag":
            # delivery of the whole sent prefix is unknown: re-send it
            # all, FLAGGED, so the dedup belt can tell a ring replay
            # from an agent restart
            ring = tuple((seq, True) if i < s["prefix"] else (seq, f)
                         for i, (seq, f) in enumerate(ring))
        return updated(s, conn=True, ring=ring, prefix=0)

    def disconnect_g(s: State) -> bool:
        return s["conn"]

    def disconnect_e(s: State) -> List[State]:
        dead = updated(s, conn=False)
        if dead["chan"]:
            # the in-flight frame's fate is exactly what a dead
            # connection cannot tell the sender: explore both
            return [dead, updated(dead, chan=())]
        return [dead]

    # -- receiver ----------------------------------------------------------
    def deliver_g(s: State) -> bool:
        return bool(s["chan"])

    def _dispatch(s: State, seq: int) -> State:
        return updated(s,
                       multi=s["multi"] or seq in s["disp"],
                       disp=s["disp"] | {seq},
                       last=max(s["last"], seq), seen=True)

    def deliver_e(s: State) -> State:
        (seq, flag), chan = s["chan"][0], s["chan"][1:]
        s = updated(s, chan=chan)
        if s["seen"] and seq <= s["last"]:
            if flag and m != "skip-dedup-seq-check":
                # a flagged frame at or below last_seq was already
                # dispatched here (or counted into the gap ledger):
                # suppress, count rx_duplicate
                return updated(s, dup=s["dup"] + 1)
            # unflagged backwards = agent restart (reset tracking and
            # deliver) — or the mutant skipping the dedup check
            return _dispatch(updated(s, last=0, seen=False), seq)
        gap = s["gap"]
        if s["seen"] and seq > s["last"] + 1:
            gap += seq - s["last"] - 1     # upstream loss, inferred
        return _dispatch(updated(s, gap=gap), seq)

    actions = [
        Action("send_new", send_g, send_e, process="sender"),
        Action("pump", pump_g, pump_e, process="sender"),
        Action("reconnect", reconnect_g, reconnect_e, process="sender"),
        Action("deliver", deliver_g, deliver_e, process="receiver"),
        Action("disconnect", disconnect_g, disconnect_e,
               process="wire", fault=FAULT_SENDER_DISCONNECT),
    ]

    # -- invariants --------------------------------------------------------
    def exactly_once(s: State) -> Optional[str]:
        if s["multi"]:
            return ("a sequence number was delivered into _dispatch "
                    "twice — at-least-once retransmit leaked through "
                    "the receiver dedup belt (double-counted sketches)")
        return None

    def sane(s: State) -> Optional[str]:
        if not (0 <= s["prefix"] <= len(s["ring"])):
            return (f"sent prefix {s['prefix']} outside the ring "
                    f"(len {len(s['ring'])})")
        return None

    def quiesced(s: State) -> bool:
        return (s["next_seq"] == MAXF and not s["chan"]
                and s["prefix"] == len(s["ring"]))

    def done(s: State) -> bool:
        return quiesced(s)

    def goal(s: State) -> bool:
        accounted = len(s["disp"]) + s["shed"] + s["gap"] + s["evs"]
        return s["conn"] and quiesced(s) and accounted >= MAXF

    return Model("sender-ring", init, actions,
                 [("exactly-once", exactly_once), ("ring-sane", sane)],
                 done=done, goal=goal)


MUTANTS = {
    "skip-dedup-seq-check": "the receiver dispatches flagged "
                            "retransmits without the seq check — "
                            "double delivery (exactly-once)",
    "reconnect-no-flag": "the ring replays unflagged after a "
                         "reconnect — the receiver reads it as an "
                         "agent restart and re-dispatches "
                         "(exactly-once)",
    "evict-unsent-silently": "ring overflow evicts a never-sent frame "
                             "without counting retransmit_shed — the "
                             "frame leaves every ledger (livelock)",
}

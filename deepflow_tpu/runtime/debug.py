"""UDP debug protocol: runtime introspection for the CLI.

Reference: server/libs/debug — a UDP request/response protocol every
ingester module registers into, driven by `deepflow-ctl ingester ...`.
Here requests/responses are single-datagram JSON: {"cmd": ...} in,
{"ok": ..., "data": ...} out. Commands: counters (scrape the Countable
registry), vtap-status (receiver per-agent sequence tracking), ping,
stacks (every thread's current Python stack — the self-profiling role
the reference's pprof server on :9526 plays, server/cmd/server/main.go).
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Callable, Dict, Optional

from deepflow_tpu.runtime.stats import StatsRegistry
from deepflow_tpu.runtime.tracing import Tracer, default_tracer

DEFAULT_DEBUG_PORT = 30035


class DebugServer:
    def __init__(self, stats: StatsRegistry, port: int = DEFAULT_DEBUG_PORT,
                 host: str = "127.0.0.1",
                 tracer: Optional[Tracer] = None) -> None:
        self.stats = stats
        self.tracer = tracer if tracer is not None else default_tracer()
        self._handlers: Dict[str, Callable[[dict], object]] = {
            "ping": lambda req: "pong",
            "counters": self._counters,
            "stacks": self._stacks,
            "latency": self._latency,
            "spans": self._spans,
            "rrt": self._rrt,
            # default supervision-tree view (the Ingester overrides this
            # with its own registration — same shape, same command)
            "supervisor": self._supervisor,
            "lint": self._lint,
            "trace-export": self._trace_export,
        }
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, port))
        self._sock.settimeout(0.2)
        self._stop = threading.Event()
        self._thread = None            # supervisor ThreadHandle

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def register(self, cmd: str, handler: Callable[[dict], object]) -> None:
        self._handlers[cmd] = handler

    def _counters(self, req: dict) -> dict:
        module = req.get("module")
        out = {}
        for s in self.stats.collect():
            if module is None or s.module.startswith(module):
                out[s.module] = s.values
        return out

    def _latency(self, req: dict) -> dict:
        """Flight-recorder per-stage latency quantiles (the `deepflow-ctl
        ingester rrt`-family backing data). `module` prefix-filters
        stage names. `occupancy` carries the continuous profiler
        reductions (device-busy fraction, feed-overlap efficiency,
        cumulative feed stall) for the CLI's occupancy columns."""
        from deepflow_tpu.runtime.profiler import default_profiler

        want = req.get("module") or ""
        return {"enabled": self.tracer.enabled,
                "stages": {k: v for k, v in self.tracer.latency().items()
                           if k.startswith(want)},
                "occupancy": default_profiler().occupancy()}

    @staticmethod
    def _trace_export(req: dict) -> dict:
        """The occupancy profiler's span ring as a Chrome-trace /
        Perfetto JSON timeline (`df-ctl trace export`). `limit` caps
        the newest events so the reply fits the one-datagram budget:
        a serialized X event runs ~130-145B (epoch-microsecond floats
        are 18-19 chars), so 350 events + track metadata + the
        occupancy wrapper stays comfortably under 65000B."""
        from deepflow_tpu.runtime.profiler import default_profiler

        limit = max(0, min(int(req.get("limit", 350)), 350))
        prof = default_profiler()
        return {"trace": prof.to_chrome_trace(limit=limit),
                "spans_recorded": prof.counters()["spans"],
                "occupancy": prof.occupancy()}

    def _spans(self, req: dict) -> dict:
        """Recent completed spans from the ring, newest first. Options:
        stage (exact), slow_ms (only slower), count (<= 200 — the reply
        must fit one datagram)."""
        count = min(int(req.get("count", 20)), 200)
        return {"enabled": self.tracer.enabled,
                "spans": self.tracer.recent(
                    n=count, stage=req.get("stage") or None,
                    slow_ms=(float(req["slow_ms"])
                             if req.get("slow_ms") is not None else None))}

    def _rrt(self, req: dict) -> dict:
        """Where-time-goes attribution: TPU transfer/kernel gauges
        (h2d MB/s, compile seconds) beside the kernel stage summaries —
        the round-trip view of one batch through the device."""
        lat = self.tracer.latency()
        return {"enabled": self.tracer.enabled,
                "gauges": self.tracer.gauges(),
                "kernel_stages": {k: v for k, v in lat.items()
                                  if k.startswith(("kernel", "shard"))},
                "spans_recorded": self.tracer.spans_recorded}

    @staticmethod
    def _supervisor(req: dict) -> dict:
        """Process supervision tree: worker liveness/restart rows + the
        retained crash ring (tracebacks truncated for the one-datagram
        budget). Pairs with `stacks` — this says WHICH worker is
        crash-looping or deadman-stale, stacks says WHERE it sits."""
        from deepflow_tpu.runtime.supervisor import default_supervisor

        sup = default_supervisor()
        want = req.get("module") or ""
        return {
            "counters": sup.counters(),
            "threads": [t for t in sup.threads() if want in t["name"]],
            "crashes": [{**c, "traceback": c["traceback"][-1200:]}
                        for c in sup.crash_log()[-8:]],
        }

    @staticmethod
    def _lint(req: dict) -> dict:
        """deepflow-lint self-scan of the INSTALLED package (analysis/):
        is the code this process is actually running clean? Per-rule
        totals plus the first findings, truncated for the one-datagram
        budget; `module` substring-filters finding paths. No baseline is
        applied here — this is the raw discipline surface; ci.sh owns
        the grandfathered-baseline gate. The ~250-file ast.parse pass
        runs inside the debug loop's request slot and takes SECONDS in
        a busy process (GIL contention) — the CLI client raises its
        datagram timeout for this command, and other debug requests
        queue behind it (ops surface, not hot path)."""
        from collections import Counter

        from deepflow_tpu.analysis import scan_package

        want = req.get("module") or ""
        fs = [f for f in scan_package() if want in f.path]
        return {"total": len(fs),
                "by_rule": dict(sorted(Counter(f.rule for f in fs).items())),
                "findings": [f.to_dict() for f in fs[:25]]}

    @staticmethod
    def _stacks(req: dict) -> dict:
        """Live stack of every thread, keyed "name (tid)". The one-shot
        on-demand form of the reference's always-on pprof endpoint —
        enough to see where a wedged decoder/sender/window thread sits
        without attaching a debugger to the process."""
        import sys
        import traceback
        names = {t.ident: t.name for t in threading.enumerate()}
        out = {}
        for tid, frame in sys._current_frames().items():
            key = f"{names.get(tid, '?')} ({tid})"
            out[key] = [f"{f.filename}:{f.lineno} {f.name}"
                        for f in traceback.extract_stack(frame)][-8:]
        return out

    def start(self) -> None:
        # supervised: a crashed debug loop restarts on the same socket
        # instead of going silently deaf (the socket survives the crash)
        from deepflow_tpu.runtime.supervisor import default_supervisor
        self._thread = default_supervisor().spawn("debug-udp", self._run)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.stop()
            self._thread.join(timeout=2)
        self._sock.close()

    def _run(self) -> None:
        from deepflow_tpu.runtime.supervisor import default_supervisor
        sup = default_supervisor()
        while not self._stop.is_set():
            sup.beat()
            try:
                data, addr = self._sock.recvfrom(65536)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                req = json.loads(data.decode())
                handler = self._handlers.get(req.get("cmd", ""))
                if handler is None:
                    resp = {"ok": False, "error": "unknown command"}
                else:
                    resp = {"ok": True, "data": handler(req)}
            except Exception as e:
                resp = {"ok": False, "error": str(e)}
            payload = json.dumps(resp).encode()
            if len(payload) > 65000:   # single-datagram protocol
                payload = json.dumps({
                    "ok": False,
                    "error": f"response too large ({len(payload)} bytes) "
                             "for one datagram; narrow with --module"}
                ).encode()
            try:
                self._sock.sendto(payload, addr)
            except OSError:
                pass


def debug_request(cmd: str, port: int = DEFAULT_DEBUG_PORT,
                  host: str = "127.0.0.1", timeout: float = 2.0,
                  **kw) -> dict:
    """One-shot client (the deepflow-ctl side)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.settimeout(timeout)
    try:
        sock.sendto(json.dumps({"cmd": cmd, **kw}).encode(), (host, port))
        data, _ = sock.recvfrom(1 << 20)
        return json.loads(data.decode())
    finally:
        sock.close()

"""Controller HTTP API (reference: server/controller/http/ routers).

Agent-facing (the trisolaris sync surface, JSON over HTTP instead of
gRPC — the reference's message/trident.proto Synchronizer service):
  POST /v1/sync             {ctrl_ip, host, revision?, boot?,
                             processes?: [{pid, name, start_time}]}
                            -> vtap_id, config, config_version,
                               platform_version, ingester,
                               gpids? (GPIDSync), upgrade? (staged)
  POST /v1/genesis          {ctrl_ip, host, interfaces: [...]}
  GET  /v1/genesis/export   locally-owned genesis domains (peer pull)

Ops-facing (driven by the CLI):
  GET  /v1/vtaps            fleet listing with liveness
  GET  /v1/vtap-groups      group names
  GET/POST /v1/vtap-group-config?group=g     config CRUD
  POST /v1/domains/<name>/resources          full domain snapshot
  GET  /v1/resources[?type=pod]
  GET  /v1/cloud/tasks      per-domain poller info + cost
  POST /v1/cloud/domains    {domain, platform: filereader|http|kubernetes_gather, ...}
  DELETE /v1/cloud/domains/<name>
  POST /v1/domains/<name>/refresh            trigger an immediate gather
  GET  /v1/platform-data    compiled enrichment tables + version
  GET  /v1/election         leader status
  POST /v1/ingesters        {addrs: [...]} membership for rebalancing
  GET  /v1/assignments
  POST /v1/upgrade-package  {name, data_b64} upload (sha256 returned)
  GET  /v1/upgrade-package?name=             download
  POST /v1/upgrade          {group, revision, package} target a group
  GET  /v1/upgrade          fleet convergence status
  DELETE /v1/upgrade/<group>
"""

from __future__ import annotations

import json
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from deepflow_tpu.controller.cloud import (CloudManager, FileReaderPlatform,
                                           HttpPlatform,
                                           KubernetesGatherPlatform,
                                           rows_to_resources)
from deepflow_tpu.controller.election import Election
from deepflow_tpu.controller.model import ResourceModel, make_resource
from deepflow_tpu.controller.monitor import FleetMonitor
from deepflow_tpu.controller.platform_compiler import compile_platform_data
from deepflow_tpu.controller.registry import VTapRegistry
from deepflow_tpu.controller.tagrecorder import TagRecorder

DEFAULT_PORT = 20417   # reference controller HTTP is 20417 in-cluster


class ControllerServer:
    def __init__(self, model: ResourceModel, registry: VTapRegistry,
                 monitor: Optional[FleetMonitor] = None,
                 election: Optional[Election] = None,
                 tagrecorder: Optional[TagRecorder] = None,
                 genesis_domain: str = "genesis",
                 genesis_peers=None,
                 cloud_resource_dir: Optional[str] = None,
                 package_dir: Optional[str] = None,
                 port: int = DEFAULT_PORT, host: str = "127.0.0.1") -> None:
        self.model = model
        # filereader domains may only read documents under this directory
        # (None = anywhere, for single-user dev). Without the fence, the
        # unauthenticated ops API would be a file-probing primitive: any
        # controller-readable path could be fed to the gather loop and
        # its parse errors read back from /v1/cloud/tasks.
        self.cloud_resource_dir = (os.path.realpath(cloud_resource_dir)
                                   if cloud_resource_dir else None)
        from deepflow_tpu.controller.genesis_sync import GenesisSync
        from deepflow_tpu.controller.recorder import Recorder
        self.recorder = Recorder(model)
        self.cloud = CloudManager(self.recorder)
        self.process_record_errors = 0
        self._proc_record_calls = 0
        self.genesis_sync = GenesisSync(model, peers=genesis_peers or ())
        self.registry = registry
        self.monitor = monitor or FleetMonitor(registry)
        self.election = election
        self.tagrecorder = tagrecorder
        self.genesis_domain = genesis_domain
        # upgrade packages: memory cache, optional disk persistence —
        # the upgrade TARGET persists in the registry file, so the
        # package must survive a controller restart too or a
        # mid-rollout restart strands the fleet on 404s
        self._packages: Dict[str, bytes] = {}
        self.package_dir = package_dir
        if package_dir is not None:
            os.makedirs(package_dir, exist_ok=True)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length).decode() if length else "{}"
                return json.loads(raw or "{}")

            def do_GET(self) -> None:
                try:
                    url = urllib.parse.urlparse(self.path)
                    qs = {k: v[0] for k, v in
                          urllib.parse.parse_qs(url.query).items()}
                    self._send(200, outer._get(url.path, qs))
                except KeyError as e:
                    self._send(404, {"error": str(e)})
                except Exception as e:
                    self._send(400, {"error": str(e)})

            def do_POST(self) -> None:
                try:
                    url = urllib.parse.urlparse(self.path)
                    qs = {k: v[0] for k, v in
                          urllib.parse.parse_qs(url.query).items()}
                    self._send(200, outer._post(url.path, qs, self._body()))
                except KeyError as e:
                    self._send(404, {"error": str(e)})
                except Exception as e:
                    self._send(400, {"error": str(e)})

            def do_DELETE(self) -> None:
                try:
                    url = urllib.parse.urlparse(self.path)
                    self._send(200, outer._delete(url.path))
                except KeyError as e:
                    self._send(404, {"error": str(e)})
                except Exception as e:
                    self._send(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    # -- routing -----------------------------------------------------------
    def _get(self, path: str, qs: dict):
        if path == "/v1/health":
            # liveness/readiness for deploy probes (manifests/k8s): cheap,
            # no model access beyond version reads
            return {"ok": True, "is_leader": self.election.is_leader
                    if self.election is not None else True,
                    "model_version": self.model.version}
        if path == "/v1/vtaps":
            status = self.monitor.check()
            return [{**vars(v), "alive": f"{v.ctrl_ip}|{v.host}"
                     in status["alive"]} for v in self.registry.list()]
        if path == "/v1/vtap-groups":
            return self.registry.groups()
        if path == "/v1/vtap-group-config":
            return self.registry.get_config(qs.get("group", "default"))
        if path == "/v1/resources":
            return [{"type": r.type, "id": r.id, "name": r.name,
                     "domain": r.domain, **dict(r.attrs)}
                    for r in self.model.list(type=qs.get("type"))]
        if path == "/v1/platform-data":
            ifaces, cidrs, services, version = compile_platform_data(
                self.model)
            return {
                "version": version,
                "interfaces": [vars(i) for i in ifaces],
                "cidrs": [vars(c) for c in cidrs],
                "services": [vars(s) for s in services],
            }
        if path == "/v1/genesis/export":
            return {"domains": self.genesis_sync.export()}
        if path == "/v1/election":
            if self.election is None:
                return {"leader": True, "identity": "standalone"}
            return {"leader": self.election.is_leader,
                    "identity": self.election.identity}
        if path == "/v1/assignments":
            return self.monitor.assignments()
        if path == "/v1/cloud/tasks":
            return [vars(i) for i in self.cloud.tasks()]
        if path == "/v1/recorder":
            # recorder debug surface (reference: deepflow-ctl recorder):
            # counters + soft-deleted rows still inside retention
            return {**self.recorder.counters(),
                    "process_record_errors": self.process_record_errors,
                    "genesis": self.genesis_sync.counters(),
                    "tombstones_rows": [
                        {"type": r.type, "id": r.id, "name": r.name,
                         "domain": r.domain}
                        for r in self.recorder.deleted_resources()]}
        if path == "/v1/upgrade":
            return self.registry.upgrade_status()
        if path == "/v1/upgrade-package":
            import base64
            import hashlib
            data = self.package_bytes(qs.get("name", ""))
            if data is None:
                raise KeyError(qs.get("name", ""))
            sha = hashlib.sha256(data).hexdigest()
            if qs.get("meta"):
                # metadata-only probe: agents validate their plugin
                # cache against this without re-downloading the bytes
                return {"name": qs["name"], "size": len(data),
                        "sha256": sha}
            return {"name": qs["name"],
                    "data_b64": base64.b64encode(data).decode(),
                    "sha256": sha}
        if path == "/health":
            return {"status": "ok"}
        raise KeyError(path)

    def _post(self, path: str, qs: dict, body: dict):
        if path == "/v1/sync":
            resp = self.registry.sync(body["ctrl_ip"], body["host"],
                                      body.get("revision", ""),
                                      bool(body.get("boot")),
                                      processes=body.get("processes"))
            if body.get("processes") and resp.get("gpids"):
                self._record_processes(resp["vtap_id"],
                                       body["processes"],
                                       resp["gpids"])
            resp["platform_version"] = self.model.version
            resp["ingester"] = self.monitor.assign(body["ctrl_ip"],
                                                   body["host"])
            return resp
        if path == "/v1/upgrade-package":
            # package bytes ride base64 inside the JSON control plane
            # (reference: rpc Upgrade streams chunks; one body here).
            # Held in memory: packages are transient distribution
            # artifacts, not durable state.
            import base64
            import hashlib
            name = body["name"]
            if "/" in name or name.startswith("."):
                raise ValueError("package name must be a bare filename")
            data = base64.b64decode(body["data_b64"])
            self._packages[name] = data
            if self.package_dir is not None:
                tmp = os.path.join(self.package_dir, name + ".tmp")
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, os.path.join(self.package_dir, name))
            return {"name": name, "size": len(data),
                    "sha256": hashlib.sha256(data).hexdigest()}
        if path == "/v1/upgrade":
            import hashlib
            pkg = body["package"]
            data = self.package_bytes(pkg)
            if data is None:
                raise KeyError(f"unknown package {pkg!r}")
            self.registry.set_upgrade(
                body.get("group", "default"), body["revision"], pkg,
                hashlib.sha256(data).hexdigest())
            return self.registry.upgrade_status()
        if path == "/v1/genesis":
            return self.genesis_report(body["host"],
                                       body.get("interfaces", []))
        if path == "/v1/vtap-group-config":
            version = self.registry.set_config(qs.get("group", "default"),
                                               body)
            return {"config_version": version}
        if path.startswith("/v1/domains/") and path.endswith("/resources"):
            domain = urllib.parse.unquote(
                path[len("/v1/domains/"):-len("/resources")])
            snapshot = rows_to_resources(body.get("resources", []), domain)
            diff = self.recorder.reconcile(domain, snapshot)
            return {"created": len(diff.created),
                    "deleted": len(diff.deleted),
                    "updated": len(diff.updated),
                    "orphaned": len(diff.orphaned),
                    "field_changes": [
                        {"type": c.type, "id": c.id, "field": c.field,
                         "old": c.old, "new": c.new}
                        for c in diff.field_changes],
                    "version": self.model.version}
        if path == "/v1/ingesters":
            self.monitor.set_ingesters(list(body.get("addrs", [])))
            return {"ingesters": self.monitor.ingesters()}
        if path == "/v1/cloud/domains":
            if not isinstance(body.get("domain"), str) or not body["domain"]:
                raise ValueError("domain must be a non-empty string")
            task = self.cloud.add(
                body["domain"], self._make_platform(body),
                interval_s=float(body.get("interval_s", 60.0)))
            return {"domain": task.domain, "platform": task.info.platform,
                    "auth_failed": task.info.auth_failed}
        if path.startswith("/v1/domains/") and path.endswith("/refresh"):
            domain = urllib.parse.unquote(
                path[len("/v1/domains/"):-len("/refresh")])
            task = self.cloud.get(domain)
            if task is None:
                raise KeyError(domain)
            ok = task.gather_once()   # synchronous: the CLI wants the diff
            return {"domain": domain, "ok": ok,
                    "error": task.info.last_error,
                    "resource_count": task.info.resource_count,
                    "version": self.model.version}
        raise KeyError(path)

    def genesis_report(self, host: str, interfaces: list) -> dict:
        """Agent-reported interfaces become resources in a PER-AGENT
        genesis domain (reference: controller/genesis sinks keyed by
        vtap) — one shared domain would let each agent's snapshot
        delete every other agent's rows. Ids must be restart-stable
        (content hash); only well-formed IPv4 addresses enter as host
        rows (a bad row would poison every later platform-data
        compile); mac-keyed ip-less entries (libvirt guest NICs) become
        vinterface rows. Shared by the JSON route and the trident gRPC
        GenesisSync rpc so the two ingest paths cannot diverge."""
        import ipaddress

        from deepflow_tpu.store.dict_store import fnv1a32
        domain = f"{self.genesis_domain}/{host}"
        snapshot = []
        for i, itf in enumerate(interfaces):
            try:
                ipaddress.IPv4Address(itf["ip"])
            except (KeyError, ValueError):
                # no (valid) ip: a libvirt guest NIC report is
                # mac-keyed (agent libvirt_xml_extractor role) —
                # model it as a vinterface row under the owning VM
                if itf.get("mac") and itf.get("domain_name"):
                    key = f"{host}|{itf['mac']}"
                    snapshot.append(make_resource(
                        "vinterface",
                        2_000_000 + (fnv1a32(key.encode()) & 0xFFFFF),
                        f"{itf['domain_name']}:{itf.get('name', i)}",
                        domain=domain,
                        mac=itf["mac"],
                        vm_name=itf["domain_name"],
                        vm_uuid=itf.get("domain_uuid", ""),
                        host=host))
                continue
            snapshot.append(make_resource(
                "host",
                1_000_000 + (fnv1a32(
                    f"{host}|{itf['ip']}".encode()) & 0xFFFFF),
                f"{host}:{itf.get('name', i)}",
                domain=domain,
                ip=itf["ip"], epc_id=itf.get("epc_id", 0)))
        diff = self.model.update_domain(domain, snapshot)
        self.genesis_sync.mark_local(domain)
        return {"created": len(diff.created),
                "deleted": len(diff.deleted)}

    def package_bytes(self, name: str) -> Optional[bytes]:
        """Memory first, then the persisted copy (controller restart
        mid-rollout must not strand the fleet)."""
        data = self._packages.get(name)
        if data is None and self.package_dir is not None and name \
                and "/" not in name and not name.startswith("."):
            try:
                with open(os.path.join(self.package_dir, name),
                          "rb") as f:
                    data = f.read()
                self._packages[name] = data
            except OSError:
                return None
        return data

    def _delete(self, path: str):
        if path.startswith("/v1/upgrade/"):
            group = urllib.parse.unquote(path[len("/v1/upgrade/"):])
            if not self.registry.clear_upgrade(group):
                raise KeyError(group)
            return {"cleared": group}
        if path.startswith("/v1/cloud/domains/"):
            domain = urllib.parse.unquote(path[len("/v1/cloud/domains/"):])
            if not self.cloud.remove(domain):
                raise KeyError(domain)
            return {"deleted": domain, "version": self.model.version}
        raise KeyError(path)

    # one model domain holds every agent's reported processes; each
    # vtap owns a SUB-DOMAIN inside it so one agent's refresh can
    # never delete another's rows (the scoped-reconcile machinery
    # built for attached k8s clusters, reused)
    PROC_DOMAIN = "genesis-processes"

    def _record_processes(self, vtap_id: int, processes: list,
                          gpids: dict) -> None:
        """Agent-reported processes -> `process` resource rows keyed
        by their GLOBAL id (reference: the recorder's process updater
        + tagrecorder ch_gprocess — what makes gprocess_id columns
        humanize to process names in the querier). Failures are
        counted, never allowed to fail the sync RPC itself."""
        try:
            # O(1) idempotent upsert of THIS vtap's sub_domain row:
            # a whole-domain reconcile here would race concurrent
            # syncs (two first-syncs each reading the list before the
            # other's write -> mutual sub_domain deletion) and pay an
            # O(model) scan per sync
            self.model.upsert(make_resource(
                "sub_domain", vtap_id, f"vtap-{vtap_id}",
                domain=self.PROC_DOMAIN))
            proc_rows = []
            for p in processes[:4096]:
                gpid = gpids.get(str(p.get("pid")))
                if not gpid:
                    continue
                proc_rows.append(make_resource(
                    "process", int(gpid),
                    str(p.get("name") or p.get("pid")),
                    domain=self.PROC_DOMAIN,
                    sub_domain_id=vtap_id, pid=int(p["pid"]),
                    start_time=int(p.get("start_time", 0)),
                    vtap_id=vtap_id))
            self.recorder.reconcile_sub_domain(
                self.PROC_DOMAIN, vtap_id, proc_rows)
            # amortized dead-vtap sweep: a decommissioned host's
            # process inventory must not accumulate forever (its own
            # reconcile never comes again) — every 256th recording
            # sync pays one pruning pass
            self._proc_record_calls += 1
            if self._proc_record_calls % 256 == 0:
                self.prune_dead_vtap_processes()
        except (ValueError, KeyError, TypeError):
            self.process_record_errors += 1

    def prune_dead_vtap_processes(self,
                                  ttl_s: float = 3600.0) -> int:
        """Drop the process sub-domains of vtaps that no longer exist
        or haven't synced within `ttl_s`; returns pruned vtap count."""
        import time as _time
        now = _time.time()
        alive = {v.vtap_id for v in self.registry.list()
                 if now - v.last_seen < ttl_s}
        pruned = 0
        for sd in self.model.list(type="sub_domain",
                                  domain=self.PROC_DOMAIN):
            if sd.id in alive:
                continue
            self.recorder.reconcile_sub_domain(self.PROC_DOMAIN,
                                               sd.id, [])
            self.model.update_domain(
                self.PROC_DOMAIN,
                [r for r in self.model.list(domain=self.PROC_DOMAIN)
                 if not (r.type == "sub_domain" and r.id == sd.id)
                 and not r.attr("sub_domain_id", 0)])
            pruned += 1
        return pruned

    @staticmethod
    def _endpoint_template_kw(body: dict, required: str,
                              optional: tuple = ()) -> dict:
        """Validated endpoint_template pass-through shared by every
        vendor branch: http(s) scheme, the literal {required}
        placeholder present, and NO braces besides the allowed
        placeholders (a typo'd or attribute-access template —
        {regoin}, {region.__x__} — must 400 here, not fail on every
        later gather)."""
        if not body.get("endpoint_template"):
            return {}
        import re
        tmpl = body["endpoint_template"]
        scheme = urllib.parse.urlparse(tmpl).scheme
        if scheme not in ("http", "https"):
            raise ValueError("endpoint_template must be http(s)")
        names = "|".join(re.escape(n) for n in (required, *optional))
        if not re.fullmatch(r"[^{}]*(\{(%s)\}[^{}]*)+" % names, tmpl) \
                or ("{%s}" % required) not in tmpl:
            allowed = ", ".join(f"{{{n}}}" for n in (required,
                                                     *optional))
            raise ValueError(f"endpoint_template must contain "
                             f"{{{required}}} and no braces besides "
                             f"{allowed}")
        return {"endpoint_template": tmpl}

    def _make_platform(self, body: dict):
        kind = body.get("platform", "filereader")
        if kind == "filereader":
            if not body.get("path"):
                raise ValueError("filereader platform requires path")
            # validate the RESOLVED path and construct the platform with
            # it: passing the raw path would let a symlink inside the
            # fence be re-pointed outside it after creation, and every
            # later poll would follow it
            real = os.path.realpath(body["path"])
            if self.cloud_resource_dir is not None:
                if not (real == self.cloud_resource_dir
                        or real.startswith(self.cloud_resource_dir + os.sep)):
                    raise ValueError(
                        "filereader path outside cloud_resource_dir")
            return FileReaderPlatform(real, body["domain"])
        if kind == "http":
            if not body.get("url"):
                raise ValueError("http platform requires url")
            # urllib's default opener happily serves file:// — without
            # this check the 'http' platform would be a fence bypass
            scheme = urllib.parse.urlparse(body["url"]).scheme
            if scheme not in ("http", "https"):
                raise ValueError(f"http platform requires an http(s) url, "
                                 f"got scheme {scheme!r}")
            return HttpPlatform(body["url"], body["domain"],
                                headers=body.get("headers"))
        if kind == "kubernetes_gather":
            return KubernetesGatherPlatform(
                self.model, body.get("cluster", body["domain"]),
                body["domain"])
        if kind == "aws":
            # reference domain-config keys (aws.go NewAws): secret_id /
            # secret_key / region filters; endpoint override for
            # gov/china partitions or the test recorder
            from deepflow_tpu.controller.cloud_aws import AwsPlatform
            if not body.get("secret_id") or not body.get("secret_key"):
                raise ValueError("aws platform requires secret_id and "
                                 "secret_key")
            kw = self._endpoint_template_kw(body, "region")
            return AwsPlatform(
                body["domain"], body["secret_id"], body["secret_key"],
                regions=tuple(body.get("regions", ())),
                api_default_region=body.get("api_default_region",
                                            "us-east-1"), **kw)
        if kind == "aliyun":
            # reference domain-config keys (aliyun.go NewAliyun):
            # secret_id/secret_key + region include list
            from deepflow_tpu.controller.cloud_aliyun import \
                AliyunPlatform
            if not body.get("secret_id") or not body.get("secret_key"):
                raise ValueError("aliyun platform requires secret_id "
                                 "and secret_key")
            # {product} optional: the real vendor routes vpc/slb
            # actions to their own hosts (cloud_aliyun.py routing)
            kw = self._endpoint_template_kw(body, "region",
                                            optional=("product",))
            return AliyunPlatform(
                body["domain"], body["secret_id"], body["secret_key"],
                regions=tuple(body.get("regions", ())),
                api_default_region=body.get("api_default_region",
                                            "cn-hangzhou"), **kw)
        if kind == "tencent":
            # reference domain-config keys (tencent.go NewTencent);
            # endpoints are service-global ({service} placeholder, the
            # region rides the X-TC-Region header)
            from deepflow_tpu.controller.cloud_tencent import \
                TencentPlatform
            if not body.get("secret_id") or not body.get("secret_key"):
                raise ValueError("tencent platform requires secret_id "
                                 "and secret_key")
            kw = self._endpoint_template_kw(body, "service")
            return TencentPlatform(
                body["domain"], body["secret_id"], body["secret_key"],
                regions=tuple(body.get("regions", ())), **kw)
        if kind == "huawei":
            # reference domain-config keys (huawei/config.go): IAM
            # password identity + project scoping; token-lifecycle
            # auth, so no secret_id/secret_key pair here
            from deepflow_tpu.controller.cloud_huawei import \
                HuaweiPlatform
            for k in ("account_name", "iam_name", "password",
                      "project_name", "project_id", "iam_endpoint"):
                if not body.get(k):
                    raise ValueError(f"huawei platform requires {k}")
            scheme = urllib.parse.urlparse(body["iam_endpoint"]).scheme
            if scheme not in ("http", "https"):
                raise ValueError("iam_endpoint must be http(s)")
            kw = self._endpoint_template_kw(body, "service")
            if not kw:
                raise ValueError(
                    "huawei platform requires endpoint_template")
            return HuaweiPlatform(
                body["domain"], body["account_name"],
                body["iam_name"], body["password"],
                body["project_name"], body["project_id"],
                body["iam_endpoint"], kw["endpoint_template"])
        if kind == "qingcloud":
            from deepflow_tpu.controller.cloud_qingcloud import \
                QingCloudPlatform
            if not body.get("secret_id") or not body.get("secret_key"):
                raise ValueError("qingcloud platform requires "
                                 "secret_id and secret_key")
            kw = {}
            if body.get("url"):
                scheme = urllib.parse.urlparse(body["url"]).scheme
                if scheme not in ("http", "https"):
                    raise ValueError("url must be http(s)")
                kw["url"] = body["url"]
            return QingCloudPlatform(
                body["domain"], body["secret_id"], body["secret_key"],
                zones=tuple(body.get("zones", ())), **kw)
        if kind == "baidubce":
            from deepflow_tpu.controller.cloud_baidubce import \
                BaiduBcePlatform
            for k in ("secret_id", "secret_key", "endpoint"):
                if not body.get(k):
                    raise ValueError(f"baidubce platform requires {k}")
            scheme = body.get("scheme", "https")
            if scheme not in ("http", "https"):
                raise ValueError("scheme must be http or https")
            return BaiduBcePlatform(
                body["domain"], body["secret_id"], body["secret_key"],
                body["endpoint"],
                region_name=body.get("region_name", "baidu"),
                scheme=scheme, bcc_host=body.get("bcc_host"))
        raise ValueError(f"unknown platform kind {kind!r}")

    # -- lifecycle ---------------------------------------------------------
    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        # supervised (ISSUE 14 baseline burn-down): crash capture for
        # the accept loop. deadman off — serve_forever cannot beat
        # without the querier's service_actions subclass
        from deepflow_tpu.runtime.supervisor import default_supervisor
        self._thread = default_supervisor().spawn(
            "controller-http",
            lambda: self._httpd.serve_forever(poll_interval=0.5),
            deadman_s=None)
        self.genesis_sync.start()
        self.cloud.start()

    def close(self) -> None:
        self.cloud.close()
        self.genesis_sync.close()
        if self._thread is not None:
            self._thread.stop()     # no restart on the way down
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2)

"""Prometheus text-exposition endpoint over the self-telemetry surfaces.

The reference server runs an always-on stats/pprof listener on :9526
(server/cmd/server/main.go); this is its Prometheus-shaped equivalent:
one HTTP endpoint serving

- every Countable the StatsRegistry scrapes, as
  `deepflow_<module>_<name>` untyped samples with the source's tags as
  labels (plus non-numeric countable values riding as labels on a
  constant-1 info sample — dropping them would hide mode flags);
- the flight recorder's per-stage latency histograms
  (`deepflow_stage_latency_seconds` with a `stage` label), in native
  Prometheus histogram form — cumulative `le` buckets read straight off
  the host DDSketch's geometric boundaries, so `histogram_quantile`
  works against them with the sketch's own relative-error bound;
- tracer gauges (h2d MB/s, compile seconds, ...) as
  `deepflow_trace_<name>`.

`validate_exposition` is the strict line-format checker the golden test
and ci.sh both run against the live endpoint — the format is a contract
with real scrapers, so "mostly parseable" is a failure.
"""

from __future__ import annotations

import re
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from deepflow_tpu.runtime.stats import StatsRegistry
from deepflow_tpu.runtime.tracing import Tracer, default_tracer

DEFAULT_PROM_PORT = 9526   # the reference's self-observation listener

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_OK = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(*parts: str) -> str:
    return _NAME_OK.sub("_", "_".join(p for p in parts if p))


def _label_name(s: str) -> str:
    s = _LABEL_OK.sub("_", s)
    return ("_" + s) if (not s or s[0].isdigit()) else s


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels(d: Dict[str, str]) -> str:
    if not d:
        return ""
    inner = ",".join(f'{_label_name(k)}="{_escape_label(str(v))}"'
                     for k, v in sorted(d.items()))
    return "{" + inner + "}"


def render_metrics(stats: Optional[StatsRegistry],
                   tracer: Optional[Tracer],
                   bucket_stride: int = 64,
                   profiler=None,
                   timeline=None) -> str:
    """One scrape: collect Countables + tracer state + the occupancy
    profiler's continuous gauges, render text exposition format
    (version 0.0.4). `profiler` defaults to the process profiler
    (runtime/profiler.py) so ``tpu_device_busy_fraction`` /
    ``tpu_feed_stall_seconds`` are freshly computed per scrape.

    With a `timeline` (runtime/timeline.py) attached, fossil gauges —
    tracer gauges whose wall stamp is past the timeline's staleness
    horizon (10x sample cadence) — are withheld COUNTED as
    ``deepflow_selfmetric_stale`` instead of silently served, and the
    timeline's ``slo_burn_rate`` family is exposed as
    ``deepflow_slo_burn_rate{slo,window}``."""
    lines: List[str] = []
    typed: set = set()

    def _sample(name: str, labels: Dict[str, str], value: float,
                mtype: str = "untyped", help_: str = "") -> None:
        if name not in typed:
            typed.add(name)
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {mtype}")
        lines.append(f"{name}{_labels(labels)} {_fmt(value)}")

    if stats is not None:
        for s in stats.collect():
            tags = dict(s.tags)
            info = {}
            for k, v in s.values.items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    info[k] = str(v)
                else:
                    _sample(_metric_name("deepflow", s.module, k), tags,
                            float(v))
            if info:
                _sample(_metric_name("deepflow", s.module, "info"),
                        {**tags, **info}, 1.0,
                        help_="non-numeric countable values as labels")

    if tracer is not None:
        hname = "deepflow_stage_latency_seconds"
        first = True
        for stage, sk in sorted(tracer.stages().items()):
            # ONE snapshot per stage: spans keep landing while we
            # render, and +Inf must equal _count in the output
            buckets, total, sum_ = sk.snapshot(bucket_stride)
            if total == 0:
                continue
            if first:
                lines.append(f"# HELP {hname} per-stage pipeline latency "
                             "(host DDSketch, relative error "
                             f"{sk.alpha})")
                lines.append(f"# TYPE {hname} histogram")
                typed.add(hname)
                first = False
            lbl = {"stage": stage}
            for le, cum in buckets:
                lines.append(
                    f"{hname}_bucket{_labels({**lbl, 'le': repr(le)})} "
                    f"{_fmt(cum)}")
            lines.append(
                f"{hname}_bucket{_labels({**lbl, 'le': '+Inf'})} "
                f"{_fmt(total)}")
            lines.append(f"{hname}_sum{_labels(lbl)} {repr(sum_)}")
            lines.append(f"{hname}_count{_labels(lbl)} {_fmt(total)}")
        from deepflow_tpu.runtime.tracing import gauge_help
        stale = timeline.stale_gauges() if timeline is not None else {}
        for name, value in sorted(tracer.gauges().items()):
            if name in stale:
                # a fossil: its writer has not refreshed it within the
                # staleness horizon — withheld, counted below, never
                # silently served as if current
                continue
            # gauges registered at runtime (a concurrently-registering
            # thread, a plugin) may lack a GAUGE_HELP entry; the strict
            # validator rejects gauge-typed series without HELP, so
            # fall back to a generic line rather than emit an
            # exposition a real scraper flags mid-incident
            _sample(_metric_name("deepflow_trace", name), {}, value,
                    mtype="gauge",
                    help_=gauge_help(name) or
                    "tracer gauge (no GAUGE_HELP entry; see "
                    "runtime/tracing.py)")
        if timeline is not None:
            _sample("deepflow_selfmetric_stale", {}, float(len(stale)),
                    mtype="gauge",
                    help_="self-metric gauge series withheld from this "
                    "scrape as stale (no write within 10x the timeline "
                    "sample cadence)")
        _sample("deepflow_trace_spans_total", {},
                float(tracer.spans_recorded), mtype="counter",
                help_="spans recorded by the flight recorder")

    if profiler is None:
        from deepflow_tpu.runtime.profiler import default_profiler
        profiler = default_profiler()
    from deepflow_tpu.runtime.profiler import PROFILER_GAUGE_HELP
    for name, value in sorted(profiler.gauges().items()):
        _sample(_metric_name("deepflow_profiler", name), {}, value,
                mtype="gauge", help_=PROFILER_GAUGE_HELP.get(name, ""))
    _sample("deepflow_profiler_spans_total", {},
            float(profiler.spans_recorded), mtype="counter",
            help_="spans recorded into the occupancy ring")

    # the feed autotuner's control-loop gauges (runtime/autotune.py):
    # rendered from the module registry like the profiler's, fresh per
    # scrape — a paused or fallen-back controller still reports its
    # enabled=0 and final knob values instead of going silently absent
    from deepflow_tpu.runtime.autotune import (AUTOTUNE_GAUGE_HELP,
                                               autotune_gauges)
    for name, value in sorted(autotune_gauges().items()):
        _sample(_metric_name("deepflow", name), {}, value,
                mtype="gauge", help_=AUTOTUNE_GAUGE_HELP.get(name, ""))

    if timeline is not None:
        for lbl, burn in sorted(timeline.slo_gauges(),
                                key=lambda p: sorted(p[0].items())):
            _sample("deepflow_slo_burn_rate", lbl, burn, mtype="gauge",
                    help_="error-budget burn rate per SLO and window "
                    "(1.0 = budget burning exactly at its sustainable "
                    "pace; see runtime/timeline.py SloRule)")

    return "\n".join(lines) + "\n"


# -- strict format checker -------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'                       # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:\\.|[^"\\])*"'       # first label
    r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="(?:\\.|[^"\\])*")*\})?'  # more labels
    r' (-?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|\+?Inf|NaN))'  # value
    r'( [0-9]+)?$')                                      # optional ts
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
                      r"(counter|gauge|histogram|summary|untyped)$")
_LE_RE = re.compile(r'le="((?:\\.|[^"\\])*)"')
_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:\\.|[^"\\])*)"')


def _label_key(labels: str) -> tuple:
    """Canonical (name, value) tuple of a label block, `le` dropped —
    the grouping key that pairs a histogram's buckets with its
    _sum/_count series regardless of label ordering."""
    return tuple(sorted((k, v) for k, v in _PAIR_RE.findall(labels)
                        if k != "le"))


def validate_exposition(text: str) -> List[str]:
    """Strict text-format (0.0.4) checker. Returns a list of problems
    (empty = valid). Enforced beyond the line grammar: body ends with a
    newline, TYPE precedes its samples and appears once, every
    gauge-typed metric carries HELP text (a gauge a scraper can't
    explain is a gauge nobody will trust during an incident), histogram
    series carry a +Inf bucket whose value equals their _count, and
    bucket counts are non-decreasing in le order."""
    problems: List[str] = []
    if not text:
        return ["empty exposition body"]
    if not text.endswith("\n"):
        problems.append("body must end with a newline")
    types: Dict[str, str] = {}
    seen_samples: set = set()
    helped: set = set()
    gauge_lines: Dict[str, int] = {}   # gauge-typed name -> TYPE line
    # histogram accounting: (base_name, labels-sans-le) -> state
    hist: Dict[tuple, dict] = {}
    for ln, line in enumerate(text.split("\n")[:-1], 1):
        if line == "":
            continue
        if line.startswith("#"):
            h = _HELP_RE.match(line)
            if h:
                if h.group(2).strip():
                    helped.add(h.group(1))
                continue
            m = _TYPE_RE.match(line)
            if not m:
                problems.append(f"line {ln}: malformed comment: {line!r}")
                continue
            name = m.group(1)
            if name in types:
                problems.append(f"line {ln}: duplicate TYPE for {name}")
            if name in seen_samples:
                problems.append(
                    f"line {ln}: TYPE for {name} after its samples")
            if m.group(2) == "gauge":
                gauge_lines[name] = ln
            types[name] = m.group(2)
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            problems.append(f"line {ln}: malformed sample: {line!r}")
            continue
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types \
                    and types[name[:-len(suffix)]] == "histogram":
                base = name[:-len(suffix)]
                break
        seen_samples.add(base)
        if base != name and types.get(base) == "histogram":
            key_labels = _label_key(labels)
            h = hist.setdefault((base, key_labels),
                                {"inf": None, "count": None, "last": None})
            if name.endswith("_bucket"):
                le = _LE_RE.search(labels)
                if le is None:
                    problems.append(
                        f"line {ln}: histogram bucket without le label")
                    continue
                if le.group(1) == "+Inf":
                    h["inf"] = float(value)
                else:
                    v = float(value)
                    if h["last"] is not None and v < h["last"]:
                        problems.append(
                            f"line {ln}: bucket counts decrease "
                            f"for {base}")
                    h["last"] = v
            elif name.endswith("_count"):
                h["count"] = float(value)
    # checked after the full pass: the format does not mandate
    # HELP-before-TYPE order, so a HELP arriving later still counts
    for name, ln in sorted(gauge_lines.items(), key=lambda kv: kv[1]):
        if name not in helped:
            problems.append(f"line {ln}: gauge {name} lacks HELP text")
    for (base, labels), h in hist.items():
        if h["inf"] is None:
            problems.append(f"histogram {base}{labels}: no +Inf bucket")
        elif h["count"] is not None and h["inf"] != h["count"]:
            problems.append(
                f"histogram {base}{labels}: +Inf bucket {h['inf']} "
                f"!= _count {h['count']}")
    return problems


class PrometheusExporter:
    """The :9526-style HTTP listener: GET /metrics + GET /healthz.

    /healthz is the fault-domain liveness contract: `health` is a
    zero-arg callable returning a dict with an "ok" bool (the ingester
    wires Ingester.health — stale supervised threads, open exporter
    breakers, a degraded tpu_sketch lane all fail it). ok -> 200, not
    ok -> 503, body either way is the full JSON verdict, so a k8s
    probe and a human curl read the same surface."""

    def __init__(self, stats: Optional[StatsRegistry] = None,
                 tracer: Optional[Tracer] = None,
                 port: int = DEFAULT_PROM_PORT,
                 host: str = "127.0.0.1",
                 health=None, timeline=None) -> None:
        self.stats = stats
        self.tracer = tracer if tracer is not None else default_tracer()
        self.health = health
        self.timeline = timeline
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:   # noqa: N802 (stdlib contract)
                path = self.path.split("?")[0]
                if path == "/healthz":
                    self._healthz()
                    return
                if path not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                try:
                    body = render_metrics(
                        exporter.stats, exporter.tracer,
                        timeline=exporter.timeline).encode()
                except Exception as e:   # a broken countable: 500, not die
                    self.send_error(500, str(e)[:200])
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _healthz(self) -> None:
                import json
                try:
                    verdict = {"ok": True} if exporter.health is None \
                        else dict(exporter.health())
                except Exception as e:
                    verdict = {"ok": False, "error": str(e)[:200]}
                body = json.dumps(verdict).encode()
                self.send_response(200 if verdict.get("ok") else 503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a) -> None:   # quiet: scrape cadence
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._thread = None            # supervisor ThreadHandle

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        if self._thread is not None:
            return
        # supervised for crash capture + restart; deadman disabled:
        # serve_forever blocks in select() with nowhere to beat from,
        # and a quiet scrape target is healthy, not wedged
        from deepflow_tpu.runtime.supervisor import default_supervisor
        self._thread = default_supervisor().spawn(
            "prom-exposition", self._server.serve_forever, deadman_s=None)

    def close(self) -> None:
        # shutdown() blocks on the serve_forever loop acking — calling
        # it with no loop running (start() never happened, or it
        # raised) would hang forever
        if self._thread is not None:
            self._thread.stop()
            self._server.shutdown()
            self._thread.join(timeout=2)
            self._thread = None
        self._server.server_close()

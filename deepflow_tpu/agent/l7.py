"""L7 protocol parsers: payload bytes -> request/response log records.

Reference: agent/src/flow_generator/protocol_logs/ — per-protocol
check_payload/parse_payload trait objects dispatched over an enum
(agent/src/common/l7_protocol_log.rs:162-219), feeding a session
aggregator that merges request+response by stream. The re-design keeps
the same two-phase contract (cheap check, then parse) as plain Python
classes in a registry; parsers run host-side on the payload slices the
batched packet decoder exposes, and their output is already the columnar
L7 record shape.

Protocol ids follow the reference's L7Protocol enum: HTTP1=20, DNS=120,
MySQL=60, Redis=80.
"""

from __future__ import annotations

import re
import struct
import threading
from dataclasses import dataclass
from typing import ClassVar, List, Optional

L7_HTTP1 = 20
L7_MYSQL = 60
L7_REDIS = 80
L7_DNS = 120

MSG_REQUEST = 0
MSG_RESPONSE = 1


@dataclass
class L7Record:
    proto: int
    msg_type: int           # MSG_REQUEST / MSG_RESPONSE
    endpoint: str = ""      # method+path / query name / statement verb
    status: int = 0         # protocol status code
    req_len: int = 0
    resp_len: int = 0
    # instrumented-app trace context (reference: http.rs decode_id) —
    # what links this packet/syscall span to OTel spans in one trace
    trace_id: str = ""
    span_id: str = ""
    # request detail (reference: HttpInfo host/user-agent/referer/
    # x-request-id/proxy-real-ip extraction, http.rs:990-1080)
    req_type: str = ""      # method
    domain: str = ""        # Host / :authority
    resource: str = ""      # full path incl. query
    version: str = ""       # "1.1" / "2"
    user_agent: str = ""
    referer: str = ""
    x_request_id: str = ""
    client_ip: str = ""     # X-Forwarded-For / X-Real-IP first hop


def parse_http_headers(payload: bytes,
                       max_headers: int = 64) -> dict:
    """Header block after the first CRLF -> {lowercase-name: value}.
    Duplicate names keep the first occurrence (proxy-chain semantics:
    the outermost hop's value). Bounded: header floods can't balloon."""
    headers: dict = {}
    head_end = payload.find(b"\r\n\r\n")
    block = payload[:head_end if head_end >= 0 else len(payload)]
    for line in block.split(b"\r\n")[1:max_headers + 1]:
        name, sep, value = line.partition(b":")
        if not sep:
            continue
        key = name.strip().decode("latin-1").lower()
        if key and key not in headers:
            headers[key] = value.strip().decode("latin-1")
    return headers


def http_body_len(payload: bytes, headers: dict) -> int:
    """Body bytes per the message's own framing (reference: http.rs
    content-length tracking): Content-Length when present; for
    Transfer-Encoding: chunked, the sum of the chunk sizes visible in
    this capture slice (each capped to what's actually present — a
    lying chunk header must not inflate the accounting); else the bytes
    past the header block."""
    head_end = payload.find(b"\r\n\r\n")
    body_off = head_end + 4 if head_end >= 0 else len(payload)
    cl = headers.get("content-length", "")
    if cl.isascii() and cl.isdigit():   # utils.text.parse_int's form
        return int(cl)
    if "chunked" in headers.get("transfer-encoding", "").lower():
        total = 0
        off = body_off
        while off < len(payload):
            line_end = payload.find(b"\r\n", off)
            if line_end < 0:
                break
            size_tok = payload[off:line_end].split(b";")[0].strip()
            # strict hex only: int(x, 16) also accepts signs and
            # underscores, and a hostile b"-2" chunk header would drive
            # the accumulated length negative (u32-wrapping downstream)
            if not size_tok or not all(c in b"0123456789abcdefABCDEF"
                                       for c in size_tok):
                break
            size = int(size_tok, 16)
            if size == 0:
                break
            avail = max(len(payload) - (line_end + 2), 0)
            total += min(size, avail)
            off = line_end + 2 + size + 2      # data + trailing CRLF
        return total
    return max(len(payload) - body_off, 0)


class HttpParser:
    """HTTP/1.x (reference: protocol_logs/http.rs): request line +
    full header extraction (host, content-type, user-agent, referer,
    x-request-id, proxy client ip), trace-context decode
    (trace_context.extract), and content-length/chunked body
    accounting."""

    proto: ClassVar[int] = L7_HTTP1
    _METHODS = (b"GET ", b"POST ", b"PUT ", b"DELETE ", b"HEAD ",
                b"OPTIONS ", b"PATCH ")

    def check(self, payload: bytes) -> bool:
        # "HTTP/2 " (ASCII status line): the http2-uprobe assembler's
        # synthesized blocks (agent/http2_trace.py) — real h2 framing
        # is binary and never hits this prefix
        return payload.startswith(self._METHODS) or \
            payload.startswith(b"HTTP/1.") or \
            payload.startswith(b"HTTP/2 ")

    def parse(self, payload: bytes) -> Optional[L7Record]:
        from deepflow_tpu.agent import trace_context

        try:
            line, _, _ = payload.partition(b"\r\n")
            parts = line.decode("latin-1").split(" ", 2)
        except Exception:
            return None
        headers = parse_http_headers(payload)
        ids = trace_context.extract(headers)
        if payload.startswith(b"HTTP/1.") or \
                payload.startswith(b"HTTP/2 "):
            # isascii() is load-bearing: str.isdigit() accepts Unicode
            # digits int() rejects (b'\xb3' -> '³'.isdigit() is True),
            # and a mutated status line must not raise out of parse()
            # (found by the registry fuzz)
            if len(parts) < 2 or not (parts[1][:3].isascii()
                                      and parts[1][:3].isdigit()):
                return None
            return L7Record(
                self.proto, MSG_RESPONSE,
                status=int(parts[1][:3]),
                resp_len=http_body_len(payload, headers),
                version=parts[0][5:],
                trace_id=ids["trace_id"], span_id=ids["span_id"],
                x_request_id=ids["x_request_id"])
        if len(parts) < 3 or not parts[2].startswith("HTTP/"):
            return None
        path = parts[1].split("?", 1)[0]
        return L7Record(
            self.proto, MSG_REQUEST,
            endpoint=f"{parts[0]} {path}",
            req_len=http_body_len(payload, headers),
            req_type=parts[0],
            domain=headers.get("host", ""),
            resource=parts[1],
            version=parts[2][5:].strip(),
            user_agent=headers.get("user-agent", ""),
            referer=headers.get("referer", ""),
            trace_id=ids["trace_id"], span_id=ids["span_id"],
            x_request_id=ids["x_request_id"],
            client_ip=ids["client_ip"])


class DnsParser:
    """DNS over UDP (reference: protocol_logs/dns.rs)."""

    proto: ClassVar[int] = L7_DNS

    def check(self, payload: bytes) -> bool:
        if len(payload) < 12:
            return False
        qd = struct.unpack_from(">H", payload, 4)[0]
        return 1 <= qd <= 4

    def parse(self, payload: bytes) -> Optional[L7Record]:
        if len(payload) < 12:
            return None
        flags = struct.unpack_from(">H", payload, 2)[0]
        is_resp = bool(flags & 0x8000)
        rcode = flags & 0x000F
        # parse the first question name
        labels = []
        off = 12
        try:
            while off < len(payload):
                ln = payload[off]
                if ln == 0 or ln >= 0xC0:
                    break
                labels.append(payload[off + 1:off + 1 + ln]
                              .decode("latin-1"))
                off += 1 + ln
        except IndexError:
            return None
        name = ".".join(labels)
        if is_resp:
            return L7Record(self.proto, MSG_RESPONSE, endpoint=name,
                            status=rcode, resp_len=len(payload))
        return L7Record(self.proto, MSG_REQUEST, endpoint=name,
                        req_len=len(payload))


class RedisParser:
    """RESP protocol (reference: protocol_logs/sql/redis.rs)."""

    proto: ClassVar[int] = L7_REDIS

    def check(self, payload: bytes) -> bool:
        return len(payload) > 2 and payload[:1] in b"*+-:$"

    def parse(self, payload: bytes) -> Optional[L7Record]:
        head = payload[:1]
        if head == b"*":
            # array of bulk strings: first element is the command
            m = re.match(rb"\*\d+\r\n\$\d+\r\n([A-Za-z]+)", payload)
            cmd = m.group(1).decode().upper() if m else ""
            return L7Record(self.proto, MSG_REQUEST, endpoint=cmd,
                            req_len=len(payload))
        if head == b"-":
            return L7Record(self.proto, MSG_RESPONSE, status=1,
                            resp_len=len(payload))
        if head in (b"+", b":", b"$"):
            return L7Record(self.proto, MSG_RESPONSE, status=0,
                            resp_len=len(payload))
        return None


class MysqlParser:
    """MySQL client/server packets (reference: protocol_logs/sql/mysql.rs).
    Command packets: 3-byte length + seq + command byte; COM_QUERY=3."""

    proto: ClassVar[int] = L7_MYSQL
    _VERBS = re.compile(rb"^\s*(SELECT|INSERT|UPDATE|DELETE|CREATE|DROP|"
                        rb"ALTER|BEGIN|COMMIT|SET|SHOW)", re.IGNORECASE)

    def check(self, payload: bytes) -> bool:
        if len(payload) < 5:
            return False
        ln = int.from_bytes(payload[:3], "little")
        return ln + 4 == len(payload) and payload[3] in (0, 1)

    def parse(self, payload: bytes) -> Optional[L7Record]:
        if len(payload) < 5:
            return None
        cmd = payload[4]
        if payload[3] == 0 and cmd == 3:        # COM_QUERY request
            m = self._VERBS.match(payload[5:])
            verb = m.group(1).decode().upper() if m else "QUERY"
            return L7Record(self.proto, MSG_REQUEST, endpoint=verb,
                            req_len=len(payload))
        if payload[3] == 1:                      # first response packet
            status = 1 if cmd == 0xFF else 0     # ERR header
            return L7Record(self.proto, MSG_RESPONSE, status=status,
                            resp_len=len(payload))
        return None


PARSERS: List = [HttpParser(), DnsParser(), MysqlParser(), RedisParser()]

# the extended set (TLS, HTTP/2+gRPC, Kafka, PostgreSQL, MongoDB, Dubbo,
# MQTT, AMQP, NATS, OpenWire, FastCGI, SofaRPC) registers behind the four
# core parsers; deferred import because l7_ext imports this module's types
def _register_extended() -> None:
    from deepflow_tpu.agent import l7_ext

    l7_ext.register_extended(PARSERS)


_register_extended()


def register_parser(parser, prepend: bool = False) -> None:
    """Plug in a custom protocol parser (the role of the reference's
    Wasm/so plugin hooks, agent/src/plugin/wasm/ — here a plain object
    with .proto, .check(payload) and .parse(payload)->L7Record, plus an
    optional .transports tuple of ip protocols it applies to).
    `prepend` lets a plugin shadow a built-in whose check() is greedy."""
    for attr in ("proto", "check", "parse"):
        if not hasattr(parser, attr):
            raise TypeError(f"parser lacks .{attr}")
    if prepend:
        PARSERS.insert(0, parser)
    else:
        PARSERS.append(parser)


def parse_payload(payload: bytes, proto: Optional[int] = None,
                  port_src: Optional[int] = None,
                  port_dst: Optional[int] = None,
                  ts_ns: int = 0,
                  ip_src: int = 0, ip_dst: int = 0,
                  ip_version: int = 4) -> Optional[L7Record]:
    """Two-phase dispatch: first parser whose cheap check passes wins
    (reference: check_payload ordering in l7_protocol_log.rs). Transport
    context, when provided, gates ambiguous parsers: DNS only on UDP or
    port 53 (byte patterns alone misfire on e.g. TLS records), and the
    byte-oriented TCP protocols never match UDP payloads.

    A parser with `wants_ctx = True` (the .so plugin adapter) receives
    the full dispatch context — the reference's parse_ctx carries
    ips/ports/time and plugins legitimately gate on them."""
    for p in PARSERS:
        if proto is not None:
            if p.proto == L7_DNS:
                if proto != 17 and 53 not in (port_src, port_dst):
                    continue
            elif proto not in getattr(p, "transports", (6,)):
                continue
        if getattr(p, "wants_ctx", False):
            ctx = (proto, port_src or 0, port_dst or 0, ts_ns,
                   ip_src, ip_dst, ip_version)
            if p.check(payload, *ctx):
                rec = p.parse(payload, *ctx)
                if rec is not None:
                    return rec
        elif p.check(payload):
            rec = p.parse(payload)
            if rec is not None:
                return rec
    return None


_DETAIL_FIELDS = ("trace_id", "span_id", "req_type", "domain",
                  "resource", "version", "user_agent", "referer",
                  "client_ip")


def _session_detail(req: Optional[L7Record],
                    resp: Optional[L7Record]) -> dict:
    """Merged string detail: the request's value wins (trace context
    and request headers live on the request); the response fills gaps
    (server-stamped trace ids). x_request_id keeps both directions —
    the reference's x_request_id_0/_1 pair is how proxy-injected ids
    correlate across hops."""
    out = {f: getattr(req, f, "") or getattr(resp, f, "")
           for f in _DETAIL_FIELDS}
    out["x_request_id_0"] = getattr(req, "x_request_id", "")
    out["x_request_id_1"] = getattr(resp, "x_request_id", "")
    return out


class SessionAggregator:
    """Merge request+response halves per (flow, stream) within a time
    window (reference: protocol_logs/parser.rs SessionAggregator :737).
    Emits merged L7Records with round-trip time filled in."""

    def __init__(self, window_ns: int = 60 * 1_000_000_000) -> None:
        self.window_ns = window_ns
        self._pending: dict = {}
        # offer() runs on the capture thread, expire() on the tick loop
        self._lock = threading.Lock()
        self.merged = 0
        self.unpaired = 0

    def offer(self, flow_key: tuple, rec: L7Record,
              ts_ns: int) -> Optional[dict]:
        """Returns a merged session dict when a pair completes. Pipelined
        requests on one connection queue FIFO, so response k pairs with
        request k (HTTP/1.1 pipelining order)."""
        key = (flow_key, rec.proto)
        if rec.msg_type == MSG_REQUEST:
            with self._lock:
                self._pending.setdefault(key, []).append((rec, ts_ns))
            return None
        with self._lock:
            queue = self._pending.get(key)
            req = queue.pop(0) if queue else None
            if queue is not None and not queue:
                del self._pending[key]
        if req is None:
            self.unpaired += 1
            return {"proto": rec.proto, "endpoint": rec.endpoint,
                    "status": rec.status, "rrt_us": 0,
                    "req_len": 0, "resp_len": rec.resp_len,
                    **_session_detail(None, rec)}
        req_rec, req_ts = req
        self.merged += 1
        return {
            "proto": rec.proto,
            "endpoint": req_rec.endpoint or rec.endpoint,
            "status": rec.status,
            "rrt_us": max(ts_ns - req_ts, 0) // 1000,
            "req_len": req_rec.req_len,
            "resp_len": rec.resp_len,
            **_session_detail(req_rec, rec),
        }

    def expire(self, now_ns: int) -> int:
        """Drop requests that never saw a response within the window."""
        dropped = 0
        with self._lock:
            for k in list(self._pending):
                queue = self._pending[k]
                keep = [(r, ts) for r, ts in queue
                        if now_ns - ts <= self.window_ns]
                dropped += len(queue) - len(keep)
                if keep:
                    self._pending[k] = keep
                else:
                    del self._pending[k]
        self.unpaired += dropped
        return dropped

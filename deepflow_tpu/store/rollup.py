"""Rollup manager: coarser-interval tables materialized on device.

Reference: server/ingester/datasource/handle.go builds ClickHouse
materialized views that collapse 1s tables into 1m/1h rows with Sum/Max/Min
aggregate functions. The TPU-native re-design runs the same collapse as a
JAX program: rows are bucketed by (key columns, floor(time/interval)) with
exact group ids computed on the host (np.unique over packed keys — cheap,
and collision-free unlike a folded hash), then every metric column is
segment-reduced in one jitted XLA program at padded static shapes. At
hot-table batch sizes on a real accelerator, group_reduce auto-switches
to the all-device path (_device_group_reduce: one sort + arithmetic
boundary detect + cumsum ids + segment reductions in one program) so no
host lexsort sits in front of the reduction.
"""

from __future__ import annotations

import functools
import os
import shutil
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

try:   # jax >= 0.4.38 re-exports it; older versions keep it experimental
    _enable_x64 = jax.enable_x64
except AttributeError:
    from jax.experimental import enable_x64 as _enable_x64

from deepflow_tpu.store.db import Store, Table
from deepflow_tpu.store.table import AggKind, TableSchema

_I64_MIN = np.int64(np.iinfo(np.int64).min)
_I64_MAX = np.int64(np.iinfo(np.int64).max)


# rollup_schema ttl sentinel (identity object: no integer the debug
# socket could pass collides with it): derive 30x the base retention
TTL_DERIVE = object()

# -- external datasources (ISSUE 7) ----------------------------------------
# Virtual datasources that live beside the rollup tiers in the
# `datasource list` surface but are not derived tables — today the
# serving sketch tables (serving/tables.py), which register a provider
# callable returning their listing rows. Process-scoped like the
# default tracer; providers must be cheap (called per debug command).
_EXTERNAL_DATASOURCES: Dict[str, "Callable[[], List[dict]]"] = {}
_EXTERNAL_LOCK = threading.Lock()


def register_datasource(name: str, provider) -> None:
    """Register a virtual datasource provider (rows for list)."""
    with _EXTERNAL_LOCK:
        _EXTERNAL_DATASOURCES[name] = provider


def unregister_datasource(name: str) -> None:
    with _EXTERNAL_LOCK:
        _EXTERNAL_DATASOURCES.pop(name, None)


def external_datasources() -> List[dict]:
    """Rows from every registered virtual datasource; a broken provider
    contributes an error row instead of killing the listing."""
    with _EXTERNAL_LOCK:
        providers = dict(_EXTERNAL_DATASOURCES)
    rows: List[dict] = []
    for name, provider in sorted(providers.items()):
        try:
            rows.extend(provider())
        except Exception as e:   # the debug socket must still answer
            rows.append({"table": name, "kind": "external",
                         "error": str(e)[:200]})
    return rows

# one shared table for both naming directions; inverse derived
_NAMED_SUFFIXES = {60: "1m", 3600: "1h", 86400: "1d"}
_SUFFIX_INTERVALS = {v: k for k, v in _NAMED_SUFFIXES.items()}


def _interval_suffix(interval: int) -> str:
    return _NAMED_SUFFIXES.get(interval, f"{interval}s")


def interval_from_table_name(base_name: str, table_name: str
                             ) -> Optional[int]:
    """Inverse of rollup_schema's naming: `vtap_flow_port.1h` -> 3600
    for base `vtap_flow_port`; None if not a rollup of this base."""
    if not table_name.startswith(base_name + "."):
        return None
    suffix = table_name[len(base_name) + 1:]
    named = _SUFFIX_INTERVALS.get(suffix)
    if named is not None:
        return named
    if suffix.endswith("s") and suffix[:-1].isdigit():
        return int(suffix[:-1])
    return None


def rollup_schema(base: TableSchema, interval: int,
                  ttl_seconds=TTL_DERIVE) -> TableSchema:
    """Derive the coarser table's schema (name suffixed `.1m`-style).
    ttl_seconds: TTL_DERIVE = 30x base retention, None = keep forever,
    >=0 = explicit seconds."""
    if ttl_seconds is TTL_DERIVE:
        ttl_seconds = None if base.ttl_seconds is None \
            else base.ttl_seconds * 30
    return TableSchema(
        name=f"{base.name}.{_interval_suffix(interval)}",
        columns=base.columns,
        time_column=base.time_column,
        partition_seconds=max(base.partition_seconds, interval * 60),
        ttl_seconds=ttl_seconds,
        version=base.version,
    )


def _next_pow2(n: int) -> int:
    return 1 << max(10, (n - 1).bit_length())


@functools.partial(jax.jit, static_argnames=("aggs", "num_segments"))
def _segment_reduce(seg: jnp.ndarray, mask: jnp.ndarray, data: jnp.ndarray,
                    aggs: Tuple[str, ...], num_segments: int) -> jnp.ndarray:
    """Reduce [rows, n_cols] int64 into [num_segments, n_cols] by agg kind.
    Padding rows (mask False) map to the trash segment num_segments-1 and
    carry neutral values, so output shape stays static across calls."""
    seg = jnp.where(mask, seg, num_segments - 1)
    outs = []
    for i, agg in enumerate(aggs):
        col = data[:, i]
        if agg == "sum" or agg == "count":
            v = jnp.where(mask, col if agg == "sum" else jnp.ones_like(col), 0)
            r = jax.ops.segment_sum(v, seg, num_segments=num_segments)
        elif agg == "min":
            v = jnp.where(mask, col, _I64_MAX)
            r = jax.ops.segment_min(v, seg, num_segments=num_segments)
        else:  # "max", "last", "key": max is a valid representative
            v = jnp.where(mask, col, _I64_MIN)
            r = jax.ops.segment_max(v, seg, num_segments=num_segments)
        outs.append(r)
    return jnp.stack(outs, axis=1)


def _unique_rows(packed: np.ndarray):
    """np.unique(axis=0) built from per-column argsorts: numpy's axis=0
    unique argsorts a void view (memcmp per compare), which profiles 5-10x
    slower than k stable i64 sorts at flow-map batch sizes. Returns
    (unique_rows, inverse) with rows in lexicographic order, matching
    np.unique's contract."""
    n, k = packed.shape
    if k == 1:
        u, inv = np.unique(packed[:, 0], return_inverse=True)
        return u[:, None], inv
    order = np.lexsort(tuple(packed[:, j] for j in reversed(range(k))))
    skeys = packed[order]
    boundary = np.empty(n, np.bool_)
    boundary[0] = True
    np.any(skeys[1:] != skeys[:-1], axis=1, out=boundary[1:])
    group_of_sorted = np.cumsum(boundary) - 1
    inverse = np.empty(n, np.int64)
    inverse[order] = group_of_sorted
    return skeys[boundary], inverse


@functools.partial(jax.jit, static_argnames=("aggs", "num_segments"))
def _device_group_reduce(keys: Tuple[jnp.ndarray, ...],
                         data: jnp.ndarray, mask: jnp.ndarray,
                         aggs: Tuple[str, ...], num_segments: int):
    """GROUP BY entirely on device: one sort + arithmetic boundary
    detection + cumsum group ids + segment reductions, one program.

    keys: n_keys u32 arrays [n]; data [n, m] i64; mask [n]. Invalid rows
    sort to the end (leading 1-bit key), contribute no boundary, and
    reduce into the trash segment. Returns (keys_out [n_keys, S],
    vals [S, m], n_groups scalar) with groups in lexicographic key
    order in slots [0, n_groups). Boundary predicates are pure
    arithmetic on the sorted lanes — no compare ops on moved data (the
    tunnel-safe discipline of ops/topk.py)."""
    n_keys = len(keys)
    invalid = jnp.logical_not(mask).astype(jnp.uint32)
    ops = ((invalid,) + tuple(keys)
           + tuple(data[:, i] for i in range(data.shape[1])))
    sorted_ops = jax.lax.sort(ops, num_keys=1 + n_keys)
    svalid = jnp.uint32(1) - sorted_ops[0]
    skeys = sorted_ops[1:1 + n_keys]
    sdata = sorted_ops[1 + n_keys:]

    def _nz(x):   # u32 1 where x != 0, arithmetic only
        return (x | (jnp.uint32(0) - x)) >> jnp.uint32(31)

    diff = jnp.zeros_like(skeys[0][1:])
    for k in skeys:
        diff = diff | _nz(k[1:] - k[:-1])
    boundary = jnp.concatenate(
        [jnp.ones((1,), jnp.uint32), diff]) * svalid
    # gid <= valid_rows - 1 < num_segments - 1 == the trash segment, so
    # a fully-distinct full batch cannot collide with trash
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg = jnp.where(svalid.astype(bool), gid, num_segments - 1)
    n_groups = jnp.sum(boundary.astype(jnp.int32))

    m = svalid.astype(bool)
    # reuse the shared per-agg dispatch (it inlines into this program);
    # seg already routes invalid rows to trash, and _segment_reduce's own
    # mask handling re-applies the identical mapping
    vals = _segment_reduce(seg, m, jnp.stack(sdata, axis=1), aggs,
                           num_segments)
    # group keys: constant within a group, so segment_max recovers them
    keys_out = [jax.ops.segment_max(
        jnp.where(m, k, jnp.uint32(0)).astype(jnp.int64),
        seg, num_segments=num_segments).astype(jnp.uint32) for k in skeys]
    return (jnp.stack(keys_out), vals, n_groups)


def group_reduce_device(cols: Dict[str, np.ndarray], key_names: List[str],
                        aggs: Dict[str, str]) -> Dict[str, np.ndarray]:
    """`group_reduce` with the group-id stage on device too (the full
    "GROUP BY runs on TPU" path). Key columns must fit uint32 (every
    schema key column does; the rollup time bucket is epoch seconds).
    Exactly equal to the host path, including group order (signed keys
    ride the lanes sign-bit-flipped so they sort like int64) — asserted
    in tests. Costs one scalar fetch (n_groups), so on the tunneled dev
    runtime prefer the host path for latency-sensitive callers
    (bench.py docstring)."""
    for nm in key_names:
        dt = np.asarray(cols[nm]).dtype
        if dt.kind not in "uib" or dt.itemsize > 4:
            raise ValueError(
                f"device GROUP BY key {nm!r} is {dt} — keys must be "
                "<=32-bit integers to ride the u32 sort lanes (floats "
                "would truncate-merge, 64-bit ints would collide); use "
                "the host path")
    n = len(next(iter(cols.values())))
    if n == 0:
        return {nm: cols[nm][:0] for nm in list(key_names) + list(aggs)}
    rows_pad = _next_pow2(n)
    value_names = list(aggs.keys())

    def pad_u32(a):
        out = np.zeros(rows_pad, np.uint32)
        a = np.asarray(a)
        if a.dtype.kind == "i":
            # sign-bit flip: order-preserving signed -> u32 mapping, so
            # groups come back in the SAME lexicographic order as the
            # host path even with negative keys (e.g. l3_epc_id = -1)
            out[:n] = a.astype(np.int64).astype(np.uint32) ^ np.uint32(
                0x80000000)
        else:
            out[:n] = a.astype(np.uint32)
        return jnp.asarray(out)

    with _enable_x64(True):
        keys = tuple(pad_u32(np.asarray(cols[nm])) for nm in key_names)
        data = np.zeros((rows_pad, len(value_names)), np.int64)
        for i, nm in enumerate(value_names):
            data[:n, i] = np.asarray(cols[nm]).astype(np.int64)
        mask = np.zeros(rows_pad, np.bool_)
        mask[:n] = True
        keys_out, vals, n_groups = _device_group_reduce(
            keys, jnp.asarray(data), jnp.asarray(mask),
            tuple(aggs[nm] for nm in value_names), rows_pad + 1)
        g = int(n_groups)
        # materializing the reduced groups IS this function's contract:
        # the rollup/compaction lane hands host arrays to the store
        # layer, and it runs off the feed hot path (tier scheduler)
        keys_np = np.asarray(keys_out)[:, :g]  # lint: disable=host-sync-in-device-path
        vals_np = np.asarray(vals)[:g]  # lint: disable=host-sync-in-device-path
    out: Dict[str, np.ndarray] = {}
    for j, nm in enumerate(key_names):
        k = keys_np[j]
        if np.asarray(cols[nm]).dtype.kind == "i":
            k = k ^ np.uint32(0x80000000)   # undo the sign-bit flip
        out[nm] = k.astype(cols[nm].dtype)
    for i, nm in enumerate(value_names):
        out[nm] = vals_np[:, i]
    return out


def group_reduce(cols: Dict[str, np.ndarray], key_names: List[str],
                 aggs: Dict[str, str],
                 return_inverse: bool = False, method: str = "auto"):
    """Exact GROUP BY: group ids + segment reduction.

    `aggs` maps value column -> sum|max|min|count. Key columns come back
    deduplicated; value columns reduced. Shared by rollups, the querier,
    and the agent flow map. With return_inverse, also returns the [n]
    row->group index (callers needing extra reductions, e.g. bitwise OR,
    reuse it instead of re-grouping).

    method: "host" computes group ids with a host lexsort and reduces on
    device; "device" runs the whole thing in one device program
    (group_reduce_device); "auto" picks device on a real accelerator at
    batch sizes where the host lexsort would dominate (the
    query-over-hot-table regime). return_inverse always takes the host
    path — the device path never materializes the row->group map.
    """
    n = len(next(iter(cols.values())))
    if method == "device" and return_inverse:
        raise ValueError("the device GROUP BY never materializes the "
                         "row->group map; use method='host' with "
                         "return_inverse")
    if not aggs:
        method = "host"   # pure dedup: the host path short-circuits it
    # device keys ride u32 lanes: a 64-bit key (mac_src, flow_id) would
    # collide and a float key would truncate-merge — those group on host
    keys_fit_u32 = all(np.asarray(cols[k]).dtype.kind in "uib"
                       and np.asarray(cols[k]).dtype.itemsize <= 4
                       for k in key_names)
    # 'auto' never picks the device path on the tunneled axon backend
    # unless explicitly opted in: group_reduce_device ends in a scalar
    # D2H fetch (int(n_groups)), and on that backend ANY fetch degrades
    # h2d ~20x for ~15s (verify skill, pathology section) — a hot-table
    # query would silently throttle ingest sharing the process.
    backend = jax.default_backend()
    auto_device_ok = backend != "cpu" and (
        backend != "axon"
        or os.environ.get("DEEPFLOW_DEVICE_GROUPBY", "") == "1")
    if method == "device" or (
            method == "auto" and not return_inverse and n >= (1 << 18)
            and keys_fit_u32 and auto_device_ok):
        return group_reduce_device(cols, key_names, aggs)
    if n == 0:
        empty = {nm: cols[nm][:0] for nm in list(key_names) + list(aggs)}
        return (empty, np.empty(0, np.int64)) if return_inverse else empty
    packed = np.stack([np.ascontiguousarray(cols[nm]).astype(np.int64)
                       for nm in key_names], axis=1)
    uniq, inverse = _unique_rows(packed)
    n_groups = uniq.shape[0]
    value_names = list(aggs.keys())
    if not value_names:   # pure dedup: SELECT k FROM t GROUP BY k
        out = {nm: uniq[:, j].astype(cols[nm].dtype)
               for j, nm in enumerate(key_names)}
        return (out, inverse) if return_inverse else out
    data = np.stack([np.asarray(cols[nm]).astype(np.int64)
                     for nm in value_names], axis=1)

    rows_pad = _next_pow2(n)
    seg = np.zeros(rows_pad, np.int32)
    seg[:n] = inverse
    mask = np.zeros(rows_pad, np.bool_)
    mask[:n] = True
    data_pad = np.zeros((rows_pad, len(value_names)), np.int64)
    data_pad[:n] = data
    seg_pad = _next_pow2(n_groups + 1)

    # Window sums of uint32 counters need 64-bit accumulators (ClickHouse
    # sums into UInt64); scope x64 to this program so the rest of the
    # framework keeps the TPU-friendly 32-bit default.
    with _enable_x64(True):
        reduced = np.asarray(_segment_reduce(
            jnp.asarray(seg), jnp.asarray(mask), jnp.asarray(data_pad),
            tuple(aggs[nm] for nm in value_names), seg_pad))[:n_groups]

    out: Dict[str, np.ndarray] = {}
    for j, nm in enumerate(key_names):
        out[nm] = uniq[:, j].astype(cols[nm].dtype)
    for i, nm in enumerate(value_names):
        out[nm] = reduced[:, i]
    return (out, inverse) if return_inverse else out


class RollupManager:
    """Maintains derived tables `<base>.<1m|1h|...>`; advance() builds only
    buckets strictly older than now-allowance, once — late data within the
    allowance still lands (the reference leans on CH background merges for
    this; we lean on build-once-behind-watermark)."""

    def __init__(self, store: Store, db: str, base: TableSchema,
                 intervals: Tuple[int, ...] = (60,),
                 allowance_seconds: int = 10) -> None:
        self.store = store
        self.db = db
        self.base = store.create_table(db, base)
        self.allowance = allowance_seconds
        self.targets: List[Tuple[int, Table]] = []
        # configured tiers UNION tiers found on disk: a runtime
        # `datasource add` persists as its table (the manifest IS the
        # registration, like everything else in this store), so a
        # restarted ingester keeps building tiers an operator added
        # (reference: datasource defs live in the controller DB)
        want = set(intervals)
        for tdb, tname in store.tables():
            if tdb != db:
                continue
            iv = interval_from_table_name(base.name, tname)
            if iv is not None:
                want.add(iv)
        for iv in sorted(want):
            # a tier removed with keep-data left a DETACHED marker: its
            # rows stay queryable but it must not resume building — and
            # the operator's detach outranks the static config list too
            # (only a datasource add clears the marker)
            name = f"{base.name}.{_interval_suffix(iv)}"
            try:
                root = store.table(db, name).root
                if os.path.exists(os.path.join(root, "DETACHED")):
                    continue
            except KeyError:
                pass   # table doesn't exist yet: nothing to detach
            self.targets.append(
                (iv, store.create_table(db, rollup_schema(base, iv))))
        # per-interval high-water mark: everything < mark already built.
        # Recovered from the target table on restart (segments are
        # append-only, so re-building an already-built bucket would
        # double-count) by reading the newest built bucket's timestamp.
        self._built_until: Dict[int, int] = {
            iv: self._recover_watermark(iv, t) for iv, t in self.targets}
        # guards targets/_built_until against runtime datasource CRUD
        # (debug-socket thread) racing advance() (pipeline thread).
        # Builds run OUTSIDE the lock (a backfill can scan days of base
        # data — holding the lock would time out the debug socket);
        # _building marks in-flight tiers, _drop_pending records a del
        # that arrived mid-build so its table is re-dropped afterwards.
        self._lock = threading.Lock()
        self._building: set = set()
        self._drop_pending: Dict[int, str] = {}   # interval -> table root

    # -- runtime datasource CRUD (reference: datasource/handle.go Handle
    # add/mod/del driven by deepflow-ctl; CH materialized views there,
    # derived tables + watermarks here) -----------------------------------
    def list_datasources(self) -> List[dict]:
        with self._lock:
            rows = [{"interval": iv, "table": t.schema.name,
                     "ttl_seconds": t.schema.ttl_seconds,
                     "built_until": self._built_until[iv]}
                    for iv, t in self.targets]
        # virtual datasources (ISSUE 7 sketch tables) ride the same
        # listing — the operator sees every queryable surface in one
        # `datasource list`
        return rows + external_datasources()

    def add_interval(self, interval: int,
                     ttl_seconds: Optional[int] = TTL_DERIVE) -> dict:
        """Create a new rollup tier at runtime. Unlike the reference's
        materialized views (which only see new inserts), the next
        advance() backfills every complete bucket still in the base
        table's retention. ttl_seconds: TTL_DERIVE = 30x base retention,
        None/0 = keep forever, >0 = explicit seconds."""
        if interval <= 0 or interval % 60:
            # the reference constrains custom tiers to whole minutes
            # (handle.go: 1m/1h composition); sub-minute tiers belong to
            # the base table
            raise ValueError("interval must be a positive multiple of 60")
        if ttl_seconds is not TTL_DERIVE and ttl_seconds is not None:
            if int(ttl_seconds) < 0:
                raise ValueError("ttl_seconds must be >= 0")
            if int(ttl_seconds) == 0:
                ttl_seconds = None                   # keep forever
        with self._lock:
            if any(iv == interval for iv, _ in self.targets):
                raise ValueError(f"datasource {interval}s already exists")
            if interval in self._building or interval in self._drop_pending:
                # a del'd tier's backfill is still draining: attaching a
                # fresh table now would let the old build overwrite the
                # new tier's watermark when it lands
                raise ValueError(
                    f"datasource {interval}s busy (build draining); retry")
            t = self.store.create_table(
                self.db, rollup_schema(self.base.schema, interval,
                                       ttl_seconds))
            marker = os.path.join(t.root, "DETACHED")
            if os.path.exists(marker):   # re-attach of a kept-data tier
                os.remove(marker)
            if ttl_seconds is not TTL_DERIVE and \
                    t.schema.ttl_seconds != ttl_seconds:
                # create_table returned an EXISTING table — the
                # requested retention must still win
                t.set_ttl(ttl_seconds)
            self.targets.append((interval, t))
            self.targets.sort()
            self._built_until[interval] = self._recover_watermark(interval, t)
            return {"interval": interval, "table": t.schema.name,
                    "ttl_seconds": t.schema.ttl_seconds}

    def remove_interval(self, interval: int, drop_data: bool = True) -> bool:
        with self._lock:
            for i, (iv, t) in enumerate(self.targets):
                if iv == interval:
                    del self.targets[i]
                    del self._built_until[iv]
                    if drop_data:
                        self.store.drop_table(self.db, t.schema.name)
                        if iv in self._building:
                            # an in-flight build may recreate the table
                            # dir with its append; advance() re-drops it
                            # when the build drains
                            self._drop_pending[iv] = t.root
                    else:
                        # kept data must not resurrect the tier on
                        # restart: mark it detached on disk
                        try:
                            with open(os.path.join(t.root, "DETACHED"),
                                      "w"):
                                pass
                        except OSError:
                            pass
                    return True
        return False

    def set_retention(self, interval: int, ttl_seconds: Optional[int]) -> bool:
        if ttl_seconds is not None and int(ttl_seconds) < 0:
            raise ValueError("ttl_seconds must be >= 0")
        with self._lock:
            for iv, t in self.targets:
                if iv == interval:
                    t.set_ttl(ttl_seconds)
                    return True
        return False

    @staticmethod
    def _recover_watermark(interval: int, target: Table) -> int:
        parts = target.partitions()
        if not parts:
            return 0
        tcol = target.schema.time_column
        psec = target.schema.partition_seconds
        last = target.scan(columns=[tcol],
                           time_range=(parts[-1], parts[-1] + psec))[tcol]
        if len(last) == 0:
            return 0
        return int(last.max()) + interval

    def advance(self, now: float) -> Dict[int, int]:
        """Build all complete buckets older than now-allowance.
        Returns {interval: rows_emitted}."""
        emitted: Dict[int, int] = {}
        with self._lock:
            targets = list(self.targets)
        for iv, target in targets:
            # bookkeeping under the lock, the build itself outside it
            # (a backfill can scan days of base data; the debug socket's
            # datasource commands must stay responsive meanwhile). The
            # _building marker keeps a concurrent del honest: its table
            # drop is re-applied after the build drains.
            with self._lock:
                if iv not in self._built_until or iv in self._building:
                    continue   # removed by datasource del / double run
                safe = int(now - self.allowance) // iv * iv
                lo = self._built_until[iv]
                if lo == 0:
                    parts = self.base.partitions()
                    if not parts:
                        emitted[iv] = 0
                        continue
                    lo = parts[0] // iv * iv
                if safe <= lo:
                    emitted[iv] = 0
                    continue
                self._building.add(iv)
            rows = None
            try:
                rows = self._build_range(iv, target, lo, safe)
            finally:
                with self._lock:
                    self._building.discard(iv)
                    if iv in self._built_until:
                        if rows is not None:   # failed build: retry later
                            self._built_until[iv] = safe
                            emitted[iv] = rows
                    else:
                        pend = self._drop_pending.pop(iv, None)
                        if pend is not None:
                            shutil.rmtree(pend, ignore_errors=True)
        return emitted

    def _build_range(self, interval: int, target: Table,
                     lo: int, hi: int) -> int:
        schema = self.base.schema
        cols = self.base.scan(time_range=(lo, hi))
        tcol = schema.time_column
        n = len(cols[tcol])
        if n == 0:
            return 0
        # keep the bucket in the schema's (u32) dtype: an int64 bucket
        # would disqualify every rollup from the device GROUP BY path
        bucket = cols[tcol] // np.uint32(interval) * np.uint32(interval)
        work = dict(cols)
        work[tcol] = bucket.astype(cols[tcol].dtype)
        key_names = [c.name for c in schema.columns if c.agg is AggKind.KEY]
        if tcol not in key_names:
            key_names.append(tcol)
        aggs = {c.name: c.agg.value for c in schema.columns
                if c.name not in key_names}
        reduced = group_reduce(work, key_names, aggs)
        out = {}
        for c in schema.columns:
            v = reduced[c.name]
            if np.dtype(c.dtype).kind == "u":
                v = np.clip(v, 0, np.iinfo(c.dtype).max)
            out[c.name] = v.astype(c.dtype)
        target.append(out)
        return len(out[tcol])

"""Agent: the capture-side pipeline, re-designed batch-columnar.

Reference: agent/ (Rust) — dispatcher pulls packets, FlowMap turns them
into TaggedFlows with TCP perf stats, protocol parsers extract L7
request logs, the quadruple generator folds flows into 1s metric
Documents, and UniformSender ships everything to the ingester
(SURVEY.md §2.1, §3.2). The re-design replaces the per-packet hash-table
hot loop with batch columnar processing: packets decode into
structure-of-arrays, flows aggregate by segment reduction (the same
device-friendly GROUP BY the server uses), and cross-batch flow state
lives in mergeable per-flow accumulators.
"""

from deepflow_tpu.agent.packet import decode_packets
from deepflow_tpu.agent.flow_map import FlowMap
from deepflow_tpu.agent.trident import Agent, AgentConfig

__all__ = ["decode_packets", "FlowMap", "Agent", "AgentConfig"]

"""AWS cloud client: SigV4 against the official AWS test vector, and a
fixture recorder that VERIFIES signatures, serves real-shaped EC2 XML
with nextToken pagination, and fans out per region (reference:
server/controller/cloud/aws/)."""

import datetime
import threading
import urllib.error
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from deepflow_tpu.controller.cloud_aws import (AwsPlatform,
                                               sigv4_headers,
                                               sigv4_signature)

ACCESS, SECRET = "AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY"


def test_sigv4_official_aws_test_vector():
    """The 'get-vanilla' case from AWS's published SigV4 test suite:
    known keys + fixed date must reproduce AWS's expected signature
    exactly — the signing math is checked against the vendor, not
    against itself."""
    now = datetime.datetime(2015, 8, 30, 12, 36, 0,
                            tzinfo=datetime.timezone.utc)
    h = sigv4_headers("GET", "https://example.amazonaws.com/", b"",
                      ACCESS, SECRET, "us-east-1", service="service",
                      now=now)
    assert h["x-amz-date"] == "20150830T123600Z"
    assert h["Authorization"] == (
        "AWS4-HMAC-SHA256 "
        "Credential=AKIDEXAMPLE/20150830/us-east-1/service/aws4_request, "
        "SignedHeaders=host;x-amz-date, "
        "Signature=5fa00fa31553b73ebf1942676e86291e8372ff2a2260"
        "956d9b8aae1d763fbf31")


_ENI_XML = """<DescribeNetworkInterfacesResponse xmlns="http://ec2.amazonaws.com/doc/2016-11-15/">
  <networkInterfaceSet>
    <item><networkInterfaceId>eni-{r}-1</networkInterfaceId>
      <subnetId>subnet-{r}1</subnetId><macAddress>02:aa:bb:cc:dd:01</macAddress>
      <attachment><instanceId>i-{r}a</instanceId></attachment>
      <privateIpAddressesSet>
        <item><privateIpAddress>10.1.1.10</privateIpAddress></item>
        <item><privateIpAddress>10.1.1.21</privateIpAddress>
          <association><publicIp>52.9.{o}.9</publicIp></association>
        </item>
      </privateIpAddressesSet>
      <association><publicIp>52.0.{o}.7</publicIp></association>
    </item>
    <item><networkInterfaceId>eni-{r}-floating</networkInterfaceId>
      <subnetId>subnet-{r}1</subnetId><macAddress>02:aa:bb:cc:dd:02</macAddress>
    </item>
  </networkInterfaceSet>
</DescribeNetworkInterfacesResponse>"""

_NAT_XML = """<DescribeNatGatewaysResponse xmlns="http://ec2.amazonaws.com/doc/2016-11-15/">
  <natGatewaySet>
    <item><natGatewayId>nat-{r}</natGatewayId><vpcId>vpc-{r}1</vpcId>
      <state>available</state>
      <natGatewayAddressSet>
        <item><publicIp>3.3.{o}.3</publicIp></item>
      </natGatewayAddressSet>
      <tagSet><item><key>Name</key><value>gw-{r}</value></item></tagSet>
    </item>
    <item><natGatewayId>nat-{r}-dead</natGatewayId><vpcId>vpc-{r}1</vpcId>
      <state>deleted</state>
      <natGatewayAddressSet>
        <item><publicIp>9.9.{o}.9</publicIp></item>
      </natGatewayAddressSet>
    </item>
  </natGatewaySet>
</DescribeNatGatewaysResponse>"""


# -- fixture recorder ------------------------------------------------------
_REGIONS_XML = """<DescribeRegionsResponse xmlns="http://ec2.amazonaws.com/doc/2016-11-15/">
  <regionInfo>
    <item><regionName>us-east-1</regionName></item>
    <item><regionName>eu-west-1</regionName></item>
    <item><regionName>ap-south-1</regionName></item>
  </regionInfo>
</DescribeRegionsResponse>"""

_AZS_XML = """<DescribeAvailabilityZonesResponse xmlns="http://ec2.amazonaws.com/doc/2016-11-15/">
  <availabilityZoneInfo>
    <item><zoneName>{r}a</zoneName><regionName>{r}</regionName></item>
    <item><zoneName>{r}b</zoneName><regionName>{r}</regionName></item>
  </availabilityZoneInfo>
</DescribeAvailabilityZonesResponse>"""

_VPCS_XML = """<DescribeVpcsResponse xmlns="http://ec2.amazonaws.com/doc/2016-11-15/">
  <vpcSet>
    <item><vpcId>vpc-{r}1</vpcId><cidrBlock>10.1.0.0/16</cidrBlock>
      <tagSet><item><key>Name</key><value>prod-{r}</value></item></tagSet>
    </item>
  </vpcSet>
</DescribeVpcsResponse>"""

_SUBNETS_XML = """<DescribeSubnetsResponse xmlns="http://ec2.amazonaws.com/doc/2016-11-15/">
  <subnetSet>
    <item><subnetId>subnet-{r}1</subnetId><vpcId>vpc-{r}1</vpcId>
      <cidrBlock>10.1.1.0/24</cidrBlock>
      <availabilityZone>{r}a</availabilityZone></item>
  </subnetSet>
</DescribeSubnetsResponse>"""

_INSTANCES_PAGE1 = """<DescribeInstancesResponse xmlns="http://ec2.amazonaws.com/doc/2016-11-15/">
  <reservationSet>
    <item><instancesSet>
      <item><instanceId>i-{r}a</instanceId>
        <privateIpAddress>10.1.1.10</privateIpAddress>
        <vpcId>vpc-{r}1</vpcId><subnetId>subnet-{r}1</subnetId>
        <placement><availabilityZone>{r}a</availabilityZone></placement>
        <tagSet><item><key>Name</key><value>web-{r}</value></item></tagSet>
      </item>
    </instancesSet></item>
  </reservationSet>
  <nextToken>PAGE2TOKEN</nextToken>
</DescribeInstancesResponse>"""

_INSTANCES_PAGE2 = """<DescribeInstancesResponse xmlns="http://ec2.amazonaws.com/doc/2016-11-15/">
  <reservationSet>
    <item><instancesSet>
      <item><instanceId>i-{r}b</instanceId>
        <privateIpAddress>10.1.1.11</privateIpAddress>
        <vpcId>vpc-{r}1</vpcId><subnetId>subnet-{r}1</subnetId>
        <placement><availabilityZone>{r}b</availabilityZone></placement>
      </item>
    </instancesSet></item>
  </reservationSet>
</DescribeInstancesResponse>"""


class _Recorder(ThreadingHTTPServer):
    """Replays EC2 fixtures; 403s any request whose SigV4 signature
    does not verify against the known secret — proving the client's
    signing end to end, not just its own self-consistency."""

    def __init__(self):
        self.calls = []
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                body = self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                if not outer._verify(self, body):
                    self.send_response(403)
                    self.end_headers()
                    return
                region = self.path.strip("/")
                form = dict(urllib.parse.parse_qsl(body.decode()))
                outer.calls.append((region, form.get("Action"),
                                    form.get("NextToken")))
                xml = outer._respond(region, form)
                data = xml.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/xml")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        super().__init__(("127.0.0.1", 0), H)

    def _verify(self, handler, body: bytes) -> bool:
        auth = handler.headers.get("Authorization", "")
        amz_date = handler.headers.get("x-amz-date", "")
        if not auth or not amz_date:
            return False
        now = datetime.datetime.strptime(
            amz_date, "%Y%m%dT%H%M%SZ").replace(
                tzinfo=datetime.timezone.utc)
        region = handler.path.strip("/") or "us-east-1"
        url = (f"http://{handler.headers['Host']}{handler.path}")
        want = sigv4_headers(
            "POST", url, body, ACCESS, SECRET, region, now=now,
            extra_headers={"content-type":
                           "application/x-www-form-urlencoded"})
        return want["Authorization"] == auth

    def _respond(self, region: str, form: dict) -> str:
        a = form["Action"]
        if a == "DescribeRegions":
            return _REGIONS_XML
        if a == "DescribeAvailabilityZones":
            return _AZS_XML.format(r=region)
        if a == "DescribeVpcs":
            return _VPCS_XML.format(r=region)
        if a == "DescribeSubnets":
            return _SUBNETS_XML.format(r=region)
        if a == "DescribeInstances":
            if form.get("NextToken") == "PAGE2TOKEN":
                return _INSTANCES_PAGE2.format(r=region)
            return _INSTANCES_PAGE1.format(r=region)
        if a == "DescribeNetworkInterfaces":
            return _ENI_XML.format(r=region,
                                   o=1 if region == "us-east-1" else 2)
        if a == "DescribeNatGateways":
            return _NAT_XML.format(r=region,
                                   o=1 if region == "us-east-1" else 2)
        raise AssertionError(f"unexpected action {a}")


@pytest.fixture
def recorder():
    srv = _Recorder()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


def _platform(srv, **kw):
    return AwsPlatform(
        "aws-dom", ACCESS, SECRET,
        endpoint_template=(
            f"http://127.0.0.1:{srv.server_address[1]}/{{region}}"),
        **kw)


def test_gather_normalizes_regions_vpcs_subnets_vms(recorder):
    p = _platform(recorder, regions=("us-east-1", "eu-west-1"))
    p.check_auth()
    rows = p.get_cloud_data()
    by = {}
    for r in rows:
        by.setdefault(r.type, []).append(r)
    assert [r.name for r in by["region"]] == ["us-east-1", "eu-west-1"]
    assert len(by["az"]) == 4
    assert sorted(r.name for r in by["vpc"]) == ["prod-eu-west-1",
                                                 "prod-us-east-1"]
    # pagination: BOTH instance pages landed, per region
    assert sorted(r.name for r in by["vm"]) == [
        "i-eu-west-1b", "i-us-east-1b", "web-eu-west-1", "web-us-east-1"]
    # epc (vpc) links resolve to the allocated vpc row ids
    vpc_ids = {r.name: r.id for r in by["vpc"]}
    vm_attrs = {r.name: dict(r.attrs) for r in by["vm"]}
    assert vm_attrs["web-us-east-1"]["epc_id"] == \
        vpc_ids["prod-us-east-1"]
    assert vm_attrs["web-us-east-1"]["ip"] == "10.1.1.10"
    subnet_attrs = {r.name: dict(r.attrs) for r in by["subnet"]}
    assert subnet_attrs["subnet-us-east-11"]["epc_id"] == \
        vpc_ids["prod-us-east-1"]
    # ENIs: attached ones land as vinterfaces with LAN + WAN ips;
    # the unattached eni-*-floating is skipped like the reference
    vifs = {r.name: dict(r.attrs) for r in by["vinterface"]}
    assert set(vifs) == {"eni-us-east-1-1", "eni-eu-west-1-1"}
    v1 = vifs["eni-us-east-1-1"]
    assert v1["mac"] == "02:aa:bb:cc:dd:01"
    # exact device link: THE attached instance, not just any vm
    vm_by_key = {r.name: r.id for r in by["vm"]}
    assert v1["device_vm_id"] == vm_by_key["web-us-east-1"]
    lan = {r.name for r in by["lan_ip"]}
    assert {"10.1.1.10", "10.1.1.21"} <= lan
    wan = {r.name for r in by["wan_ip"]}
    # primary (eni-level) AND secondary (per-address) EIPs
    assert {"52.0.1.7", "52.0.2.7", "52.9.1.9", "52.9.2.9"} <= wan
    # NAT gateways + nat-linked floating ips (same EC2 Query wire);
    # deleted-state gateways and their (possibly reassigned) IPs are
    # FILTERED like the reference does
    nat_ids = {r.name: r.id for r in by["nat_gateway"]}
    nat_attrs = {r.name: dict(r.attrs) for r in by["nat_gateway"]}
    assert set(nat_ids) == {"gw-us-east-1", "gw-eu-west-1"}
    assert nat_attrs["gw-us-east-1"]["vpc_id"] == \
        vpc_ids["prod-us-east-1"]
    fips = {r.name: dict(r.attrs) for r in by["floating_ip"]}
    # per-region IPs link to THEIR OWN region's gateway, exactly
    assert fips["3.3.1.3"]["nat_gateway_id"] == nat_ids["gw-us-east-1"]
    assert fips["3.3.2.3"]["nat_gateway_id"] == nat_ids["gw-eu-west-1"]
    assert not any(n.startswith("9.9.") for n in fips)
    # region fan-out actually happened (distinct endpoints by path)
    regions_hit = {c[0] for c in recorder.calls}
    assert regions_hit == {"us-east-1", "eu-west-1"}
    # DescribeInstances paged exactly once per region
    tokens = [c for c in recorder.calls
              if c[1] == "DescribeInstances" and c[2] == "PAGE2TOKEN"]
    assert len(tokens) == 2


def test_bad_secret_fails_auth(recorder):
    p = AwsPlatform(
        "aws-dom", ACCESS, "WRONG-SECRET",
        endpoint_template=(
            f"http://127.0.0.1:{recorder.server_address[1]}/{{region}}"))
    with pytest.raises(urllib.error.HTTPError):
        p.check_auth()


def test_controller_drives_aws_domain(recorder, tmp_path):
    """The ops API wires an aws domain end to end: platform construct,
    gather, recorder reconcile, rows visible in /v1/resources."""
    import json
    import urllib.request

    from deepflow_tpu.controller.model import ResourceModel
    from deepflow_tpu.controller.monitor import FleetMonitor
    from deepflow_tpu.controller.registry import VTapRegistry
    from deepflow_tpu.controller.server import ControllerServer

    reg = VTapRegistry()
    srv = ControllerServer(ResourceModel(), reg, FleetMonitor(reg),
                           port=0)
    srv.start()
    try:
        def post(path, body):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}{path}",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.load(r)

        post("/v1/cloud/domains", {
            "domain": "aws-prod", "platform": "aws",
            "secret_id": ACCESS, "secret_key": SECRET,
            "regions": ["us-east-1"],
            "endpoint_template":
                f"http://127.0.0.1:{recorder.server_address[1]}"
                "/{region}"})
        out = post("/v1/domains/aws-prod/refresh", {})
        assert out["ok"] is True
        assert out["resource_count"] >= 6
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/resources?type=vm",
                timeout=5) as r:
            vms = json.load(r)
        names = {h["name"] for h in vms}
        assert {"web-us-east-1", "i-us-east-1b"} <= names
    finally:
        srv.close()


def test_bad_endpoint_template_rejected_at_config_time():
    import json
    import urllib.request

    from deepflow_tpu.controller.model import ResourceModel
    from deepflow_tpu.controller.monitor import FleetMonitor
    from deepflow_tpu.controller.registry import VTapRegistry
    from deepflow_tpu.controller.server import ControllerServer

    reg = VTapRegistry()
    srv = ControllerServer(ResourceModel(), reg, FleetMonitor(reg),
                           port=0)
    srv.start()
    try:
        for bad in ("https://x/{regoin}/", "https://x{", "file:///e/{region}"):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/cloud/domains",
                data=json.dumps({
                    "domain": "d", "platform": "aws",
                    "secret_id": "a", "secret_key": "b",
                    "endpoint_template": bad}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=5)
            assert e.value.code == 400
    finally:
        srv.close()


def test_eni_addresses_compile_into_platform_data(recorder):
    """ENI lan/wan ips become InterfaceInfo rows carrying the device
    VM's identity — the enrichment a vm row's single primary ip can't
    provide for secondary addresses and EIPs."""
    from deepflow_tpu.controller.model import ResourceModel
    from deepflow_tpu.controller.platform_compiler import \
        compile_platform_data
    from deepflow_tpu.controller.recorder import Recorder

    model = ResourceModel()
    p = _platform(recorder, regions=("us-east-1",))
    Recorder(model).reconcile("aws-dom", p.get_cloud_data())
    ifaces, _cidrs, _svcs, _v = compile_platform_data(model)
    by_ip = {}
    import ipaddress
    for i in ifaces:
        by_ip[str(ipaddress.IPv4Address(i.ip))] = i
    vm_id = next(r.id for r in model.list(type="vm")
                 if r.name == "web-us-east-1")
    # secondary private ip AND its EIP both map to the attached VM
    for addr in ("10.1.1.21", "52.9.1.9", "52.0.1.7"):
        assert addr in by_ip, addr
        assert by_ip[addr].l3_device_type == 1
        assert by_ip[addr].l3_device_id == vm_id

"""AF_XDP capture source: real XDP redirect on loopback.

These tests attach a REAL XDP program (generic mode) to lo and read
frames out of the XSK rings. While attached, the redirect CONSUMES
lo's ingress — each test keeps the window short and detaches in a
finally so the rest of the suite (and any loopback tunnel) is
untouched. Skipped wholesale where the container forbids the path."""

import socket
import time

import pytest

from deepflow_tpu.agent import xdp

pytestmark = pytest.mark.skipif(not xdp.available(),
                                reason="AF_XDP unavailable")


def _flood(port: int, n: int, tag: bytes = b"x") -> None:
    tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    for i in range(n):
        tx.sendto(tag + b"-%d" % i, ("127.0.0.1", port))
    tx.close()


def test_xdp_capture_roundtrip():
    src = xdp.XdpSource("lo", frame_count=256)
    try:
        _flood(55988, 50, b"xdpA")
        time.sleep(0.2)
        frames, stamps = src.read_batch()
        hits = sum(1 for f in frames if b"xdpA-" in f)
        assert hits == 50
        assert len(stamps) == len(frames)
        # frames recycle through the fill ring: a second burst larger
        # than half the UMEM must still arrive intact
        _flood(55988, 200, b"xdpB")
        time.sleep(0.2)
        frames, _ = src.read_batch()
        assert sum(1 for f in frames if b"xdpB-" in f) == 200
        dropped, ring_full = src.statistics()
        assert dropped == 0
    finally:
        src.close()


def test_xdp_capture_loop_and_flow_map():
    """CaptureLoop + a real FlowMap over XSK frames: the decode path
    accepts XDP-delivered frames like any other source's."""
    from deepflow_tpu.agent.afpacket import CaptureLoop
    from deepflow_tpu.agent.packet import decode_packets
    import numpy as np

    class DecodeAgent:
        def __init__(self):
            self.rows = 0

        def feed(self, frames, stamps):
            pkt = decode_packets(frames, np.asarray(stamps, np.uint64))
            self.rows += int(pkt["valid"].sum())
            return len(frames)

    agent = DecodeAgent()
    src = xdp.XdpSource("lo", frame_count=256)
    loop = CaptureLoop(src, agent)
    loop.start()
    try:
        _flood(55987, 80, b"flow")
        deadline = time.time() + 3
        while time.time() < deadline and agent.rows < 80:
            time.sleep(0.05)
        assert agent.rows >= 80
    finally:
        loop.close()


def test_xdp_detach_restores_loopback():
    """After close(), lo ingress must flow normally again (the XDP
    program is removed via netlink, not leaked)."""
    src = xdp.XdpSource("lo", frame_count=64)
    src.close()
    rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))
    rx.settimeout(2)
    port = rx.getsockname()[1]
    tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    tx.sendto(b"after-detach", ("127.0.0.1", port))
    tx.close()
    assert rx.recv(64) == b"after-detach"
    rx.close()


def test_xdp_bootstrap_validation(tmp_path):
    from deepflow_tpu.agent.__main__ import load_bootstrap
    p = tmp_path / "a.yaml"
    p.write_text("capture: {engine: xdp}\n")
    with pytest.raises(ValueError, match="iface"):
        load_bootstrap(str(p))
    p.write_text("capture: {engine: raw, queue: 1}\n")
    with pytest.raises(ValueError, match="queue"):
        load_bootstrap(str(p))
    p.write_text("capture: {engine: xdp, iface: lo, bpf: {proto: 6}}\n")
    with pytest.raises(ValueError, match="raw or ring"):
        load_bootstrap(str(p))
    p.write_text("capture: {engine: xdp, iface: lo, frame_count: 128}\n")
    _, capture = load_bootstrap(str(p))
    assert capture["frame_count"] == 128

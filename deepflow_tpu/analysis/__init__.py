"""deepflow-lint: AST invariant checks for the pipeline's disciplines.

Entry points: `df-ctl lint` (deepflow_tpu/cli.py), the `lint` debug
command (runtime/debug.py), and ci.sh's failing lint step against the
committed `.lint-baseline.json` + `.lint-twins.json` +
`.model-conform.json`. See core.py for the framework, checkers.py for
the per-file rules, concurrency.py for the whole-program lock/race
rules, twins.py for the host/device twin registry behind the
twin-drift gate, docdrift.py for the README knob/gauge coverage rule,
and model/ for deepflow-model — the explicit-state protocol checker
behind `df-ctl verify` and the model-conform gate (ISSUE 14). Rule
modules are discovered dynamically (core.all_rules walks the package),
so a new checker file registers itself.
"""

from deepflow_tpu.analysis.core import (Finding, all_rules,
                                        default_conform_store_path,
                                        default_doc_path,
                                        default_twin_store_path,
                                        findings_to_json,
                                        findings_to_sarif,
                                        format_findings, load_baseline,
                                        new_findings, run_lint,
                                        run_on_sources, save_baseline,
                                        scan_package)
from deepflow_tpu.analysis.twins import host_twin_of

__all__ = ["Finding", "all_rules", "default_conform_store_path",
           "default_doc_path", "default_twin_store_path",
           "findings_to_json", "findings_to_sarif", "format_findings",
           "host_twin_of", "load_baseline", "new_findings", "run_lint",
           "run_on_sources", "save_baseline", "scan_package"]

import numpy as np

from deepflow_tpu.decode import decode_l4_records, decode_metric_records
from deepflow_tpu.replay import SyntheticAgent
from deepflow_tpu.wire import (
    BaseHeader,
    FlowHeader,
    FrameReader,
    MessageType,
    encode_frame,
    iter_pb_records,
    pack_pb_records,
)


def test_base_header_roundtrip():
    h = BaseHeader(frame_size=12345, msg_type=MessageType.TAGGEDFLOW)
    enc = h.encode()
    assert len(enc) == 5
    assert enc[:4] == (12345).to_bytes(4, "big")      # big-endian frame size
    d = BaseHeader.decode(enc)
    assert d.frame_size == 12345 and d.msg_type == MessageType.TAGGEDFLOW


def test_flow_header_roundtrip():
    h = FlowHeader(version=20220117, sequence=99, vtap_id=42)
    enc = h.encode()
    assert len(enc) == 14
    assert enc[:4] == (20220117).to_bytes(4, "little")  # little-endian
    d = FlowHeader.decode(enc)
    assert (d.version, d.sequence, d.vtap_id) == (20220117, 99, 42)


def test_pb_record_packing():
    recs = [b"aaa", b"", b"0123456789"]
    packed = pack_pb_records(recs)
    assert list(iter_pb_records(packed)) == recs


def test_frame_reader_handles_arbitrary_chunking():
    agent = SyntheticAgent()
    _, recs = agent.l4_batch(100)
    frames = list(agent.frames(recs, MessageType.TAGGEDFLOW, per_frame=16))
    stream = b"".join(frames)
    reader = FrameReader()
    got = []
    for i in range(0, len(stream), 7):                 # pathological chunking
        got.extend(reader.feed(stream[i:i + 7]))
    assert len(got) == len(frames)
    out = []
    for fr in got:
        assert fr.msg_type == MessageType.TAGGEDFLOW
        assert fr.flow_header.vtap_id == agent.vtap_id
        out.extend(iter_pb_records(fr.payload))
    assert len(out) == 100
    seqs = [fr.flow_header.sequence for fr in got]
    assert seqs == sorted(seqs)


def test_l4_decode_matches_ground_truth():
    agent = SyntheticAgent()
    cols, recs = agent.l4_batch(500)
    got = decode_l4_records(recs)
    assert np.array_equal(got["ip_src"], cols["ip_src"])
    assert np.array_equal(got["ip_dst"], cols["ip_dst"])
    assert np.array_equal(got["port_dst"], cols["port_dst"])
    assert np.array_equal(got["proto"], cols["proto"])
    assert np.array_equal(got["byte_tx"], cols["byte_tx"].astype(np.uint32))
    assert np.array_equal(got["rtt"], cols["rtt"])
    assert np.array_equal(got["retrans"], cols["retrans"])
    assert np.array_equal(got["l3_epc_id"], cols["l3_epc_id"])
    assert np.array_equal(
        got["timestamp"], (cols["start_time"] // 10**9).astype(np.uint32))


def test_metric_decode_roundtrip():
    agent = SyntheticAgent()
    recs = [
        agent.metric_record(1700000000 + i, svc=i % 4,
                            traffic=dict(packet_tx=10 * i, byte_rx=100 * i,
                                         new_flow=i))
        for i in range(20)
    ]
    cols = decode_metric_records(recs)
    assert cols["timestamp"][5] == 1700000005
    assert cols["packet_tx"][3] == 30
    assert cols["byte_rx"][7] == 700
    assert cols["new_flow"][19] == 19
    assert cols["server_port"][0] == agent.server_ports[0]


def test_oversize_frame_rejected():
    import pytest
    with pytest.raises(ValueError):
        encode_frame(MessageType.TAGGEDFLOW, b"x" * 600_000)


def test_malformed_headers_rejected_not_looped():
    """Corrupt frame sizes must raise, not spin or desync (DoS guard)."""
    import pytest
    r = FrameReader()
    with pytest.raises(ValueError):                 # frame_size == 0
        list(r.feed((0).to_bytes(4, "big") + bytes([1]) + b"xxxx"))
    r = FrameReader()
    with pytest.raises(ValueError):                 # below flow-header min
        list(r.feed((10).to_bytes(4, "big")
                    + bytes([int(MessageType.TAGGEDFLOW)]) + b"x" * 10))
    r = FrameReader()
    with pytest.raises(ValueError):                 # unknown message type
        list(r.feed((20).to_bytes(4, "big") + bytes([99]) + b"x" * 15))


def test_metric_tag_code_roundtrip():
    """The zerodoc Code bitmask travels the Document wire and lands as a
    grouping dimension: documents tagged over different dimension sets
    must never merge (tag.go:36-95)."""
    from deepflow_tpu.agent.quadruple import documents_to_records

    doc_cols = {k: np.asarray(v) for k, v in {
        "timestamp": [1700000000], "ip": [0x0A000001],
        "server_port": [80], "vtap_id": [1], "protocol": [6],
        "packet_tx": [5], "packet_rx": [5], "byte_tx": [500],
        "byte_rx": [900], "new_flow": [1], "closed_flow": [0],
        "retrans": [0], "rtt_sum": [100], "rtt_count": [1],
    }.items()}
    recs = documents_to_records(doc_cols)
    cols = decode_metric_records(recs)
    want = 0x1 | (1 << 42) | (1 << 43) | (1 << 47)
    assert cols["tag_code"].dtype == np.uint64
    assert int(cols["tag_code"][0]) == want

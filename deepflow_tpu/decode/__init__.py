from deepflow_tpu.decode.columnar import (
    decode_l4_records,
    decode_l7_records,
    decode_metric_records,
)

__all__ = ["decode_l4_records", "decode_l7_records", "decode_metric_records"]

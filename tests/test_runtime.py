"""Runtime layer tests: queues, throttler, stats, exporters, receiver.

Mirrors the reference's own coverage of these pieces (go tests in
server/ingester/droplet/queue, libs/queue, libs/receiver — SURVEY.md §4),
plus a live loopback firehose test: SyntheticAgent frames -> TCP/UDP socket
-> Receiver -> MultiQueue -> frame payload decode round-trip.
"""

import socket
import threading
import time

import numpy as np
import pytest

from deepflow_tpu.replay.generator import SyntheticAgent
from deepflow_tpu.runtime.exporters import Exporters, QueueWorkerExporter
from deepflow_tpu.runtime.queues import MultiQueue, OverwriteQueue
from deepflow_tpu.runtime.receiver import Receiver
from deepflow_tpu.runtime.stats import StatsRegistry
from deepflow_tpu.runtime.throttler import ThrottlingQueue
from deepflow_tpu.wire import MessageType, iter_pb_records
from deepflow_tpu.wire.gen import flow_log_pb2


# ---------------------------------------------------------------- queues

def test_overwrite_queue_fifo_and_batch():
    q = OverwriteQueue("t", 8)
    q.puts(list(range(5)))
    assert q.gets(3) == [0, 1, 2]
    assert q.gets(10, timeout=0.01) == [3, 4]
    assert q.gets(1, timeout=0.01) == []


def test_overwrite_queue_overwrites_oldest():
    q = OverwriteQueue("t", 4)
    q.puts(list(range(6)))          # 0,1 overwritten
    assert q.counters()["overwritten"] == 2
    assert q.gets(10, timeout=0.01) == [2, 3, 4, 5]


def test_overwrite_queue_close_wakes_reader():
    q = OverwriteQueue("t", 4)
    got = []

    def reader():
        got.append(q.gets(1, timeout=5))

    t = threading.Thread(target=reader)
    t.start()
    time.sleep(0.05)
    q.close()
    t.join(timeout=2)
    assert got == [[]]
    # ISSUE 4 satellite: a post-close put is a COUNTED drop, not a
    # raise — during the shutdown drain ladder racing producers must
    # not crash-loop their supervisors
    q.put(1)
    assert q.counters()["closed_dropped"] == 1


def test_multi_queue_hashes_consistently():
    mq = MultiQueue("t", 4, 16)
    for vtap in (7, 8, 7, 9, 7):
        mq.put(vtap, vtap)
    # all vtap=7 items landed on the same sub-queue, in order
    idx = 7 % 4
    items = mq.gets(idx, 10, timeout=0.01)
    assert items.count(7) == 3


# ------------------------------------------------------------- throttler

def test_throttler_passthrough_under_cap():
    out = []
    clk = [100.0]
    t = ThrottlingQueue(out.extend, throttle_per_s=10, bucket_s=1,
                        seed=1, clock=lambda: clk[0])
    for i in range(10):
        assert t.send(i)
    t.flush()
    assert out == list(range(10))


def test_throttler_reservoir_caps_and_is_uniformish():
    out = []
    clk = [100.0]
    t = ThrottlingQueue(out.extend, throttle_per_s=100, bucket_s=1,
                        seed=7, clock=lambda: clk[0])
    for i in range(10_000):
        t.send(i)
    t.flush()
    assert len(out) == 100
    # uniform over the bucket: mean near 5000, not clustered at the start
    assert 3000 < np.mean(out) < 7000
    assert t.counters()["sampled_out"] == 10_000 - 100


def test_throttler_bucket_roll_flushes():
    out = []
    clk = [100.0]
    t = ThrottlingQueue(lambda b: out.append(list(b)), throttle_per_s=1000,
                        bucket_s=1, clock=lambda: clk[0])
    t.send("a")
    clk[0] = 101.5   # next bucket
    t.send("b")
    assert out == [["a"]]


# ----------------------------------------------------------------- stats

def test_stats_registry_collects_and_sinks():
    reg = StatsRegistry()
    q = OverwriteQueue("t", 4)
    reg.register("queue.t", q.counters, tags={"module": "test"})
    q.put(1)
    seen = []
    reg.add_sink(seen.append)
    samples = reg.collect()
    assert len(samples) == 1
    assert samples[0].values["in"] == 1
    assert seen[0].module == "queue.t"
    assert reg.history("queue.t")


def test_stats_registry_survives_broken_source():
    reg = StatsRegistry()
    reg.register("bad", lambda: 1 / 0)
    reg.register("good", lambda: {"x": 1})
    samples = reg.collect()
    assert [s.module for s in samples] == ["good"]


# ------------------------------------------------------------- exporters

class _SinkExporter(QueueWorkerExporter):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.seen = []

    def process(self, chunks):
        self.seen.extend(chunks)


def test_exporter_registry_filters_and_fans_out():
    ex = Exporters()
    a = _SinkExporter(name="a", streams=["l4_flow_log"])
    b = _SinkExporter(name="b", streams=["l7_flow_log"])
    ex.register(a)
    ex.register(b)
    ex.start()
    ex.put("l4_flow_log", 0, {"ip_src": np.array([1])})
    deadline = time.time() + 2
    while not a.seen and time.time() < deadline:
        time.sleep(0.01)
    ex.close()
    assert len(a.seen) == 1 and a.seen[0][0] == "l4_flow_log"
    assert not b.seen
    assert ex.counters()["filtered"] == 1


# -------------------------------------------------------------- receiver

def _wait(predicate, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture
def receiver():
    r = Receiver(port=0)
    mq = MultiQueue("taggedflow", 2, 1024,)
    r.register_handler(MessageType.TAGGEDFLOW, mq)
    r.start()
    yield r, mq
    r.close()


def _drain(mq, n_expected):
    frames = []
    for i in range(len(mq.queues)):
        frames.extend(mq.gets(i, 10_000, timeout=0.01))
    return frames


def test_receiver_tcp_roundtrip(receiver):
    r, mq = receiver
    agent = SyntheticAgent(vtap_id=42)
    cols, records = agent.l4_batch(100)
    frames = list(agent.frames(records, MessageType.TAGGEDFLOW, per_frame=32))

    with socket.create_connection(("127.0.0.1", r.bound_port)) as s:
        for f in frames:
            s.sendall(f)
        assert _wait(lambda: r.rx_frames >= len(frames))

    got = _drain(mq, len(frames))
    assert len(got) == len(frames)
    # payloads decode back to the original records
    all_records = [raw for f in got for raw in iter_pb_records(f.payload)]
    assert len(all_records) == 100
    m = flow_log_pb2.TaggedFlow()
    m.ParseFromString(all_records[0])
    assert m.flow.flow_key.vtap_id == 42
    # vtap status tracked, no gaps
    st = r.status()[(42, int(MessageType.TAGGEDFLOW))]
    assert st.rx_frames == len(frames) and st.rx_dropped == 0


def test_receiver_udp_and_seq_gap_tracking(receiver):
    r, mq = receiver
    agent = SyntheticAgent(vtap_id=7)
    _, records = agent.l4_batch(8)
    frames = list(agent.frames(records, MessageType.TAGGEDFLOW, per_frame=2))
    assert len(frames) == 4
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    # drop frame[1] and frame[2]: a 2-frame sequence gap
    sock.sendto(frames[0], ("127.0.0.1", r.bound_port))
    sock.sendto(frames[3], ("127.0.0.1", r.bound_port))
    sock.close()
    assert _wait(lambda: r.rx_frames >= 2)
    st = r.status()[(7, int(MessageType.TAGGEDFLOW))]
    assert st.rx_dropped == 2
    assert r.counters()["seq_dropped"] == 2


def test_receiver_garbage_tcp_counted(receiver):
    r, _ = receiver
    with socket.create_connection(("127.0.0.1", r.bound_port)) as s:
        s.sendall(b"\xff" * 64)   # frame_size way over max
    assert _wait(lambda: r.rx_errors >= 1)


def test_debug_stacks():
    """The stacks debug command returns every live thread's frames (the
    pprof-analogue one-shot profiler)."""
    from deepflow_tpu.runtime.debug import DebugServer, debug_request
    from deepflow_tpu.runtime.stats import StatsRegistry

    srv = DebugServer(StatsRegistry(), port=0)
    srv.start()
    try:
        out = debug_request("stacks", port=srv.port)
        assert out["ok"]
        names = list(out["data"])
        assert any("MainThread" in k for k in names)
        assert any("debug-udp" in k for k in names)
        frames = next(iter(out["data"].values()))
        assert all(":" in f for f in frames)
    finally:
        srv.close()


def test_sketch_exporter_dict_wire_matches_lanes_wire():
    """The exporter's default dictionary wire must land the exact
    additive sketch state the stateless packed lane lands for the same
    chunks — the product-path version of test_flow_dict's equivalence
    (the dict lane is the default precisely because state is provably
    identical at half the transfer bytes)."""
    from deepflow_tpu.batch.schema import L4_SCHEMA
    from deepflow_tpu.runtime.tpu_sketch import TpuSketchExporter

    rng = np.random.default_rng(17)
    # packet sums intentionally exceed the dict wire's u16 field:
    # entropy saturates per-record weights at 65535 on every path, so
    # the equality must hold regardless
    pool = {name: rng.integers(0, 1 << 16, 512).astype(dt)
            for name, dt in L4_SCHEMA.columns}
    chunks = []
    for _ in range(4):
        picks = rng.integers(0, 512, 2000)
        chunks.append({k: v[picks] for k, v in pool.items()})

    a = TpuSketchExporter(store=None, window_seconds=3600,
                          batch_rows=1024, wire="dict")
    b = TpuSketchExporter(store=None, window_seconds=3600,
                          batch_rows=1024, wire="lanes")
    try:
        assert a.wire == "dict" and b.wire == "lanes"
        for c in chunks:
            a.process([("l4_flow_log", 0, c)])
            b.process([("l4_flow_log", 0, c)])
        assert int(a.state.rows_seen) > 0
        np.testing.assert_array_equal(np.asarray(a.state.sketch.counts),
                                      np.asarray(b.state.sketch.counts))
        np.testing.assert_array_equal(
            np.asarray(a.state.services.registers),
            np.asarray(b.state.services.registers))
        np.testing.assert_array_equal(np.asarray(a.state.ent.hist),
                                      np.asarray(b.state.ent.hist))
        assert int(a.state.rows_seen) == int(b.state.rows_seen)
    finally:
        a.close()
        b.close()


def test_staged_update_failure_counter_surfaces():
    """A staged ring-admission failure is observable through the
    exporter's counters (deepflow_system), not only in logs."""
    from deepflow_tpu.models import flow_suite
    from deepflow_tpu.runtime.tpu_sketch import TpuSketchExporter

    exp = TpuSketchExporter(store=None, window_seconds=3600, staged=True)
    try:
        assert exp.counters().get("ring_admission_failures") == 0
        exp._update.admission_failures += 1   # simulate a skipped batch
        assert exp.counters()["ring_admission_failures"] == 1
        # the attribute is part of make_staged_update's contract
        fn = flow_suite.make_staged_update(exp.cfg)
        assert fn.admission_failures == 0
    finally:
        exp.close()

"""Extended L7 parsers: fixture-replay style tests with hand-built
payload bytes per protocol (the reference's own test approach,
agent/src/flow_generator/protocol_logs/*/ #[cfg(test)] fixtures)."""

import struct

import pytest

from deepflow_tpu.agent.l7 import (MSG_REQUEST, MSG_RESPONSE, PARSERS,
                                   SessionAggregator, parse_payload)
from deepflow_tpu.agent import l7_ext
from deepflow_tpu.agent.l7_ext import (
    L7_AMQP, L7_DUBBO, L7_FASTCGI, L7_GRPC, L7_HTTP2, L7_KAFKA,
    L7_MONGODB, L7_MQTT, L7_NATS, L7_OPENWIRE, L7_POSTGRESQL,
    L7_SOFARPC, L7_TLS, hpack_headers, huffman_decode)
from deepflow_tpu.agent.sql_obfuscate import obfuscate_sql, sql_verb


def _dispatch(payload, proto=6, ps=40000, pd=443):
    return parse_payload(payload, proto=proto, port_src=ps, port_dst=pd)


# ---------------------------------------------------------------- TLS --

def _client_hello(sni=b"api.example.com"):
    ext = struct.pack(">HHHBH", 0, len(sni) + 5, len(sni) + 3, 0,
                      len(sni)) + sni
    exts = struct.pack(">H", len(ext)) + ext
    body = (b"\x03\x03" + b"\x00" * 32        # version + random
            + b"\x00"                          # session id len
            + b"\x00\x02\x13\x01"              # one cipher suite
            + b"\x01\x00"                      # compression
            + exts)
    hs = b"\x01" + len(body).to_bytes(3, "big") + body
    return b"\x16\x03\x01" + struct.pack(">H", len(hs)) + hs


def test_tls_client_hello_sni():
    rec = _dispatch(_client_hello())
    assert rec is not None and rec.proto == L7_TLS
    assert rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "api.example.com"


def test_tls_server_hello_and_alert():
    body = b"\x03\x03" + b"\x00" * 32 + b"\x00" + b"\x13\x01" + b"\x00"
    hs = b"\x02" + len(body).to_bytes(3, "big") + body
    sh = b"\x16\x03\x03" + struct.pack(">H", len(hs)) + hs
    rec = _dispatch(sh)
    assert rec.proto == L7_TLS and rec.msg_type == MSG_RESPONSE
    alert = b"\x15\x03\x03\x00\x02\x02\x28"       # fatal handshake_failure
    rec = _dispatch(alert)
    assert rec.msg_type == MSG_RESPONSE and rec.status == 2


def test_tls_session_pairing():
    agg = SessionAggregator()
    flow = (1, 2, 3, 4, 6)
    req = _dispatch(_client_hello())
    agg.offer((flow, req.proto), req, 1_000_000_000)
    body = b"\x03\x03" + b"\x00" * 32 + b"\x00" + b"\x13\x01" + b"\x00"
    hs = b"\x02" + len(body).to_bytes(3, "big") + body
    resp = _dispatch(b"\x16\x03\x03" + struct.pack(">H", len(hs)) + hs)
    merged = agg.offer((flow, resp.proto), resp, 1_003_000_000)
    assert merged is not None
    assert merged["endpoint"] == "api.example.com"
    assert merged["rrt_us"] == 3000


# ------------------------------------------------------------- HTTP/2 --

def _h2_headers_frame(block, stream=1, flags=0x4):
    return len(block).to_bytes(3, "big") + bytes([0x1, flags]) + \
        struct.pack(">I", stream) + block


def test_http2_request_with_hpack_huffman():
    # RFC 7541 C.4.1 block: GET http://www.example.com/
    block = bytes.fromhex("828684418cf1e3c2e5f23a6ba0ab90f4ff")
    payload = l7_ext._H2_PREFACE + _h2_headers_frame(block)
    rec = _dispatch(payload)
    assert rec.proto == L7_HTTP2 and rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "GET /"


def test_http2_response_status():
    block = bytes.fromhex("88")                    # :status 200 indexed
    rec = _dispatch(_h2_headers_frame(block))
    assert rec.proto == L7_HTTP2 and rec.msg_type == MSG_RESPONSE
    assert rec.status == 200


def test_http2_grpc_detection():
    # :method POST (idx 3), :path literal, content-type literal
    path = b"/pkg.Svc/Method"
    block = (b"\x83"
             + b"\x44" + bytes([len(path)]) + path        # :path literal
             + b"\x5f" + bytes([16]) + b"application/grpc")
    rec = _dispatch(_h2_headers_frame(block))
    assert rec.proto == L7_GRPC
    assert rec.endpoint == "POST /pkg.Svc/Method"


def test_huffman_rfc_vectors():
    assert huffman_decode(
        bytes.fromhex("f1e3c2e5f23a6ba0ab90f4ff")) == "www.example.com"
    assert huffman_decode(bytes.fromhex("a8eb10649cbf")) == "no-cache"
    assert huffman_decode(bytes.fromhex("6402")) == "302"
    assert hpack_headers(
        bytes.fromhex("828684418cf1e3c2e5f23a6ba0ab90f4ff")) == [
        (":method", "GET"), (":scheme", "http"), (":path", "/"),
        (":authority", "www.example.com")]


# -------------------------------------------------------------- Kafka --

def _kafka_request(api_key=0, client=b"producer-1"):
    hdr = struct.pack(">hhih", api_key, 7, 42, len(client)) + client
    body = hdr + b"\x00" * 8
    return struct.pack(">i", len(body)) + body


def test_kafka_produce_request():
    rec = _dispatch(_kafka_request(0))
    assert rec.proto == L7_KAFKA and rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "Produce producer-1"


def test_kafka_fetch_and_response():
    rec = _dispatch(_kafka_request(1, b"consumer"))
    assert rec.endpoint == "Fetch consumer"
    resp_body = struct.pack(">i", 42) + b"\x00" * 6
    resp = struct.pack(">i", len(resp_body)) + resp_body
    rec = _dispatch(resp)
    assert rec.proto == L7_KAFKA and rec.msg_type == MSG_RESPONSE


# --------------------------------------------------------- PostgreSQL --

def _pg_msg(t, body):
    return t + struct.pack(">i", len(body) + 4) + body


def test_postgres_simple_query_obfuscated():
    q = _pg_msg(b"Q", b"SELECT * FROM users WHERE id = 42\x00")
    rec = _dispatch(q)
    assert rec.proto == L7_POSTGRESQL and rec.msg_type == MSG_REQUEST
    assert rec.endpoint.startswith("SELECT")
    assert "42" not in rec.endpoint          # literal obfuscated
    assert "?" in rec.endpoint


def test_postgres_error_response():
    body = b"SERROR\x00C42703\x00Mcolumn does not exist\x00\x00"
    rec = _dispatch(_pg_msg(b"E", body))
    assert rec.proto == L7_POSTGRESQL and rec.msg_type == MSG_RESPONSE
    assert rec.status == 1 and rec.endpoint == "ERROR"


def test_postgres_ready_for_query_is_response():
    rec = _dispatch(_pg_msg(b"Z", b"I"))
    assert rec.proto == L7_POSTGRESQL and rec.msg_type == MSG_RESPONSE


# ------------------------------------------------------------ MongoDB --

def _bson_doc(first_key=b"find"):
    elem = b"\x02" + first_key + b"\x00" + struct.pack("<i", 5) + b"coll\x00"
    doc = struct.pack("<i", 4 + len(elem) + 1) + elem + b"\x00"
    return doc


def _mongo_op_msg(req_id=7, resp_to=0):
    sections = b"\x00" + _bson_doc()
    body = struct.pack("<I", 0) + sections
    header = struct.pack("<iiii", 16 + len(body), req_id, resp_to, 2013)
    return header + body


def test_mongo_op_msg_command():
    rec = _dispatch(_mongo_op_msg())
    assert rec.proto == L7_MONGODB and rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "find"


def test_mongo_response_by_response_to():
    rec = _dispatch(_mongo_op_msg(req_id=8, resp_to=7))
    assert rec.proto == L7_MONGODB and rec.msg_type == MSG_RESPONSE


# -------------------------------------------------------------- Dubbo --

def _hessian_str(s):
    assert len(s) < 32
    return bytes([len(s)]) + s


def _dubbo_request():
    body = (_hessian_str(b"2.0.2")
            + _hessian_str(b"com.acme.UserService")
            + _hessian_str(b"1.0.0")
            + _hessian_str(b"getUser"))
    return b"\xda\xbb\xc2\x00" + struct.pack(">Q", 1) + \
        struct.pack(">I", len(body)) + body


def test_dubbo_request_service_method():
    rec = _dispatch(_dubbo_request())
    assert rec.proto == L7_DUBBO and rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "com.acme.UserService.getUser"


def test_dubbo_response_status():
    ok = b"\xda\xbb\x02\x14" + struct.pack(">Q", 1) + \
        struct.pack(">I", 2) + b"\x91\x05"
    rec = _dispatch(ok)
    assert rec.proto == L7_DUBBO and rec.msg_type == MSG_RESPONSE
    assert rec.status == 0
    bad = b"\xda\xbb\x02\x28" + struct.pack(">Q", 1) + \
        struct.pack(">I", 2) + b"\x91\x05"
    assert _dispatch(bad).status == 1


def test_dubbo_heartbeat_skipped():
    hb = b"\xda\xbb\xe2\x00" + struct.pack(">Q", 1) + \
        struct.pack(">I", 1) + b"N"
    assert _dispatch(hb) is None


# --------------------------------------------------------------- MQTT --

def _mqtt_connect(client_id=b"sensor-7"):
    var = struct.pack(">H", 4) + b"MQTT" + b"\x04\x02" + \
        struct.pack(">H", 60) + struct.pack(">H", len(client_id)) + client_id
    return bytes([0x10, len(var)]) + var


def _mqtt_publish(topic=b"metrics/cpu"):
    var = struct.pack(">H", len(topic)) + topic + b"payload"
    return bytes([0x30, len(var)]) + var


def test_mqtt_connect_and_connack():
    rec = _dispatch(_mqtt_connect())
    assert rec.proto == L7_MQTT and rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "sensor-7"
    connack = bytes([0x20, 2, 0, 0])
    rec = _dispatch(connack)
    assert rec.msg_type == MSG_RESPONSE and rec.status == 0


def test_mqtt_publish_topic():
    rec = _dispatch(_mqtt_publish())
    assert rec.proto == L7_MQTT
    assert rec.endpoint == "metrics/cpu"


def test_mqtt_rejects_wrong_length():
    assert _dispatch(bytes([0x30, 200]) + b"xx") is None or True
    # malformed remaining-length must not crash the dispatcher


# --------------------------------------------------------------- AMQP --

def _amqp_method(cls_id, meth_id, args=b""):
    body = struct.pack(">HH", cls_id, meth_id) + args
    return b"\x01" + struct.pack(">H", 0) + struct.pack(">I", len(body)) + \
        body + b"\xce"


def test_amqp_basic_publish():
    rec = _dispatch(_amqp_method(60, 40))
    assert rec.proto == L7_AMQP and rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "basic.publish"


def test_amqp_declare_ok_is_response():
    rec = _dispatch(_amqp_method(50, 11))
    assert rec.msg_type == MSG_RESPONSE
    assert rec.endpoint == "queue.declare-ok"


def test_amqp_protocol_header():
    rec = _dispatch(b"AMQP\x00\x00\x09\x01")
    assert rec.proto == L7_AMQP and rec.msg_type == MSG_REQUEST


# --------------------------------------------------------------- NATS --

def test_nats_pub_sub_msg():
    rec = _dispatch(b"PUB orders.new 5\r\nhello\r\n")
    assert rec.proto == L7_NATS and rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "PUB orders.new"
    rec = _dispatch(b"MSG orders.new 1 5\r\nhello\r\n")
    assert rec.msg_type == MSG_RESPONSE
    assert rec.endpoint == "MSG orders.new"
    rec = _dispatch(b"-ERR 'Unknown Subject'\r\n")
    assert rec.status == 1


# ----------------------------------------------------------- OpenWire --

def test_openwire_wireformat_info():
    body = b"\x01" + b"\x00\x08ActiveMQ" + b"\x00" * 4
    payload = struct.pack(">I", len(body)) + body
    rec = _dispatch(payload)
    assert rec.proto == L7_OPENWIRE and rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "WireFormatInfo"


def test_openwire_response():
    body = b"\x1e" + b"\x00" * 8
    payload = struct.pack(">I", len(body)) + body
    rec = _dispatch(payload)
    assert rec.proto == L7_OPENWIRE and rec.msg_type == MSG_RESPONSE


# ------------------------------------------------------------ FastCGI --

def _fcgi_record(rtype, body, req_id=1):
    return struct.pack(">BBHHBB", 1, rtype, req_id, len(body), 0, 0) + body


def _fcgi_pair(k, v):
    return bytes([len(k), len(v)]) + k + v


def test_fastcgi_params_request():
    params = _fcgi_pair(b"REQUEST_METHOD", b"GET") + \
        _fcgi_pair(b"SCRIPT_NAME", b"/index.php")
    payload = _fcgi_record(1, struct.pack(">HB5x", 1, 0)) + \
        _fcgi_record(4, params) + _fcgi_record(4, b"")
    rec = _dispatch(payload)
    assert rec.proto == L7_FASTCGI and rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "GET /index.php"


def test_fastcgi_stdout_response():
    body = b"Status: 404 Not Found\r\nContent-type: text/html\r\n\r\n"
    rec = _dispatch(_fcgi_record(6, body))
    assert rec.proto == L7_FASTCGI and rec.msg_type == MSG_RESPONSE
    assert rec.status == 404


# ------------------------------------------------------------ SofaRPC --

def _bolt_request():
    cls = b"com.alipay.sofa.rpc.core.request.SofaRequest"
    header = (b"sofa_head_target_service\x00com.acme.HelloService:1.0\x00"
              b"sofa_head_method_name\x00sayHello\x00")
    # proto, type, cmdcode, ver2, reqid, codec, timeout, classLen,
    # headerLen, contentLen = 22 bytes
    fixed = struct.pack(">BBHBIBIHHI", 1, 1, 1, 1, 77, 1, 3000,
                        len(cls), len(header), 0)
    return fixed + cls + header


def test_sofarpc_request():
    rec = _dispatch(_bolt_request())
    assert rec.proto == L7_SOFARPC and rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "com.acme.HelloService:1.0.sayHello"


def test_sofarpc_response_status():
    # proto, type, cmdcode, ver2, reqid, codec, respStatus, classLen,
    # headerLen, contentLen = 20 bytes
    resp = struct.pack(">BBHBIBHHHI", 1, 0, 2, 1, 77, 1, 0, 0, 0, 0)
    rec = _dispatch(resp)
    assert rec.proto == L7_SOFARPC and rec.msg_type == MSG_RESPONSE
    assert rec.status == 0


# --------------------------------------------- SQL obfuscation + misc --

def test_obfuscate_sql_literals():
    assert obfuscate_sql(b"SELECT * FROM t WHERE a = 'secret' AND b = 42") \
        == "SELECT * FROM t WHERE a = ? AND b = ?"
    assert obfuscate_sql(b"INSERT INTO t VALUES (1, 'x', 0x1F)") == \
        "INSERT INTO t VALUES (?, ?, ?)"
    assert obfuscate_sql(b"SELECT 1 -- comment\nFROM t") == \
        "SELECT ? FROM t"
    assert obfuscate_sql(b"SELECT /* hint */ col FROM tab1e2") == \
        "SELECT col FROM tab1e2"
    assert obfuscate_sql(b"UPDATE t SET s = 'it''s' WHERE i=1e5") == \
        "UPDATE t SET s = ? WHERE i=?"


def test_sql_verb():
    assert sql_verb(b"  select * from t") == "SELECT"
    assert sql_verb(b"INSERT INTO t") == "INSERT"


def test_all_extended_parsers_registered():
    protos = {p.proto for p in PARSERS}
    for want in (L7_TLS, L7_HTTP2, L7_KAFKA, L7_POSTGRESQL, L7_MONGODB,
                 L7_DUBBO, L7_MQTT, L7_AMQP, L7_NATS, L7_OPENWIRE,
                 L7_FASTCGI, L7_SOFARPC):
        assert want in protos, f"missing parser for proto {want}"


def test_extended_parsers_do_not_shadow_core():
    """HTTP/1, DNS, MySQL, Redis payloads still parse to core protocols."""
    from deepflow_tpu.agent.l7 import L7_DNS, L7_HTTP1, L7_MYSQL, L7_REDIS

    assert _dispatch(b"GET /x HTTP/1.1\r\nHost: a\r\n\r\n").proto == L7_HTTP1
    dns_q = struct.pack(">HHHHHH", 1, 0x0100, 1, 0, 0, 0) + \
        b"\x03www\x07example\x03com\x00" + struct.pack(">HH", 1, 1)
    assert _dispatch(dns_q, proto=17, pd=53).proto == L7_DNS
    mysql = b"\x0b\x00\x00\x00\x03SELECT 1xx"[:4 + 11]
    redis = b"*1\r\n$4\r\nPING\r\n"
    assert _dispatch(redis).proto == L7_REDIS


def test_random_bytes_do_not_crash():
    import numpy as np

    rng = np.random.default_rng(7)
    for _ in range(200):
        blob = rng.integers(0, 256, rng.integers(1, 300)).astype(
            np.uint8).tobytes()
        parse_payload(blob, proto=6, port_src=1234, port_dst=5678)


def _huff_encode(raw: bytes) -> bytes:
    """RFC 7541 Huffman bit-packing over the spec table — the single
    test-side encoder both huffman tests share."""
    from deepflow_tpu.agent.l7_ext import _HUFF_TABLE
    acc, nbits = 0, 0
    for ch in raw:
        code, ln = _HUFF_TABLE[ch]
        acc = (acc << ln) | code
        nbits += ln
    if not nbits:
        return b""
    pad = (8 - nbits % 8) % 8
    acc = (acc << pad) | ((1 << pad) - 1)
    return int.to_bytes(acc, (nbits + pad) // 8, "big")


def test_huffman_full_table_rare_symbols():
    """Round-3: the COMPLETE RFC 7541 table — header values with rare
    symbols (uppercase URLs, base64 ids with + / =) decode instead of
    falling back to hex placeholders."""
    def encode(s: str) -> bytes:
        return _huff_encode(s.encode("latin-1"))

    for s in ("/API/V2/Users?id=AbC+9/zZ==",
              "Mozilla/5.0 (X11; Linux x86_64) \"quoted\"",
              "\x00\x7f\xff high+low bytes \xe4\xb8\xad"):
        # latin-1 round trip: the table covers all 256 byte values
        raw = s.encode("latin-1", "replace").decode("latin-1")
        assert huffman_decode(encode(raw)) == raw, s


def test_hpack_dynamic_table_cross_frame():
    """Incremental-indexing entries persist across HEADERS frames on the
    same connection direction (RFC 7541 §2.3.2): frame 1 adds a literal,
    frame 2 references it by dynamic index 62."""
    from deepflow_tpu.agent.l7_ext import Http2Parser

    def h2_frame(block: bytes) -> bytes:
        return len(block).to_bytes(3, "big") + b"\x01\x04" + \
            b"\x00\x00\x00\x01" + block

    p = Http2Parser()
    ctx = dict(proto=6, port_src=5000, port_dst=80, ts_ns=0,
               ip_src=0x0A000001, ip_dst=0x0A000002)
    # frame 1: :method GET (static 2) + literal-with-indexing
    # :path /svc/a (name from static 4, value literal)
    blk1 = bytes([0x82]) + bytes([0x44]) + bytes([0x06]) + b"/svc/a"
    rec1 = p.parse(h2_frame(blk1), **ctx)
    assert rec1 is not None and rec1.endpoint == "GET /svc/a"
    # frame 2 (same direction): :method GET + dynamic index 62
    blk2 = bytes([0x82]) + bytes([0x80 | 62])
    rec2 = p.parse(h2_frame(blk2), **ctx)
    assert rec2 is not None and rec2.endpoint == "GET /svc/a"
    # a DIFFERENT connection must NOT see that table entry
    other = dict(ctx, port_src=6000)
    rec3 = p.parse(h2_frame(blk2), **other)
    assert rec3 is None or rec3.endpoint != "GET /svc/a"


def test_hpack_dynamic_table_eviction():
    """Entries evict at the size bound (name+value+32 each) and a
    dynamic table size update shrinks the bound."""
    from deepflow_tpu.agent.l7_ext import HpackDecoder
    d = HpackDecoder(max_size=100)
    d.decode(bytes([0x40, 0x03]) + b"aaa" + bytes([0x03]) + b"AAA")
    d.decode(bytes([0x40, 0x03]) + b"bbb" + bytes([0x03]) + b"BBB")
    d.decode(bytes([0x40, 0x03]) + b"ccc" + bytes([0x03]) + b"CCC")
    # 3 * (3+3+32) = 114 > 100 -> the oldest ('aaa') is gone
    assert d._entry(62) == ("ccc", "CCC")
    assert d._entry(63) == ("bbb", "BBB")
    assert d._entry(64) == ("", "")
    # size update to 0 flushes everything
    d.decode(bytes([0x20]))
    assert d._entry(62) == ("", "")


# ------------------------------------------------------------- Oracle --

def _tns(ptype, body):
    ln = 8 + len(body)
    return struct.pack(">HHBBH", ln, 0, ptype, 0, 0) + body


def test_oracle_tns_connect_and_accept():
    from deepflow_tpu.agent.l7_ext import L7_ORACLE

    desc = (b"(DESCRIPTION=(CONNECT_DATA=(SERVICE_NAME=orcl.prod)"
            b"(CID=(PROGRAM=sqlplus)))(ADDRESS=(HOST=db1)(PORT=1521)))")
    conn = _tns(1, b"\x01\x36\x01\x2c" + b"\x00" * 22 + desc)
    rec = _dispatch(conn, pd=1521)
    assert rec is not None and rec.proto == L7_ORACLE
    assert rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "CONNECT orcl.prod"
    acc = _tns(2, b"\x01\x36\x00\x00" + b"\x00" * 16)
    rec = _dispatch(acc, pd=1521)
    assert rec.proto == L7_ORACLE and rec.msg_type == MSG_RESPONSE
    assert rec.status == 0


def test_oracle_tns_refuse_and_oci_call():
    from deepflow_tpu.agent.l7_ext import L7_ORACLE

    ref = _tns(4, b"\x01\x01\x00\x10(ERR=12514)(DESCRIPTION=x)")
    rec = _dispatch(ref, pd=1521)
    assert rec.msg_type == MSG_RESPONSE and rec.status == 12514
    # DATA + user OCI function 0x5e with embedded SQL
    sql = b"SELECT name FROM users WHERE id = 7"
    data = _tns(6, b"\x00\x00" + b"\x03\x5e" + sql)
    rec = _dispatch(data, pd=1521)
    assert rec.proto == L7_ORACLE and rec.msg_type == MSG_REQUEST
    assert rec.endpoint.startswith("QUERY SELECT")
    assert "7" not in rec.endpoint          # literals obfuscated


def test_oracle_binds_and_binary_never_leak():
    """The TTI payload carries binary fields + bind values after the
    statement: nothing past the first non-printable byte may reach the
    endpoint (the sql_obfuscate PII contract)."""
    from deepflow_tpu.agent.l7_ext import L7_ORACLE

    sql = b"SELECT a FROM t WHERE e = :1"
    binds = b"\x00\x17\x02user@example.com\x01\x7f"
    data = _tns(6, b"\x00\x00" + b"\x03\x5e" + sql + binds)
    rec = _dispatch(data, pd=1521)
    assert rec.proto == L7_ORACLE
    assert "user@example.com" not in rec.endpoint
    assert all(0x20 <= ord(c) < 0x7F for c in rec.endpoint)
    assert len(rec.endpoint) <= 128


def test_hpack_roundtrip_property():
    """Property test: random header lists encoded with an in-test HPACK
    encoder (dynamic-table refs, incremental indexing, Huffman) decode
    back exactly through a stateful HpackDecoder pair — deep coverage of
    index arithmetic and eviction none of the fixed blocks reach."""
    import random

    from deepflow_tpu.agent.l7_ext import _HPACK_STATIC, HpackDecoder

    rnd = random.Random(0xBEEF)

    def hint(value, prefix, first_byte):
        if value < (1 << prefix) - 1:
            return bytes([first_byte | value])
        out = [first_byte | ((1 << prefix) - 1)]
        value -= (1 << prefix) - 1
        while value >= 0x80:
            out.append((value & 0x7F) | 0x80)
            value >>= 7
        out.append(value)
        return bytes(out)

    def hstr(s, huff):
        raw = s.encode("latin-1")
        if huff:
            raw = _huff_encode(raw)
            return hint(len(raw), 7, 0x80) + raw
        return hint(len(raw), 7, 0x00) + raw

    class Encoder:
        """Minimal spec-following encoder with its own dynamic table."""

        def __init__(self, max_size=256):   # small: forces eviction
            self.dyn = []
            self.size = 0
            self.max = max_size

        def _evict(self):
            while self.size > self.max and self.dyn:
                n, v = self.dyn.pop()
                self.size -= len(n) + len(v) + 32

        def encode(self, headers):
            out = b""
            for name, value in headers:
                # full match in static?
                static_full = next((i for i, (n, v)
                                    in _HPACK_STATIC.items()
                                    if n == name and v == value), None)
                dyn_full = next((i for i, (n, v)
                                 in enumerate(self.dyn)
                                 if n == name and v == value), None)
                if static_full is not None and rnd.random() < 0.5:
                    out += hint(static_full, 7, 0x80)
                    continue
                if dyn_full is not None and rnd.random() < 0.7:
                    out += hint(62 + dyn_full, 7, 0x80)
                    continue
                # literal with incremental indexing; name may be indexed
                name_idx = next((i for i, (n, _)
                                 in _HPACK_STATIC.items() if n == name),
                                None)
                if name_idx is None:
                    name_idx = next((62 + i for i, (n, _)
                                     in enumerate(self.dyn)
                                     if n == name), None)
                if name_idx is not None and rnd.random() < 0.7:
                    out += hint(name_idx, 6, 0x40)
                else:
                    out += hint(0, 6, 0x40)
                    out += hstr(name, rnd.random() < 0.5)
                out += hstr(value, rnd.random() < 0.5)
                self.dyn.insert(0, (name, value))
                self.size += len(name) + len(value) + 32
                self._evict()
            return out

    names = [":method", ":path", "x-trace", "content-type", "cookie"]
    values = ["GET", "/a", "/b/c?q=1", "abc123==", "Zm9vYmFy",
              "text/html; charset=UTF-8", "k=v; k2=\"v2\""]
    enc = Encoder()
    dec = HpackDecoder(max_size=256)
    for frame in range(40):
        headers = [(rnd.choice(names), rnd.choice(values))
                   for _ in range(rnd.randint(1, 6))]
        block = enc.encode(headers)
        got = dec.decode(block)
        assert got == headers, (frame, got, headers)


def test_tls_sessions_carry_is_tls_on_the_wire():
    """A packet-path session the TLS parser recognized must ship with
    the same is_tls bit the uprobe sources set — one query predicate
    covers both observation modes."""
    from deepflow_tpu.agent.l7 import L7_HTTP1
    from deepflow_tpu.agent.l7_ext import L7_TLS
    from deepflow_tpu.agent.trident import l7_session_message

    rec = {"proto": L7_TLS, "endpoint": "svc.example:443",
           "status": 0, "rrt_us": 120, "req_len": 0, "resp_len": 0}
    m = l7_session_message((1, 2, 40000, 443, 6), rec, 1_000_000, 7)
    assert m.flags & 1
    rec["proto"] = L7_HTTP1                # plaintext
    m = l7_session_message((1, 2, 40000, 80, 6), rec, 1_000_000, 7)
    assert m.flags & 1 == 0

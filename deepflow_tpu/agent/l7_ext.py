"""Extended L7 protocol parsers: TLS, HTTP/2+gRPC, Kafka, PostgreSQL,
MongoDB, Dubbo, MQTT, AMQP, NATS, OpenWire, FastCGI, SofaRPC.

Reference: agent/src/flow_generator/protocol_logs/{tls.rs, http.rs (+
plugins/http2 HPACK), mq/{kafka.rs, mqtt.rs, amqp.rs, openwire.rs,
nats.rs}, sql/{postgresql.rs, mongo.rs}, rpc/{dubbo.rs, sofa_rpc.rs,
fastcgi.rs}} — each a check_payload/parse_payload pair over the same
two-phase contract as l7.py. Protocol ids follow the reference
L7Protocol enum (agent/crates/public/src/l7_protocol.rs:36-73).

All parsers here are TCP-transported; they register into l7.PARSERS via
register_extended() (called from l7 import time), ordered so magic-byte
protocols (TLS, Dubbo, AMQP, OpenWire) check before the heuristic ones.
"""

from __future__ import annotations

from collections import OrderedDict, deque
import re
import struct
from typing import ClassVar, Dict, List, Optional, Tuple

from deepflow_tpu.agent.l7 import (MSG_REQUEST, MSG_RESPONSE, L7Record)
from deepflow_tpu.agent.sql_obfuscate import obfuscate_sql, sql_verb
from deepflow_tpu.utils.text import parse_int

L7_HTTP2 = 21
L7_DUBBO = 40
L7_GRPC = 41
L7_SOFARPC = 43
L7_FASTCGI = 44
L7_POSTGRESQL = 61
L7_MONGODB = 81
L7_KAFKA = 100
L7_MQTT = 101
L7_AMQP = 102
L7_OPENWIRE = 103
L7_NATS = 104
L7_TLS = 121


# ---------------------------------------------------------------------------
# TLS (reference: protocol_logs/tls.rs)
# ---------------------------------------------------------------------------

class TlsParser:
    """TLS record layer + ClientHello/ServerHello handshake headers.
    endpoint = SNI server name (requests); status carries the alert
    level on alert records."""

    proto: ClassVar[int] = L7_TLS

    def check(self, payload: bytes) -> bool:
        if len(payload) < 6 or payload[0] not in (0x14, 0x15, 0x16, 0x17):
            return False
        if payload[1] != 0x03 or payload[2] > 0x04:
            return False
        rec_len = struct.unpack_from(">H", payload, 3)[0]
        return 0 < rec_len <= (1 << 14) + 256

    def _sni(self, hello: bytes) -> str:
        """Walk ClientHello to the server_name extension (type 0)."""
        try:
            off = 34                                  # version + random
            off += 1 + hello[off]                     # session id
            cs_len = struct.unpack_from(">H", hello, off)[0]
            off += 2 + cs_len                         # cipher suites
            off += 1 + hello[off]                     # compression methods
            if off + 2 > len(hello):
                return ""
            ext_len = struct.unpack_from(">H", hello, off)[0]
            off += 2
            end = min(off + ext_len, len(hello))
            while off + 4 <= end:
                etype, elen = struct.unpack_from(">HH", hello, off)
                off += 4
                if etype == 0 and off + 5 <= end:     # server_name
                    name_len = struct.unpack_from(">H", hello, off + 3)[0]
                    return hello[off + 5:off + 5 + name_len] \
                        .decode("latin-1")
                off += elen
        except (IndexError, struct.error):
            pass
        return ""

    def parse(self, payload: bytes) -> Optional[L7Record]:
        rtype = payload[0]
        if rtype == 0x15 and len(payload) >= 7:        # alert
            return L7Record(self.proto, MSG_RESPONSE, endpoint="alert",
                            status=payload[5], resp_len=len(payload))
        if rtype == 0x17:                              # application data
            return None                                # not a log event
        if rtype != 0x16 or len(payload) < 9:
            return None
        hs_type = payload[5]
        body = payload[9:]
        if hs_type == 1:                               # ClientHello
            return L7Record(self.proto, MSG_REQUEST,
                            endpoint=self._sni(body),
                            req_len=len(payload))
        if hs_type == 2:                               # ServerHello
            return L7Record(self.proto, MSG_RESPONSE, status=0,
                            resp_len=len(payload))
        return None


# ---------------------------------------------------------------------------
# HTTP/2 + gRPC (reference: protocol_logs/http.rs:503 + plugins/http2)
# ---------------------------------------------------------------------------

# RFC 7541 Appendix B: the COMPLETE Huffman code table — (code, bits)
# for every byte 0..255 plus EOS(256). Spec constants, verified against
# the RFC Appendix C.4 test vectors (tests/test_l7_ext.py); with the
# full table no header value ever falls back to a hex placeholder.
_HUFF_TABLE = (
    (0x1ff8, 13), (0x7fffd8, 23), (0xfffffe2, 28), (0xfffffe3, 28),
    (0xfffffe4, 28), (0xfffffe5, 28), (0xfffffe6, 28), (0xfffffe7, 28),
    (0xfffffe8, 28), (0xffffea, 24), (0x3ffffffc, 30), (0xfffffe9, 28),
    (0xfffffea, 28), (0x3ffffffd, 30), (0xfffffeb, 28), (0xfffffec, 28),
    (0xfffffed, 28), (0xfffffee, 28), (0xfffffef, 28), (0xffffff0, 28),
    (0xffffff1, 28), (0xffffff2, 28), (0x3ffffffe, 30), (0xffffff3, 28),
    (0xffffff4, 28), (0xffffff5, 28), (0xffffff6, 28), (0xffffff7, 28),
    (0xffffff8, 28), (0xffffff9, 28), (0xffffffa, 28), (0xffffffb, 28),
    (0x14, 6), (0x3f8, 10), (0x3f9, 10), (0xffa, 12),
    (0x1ff9, 13), (0x15, 6), (0xf8, 8), (0x7fa, 11),
    (0x3fa, 10), (0x3fb, 10), (0xf9, 8), (0x7fb, 11),
    (0xfa, 8), (0x16, 6), (0x17, 6), (0x18, 6),
    (0x0, 5), (0x1, 5), (0x2, 5), (0x19, 6),
    (0x1a, 6), (0x1b, 6), (0x1c, 6), (0x1d, 6),
    (0x1e, 6), (0x1f, 6), (0x5c, 7), (0xfb, 8),
    (0x7ffc, 15), (0x20, 6), (0xffb, 12), (0x3fc, 10),
    (0x1ffa, 13), (0x21, 6), (0x5d, 7), (0x5e, 7),
    (0x5f, 7), (0x60, 7), (0x61, 7), (0x62, 7),
    (0x63, 7), (0x64, 7), (0x65, 7), (0x66, 7),
    (0x67, 7), (0x68, 7), (0x69, 7), (0x6a, 7),
    (0x6b, 7), (0x6c, 7), (0x6d, 7), (0x6e, 7),
    (0x6f, 7), (0x70, 7), (0x71, 7), (0x72, 7),
    (0xfc, 8), (0x73, 7), (0xfd, 8), (0x1ffb, 13),
    (0x7fff0, 19), (0x1ffc, 13), (0x3ffc, 14), (0x22, 6),
    (0x7ffd, 15), (0x3, 5), (0x23, 6), (0x4, 5),
    (0x24, 6), (0x5, 5), (0x25, 6), (0x26, 6),
    (0x27, 6), (0x6, 5), (0x74, 7), (0x75, 7),
    (0x28, 6), (0x29, 6), (0x2a, 6), (0x7, 5),
    (0x2b, 6), (0x76, 7), (0x2c, 6), (0x8, 5),
    (0x9, 5), (0x2d, 6), (0x77, 7), (0x78, 7),
    (0x79, 7), (0x7a, 7), (0x7b, 7), (0x7ffe, 15),
    (0x7fc, 11), (0x3ffd, 14), (0x1ffd, 13), (0xffffffc, 28),
    (0xfffe6, 20), (0x3fffd2, 22), (0xfffe7, 20), (0xfffe8, 20),
    (0x3fffd3, 22), (0x3fffd4, 22), (0x3fffd5, 22), (0x7fffd9, 23),
    (0x3fffd6, 22), (0x7fffda, 23), (0x7fffdb, 23), (0x7fffdc, 23),
    (0x7fffdd, 23), (0x7fffde, 23), (0xffffeb, 24), (0x7fffdf, 23),
    (0xffffec, 24), (0xffffed, 24), (0x3fffd7, 22), (0x7fffe0, 23),
    (0xffffee, 24), (0x7fffe1, 23), (0x7fffe2, 23), (0x7fffe3, 23),
    (0x7fffe4, 23), (0x1fffdc, 21), (0x3fffd8, 22), (0x7fffe5, 23),
    (0x3fffd9, 22), (0x7fffe6, 23), (0x7fffe7, 23), (0xffffef, 24),
    (0x3fffda, 22), (0x1fffdd, 21), (0xfffe9, 20), (0x3fffdb, 22),
    (0x3fffdc, 22), (0x7fffe8, 23), (0x7fffe9, 23), (0x1fffde, 21),
    (0x7fffea, 23), (0x3fffdd, 22), (0x3fffde, 22), (0xfffff0, 24),
    (0x1fffdf, 21), (0x3fffdf, 22), (0x7fffeb, 23), (0x7fffec, 23),
    (0x1fffe0, 21), (0x1fffe1, 21), (0x3fffe0, 22), (0x1fffe2, 21),
    (0x7fffed, 23), (0x3fffe1, 22), (0x7fffee, 23), (0x7fffef, 23),
    (0xfffea, 20), (0x3fffe2, 22), (0x3fffe3, 22), (0x3fffe4, 22),
    (0x7ffff0, 23), (0x3fffe5, 22), (0x3fffe6, 22), (0x7ffff1, 23),
    (0x3ffffe0, 26), (0x3ffffe1, 26), (0xfffeb, 20), (0x7fff1, 19),
    (0x3fffe7, 22), (0x7ffff2, 23), (0x3fffe8, 22), (0x1ffffec, 25),
    (0x3ffffe2, 26), (0x3ffffe3, 26), (0x3ffffe4, 26), (0x7ffffde, 27),
    (0x7ffffdf, 27), (0x3ffffe5, 26), (0xfffff1, 24), (0x1ffffed, 25),
    (0x7fff2, 19), (0x1fffe3, 21), (0x3ffffe6, 26), (0x7ffffe0, 27),
    (0x7ffffe1, 27), (0x3ffffe7, 26), (0x7ffffe2, 27), (0xfffff2, 24),
    (0x1fffe4, 21), (0x1fffe5, 21), (0x3ffffe8, 26), (0x3ffffe9, 26),
    (0xffffffd, 28), (0x7ffffe3, 27), (0x7ffffe4, 27), (0x7ffffe5, 27),
    (0xfffec, 20), (0xfffff3, 24), (0xfffed, 20), (0x1fffe6, 21),
    (0x3fffe9, 22), (0x1fffe7, 21), (0x1fffe8, 21), (0x7ffff3, 23),
    (0x3fffea, 22), (0x3fffeb, 22), (0x1ffffee, 25), (0x1ffffef, 25),
    (0xfffff4, 24), (0xfffff5, 24), (0x3ffffea, 26), (0x7ffff4, 23),
    (0x3ffffeb, 26), (0x7ffffe6, 27), (0x3ffffec, 26), (0x3ffffed, 26),
    (0x7ffffe7, 27), (0x7ffffe8, 27), (0x7ffffe9, 27), (0x7ffffea, 27),
    (0x7ffffeb, 27), (0xffffffe, 28), (0x7ffffec, 27), (0x7ffffed, 27),
    (0x7ffffee, 27), (0x7ffffef, 27), (0x7fffff0, 27), (0x3ffffee, 26),
    (0x3fffffff, 30),
)

_HUFF_BY_LEN: Dict[int, Dict[int, int]] = {}
for _sym, (_code, _bits) in enumerate(_HUFF_TABLE):
    _HUFF_BY_LEN.setdefault(_bits, {})[_code] = _sym
_HUFF_LENS = tuple(sorted(_HUFF_BY_LEN))
_EOS = 256


def huffman_decode(data: bytes) -> Optional[str]:
    """HPACK Huffman string decode (RFC 7541 §5.2); None on EOS in the
    stream or non-ones padding — both are coding errors."""
    out = []
    acc = 0
    nbits = 0
    for byte in data:
        acc = (acc << 8) | byte
        nbits += 8
        while nbits >= 5:
            matched = False
            for ln in _HUFF_LENS:
                if ln > nbits:
                    break
                code = (acc >> (nbits - ln)) & ((1 << ln) - 1)
                sym = _HUFF_BY_LEN[ln].get(code)
                if sym is not None:
                    if sym == _EOS:       # explicit EOS is an error
                        return None
                    out.append(chr(sym))
                    nbits -= ln
                    acc &= (1 << nbits) - 1
                    matched = True
                    break
            if not matched:
                break
    # trailing bits must be all-ones padding (EOS prefix), < 8 of them
    if nbits > 7 or (nbits and (acc & ((1 << nbits) - 1))
                     != (1 << nbits) - 1):
        return None
    return "".join(out)


# HPACK static table entries used for request/response reconstruction
# (RFC 7541 Appendix A; indices 1-61)
_HPACK_STATIC = {
    1: (":authority", ""), 2: (":method", "GET"), 3: (":method", "POST"),
    4: (":path", "/"), 5: (":path", "/index.html"), 6: (":scheme", "http"),
    7: (":scheme", "https"), 8: (":status", "200"), 9: (":status", "204"),
    10: (":status", "206"), 11: (":status", "304"), 12: (":status", "400"),
    13: (":status", "404"), 14: (":status", "500"),
    15: ("accept-charset", ""), 16: ("accept-encoding", "gzip, deflate"),
    17: ("accept-language", ""), 18: ("accept-ranges", ""),
    19: ("accept", ""), 20: ("access-control-allow-origin", ""),
    21: ("age", ""), 22: ("allow", ""), 23: ("authorization", ""),
    24: ("cache-control", ""), 25: ("content-disposition", ""),
    26: ("content-encoding", ""), 27: ("content-language", ""),
    28: ("content-length", ""), 29: ("content-location", ""),
    30: ("content-range", ""), 31: ("content-type", ""), 32: ("cookie", ""),
    33: ("date", ""), 34: ("etag", ""), 35: ("expect", ""),
    36: ("expires", ""), 37: ("from", ""), 38: ("host", ""),
    39: ("if-match", ""), 40: ("if-modified-since", ""),
    41: ("if-none-match", ""), 42: ("if-range", ""),
    43: ("if-unmodified-since", ""), 44: ("last-modified", ""),
    45: ("link", ""), 46: ("location", ""), 47: ("max-forwards", ""),
    48: ("proxy-authenticate", ""), 49: ("proxy-authorization", ""),
    50: ("range", ""), 51: ("referer", ""), 52: ("refresh", ""),
    53: ("retry-after", ""), 54: ("server", ""), 55: ("set-cookie", ""),
    56: ("strict-transport-security", ""), 57: ("transfer-encoding", ""),
    58: ("user-agent", ""), 59: ("vary", ""), 60: ("via", ""),
    61: ("www-authenticate", ""),
}

_H2_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"


def _hpack_int(data: bytes, off: int, prefix: int) -> Tuple[int, int]:
    """Decode an HPACK prefix integer; returns (value, next_offset)."""
    mask = (1 << prefix) - 1
    v = data[off] & mask
    off += 1
    if v < mask:
        return v, off
    shift = 0
    while off < len(data):
        b = data[off]
        off += 1
        v += (b & 0x7F) << shift
        shift += 7
        if not (b & 0x80):
            break
    return v, off


def _hpack_str(data: bytes, off: int) -> Tuple[str, int]:
    huff = bool(data[off] & 0x80)
    ln, off = _hpack_int(data, off, 7)
    raw = data[off:off + ln]
    off += ln
    if huff:
        s = huffman_decode(raw)
        return (s if s is not None else raw.hex()), off
    return raw.decode("latin-1", "replace"), off


class HpackDecoder:
    """RFC 7541-complete HPACK decoder: static table + a real dynamic
    table with size-based eviction (§4.2; entry cost name+value+32).
    One instance per connection DIRECTION — HPACK state is per sender.
    The reference's http2 plugin carries equivalent per-session table
    state (agent/plugins/http2)."""

    _HARD_MAX = 1 << 16

    def __init__(self, max_size: int = 4096) -> None:
        self._dyn: "deque[Tuple[str, str]]" = deque()
        self._size = 0
        self._max = max_size

    def _entry(self, idx: int) -> Tuple[str, str]:
        if idx in _HPACK_STATIC:
            return _HPACK_STATIC[idx]
        d = idx - 62
        if 0 <= d < len(self._dyn):
            return self._dyn[d]          # newest-first (§2.3.2)
        return ("", "")

    def _add(self, name: str, val: str) -> None:
        self._dyn.appendleft((name, val))
        self._size += len(name) + len(val) + 32
        self._evict()

    def _evict(self) -> None:
        while self._size > self._max and self._dyn:
            n, v = self._dyn.pop()
            self._size -= len(n) + len(v) + 32

    def decode(self, block: bytes,
               max_headers: int = 64) -> List[Tuple[str, str]]:
        """Decode one header block. The WHOLE block is always consumed —
        a stateful decoder that stopped early (header cap) would let its
        dynamic table silently diverge from the sender's; past the cap,
        fields still process for their table side effects and are just
        not reported. A name index pointing at a missing dynamic entry
        (evicted here / lost packet) keeps WIRE SYNC: only the value
        string follows on the wire, so only the value is read and the
        name stays empty — never re-interpret the value as a name."""
        out: List[Tuple[str, str]] = []
        off = 0
        try:
            while off < len(block):
                b = block[off]
                if b & 0x80:                      # indexed field
                    idx, off = _hpack_int(block, off, 7)
                    if len(out) < max_headers:
                        out.append(self._entry(idx))
                elif b & 0x40:                    # literal, incremental idx
                    idx, off = _hpack_int(block, off, 6)
                    if idx:
                        name = self._entry(idx)[0]
                    else:
                        name, off = _hpack_str(block, off)
                    val, off = _hpack_str(block, off)
                    self._add(name, val)
                    if len(out) < max_headers:
                        out.append((name, val))
                elif b & 0x20:                    # dynamic table size upd
                    sz, off = _hpack_int(block, off, 5)
                    self._max = min(sz, self._HARD_MAX)
                    self._evict()
                else:                             # literal, no indexing
                    idx, off = _hpack_int(block, off, 4)
                    if idx:
                        name = self._entry(idx)[0]
                    else:
                        name, off = _hpack_str(block, off)
                    val, off = _hpack_str(block, off)
                    if len(out) < max_headers:
                        out.append((name, val))
        except (IndexError, struct.error):
            pass
        return out


def hpack_headers(block: bytes, max_headers: int = 64) -> List[Tuple[str, str]]:
    """Stateless HPACK decode: a fresh table per block. Incremental
    entries still resolve WITHIN the block; cross-frame references need
    the per-connection decoder (Http2Parser keeps one per direction)."""
    return HpackDecoder().decode(block, max_headers)


class Http2Parser:
    """HTTP/2 frames; HEADERS blocks decode via HPACK with a REAL
    per-connection-direction dynamic table (LRU of HpackDecoders keyed
    by the dispatch 4-tuple — cross-packet indexed references resolve).
    gRPC calls (content-type application/grpc*) report as
    L7Protocol.Grpc like the reference."""

    proto: ClassVar[int] = L7_HTTP2
    wants_ctx: ClassVar[bool] = True

    _FRAME_HEADERS = 0x1
    _MAX_CONNS = 512

    def __init__(self) -> None:
        self._conns: "OrderedDict[tuple, HpackDecoder]" = OrderedDict()

    def _decoder(self, key) -> HpackDecoder:
        if key is None:
            return HpackDecoder()        # ctx-less callers: stateless
        d = self._conns.get(key)
        if d is None:
            d = HpackDecoder()
            self._conns[key] = d
            while len(self._conns) > self._MAX_CONNS:
                self._conns.popitem(last=False)
        else:
            self._conns.move_to_end(key)
        return d

    def check(self, payload: bytes, proto=None, port_src: int = 0,
              port_dst: int = 0, ts_ns: int = 0, ip_src: int = 0,
              ip_dst: int = 0, ip_version: int = 4) -> bool:
        if payload.startswith(_H2_PREFACE):
            return True
        if len(payload) < 9:
            return False
        ln = int.from_bytes(payload[:3], "big")
        ftype = payload[3]
        # plausible first frame: SETTINGS(4)/HEADERS(1)/WINDOW_UPDATE(8)
        return ftype in (0x1, 0x4, 0x8) and ln <= 1 << 14 and \
            9 + ln <= len(payload) + (1 << 14)

    def parse(self, payload: bytes, proto=None, port_src: int = 0,
              port_dst: int = 0, ts_ns: int = 0, ip_src: int = 0,
              ip_dst: int = 0,
              ip_version: int = 4) -> Optional[L7Record]:
        # direction-scoped HPACK state: the sender's table
        key = ((ip_src, ip_dst, port_src, port_dst)
               if (ip_src or ip_dst or port_src or port_dst) else None)
        dec = self._decoder(key)
        off = 0
        if payload.startswith(_H2_PREFACE):
            off = len(_H2_PREFACE)
        # EVERY headers frame in the payload must be decoded — returning
        # at the first record would skip later frames' incremental-index
        # entries and silently desync the connection's dynamic table
        # from the sender's; the first record found is reported.
        rec: Optional[L7Record] = None
        while off + 9 <= len(payload):
            ln = int.from_bytes(payload[off:off + 3], "big")
            ftype = payload[off + 3]
            flags = payload[off + 4]
            body = payload[off + 9:off + 9 + ln]
            off += 9 + ln
            if ftype != self._FRAME_HEADERS:
                continue
            if flags & 0x8:                        # PADDED
                body = body[1:len(body) - body[0]] if body else body
            if flags & 0x20:                       # PRIORITY
                body = body[5:]
            # first occurrence wins on duplicates — the same proxy-chain
            # semantics parse_http_headers documents for HTTP/1, so one
            # request yields the same client_ip/trace id on either version
            hdrs: dict = {}
            for hk, hv in dec.decode(body):
                hdrs.setdefault(hk, hv)
            if rec is not None:
                continue                           # state only
            from deepflow_tpu.agent import trace_context
            ids = trace_context.extract(hdrs)
            status = hdrs.get(":status")
            if status is not None:
                code = parse_int(status)
                rec = L7Record(self.proto, MSG_RESPONSE, status=code,
                               resp_len=len(payload), version="2",
                               trace_id=ids["trace_id"],
                               span_id=ids["span_id"],
                               x_request_id=ids["x_request_id"])
                continue
            method = hdrs.get(":method")
            if method is not None:
                full_path = hdrs.get(":path", "")
                path = full_path.split("?", 1)[0]
                proto_ = self.proto
                if hdrs.get("content-type", "").startswith(
                        "application/grpc"):
                    proto_ = L7_GRPC
                rec = L7Record(proto_, MSG_REQUEST,
                               endpoint=f"{method} {path}",
                               req_len=len(payload),
                               req_type=method,
                               domain=hdrs.get(":authority", ""),
                               resource=full_path, version="2",
                               user_agent=hdrs.get("user-agent", ""),
                               referer=hdrs.get("referer", ""),
                               trace_id=ids["trace_id"],
                               span_id=ids["span_id"],
                               x_request_id=ids["x_request_id"],
                               client_ip=ids["client_ip"])
        return rec


# ---------------------------------------------------------------------------
# Kafka (reference: protocol_logs/mq/kafka.rs)
# ---------------------------------------------------------------------------

_KAFKA_APIS = {
    0: "Produce", 1: "Fetch", 2: "ListOffsets", 3: "Metadata",
    8: "OffsetCommit", 9: "OffsetFetch", 10: "FindCoordinator",
    11: "JoinGroup", 12: "Heartbeat", 13: "LeaveGroup", 14: "SyncGroup",
    15: "DescribeGroups", 16: "ListGroups", 17: "SaslHandshake",
    18: "ApiVersions", 19: "CreateTopics", 20: "DeleteTopics",
}


class KafkaParser:
    """Kafka request/response headers. Requests carry api_key + client_id;
    responses are matched FIFO per flow (correlation id is recorded as
    status 0 — error codes live per-partition in the body)."""

    proto: ClassVar[int] = L7_KAFKA
    _MAX_API = 67

    def check(self, payload: bytes) -> bool:
        if len(payload) < 12:
            return False
        ln = struct.unpack_from(">i", payload)[0]
        if not (8 <= ln <= 1 << 24):
            return False
        api_key, api_ver = struct.unpack_from(">hh", payload, 4)
        if 0 <= api_key <= self._MAX_API and 0 <= api_ver <= 20:
            return True
        # response: length + correlation id only — accept when the frame
        # length matches the payload exactly (strong signal)
        return ln + 4 == len(payload)

    def parse(self, payload: bytes) -> Optional[L7Record]:
        ln = struct.unpack_from(">i", payload)[0]
        api_key, api_ver = struct.unpack_from(">hh", payload, 4)
        if 0 <= api_key <= self._MAX_API and 0 <= api_ver <= 20 \
                and len(payload) >= 14:
            client_len = struct.unpack_from(">h", payload, 12)[0]
            client = ""
            if 0 < client_len <= 255 and 14 + client_len <= len(payload):
                client = payload[14:14 + client_len].decode("latin-1",
                                                            "replace")
            api = _KAFKA_APIS.get(api_key, f"Api{api_key}")
            ep = f"{api}" + (f" {client}" if client else "")
            return L7Record(self.proto, MSG_REQUEST, endpoint=ep,
                            req_len=len(payload))
        if ln + 4 == len(payload):
            return L7Record(self.proto, MSG_RESPONSE, status=0,
                            resp_len=len(payload))
        return None


# ---------------------------------------------------------------------------
# PostgreSQL (reference: protocol_logs/sql/postgresql.rs)
# ---------------------------------------------------------------------------

class PostgresParser:
    """PostgreSQL extended/simple protocol messages. Query statements are
    obfuscated (sql_obfuscate.py) before becoming the endpoint."""

    proto: ClassVar[int] = L7_POSTGRESQL
    _REQ = frozenset(b"QPBEDCFfSX")
    _RESP = frozenset(b"RSKZTDCEINV123nst")

    def check(self, payload: bytes) -> bool:
        if len(payload) < 5:
            return False
        t = payload[0]
        if t not in self._REQ and t not in self._RESP:
            # startup: int32 len + protocol version 3.0
            if len(payload) >= 8:
                ln, ver = struct.unpack_from(">ii", payload)
                return ln == len(payload) and ver == 0x0003_0000
            return False
        ln = struct.unpack_from(">i", payload, 1)[0]
        return 4 <= ln <= (1 << 24)

    def parse(self, payload: bytes) -> Optional[L7Record]:
        t = payload[0:1]
        if t == b"Q" and len(payload) > 5:            # simple query
            stmt = payload[5:].rstrip(b"\x00")
            return L7Record(
                self.proto, MSG_REQUEST,
                endpoint=f"{sql_verb(stmt)} {obfuscate_sql(stmt)}"[:128],
                req_len=len(payload))
        if t == b"P" and len(payload) > 5:            # Parse (prepared)
            body = payload[5:]
            nul = body.find(b"\x00")                  # statement name
            stmt = body[nul + 1:body.find(b"\x00", nul + 1)] \
                if nul >= 0 else b""
            return L7Record(
                self.proto, MSG_REQUEST,
                endpoint=f"{sql_verb(stmt)} {obfuscate_sql(stmt)}"[:128],
                req_len=len(payload))
        if t in (b"B", b"E", b"D", b"C", b"F", b"S", b"X"):
            return L7Record(self.proto, MSG_REQUEST, endpoint="",
                            req_len=len(payload))
        if t in (b"T", b"Z", b"K", b"R", b"I", b"n", b"s", b"1", b"2",
                 b"3", b"V"):
            return L7Record(self.proto, MSG_RESPONSE, status=0,
                            resp_len=len(payload))
        if len(payload) >= 8 and \
                struct.unpack_from(">i", payload, 0)[0] == len(payload):
            return L7Record(self.proto, MSG_REQUEST, endpoint="startup",
                            req_len=len(payload))
        return None


class PostgresErrorParser:
    """ErrorResponse ('E') conflicts with Execute ('E' request); split so
    server->client error frames rank as responses with status=1. The
    session layer orients by msg_type, so a dedicated parser keyed on the
    severity field keeps the two apart."""

    proto: ClassVar[int] = L7_POSTGRESQL

    def check(self, payload: bytes) -> bool:
        return len(payload) > 6 and payload[0:1] == b"E" and \
            payload[5:6] == b"S"  # severity field marker

    def parse(self, payload: bytes) -> Optional[L7Record]:
        sev_end = payload.find(b"\x00", 6)
        severity = payload[6:sev_end].decode("latin-1", "replace") \
            if sev_end > 0 else ""
        status = 1 if severity in ("ERROR", "FATAL", "PANIC") else 0
        return L7Record(self.proto, MSG_RESPONSE, endpoint=severity,
                        status=status, resp_len=len(payload))


# ---------------------------------------------------------------------------
# MongoDB (reference: protocol_logs/sql/mongo.rs)
# ---------------------------------------------------------------------------

class MongoParser:
    """Mongo wire protocol: OP_MSG (2013) / OP_QUERY (2004) / OP_REPLY.
    endpoint = the command name (first BSON key of section 0)."""

    proto: ClassVar[int] = L7_MONGODB
    _OPS = {1: "OP_REPLY", 2004: "OP_QUERY", 2005: "OP_GET_MORE",
            2010: "OP_COMMAND", 2011: "OP_COMMANDREPLY", 2013: "OP_MSG"}

    def check(self, payload: bytes) -> bool:
        if len(payload) < 16:
            return False
        msg_len, _req, _resp, opcode = struct.unpack_from("<iiii", payload)
        return 16 <= msg_len <= (1 << 25) and opcode in self._OPS

    @staticmethod
    def _first_bson_key(doc: bytes) -> str:
        if len(doc) < 5:
            return ""
        etype = doc[4]
        if etype == 0:
            return ""
        end = doc.find(b"\x00", 5)
        return doc[5:end].decode("latin-1", "replace") if end > 0 else ""

    def parse(self, payload: bytes) -> Optional[L7Record]:
        _len, _req, resp_to, opcode = struct.unpack_from("<iiii", payload)
        is_resp = resp_to != 0 or opcode in (1, 2011)
        cmd = ""
        if opcode == 2013 and len(payload) >= 21:     # OP_MSG
            # flagBits u32 then section kind 0 + BSON
            if payload[20] == 0:
                cmd = self._first_bson_key(payload[21:])
        elif opcode == 2004:                          # OP_QUERY
            # flags u32, then cstring collection name
            end = payload.find(b"\x00", 20)
            if end > 0:
                cmd = payload[20:end].decode("latin-1", "replace")
        if is_resp:
            return L7Record(self.proto, MSG_RESPONSE, endpoint=cmd,
                            status=0, resp_len=len(payload))
        return L7Record(self.proto, MSG_REQUEST, endpoint=cmd,
                        req_len=len(payload))


# ---------------------------------------------------------------------------
# Dubbo (reference: protocol_logs/rpc/dubbo.rs)
# ---------------------------------------------------------------------------

class DubboParser:
    """Dubbo framed protocol (magic 0xdabb). Hessian2-serialized request
    bodies open with small strings: dubbo version, service path, service
    version, method — parsed as the length-prefixed run the reference's
    hessian walker reads."""

    proto: ClassVar[int] = L7_DUBBO

    def check(self, payload: bytes) -> bool:
        return len(payload) >= 16 and payload[:2] == b"\xda\xbb"

    @staticmethod
    def _hessian_strings(body: bytes, limit: int = 4) -> List[str]:
        out: List[str] = []
        off = 0
        while off < len(body) and len(out) < limit:
            b = body[off]
            if b <= 0x1F:                   # short utf8 string
                s = body[off + 1:off + 1 + b]
                if len(s) < b:
                    break
                out.append(s.decode("utf-8", "replace"))
                off += 1 + b
            elif 0x30 <= b <= 0x33 and off + 1 < len(body):  # medium str
                ln = ((b - 0x30) << 8) + body[off + 1]
                s = body[off + 2:off + 2 + ln]
                if len(s) < ln:
                    break
                out.append(s.decode("utf-8", "replace"))
                off += 2 + ln
            else:
                break
        return out

    def parse(self, payload: bytes) -> Optional[L7Record]:
        flags, status = payload[2], payload[3]
        is_req = bool(flags & 0x80)
        is_event = bool(flags & 0x20)
        if is_event:
            return None                       # heartbeats aren't log rows
        if is_req:
            strings = self._hessian_strings(payload[16:])
            ep = ""
            if len(strings) >= 4:
                ep = f"{strings[1]}.{strings[3]}"      # service.method
            elif len(strings) >= 2:
                ep = strings[1]
            return L7Record(self.proto, MSG_REQUEST, endpoint=ep,
                            req_len=len(payload))
        # response: status 20 = OK (reference maps others to error)
        return L7Record(self.proto, MSG_RESPONSE,
                        status=0 if status == 20 else 1,
                        resp_len=len(payload))


# ---------------------------------------------------------------------------
# MQTT (reference: protocol_logs/mq/mqtt.rs)
# ---------------------------------------------------------------------------

class MqttParser:
    """MQTT 3.1/3.1.1/5 control packets. endpoint = topic (PUBLISH) or
    client id (CONNECT)."""

    proto: ClassVar[int] = L7_MQTT
    _REQ_TYPES = {1: "CONNECT", 3: "PUBLISH", 8: "SUBSCRIBE",
                  10: "UNSUBSCRIBE", 12: "PINGREQ", 14: "DISCONNECT"}
    _RESP_TYPES = {2: "CONNACK", 4: "PUBACK", 9: "SUBACK",
                   11: "UNSUBACK", 13: "PINGRESP"}

    @staticmethod
    def _remaining_len(payload: bytes) -> Tuple[int, int]:
        """(value, header_len) of the MQTT varint; (-1, 0) on overflow."""
        v = 0
        for i in range(1, min(5, len(payload))):
            b = payload[i]
            v |= (b & 0x7F) << (7 * (i - 1))
            if not (b & 0x80):
                return v, i + 1
        return -1, 0

    def check(self, payload: bytes) -> bool:
        if len(payload) < 2:
            return False
        ptype = payload[0] >> 4
        if ptype == 0 or ptype == 15:
            return False
        rl, hl = self._remaining_len(payload)
        if rl < 0 or hl + rl != len(payload):
            return False
        if ptype == 1:                        # CONNECT: protocol name
            return payload[hl + 2:hl + 6] in (b"MQTT", b"MQIs")
        return True

    def parse(self, payload: bytes) -> Optional[L7Record]:
        ptype = payload[0] >> 4
        rl, hl = self._remaining_len(payload)
        if ptype == 1:                         # CONNECT
            name_len = struct.unpack_from(">H", payload, hl)[0]
            off = hl + 2 + name_len + 4        # + version + flags + keepal
            cid = ""
            if off + 2 <= len(payload):
                cid_len = struct.unpack_from(">H", payload, off)[0]
                cid = payload[off + 2:off + 2 + cid_len] \
                    .decode("latin-1", "replace")
            return L7Record(self.proto, MSG_REQUEST, endpoint=cid,
                            req_len=len(payload))
        if ptype == 3:                         # PUBLISH
            tlen = struct.unpack_from(">H", payload, hl)[0]
            topic = payload[hl + 2:hl + 2 + tlen].decode("latin-1",
                                                         "replace")
            return L7Record(self.proto, MSG_REQUEST, endpoint=topic,
                            req_len=len(payload))
        if ptype == 2:                         # CONNACK: return code
            code = payload[hl + 1] if hl + 1 < len(payload) else 0
            return L7Record(self.proto, MSG_RESPONSE, status=code,
                            resp_len=len(payload))
        if ptype in self._RESP_TYPES:
            return L7Record(self.proto, MSG_RESPONSE, status=0,
                            resp_len=len(payload))
        if ptype in self._REQ_TYPES:
            return L7Record(self.proto, MSG_REQUEST,
                            endpoint=self._REQ_TYPES[ptype],
                            req_len=len(payload))
        return None


# ---------------------------------------------------------------------------
# AMQP 0-9-1 (reference: protocol_logs/mq/amqp.rs)
# ---------------------------------------------------------------------------

_AMQP_METHODS = {
    (10, 10): "connection.start", (10, 11): "connection.start-ok",
    (10, 30): "connection.tune", (10, 31): "connection.tune-ok",
    (10, 40): "connection.open", (10, 41): "connection.open-ok",
    (10, 50): "connection.close", (10, 51): "connection.close-ok",
    (20, 10): "channel.open", (20, 11): "channel.open-ok",
    (20, 40): "channel.close", (20, 41): "channel.close-ok",
    (40, 10): "exchange.declare", (40, 11): "exchange.declare-ok",
    (50, 10): "queue.declare", (50, 11): "queue.declare-ok",
    (50, 20): "queue.bind", (50, 21): "queue.bind-ok",
    (60, 10): "basic.qos", (60, 11): "basic.qos-ok",
    (60, 20): "basic.consume", (60, 21): "basic.consume-ok",
    (60, 40): "basic.publish", (60, 50): "basic.return",
    (60, 60): "basic.deliver", (60, 70): "basic.get",
    (60, 71): "basic.get-ok", (60, 80): "basic.ack",
}


class AmqpParser:
    proto: ClassVar[int] = L7_AMQP

    def check(self, payload: bytes) -> bool:
        if payload.startswith(b"AMQP\x00"):
            return True
        if len(payload) < 8 or payload[0] not in (1, 2, 3, 8):
            return False
        size = struct.unpack_from(">I", payload, 3)[0]
        end = 7 + size
        return end < len(payload) + 1 and size < (1 << 24) and \
            (end >= len(payload) or payload[end] == 0xCE)

    def parse(self, payload: bytes) -> Optional[L7Record]:
        if payload.startswith(b"AMQP\x00"):
            return L7Record(self.proto, MSG_REQUEST,
                            endpoint="protocol-header",
                            req_len=len(payload))
        ftype = payload[0]
        if ftype != 1:                         # content header/body frames
            return None
        cls_id, meth_id = struct.unpack_from(">HH", payload, 7)
        name = _AMQP_METHODS.get((cls_id, meth_id),
                                 f"{cls_id}.{meth_id}")
        # -ok/deliver/return frames travel server->client
        is_resp = name.endswith("-ok") or name in ("basic.deliver",
                                                   "basic.return")
        if is_resp:
            return L7Record(self.proto, MSG_RESPONSE, endpoint=name,
                            status=0, resp_len=len(payload))
        return L7Record(self.proto, MSG_REQUEST, endpoint=name,
                        req_len=len(payload))


# ---------------------------------------------------------------------------
# NATS (reference: protocol_logs/mq/nats.rs)
# ---------------------------------------------------------------------------

class NatsParser:
    proto: ClassVar[int] = L7_NATS
    _REQ = (b"PUB ", b"SUB ", b"UNSUB ", b"CONNECT ", b"HPUB ")
    _RESP = (b"MSG ", b"HMSG ", b"INFO ", b"+OK", b"-ERR", b"PONG")

    def check(self, payload: bytes) -> bool:
        return payload.startswith(self._REQ + self._RESP + (b"PING",))

    def parse(self, payload: bytes) -> Optional[L7Record]:
        line, _, _ = payload.partition(b"\r\n")
        parts = line.decode("latin-1", "replace").split(" ")
        verb = parts[0]
        if verb in ("PUB", "HPUB", "SUB", "UNSUB"):
            subject = parts[1] if len(parts) > 1 else ""
            return L7Record(self.proto, MSG_REQUEST,
                            endpoint=f"{verb} {subject}",
                            req_len=len(payload))
        if verb in ("CONNECT", "PING"):
            return L7Record(self.proto, MSG_REQUEST, endpoint=verb,
                            req_len=len(payload))
        if verb in ("MSG", "HMSG"):
            subject = parts[1] if len(parts) > 1 else ""
            return L7Record(self.proto, MSG_RESPONSE,
                            endpoint=f"MSG {subject}",
                            resp_len=len(payload))
        if verb == "-ERR":
            return L7Record(self.proto, MSG_RESPONSE, status=1,
                            resp_len=len(payload))
        return L7Record(self.proto, MSG_RESPONSE, status=0,
                        resp_len=len(payload))


# ---------------------------------------------------------------------------
# OpenWire / ActiveMQ (reference: protocol_logs/mq/openwire.rs)
# ---------------------------------------------------------------------------

class OpenWireParser:
    """Length-prefixed OpenWire commands; WIREFORMAT_INFO carries the
    ActiveMQ magic. Producer/consumer data types from the OpenWire v12
    command ids the reference handles."""

    proto: ClassVar[int] = L7_OPENWIRE
    _TYPES = {1: "WireFormatInfo", 2: "BrokerInfo", 3: "ConnectionInfo",
              4: "SessionInfo", 5: "ConsumerInfo", 6: "ProducerInfo",
              23: "Message", 24: "ActiveMQBytesMessage",
              25: "ActiveMQMapMessage", 27: "ActiveMQTextMessage",
              30: "Response", 31: "ExceptionResponse",
              10: "KeepAliveInfo", 11: "ShutdownInfo"}

    def check(self, payload: bytes) -> bool:
        if len(payload) < 5:
            return False
        ln = struct.unpack_from(">I", payload)[0]
        dtype = payload[4]
        if dtype == 1:
            return payload[5:24].find(b"ActiveMQ") >= 0
        # whole-command frames: the length prefix must match exactly,
        # else HTTP/2 frame headers (00 00 xx type ...) false-positive
        return dtype in self._TYPES and ln + 4 == len(payload) \
            and ln < (1 << 24)

    def parse(self, payload: bytes) -> Optional[L7Record]:
        dtype = payload[4]
        name = self._TYPES.get(dtype, f"type{dtype}")
        if dtype in (30, 31):
            return L7Record(self.proto, MSG_RESPONSE, endpoint=name,
                            status=0 if dtype == 30 else 1,
                            resp_len=len(payload))
        return L7Record(self.proto, MSG_REQUEST, endpoint=name,
                        req_len=len(payload))


# ---------------------------------------------------------------------------
# FastCGI (reference: protocol_logs/rpc/fastcgi.rs)
# ---------------------------------------------------------------------------

class FastCgiParser:
    """FastCGI records. PARAMS carry the CGI environment; endpoint is
    REQUEST_METHOD + SCRIPT_NAME like the reference's http-over-fcgi
    reconstruction."""

    proto: ClassVar[int] = L7_FASTCGI
    _BEGIN, _PARAMS, _STDIN, _STDOUT, _END = 1, 4, 5, 6, 3

    def check(self, payload: bytes) -> bool:
        return len(payload) >= 8 and payload[0] == 1 and \
            1 <= payload[1] <= 11

    @staticmethod
    def _params(body: bytes) -> Dict[str, str]:
        out: Dict[str, str] = {}
        off = 0
        try:
            while off < len(body) and len(out) < 64:
                nl = body[off]
                if nl >> 7:
                    nl = struct.unpack_from(">I", body, off)[0] & 0x7FFFFFFF
                    off += 4
                else:
                    off += 1
                vl = body[off]
                if vl >> 7:
                    vl = struct.unpack_from(">I", body, off)[0] & 0x7FFFFFFF
                    off += 4
                else:
                    off += 1
                name = body[off:off + nl].decode("latin-1", "replace")
                off += nl
                out[name] = body[off:off + vl].decode("latin-1", "replace")
                off += vl
        except (IndexError, struct.error):
            pass
        return out

    def parse(self, payload: bytes) -> Optional[L7Record]:
        off = 0
        params: Dict[str, str] = {}
        saw_stdout = saw_end = False
        while off + 8 <= len(payload):
            rtype = payload[off + 1]
            clen = struct.unpack_from(">H", payload, off + 4)[0]
            plen = payload[off + 6]
            body = payload[off + 8:off + 8 + clen]
            off += 8 + clen + plen
            if rtype == self._PARAMS and clen:
                params.update(self._params(body))
            elif rtype == self._STDOUT and clen:
                saw_stdout = True
                m = re.search(rb"Status:\s*(\d{3})", body)
                status = int(m.group(1)) if m else 200
                return L7Record(self.proto, MSG_RESPONSE, status=status,
                                resp_len=len(payload))
            elif rtype == self._END:
                saw_end = True
        if params:
            ep = f"{params.get('REQUEST_METHOD', '')} " \
                 f"{params.get('SCRIPT_NAME', params.get('REQUEST_URI', ''))}"
            return L7Record(self.proto, MSG_REQUEST, endpoint=ep.strip(),
                            req_len=len(payload))
        if saw_stdout or saw_end:
            return L7Record(self.proto, MSG_RESPONSE, status=0,
                            resp_len=len(payload))
        return L7Record(self.proto, MSG_REQUEST, endpoint="",
                        req_len=len(payload))


# ---------------------------------------------------------------------------
# SofaRPC / bolt (reference: protocol_logs/rpc/sofa_rpc.rs)
# ---------------------------------------------------------------------------

class SofaRpcParser:
    """Bolt v1 frames. endpoint = header service + sofa method name,
    pulled from the classname/header region the reference reads."""

    proto: ClassVar[int] = L7_SOFARPC

    def check(self, payload: bytes) -> bool:
        # bolt v1: request headers are 22 bytes, response headers 20
        if len(payload) < 20 or payload[0] != 1:
            return False
        return payload[1] in (0, 1, 2)                 # resp/req/req-oneway

    def parse(self, payload: bytes) -> Optional[L7Record]:
        ptype = payload[1]
        if ptype in (1, 2):                            # request
            class_len, header_len = struct.unpack_from(">HH", payload, 14)
            content_len = struct.unpack_from(">I", payload, 18)[0]
            if 22 + class_len + header_len > len(payload) or \
                    content_len > (1 << 24):
                return None
            off = 22 + class_len
            header = payload[off:off + header_len]
            kv = {}
            parts = header.split(b"\x00")
            for i in range(0, len(parts) - 1, 2):
                kv[parts[i].decode("latin-1", "replace")] = \
                    parts[i + 1].decode("latin-1", "replace")
            service = kv.get("sofa_head_target_service", "")
            method = kv.get("sofa_head_method_name", "")
            ep = f"{service}.{method}" if service or method else \
                payload[22:22 + class_len].decode("latin-1", "replace")
            return L7Record(self.proto, MSG_REQUEST, endpoint=ep,
                            req_len=len(payload))
        # response: resp status u16 at offset 10 (0 = success)
        status = struct.unpack_from(">H", payload, 10)[0]
        return L7Record(self.proto, MSG_RESPONSE,
                        status=0 if status == 0 else 1,
                        resp_len=len(payload))


EXTENDED_PARSERS: List = [
    # magic-byte protocols first: their checks can't false-positive
    TlsParser(), DubboParser(), OpenWireParser(), SofaRpcParser(),
    Http2Parser(), MongoParser(), AmqpParser(), NatsParser(),
    MqttParser(), FastCgiParser(), PostgresErrorParser(), PostgresParser(),
    KafkaParser(),
]


def register_extended(parsers_list: List) -> None:
    """Append the extended set to an l7.PARSERS-style registry, keeping
    the four original parsers (HTTP/1, DNS, MySQL, Redis) in front: their
    checks are the cheapest and their traffic the most common."""
    known = {type(p) for p in parsers_list}
    for p in EXTENDED_PARSERS:
        if type(p) not in known:
            parsers_list.append(p)


# ---------------------------------------------------------------------------
# Oracle TNS (reference: protocol_logs/sql/oracle.rs — whose OSS build
# stubs the parse out to an enterprise crate; this is a clean-room
# parser of the PUBLIC TNS wire format, so the open build here covers
# more than the reference's open build does)
# ---------------------------------------------------------------------------

L7_ORACLE = 62

# TNS packet types (public protocol)
_TNS_CONNECT = 1
_TNS_ACCEPT = 2
_TNS_REFUSE = 4
_TNS_REDIRECT = 5
_TNS_DATA = 6
_TNS_MARKER = 12

# TTI data ids seen at the start of DATA payloads (oracle.rs:72 names
# 0x03 user-OCI-function); call ids for the common statement path
_OCI_CALLS = {
    0x02: "OPEN", 0x03: "QUERY", 0x04: "EXECUTE", 0x05: "FETCH",
    0x08: "CLOSE", 0x09: "DISCONNECT", 0x0c: "AUTOCOMMIT",
    0x3b: "VERSION", 0x5e: "QUERY", 0x60: "LOB_OP", 0x76: "AUTH",
    0x73: "AUTH_SESSION",
}


class OracleParser:
    """TNS framing + the session-visible verbs.

    CONNECT extracts SERVICE_NAME from the connect descriptor as the
    endpoint; ACCEPT/REFUSE close the handshake (REFUSE carries the
    refusal reason string); DATA packets report the OCI function when
    the payload opens with the user-OCI data id, with embedded SQL text
    obfuscated through the shared sql_obfuscate pass."""

    proto: ClassVar[int] = L7_ORACLE
    _MAX_LEN = 1 << 16

    def check(self, payload: bytes) -> bool:
        if len(payload) < 8:
            return False
        ln = struct.unpack_from(">H", payload)[0]
        ptype = payload[4]
        if ptype not in (_TNS_CONNECT, _TNS_ACCEPT, _TNS_REFUSE,
                         _TNS_REDIRECT, _TNS_DATA, _TNS_MARKER):
            return False
        if not (8 <= ln <= self._MAX_LEN):
            return False
        # CONNECT must carry a descriptor; DATA needs the 2-byte flags
        if ptype == _TNS_CONNECT:
            return len(payload) >= 34 and b"(" in payload[8:]
        # other types: the frame length must be plausible vs the capture
        return ln <= len(payload) + self._MAX_LEN // 2

    @staticmethod
    def _descriptor_field(text: bytes, key: bytes) -> str:
        i = text.find(key + b"=")
        if i < 0:
            return ""
        j = i + len(key) + 1
        end = j
        while end < len(text) and text[end:end + 1] not in (b")", b"("):
            end += 1
        return text[j:end].decode("latin-1", "replace").strip()

    def parse(self, payload: bytes) -> Optional[L7Record]:
        ptype = payload[4]
        if ptype == _TNS_CONNECT:
            svc = self._descriptor_field(payload[8:], b"SERVICE_NAME") \
                or self._descriptor_field(payload[8:], b"SID")
            return L7Record(self.proto, MSG_REQUEST,
                            endpoint=f"CONNECT {svc}".strip(),
                            req_len=len(payload))
        if ptype == _TNS_ACCEPT:
            return L7Record(self.proto, MSG_RESPONSE, status=0,
                            resp_len=len(payload))
        if ptype == _TNS_REFUSE:
            reason = self._descriptor_field(payload[8:], b"ERR")
            code = parse_int(reason, default=1)
            return L7Record(self.proto, MSG_RESPONSE, status=code,
                            endpoint="REFUSED", resp_len=len(payload))
        if ptype != _TNS_DATA or len(payload) < 11:
            return None                    # markers/redirects: not log events
        data = payload[10:]                # skip 2-byte data flags
        if not data:
            return None
        data_id = data[0]
        if data_id == 0x03 and len(data) >= 2:     # user OCI function
            call = _OCI_CALLS.get(data[1], f"CALL_{data[1]:02x}")
            # statement text rides in the TTI payload surrounded by
            # binary TTC fields and bind data: bound the slice at the
            # first non-printable byte BEFORE obfuscating, so control
            # bytes and out-of-band bind values (PII) can never leak
            # into the endpoint
            tail = data[2:]
            end = 0
            while end < len(tail) and 0x20 <= tail[end] < 0x7F:
                end += 1
            text = tail[:end]
            verb = sql_verb(text)
            sql = obfuscate_sql(text) if verb else ""
            endpoint = (f"{call} {sql}".strip() if sql else call)[:128]
            return L7Record(self.proto, MSG_REQUEST, endpoint=endpoint,
                            req_len=len(payload))
        if data_id == 0x04 and len(data) >= 5:     # return status
            # sequence# then a u16 return code in the common layout
            code = struct.unpack_from(">H", data, 3)[0]
            return L7Record(self.proto, MSG_RESPONSE, status=code,
                            resp_len=len(payload))
        return None


# registered last: the TNS check is structural (type byte + frame
# length) rather than magic-byte, so every stronger check goes first
EXTENDED_PARSERS.append(OracleParser())

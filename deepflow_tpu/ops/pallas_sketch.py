"""Fused Pallas unpack+sketch kernel: one VMEM-resident pass per batch.

The coalesced feed (ISSUE 5) got the batch across the link as one
transfer; what is left of the per-batch device cost is XLA scheduling
the step as SEPARATE histogram passes — the CMS rides mxu_hist's scan,
the entropy histogram rides another, and each re-reads the unpacked
lane columns from HBM. This kernel fuses the whole histogram half of
`flow_suite.update` into a single Pallas program:

- the unpack prologue (ports/proto/packet split out of the 4 staged
  lane words) runs IN-KERNEL on each chunk, so the staged plane is
  read from HBM exactly once and the derived columns never exist
  outside VMEM;
- the 5-tuple fold and the multiply-shift bucket hashes are the ACTUAL
  utils/u32.fold_columns / ops/hashing.bucket helpers (plain jnp ops,
  traced straight into the kernel body — they cannot drift from the
  unfused path), run on the same chunk while it is resident;
- the Count-Min rows AND the 4 entropy feature rows accumulate into
  VMEM-resident accumulators via the same one-hot MXU contraction as
  ops/mxu_hist, written back to HBM once at the end (the
  ops/pallas_hist residency pattern, extended across both sketch
  families).

HLL's scatter-max and the top-K ring stay XLA ops in the surrounding
jitted program (flow_suite.update_lanes_fused): a grouped scatter-max
has no MXU form, and the ring path's sort must stay out of Mosaic.

Bit-exactness: the CMS half is unconditional — mask weights are 0/1,
so a cell's per-batch sum is bounded by batch_rows (< 2^24 at any
sane capacity) and the f32 accumulation is exact regardless of
partial-sum order. The entropy half is exact only while a cell's
per-batch weighted sum stays below 2^24: weights saturate at
256**planes - 1 per record exactly like mxu_hist, so a batch that
concentrates many max-weight records on one bucket (a DDoS-shaped
burst) can push a cell sum past 2^24, where f32 rounds — and this
kernel's partial-sum order (chunk=1024, per-plane scaled adds)
differs from mxu_hist's (chunk=8192, planes recombined per chunk),
so the two paths may round apart by a few counts there. Within the
bound they agree bit-for-bit no matter which unit ran them —
asserted in tests/test_staging.py via interpret mode; the identity
tests and the ci.sh equality gates stay inside it by construction.

STATUS (2026-08-03): correctness-pinned (interpret-mode tests beside
the unfused reference); NOT yet measured on a real chip — this
environment has no TPU, and ops/pallas_hist.py's history says the
residency premise must be proven on silicon, not assumed. Hence the
same posture: auto dispatch takes this kernel only on a TPU backend
under the DEEPFLOW_SKETCH_PALLAS=1 opt-in (flow_suite.use_fused_hists),
and kernel_bench grows the A/B to read the verdict off a real v5e.

VMEM budget at the defaults (chunk=1024, CMS [4, 2^17], entropy
[4, 2^12]): CMS accumulator 2 MB + entropy accumulator 64 KB, one-hots
(1024, 512) + (1024, 256) bf16 = 1.5 MB, lane chunk 16 KB — well
inside ~16 MB.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepflow_tpu.ops import hashing
from deepflow_tpu.ops.mxu_hist import _split_hi_lo
from deepflow_tpu.ops.pallas_hist import tpu_compiler_params
from deepflow_tpu.utils.u32 import fold_columns


def _hist_body(fkey, feats, pkts, mask, cms_seed_ref, ent_seed_ref,
               cms_ref, ent_ref, *, chunk, cms_d, cms_width, ent_f,
               ent_width, ent_weight_planes):
    """The kernel's shared histogram half: CMS rows over the folded
    flow key + entropy feature rows, accumulated into the VMEM-resident
    refs. Both the lane kernel and the dict-wire news kernel call this
    after their own unpack prologues — the math is one definition, so
    the wires cannot drift apart."""
    u = jnp.uint32
    cms_hi, cms_lo = _split_hi_lo(cms_width)
    ent_hi, ent_lo = _split_hi_lo(ent_width)
    cms_lw = int(np.log2(cms_width))
    ent_lw = int(np.log2(ent_width))

    # Count-Min rows: mask-only weights (one 0/1 plane)
    w_mask = mask[:, None].astype(jnp.bfloat16)            # [chunk, 1]
    chi_iota = lax.broadcasted_iota(jnp.int32, (chunk, cms_hi), 1)
    clo_iota = lax.broadcasted_iota(jnp.int32, (chunk, cms_lo), 1)
    for j in range(cms_d):
        mult = cms_seed_ref[j, 0].astype(u)   # i32 scalar, bits kept
        salt = cms_seed_ref[j, 1].astype(u)
        idx = hashing.bucket(fkey, mult, salt, cms_lw)
        a = ((idx // cms_lo)[:, None] == chi_iota).astype(jnp.bfloat16) \
            * w_mask
        b = ((idx % cms_lo)[:, None] == clo_iota).astype(jnp.bfloat16)
        cms_ref[j] += lax.dot_general(
            a, b, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    # entropy features: packet weights, saturated then masked exactly
    # like mxu_hist.hist_masked (min first == mask first for 0/1 masks)
    wm = jnp.minimum(pkts, np.int32(256 ** ent_weight_planes - 1)) \
        * mask.astype(jnp.int32)                           # [chunk]
    ehi_iota = lax.broadcasted_iota(jnp.int32, (chunk, ent_hi), 1)
    elo_iota = lax.broadcasted_iota(jnp.int32, (chunk, ent_lo), 1)
    for f in range(ent_f):
        mult = ent_seed_ref[f, 0].astype(u)
        salt = ent_seed_ref[f, 1].astype(u)
        idx = hashing.bucket(feats[f], mult, salt, ent_lw)
        hi_oh = (idx // ent_lo)[:, None] == ehi_iota
        b = ((idx % ent_lo)[:, None] == elo_iota).astype(jnp.bfloat16)
        for plane in range(ent_weight_planes):
            wp = (((wm >> (8 * plane)) & 0xFF)[:, None]
                  ).astype(jnp.bfloat16)
            a = hi_oh.astype(jnp.bfloat16) * wp
            ent_ref[f] += lax.dot_general(
                a, b, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) \
                * np.float32(256.0 ** plane)


def _kernel(n_ref, lanes_ref, cms_seed_ref, ent_seed_ref,
            cms_ref, ent_ref, *, chunk, cms_d, cms_width, ent_f,
            ent_width, ent_weight_planes):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        cms_ref[:] = jnp.zeros_like(cms_ref)
        ent_ref[:] = jnp.zeros_like(ent_ref)

    u = jnp.uint32
    lanes = lanes_ref[:]                      # [4, chunk] uint32
    ip_src, ip_dst = lanes[0], lanes[1]
    # unpack prologue, in-kernel (flow_suite.unpack_lanes, op for op)
    port_src = lanes[2] >> u(16)
    port_dst = lanes[2] & u(0xFFFF)
    proto = lanes[3] >> u(24)
    pkts = (lanes[3] & u(0xFFFFFF)).astype(jnp.int32)

    # per-lane validity from the batch's n word: padded (or stale
    # staging) lanes carry weight 0 everywhere, exactly like the
    # unfused mask path
    pos = lax.broadcasted_iota(jnp.int32, (chunk, 1), 0)[:, 0]
    mask = (pos + pl.program_id(0) * chunk) < n_ref[0]

    # flow key: the REAL utils/u32.fold_columns — plain jnp ops, so the
    # unfused path's hash helpers trace straight into the kernel body
    # and can never drift from it
    fkey = fold_columns((ip_src, ip_dst, port_src, port_dst, proto))

    _hist_body(fkey, (ip_src, ip_dst, port_src, port_dst), pkts, mask,
               cms_seed_ref, ent_seed_ref, cms_ref, ent_ref,
               chunk=chunk, cms_d=cms_d, cms_width=cms_width,
               ent_f=ent_f, ent_width=ent_width,
               ent_weight_planes=ent_weight_planes)


def _news_kernel(n_ref, rows_ref, cms_seed_ref, ent_seed_ref,
                 cms_ref, ent_ref, *, chunk, cms_d, cms_width, ent_f,
                 ent_width, ent_weight_planes):
    """The dict wire's (6, C) NEWS plane, unpacked in-kernel: row 0 is
    the dictionary index (sketch math never reads it), rows 1-3 the
    three packed key words, row 4 the RAW proto byte, row 5 the
    PKTS_CAP'd packet count. The unpack mirrors flow_dict.update_news's
    lane construction + flow_suite.unpack_lanes op for op:
    (plane[4] << 24) >> 24 == plane[4] & 0xFF on the u8-valued wire
    row, and (proto<<24 | pkts) & 0xFFFFFF == pkts with pkts <= 0xFFFF."""
    @pl.when(pl.program_id(0) == 0)
    def _init():
        cms_ref[:] = jnp.zeros_like(cms_ref)
        ent_ref[:] = jnp.zeros_like(ent_ref)

    u = jnp.uint32
    rows = rows_ref[:]                        # [6, chunk] uint32
    ip_src, ip_dst = rows[1], rows[2]
    port_src = rows[3] >> u(16)
    port_dst = rows[3] & u(0xFFFF)
    proto = rows[4] & u(0xFF)
    pkts = (rows[5] & u(0xFFFFFF)).astype(jnp.int32)

    pos = lax.broadcasted_iota(jnp.int32, (chunk, 1), 0)[:, 0]
    mask = (pos + pl.program_id(0) * chunk) < n_ref[0]

    fkey = fold_columns((ip_src, ip_dst, port_src, port_dst, proto))

    _hist_body(fkey, (ip_src, ip_dst, port_src, port_dst), pkts, mask,
               cms_seed_ref, ent_seed_ref, cms_ref, ent_ref,
               chunk=chunk, cms_d=cms_d, cms_width=cms_width,
               ent_f=ent_f, ent_width=ent_width,
               ent_weight_planes=ent_weight_planes)


def _call_hists(kernel, nrows, plane, n, cms_seeds, ent_seeds, *,
                cms_log2_width, ent_log2_buckets, weight_planes,
                chunk, interpret):
    """Shared pallas_call plumbing for the (nrows, C) plane kernels:
    chunked grid over the column axis, both accumulators mapped to the
    same block every step, scalars riding SMEM as bit-preserved
    int32."""
    C = int(plane.shape[1])
    d = int(cms_seeds.shape[0])
    f = int(ent_seeds.shape[0])
    cms_w, ent_w = 1 << cms_log2_width, 1 << ent_log2_buckets
    cms_hi, cms_lo = _split_hi_lo(cms_w)
    ent_hi, ent_lo = _split_hi_lo(ent_w)
    chunk = min(chunk, C)
    while C % chunk:                 # batch capacities are powers of two;
        chunk //= 2                  # anything else degrades, still correct
    nchunk = C // chunk

    kern = functools.partial(
        kernel, chunk=chunk, cms_d=d, cms_width=cms_w, ent_f=f,
        ent_width=ent_w, ent_weight_planes=weight_planes)
    # scalars ride SMEM as int32 (bit-preserving: the kernel's
    # astype(uint32) wraps the bits back); the lane plane streams
    # through VMEM chunk blocks while both accumulators stay mapped to
    # the SAME block every step — the pallas_hist residency pattern
    cms_h, ent_h = pl.pallas_call(
        kern,
        grid=(nchunk,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((nrows, chunk), lambda i: (0, i)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((d, cms_hi, cms_lo), lambda i: (0, 0, 0)),
            pl.BlockSpec((f, ent_hi, ent_lo), lambda i: (0, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d, cms_hi, cms_lo), jnp.float32),
            jax.ShapeDtypeStruct((f, ent_hi, ent_lo), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
    )(
        jnp.asarray(n).astype(jnp.int32).reshape(1),
        plane,
        lax.bitcast_convert_type(cms_seeds, jnp.int32),
        lax.bitcast_convert_type(ent_seeds, jnp.int32),
    )
    return cms_h.reshape(d, cms_w), ent_h.reshape(f, ent_w)


@functools.partial(jax.jit, static_argnames=(
    "cms_log2_width", "ent_log2_buckets", "weight_planes", "chunk",
    "interpret"))
def fused_lane_hists(plane: jnp.ndarray, n: jnp.ndarray,
                     cms_seeds: jnp.ndarray, ent_seeds: jnp.ndarray, *,
                     cms_log2_width: int, ent_log2_buckets: int,
                     weight_planes: int = 2, chunk: int = 1024,
                     interpret: bool = False):
    """One staged (4, C) lane plane + its n word -> (cms_hist, ent_hist)
    f32 deltas, computed in a single fused kernel.

    cms_hist is [d, 2^cms_log2_width] over the folded 5-tuple flow key
    (== mxu_hist.hist_masked over hashing.multi_bucket, bit-exact);
    ent_hist is [4, 2^ent_log2_buckets] over ip_src/ip_dst/port_src/
    port_dst with capped packet weights (== entropy.update's histogram
    delta). The caller adds the deltas into the int32 sketch state.
    """
    return _call_hists(_kernel, 4, plane, n, cms_seeds, ent_seeds,
                       cms_log2_width=cms_log2_width,
                       ent_log2_buckets=ent_log2_buckets,
                       weight_planes=weight_planes, chunk=chunk,
                       interpret=interpret)


@functools.partial(jax.jit, static_argnames=(
    "cms_log2_width", "ent_log2_buckets", "weight_planes", "chunk",
    "interpret"))
def fused_news_hists(plane: jnp.ndarray, n: jnp.ndarray,
                     cms_seeds: jnp.ndarray, ent_seeds: jnp.ndarray, *,
                     cms_log2_width: int, ent_log2_buckets: int,
                     weight_planes: int = 2, chunk: int = 1024,
                     interpret: bool = False):
    """One dict-wire (6, C) NEWS plane + its n word -> the same
    (cms_hist, ent_hist) f32 deltas as `fused_lane_hists` would produce
    for the equivalent lane batch: the news unpack runs in-kernel
    (`_news_kernel`), the histogram math is the shared `_hist_body`.
    Hits planes need no kernel of their own — their table gather is an
    XLA op, and the gathered (4, 2H) lane plane rides
    `fused_lane_hists` unchanged (models/flow_dict.update_hits)."""
    return _call_hists(_news_kernel, 6, plane, n, cms_seeds, ent_seeds,
                       cms_log2_width=cms_log2_width,
                       ent_log2_buckets=ent_log2_buckets,
                       weight_planes=weight_planes, chunk=chunk,
                       interpret=interpret)

"""In-process snapshot cache with staleness-bounded reads.

The read plane's only state: a bounded deque of the most recent
:class:`~deepflow_tpu.runtime.snapbus.SketchSnapshot`s, fed push-style by
the bus (the subscriber callback just appends a reference — it runs at
window close under the exporter's state lock and must stay O(1)).

Staleness contract (the ``max_staleness_s`` knob): every read checks the
newest cached snapshot's age. A stale cache REFRESHES — it re-pulls the
bus (``refresh``: in-process latest, falling back to the disk store a
companion/previous process wrote). It never syncs the device and never
touches the feed/drain hot path; if nothing newer exists anywhere, the
stale snapshot is served anyway with its age reported honestly
(``stale_served`` counts it, the ``sketch_snapshot_staleness_s`` gauge
shows it) — a dashboard answering "as of 8s ago" beats a dashboard
hanging a query on a quiet ingest.

deepflow-lint's host-sync-in-device-path rule covers this file;
``refresh`` is the one sanctioned sync — and it is a *bus* sync (host
npz / host arrays), not a device one.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional

from deepflow_tpu.runtime.snapbus import SketchSnapshot, SnapshotBus

__all__ = ["SnapshotCache"]


class SnapshotCache:
    """Subscribes to a SnapshotBus; serves recent snapshots to readers."""

    def __init__(self, bus: SnapshotBus, max_staleness_s: float = 5.0,
                 history: int = 128,
                 clock: Callable[[], float] = time.time) -> None:
        self.bus = bus
        self.max_staleness_s = float(max_staleness_s)
        self.history = int(history)
        self._clock = clock
        self._lock = threading.Lock()
        self._snaps: deque = deque(maxlen=self.history)
        self.reads = 0
        self.refreshes = 0
        self.stale_served = 0
        self._unsubscribe = bus.subscribe(self._on_snapshot)

    # -- bus side ----------------------------------------------------------
    def _on_snapshot(self, snap: SketchSnapshot) -> None:
        """Subscriber callback: runs at window close under the
        exporter's state lock — append a reference, nothing else."""
        with self._lock:
            self._snaps.append(snap)

    def close(self) -> None:
        self._unsubscribe()

    # -- read side ---------------------------------------------------------
    def refresh(self) -> Optional[SketchSnapshot]:
        """The sanctioned stale-cache recovery: re-pull the bus (its
        in-process latest, else its disk store). Never the device."""
        self.refreshes += 1
        snap = self.bus.latest()
        if snap is not None:
            with self._lock:
                last = self._snaps[-1] if self._snaps else None
                # disk re-reads mint fresh seqs for the SAME snapshot:
                # dedup on (step, wall_time) so a quiet bus polled every
                # read doesn't fill the deque with copies
                if last is None or (snap is not last
                                    and (snap.step, snap.wall_time)
                                    > (last.step, last.wall_time)):
                    self._snaps.append(snap)
        return snap

    def staleness_s(self) -> float:
        """Age of the newest snapshot; +inf when none exists yet."""
        with self._lock:
            snap = self._snaps[-1] if self._snaps else None
        if snap is None:
            return float("inf")
        return max(0.0, self._clock() - snap.wall_time)

    def latest(self) -> Optional[SketchSnapshot]:
        """Staleness-bounded read of the newest snapshot."""
        self.reads += 1
        with self._lock:
            snap = self._snaps[-1] if self._snaps else None
        now = self._clock()
        if snap is None or now - snap.wall_time > self.max_staleness_s:
            got = self.refresh()
            if got is not None and (snap is None or got.seq >= snap.seq):
                snap = got
            if snap is not None \
                    and now - snap.wall_time > self.max_staleness_s:
                # nothing fresher exists anywhere: serve it, count it
                self.stale_served += 1
        return snap

    def window_range(self, lo: Optional[float],
                     hi: Optional[float]) -> List[SketchSnapshot]:
        """Snapshots whose wall_time falls in [lo, hi) — the mapping
        from query time bounds to snapshot windows. None = unbounded.
        Ascending wall-time order; duplicate steps keep the newest seq
        (a checkpoint_now re-publish supersedes the cadence publish)."""
        self.reads += 1
        with self._lock:
            snaps = list(self._snaps)
        by_step: dict = {}
        for s in snaps:
            if lo is not None and s.wall_time < lo:
                continue
            if hi is not None and s.wall_time >= hi:
                continue
            prev = by_step.get(s.step)
            if prev is None or s.seq > prev.seq:
                by_step[s.step] = s
        return sorted(by_step.values(), key=lambda s: (s.wall_time, s.step))

    def counters(self) -> dict:
        with self._lock:
            cached = len(self._snaps)
            newest = self._snaps[-1].step if self._snaps else -1
        st = self.staleness_s()
        return {"cached": cached, "newest_step": newest,
                "reads": self.reads, "refreshes": self.refreshes,
                "stale_served": self.stale_served,
                "staleness_s": -1.0 if st == float("inf") else round(st, 3),
                "max_staleness_s": self.max_staleness_s}

"""The ISSUE 7 sketch-serving read path: snapshot bus pub/sub +
versioning, staleness-bounded cache reads, point-query answers vs the
device kernels and the exact shadow, and read-vs-ingest isolation
(bit-identical sketch state with a reader hammering the cache)."""

import os
import threading
import time

import numpy as np
import pytest

from deepflow_tpu.batch.schema import L4_SCHEMA
from deepflow_tpu.models import flow_suite
from deepflow_tpu.runtime.snapbus import SnapshotBus
from deepflow_tpu.runtime.tpu_sketch import TpuSketchExporter
from deepflow_tpu.serving import SketchTables, SnapshotCache
from deepflow_tpu.utils.u32 import fold_columns_np

CFG = flow_suite.FlowSuiteConfig(cms_log2_width=12, ring_size=256,
                                 hll_groups=32, hll_precision=8,
                                 entropy_log2_buckets=8)


def _l4_cols(n, seed=0, pool=64):
    """Realistic sketch columns: ports < 2^16, proto < 2^8 (the packed
    lane masks to range — out-of-range synthetic values would fork the
    flow key between host fold and device wire)."""
    rng = np.random.default_rng(seed)
    base = {
        "ip_src": rng.integers(0, 1 << 30, pool).astype(np.uint32),
        "ip_dst": rng.integers(0, 1 << 30, pool).astype(np.uint32),
        "port_src": rng.integers(0, 1 << 16, pool).astype(np.uint32),
        "port_dst": rng.integers(0, 1 << 16, pool).astype(np.uint32),
        "proto": rng.integers(0, 255, pool).astype(np.uint32),
    }
    picks = rng.integers(0, pool, n)
    cols = {}
    for name, dt in L4_SCHEMA.columns:
        if name in base:
            cols[name] = base[name][picks].astype(dt)
        else:
            cols[name] = rng.integers(0, 1 << 10, n).astype(dt)
    return cols


def _keys_of(cols):
    return fold_columns_np([cols["ip_src"], cols["ip_dst"],
                            cols["port_src"], cols["port_dst"],
                            cols["proto"]])


# -- snapshot bus ----------------------------------------------------------
def test_bus_publish_subscribe_versioning(tmp_path):
    bus = SnapshotBus(str(tmp_path))
    state = flow_suite.init(CFG)
    got = []
    unsub = bus.subscribe(got.append)
    s1 = bus.publish(state, 1, wall_time=100.0, tags={"lossy": False})
    s2 = bus.publish(state, 2, wall_time=101.0)
    assert [s.step for s in got] == [1, 2]
    assert s2.seq > s1.seq                      # versioned
    assert bus.latest().step == 2
    assert s1.path and os.path.exists(s2.path)
    # a LATE subscriber gets the current latest immediately
    late = []
    bus.subscribe(late.append)
    assert [s.step for s in late] == [2]
    # unsubscribe stops delivery
    unsub()
    bus.publish(state, 3, wall_time=102.0)
    assert [s.step for s in got] == [1, 2]
    assert [s.step for s in late] == [2, 3]
    # tags + wall time survive the disk round trip (a fresh bus on the
    # same directory = the restart/companion-process reader)
    bus2 = SnapshotBus(str(tmp_path))
    snap = bus2.read_latest()
    assert snap.step == 3 and snap.wall_time == 102.0
    lossy_snap = SnapshotBus(str(tmp_path), keep=10)
    lossy_snap.publish(state, 4, wall_time=103.0, tags={"lossy": True})
    assert SnapshotBus(str(tmp_path)).read_latest().tags == {"lossy": True}


def test_bus_in_memory_only():
    """directory=None: pub/sub without durability (StorageDisabled)."""
    bus = SnapshotBus(None)
    got = []
    bus.subscribe(got.append)
    snap = bus.publish(flow_suite.init(CFG), 7, wall_time=5.0,
                       to_disk=False)
    assert snap.path is None and got and got[0].step == 7
    assert bus.latest() is snap
    assert bus.counters()["saves"] == 0
    assert bus.counters()["published"] == 1


def test_bus_subscriber_error_contained(tmp_path):
    bus = SnapshotBus(str(tmp_path))
    good = []

    def bad(_snap):
        raise RuntimeError("broken reader")

    bus.subscribe(bad)
    bus.subscribe(good.append)
    bus.publish(flow_suite.init(CFG), 1)
    assert good and bus.counters()["subscriber_errors"] == 1


def test_bus_fsyncs_file_and_directory(tmp_path, monkeypatch):
    """The ISSUE 7 durability satellite: the tmp file is fsynced before
    the rename and the directory after it."""
    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync",
                        lambda fd: (synced.append(fd), real_fsync(fd)))
    SnapshotBus(str(tmp_path)).publish(flow_suite.init(CFG), 1)
    assert len(synced) >= 2, "expected file + directory fsync"


def test_restore_stashes_last_restored_step(tmp_path):
    bus = SnapshotBus(str(tmp_path))
    bus.publish(flow_suite.init(CFG), 5)
    assert bus.counters()["last_restored_step"] == -1
    assert bus.restore(flow_suite.init(CFG)) is not None
    assert bus.counters()["last_restored_step"] == 5
    # incompatible restore leaves the stash untouched
    other = flow_suite.FlowSuiteConfig(cms_log2_width=10, ring_size=64,
                                       hll_groups=8, hll_precision=6,
                                       entropy_log2_buckets=6)
    assert bus.restore(flow_suite.init(other)) is None
    assert bus.counters()["last_restored_step"] == 5


# -- staleness-bounded cache ----------------------------------------------
def test_cache_staleness_miss_and_refresh(tmp_path):
    clock = [1000.0]
    writer = SnapshotBus(str(tmp_path))      # the "other process"
    reader_bus = SnapshotBus(str(tmp_path))
    cache = SnapshotCache(reader_bus, max_staleness_s=2.0,
                          clock=lambda: clock[0])
    state = flow_suite.init(CFG)
    writer.publish(state, 1, wall_time=999.5)
    # cold cache: first read is a miss that refreshes from the bus disk
    snap = cache.latest()
    assert snap is not None and snap.step == 1
    assert cache.refreshes == 1 and cache.stale_served == 0
    assert cache.staleness_s() == pytest.approx(0.5)
    # fresh enough: no refresh
    cache.latest()
    assert cache.refreshes == 1
    # the writer publishes a newer snapshot; the cache only notices
    # once its copy goes stale (the re-subscribe/refresh contract)
    writer.publish(state, 2, wall_time=1003.0)
    clock[0] = 1004.0
    snap = cache.latest()
    assert snap.step == 2 and cache.refreshes == 2
    assert cache.stale_served == 0
    # nothing newer exists anywhere: the stale snapshot is served and
    # counted, never a hang and never a device sync
    clock[0] = 1010.0
    snap = cache.latest()
    assert snap.step == 2 and cache.stale_served == 1


def test_cache_window_range_maps_time_bounds(tmp_path):
    bus = SnapshotBus(str(tmp_path), keep=10)
    cache = SnapshotCache(bus, max_staleness_s=1e9)
    state = flow_suite.init(CFG)
    for step, wall in ((1, 100.0), (2, 101.0), (3, 102.0)):
        bus.publish(state, step, wall_time=wall)
    assert [s.step for s in cache.window_range(100.5, 102.5)] == [2, 3]
    assert [s.step for s in cache.window_range(None, None)] == [1, 2, 3]
    # a re-publish of the same step (checkpoint_now) supersedes
    bus.publish(state, 3, wall_time=102.6)
    got = cache.window_range(None, None)
    assert [s.step for s in got] == [1, 2, 3]
    assert got[-1].wall_time == 102.6


# -- point queries vs the device kernels + exact shadow --------------------
@pytest.fixture
def served(tmp_path):
    exp = TpuSketchExporter(cfg=CFG, store=None, batch_rows=2048,
                            window_seconds=3600, wire="lanes",
                            checkpoint_dir=str(tmp_path / "ckpt"),
                            audit_rate=1.0)
    cache = SnapshotCache(exp.snapshot_bus, max_staleness_s=1e9)
    tables = SketchTables(cache)
    cols = _l4_cols(20000, seed=3)
    exp.process([("l4_flow_log", 0, cols)])
    shadow_counts = dict(exp._audit._counts)     # exact, pre-close
    out = exp.flush_window(now=1000.0)
    yield exp, tables, cols, out, shadow_counts
    exp.close()


def test_point_queries_match_device(served):
    """Every served estimator is the host twin of its device kernel:
    identical top-K, bit-equal CMS point estimates, same HLL estimate,
    same entropies — for the very snapshot the device flushed."""
    import jax
    import jax.numpy as jnp

    from deepflow_tpu.ops import cms

    exp, tables, cols, out, _shadow = served
    snap = tables.cache.latest()
    assert snap.step == 1 and snap.wall_time == 1000.0

    # top-K: serving rows == the device flush readout
    dev_keys = np.asarray(out.topk_keys)
    dev_counts = np.asarray(out.topk_counts)
    live = dev_counts > 0
    rows = tables.topk(int(live.sum()))
    assert [r["flow_key"] for r in rows] == dev_keys[live].tolist()
    assert [r["count"] for r in rows] == dev_counts[live].tolist()

    # CMS: rebuild the snapshot state on device, query the same keys
    treedef = jax.tree_util.tree_structure(flow_suite.init(CFG))
    st = jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(a) for a in snap.leaves])
    keys = np.unique(_keys_of(cols))[:256]
    dev_est = np.asarray(cms.query(st.sketch, jnp.asarray(keys)))
    view = tables._view(snap)
    np.testing.assert_array_equal(view.cms_points(keys), dev_est)
    assert all(view.cms_point(int(k)) == int(e)
               for k, e in zip(keys[:64], dev_est[:64]))

    # HLL cardinality: the flush's distinct_clients number
    card = tables.hll_card()["cardinality"]
    assert card == pytest.approx(
        float(np.asarray(out.service_cardinality).sum()), rel=1e-5)

    # entropy timeline row: the flush's 4 features
    ent = tables.entropy()
    dev_ent = np.asarray(out.entropies)
    from deepflow_tpu.serving.tables import ENTROPY_COLS
    for i, c in enumerate(ENTROPY_COLS):
        assert ent[c] == pytest.approx(float(dev_ent[i]), abs=1e-5)


def test_point_queries_vs_exact_shadow(served):
    """CMS point estimates against the PR 6 exact shadow: never under
    the true count (the CMS invariant) and inside the epsilon bound on
    the window's heavy hitters."""
    exp, tables, cols, out, shadow = served
    snap = tables.cache.latest()
    view = tables._view(snap)
    n_total = int(np.asarray(out.rows))
    assert n_total == 20000
    eps = np.e / float(1 << CFG.cms_log2_width)
    heavy = sorted(shadow.items(), key=lambda kv: -kv[1])[:50]
    for key, exact in heavy:
        est = view.cms_point(key)
        assert est >= exact, (key, est, exact)
        assert (est - exact) / n_total <= eps, (key, est, exact)


def test_sql_time_bounds_and_summary(tmp_path):
    exp = TpuSketchExporter(cfg=CFG, store=None, batch_rows=2048,
                            window_seconds=3600, wire="lanes")
    cache = SnapshotCache(exp.snapshot_bus, max_staleness_s=1e9)
    tables = SketchTables(cache)
    from deepflow_tpu.querier.sql import parse_sql
    try:
        for w, now in ((1, 100.0), (2, 200.0), (3, 300.0)):
            exp.process([("l4_flow_log", 0, _l4_cols(4000, seed=w))])
            exp.flush_window(now=now)
        res = tables.sql(parse_sql(
            "SELECT sketch.entropy FROM sketch "
            "WHERE time >= 150 AND time < 301"))
        assert [r[1] for r in res.values] == [2, 3]   # window column
        res = tables.sql(parse_sql("SELECT * FROM sketch"))
        assert res.columns[:3] == ["time", "window", "rows"]
        assert res.values[0][2] == 4000
        res = tables.sql(parse_sql(
            "SELECT sketch.topk(3) FROM sketch LIMIT 2"))
        assert len(res.values) == 2
        with pytest.raises(ValueError):
            tables.sql(parse_sql("SELECT sketch.nope(1) FROM sketch"))
        with pytest.raises(ValueError):
            tables.sql(parse_sql(
                "SELECT sketch.topk(3) FROM sketch WHERE proto = 6"))
    finally:
        exp.close()


def test_reads_concurrent_with_ingest_bit_identical():
    """A reader hammering the cache while ingest runs must leave the
    sketch state bit-identical to a no-readers twin — the read plane
    provably never touches the write plane."""
    import jax

    def run(with_reader: bool):
        exp = TpuSketchExporter(cfg=CFG, store=None, batch_rows=2048,
                                window_seconds=3600, wire="lanes",
                                prefetch_depth=2)
        cache = SnapshotCache(exp.snapshot_bus, max_staleness_s=1e9)
        tables = SketchTables(cache)
        exp.process([("l4_flow_log", 0, _l4_cols(6000, seed=1))])
        exp.flush_window(now=100.0)
        stop = threading.Event()
        reads = [0]

        def reader():
            hot = [r["flow_key"] for r in tables.topk(16)] or [1]
            i = 0
            while not stop.is_set():
                tables.cms_point(hot[i % len(hot)])
                tables.hll_card()
                i += 1
            reads[0] = i

        t = None
        if with_reader:
            t = threading.Thread(target=reader, daemon=True)
            t.start()
        for seed in range(2, 6):
            exp.process([("l4_flow_log", 0, _l4_cols(6000, seed=seed))])
        assert exp._feed.drain(30)
        if t is not None:
            stop.set()
            t.join(timeout=10)
            assert reads[0] > 0
        leaves = [np.asarray(a)
                  for a in jax.tree_util.tree_leaves(exp.state)]
        exp.close()
        return leaves

    with_r = run(True)
    without_r = run(False)
    for a, b in zip(with_r, without_r):
        np.testing.assert_array_equal(a, b)


# -- gauges + supervised querier server ------------------------------------
def test_serving_gauges_emitted(tmp_path):
    from deepflow_tpu.runtime.tracing import default_tracer
    tr = default_tracer()
    tr.enable()
    try:
        exp = TpuSketchExporter(cfg=CFG, store=None, batch_rows=2048,
                                window_seconds=3600, wire="lanes")
        cache = SnapshotCache(exp.snapshot_bus, max_staleness_s=60.0)
        tables = SketchTables(cache, tracer=tr)
        exp.process([("l4_flow_log", 0, _l4_cols(4000, seed=2))])
        exp.flush_window(now=time.time())
        deadline = time.time() + 5
        while time.time() < deadline:
            tables.cms_point(123)
            if "querier_read_qps" in tr.gauges():
                break
        g = tr.gauges()
        assert g["querier_read_qps"] > 0
        assert g["querier_read_p99_s"] > 0
        assert 0 <= g["sketch_snapshot_staleness_s"] <= 60.0
        # every serving gauge carries HELP (the strict exposition rule)
        from deepflow_tpu.runtime.tracing import gauge_help
        for name in ("querier_read_qps", "querier_read_p99_s",
                     "sketch_snapshot_staleness_s"):
            assert gauge_help(name)
        exp.close()
    finally:
        tr.disable()


def test_querier_server_supervised(tmp_path):
    import json
    import urllib.request

    from deepflow_tpu.querier.server import QuerierServer
    from deepflow_tpu.runtime.supervisor import default_supervisor
    from deepflow_tpu.store.db import Store
    from deepflow_tpu.store.dict_store import TagDictRegistry

    srv = QuerierServer(Store(str(tmp_path)), TagDictRegistry(None),
                        port=0)
    srv.start()
    try:
        mine = [t for t in default_supervisor().threads()
                if t["name"] == "querier-http"]
        assert mine and mine[-1]["alive"] and mine[-1]["crashes"] == 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/health", timeout=5) as r:
            assert json.load(r)["status"] == "ok"
    finally:
        srv.close()
    mine = [t for t in default_supervisor().threads()
            if t["name"] == "querier-http"]
    assert mine[-1]["done"]        # shutdown = normal completion


def test_datasource_listing_includes_sketch(tmp_path):
    from deepflow_tpu.store import rollup
    exp = TpuSketchExporter(cfg=CFG, store=None, batch_rows=2048,
                            window_seconds=3600)
    tables = SketchTables(SnapshotCache(exp.snapshot_bus))
    tables.register_datasource()
    try:
        rows = rollup.external_datasources()
        names = {r["table"] for r in rows}
        assert {"sketch.topk", "sketch.cms_point", "sketch.hll_card",
                "sketch.entropy"} <= names
    finally:
        tables.unregister_datasource()
        exp.close()
    assert rollup.external_datasources() == []


def test_cli_one_shot_snapshot_query(tmp_path, capsys):
    from deepflow_tpu.cli import main as cli_main

    ck = str(tmp_path / "ckpt")
    exp = TpuSketchExporter(cfg=CFG, store=None, batch_rows=2048,
                            window_seconds=3600, checkpoint_dir=ck)
    exp.process([("l4_flow_log", 0, _l4_cols(8000, seed=9))])
    exp.flush_window(now=1234.0)
    exp.close()
    assert cli_main(["query", "--snapshots", ck,
                     "SELECT sketch.topk(3) FROM sketch"]) == 0
    out = capsys.readouterr().out
    assert "flow_key" in out and "1234" in out
    # non-sketch SQL is refused crisply in snapshot mode
    assert cli_main(["query", "--snapshots", ck,
                     "SELECT * FROM flows"]) == 2


def test_read_latest_caches_unchanged_disk_snapshot(tmp_path):
    """A polling reader against a quiet companion-process store must get
    the SAME snapshot object back (one seq, one npz load) — not a fresh
    load per query; a re-published file IS re-read."""
    writer = SnapshotBus(str(tmp_path))
    reader = SnapshotBus(str(tmp_path))
    state = flow_suite.init(CFG)
    writer.publish(state, 1, wall_time=100.0)
    a = reader.read_latest()
    b = reader.read_latest()
    assert a is b and a.seq == b.seq
    # the cached object also serves the stale-cache refresh path
    # without growing the deque or the view cache
    cache = SnapshotCache(reader, max_staleness_s=0.0)
    tables = SketchTables(cache)
    for _ in range(32):
        tables.topk(3)
    assert cache.counters()["cached"] == 1
    assert len(tables._views) == 1
    # content change at the same path: must be re-read
    time.sleep(0.02)
    writer.publish(state, 1, wall_time=105.0)
    c = reader.read_latest()
    assert c is not a and c.wall_time == 105.0

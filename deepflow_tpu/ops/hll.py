"""Grouped HyperLogLog on device: `[groups, m]` register arrays.

Tracks distinct-cardinality (e.g. distinct client IPs per service_id — the
l7_flow_log HLL config in BASELINE.md) for many groups at once. Registers are
int32 for VPU friendliness (values fit in 6 bits). Updates are one flattened
scatter-max; merge across chips is elementwise max, so multi-device merge is
a single `lax.pmax`/psum-style ICI collective.

Estimator: Ertl's improved estimator ("New cardinality estimation algorithms
for HyperLogLog sketches", 2017) — bias-free across the full range without
HLL++ empirical tables, built from fixed-iteration σ/τ series that jit
cleanly (no data-dependent loops).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from deepflow_tpu.utils.u32 import as_u32, mix32

_U32 = np.uint32


class HLLState(NamedTuple):
    registers: jnp.ndarray  # [groups, m] int32


def init(groups: int, precision: int = 12) -> HLLState:
    """precision p: m = 2^p registers per group (p=12 -> ~1.6% rel. error)."""
    if not (4 <= precision <= 16):
        raise ValueError(f"precision {precision} out of range")
    return HLLState(registers=jnp.zeros((groups, 1 << precision), dtype=jnp.int32))


def _precision(state: HLLState) -> int:
    return int(np.log2(state.registers.shape[1]))


def update(state: HLLState, group_ids: jnp.ndarray, keys: jnp.ndarray,
           mask: jnp.ndarray | None = None) -> HLLState:
    g, m = state.registers.shape
    p = int(np.log2(m))
    h = mix32(as_u32(keys))
    reg_idx = (h >> _U32(32 - p)).astype(jnp.int32)             # top p bits
    rest = h << _U32(p)                                          # low 32-p bits up top
    # the int32 cast is a deliberate bit REINTERPRETATION for clz (u32
    # wrap to int32 preserves the bit pattern; clz counts bits, not
    # values), not a range-losing narrowing
    rho = jnp.minimum(jax.lax.clz(rest.astype(jnp.int32)),  # lint: disable=u32-overflow
                      32 - p) + 1
    gid = jnp.clip(group_ids.astype(jnp.int32), 0, g - 1)
    if mask is not None:
        # masked lanes write rho=0: a no-op for scatter-max (registers >= 0)
        rho = jnp.where(mask, rho, 0)
    flat = gid * m + reg_idx
    regs = state.registers.reshape(-1).at[flat].max(rho, mode="drop").reshape(g, m)
    return HLLState(registers=regs)


def _sigma(x: jnp.ndarray, iters: int = 32) -> jnp.ndarray:
    """Ertl σ(x) = x + Σ x^(2^k) 2^(k-1); diverges at x=1 (guarded by caller)."""
    y = jnp.ones_like(x)
    z = x
    for _ in range(iters):
        x = x * x
        z = z + x * y
        y = y + y
    return z


def _tau(x: jnp.ndarray, iters: int = 32) -> jnp.ndarray:
    """Ertl τ(x); τ(0) = τ(1) = 0."""
    y = jnp.ones_like(x)
    z = 1.0 - x
    for _ in range(iters):
        x = jnp.sqrt(x)
        y = 0.5 * y
        z = z - jnp.square(1.0 - x) * y
    return z / 3.0


def estimate(state: HLLState) -> jnp.ndarray:
    """[groups] float32 cardinality estimates (Ertl improved estimator)."""
    g, m = state.registers.shape
    p = int(np.log2(m))
    q = 32 - p
    # Per-group histogram C[k] of register values, k in [0, q+1], via a
    # flattened scatter-add: O(g*m) work, no [g, m, q+2] broadcast blow-up.
    ks = jnp.arange(q + 2, dtype=jnp.int32)
    rows = jnp.repeat(jnp.arange(g, dtype=jnp.int32), m)
    flat = rows * (q + 2) + jnp.clip(state.registers.reshape(-1), 0, q + 1)
    c = jnp.zeros((g * (q + 2),), jnp.int32).at[flat].add(1).reshape(g, q + 2)
    c = c.astype(jnp.float32)                                     # [g, q+2]
    mf = jnp.float32(m)
    z = mf * _tau(1.0 - c[:, q + 1] / mf) * jnp.float32(2.0 ** (-q))
    pow2 = jnp.exp2(-ks[1:q + 1].astype(jnp.float32))             # [q]
    mid = jnp.sum(c[:, 1:q + 1] * pow2[None, :], axis=1)
    denom = z + mid + mf * _sigma(c[:, 0] / mf)
    alpha_inf = jnp.float32(1.0 / (2.0 * np.log(2.0)))
    est = alpha_inf * mf * mf / denom
    # All-zero sketch (σ(1) series saturates at iteration cap) -> exactly 0.
    return jnp.where(c[:, 0] >= mf, 0.0, est)


def merge(a: HLLState, b: HLLState) -> HLLState:
    return HLLState(registers=jnp.maximum(a.registers, b.registers))


def reset(state: HLLState) -> HLLState:
    return HLLState(registers=jnp.zeros_like(state.registers))

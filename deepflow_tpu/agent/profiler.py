"""Continuous OnCPU profiler: perf sampling -> folded stacks -> wire.

Reference: agent/src/ebpf/kernel/perf_profiler.c (a perf-event-driven
stack sampler feeding a BPF stack map) +
agent/src/ebpf/user/profile/stringifier.c (stack-id -> folded "a;b;c"
frame strings) + profile/profile.c (the OnCPU profile stream).

TPU-host re-design: the sampler uses perf_event_open(2) directly —
PERF_COUNT_SW_CPU_CLOCK at a fixed frequency with kernel-unwound user
callchains (PERF_SAMPLE_CALLCHAIN; the kernel walks frame pointers, the
same unwind source the reference's BPF program uses) read from the mmap
ring. Symbolization is /proc-based: /proc/<pid>/maps executable
regions + an in-tree ELF .symtab/.dynsym reader (no libelf/pyelftools).
Folded stacks then ride the EXISTING profile wire
(wire/protos/telemetry.proto Profile records -> MessageType.PROFILE
firehose -> pipelines/profile.py in_process_profile -> querier flame),
so the agent side that was ingestion-only in round 3 now PRODUCES.

No kprobes needed: software-clock sampling works where kprobe attach is
masked (this container included), which is exactly why it's the
profiler datapath of choice here.
"""

from __future__ import annotations

import bisect
import ctypes
import mmap
import os
import struct
import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

_libc = ctypes.CDLL(None, use_errno=True)
_NR_PERF_EVENT_OPEN = {"x86_64": 298, "aarch64": 241, "riscv64": 241,
                       "s390x": 331, "ppc64le": 319}.get(
                           __import__("platform").machine())

PERF_TYPE_SOFTWARE = 1
PERF_COUNT_SW_CPU_CLOCK = 0
PERF_SAMPLE_TID = 0x2
PERF_SAMPLE_CALLCHAIN = 0x20
PERF_RECORD_SAMPLE = 9
PERF_EVENT_IOC_ENABLE = 0x2400
PERF_EVENT_IOC_DISABLE = 0x2401
# callchain context markers (PERF_CONTEXT_*): huge sentinel "addresses"
# separating kernel/user sections of the chain, never real code
_CONTEXT_FLOOR = 0xFFFFFFFFFFFFF000

_ATTR_SIZE = 128
# flag bits in perf_event_attr (bit offsets within the u64 at +40)
_F_DISABLED = 1 << 0
_F_EXCLUDE_KERNEL = 1 << 5
_F_EXCLUDE_HV = 1 << 6
_F_FREQ = 1 << 10

# perf_event_mmap_page: data_head/data_tail byte offsets
_HEAD_OFF, _TAIL_OFF = 1024, 1032


def available() -> bool:
    return _NR_PERF_EVENT_OPEN is not None


def _perf_event_open(pid: int, freq_hz: int) -> int:
    attr = bytearray(_ATTR_SIZE)
    struct.pack_into("<IIQQQ", attr, 0, PERF_TYPE_SOFTWARE, _ATTR_SIZE,
                     PERF_COUNT_SW_CPU_CLOCK, freq_hz,
                     PERF_SAMPLE_TID | PERF_SAMPLE_CALLCHAIN)
    struct.pack_into("<Q", attr, 40,
                     _F_DISABLED | _F_EXCLUDE_KERNEL | _F_EXCLUDE_HV
                     | _F_FREQ)
    buf = (ctypes.c_char * _ATTR_SIZE).from_buffer(attr)
    fd = _libc.syscall(_NR_PERF_EVENT_OPEN, ctypes.byref(buf),
                       pid, -1, -1, 0)
    if fd < 0:
        err = ctypes.get_errno()
        raise OSError(err, f"perf_event_open: {os.strerror(err)}")
    return fd


# -- ELF symbol reader (64-bit LE, .symtab + .dynsym STT_FUNC) ------------
def elf_function_symbols(path: str) -> Tuple[List[int], List[str], bool]:
    """([addr...sorted], [name...], is_pie). Missing/odd files -> empty."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return [], [], False
    if len(data) < 64 or data[:4] != b"\x7fELF" or data[4] != 2 \
            or data[5] != 1:
        return [], [], False
    e_type = struct.unpack_from("<H", data, 16)[0]
    is_pie = e_type == 3                                   # ET_DYN
    e_shoff, = struct.unpack_from("<Q", data, 0x28)
    e_shentsize, e_shnum = struct.unpack_from("<HH", data, 0x3A)
    syms: Dict[int, str] = {}
    for i in range(e_shnum):
        off = e_shoff + i * e_shentsize
        if off + 64 > len(data):
            break
        sh_type, = struct.unpack_from("<I", data, off + 4)
        if sh_type not in (2, 11):                         # SYMTAB/DYNSYM
            continue
        sh_offset, sh_size = struct.unpack_from("<QQ", data, off + 24)
        sh_link, = struct.unpack_from("<I", data, off + 40)
        sh_entsize, = struct.unpack_from("<Q", data, off + 56)
        if sh_entsize != 24 or sh_link >= e_shnum:
            continue
        stroff, strsz = struct.unpack_from(
            "<QQ", data, e_shoff + sh_link * e_shentsize + 24)
        strtab = data[stroff:stroff + strsz]
        for s in range(sh_offset, min(sh_offset + sh_size, len(data)),
                       24):
            st_name, st_info = struct.unpack_from("<IB", data, s)
            if st_info & 0xF != 2:                         # STT_FUNC only
                continue
            st_value, = struct.unpack_from("<Q", data, s + 8)
            if st_value == 0 or st_name >= len(strtab):
                continue
            end = strtab.find(b"\0", st_name)
            if end < 0:              # unterminated final entry: keep all
                end = len(strtab)
            name = strtab[st_name:end].decode("utf-8", "replace")
            if name:
                syms.setdefault(st_value, name)
    addrs = sorted(syms)
    return addrs, [syms[a] for a in addrs], is_pie


@dataclass
class _Module:
    start: int
    end: int
    bias: int            # runtime addr = file vaddr + bias
    name: str
    addrs: List[int]
    names: List[str]


class Symbolizer:
    """ip -> function name for one process, from /proc/<pid>/maps +
    the modules' own symbol tables (the stringifier.c role)."""

    def __init__(self, pid: int) -> None:
        self.pid = pid
        self._modules: List[_Module] = []
        self._load()

    def _load(self) -> None:
        try:
            with open(f"/proc/{self.pid}/maps") as f:
                lines = f.readlines()
        except OSError:
            return
        cache: Dict[str, Tuple[List[int], List[str], bool]] = {}
        for line in lines:
            parts = line.split()
            if len(parts) < 6 or "x" not in parts[1]:
                continue
            path = parts[5]
            if not path.startswith("/"):
                continue
            start, end = (int(x, 16) for x in parts[0].split("-"))
            offset = int(parts[2], 16)
            if path not in cache:
                cache[path] = elf_function_symbols(path)
            addrs, names, is_pie = cache[path]
            if not addrs:
                continue
            # ET_DYN (PIE/.so): runtime = vaddr + (start - offset); the
            # first LOAD's vaddr~=offset alignment makes this exact for
            # standard links. ET_EXEC: symbols are absolute already.
            bias = (start - offset) if is_pie else 0
            self._modules.append(_Module(start, end, bias,
                                         os.path.basename(path),
                                         addrs, names))
        self._modules.sort(key=lambda m: m.start)

    def resolve(self, ip: int) -> str:
        for m in self._modules:
            if m.start <= ip < m.end:
                v = ip - m.bias
                i = bisect.bisect_right(m.addrs, v) - 1
                if i >= 0:
                    return m.names[i]
                return f"{m.name}+0x{ip - m.start:x}"
        return "[unknown]"


class _TaskEvent:
    """One perf event + mmap ring bound to one task (thread)."""

    def __init__(self, tid: int, freq_hz: int, ring_pages: int) -> None:
        self.fd = _perf_event_open(tid, freq_hz)
        try:
            self.ring = mmap.mmap(self.fd,
                                  (ring_pages + 1) * mmap.PAGESIZE)
        except OSError:
            # e.g. perf_event_mlock_kb budget exhausted: the fd must
            # not outlive the failed construction — a retrying agent
            # loop would otherwise leak one per cycle
            os.close(self.fd)
            raise
        self.data_size = ring_pages * mmap.PAGESIZE

    def close(self) -> None:
        if self.fd >= 0:
            self.ring.close()
            os.close(self.fd)
            self.fd = -1


class OnCpuProfiler:
    """Sample one process's on-CPU user stacks; emit folded stacks.

    One perf event PER TASK: on this kernel class, inherit=1 refuses
    ring mmap (EINVAL), so a single process-wide event would silently
    sample only the main thread — worker-thread CPU (any thread pool)
    would be invisible. Tasks are snapshotted from /proc/<pid>/task at
    construction; threads spawned mid-window are picked up by the next
    profiling cycle. run(duration) -> {folded_stack: sample_count}."""

    def __init__(self, pid: int, freq_hz: int = 199,
                 ring_pages: int = 16, max_tasks: int = 64) -> None:
        if not available():
            raise OSError(38, "perf_event_open unsupported here")
        self.pid = pid
        self.freq_hz = freq_hz
        try:
            tids = sorted(int(t) for t in
                          os.listdir(f"/proc/{pid}/task"))[:max_tasks]
        except OSError:
            tids = [pid]
        self._events: List[_TaskEvent] = []
        last: Optional[OSError] = None
        for tid in tids:
            try:
                self._events.append(_TaskEvent(tid, freq_hz, ring_pages))
            except OSError as e:
                last = e          # tid exited, or perf/mlock refused
                continue
        if not self._events:
            raise last or OSError(3, f"no profilable tasks in pid {pid}")
        self.samples_seen = 0
        self.samples_other = 0       # non-SAMPLE ring records (lost, ...)

    def run(self, duration_s: float,
            symbolizer: Optional[Symbolizer] = None) -> Dict[str, int]:
        sym = symbolizer or Symbolizer(self.pid)
        import fcntl
        for ev in self._events:
            fcntl.ioctl(ev.fd, PERF_EVENT_IOC_ENABLE, 0)
        time.sleep(duration_s)
        for ev in self._events:
            fcntl.ioctl(ev.fd, PERF_EVENT_IOC_DISABLE, 0)
        folded: Dict[str, int] = {}
        for ev in self._events:
            for pid, tid, ips in self._drain(ev):
                frames = [sym.resolve(ip) for ip in ips
                          if ip < _CONTEXT_FLOOR]
                if not frames:
                    continue
                # kernel chains are leaf-first; folded is root-first
                folded_key = ";".join(reversed(frames))
                folded[folded_key] = folded.get(folded_key, 0) + 1
                self.samples_seen += 1
        return folded

    def _drain(self, ev: _TaskEvent
               ) -> Iterable[Tuple[int, int, List[int]]]:
        head, = struct.unpack_from("<Q", ev.ring, _HEAD_OFF)
        tail, = struct.unpack_from("<Q", ev.ring, _TAIL_OFF)

        def at(off: int, n: int) -> bytes:
            off %= ev.data_size
            base = mmap.PAGESIZE + off
            if off + n <= ev.data_size:
                return ev.ring[base:base + n]
            first = ev.data_size - off
            return ev.ring[base:base + first] + \
                ev.ring[mmap.PAGESIZE:mmap.PAGESIZE + n - first]

        while tail < head:
            rtype, _misc, size = struct.unpack("<IHH", at(tail, 8))
            if size < 8:
                break
            if rtype == PERF_RECORD_SAMPLE and size >= 24:
                body = at(tail + 8, size - 8)
                pid, tid = struct.unpack_from("<II", body, 0)
                nr, = struct.unpack_from("<Q", body, 8)
                nr = min(nr, (len(body) - 16) // 8)
                ips = list(struct.unpack_from(f"<{nr}Q", body, 16))
                yield pid, tid, ips
            else:
                self.samples_other += 1
            tail += size
        struct.pack_into("<Q", ev.ring, _TAIL_OFF, tail)

    @property
    def task_count(self) -> int:
        return len(self._events)

    def close(self) -> None:
        for ev in self._events:
            ev.close()
        self._events = []


def folded_to_profile_records(folded: Dict[str, int], app_service: str,
                              pid: int, vtap_id: int = 0,
                              ts_ns: Optional[int] = None) -> List[bytes]:
    """Folded stacks -> serialized telemetry.Profile records, the exact
    wire the ingester's profile pipeline consumes (event_type on-cpu,
    value = sample count)."""
    from deepflow_tpu.wire.gen import telemetry_pb2

    ts = int(time.time() * 1e9) if ts_ns is None else ts_ns
    out = []
    for stack, count in sorted(folded.items()):
        p = telemetry_pb2.Profile(
            timestamp=ts, app_service=app_service, pid=pid,
            vtap_id=vtap_id, event_type="on-cpu", stack=stack,
            value=count)
        out.append(p.SerializeToString())
    return out

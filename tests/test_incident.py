"""ISSUE 16: the incident flight recorder — durable correlated bundles,
rate limiting, budget eviction, SQL, and the edge-triggered watcher.

Contracts under test: a trigger captures exactly one fsynced bundle
(manifest + trigger + timeline window + trace + counters + snapbus
heads) whose timeline window covers the trigger instant; capture is
globally rate-limited with suppressions COUNTED; the directory is
bounded by budget_bytes with oldest-first eviction COUNTED; unreadable
manifests are skipped COUNTED; bundles answer SELECT * FROM incidents;
and the watcher fires on edges only (closed->open, ok->not-ok, rising
alert count, SLO entering fast-burn), never on levels."""

import json
import os

import pytest

from deepflow_tpu.runtime.incident import (IncidentRecorder,
                                           IncidentWatcher,
                                           BUNDLE_VERSION)
from deepflow_tpu.runtime.stats import StatsRegistry
from deepflow_tpu.runtime.timeline import Timeline, SloRule


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class _FakeSnap:
    step = 7
    seq = 3
    wall_time = 999.5
    path = "/snap/sketch-7"
    leaves = [1, 2, 3]
    tags = {"window": 7}


class _FakeBus:
    def __init__(self, snap=_FakeSnap()):
        self._snap = snap

    def latest(self):
        return self._snap


def _recorder(tmp_path, clock, timeline=None, **kw):
    kw.setdefault("min_interval_s", 30.0)
    kw.setdefault("window_s", 60.0)
    return IncidentRecorder(str(tmp_path / "incidents"),
                            timeline=timeline, clock=clock, **kw)


def _timeline_with_data(clock):
    tl = Timeline(sample_s=1.0, hot_samples=64, coarse_every=4,
                  clock=clock)
    for i in range(30):
        tl.record("receiver_rx_frames", float(i * 10), now=970.0 + i)
    return tl


# -------------------------------------------------------------- capture

def test_capture_bundle_layout_and_durability(tmp_path):
    clock = _Clock()
    stats = StatsRegistry()
    stats.register("receiver", lambda: {"rx_frames": 42})
    tl = _timeline_with_data(clock)
    rec = _recorder(tmp_path, clock, timeline=tl, stats=stats,
                    snapbuses={"sketch": _FakeBus(),
                               "anomaly": _FakeBus(None)})
    path = rec.capture("breaker_open", {"breaker": "flaky"})
    assert path is not None and os.path.isdir(path)
    base = os.path.basename(path)
    assert base.startswith("inc-1000-0001-breaker_open")
    names = sorted(os.listdir(path))
    # no profiler attached -> no trace.json; every other section present
    assert names == ["counters.json", "manifest.json", "snapbus.json",
                     "timeline.json", "trigger.json"]

    m = json.load(open(os.path.join(path, "manifest.json")))
    assert m["version"] == BUNDLE_VERSION
    assert m["id"] == base and m["kind"] == "breaker_open"
    assert sorted(m["files"]) == [n for n in names if n != "manifest.json"]
    assert all(m["files"][f] == os.path.getsize(os.path.join(path, f))
               for f in m["files"])
    # the timeline window covers the trigger instant
    t = json.load(open(os.path.join(path, "timeline.json")))
    lo, hi = t["window"]
    assert lo <= m["wall_time"] <= hi
    series = {s["metric"]: s for s in t["series"]}
    assert "receiver_rx_frames" in series
    assert all(lo <= ts <= clock.t + 1.0
               for ts in series["receiver_rx_frames"]["ts"])
    trg = json.load(open(os.path.join(path, "trigger.json")))
    assert trg == {"kind": "breaker_open", "wall_time": 1000.0,
                   "detail": {"breaker": "flaky"}}
    counters = json.load(open(os.path.join(path, "counters.json")))
    assert any(c["module"] == "receiver" and
               c["values"]["rx_frames"] == 42 for c in counters)
    snap = json.load(open(os.path.join(path, "snapbus.json")))
    assert snap["sketch"]["step"] == 7 and snap["sketch"]["leaves"] == 3
    assert snap["anomaly"] is None
    # no torn tmp directories left behind
    assert all(not n.startswith(".")
               for n in os.listdir(rec.directory))
    # trace.json present even with no profiler attached? profiler=None
    # means the recorder skips it — this recorder had none
    assert rec.counters()["captured"] == 1


def test_capture_without_optional_sources(tmp_path):
    clock = _Clock()
    rec = _recorder(tmp_path, clock)      # no timeline/profiler/stats
    path = rec.capture("healthz", {})
    names = sorted(os.listdir(path))
    assert names == ["manifest.json", "snapbus.json", "trigger.json"]
    assert json.load(open(os.path.join(path, "manifest.json")))["kind"] \
        == "healthz"


def test_rate_limit_is_global_and_counted(tmp_path):
    clock = _Clock()
    rec = _recorder(tmp_path, clock, min_interval_s=30.0)
    assert rec.capture("breaker_open", {}) is not None
    # a different KIND within the interval is still suppressed: one bad
    # moment trips several detectors and must yield ONE bundle
    clock.t += 5.0
    assert rec.capture("healthz", {}) is None
    assert rec.capture("slo_fast_burn", {}) is None
    assert rec.counters()["suppressed"] == 2
    clock.t += 30.0
    assert rec.capture("healthz", {}) is not None
    assert rec.counters()["captured"] == 2
    assert len(rec.list()) == 2


def test_budget_eviction_oldest_first_counted(tmp_path):
    clock = _Clock()
    rec = _recorder(tmp_path, clock, min_interval_s=0.0,
                    budget_bytes=1)       # everything over budget
    first = rec.capture("a", {})
    clock.t += 60.0
    second = rec.capture("b", {})
    # first capture evicted the (empty) excess; second evicted first
    assert not os.path.exists(first)
    assert os.path.exists(second) or rec.counters()["bundles_evicted"] >= 1
    c = rec.counters()
    assert c["bundles_evicted"] >= 1
    assert c["bytes_evicted"] > 0
    # with a sane budget nothing is evicted
    rec2 = IncidentRecorder(str(tmp_path / "inc2"), clock=clock,
                            min_interval_s=0.0,
                            budget_bytes=64 << 20)
    rec2.capture("a", {})
    clock.t += 1.0
    rec2.capture("b", {})
    assert rec2.counters()["bundles_evicted"] == 0
    assert len(rec2.list()) == 2


def test_unreadable_manifest_skipped_counted(tmp_path):
    clock = _Clock()
    rec = _recorder(tmp_path, clock)
    rec.capture("a", {})
    torn = os.path.join(rec.directory, "inc-999-0000-torn")
    os.makedirs(torn)
    with open(os.path.join(torn, "manifest.json"), "w") as f:
        f.write("{not json")
    listing = rec.list()
    assert len(listing) == 1              # the torn bundle is skipped
    assert rec.counters()["manifest_errors"] == 1
    assert rec.counters()["bundles"] == 2  # ...but still counted on disk


def test_list_survives_restart(tmp_path):
    clock = _Clock()
    rec = _recorder(tmp_path, clock)
    p = rec.capture("a", {"x": 1})
    # a fresh recorder over the same directory sees the bundle: the
    # directory is the source of truth
    rec2 = IncidentRecorder(rec.directory, clock=clock)
    listing = rec2.list()
    assert len(listing) == 1
    assert listing[0]["id"] == os.path.basename(p)
    assert listing[0]["path"] == p
    assert listing[0]["bytes"] > 0


# ------------------------------------------------------------------ SQL

def test_sql_select_from_incidents(tmp_path):
    from deepflow_tpu.querier import QueryEngine
    from deepflow_tpu.store.db import Store
    from deepflow_tpu.store.dict_store import TagDictRegistry
    clock = _Clock()
    rec = _recorder(tmp_path, clock, min_interval_s=0.0)
    rec.capture("breaker_open", {"breaker": "flaky"})
    clock.t += 100.0
    rec.capture("healthz", {})
    eng = QueryEngine(Store(str(tmp_path / "store")),
                      TagDictRegistry(None), incidents=rec)
    r = eng.execute("SELECT * FROM incidents")
    assert r.columns == ["time", "id", "kind", "bytes", "files",
                         "detail"]
    assert [row[2] for row in r.values] == ["breaker_open", "healthz"]
    assert r.values[0][0] == 1000 and r.values[1][0] == 1100
    assert json.loads(r.values[0][5]) == {"breaker": "flaky"}
    assert all(row[3] > 0 and row[4] >= 2 for row in r.values)
    # time bounds + LIMIT
    r = eng.execute("SELECT * FROM incidents WHERE time >= 1050")
    assert [row[2] for row in r.values] == ["healthz"]
    r = eng.execute("SELECT * FROM incidents LIMIT 1")
    assert len(r.values) == 1
    with pytest.raises(ValueError):
        eng.execute("SELECT kind FROM incidents")


# -------------------------------------------------------------- watcher

def test_watcher_breaker_edge(tmp_path):
    clock = _Clock()
    rec = _recorder(tmp_path, clock, min_interval_s=0.0)
    state = {"flaky": {"state": "closed", "opens": 0}}
    w = IncidentWatcher(rec, breakers_fn=lambda: state)
    w.tick(clock.t)
    assert w.triggers == 0
    state["flaky"]["state"] = "open"
    clock.t += 1.0
    w.tick(clock.t)
    assert w.triggers == 1
    # a breaker STAYING open is one incident, not one per tick
    clock.t += 1.0
    w.tick(clock.t)
    assert w.triggers == 1
    # half-open is recovery probing, not a new incident
    state["flaky"]["state"] = "half-open"
    w.tick(clock.t + 1)
    assert w.triggers == 1
    # closed -> open again: a NEW edge fires
    state["flaky"]["state"] = "closed"
    w.tick(clock.t + 2)
    state["flaky"]["state"] = "open"
    w.tick(clock.t + 3)
    assert w.triggers == 2
    kinds = [m["kind"] for m in rec.list()]
    assert kinds.count("breaker_open") == 2


def test_watcher_health_and_alarm_edges(tmp_path):
    clock = _Clock()
    rec = _recorder(tmp_path, clock, min_interval_s=0.0)
    health = {"ok": True, "accuracy_alarm": False}
    w = IncidentWatcher(rec, health_fn=lambda: dict(health))
    w.tick(clock.t)
    assert w.triggers == 0
    health["ok"] = False
    w.tick(clock.t + 1)
    assert w.triggers == 1                # ok -> not-ok edge
    w.tick(clock.t + 2)
    assert w.triggers == 1                # staying not-ok: no re-fire
    health["accuracy_alarm"] = True
    w.tick(clock.t + 3)
    assert w.triggers == 2                # alarm latching edge
    w.tick(clock.t + 4)
    assert w.triggers == 2
    kinds = sorted(m["kind"] for m in rec.list())
    assert kinds == ["accuracy_alarm", "healthz"]


def test_watcher_alert_count_and_fast_burn(tmp_path):
    clock = _Clock()
    rec = _recorder(tmp_path, clock, min_interval_s=0.0)
    alerts = {"n": 0.0}
    tl = Timeline(sample_s=1.0, hot_samples=32, clock=clock,
                  fast_burn_threshold=14.4)
    tl.add_slo(SloRule("avail", objective=0.999, kind="threshold",
                       series="bad_g", bound=0.5))
    w = IncidentWatcher(rec, alerts_fn=lambda: alerts["n"],
                        timeline=tl)
    tl.add_tick_hook(w.tick)
    tl.record("bad_g", 0.0, now=clock.t)
    tl.sample_once()
    assert w.triggers == 0                # baseline established
    alerts["n"] = 3.0
    clock.t += 1.0
    tl.record("bad_g", 0.0, now=clock.t)
    tl.sample_once()
    assert w.triggers == 1                # rising alert count
    # SLO entering fast-burn: the violated threshold series pushes the
    # fast-window burn to 1000 >> 14.4
    clock.t += 1.0
    tl.record("bad_g", 1.0, now=clock.t)
    tl.sample_once()
    assert w.triggers == 2
    kinds = sorted(m["kind"] for m in rec.list())
    assert kinds == ["anomaly_alert", "slo_fast_burn"]
    # still burning next tick: no re-fire (edge, not level)
    clock.t += 1.0
    tl.record("bad_g", 1.0, now=clock.t)
    tl.sample_once()
    assert w.triggers == 2


def test_watcher_burst_collapses_to_one_bundle(tmp_path):
    """One bad moment trips several detectors; the recorder's global
    rate limit collapses the correlated edges into a single bundle."""
    clock = _Clock()
    rec = _recorder(tmp_path, clock, min_interval_s=30.0)
    state = {"flaky": {"state": "closed"}}
    health = {"ok": True}
    w = IncidentWatcher(rec, health_fn=lambda: dict(health),
                        breakers_fn=lambda: state)
    w.tick(clock.t)
    state["flaky"]["state"] = "open"      # breaker opens AND health
    health["ok"] = False                  # flips in the same tick
    clock.t += 1.0
    w.tick(clock.t)
    assert w.triggers == 2                # both edges detected...
    assert rec.counters()["captured"] == 1   # ...one durable bundle
    assert rec.counters()["suppressed"] == 1


def test_watcher_source_errors_do_not_kill_tick(tmp_path):
    clock = _Clock()
    rec = _recorder(tmp_path, clock, min_interval_s=0.0)

    def bad_fn():
        raise RuntimeError("probe down")

    health = {"ok": True}
    w = IncidentWatcher(rec, health_fn=lambda: dict(health),
                        breakers_fn=bad_fn, alerts_fn=bad_fn)
    w.tick(clock.t)                       # must not raise
    health["ok"] = False
    w.tick(clock.t + 1)
    assert w.triggers == 1                # healthy sources still fire

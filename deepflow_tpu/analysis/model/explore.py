"""The explicit-state explorer behind `df-ctl verify` (ISSUE 14).

Breadth-first search over a Model's reachable states:

- every reached state is checked against every invariant; the first
  violation stops the search with a counterexample — BFS means the
  trace is a SHORTEST schedule to the bug, which is what makes the
  output readable as a post-mortem instead of a core dump;
- fault actions draw from a per-execution budget (`max_faults`), the
  "N shards, <= 2 concurrent faults" bound that keeps CI honest; a
  state reached with fewer faults spent dominates the same state
  reached with more (more remaining budget = strictly more behaviors),
  so each canonical state is expanded once, at its cheapest fault cost;
- symmetry reduction: successors are canonicalized through the model's
  `symmetry` hook before hashing, so schedules that differ only by a
  shard-id permutation collapse into one state;
- a state with no enabled action that the model does not bless as
  `done` is a DEADLOCK;
- after the (violation-free) sweep, the liveness pass: every reachable
  state must be able to reach a `goal` state through NON-fault actions.
  A state that cannot is a LIVELOCK under weak fairness — in these
  models every progress action stays enabled once enabled (queues
  don't spontaneously drain, deadlines don't un-expire), so "goal
  unreachable" is exactly "some fair schedule never resolves the
  ledger", without the full machinery of Büchi acceptance. Progress
  may not DEPEND on injecting further faults, hence the non-fault
  restriction; non-fault transitions never consult the fault budget,
  so the goal-reachability graph is well-defined per canonical state.

The wall-clock budget (`budget_s`) returns an INCOMPLETE result rather
than lying: `CheckResult.complete` is False and the CLI exits 2 — a
partial sweep is not a proof (no-silent-caps).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from deepflow_tpu.analysis.model.spec import Model, State

__all__ = ["Violation", "CheckResult", "check", "render_trace"]

# (canonical state, faults spent) — the trace-tree node identity
_Key = Tuple[tuple, int]


class Violation:
    """One counterexample: what broke, and the schedule that breaks it."""

    def __init__(self, kind: str, name: str, message: str,
                 trace: List[str], state: State) -> None:
        self.kind = kind          # "invariant" | "deadlock" | "livelock"
        self.name = name
        self.message = message
        self.trace = trace        # action labels, init -> violating state
        self.state = state


class CheckResult:
    def __init__(self, model: Model, ok: bool, complete: bool,
                 states: int, transitions: int, elapsed_s: float,
                 max_faults: int,
                 violation: Optional[Violation] = None) -> None:
        self.model_name = model.name
        self.ok = ok
        self.complete = complete
        self.states = states
        self.transitions = transitions
        self.elapsed_s = elapsed_s
        self.max_faults = max_faults
        self.violation = violation

    def to_dict(self) -> dict:
        out = {"model": self.model_name, "ok": self.ok,
               "complete": self.complete, "states": self.states,
               "transitions": self.transitions,
               "elapsed_s": round(self.elapsed_s, 3),
               "max_faults": self.max_faults}
        if self.violation is not None:
            v = self.violation
            out["violation"] = {"kind": v.kind, "name": v.name,
                                "message": v.message,
                                "trace_len": len(v.trace)}
        return out


def check(model: Model, max_faults: int = 2,
          budget_s: Optional[float] = None,
          max_states: int = 2_000_000,
          symmetry: bool = True) -> CheckResult:
    """Exhaustively explore `model` within the fault budget.

    `symmetry=False` disables the reduction (the state-count-bound test
    proves the reduction actually reduces)."""
    t0 = time.monotonic()
    sym = model.symmetry if symmetry else None

    def canon_state(state: State) -> State:
        return dict(sym(state)) if sym is not None else state

    init = canon_state(dict(model.init))
    init_canon = tuple(sorted(init.items()))

    # canon -> fewest faults it was reached with (domination pruning);
    # every canon in here is expanded exactly once, at that cost
    best: Dict[tuple, int] = {init_canon: 0}
    # trace tree: (canon, faults) -> (parent node, action label)
    parent: Dict[_Key, Tuple[Optional[_Key], Optional[str]]] = {
        (init_canon, 0): (None, None)}
    # non-fault edges REVERSED, canon-level, for the liveness pass
    rev: Dict[tuple, List[tuple]] = {}
    goal_canons: Set[tuple] = set()
    queue: deque = deque([(init, 0)])
    transitions = 0
    expanded = 0

    def trace_of(key: _Key) -> List[str]:
        steps: List[str] = []
        cur: Optional[_Key] = key
        while cur is not None:
            p, label = parent[cur]
            if label is not None:
                steps.append(label)
            cur = p
        steps.reverse()
        return steps

    def result(ok: bool, complete: bool,
               violation: Optional[Violation] = None) -> CheckResult:
        return CheckResult(model, ok, complete, len(best), transitions,
                           time.monotonic() - t0, max_faults, violation)

    # the initial state must satisfy the invariants too
    bad = model.check_invariants(init)
    if bad is not None:
        return result(False, True, Violation(
            "invariant", bad[0], bad[1], [], init))

    while queue:
        expanded += 1
        if budget_s is not None and (expanded & 0x1FF) == 0 \
                and time.monotonic() - t0 > budget_s:
            return result(True, False)
        state, faults = queue.popleft()
        canon = tuple(sorted(state.items()))
        if best.get(canon, max_faults + 1) < faults:
            continue              # dominated while queued
        key: _Key = (canon, faults)
        if model.goal is not None and model.goal(state):
            goal_canons.add(canon)
        any_enabled = False
        for action in model.enabled(state):
            if action.fault is not None and faults >= max_faults:
                continue          # budget spent: this fault can't fire
            any_enabled = True
            nf = faults + (1 if action.fault is not None else 0)
            for succ in action.successors(state):
                transitions += 1
                succ = canon_state(succ)
                scanon = tuple(sorted(succ.items()))
                skey: _Key = (scanon, nf)
                if skey not in parent:
                    parent[skey] = (key, action.label())
                if action.fault is None:
                    rev.setdefault(scanon, []).append(canon)
                bad = model.check_invariants(succ)
                if bad is not None:
                    return result(False, True, Violation(
                        "invariant", bad[0], bad[1],
                        trace_of(skey), succ))
                prior = best.get(scanon)
                if prior is None or nf < prior:
                    best[scanon] = nf
                    if len(best) > max_states:
                        return result(True, False)
                    queue.append((succ, nf))
        if not any_enabled and not model.done(state):
            return result(False, True, Violation(
                "deadlock", "deadlock",
                "no action is enabled and the model is not done — "
                "the protocol wedged", trace_of(key), state))

    # -- liveness: every state reaches a goal via non-fault steps ----------
    if model.goal is not None:
        reaches = set(goal_canons)
        frontier = list(goal_canons)
        while frontier:
            nxt: List[tuple] = []
            for node in frontier:
                for pred in rev.get(node, ()):
                    if pred not in reaches:
                        reaches.add(pred)
                        nxt.append(pred)
            frontier = nxt
        for canon, faults in sorted(best.items(),
                                    key=lambda kv: kv[1]):
            if canon in reaches:
                continue
            stuck = dict(canon)
            enabled = [a.label() for a in model.enabled(stuck)
                       if a.fault is None]
            return result(False, True, Violation(
                "livelock", "goal-unreachable",
                "no sequence of protocol steps from here ever reaches "
                "the goal (ledger resolved / epoch quiet) — a "
                "weakly-fair schedule spins forever; enabled non-fault "
                f"steps: {enabled or ['<none>']}",
                trace_of((canon, faults)), stuck))
    return result(True, True)


def render_trace(result: CheckResult) -> str:
    """The counterexample as a readable schedule (the `--trace-out`
    artifact). Registry-armable fault steps carry their real
    runtime/faults.py site string, so a trace reads like the chaos
    spec that would replay it (process-level events like SIGKILL are
    named as such — never site-shaped)."""
    lines: List[str] = []
    r = result
    lines.append(f"model: {r.model_name}  "
                 f"(states={r.states}, transitions={r.transitions}, "
                 f"max_faults={r.max_faults}, "
                 f"elapsed={r.elapsed_s:.2f}s, "
                 f"complete={'yes' if r.complete else 'NO — budget'})")
    if r.violation is None:
        lines.append("result: OK — every invariant holds in every "
                     "reachable state; every state resolves")
        return "\n".join(lines)
    v = r.violation
    lines.append(f"result: {v.kind.upper()} [{v.name}]")
    lines.append(f"  {v.message}")
    lines.append("schedule (shortest):")
    if not v.trace:
        lines.append("  <initial state>")
    for i, step in enumerate(v.trace, 1):
        lines.append(f"  {i:3d}. {step}")
    lines.append("state at violation:")
    for k in sorted(v.state):
        lines.append(f"  {k} = {v.state[k]!r}")
    return "\n".join(lines)

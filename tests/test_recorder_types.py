"""Per-type recorder diff tests for the round-5 model widening: every
new resource family gets create/update/delete/orphan coverage, plus
sub_domain-scoped reconciliation (reference: the 46-file
server/controller/recorder/db test suite + cloud/sub_domain.go)."""

import pytest

from deepflow_tpu.controller.model import (RESOURCE_TYPES, ResourceModel,
                                           make_resource)
from deepflow_tpu.controller.recorder import PARENT_LINKS, Recorder

D = "cloud-1"


def _mk(model=None):
    return Recorder(model or ResourceModel())


# (child type, parent chain bottom-up as (type, id, extra attrs))
# exercising every NEW family's full link path
FAMILIES = {
    "lb_target_server": [
        ("vpc", 10, {}),
        ("lb", 20, {"vpc_id": 10}),
        ("lb_listener", 30, {"lb_id": 20}),
        ("lb_target_server", 40, {"lb_id": 20, "lb_listener_id": 30}),
    ],
    "lb_vm_connection": [
        ("vpc", 10, {}),
        ("vm", 11, {"vpc_id": 10}),
        ("lb", 20, {"vpc_id": 10}),
        ("lb_vm_connection", 41, {"lb_id": 20, "vm_id": 11}),
    ],
    "nat_rule": [
        ("vpc", 10, {}),
        ("nat_gateway", 50, {"vpc_id": 10}),
        ("nat_rule", 51, {"nat_gateway_id": 50}),
    ],
    "nat_vm_connection": [
        ("vpc", 10, {}),
        ("vm", 11, {"vpc_id": 10}),
        ("nat_gateway", 50, {"vpc_id": 10}),
        ("nat_vm_connection", 52, {"nat_gateway_id": 50, "vm_id": 11}),
    ],
    "floating_ip": [
        ("vpc", 10, {}),
        ("vm", 11, {"vpc_id": 10}),
        ("floating_ip", 60, {"vpc_id": 10, "vm_id": 11,
                             "ip": "1.2.3.4"}),
    ],
    "pod_ingress_rule_backend": [
        ("pod_cluster", 70, {}),
        ("pod_ns", 71, {"pod_cluster_id": 70}),
        ("pod_ingress", 72, {"pod_ns_id": 71}),
        ("pod_ingress_rule", 73, {"pod_ingress_id": 72}),
        ("pod_ingress_rule_backend", 74, {"pod_ingress_rule_id": 73,
                                          "port": 8080}),
    ],
    "pod_service_port": [
        ("vpc", 10, {}),
        ("service", 80, {"vpc_id": 10}),
        ("pod_service_port", 81, {"service_id": 80, "port": 443,
                                  "protocol": "TCP"}),
    ],
    "pod_group_port": [
        ("vpc", 10, {}),
        ("pod_cluster", 70, {}),
        ("pod_ns", 71, {"pod_cluster_id": 70}),
        ("pod_group", 75, {"pod_ns_id": 71}),
        ("service", 80, {"vpc_id": 10}),
        ("pod_group_port", 82, {"pod_group_id": 75, "service_id": 80,
                                "port": 8443}),
    ],
    "pod_replica_set": [
        ("pod_cluster", 70, {}),
        ("pod_ns", 71, {"pod_cluster_id": 70}),
        ("pod_group", 75, {"pod_ns_id": 71}),
        ("pod_replica_set", 76, {"pod_group_id": 75}),
    ],
    "vm_pod_node_connection": [
        ("vpc", 10, {}),
        ("vm", 11, {"vpc_id": 10}),
        ("pod_cluster", 70, {}),
        ("pod_node", 77, {"pod_cluster_id": 70}),
        ("vm_pod_node_connection", 78, {"vm_id": 11,
                                        "pod_node_id": 77}),
    ],
    "process": [
        ("pod_cluster", 70, {}),
        ("pod_ns", 71, {"pod_cluster_id": 70}),
        ("pod", 79, {"pod_ns_id": 71}),
        ("process", 90, {"pod_id": 79, "pid": 1234,
                         "process_name": "nginx"}),
    ],
    "routing_table": [
        ("vpc", 10, {}),
        ("vrouter", 91, {"vpc_id": 10}),
        ("routing_table", 92, {"vrouter_id": 91}),
    ],
    "security_group_rule": [
        ("security_group", 93, {}),
        ("security_group_rule", 94, {"security_group_id": 93}),
    ],
    "wan_ip": [
        ("vpc", 10, {}),
        ("subnet", 95, {"vpc_id": 10}),
        ("vinterface", 96, {"subnet_id": 95}),
        ("wan_ip", 97, {"vinterface_id": 96, "ip": "5.6.7.8"}),
    ],
    "rds_instance": [
        ("vpc", 10, {}),
        ("rds_instance", 98, {"vpc_id": 10, "engine": "mysql"}),
    ],
    "redis_instance": [
        ("vpc", 10, {}),
        ("redis_instance", 99, {"vpc_id": 10}),
    ],
}


def _rows(chain):
    return [make_resource(t, i, f"{t}-{i}", domain=D, **extra)
            for t, i, extra in chain]


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_family_create_update_delete(family):
    rec = _mk()
    chain = FAMILIES[family]
    rows = _rows(chain)
    # create: full chain lands, parents first in the created order
    d = rec.reconcile(D, rows)
    assert not d.orphaned
    created_types = [r.type for r in d.created]
    assert created_types[-1] == family
    order = {t: i for i, t in enumerate(RESOURCE_TYPES)}
    assert created_types == sorted(created_types, key=lambda t: order[t])
    # update: rename the leaf -> exactly one field change
    leaf_t, leaf_id, extra = chain[-1]
    renamed = rows[:-1] + [make_resource(leaf_t, leaf_id, "renamed",
                                         domain=D, **extra)]
    d = rec.reconcile(D, renamed)
    changes = [(c.field, c.new) for c in d.field_changes]
    assert ("name", "renamed") in changes and len(changes) == 1
    # delete: drop the leaf -> deleted + tombstoned
    d = rec.reconcile(D, rows[:-1])
    assert [r.id for r in d.deleted] == [leaf_id]
    assert any(r.id == leaf_id for r in rec.deleted_resources())


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_family_orphan_quarantine(family):
    """A NEW leaf whose direct parent is absent quarantines (never
    half-lands); pre-existing rows hold last-good instead."""
    rec = _mk()
    chain = FAMILIES[family]
    rows = _rows(chain)
    parent_ids = {i for t, i, _ in chain[:-1]}
    if not parent_ids:
        pytest.skip("family has no parent")
    d = rec.reconcile(D, [rows[-1]])          # leaf without its chain
    assert [r.type for r in d.orphaned] == [family]
    assert rec.model.get(chain[-1][0], chain[-1][1]) is None


def test_every_new_type_is_modeled_and_linked():
    """The verdict's breadth bar: >= 25 types, and every non-root type
    in PARENT_LINKS resolves to modeled parent types."""
    assert len(RESOURCE_TYPES) >= 25
    for child, links in PARENT_LINKS.items():
        assert child in RESOURCE_TYPES
        for _attr, parent in links:
            assert parent in RESOURCE_TYPES


def test_sub_domain_scoped_reconcile_cannot_touch_domain_rows():
    """cloud/sub_domain.go discipline: the k8s sub-domain refresh owns
    ONLY rows carrying its sub_domain_id; the domain refresh owns only
    un-scoped rows."""
    rec = _mk()
    base = [make_resource("region", 1, "r", domain=D),
            make_resource("sub_domain", 5, "k8s-a", domain=D),
            make_resource("vpc", 10, "v", domain=D)]
    rec.reconcile(D, base)
    sd_rows = [make_resource("pod_cluster", 100, "c", domain=D,
                             sub_domain_id=5),
               make_resource("pod_ns", 101, "ns", domain=D,
                             sub_domain_id=5, pod_cluster_id=100)]
    d = rec.reconcile_sub_domain(D, 5, sd_rows)
    assert len(d.created) == 2
    # an empty sub-domain refresh deletes ITS rows only
    d = rec.reconcile_sub_domain(D, 5, [])
    assert sorted(r.id for r in d.deleted) == [100, 101]
    assert rec.model.get("vpc", 10) is not None
    assert rec.model.get("region", 1) is not None
    # ...and a full-domain refresh never deletes sub-domain rows
    rec.reconcile_sub_domain(D, 5, sd_rows)
    d = rec.reconcile(D, base)
    assert not d.deleted
    assert rec.model.get("pod_cluster", 100) is not None


def test_sub_domain_membership_is_validated_like_a_link():
    """A row claiming a sub_domain_id that exists nowhere quarantines
    — membership is a parent link, not a free-form tag."""
    rec = _mk()
    rec.reconcile(D, [make_resource("pod_cluster", 70, "c", domain=D)])
    d = rec.reconcile(D, [
        make_resource("pod_cluster", 70, "c", domain=D),
        make_resource("pod_node", 71, "n", domain=D,
                      pod_cluster_id=70, sub_domain_id=999)])
    assert [r.id for r in d.orphaned] == [71]


def test_sub_domain_refresh_rejects_foreign_rows():
    rec = _mk()
    rec.reconcile(D, [make_resource("sub_domain", 5, "k8s", domain=D)])
    with pytest.raises(ValueError):
        rec.reconcile_sub_domain(D, 5, [
            make_resource("pod_cluster", 100, "c", domain=D)])  # no attr


def test_tagrecorder_covers_new_dimensions(tmp_path):
    from deepflow_tpu.controller.tagrecorder import TagRecorder

    model = ResourceModel()
    tr = TagRecorder(model, root=str(tmp_path))
    rec = Recorder(model)
    rec.reconcile(D, _rows(FAMILIES["lb_target_server"])
                  + _rows(FAMILIES["process"]))
    assert tr.name("lb", 20) == "lb-20"
    assert tr.column_name("lb_id", 20) == "lb-20"
    assert tr.column_name("gprocess_id_0", 90) == "process-90"
    assert tr.column_name("vm_id_1", 11) is None   # not created here


def test_full_domain_refresh_rejects_scoped_rows():
    """Scope symmetry: a sub_domain-carrying row upserted by the
    full-domain path would be deletable by NO refresh (an immortal
    stale resource) — it must fail whole instead."""
    rec = _mk()
    rec.reconcile(D, [make_resource("sub_domain", 5, "k8s", domain=D)])
    with pytest.raises(ValueError):
        rec.model.update_domain(D, [
            make_resource("sub_domain", 5, "k8s", domain=D),
            make_resource("pod_cluster", 100, "c", domain=D,
                          sub_domain_id=5)])


def test_created_order_is_parents_first_for_vm():
    """vm links vpc (and host); RESOURCE_TYPES must order both parents
    before it, or subscribers see the child first."""
    idx = {t: i for i, t in enumerate(RESOURCE_TYPES)}
    for child, links in PARENT_LINKS.items():
        for _attr, parent in links:
            assert idx[parent] < idx[child], (parent, child)


def test_agent_reported_processes_become_gprocess_rows(tmp_path):
    """The JSON sync's GPIDSync leg lands `process` resource rows
    keyed by GLOBAL id, per-vtap sub-domain scoped, humanizable via
    tagrecorder (reference: recorder process updater + ch_gprocess)."""
    import json
    import urllib.request

    from deepflow_tpu.controller.monitor import FleetMonitor
    from deepflow_tpu.controller.registry import VTapRegistry
    from deepflow_tpu.controller.server import ControllerServer
    from deepflow_tpu.controller.tagrecorder import TagRecorder

    model = ResourceModel()
    tr = TagRecorder(model)
    reg = VTapRegistry()
    srv = ControllerServer(model, reg, FleetMonitor(reg), port=0,
                           tagrecorder=tr)
    srv.start()
    try:
        def sync(ctrl_ip, host, procs):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/sync",
                data=json.dumps({"ctrl_ip": ctrl_ip, "host": host,
                                 "processes": procs}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.load(r)

        r1 = sync("10.0.0.1", "n1",
                  [{"pid": 100, "name": "nginx", "start_time": 5},
                   {"pid": 200, "name": "envoy", "start_time": 6}])
        r2 = sync("10.0.0.2", "n2",
                  [{"pid": 100, "name": "redis", "start_time": 7}])
        rows = {r.id: r for r in model.list(type="process")}
        g_nginx = int(r1["gpids"]["100"])
        g_redis = int(r2["gpids"]["100"])
        assert rows[g_nginx].name == "nginx"
        assert rows[g_redis].name == "redis"
        assert g_nginx != g_redis           # same pid, two vtaps
        # querier humanization surface: gprocess_id -> name
        assert tr.column_name("gprocess_id_0", g_nginx) == "nginx"
        # vtap 1 re-syncs with nginx gone: ITS row dies, vtap 2's stays
        sync("10.0.0.1", "n1",
             [{"pid": 200, "name": "envoy", "start_time": 6}])
        rows = {r.id: r for r in model.list(type="process")}
        assert g_nginx not in rows and g_redis in rows
        assert srv.process_record_errors == 0
    finally:
        srv.close()


def test_dead_vtap_process_rows_pruned(tmp_path):
    """A decommissioned vtap's process inventory must not live
    forever: the sweep drops its sub-domain and rows while live
    vtaps' rows survive."""
    import json
    import urllib.request

    from deepflow_tpu.controller.monitor import FleetMonitor
    from deepflow_tpu.controller.registry import VTapRegistry
    from deepflow_tpu.controller.server import ControllerServer

    model = ResourceModel()
    reg = VTapRegistry()
    srv = ControllerServer(model, reg, FleetMonitor(reg), port=0)
    srv.start()
    try:
        def sync(ctrl_ip, host, procs):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/sync",
                data=json.dumps({"ctrl_ip": ctrl_ip, "host": host,
                                 "processes": procs}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.load(r)

        sync("10.0.0.1", "n1", [{"pid": 1, "name": "a",
                                 "start_time": 1}])
        sync("10.0.0.2", "n2", [{"pid": 2, "name": "b",
                                 "start_time": 2}])
        assert len(model.list(type="process")) == 2
        # age out vtap 1 only
        for v in reg.list():
            if v.host == "n1":
                v.last_seen = 0.0
        assert srv.prune_dead_vtap_processes(ttl_s=3600) == 1
        procs = model.list(type="process")
        assert [p.name for p in procs] == ["b"]
        assert [s.name for s in model.list(type="sub_domain")] \
            == [f"vtap-{procs[0].attr('vtap_id')}"]
        assert srv.process_record_errors == 0
    finally:
        srv.close()

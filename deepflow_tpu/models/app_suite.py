"""AppSuite: per-service RED metrics (Rate/Errors/Duration) on device.

Role: the reference's application observability reads request rates,
error ratios, and latency quantiles per service out of ClickHouse —
vtap_app_* meter tables for rate/error sums (server/ingester/
flow_metrics/dbwriter) and `quantile()` over l7_flow_log.rrt at query
time (querier derived metrics). A streaming TPU backend keeps the same
answers as device sketches instead: one batched update per l7 window
advances, for every hashed service group at once,

- request counts            (histogram over the service space, MXU)
- error counts              (same histogram, error-masked lanes)
- latency DDSketch          (ops/ddsketch: mergeable log buckets)

`flush` returns per-group request/error counts and p50/p95/p99 with
bounded relative error. Everything merges by add, so multi-chip runs
psum the state exactly like the other suites (parallel/sharded.py
pattern); windows replay-merge for checkpoints the same way.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, NamedTuple, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from deepflow_tpu.ops import ddsketch, mxu_hist
from deepflow_tpu.utils.u32 import fold_columns


@dataclass(frozen=True)
class AppSuiteConfig:
    groups: int = 1024            # hashed service space
    dd_buckets: int = 512         # see DDSketchConfig: range = g^buckets
    dd_alpha: float = 0.02
    quantiles: Tuple[float, ...] = (0.5, 0.95, 0.99)

    @property
    def dd(self) -> ddsketch.DDSketchConfig:
        return ddsketch.DDSketchConfig(groups=self.groups,
                                       buckets=self.dd_buckets,
                                       alpha=self.dd_alpha)


class AppSuiteState(NamedTuple):
    requests: jnp.ndarray         # [groups] f32
    errors: jnp.ndarray           # [groups] f32
    rrt: ddsketch.DDSketchState


class AppWindowOutput(NamedTuple):
    requests: jnp.ndarray         # [groups] f32
    errors: jnp.ndarray           # [groups] f32 (count — ratios don't
    #                               aggregate across windows)
    error_ratio: jnp.ndarray      # [groups] f32 in [0, 1]
    rrt_quantiles: jnp.ndarray    # [len(quantiles), groups] f32 (us)
    # the window's raw sketch (device references, zero copy until
    # fetched): consumers that surface the sketch as Prometheus `le`
    # buckets (runtime/app_red.py prom_bucket_stride) read these; others
    # never materialize them
    rrt_hist: jnp.ndarray         # [groups, buckets] f32
    rrt_zeros: jnp.ndarray        # [groups] f32 (values < min_value)


def init(cfg: AppSuiteConfig) -> AppSuiteState:
    return AppSuiteState(
        requests=jnp.zeros((cfg.groups,), jnp.float32),
        errors=jnp.zeros((cfg.groups,), jnp.float32),
        rrt=ddsketch.init(cfg.dd),
    )


def service_group(cols: Dict[str, jnp.ndarray], groups: int) -> jnp.ndarray:
    """[n] int32 hashed service id from the l7 row's server side —
    the same (ip, port, proto) key space as flow_suite.service_key."""
    key = fold_columns([cols["ip_dst"], cols["port_dst"],
                        cols.get("protocol", cols.get("proto"))])
    return (key % np.uint32(groups)).astype(jnp.int32)


def update(state: AppSuiteState, cols: Dict[str, jnp.ndarray],
           mask: jnp.ndarray, cfg: AppSuiteConfig) -> AppSuiteState:
    """One static-shape l7 batch: needs ip_dst/port_dst/protocol (the
    service key), status (0 ok), and rrt_us columns."""
    group = service_group(cols, cfg.groups)
    status = cols["status"].astype(jnp.uint32)
    # the status column carries protocol-native codes: HTTP parsers
    # store the raw response code (200/404/500, agent/l7.py HttpParser),
    # the enum-style parsers store 0 ok / small nonzero error codes
    # (MySQL/Redis/DNS rcode). Error = HTTP 4xx/5xx, or a nonzero
    # sub-100 enum code; HTTP 1xx-3xx are NOT errors.
    is_err = (status >= 400) | ((status > 0) & (status < 100))
    err_mask = jnp.logical_and(mask, is_err)
    req = mxu_hist.hist_masked(group[None, :], cfg.groups, None,
                               mask).reshape(-1)
    err = mxu_hist.hist_masked(group[None, :], cfg.groups, None,
                               err_mask).reshape(-1)
    rrt = ddsketch.update(state.rrt, group, cols["rrt_us"], mask=mask,
                          cfg=cfg.dd)
    return AppSuiteState(requests=state.requests + req,
                         errors=state.errors + err, rrt=rrt)


def merge(a: AppSuiteState, b: AppSuiteState) -> AppSuiteState:
    """Exact union: the psum/window-merge form (every field adds)."""
    return AppSuiteState(requests=a.requests + b.requests,
                         errors=a.errors + b.errors,
                         rrt=ddsketch.merge(a.rrt, b.rrt))


def flush(state: AppSuiteState, cfg: AppSuiteConfig
          ) -> Tuple[AppSuiteState, AppWindowOutput]:
    qs = jnp.stack([ddsketch.quantile(state.rrt, q, cfg.dd)
                    for q in cfg.quantiles])
    safe = jnp.maximum(state.requests, 1.0)
    out = AppWindowOutput(
        requests=state.requests,
        errors=state.errors,
        error_ratio=state.errors / safe,
        rrt_quantiles=qs,
        rrt_hist=state.rrt.hist,
        rrt_zeros=state.rrt.zeros,
    )
    return init(cfg), out

"""socket_trace: in-kernel syscall tracing programs, built in-tree.

Reference: agent/src/ebpf/kernel/socket_trace.c — ~2.5k LoC of kprobe C
that hooks read/write/sendmsg/recvmsg, builds SK_BPF_DATA records
(pid/tid, timestamp, direction, capture seq, payload bytes) and applies
the thread-session trace-id discipline (ingress data on a thread parks
a fresh trace id in a map; egress on the same thread consumes it — the
implicit context propagation that chains a service's inbound request to
its outbound call). Records stream to userspace over a perf event
array; agent/src/ebpf/user/socket.c consumes them.

This module authors the same program suite directly in the in-tree
eBPF assembler (agent/bpf.py) — no clang, no libbpf, no ELF:

- maps: `active` (HASH pid_tgid -> {buf, fd, is_msg} syscall-entry
  stash), `trace` (HASH pid_tgid -> parked trace id), `conf` (ARRAY
  [next_trace_id, capture_seq] allocation cells), `events`
  (PERF_EVENT_ARRAY record stream);
- programs: two entry stashers (plain-buffer read/write vs msghdr
  sendmsg/recvmsg arg shapes) and two exit builders (ingress parks a
  freshly allocated trace id, egress consumes the parked one), each
  building the 192-byte SOCK_DATA record on the BPF stack — zero-fill,
  field stores, bounded payload probe_read — and emitting it via
  bpf_perf_event_output;
- the userspace image of the record (`parse_record`) feeds the SAME
  `EbpfTracer` pipeline the fixture replay does (`feed_raw`), so the
  kernel source and the replay source are interchangeable upstream of
  the session aggregator.

The programs LOAD through the kernel verifier on this container's
kernel (tests/test_socket_trace.py asserts it) — a program that loads
is kernel-checked for memory safety, not merely syntax-checked. ATTACH
needs a kprobe PMU (/sys/bus/event_source/devices/kprobe) or tracefs,
which containers typically mask; `attach_available()` probes for the
capability and the agent degrades to the fixture/replay path when it's
absent, exactly as round-3's verdict prescribed.

x86_64 ABI facts baked into the programs (documented, attach-point
contracts, not verifier requirements):
- syscall wrapper `__x64_sys_read(struct pt_regs *regs)`: the OUTER
  pt_regs' di (offset 112) holds a pointer to the INNER pt_regs whose
  di/si/dx are the user's fd/buf/count;
- kretprobe return value: pt_regs->ax at offset 80;
- struct user_msghdr: msg_iov at +16; struct iovec: iov_base at +0.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from deepflow_tpu.agent.bpf import (BPF_ADD, BPF_DW, BPF_SUB,
                                    BPF_JEQ, BPF_JGE, BPF_JGT, BPF_JNE,
                                    BPF_JSLE, BPF_LSH,
                                    BPF_MAP_TYPE_HASH,
                                    BPF_MAP_TYPE_LRU_HASH, BPF_OR,
                                    BPF_RSH,
                                    BPF_MAP_TYPE_PERF_EVENT_ARRAY,
                                    BPF_PROG_TYPE_KPROBE, BPF_W,
                                    FN_get_current_comm,
                                    FN_get_current_pid_tgid,
                                    FN_ktime_get_ns, FN_map_delete_elem,
                                    FN_map_lookup_elem,
                                    FN_map_update_elem,
                                    FN_get_current_task,
                                    FN_perf_event_output, FN_probe_read,
                                    R0, R1, R2, R3, R4, R5, R6, R7, R8,
                                    R9, R10, Asm, Map, Program, available,
                                    load)

T_INGRESS, T_EGRESS = 0, 1

# data provenance, packed into the record direction word's high half
# (reference: process_data_extra_source, ebpf/kernel/include/common.h:79)
SOURCE_SYSCALL = 0
SOURCE_GO_TLS_UPROBE = 1
SOURCE_GO_HTTP2_UPROBE = 2
SOURCE_OPENSSL_UPROBE = 3
SOURCE_IO_EVENT = 4
TLS_SOURCES = (SOURCE_GO_TLS_UPROBE, SOURCE_GO_HTTP2_UPROBE,
               SOURCE_OPENSSL_UPROBE)

# -- SOCK_DATA record: the kernel->user wire image -------------------------
PAYLOAD_CAP = 128
RECORD_SIZE = 192
# <  pid_tgid  ts  trace_id cap_seq fd  dir len  comm16  payload128
_RECORD_FMT = "<QQQQQII16s128s"
assert struct.calcsize(_RECORD_FMT) == RECORD_SIZE

# x86_64 pt_regs field offsets
_PT_DI, _PT_SI, _PT_AX = 112, 104, 80
# struct user_msghdr / iovec hops
_MSG_IOV_OFF, _IOV_BASE_OFF, _IOV_LEN_OFF = 16, 0, 8

# stack frame (offsets from R10). The uprobe/http2 modules allocate
# their extra slots BELOW this frame's end (-264): extending the frame
# DOWNWARD means renumbering theirs too (uprobe_trace.py's _GOSTASH
# starts at -288) — which is why the goid slots live in the free space
# ABOVE the stash build area instead (-16..-1; the stash value ends at
# -17).
_GOIDVAL = -16       # goid scratch (8B, -16..-9)
_PIKEY = -8          # u32 tgid key for proc_info lookups (-8..-5)
_REC = -192          # SOCK_DATA record
_KEY = -200          # pid_tgid hash key
_CONFKEY = -208      # u32 conf array index
_FDSAVE = -216       # stashed fd across helper calls
_FLAG = -224         # is_msg flag
_SCRATCH = -232      # pointer-hop scratch
_IOVPAIR = -264      # first iovec {iov_base, iov_len} read as ONE 16B
                     # probe_read (-264..-249; -248.. is _TRVAL's 16B)
_TRVAL = -248        # trace-map value {id, fd} (16B)

# proc_info value layout shared with the uprobe suite (ONE map, pushed
# once per managed Go tgid): {reg_abi, conn_off, fd_off, sysfd_off,
# goid_off, fsbase_off} — the syscall programs read reg_abi (+0),
# goid_off (+16) and fsbase_off (+20: task_struct->thread.fsbase from
# kernel BTF, the stack-ABI g location %fs:-8; 0 = fs path
# unavailable)
_PI_REG_ABI = 0
_PI_GOID_OFF = 16
_PI_FSBASE_OFF = 20


@dataclass
class SocketTraceMaps:
    active: Map          # pid_tgid -> {buf, fd, is_msg, gokey, enter_ts}
    trace: Map           # pid_tgid | goid key -> {parked trace id, fd}
    conf: Map            # [0]=next trace id, [1]=capture seq
    events: Map          # perf record stream
    proc_info: Map       # tgid -> {reg_abi, walk offs, goid_off} (24B)

    def close(self) -> None:
        for m in (self.active, self.trace, self.conf, self.events,
                  self.proc_info):
            m.close()

    def set_proc_info(self, tgid: int, reg_abi: bool, conn_off: int = 0,
                      fd_off: int = 0, sysfd_off: int = 16,
                      goid_off: int = 0,
                      fsbase_off: Optional[int] = None) -> None:
        """One row enables goroutine-id trace keying for a tgid in BOTH
        suites (the uprobe maps alias this map when shared). For
        stack-ABI rows the programs reach g through %fs:-8 via
        task->thread.fsbase at `fsbase_off` (default: discovered from
        kernel BTF; 0 = unavailable, keying falls back to
        pid_tgid)."""
        if fsbase_off is None:
            from deepflow_tpu.agent.btf import fsbase_offset
            fsbase_off = fsbase_offset() if not reg_abi else 0
        self.proc_info.update_bytes(
            struct.pack("<I", tgid),
            struct.pack("<IIIIII", 1 if reg_abi else 0, conn_off, fd_off,
                        sysfd_off, goid_off,
                        0 if reg_abi else fsbase_off))


def create_maps(ncpus: Optional[int] = None) -> SocketTraceMaps:
    ncpus = ncpus or os.cpu_count() or 1
    made: List[Map] = []
    try:
        # active + trace are LRU: entries whose consumer never runs (a
        # kill between enter and exit; a goroutine that parks an
        # ingress id and exits without an egress — goid keys are
        # monotonic and never naturally overwritten) must age out
        # instead of filling the map and silently stopping ALL
        # stash/park updates process-wide (socket_trace.c's maps are
        # LRU for the same reason). proc_info stays a plain HASH:
        # eviction there would silently disable goid keying for a
        # managed process, and its population is bounded by tgids.
        for args in ((8192, 40, BPF_MAP_TYPE_LRU_HASH, 8),
                     (8192, 16, BPF_MAP_TYPE_LRU_HASH, 8),
                     (2, 8),
                     (ncpus, 4, BPF_MAP_TYPE_PERF_EVENT_ARRAY),
                     (1024, 24, BPF_MAP_TYPE_HASH, 4)):
            made.append(Map(*args))
    except OSError:
        for m in made:           # no orphan fds on partial creation
            m.close()
        raise
    maps = SocketTraceMaps(*made)
    maps.conf.update(0, 1)       # trace ids allocate from 1 (0 = none)
    maps.conf.update(1, 0)
    return maps


def emit_fs_g_load(a: Asm, fsbase_slot: int, scratch_slot: int,
                   fault_label: str) -> None:
    """Stack-ABI g load: current task -> thread.fsbase (offset in the
    u32 stack slot `fsbase_slot`, BTF-discovered) -> *(fsbase - 8),
    i.e. %fs:-8 where pre-1.17 Go keeps g. Leaves g in R3; clobbers
    R0-R3 and `scratch_slot` (8B). Jumps to `fault_label` on any
    failed hop — ONE emitter for both suites, like emit_gokey_pack:
    the syscall and uprobe programs chain only while their g
    derivation is bit-identical."""
    a.call(FN_get_current_task)
    a.ldx_mem(BPF_W, R1, R10, fsbase_slot)
    a.mov_reg(R3, R0).alu_reg(BPF_ADD, R3, R1)     # &task->thread.fsbase
    a.st_imm(BPF_DW, R10, scratch_slot, 0)
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, scratch_slot)
    a.mov_imm(R2, 8)
    a.call(FN_probe_read)
    a.jmp_imm(BPF_JNE, R0, 0, fault_label)
    a.ldx_mem(BPF_DW, R3, R10, scratch_slot)       # fsbase
    a.jmp_imm(BPF_JEQ, R3, 0, fault_label)
    a.alu_imm(BPF_SUB, R3, 8)                      # &(%fs:-8) = &g
    a.st_imm(BPF_DW, R10, scratch_slot, 0)
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, scratch_slot)
    a.mov_imm(R2, 8)
    a.call(FN_probe_read)
    a.jmp_imm(BPF_JNE, R0, 0, fault_label)
    a.ldx_mem(BPF_DW, R3, R10, scratch_slot)       # g


def emit_gokey_pack(a: Asm) -> None:
    """bit63 | tgid<<32 | (goid & 0xffffffff) -> R1. Expects R1=goid,
    R7=pid_tgid; clobbers R2. ONE emitter for both suites — the
    syscall and uprobe programs chain trace ids across sources only
    while their keys are bit-identical, so the packing must be
    structural, not maintained-by-parallel-edit (review r5). Bit 63
    partitions goid keys from pid_tgid keys (whose high word is a
    tgid < 2^22)."""
    a.alu_imm(BPF_LSH, R1, 32).alu_imm(BPF_RSH, R1, 32)  # goid lo32
    a.mov_reg(R2, R7).alu_imm(BPF_RSH, R2, 32).alu_imm(BPF_LSH, R2, 32)
    a.alu_reg(BPF_OR, R1, R2)                      # | tgid<<32
    a.mov_imm(R2, 1).alu_imm(BPF_LSH, R2, 63)
    a.alu_reg(BPF_OR, R1, R2)                      # | bit63 partition


def build_enter(maps: SocketTraceMaps, is_msg: bool) -> Asm:
    """Syscall-entry stash: {buf_or_msghdr, fd, is_msg, gokey} keyed by
    pid_tgid, consumed by the exit program (socket_trace.c's
    active_*_args_map role).

    gokey: for a proc_info-managed register-ABI Go tgid, the
    bit63|tgid<<32|goid trace key, read HERE — at syscall entry the
    inner pt_regs carry the user registers, so g is reachable
    (inner->r14); at the kretprobe they don't. A goroutine cannot
    migrate OS threads while blocked IN a syscall, so the pid_tgid
    stash key stays correct — only the trace park/consume needs the
    goid key, and the exit reads it from the stash. This is what lets
    a TLS-uprobe park chain into a plaintext syscall consume (and
    vice versa) for Go processes: both sources build the IDENTICAL
    key (uprobe_trace._goid_rekey). Same fault discipline as the
    uprobe side: keying enabled but goid unreadable -> drop the call
    (no stash), never a mismatched-key record. A non-goroutine thread
    in a managed process (cgo, runtime sysmon) carries garbage in
    r14: its reads either fault (dropped — such threads are not app
    traffic) or yield a key whose top half still carries the REAL
    tgid with bit 63, so it cannot collide into another process or
    the pid_tgid key space."""
    a = Asm()
    a.mov_reg(R6, R1)
    a.call(FN_get_current_pid_tgid)
    a.mov_reg(R7, R0)
    a.stx_mem(BPF_DW, R10, R7, _KEY)
    # inner pt_regs* = outer->di
    a.ldx_mem(BPF_DW, R8, R6, _PT_DI)
    # stash value {buf@-56, fd@-48, is_msg@-40, gokey@-32,
    # enter_ts@-24}: arg fields live in the inner pt_regs (kernel
    # memory) -> probe_read, which zero-fills the destination on
    # fault, so a failed read degrades to payload_len 0 downstream
    # instead of leaking uninitialized stack. enter_ts is what lets
    # the exit compute the syscall's latency (the reference's
    # data_args->enter_ts, socket_trace.c:2433 — the io_event gate)
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, -56)
    a.mov_imm(R2, 8)
    a.mov_reg(R3, R8).alu_imm(BPF_ADD, R3, _PT_SI)
    a.call(FN_probe_read)
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, -48)
    a.mov_imm(R2, 8)
    a.mov_reg(R3, R8).alu_imm(BPF_ADD, R3, _PT_DI)
    a.call(FN_probe_read)
    a.st_imm(BPF_DW, R10, -40, 1 if is_msg else 0)
    a.st_imm(BPF_DW, R10, -32, 0)                  # gokey default: none
    a.call(FN_ktime_get_ns)
    a.stx_mem(BPF_DW, R10, R0, -24)                # enter_ts
    # -- goid trace key for managed Go tgids ------------------------------
    a.mov_reg(R1, R7).alu_imm(BPF_RSH, R1, 32)
    a.stx_mem(BPF_W, R10, R1, _PIKEY)
    a.ld_map_fd(R1, maps.proc_info)
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _PIKEY)
    a.call(FN_map_lookup_elem)
    a.jmp_imm(BPF_JEQ, R0, 0, "stash")             # unmanaged: pid_tgid
    a.ldx_mem(BPF_W, R9, R0, _PI_GOID_OFF)
    a.jmp_imm(BPF_JEQ, R9, 0, "stash")             # keying disabled
    a.ldx_mem(BPF_W, R1, R0, _PI_REG_ABI)
    a.jmp_imm(BPF_JNE, R1, 0, "g_reg")
    # stack-ABI Go (< 1.17): g lives at %fs:-8, reached through
    # task_struct->thread.fsbase at the BTF-discovered offset; 0 means
    # no BTF on this kernel — keying UNAVAILABLE, pid_tgid fallback
    # (not a fault: nothing was attempted)
    a.ldx_mem(BPF_W, R1, R0, _PI_FSBASE_OFF)
    a.jmp_imm(BPF_JEQ, R1, 0, "stash")
    a.stx_mem(BPF_W, R10, R1, _PIKEY)              # lookup done: reuse
    emit_fs_g_load(a, _PIKEY, _GOIDVAL, "drop")    # g -> R3
    a.jmp_imm(BPF_JEQ, R3, 0, "drop")
    a.jmp("g_have")
    a.label("g_reg")
    # register ABI: g value = inner pt_regs' saved user r14
    a.st_imm(BPF_DW, R10, _GOIDVAL, 0)
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _GOIDVAL)
    a.mov_imm(R2, 8)
    a.mov_reg(R3, R8).alu_imm(BPF_ADD, R3, 8)      # &inner->r14
    a.call(FN_probe_read)
    a.jmp_imm(BPF_JNE, R0, 0, "drop")              # unreadable: drop
    a.ldx_mem(BPF_DW, R3, R10, _GOIDVAL)
    a.jmp_imm(BPF_JEQ, R3, 0, "drop")
    a.label("g_have")
    a.alu_reg(BPF_ADD, R3, R9)                     # &g.goid
    a.st_imm(BPF_DW, R10, _GOIDVAL, 0)
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _GOIDVAL)
    a.mov_imm(R2, 8)
    a.call(FN_probe_read)
    a.jmp_imm(BPF_JNE, R0, 0, "drop")
    a.ldx_mem(BPF_DW, R1, R10, _GOIDVAL)
    a.jmp_imm(BPF_JEQ, R1, 0, "drop")
    emit_gokey_pack(a)
    a.stx_mem(BPF_DW, R10, R1, -32)                # gokey into stash
    a.label("stash")
    a.ld_map_fd(R1, maps.active)
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
    a.mov_reg(R3, R10).alu_imm(BPF_ADD, R3, -56)
    a.mov_imm(R4, 0)                               # BPF_ANY
    a.call(FN_map_update_elem)
    a.exit_imm(0)
    # goid-read fault: this call is DROPPED, and any stale stash under
    # this pid_tgid must go with it — a missed kretprobe (maxactive
    # exhaustion) leaves the previous call's entry behind, and without
    # this delete THIS call's exit would pair with that stale stash
    # (wrong buf pointer, wrong enter_ts latency) instead of being
    # dropped (ADVICE r5). _KEY still holds pid_tgid: nothing on the
    # goid path writes it, and map helpers clobber only R0-R5.
    a.label("drop")
    a.ld_map_fd(R1, maps.active)
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
    a.call(FN_map_delete_elem)
    a.exit_imm(0)
    return a


def build_exit(maps: SocketTraceMaps, direction: int) -> Asm:
    """Syscall-exit record builder + trace-id discipline. `direction`
    T_INGRESS (read/recvmsg: allocate + park a trace id) or T_EGRESS
    (write/sendmsg: consume the parked one)."""
    a = Asm()
    a.mov_reg(R6, R1)
    a.call(FN_get_current_pid_tgid)
    a.mov_reg(R7, R0)
    a.stx_mem(BPF_DW, R10, R7, _KEY)
    # entry stash (absent = a syscall we didn't see enter; drop)
    a.ld_map_fd(R1, maps.active)
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
    a.call(FN_map_lookup_elem)
    a.jmp_imm(BPF_JEQ, R0, 0, "done")
    a.ldx_mem(BPF_DW, R9, R0, 0)                   # buf / msghdr*
    a.ldx_mem(BPF_DW, R1, R0, 8)
    a.stx_mem(BPF_DW, R10, R1, _FDSAVE)            # fd
    a.ldx_mem(BPF_DW, R1, R0, 16)
    a.stx_mem(BPF_DW, R10, R1, _FLAG)              # is_msg
    a.ldx_mem(BPF_DW, R1, R0, 24)                  # gokey (0 = none)
    a.stx_mem(BPF_DW, R10, R1, _GOIDVAL)
    a.ldx_mem(BPF_DW, R1, R0, 32)                  # enter_ts
    a.stx_mem(BPF_DW, R10, R1, _SCRATCH)
    a.ld_map_fd(R1, maps.active)                   # consume the stash
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
    a.call(FN_map_delete_elem)
    # a goid-keyed call (Go process, enter read the key from g) parks/
    # consumes its trace id under the gokey — the SAME key the TLS
    # uprobe programs build, which is what chains a decrypted read to
    # this goroutine's plaintext egress across sources and threads
    a.ldx_mem(BPF_DW, R1, R10, _GOIDVAL)
    a.jmp_imm(BPF_JEQ, R1, 0, "pidkey")
    a.stx_mem(BPF_DW, R10, R1, _KEY)
    a.label("pidkey")
    # syscall latency = now - enter_ts, clamped to u32 ns (~4.3s cap):
    # rides the record fd word's high half (the fd itself is a small
    # int) so the wire image stays 192B. The userspace io-event gate
    # (reference: trace_io_event_common, socket_trace.c:2393) needs it
    # to attach slow file-IO spans to in-flight traces.
    a.call(FN_ktime_get_ns)
    a.mov_reg(R1, R0)
    a.ldx_mem(BPF_DW, R2, R10, _SCRATCH)           # enter_ts
    a.jmp_imm(BPF_JEQ, R2, 0, "lat_zero")          # old/faulted stash
    a.alu_reg(BPF_SUB, R1, R2)
    a.mov32_imm(R2, 0xFFFFFFFF)
    a.jmp_reg(BPF_JGT, R1, R2, "lat_cap")
    a.jmp("lat_done")
    a.label("lat_cap").mov_reg(R1, R2)
    a.jmp("lat_done")
    a.label("lat_zero").mov_imm(R1, 0)
    a.label("lat_done")
    a.stx_mem(BPF_DW, R10, R1, _SCRATCH)           # latency slot
    # ret bytes (kretprobe: pt_regs->ax); <= 0 = error/EOF, no record
    a.ldx_mem(BPF_DW, R8, R6, _PT_AX)
    a.jmp_imm(BPF_JSLE, R8, 0, "done")
    a.jmp_imm(BPF_JGT, R8, PAYLOAD_CAP, "clamp")
    a.jmp("len_ok")
    a.label("clamp").mov_imm(R8, PAYLOAD_CAP)
    a.label("len_ok")
    emit_record_tail(a, maps, direction, msghdr_check=True,
                     latency_slot=_SCRATCH)
    a.label("done")
    a.exit_imm(0)
    return a


def emit_record_tail(a: Asm, maps, direction: int, source: int = 0,
                     msghdr_check: bool = False,
                     latency_slot: int = None) -> Asm:
    """The shared SOCK_DATA record build + trace-id discipline + perf
    emit — the tail every record-producing exit program ends with
    (syscall kretprobes here; SSL/Go-TLS uprobe exits in
    agent/uprobe_trace.py, which is why `maps` is duck-typed: anything
    with .trace/.conf/.events Map attributes).

    Register/stack CONTRACT on entry (the callers' prologues establish
    it): R6=ctx, R7=pid_tgid, R8=payload length already clamped to
    (0, PAYLOAD_CAP], R9=user buffer pointer (or user_msghdr* when
    `msghdr_check` and the _FLAG slot is nonzero), _KEY holds the
    caller's park/consume key — pid_tgid here and for pid_tgid-keyed
    uprobe callers, the bit63|tgid|goid key for goid-keyed Go-TLS
    callers (uprobe_trace._goid_rekey) — and _FDSAVE the fd. The
    record's own pid_tgid field always comes from R7, whatever the
    key shape. Jumps target the "done" label the CALLER must
    place before its exit. `source` is the reference's
    process_data_extra_source (common.h:79): packed into the record's
    direction word's high half — SOURCE_SYSCALL (0) keeps the word
    byte-identical to pre-uprobe records."""
    # zero the whole record: the verifier requires every byte a helper
    # reads (perf_event_output) to be initialized, and holes must not
    # leak stale stack to userspace
    for k in range(RECORD_SIZE // 8):
        a.st_imm(BPF_DW, R10, _REC + 8 * k, 0)
    a.stx_mem(BPF_DW, R10, R7, _REC + 0)           # pid_tgid
    a.call(FN_ktime_get_ns)
    a.stx_mem(BPF_DW, R10, R0, _REC + 8)           # timestamp
    # -- trace-id discipline (socket_trace.c:960-1060 park/consume) ----
    if direction == T_INGRESS:
        # continuation first (socket_trace.c: ingress on the SAME
        # socket continues the parked id — an HTTP request arriving
        # over several read()s must not fragment into several traces);
        # a different socket's ingress allocates fresh and re-parks
        a.ld_map_fd(R1, maps.trace)
        a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
        a.call(FN_map_lookup_elem)
        a.jmp_imm(BPF_JEQ, R0, 0, "alloc")
        a.ldx_mem(BPF_DW, R1, R0, 0)               # parked id
        a.ldx_mem(BPF_DW, R2, R0, 8)               # parked fd
        a.ldx_mem(BPF_DW, R3, R10, _FDSAVE)
        a.jmp_reg(BPF_JNE, R2, R3, "alloc")
        a.stx_mem(BPF_DW, R10, R1, _REC + 16)      # same socket: reuse
        a.jmp("no_trace")
        a.label("alloc")
        a.st_imm(BPF_W, R10, _CONFKEY, 0)
        a.ld_map_fd(R1, maps.conf)
        a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _CONFKEY)
        a.call(FN_map_lookup_elem)
        a.jmp_imm(BPF_JEQ, R0, 0, "no_trace")
        a.mov_imm(R1, 1)
        a.atomic_fetch_add(BPF_DW, R0, R1, 0)      # R1 = allocated id
        a.stx_mem(BPF_DW, R10, R1, _REC + 16)
        a.stx_mem(BPF_DW, R10, R1, _TRVAL)
        a.ldx_mem(BPF_DW, R1, R10, _FDSAVE)
        a.stx_mem(BPF_DW, R10, R1, _TRVAL + 8)
        a.ld_map_fd(R1, maps.trace)
        a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
        a.mov_reg(R3, R10).alu_imm(BPF_ADD, R3, _TRVAL)
        a.mov_imm(R4, 0)
        a.call(FN_map_update_elem)
    else:
        # consume: the id parked by this thread's last ingress
        a.ld_map_fd(R1, maps.trace)
        a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
        a.call(FN_map_lookup_elem)
        a.jmp_imm(BPF_JEQ, R0, 0, "no_trace")
        a.ldx_mem(BPF_DW, R1, R0, 0)
        a.stx_mem(BPF_DW, R10, R1, _REC + 16)
        a.ld_map_fd(R1, maps.trace)
        a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _KEY)
        a.call(FN_map_delete_elem)
    a.label("no_trace")
    # capture sequence: conf[1] fetch-add
    a.st_imm(BPF_W, R10, _CONFKEY, 1)
    a.ld_map_fd(R1, maps.conf)
    a.mov_reg(R2, R10).alu_imm(BPF_ADD, R2, _CONFKEY)
    a.call(FN_map_lookup_elem)
    a.jmp_imm(BPF_JEQ, R0, 0, "no_seq")
    a.mov_imm(R1, 1)
    a.atomic_fetch_add(BPF_DW, R0, R1, 0)
    a.stx_mem(BPF_DW, R10, R1, _REC + 24)
    a.label("no_seq")
    a.ldx_mem(BPF_DW, R1, R10, _FDSAVE)
    if latency_slot is not None:
        # fd word = fd | latency_ns << 32 — _FDSAVE itself stays the
        # PURE fd (the trace park value and its continuation compare
        # use it; a latency-tainted fd would break same-socket
        # continuation). Only the emitted record carries the packing.
        a.alu_imm(BPF_LSH, R1, 32).alu_imm(BPF_RSH, R1, 32)
        a.ldx_mem(BPF_DW, R2, R10, latency_slot)
        a.alu_imm(BPF_LSH, R2, 32)
        a.alu_reg(BPF_OR, R1, R2)
    a.stx_mem(BPF_DW, R10, R1, _REC + 32)          # fd
    a.st_imm(BPF_W, R10, _REC + 40,
             direction | (source << 16))           # dir | source<<16
    a.stx_mem(BPF_W, R10, R8, _REC + 44)           # data_len
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _REC + 48)
    a.mov_imm(R2, 16)
    a.call(FN_get_current_comm)
    if msghdr_check:
        # msghdr shape: two probe_read hops to the first iovec's base
        a.ldx_mem(BPF_DW, R1, R10, _FLAG)
        a.jmp_imm(BPF_JEQ, R1, 0, "copy")
        a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _SCRATCH)
        a.mov_imm(R2, 8)
        a.mov_reg(R3, R9).alu_imm(BPF_ADD, R3, _MSG_IOV_OFF)
        a.call(FN_probe_read)
        a.ldx_mem(BPF_DW, R9, R10, _SCRATCH)       # iov*
        # whole first iovec {iov_base, iov_len} in ONE 16B probe_read
        # (advisor r4): a scattered sendmsg whose FIRST iovec is
        # shorter than the ret-clamped length must not capture
        # adjacent process memory — clamp the copy to
        # min(ret, iov_len, CAP) like the reference does
        a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _IOVPAIR)
        a.mov_imm(R2, 16)
        a.mov_reg(R3, R9)
        a.call(FN_probe_read)
        a.ldx_mem(BPF_DW, R9, R10, _IOVPAIR + _IOV_BASE_OFF)
        a.ldx_mem(BPF_DW, R1, R10, _IOVPAIR + _IOV_LEN_OFF)
        # verifier-friendly clamp: the JGT pins R1 <= CAP on
        # fallthrough (an imm bound the verifier tracks precisely),
        # so the mov leaves R8 bounded for the copy's size argument
        a.jmp_imm(BPF_JGT, R1, PAYLOAD_CAP, "iov_ok")
        a.jmp_reg(BPF_JGE, R1, R8, "iov_ok")
        a.mov_reg(R8, R1)
        a.stx_mem(BPF_W, R10, R8, _REC + 44)       # data_len reflects
        a.jmp_imm(BPF_JEQ, R8, 0, "emit")          # empty iovec
        a.label("iov_ok")
    a.label("copy")
    # bounded payload copy: R8 in (0, PAYLOAD_CAP] by the clamp above
    a.mov_reg(R1, R10).alu_imm(BPF_ADD, R1, _REC + 64)
    a.mov_reg(R2, R8)
    a.mov_reg(R3, R9)
    a.call(FN_probe_read)
    a.jmp_imm(BPF_JEQ, R0, 0, "emit")
    a.st_imm(BPF_W, R10, _REC + 44, 0)             # faulted: len 0
    a.label("emit")
    # perf_event_output(ctx, events, CURRENT_CPU, rec, RECORD_SIZE)
    a.mov_reg(R1, R6)
    a.ld_map_fd(R2, maps.events)
    a.mov32_imm(R3, 0xFFFFFFFF)                    # BPF_F_CURRENT_CPU
    a.mov_reg(R4, R10).alu_imm(BPF_ADD, R4, _REC)
    a.mov_imm(R5, RECORD_SIZE)
    a.call(FN_perf_event_output)
    return a


# attach matrix: syscall -> (enter shape, exit direction)
SYSCALLS = {
    "read": ("buf", T_INGRESS),
    "recvmsg": ("msg", T_INGRESS),
    "write": ("buf", T_EGRESS),
    "sendmsg": ("msg", T_EGRESS),
}


class SocketTraceSuite:
    """The loaded program set + maps. Construction runs every program
    through the kernel verifier; failure raises with the verifier log
    (bpf.load surfaces it)."""

    def __init__(self) -> None:
        self.maps = create_maps()
        loaded: List[Program] = []
        try:
            for builder in (lambda: build_enter(self.maps, is_msg=False),
                            lambda: build_enter(self.maps, is_msg=True),
                            lambda: build_exit(self.maps, T_INGRESS),
                            lambda: build_exit(self.maps, T_EGRESS)):
                loaded.append(self._load(builder()))
        except OSError:
            # a kernel that rejects one program (e.g. pre-5.12 lacks
            # BPF_ATOMIC|BPF_FETCH) must not leak the maps or the
            # programs already loaded — probing callers retry
            for p in loaded:
                p.close()
            self.maps.close()
            raise
        (self.enter_buf, self.enter_msg,
         self.exit_ingress, self.exit_egress) = loaded

    @staticmethod
    def _load(asm: Asm) -> Program:
        return load(asm.assemble(), prog_type=BPF_PROG_TYPE_KPROBE)

    def programs(self) -> Dict[str, Tuple[Program, Program]]:
        """syscall -> (enter program, exit program), the kprobe/
        kretprobe pair to attach per SYSCALLS."""
        enter = {"buf": self.enter_buf, "msg": self.enter_msg}
        exit_ = {T_INGRESS: self.exit_ingress, T_EGRESS: self.exit_egress}
        return {name: (enter[shape], exit_[direction])
                for name, (shape, direction) in SYSCALLS.items()}

    def close(self) -> None:
        for p in (self.enter_buf, self.enter_msg, self.exit_ingress,
                  self.exit_egress):
            p.close()
        self.maps.close()


_ATTACH_CACHE: Optional[Tuple[bool, str]] = None


def attach_available() -> Tuple[bool, str]:
    """CAPABILITY probe: could kprobes be attached here? Needs the
    kprobe PMU (perf_event_open) or tracefs kprobe_events — both
    typically masked in containers. This reports capability only; the
    attach/perf-reader wiring that would switch the agent from the
    replay path to the kernel source keys off it. Cached: the answer is
    static per process and the available() gate costs real bpf(2)
    syscalls (a debug-dump poll loop must not re-pay them)."""
    global _ATTACH_CACHE
    if _ATTACH_CACHE is not None:
        return _ATTACH_CACHE
    if not available():
        _ATTACH_CACHE = (False, "bpf(2) unavailable")
    elif os.path.exists("/sys/bus/event_source/devices/kprobe/type"):
        _ATTACH_CACHE = (True, "kprobe PMU")
    else:
        for tracefs in ("/sys/kernel/tracing",
                        "/sys/kernel/debug/tracing"):
            if os.access(os.path.join(tracefs, "kprobe_events"), os.W_OK):
                _ATTACH_CACHE = (True, f"tracefs at {tracefs}")
                break
        else:
            _ATTACH_CACHE = (False,
                             "no kprobe PMU and no writable tracefs")
    return _ATTACH_CACHE


def parse_record(buf: bytes,
                 resolver: Optional[Callable] = None) -> "SyscallRecord":
    """One SOCK_DATA record -> the SyscallRecord the EbpfTracer
    pipeline consumes — the kernel source and the fixture replay are
    interchangeable above this line. `resolver(pid, fd)` may supply
    ((ip_src, ip_dst, port_src, port_dst)) from /proc; without it the
    flow tuple is zeros (sessions still pair per pid/fd/direction)."""
    from deepflow_tpu.agent.ebpf_source import SyscallRecord

    (pid_tgid, ts, trace_id, cap_seq, fd_word, dirword, data_len, comm,
     payload) = struct.unpack(_RECORD_FMT, buf[:RECORD_SIZE])
    direction, source = dirword & 0xFFFF, dirword >> 16
    tgid, tid = pid_tgid >> 32, pid_tgid & 0xFFFFFFFF
    # fd word: fd in the low half, syscall latency (u32 ns, clamped in
    # kernel) in the high half — records from pre-latency sources have
    # 0 there, which reads as latency 0
    fd, latency_ns = fd_word & 0xFFFFFFFF, fd_word >> 32
    ips = (0, 0, 0, 0)
    if resolver is not None:
        got = resolver(tgid, fd)
        if got is not None:
            # resolver convention: (local, remote, lport, rport). The
            # record convention is ip_src = SENDER of the data, so an
            # ingress record (remote peer sent it) swaps the tuple —
            # otherwise every live inbound request exports client and
            # server reversed
            if direction == T_INGRESS:
                ips = (got[1], got[0], got[3], got[2])
            else:
                ips = got
    return SyscallRecord(
        pid=tgid, tid=tid, direction=direction, source=source, fd=fd,
        timestamp_ns=ts,
        ip_src=ips[0], ip_dst=ips[1], port_src=ips[2], port_dst=ips[3],
        cap_seq=cap_seq,
        latency_ns=latency_ns,
        process_kname=comm.split(b"\0", 1)[0].decode("latin-1"),
        payload=payload[:min(data_len, PAYLOAD_CAP)],
        kernel_trace_id=trace_id,
        from_kernel=True,
    )


def pack_record(pid: int, tid: int, direction: int, ts_ns: int,
                payload: bytes, fd: int = 3, trace_id: int = 0,
                cap_seq: int = 0, comm: str = "",
                source: int = SOURCE_SYSCALL,
                latency_ns: int = 0) -> bytes:
    """Build a SOCK_DATA record byte-image (tests + fixture replay in
    the kernel wire format — the inverse of parse_record). latency_ns
    rides the fd word's high half exactly as the kernel packs it."""
    fd_word = (fd & 0xFFFFFFFF) | (min(latency_ns, 0xFFFFFFFF) << 32)
    return struct.pack(
        _RECORD_FMT, (pid << 32) | tid, ts_ns, trace_id, cap_seq,
        fd_word, direction | (source << 16),
        min(len(payload), PAYLOAD_CAP),
        comm.encode("latin-1")[:16],
        payload[:PAYLOAD_CAP])

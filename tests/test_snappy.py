"""Pure-Python snappy decompressor against hand-built blocks."""

import pytest

from deepflow_tpu.utils.snappy import SnappyError, decompress


def _varint(n):
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _literal(data):
    n = len(data) - 1
    if n < 60:
        return bytes([n << 2]) + data
    if n < 256:
        return bytes([60 << 2]) + bytes([n]) + data
    return bytes([61 << 2]) + n.to_bytes(2, "little") + data


def test_literals():
    payload = b"hello snappy world"
    block = _varint(len(payload)) + _literal(payload)
    assert decompress(block) == payload


def test_copy_1byte_offset():
    # "abcd" then copy len=4 offset=4 -> "abcdabcd"
    block = _varint(8) + _literal(b"abcd") + bytes([(0 << 5) | 1, 4])
    assert decompress(block) == b"abcdabcd"


def test_overlapping_copy_rle():
    # "ab" then copy len=6 offset=2 -> "abababab"
    tag = ((6 - 4) << 2) | 1
    block = _varint(8) + _literal(b"ab") + bytes([tag, 2])
    assert decompress(block) == b"abababab"


def test_copy_2byte_offset():
    data = bytes(range(256)) * 2
    length = 60  # copy-2 tag length field is 6 bits (1..64)
    tag2 = bytes([((length - 1) << 2) | 2]) + (300).to_bytes(2, "little")
    block = _varint(len(data) + length) + _literal(data) + tag2
    out = decompress(block)
    assert out[:len(data)] == data
    assert out[len(data):] == \
        data[len(data) - 300:len(data) - 300 + length]


def test_errors():
    with pytest.raises(SnappyError):
        decompress(b"")
    with pytest.raises(SnappyError):
        decompress(_varint(10) + _literal(b"ab"))   # length mismatch
    with pytest.raises(SnappyError):
        decompress(_varint(4) + bytes([(0 << 5) | 1, 9]))  # bad offset


def test_remote_write_roundtrip_through_collector(tmp_path):
    """Snappy-encoded WriteRequest -> integration collector -> ingester."""
    import time
    import urllib.request

    from deepflow_tpu.agent.integration import IntegrationCollector
    from deepflow_tpu.pipelines import Ingester, IngesterConfig
    from deepflow_tpu.wire.gen import telemetry_pb2

    ing = Ingester(IngesterConfig(listen_port=0, store_path=str(tmp_path)))
    ing.start()
    coll = IntegrationCollector(f"127.0.0.1:{ing.port}", port=0)
    coll.start()
    try:
        wr = telemetry_pb2.WriteRequest()
        ts = wr.timeseries.add()
        ts.labels.add(name="__name__", value="up")
        ts.samples.add(value=7.0, timestamp=1_700_000_000_000)
        raw = wr.SerializeToString()
        # snappy-encode as a single literal block (valid snappy)
        body = _varint(len(raw)) + _literal(raw)
        req = urllib.request.Request(
            f"http://127.0.0.1:{coll.port}/api/v1/prometheus", data=body,
            headers={"Content-Encoding": "snappy"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 204
        deadline = time.time() + 10
        while ing.ext_metrics.samples < 1 and time.time() < deadline:
            time.sleep(0.05)
        ing.flush()
        rows = ing.store.table("ext_metrics", "ext_samples").scan()
        assert rows["value"].tolist() == [7.0]
    finally:
        coll.close()
        ing.close()

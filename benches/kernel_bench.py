"""Per-kernel microbenches (reference role: agent/benches/ criterion suite).

Times each sketch/analytics kernel at fixed shapes on whatever backend JAX
resolves (the driver's real chip, or CPU under JAX_PLATFORMS=cpu) plus the
native C++ decoder, and prints one JSON line per kernel:

    {"bench": "cms_update", "rows_per_sec": ..., "ms_per_iter": ...,
     "shape": "...", "backend": "cpu"}

Run:  python benches/kernel_bench.py [--batch 1048576] [--iters 20]
      [--only cms_update,hll_update]

Each timed fn is jitted with donated state where the real pipelines donate,
warmed twice, then timed over `iters` calls. How the window CLOSES matters
on the tunneled runtime: block_until_ready can ack before device execution
drains there, inflating dispatch-bound numbers ~200x (measured 2026-07-31,
docs/BENCH_NOTES_r3.md). Default close is block_until_ready (fine on CPU
and local chips); pass --fetch-close on the tunneled chip to close with a
4-byte result fetch — bench.py's kernel-phase discipline — minus a
separately-measured fetch round-trip so the tunnel RTT doesn't ride on
ms_per_iter.
"""

from __future__ import annotations

import argparse
import os
import json
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1 << 20)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--only", type=str, default="")
    ap.add_argument("--fetch-close", action="store_true",
                    help="close every timed window with a 4-byte result "
                    "fetch: on the tunneled runtime block_until_ready "
                    "can ack before execution drains, overcounting "
                    "dispatch-bound kernels. The fetch trips the "
                    "~15s h2d slow mode (verify skill), so use with "
                    "--only when comparing kernels back to back.")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepflow_tpu.ops import cms, entropy, hll, mxu_hist, pca, topk

    backend = jax.default_backend()
    n = args.batch
    rng = np.random.default_rng(0xBE7C)
    keys = jnp.asarray(rng.integers(0, 1 << 20, n, dtype=np.uint32))
    groups = jnp.asarray(rng.integers(0, 64, n, dtype=np.uint32))
    mask = jnp.ones(n, jnp.bool_)

    results = []

    def bench(name, shape, fn, state_factory, *xs, rows=None):
        """Time state = fn(state, *xs) over iters (donated state, fresh
        per bench so donation can't free a buffer another bench holds)."""
        if args.only and name not in args.only.split(","):
            return
        step = jax.jit(fn, donate_argnums=0)

        def drain(state):
            """Wait for the device to really finish `state`."""
            if args.fetch_close:
                # 4-byte fetch of the first leaf: the only wait this
                # runtime cannot ack early (bench.py close_with_fetch)
                leaf = jax.tree_util.tree_leaves(state)[0]
                np.asarray(jnp.ravel(leaf)[0])
            else:
                jax.block_until_ready(state)

        s = state_factory()
        for _ in range(2):
            s = step(s, *xs)
        drain(s)
        # the closing fetch's own round-trip rides INSIDE the timed
        # window; measure it on the already-drained state and subtract
        # (tunnel RTT can be several ms — same order as a kernel call)
        fetch_ms = 0.0
        if args.fetch_close:
            t0 = time.perf_counter()
            drain(s)
            fetch_ms = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(args.iters):
            s = step(s, *xs)
        drain(s)
        dt = max(time.perf_counter() - t0 - fetch_ms, 1e-9)
        r = {"bench": name, "shape": shape, "backend": backend,
             "ms_per_iter": round(1e3 * dt / args.iters, 3),
             "fetch_closed": bool(args.fetch_close)}
        if args.fetch_close:
            r["fetch_rtt_ms"] = round(1e3 * fetch_ms, 3)
        if rows is not None:
            r["rows_per_sec"] = round(rows * args.iters / dt)
        results.append(r)
        print(json.dumps(r), flush=True)

    # -- cms ---------------------------------------------------------------
    def cms_init():
        return cms.init(depth=4, log2_width=16)

    bench("cms_update", f"[{n}] keys, 4x2^16",
          lambda s, k: cms.update(s, k), cms_init, keys, rows=n)
    bench("cms_update_conservative", f"[{n}] keys, 4x2^16",
          lambda s, k: cms.update_conservative(s, k), cms_init, keys,
          rows=n)
    bench("cms_query", f"[{n}] keys, 4x2^16",
          lambda s, k: s._replace(
              seeds=s.seeds + (cms.query(s, k) > (1 << 30)).astype(
                  s.seeds.dtype).sum()),   # keep state-shaped for donate
          cms_init, keys, rows=n)

    # -- hll ---------------------------------------------------------------
    bench("hll_update", f"[{n}] keys, 64 groups, p=12",
          lambda s, g, k: hll.update(s, g, k),
          lambda: hll.init(groups=64, precision=12), groups, keys, rows=n)

    # -- entropy / mxu hist -----------------------------------------------
    feats = jnp.stack([keys, keys ^ 0x5A5A, keys >> 3, keys << 1])
    bench("entropy_update_mxu", f"[4,{n}] -> 2^12 buckets",
          lambda s, f, m: entropy.update(s, f, None, m),
          lambda: entropy.init(features=4, log2_buckets=12), feats,
          mask, rows=n)

    idx = jnp.asarray(rng.integers(0, 1 << 12, (4, n), dtype=np.uint32))

    def hist_step(acc, ix):
        return acc + mxu_hist.hist(ix, 1 << 12).astype(acc.dtype)

    bench("mxu_hist", f"[4,{n}] -> 2^12", hist_step,
          lambda: jnp.zeros((4, 1 << 12), jnp.int32), idx, rows=n)

    # Pallas VMEM-resident accumulator vs the XLA scan carry, at the
    # CMS shape (the BENCH kernel hot path). Real TPUs only: the Mosaic
    # interpreter would measure nothing real, and the kernel's TPU
    # compiler params don't lower on GPU.
    if backend in ("tpu", "axon"):
        from deepflow_tpu.ops.pallas_hist import hist_pallas

        idx16 = jnp.asarray(rng.integers(0, 1 << 16, (4, n),
                                         dtype=np.int32))

        for name, fn in (
                ("hist_xla_2e16",
                 lambda ix, w: mxu_hist.hist(ix, w, method="xla")),
                ("hist_pallas_2e16",
                 lambda ix, w: hist_pallas(ix, w))):
            bench(name, f"[4,{n}] -> 2^16",
                  lambda acc, ix, f=fn: acc + f(ix, 1 << 16),
                  lambda: jnp.zeros((4, 1 << 16), jnp.float32), idx16,
                  rows=n)

        # ISSUE 9 whole-step A/B at the staged-lane production shape:
        # update over one staged plane with the CMS+entropy histogram
        # half unfused (XLA) vs fused into the single Pallas kernel
        # (ops/pallas_sketch.py) — the on-silicon verdict its STATUS
        # note calls for. Bit-identical outputs within the 2^24
        # cell-sum bound (tests/test_staging.py, ops/pallas_sketch.py);
        # this measures only the dispatch/residency difference.
        from deepflow_tpu.models import flow_suite as fs

        lane_plane = jnp.asarray(
            rng.integers(0, 1 << 32, (4, n), dtype=np.uint32))
        lane_n = jnp.uint32(n)
        cfg_u = fs.FlowSuiteConfig(fused_hists=False)
        cfg_f = fs.FlowSuiteConfig(fused_hists=True)

        def lanes_step_unfused(s, p, m):
            lanes = {"ip_src": p[0], "ip_dst": p[1],
                     "ports": p[2], "proto_pkts": p[3]}
            mask = jnp.arange(p.shape[1]) < m
            return fs.update(s, fs.unpack_lanes(lanes), mask, cfg_u)

        bench("lanes_step_unfused", f"[4,{n}] staged plane, prod cfg",
              lanes_step_unfused, lambda: fs.init(cfg_u),
              lane_plane, lane_n, rows=n)
        bench("lanes_step_fused_pallas",
              f"[4,{n}] staged plane, prod cfg",
              lambda s, p, m: fs.update_lanes_fused(s, p, m, cfg_f),
              lambda: fs.init(cfg_f), lane_plane, lane_n, rows=n)

    # -- topk admission ----------------------------------------------------
    # populated, NON-donated sketch shared by the ring benches
    query_sketch = jax.jit(cms.update)(cms_init(), keys)
    jax.block_until_ready(query_sketch)
    bench("topk_offer_sampled", f"[{n}] keys, ring 512, 1/16 sample",
          lambda s, k, sk: topk.offer(s, k, sk, sample_log2=4),
          lambda: topk.init(ring_size=512), keys, query_sketch, rows=n)
    bench("topk_offer_full", f"[{n}] keys, ring 512",
          lambda s, k, sk: topk.offer(s, k, sk),
          lambda: topk.init(ring_size=512), keys, query_sketch, rows=n)

    # -- ddsketch ----------------------------------------------------------
    from deepflow_tpu.ops import ddsketch

    dd_cfg = ddsketch.DDSketchConfig()
    rrt = jnp.asarray(rng.integers(1, 1_000_000, n).astype(np.uint32))
    bench("ddsketch_update",
          f"[{n}] values, {dd_cfg.groups}x{dd_cfg.buckets}",
          lambda s, g, v: ddsketch.update(s, g, v, cfg=dd_cfg),
          lambda: ddsketch.init(dd_cfg),
          (groups % np.uint32(dd_cfg.groups)).astype(jnp.int32), rrt,
          rows=n)

    # -- pca ---------------------------------------------------------------
    x = jnp.asarray(rng.normal(size=(min(n, 1 << 17), 12)), jnp.float32)
    bench("pca_update", f"[{x.shape[0]},12] k=3",
          lambda s, xx: pca.update(s, xx), lambda: pca.init(12, 3), x,
          rows=x.shape[0])

    only = args.only.split(",") if args.only else None

    def host_bench(name, shape, fn, rows, iters, bench_backend="host"):
        """Plain-callable timing (warmup once, time `iters`) with the
        same JSON emit as the jitted benches."""
        if only is not None and name not in only:
            return
        fn()
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        dt = time.perf_counter() - t0
        r = {"bench": name, "shape": shape, "backend": bench_backend,
             "ms_per_iter": round(1e3 * dt / iters, 3),
             "rows_per_sec": round(rows * iters / dt)}
        results.append(r)
        print(json.dumps(r), flush=True)

    # -- device GROUP BY vs host group-ids --------------------------------
    from deepflow_tpu.store.rollup import group_reduce

    gcols = {"ip": rng.integers(0, 4096, n).astype(np.uint32),
             "port": rng.integers(0, 64, n).astype(np.uint32),
             "bytes": rng.integers(0, 1500, n).astype(np.uint32)}
    for method in ("host", "device"):
        host_bench(
            f"group_reduce_{method}", f"[{n}] rows, 2 keys",
            lambda m=method: group_reduce(gcols, ["ip", "port"],
                                          {"bytes": "sum"}, method=m),
            rows=n, iters=max(4, args.iters // 4), bench_backend=backend)

    # -- sketch-lane pack (host) ------------------------------------------
    from deepflow_tpu.models import flow_suite

    pcols = {k: rng.integers(0, 2**31, n, dtype=np.uint64).astype(np.uint32)
             for k in ("ip_src", "ip_dst", "port_src", "port_dst",
                       "proto", "packet_tx", "packet_rx")}
    host_bench("pack_lanes", f"[{n}] rows -> 4 planes",
               lambda: flow_suite.pack_lanes(pcols), rows=n,
               iters=args.iters)

    # -- native decoder (host C++, no jit) --------------------------------
    if only is None or "native_decode" in only:
        from deepflow_tpu.decode import native
        from deepflow_tpu.replay.generator import SyntheticAgent
        from deepflow_tpu.wire.codec import pack_pb_records

        if native.available():
            agent = SyntheticAgent()
            nrec = 1 << 16
            cols, records = agent.l4_batch(nrec)
            payload = pack_pb_records(records)
            out32 = np.empty((len(native.L4_COLS32), nrec), np.uint32)
            out64 = np.empty((len(native.L4_COLS64), nrec), np.uint64)
            # MT speedup is bounded by the cores this cgroup actually
            # grants (the build container exposes ONE); report it so a
            # flat mt number on a 1-core box reads as expected, not
            # broken. The pool's correctness is gated by the ci.sh TSAN
            # step at 1-8 threads regardless of core count.
            n_cores = len(os.sched_getaffinity(0))
            for threads in (1, 0):   # 0 = all cores
                native.decode_l4_into(payload, out32, out64,
                                      n_threads=threads)
                t0 = time.perf_counter()
                iters = max(4, args.iters // 2)
                for _ in range(iters):
                    rows, bad, _ = native.decode_l4_into(
                        payload, out32, out64, n_threads=threads)
                dt = time.perf_counter() - t0
                r = {"bench": "native_decode_mt" if threads == 0
                     else "native_decode",
                     "shape": f"[{nrec}] TaggedFlow, "
                     f"{len(native.L4_COLS32) + len(native.L4_COLS64)} cols",
                     "backend": "host",
                     "ms_per_iter": round(1e3 * dt / iters, 3),
                     "rows_per_sec": round(nrec * iters / dt)}
                if threads == 0:
                    r["cores_available"] = n_cores
                results.append(r)
                print(json.dumps(r), flush=True)

    print(json.dumps({"bench": "summary", "backend": backend,
                      "kernels": len(results)}))


if __name__ == "__main__":
    main()

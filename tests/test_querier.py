"""Querier: SQL parse goldens, execution vs numpy, PromQL, HTTP API."""

import json
import urllib.request

import numpy as np
import pytest

from deepflow_tpu.querier import QueryEngine, parse_sql
from deepflow_tpu.querier.promql import PromEngine, parse_promql
from deepflow_tpu.querier.server import QuerierServer
from deepflow_tpu.querier.sql import Agg, BinOp, Column, Select, Show
from deepflow_tpu.store import AggKind, ColumnSpec, Store, TableSchema
from deepflow_tpu.store.dict_store import TagDictRegistry


# -- parser goldens --------------------------------------------------------
def test_parse_select_golden():
    s = parse_sql(
        "SELECT ip_dst, Sum(byte_tx) AS bytes, Sum(retrans)/Sum(packet_tx) "
        "FROM l4_flow_log WHERE timestamp >= 100 AND timestamp < 200 "
        "AND proto = 6 GROUP BY ip_dst ORDER BY bytes DESC LIMIT 10")
    assert isinstance(s, Select)
    assert s.table == "l4_flow_log"
    assert [c.op for c in s.where] == [">=", "<", "="]
    assert s.group_by == ["ip_dst"]
    assert s.order_by == [("bytes", True)]
    assert s.limit == 10
    assert isinstance(s.items[2].expr, BinOp)
    assert isinstance(s.items[2].expr.left, Agg)


def test_parse_show():
    assert parse_sql("show databases") == Show("databases")
    assert parse_sql("SHOW TAGS FROM l4_flow_log") == \
        Show("tags", "l4_flow_log")
    with pytest.raises(ValueError):
        parse_sql("DROP TABLE x")


# -- execution -------------------------------------------------------------
@pytest.fixture
def engine(tmp_path):
    store = Store(str(tmp_path))
    schema = TableSchema(
        name="flows",
        columns=(
            ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
            ColumnSpec("ip", np.dtype(np.uint32), AggKind.KEY),
            ColumnSpec("proto", np.dtype(np.uint32), AggKind.KEY),
            ColumnSpec("bytes", np.dtype(np.uint32), AggKind.SUM),
            ColumnSpec("rtt", np.dtype(np.uint32), AggKind.MAX),
        ))
    t = store.create_table("flow_log", schema)
    rng = np.random.default_rng(3)
    n = 2000
    cols = {
        "timestamp": rng.integers(0, 100, n).astype(np.uint32),
        "ip": rng.integers(1, 5, n).astype(np.uint32),
        "proto": np.where(rng.random(n) < 0.5, 6, 17).astype(np.uint32),
        "bytes": rng.integers(0, 1000, n).astype(np.uint32),
        "rtt": rng.integers(0, 9999, n).astype(np.uint32),
    }
    t.append(cols)
    eng = QueryEngine(store, TagDictRegistry(None))
    return eng, cols


def test_group_by_matches_numpy(engine):
    eng, cols = engine
    res = eng.execute("SELECT ip, Sum(bytes) AS b, Max(rtt) AS r, Count(*) "
                      "AS n FROM flows WHERE proto = 6 GROUP BY ip "
                      "ORDER BY ip")
    sel = cols["proto"] == 6
    for row in res.values:
        ip, b, r, n = row
        m = sel & (cols["ip"] == ip)
        assert b == int(cols["bytes"][m].sum())
        assert r == int(cols["rtt"][m].max())
        assert n == int(m.sum())


def test_derived_metric_and_avg(engine):
    eng, cols = engine
    res = eng.execute("SELECT Avg(bytes) AS a, Sum(bytes)/Count(*) AS d "
                      "FROM flows")
    a, d = res.values[0]
    assert a == pytest.approx(cols["bytes"].mean(), rel=1e-9)
    assert d == pytest.approx(cols["bytes"].mean(), rel=1e-9)


def test_time_pruning_and_in(engine):
    eng, cols = engine
    res = eng.execute("SELECT Count(*) AS n FROM flows WHERE "
                      "timestamp >= 10 AND timestamp < 20 AND ip IN (1, 2)")
    m = (cols["timestamp"] >= 10) & (cols["timestamp"] < 20) & \
        np.isin(cols["ip"], [1, 2])
    assert res.values[0][0] == int(m.sum())


def test_raw_rows_limit(engine):
    eng, _ = engine
    res = eng.execute("SELECT ip, bytes FROM flows LIMIT 5")
    assert res.columns == ["ip", "bytes"]
    assert len(res.values) == 5


def test_show_tags_metrics(engine):
    eng, _ = engine
    tags = eng.execute("SHOW TAGS FROM flows")
    assert ["timestamp", "ip", "proto"] == [r[0] for r in tags.values]
    mets = eng.execute("SHOW METRICS FROM flows")
    assert [r[0] for r in mets.values] == ["bytes", "rtt"]


# -- promql ----------------------------------------------------------------
def test_parse_promql():
    from deepflow_tpu.querier.promql import AggExpr, Func, Selector
    e = parse_promql('sum by (job) (rate(http_requests_total'
                     '{job=~"api.*", env!="dev"}[5m]))')
    assert isinstance(e, AggExpr)
    assert e.op == "sum" and e.by == ("job",)
    assert isinstance(e.arg, Func) and e.arg.name == "rate"
    sel = e.arg.args[0]
    assert isinstance(sel, Selector)
    assert sel.metric == "http_requests_total"
    assert sel.range_s == 300
    assert ("env", "!=", "dev") in sel.matchers
    off = parse_promql('rps offset 5m')
    assert off == Selector("rps", (), None, 300)
    q = parse_promql('histogram_quantile(0.9, '
                     'rate(rrt_bucket[1m])) * 2')
    from deepflow_tpu.querier.promql import Bin, Num
    assert isinstance(q, Bin) and q.op == "*" and q.right == Num(2.0)


@pytest.fixture
def prom(tmp_path):
    from deepflow_tpu.pipelines.ext_metrics import SAMPLE_TABLE
    store = Store(str(tmp_path / "store"))
    dicts = TagDictRegistry(str(tmp_path / "store"))
    t = store.create_table("ext_metrics", SAMPLE_TABLE)
    md, ld = dicts.get("metric_name"), dicts.get("label_set")
    mh = md.encode_one("rps")
    rows = []
    for job, start in (("api", 10.0), ("web", 100.0)):
        lh = ld.encode_one(f"job={job}")
        for i in range(10):
            rows.append((1000 + i * 10, mh, lh, start + i))
    arr = np.array(rows)
    t.append({"timestamp": arr[:, 0].astype(np.uint32),
              "metric": arr[:, 1].astype(np.uint32),
              "labels": arr[:, 2].astype(np.uint32),
              "value": arr[:, 3].astype(np.float32)})
    return PromEngine(store, dicts), store, dicts


def test_promql_instant_and_rate(prom):
    eng, _, _ = prom
    out = eng.query('rps{job="api"}', at=1100)
    assert len(out) == 1
    assert float(out[0]["value"][1]) == 19.0   # last sample
    out = eng.query('rate(rps[2m])', at=1100)
    assert len(out) == 2
    # both series rise 1 per 10s. Upstream extrapolatedRate semantics:
    # window [980, 1100], samples 1000..1090 -> delta 9 over 90s
    # sampled, extrapolated by (90 + 5 + 10)/90 (start is beyond the
    # 1.1x-interval threshold -> half interval; end is within), over
    # the 120s range: 9 * (105/90) / 120 = 0.0875
    for r in out:
        assert float(r["value"][1]) == pytest.approx(0.0875)
    out = eng.query('sum by (job) (rps)', at=1100)
    assert {r["metric"]["job"]: float(r["value"][1]) for r in out} == \
        {"api": 19.0, "web": 109.0}


# -- http ------------------------------------------------------------------
def test_http_api(engine, prom):
    eng, cols = engine
    peng, store, dicts = prom
    srv = QuerierServer(eng.store, eng.tag_dicts
                        if eng.tag_dicts is not None else TagDictRegistry(None),
                        port=0)
    srv.start()
    try:
        body = "db=flow_log&sql=" + urllib.parse.quote(
            "SELECT Count(*) AS n FROM flows")
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/query", data=body.encode(),
            headers={"Content-Type": "application/x-www-form-urlencoded"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            payload = json.load(resp)
        assert payload["result"]["columns"] == ["n"]
        assert payload["result"]["values"][0][0] == 2000
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/health", timeout=5) as resp:
            assert json.load(resp)["status"] == "ok"
    finally:
        srv.close()


import urllib.parse  # noqa: E402  (used in test_http_api)


def test_debug_server():
    from deepflow_tpu.runtime.debug import DebugServer, debug_request
    from deepflow_tpu.runtime.stats import StatsRegistry

    stats = StatsRegistry()
    stats.register("decoder.l4", lambda: {"records": 42})
    srv = DebugServer(stats, port=0)
    srv.start()
    try:
        assert debug_request("ping", port=srv.port)["data"] == "pong"
        out = debug_request("counters", port=srv.port, module="decoder")
        assert out["ok"] and out["data"]["decoder.l4"]["records"] == 42
        assert not debug_request("nope", port=srv.port)["ok"]
    finally:
        srv.close()


def test_promql_query_range(prom):
    eng, _, _ = prom
    # matrix over the sample window: both series step up 1 per 10s
    out = eng.query_range('rps{job="api"}', start=1000, end=1090, step=30)
    assert len(out) == 1
    vals = out[0]["values"]
    assert vals == [[1000, "10.0"], [1030, "13.0"], [1060, "16.0"],
                    [1090, "19.0"]]
    # rate over the grid
    out = eng.query_range('rate(rps[1m])', start=1060, end=1090, step=30)
    assert len(out) == 2
    for series in out:
        for _, v in series["values"]:
            assert float(v) == pytest.approx(0.1)
    # aggregated matrix
    out = eng.query_range('sum by (job) (rps)', start=1090, end=1090, step=10)
    assert {r["metric"]["job"]: r["values"][0][1] for r in out} == \
        {"api": "19.0", "web": "109.0"}
    # grid points before the first sample are absent, not zero
    out = eng.query_range('rps{job="api"}', start=400, end=1000, step=300)
    assert out[0]["values"] == [[1000, "10.0"]]


def test_promql_query_range_validates(prom):
    eng, _, _ = prom
    with pytest.raises(ValueError):
        eng.query_range("rps", start=100, end=50, step=10)
    with pytest.raises(ValueError):
        eng.query_range("rps", start=0, end=50, step=0)


def _profile_fixture(tmp_path):
    from deepflow_tpu.pipelines.profile import PROFILE_DB, PROFILE_TABLE
    from deepflow_tpu.querier.profile import ProfileQuery

    store = Store(str(tmp_path / "pstore"))
    dicts = TagDictRegistry(str(tmp_path / "pstore"))
    t = store.create_table(PROFILE_DB, PROFILE_TABLE)
    stacks = dicts.get("profile_stack")
    names = dicts.get("profile_name")
    svc = names.encode_one("checkout")
    cpu = names.encode_one("on-cpu")
    rows = [
        ("main;handler;db_query", 10),
        ("main;handler;db_query", 5),
        ("main;handler;render", 7),
        ("main;gc", 3),
    ]
    n = len(rows)
    t.append({
        "timestamp": np.full(n, 1000, np.uint32),
        "app_service": np.full(n, svc, np.uint32),
        "event_type": np.full(n, cpu, np.uint32),
        "stack": np.array([stacks.encode_one(s) for s, _ in rows],
                          np.uint32),
        "pid": np.full(n, 1, np.uint32),
        "vtap_id": np.full(n, 1, np.uint32),
        "pod_id": np.zeros(n, np.uint32),
        "value": np.array([v for _, v in rows], np.uint32),
    })
    return ProfileQuery(store, dicts)


def test_profile_flame_graph(tmp_path):
    pq = _profile_fixture(tmp_path)
    tree = pq.flame(app_service="checkout")
    assert tree["total_value"] == 25
    main = tree["children"][0]
    assert main["name"] == "main" and main["total_value"] == 25
    handler = main["children"][0]
    assert handler["name"] == "handler" and handler["total_value"] == 22
    # children sorted by total, leaf self-values correct
    assert [c["name"] for c in handler["children"]] == ["db_query", "render"]
    assert handler["children"][0]["self_value"] == 15
    assert main["children"][1]["name"] == "gc"
    assert main["children"][1]["self_value"] == 3
    # filter that matches nothing
    assert pq.flame(app_service="nope")["total_value"] == 0


def test_profile_top_functions(tmp_path):
    pq = _profile_fixture(tmp_path)
    top = pq.top_functions(event_type="on-cpu")
    by_name = {r["name"]: r for r in top}
    assert by_name["db_query"]["self_value"] == 15
    assert by_name["handler"]["total_value"] == 22
    assert by_name["handler"]["self_value"] == 0
    assert by_name["main"]["total_value"] == 25


def test_http_query_range_and_profile_endpoints(tmp_path, prom):
    import urllib.request as _rq

    peng, store, dicts = prom
    # profile rows live in the same store/dicts for this server instance
    from deepflow_tpu.pipelines.profile import PROFILE_DB, PROFILE_TABLE
    t = store.create_table(PROFILE_DB, PROFILE_TABLE)
    stacks, names = dicts.get("profile_stack"), dicts.get("profile_name")
    t.append({
        "timestamp": np.array([1000], np.uint32),
        "app_service": np.array([names.encode_one("checkout")], np.uint32),
        "event_type": np.array([names.encode_one("on-cpu")], np.uint32),
        "stack": np.array([stacks.encode_one("main;work")], np.uint32),
        "pid": np.array([1], np.uint32),
        "vtap_id": np.array([1], np.uint32),
        "pod_id": np.array([0], np.uint32),
        "value": np.array([9], np.uint32),
    })
    srv = QuerierServer(store, dicts, port=0)
    srv.start()
    try:
        url = (f"http://127.0.0.1:{srv.port}/api/v1/query_range?"
               + urllib.parse.urlencode(
                   {"query": "rps", "start": 1090, "end": 1090, "step": 10}))
        with _rq.urlopen(url, timeout=5) as resp:
            payload = json.load(resp)
        assert payload["status"] == "success"
        assert payload["data"]["resultType"] == "matrix"
        assert len(payload["data"]["result"]) == 2
        # malformed: missing step
        try:
            _rq.urlopen(
                f"http://127.0.0.1:{srv.port}/api/v1/query_range?query=rps",
                timeout=5)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
        with _rq.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/profile/flame"
                "?app_service=checkout", timeout=5) as resp:
            tree = json.load(resp)["result"]
        assert tree["total_value"] == 9
        assert tree["children"][0]["name"] == "main"
        with _rq.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/profile/top", timeout=5) \
                as resp:
            top = json.load(resp)["result"]
        assert {r["name"] for r in top} == {"main", "work"}
    finally:
        srv.close()
        dicts.close()


import urllib.error  # noqa: E402  (used above)


def test_query_paths_never_grow_dicts(prom, tmp_path):
    """Unknown metric / service names on the read path must not journal
    new dictionary entries (a typo'd dashboard would grow them forever)."""
    eng, store, dicts = prom
    md = dicts.get("metric_name")
    before = len(md._s2h) if hasattr(md, "_s2h") else None
    assert eng.query("totally_unknown_metric") == []
    assert eng.query_range("totally_unknown_metric", 0, 10, 5) == []
    assert md.lookup("totally_unknown_metric") is None
    pq = _profile_fixture(tmp_path)
    assert pq.flame(app_service="ghost-service")["total_value"] == 0
    assert pq.names.lookup("ghost-service") is None


def test_query_range_disjoint_series_no_warning(prom):
    """max() over series alive at disjoint grid points must not emit
    All-NaN warnings (or crash under -W error)."""
    import warnings

    eng, store, dicts = prom
    from deepflow_tpu.pipelines.ext_metrics import SAMPLE_TABLE
    md, ld = dicts.get("metric_name"), dicts.get("label_set")
    t = store.table("ext_metrics", "ext_samples")
    mh = md.encode_one("spiky")
    t.append({"timestamp": np.array([1000, 3000], np.uint32),
              "metric": np.full(2, mh, np.uint32),
              "labels": np.array([ld.encode_one("job=a"),
                                  ld.encode_one("job=b")], np.uint32),
              "value": np.array([1.0, 2.0], np.float32)})
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = eng.query_range("max(spiky)", start=1000, end=3000, step=500)
    pts = dict(out[0]["values"])
    # only the sample instants are within the 300s lookback of a grid
    # point; the dead middle of the grid is absent, not zero or NaN
    assert pts == {1000: "1.0", 3000: "2.0"}


def test_http_post_query_range_and_inclusive_profile_end(tmp_path, prom):
    """Grafana POSTs /api/v1/query_range with a form body; profile
    endpoints treat end as inclusive."""
    import urllib.request as _rq

    peng, store, dicts = prom
    from deepflow_tpu.pipelines.profile import PROFILE_DB, PROFILE_TABLE
    t = store.create_table(PROFILE_DB, PROFILE_TABLE)
    stacks, names = dicts.get("profile_stack"), dicts.get("profile_name")
    t.append({
        "timestamp": np.array([1000], np.uint32),
        "app_service": np.array([names.encode_one("svc")], np.uint32),
        "event_type": np.array([names.encode_one("on-cpu")], np.uint32),
        "stack": np.array([stacks.encode_one("main;work")], np.uint32),
        "pid": np.array([1], np.uint32),
        "vtap_id": np.array([1], np.uint32),
        "pod_id": np.array([0], np.uint32),
        "value": np.array([9], np.uint32),
    })
    srv = QuerierServer(store, dicts, port=0)
    srv.start()
    try:
        body = urllib.parse.urlencode(
            {"query": "rps", "start": 1090, "end": 1090, "step": 10}
        ).encode()
        req = _rq.Request(
            f"http://127.0.0.1:{srv.port}/api/v1/query_range", data=body,
            headers={"Content-Type": "application/x-www-form-urlencoded"})
        with _rq.urlopen(req, timeout=5) as resp:
            payload = json.load(resp)
        assert payload["status"] == "success"
        assert len(payload["data"]["result"]) == 2
        # sample at exactly end=1000 is included
        with _rq.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/profile/flame"
                "?start=900&end=1000", timeout=5) as resp:
            assert json.load(resp)["result"]["total_value"] == 9
    finally:
        srv.close()
        dicts.close()


def test_cli_promql_flag_conflicts(capsys):
    from deepflow_tpu.cli import main as cli_main

    assert cli_main(["promql", "rps", "--start", "1"]) == 1
    assert "together" in capsys.readouterr().err
    assert cli_main(["promql", "rps", "--time", "5",
                     "--start", "1", "--end", "2"]) == 1
    assert "conflicts" in capsys.readouterr().err


def test_derived_metric_library(engine):
    """Named derived metrics expand to expressions (reference:
    engine/clickhouse/metrics registry)."""
    from deepflow_tpu.store import AggKind, ColumnSpec, Store, TableSchema

    eng, cols = engine
    # a metrics-shaped table in the same store
    t = eng.store.create_table("flow_metrics", TableSchema(
        name="m",
        columns=(
            ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
            ColumnSpec("ip", np.dtype(np.uint32), AggKind.KEY),
            ColumnSpec("rtt_sum", np.dtype(np.uint32), AggKind.SUM),
            ColumnSpec("rtt_count", np.dtype(np.uint32), AggKind.SUM),
            ColumnSpec("byte_tx", np.dtype(np.uint32), AggKind.SUM),
            ColumnSpec("byte_rx", np.dtype(np.uint32), AggKind.SUM),
        )))
    t.append({"timestamp": np.array([1, 1, 2], np.uint32),
              "ip": np.array([10, 10, 11], np.uint32),
              "rtt_sum": np.array([100, 300, 40], np.uint32),
              "rtt_count": np.array([1, 3, 2], np.uint32),
              "byte_tx": np.array([5, 5, 7], np.uint32),
              "byte_rx": np.array([1, 1, 3], np.uint32)})
    res = eng.execute("SELECT ip, rtt_avg, byte FROM m GROUP BY ip "
                      "ORDER BY ip")
    assert res.columns == ["ip", "rtt_avg", "byte"]
    assert res.values[0] == [10, 100.0, 12]     # (100+300)/(1+3), 5+5+1+1
    assert res.values[1] == [11, 20.0, 10]
    # SHOW METRICS lists the satisfiable derived metrics with units
    show = eng.execute("SHOW METRICS FROM m")
    by_name = {r[0]: r for r in show.values}
    assert by_name["rtt_avg"][1] == "derived"
    assert by_name["rtt_avg"][2] == "us"
    assert "retrans_ratio" not in by_name       # columns absent
    # real columns always win over library names: a table column named
    # like a library metric is listed once, as the real column
    t2 = eng.store.create_table("flow_metrics", TableSchema(
        name="m2",
        columns=(
            ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
            ColumnSpec("new_flow", np.dtype(np.uint32), AggKind.SUM),
        )))
    t2.append({"timestamp": np.array([1, 1], np.uint32),
               "new_flow": np.array([2, 3], np.uint32)})
    show2 = eng.execute("SHOW METRICS FROM m2")
    names = [r[0] for r in show2.values]
    assert names.count("new_flow") == 1
    assert [r for r in show2.values if r[0] == "new_flow"][0][1] == "sum"
    # SELECT of the shadowed name aggregates the REAL column
    res2 = eng.execute("SELECT Sum(new_flow) AS n FROM m2")
    assert res2.values[0][0] == 5


def test_prometheus_remote_read(prom):
    """Remote-read serves snappy prompb matrices a federated Prometheus
    can pull (reference: querier/app/prometheus remote read)."""
    import urllib.request as _rq

    from deepflow_tpu.utils import snappy
    from deepflow_tpu.wire.gen import telemetry_pb2 as pb

    peng, store, dicts = prom
    srv = QuerierServer(store, dicts, port=0)
    srv.start()
    try:
        req = pb.ReadRequest()
        q = req.queries.add()
        q.start_timestamp_ms = 1000_000
        q.end_timestamp_ms = 1090_000
        m = q.matchers.add()
        m.type = pb.LabelMatcher.EQ
        m.name = "__name__"
        m.value = "rps"
        m2 = q.matchers.add()
        m2.type = pb.LabelMatcher.RE
        m2.name = "job"
        m2.value = "a.*"
        body = snappy.compress(req.SerializeToString())
        hr = _rq.Request(f"http://127.0.0.1:{srv.port}/api/v1/read",
                         data=body,
                         headers={"Content-Type": "application/x-protobuf",
                                  "Content-Encoding": "snappy"})
        with _rq.urlopen(hr, timeout=5) as resp:
            out = pb.ReadResponse()
            out.ParseFromString(snappy.decompress(resp.read()))
        assert len(out.results) == 1
        series = out.results[0].timeseries
        assert len(series) == 1                      # only job=api matches
        labels = {l.name: l.value for l in series[0].labels}
        assert labels == {"__name__": "rps", "job": "api"}
        assert len(series[0].samples) == 10
        assert series[0].samples[0].timestamp == 1000_000
        assert series[0].samples[0].value == 10.0
        assert series[0].samples[-1].value == 19.0
    finally:
        srv.close()
        dicts.close()


def test_query_rollup_table_relative_name(tmp_path):
    """`FROM flows.1m` with db set must hit the rollup table, not be
    misread as db `flows` table `1m`."""
    import numpy as np

    from deepflow_tpu.querier import QueryEngine
    from deepflow_tpu.store import AggKind, ColumnSpec, Store, TableSchema
    from deepflow_tpu.store.dict_store import TagDictRegistry
    from deepflow_tpu.store.rollup import RollupManager

    store = Store(str(tmp_path))
    schema = TableSchema(
        name="flows",
        columns=(ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("ip", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("bytes", np.dtype(np.uint32), AggKind.SUM)))
    mgr = RollupManager(store, "flow_log", schema, intervals=(60,))
    t0 = 1_700_000_040
    mgr.base.append({
        "timestamp": np.arange(t0, t0 + 120, dtype=np.uint32),
        "ip": np.tile(np.arange(2, dtype=np.uint32), 60),
        "bytes": np.full(120, 10, np.uint32)})
    assert mgr.advance(now=t0 + 600)[60] == 4
    eng = QueryEngine(store, TagDictRegistry(None))
    for sql in ("SELECT ip, Sum(bytes) AS b FROM flows.1m GROUP BY ip",
                "SELECT ip, Sum(bytes) AS b FROM flow_log.flows.1m "
                "GROUP BY ip"):
        res = eng.execute(sql, db="flow_log")
        assert sorted(r[1] for r in res.values) == [600, 600], sql


def test_explicit_db_stays_scoped(tmp_path):
    import numpy as np
    import pytest

    from deepflow_tpu.querier import QueryEngine
    from deepflow_tpu.store import AggKind, ColumnSpec, Store, TableSchema
    from deepflow_tpu.store.dict_store import TagDictRegistry

    store = Store(str(tmp_path))
    t = store.create_table("prom", TableSchema(
        name="samples",
        columns=(ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("v", np.dtype(np.uint32), AggKind.SUM))))
    t.append({"timestamp": np.arange(3, dtype=np.uint32),
              "v": np.ones(3, np.uint32)})
    eng = QueryEngine(store, TagDictRegistry(None))
    # unscoped: global search finds it
    assert eng.execute("SELECT Count(*) AS n FROM samples"
                       ).values[0][0] == 3
    # a typo'd db must error, not answer from another database
    with pytest.raises(KeyError, match="flow_log"):
        eng.execute("SELECT Count(*) AS n FROM samples", db="flow_log")


def test_where_by_resource_name(tmp_path):
    """WHERE pod_id = 'name' filters through the tagrecorder (the
    reference's auto-tag name conditions), including duplicate names."""
    import numpy as np

    from deepflow_tpu.controller import ResourceModel
    from deepflow_tpu.controller.model import make_resource
    from deepflow_tpu.controller.tagrecorder import TagRecorder
    from deepflow_tpu.querier import QueryEngine
    from deepflow_tpu.store import AggKind, ColumnSpec, Store, TableSchema
    from deepflow_tpu.store.dict_store import TagDictRegistry

    model = ResourceModel()
    model.update_domain("d", [
        make_resource("pod", 7, "api-0", "d"),
        make_resource("pod", 8, "web-0", "d"),
        make_resource("pod", 9, "api-0", "d"),   # same name, other ns
    ])
    tr = TagRecorder(model)
    store = Store(str(tmp_path))
    t = store.create_table("flow_log", TableSchema(
        name="flows",
        columns=(ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("pod_id_0", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("bytes", np.dtype(np.uint32), AggKind.SUM))))
    t.append({"timestamp": np.arange(4, dtype=np.uint32),
              "pod_id_0": np.array([7, 8, 9, 7], np.uint32),
              "bytes": np.array([10, 20, 30, 40], np.uint32)})
    eng = QueryEngine(store, TagDictRegistry(None), tagrecorder=tr)
    res = eng.execute("SELECT Sum(bytes) AS b FROM flows "
                      "WHERE pod_id_0 = 'api-0'", db="flow_log")
    assert res.values[0][0] == 80   # ids 7 and 9
    res = eng.execute("SELECT Sum(bytes) AS b FROM flows "
                      "WHERE pod_id_0 = 'web-0'", db="flow_log")
    assert res.values[0][0] == 20
    res = eng.execute("SELECT Sum(bytes) AS b FROM flows "
                      "WHERE pod_id_0 != 'api-0'", db="flow_log")
    assert res.values[0][0] == 20
    # unknown name matches nothing
    res = eng.execute("SELECT Count(*) AS n FROM flows "
                      "WHERE pod_id_0 = 'nope'", db="flow_log")
    assert res.values[0][0] == 0
    # IN with a duplicate name flattens to all matching ids
    res = eng.execute("SELECT Sum(bytes) AS b FROM flows "
                      "WHERE pod_id_0 IN ('api-0', 'web-0')",
                      db="flow_log")
    assert res.values[0][0] == 100


def test_promql_regex_matchers(prom):
    eng, _, _ = prom
    out = eng.query('rps{job=~"a.*"}', at=1100)
    assert len(out) == 1 and out[0]["metric"]["job"] == "api"
    out = eng.query('rps{job!~"a.*"}', at=1100)
    assert len(out) == 1 and out[0]["metric"]["job"] == "web"
    out = eng.query('rps{job=~".*"}', at=1100)
    assert len(out) == 2


def test_promql_discovery_endpoints(prom, tmp_path):
    """Grafana datasource discovery: labels, label values, series."""
    import json
    import urllib.parse
    import urllib.request

    from deepflow_tpu.querier.server import QuerierServer

    eng, store, dicts = prom
    assert eng.label_names() == ["__name__", "job"]
    assert eng.label_values("job") == ["api", "web"]
    assert eng.label_values("__name__") == ["rps"]
    series = eng.series('rps{job="api"}', start=900, end=1200)
    assert series == [{"__name__": "rps", "job": "api"}]

    srv = QuerierServer(store, dicts, port=0)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(f"{base}/api/v1/labels") as r:
            assert json.load(r)["data"] == ["__name__", "job"]
        with urllib.request.urlopen(f"{base}/api/v1/label/job/values") as r:
            assert json.load(r)["data"] == ["api", "web"]
        q = urllib.parse.urlencode(
            {"match[]": "rps", "start": 900, "end": 1200})
        with urllib.request.urlopen(f"{base}/api/v1/series?{q}") as r:
            data = json.load(r)["data"]
        assert {d["job"] for d in data} == {"api", "web"}
        # repeated match[] params union (and dedupe)
        q2 = ("match%5B%5D=rps%7Bjob%3D%22api%22%7D"
              "&match%5B%5D=rps&start=900&end=1200")
        with urllib.request.urlopen(f"{base}/api/v1/series?{q2}") as r:
            data = json.load(r)["data"]
        assert len(data) == 2
        assert {d["job"] for d in data} == {"api", "web"}
    finally:
        srv.close()


def test_having_clause(tmp_path):
    import numpy as np

    from deepflow_tpu.querier import QueryEngine
    from deepflow_tpu.store import AggKind, ColumnSpec, Store, TableSchema
    from deepflow_tpu.store.dict_store import TagDictRegistry

    store = Store(str(tmp_path))
    t = store.create_table("flow_log", TableSchema(
        name="flows",
        columns=(ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("ip", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("bytes", np.dtype(np.uint32), AggKind.SUM))))
    t.append({"timestamp": np.arange(6, dtype=np.uint32),
              "ip": np.array([1, 1, 1, 2, 2, 3], np.uint32),
              "bytes": np.array([10, 10, 10, 10, 10, 10], np.uint32)})
    eng = QueryEngine(store, TagDictRegistry(None))
    res = eng.execute(
        "SELECT ip, Sum(bytes) AS b FROM flows GROUP BY ip "
        "HAVING b > 15 ORDER BY b DESC", db="flow_log")
    assert res.values == [[1, 30], [2, 20]]
    res = eng.execute(
        "SELECT ip, Count(*) AS n FROM flows GROUP BY ip "
        "HAVING n >= 2 AND n < 3", db="flow_log")
    assert res.values == [[2, 2]]
    # referencing a non-output column errors loudly
    import pytest
    with pytest.raises(ValueError, match="HAVING"):
        eng.execute("SELECT ip FROM flows GROUP BY ip HAVING nope > 1",
                    db="flow_log")


def test_having_with_dictionary_string(tmp_path):
    """HAVING on a hash column with a string literal translates through
    the dictionaries like WHERE does (and never raises TypeError)."""
    import numpy as np

    from deepflow_tpu.querier import QueryEngine
    from deepflow_tpu.store import AggKind, ColumnSpec, Store, TableSchema
    from deepflow_tpu.store.dict_store import TagDictRegistry

    store = Store(str(tmp_path))
    dicts = TagDictRegistry(None)
    ep = dicts.get("l7_endpoint")
    h1, h2 = ep.encode_one("GET /a"), ep.encode_one("GET /b")
    t = store.create_table("flow_log", TableSchema(
        name="l7",
        columns=(ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("endpoint_hash", np.dtype(np.uint32),
                            AggKind.KEY),
                 ColumnSpec("rrt_us", np.dtype(np.uint32), AggKind.SUM))))
    t.append({"timestamp": np.arange(4, dtype=np.uint32),
              "endpoint_hash": np.array([h1, h1, h2, h2], np.uint32),
              "rrt_us": np.array([10, 20, 30, 40], np.uint32)})
    eng = QueryEngine(store, dicts)
    res = eng.execute(
        "SELECT endpoint_hash, Sum(rrt_us) AS r FROM l7 "
        "GROUP BY endpoint_hash HAVING endpoint_hash = 'GET /a'",
        db="flow_log")
    assert res.values == [["GET /a", 30]]
    # unknown string matches nothing, != matches everything
    res = eng.execute(
        "SELECT endpoint_hash FROM l7 GROUP BY endpoint_hash "
        "HAVING endpoint_hash != 'nope'", db="flow_log")
    assert len(res.values) == 2


def test_select_star(tmp_path):
    import numpy as np

    from deepflow_tpu.querier import QueryEngine
    from deepflow_tpu.store import AggKind, ColumnSpec, Store, TableSchema
    from deepflow_tpu.store.dict_store import TagDictRegistry

    store = Store(str(tmp_path))
    t = store.create_table("flow_log", TableSchema(
        name="flows",
        columns=(ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("ip", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("bytes", np.dtype(np.uint32), AggKind.SUM))))
    t.append({"timestamp": np.arange(3, dtype=np.uint32),
              "ip": np.array([7, 8, 9], np.uint32),
              "bytes": np.array([1, 2, 3], np.uint32)})
    eng = QueryEngine(store, TagDictRegistry(None))
    res = eng.execute("SELECT * FROM flows ORDER BY timestamp LIMIT 2",
                      db="flow_log")
    assert res.columns == ["timestamp", "ip", "bytes"]
    assert res.values == [[0, 7, 1], [1, 8, 2]]
    # WHERE composes with *
    res = eng.execute("SELECT * FROM flows WHERE ip = 9", db="flow_log")
    assert res.values == [[2, 9, 3]]


def test_order_by_multiple_keys(tmp_path):
    import numpy as np

    from deepflow_tpu.querier import QueryEngine
    from deepflow_tpu.store import AggKind, ColumnSpec, Store, TableSchema
    from deepflow_tpu.store.dict_store import TagDictRegistry

    store = Store(str(tmp_path))
    t = store.create_table("flow_log", TableSchema(
        name="flows",
        columns=(ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("ip", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("bytes", np.dtype(np.uint32), AggKind.SUM))))
    t.append({"timestamp": np.arange(4, dtype=np.uint32),
              "ip": np.array([2, 1, 2, 1], np.uint32),
              "bytes": np.array([5, 9, 3, 9], np.uint32)})
    eng = QueryEngine(store, TagDictRegistry(None))
    res = eng.execute(
        "SELECT ip, bytes, timestamp FROM flows "
        "ORDER BY ip ASC, bytes DESC, timestamp ASC", db="flow_log")
    assert res.values == [[1, 9, 1], [1, 9, 3], [2, 5, 0], [2, 3, 2]]


def test_select_star_with_group_by_errors_cleanly(tmp_path):
    import numpy as np
    import pytest

    from deepflow_tpu.querier import QueryEngine
    from deepflow_tpu.store import AggKind, ColumnSpec, Store, TableSchema
    from deepflow_tpu.store.dict_store import TagDictRegistry

    store = Store(str(tmp_path))
    store.create_table("flow_log", TableSchema(
        name="flows",
        columns=(ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("ip", np.dtype(np.uint32), AggKind.KEY),
                 ColumnSpec("bytes", np.dtype(np.uint32), AggKind.SUM))))
    eng = QueryEngine(store, TagDictRegistry(None))
    with pytest.raises(ValueError, match="GROUP BY"):
        eng.execute("SELECT * FROM flows GROUP BY ip", db="flow_log")


def test_parse_time_bucket():
    from deepflow_tpu.querier.sql import TimeBucket
    s = parse_sql("SELECT time(60), Sum(bytes) FROM flows "
                  "GROUP BY time(60), ip ORDER BY time")
    assert s.group_by == [TimeBucket(60), "ip"]
    assert s.items[0].expr == TimeBucket(60)
    # interval() is an alias
    s2 = parse_sql("SELECT Sum(bytes) FROM flows GROUP BY interval(30)")
    assert s2.group_by == [TimeBucket(30)]
    with pytest.raises(ValueError):
        parse_sql("SELECT 1 FROM t GROUP BY time(60), time(30)")
    with pytest.raises(ValueError):
        parse_sql("SELECT 1 FROM t GROUP BY time(0)")


def test_time_bucket_matches_numpy(engine):
    """GROUP BY time(N) goldens vs a direct numpy computation."""
    eng, cols = engine
    r = eng.execute(
        "SELECT time(10), Sum(bytes) AS b FROM flows "
        "GROUP BY time(10) ORDER BY time")
    assert r.columns == ["time", "b"]
    want = {}
    for ts, by in zip((cols["timestamp"] // 10) * 10, cols["bytes"]):
        want[int(ts)] = want.get(int(ts), 0) + int(by)
    got = {int(row[0]): int(row[1]) for row in r.values}
    assert got == want
    # buckets come back sorted by the ORDER BY
    assert [row[0] for row in r.values] == sorted(got)


def test_time_bucket_with_key_and_where(engine):
    eng, cols = engine
    r = eng.execute(
        "SELECT time(20), ip, Sum(bytes) AS b FROM flows "
        "WHERE proto = 6 GROUP BY time(20), ip "
        "ORDER BY time, ip")
    m = cols["proto"] == 6
    want = {}
    for ts, ip, by in zip((cols["timestamp"][m] // 20) * 20,
                          cols["ip"][m], cols["bytes"][m]):
        want[(int(ts), int(ip))] = want.get((int(ts), int(ip)), 0) + int(by)
    got = {(int(a), int(b)): int(c) for a, b, c in r.values}
    assert got == want


def test_time_bucket_requires_group(engine):
    eng, _ = engine
    with pytest.raises(ValueError):
        eng.execute("SELECT time(60), Sum(bytes) FROM flows GROUP BY ip")
    with pytest.raises(ValueError):
        eng.execute("SELECT time(60), Sum(bytes) FROM flows "
                    "GROUP BY time(30)")


def test_promql_increase_irate_offset(prom):
    eng, _, _ = prom
    # increase = rate * range: 0.0875 * 120 = 10.5
    out = eng.query('increase(rps{job="api"}[2m])', at=1100)
    assert float(out[0]["value"][1]) == pytest.approx(10.5)
    # irate: last two samples (1080->1090), 1 per 10s
    out = eng.query('irate(rps{job="api"}[2m])', at=1100)
    assert float(out[0]["value"][1]) == pytest.approx(0.1)
    # offset 50s: instant value at 1050 is start + 5
    out = eng.query('rps{job="api"} offset 50s', at=1100)
    assert float(out[0]["value"][1]) == 15.0


def test_promql_binary_ops(prom):
    eng, _, _ = prom
    out = eng.query('rps{job="api"} * 2', at=1100)
    assert float(out[0]["value"][1]) == 38.0
    out = eng.query('rps / rps', at=1100)          # vector/vector
    assert len(out) == 2
    for r in out:
        assert float(r["value"][1]) == 1.0
    out = eng.query('rps - rps{job="api"}', at=1100)
    # one-to-one match: only the api series joins
    assert len(out) == 1 and float(out[0]["value"][1]) == 0.0


def test_promql_counter_reset_correction(prom):
    eng, store, dicts = prom
    t = store.table("ext_metrics", "ext_samples")
    mh = dicts.get("metric_name").encode_one("ctr")
    lh = dicts.get("label_set").encode_one("job=r")
    # counter climbs to 50, resets to 3, climbs again: true increase
    # within the sampled span = (50 - 10) + 3 + (13 - 3) ... corrected
    ts = np.array([1000, 1010, 1020, 1030, 1040], np.uint32)
    vs = np.array([10.0, 30.0, 50.0, 3.0, 13.0], np.float32)
    t.append({"timestamp": ts, "metric": np.full(5, mh, np.uint32),
              "labels": np.full(5, lh, np.uint32),
              "value": vs})
    out = eng.query('increase(ctr[40s])', at=1040)
    # corrected delta over [1000,1040] = (63+50) - 10 = wait:
    # corrected series = 10,30,50,53,63 -> delta 53; window == sampled
    # span exactly, no extrapolation slack beyond edges (to_start=0,
    # to_end=0), counter clamp no-op -> 53
    assert float(out[0]["value"][1]) == pytest.approx(53.0)


def test_promql_histogram_quantile(prom):
    eng, store, dicts = prom
    t = store.table("ext_metrics", "ext_samples")
    mh = dicts.get("metric_name").encode_one("lat_bucket")
    rows_le = [("0.1", 10.0), ("0.5", 70.0), ("1", 90.0), ("+Inf", 100.0)]
    for le, c in rows_le:
        lh = dicts.get("label_set").encode_one(f"job=h,le={le}")
        t.append({"timestamp": np.array([1100], np.uint32),
                  "metric": np.array([mh], np.uint32),
                  "labels": np.array([lh], np.uint32),
                  "value": np.array([c], np.float32)})
    out = eng.query('histogram_quantile(0.5, lat_bucket)', at=1100)
    assert len(out) == 1
    assert out[0]["metric"] == {"job": "h"}
    # rank = 50 -> bucket (0.1, 0.5]: 0.1 + 0.4*(50-10)/(70-10) = 0.3667
    assert float(out[0]["value"][1]) == pytest.approx(0.1 + 0.4 * 40 / 60)
    # phi=0.95 -> rank 95 -> bucket (1, +Inf] -> highest finite bound
    out = eng.query('histogram_quantile(0.95, lat_bucket)', at=1100)
    assert float(out[0]["value"][1]) == pytest.approx(1.0)


def test_promql_range_histogram_quantile(prom):
    """histogram_quantile over a range grid: per-point interpolation."""
    eng, store, dicts = prom
    t = store.table("ext_metrics", "ext_samples")
    mh = dicts.get("metric_name").encode_one("h2_bucket")
    for le, c0 in (("1", 50.0), ("+Inf", 100.0)):
        lh = dicts.get("label_set").encode_one(f"le={le}")
        t.append({"timestamp": np.array([1000, 1060], np.uint32),
                  "metric": np.full(2, mh, np.uint32),
                  "labels": np.full(2, lh, np.uint32),
                  "value": np.array([c0, c0 * 2], np.float32)})
    res = eng.query_range('histogram_quantile(0.25, h2_bucket)',
                          start=1000, end=1060, step=60)
    assert len(res) == 1
    # rank 25 of 100 (then 50 of 200) -> within (0,1]: 0.5 both points
    assert [float(v) for _, v in res[0]["values"]] == \
        pytest.approx([0.5, 0.5])


def test_promql_over_time_functions(prom):
    eng, _, _ = prom
    # samples: api = 10..19 at t = 1000,1010,...,1090
    out = eng.query('max_over_time(rps{job="api"}[1m])', at=1090)
    # window (1030, 1090]: samples 14..19 -> max 19
    assert float(out[0]["value"][1]) == 19.0
    out = eng.query('avg_over_time(rps{job="api"}[1m])', at=1090)
    assert float(out[0]["value"][1]) == pytest.approx(np.mean(
        [14, 15, 16, 17, 18, 19]))
    out = eng.query('sum_over_time(rps{job="api"}[1m])', at=1090)
    assert float(out[0]["value"][1]) == sum([14, 15, 16, 17, 18, 19])
    out = eng.query('count_over_time(rps{job="api"}[1m])', at=1090)
    assert float(out[0]["value"][1]) == 6.0
    out = eng.query('last_over_time(rps{job="api"}[1m])', at=1090)
    assert float(out[0]["value"][1]) == 19.0


def test_promql_subquery(prom):
    eng, _, _ = prom
    # instant vector evaluated on a 10s sub-grid inside a 60s window:
    # the series is sampled every 10s so every sub-grid point resolves
    out = eng.query('max_over_time(rps{job="api"}[1m:10s])', at=1090)
    assert float(out[0]["value"][1]) == 19.0
    # rate over a subquery of the raw series behaves like rate over the
    # raw samples when the sub-grid lands on the sample times
    out = eng.query('max_over_time(rate(rps{job="api"}[40s])[1m:10s])',
                    at=1090)
    assert len(out) == 1
    assert float(out[0]["value"][1]) > 0


def test_promql_subquery_edge_forms(prom):
    eng, _, _ = prom
    from deepflow_tpu.querier.promql import parse_promql, Subquery
    # subquery suffix on aggregations and histogram_quantile
    e = parse_promql('max_over_time(sum(rate(rps[5m]))[30m:1m])')
    assert isinstance(e.args[0], Subquery)
    e2 = parse_promql('max_over_time(histogram_quantile(0.9, x)[30m:1m])')
    assert isinstance(e2.args[0], Subquery)
    # default-resolution form: step picked at evaluation time
    e3 = parse_promql('avg_over_time(rps[1m:])')
    assert isinstance(e3.args[0], Subquery) and e3.args[0].step_s == 0
    out = eng.query('avg_over_time(rps{job="api"}[1m:])', at=1090)
    assert len(out) == 1 and float(out[0]["value"][1]) > 0
    # absolute step anchoring: asking at t and t+1 for the same window
    # must sample the same inner timestamps (no refresh jitter)
    a = eng.query('max_over_time(rps{job="api"}[1m:10s])', at=1090)
    b = eng.query('max_over_time(rps{job="api"}[1m:10s])', at=1091)
    assert float(a[0]["value"][1]) == float(b[0]["value"][1])


def test_with_join_two_queries(engine):
    """The reference's Grafana panel shape: two aggregated CTEs joined
    on their shared tag (clickhouse_test.go:452)."""
    eng, cols = engine
    r = eng.execute(
        "WITH q1 AS (SELECT ip, Sum(bytes) AS b FROM flows "
        "WHERE proto = 6 GROUP BY ip), "
        "q2 AS (SELECT ip, Count(*) AS n FROM flows "
        "WHERE proto = 17 GROUP BY ip) "
        "SELECT q1.ip, q1.b AS b, q2.n FROM q1 LEFT JOIN q2 "
        "ON q1.ip = q2.ip ORDER BY b DESC")
    assert r.columns == ["q1.ip", "b", "q2.n"]
    m6 = cols["proto"] == 6
    m17 = cols["proto"] == 17
    for ip, b, n in r.values:
        assert b == int(cols["bytes"][m6 & (cols["ip"] == ip)].sum())
        assert n == int((m17 & (cols["ip"] == ip)).sum())
    # descending by b
    bs = [row[1] for row in r.values]
    assert bs == sorted(bs, reverse=True)


def test_with_inner_join_drops_unmatched(engine):
    eng, cols = engine
    r = eng.execute(
        "WITH a AS (SELECT ip, Count(*) AS n FROM flows "
        "WHERE ip IN (1, 2) GROUP BY ip), "
        "b AS (SELECT ip, Count(*) AS m FROM flows "
        "WHERE ip IN (2, 3) GROUP BY ip) "
        "SELECT a.ip, a.n AS left_n, b.m FROM a JOIN b ON a.ip = b.ip")
    assert r.columns == ["a.ip", "left_n", "b.m"]
    assert [row[0] for row in r.values] == [2]     # only the overlap
    assert all(v is not None for row in r.values for v in row)


def test_left_join_none_fill_and_guards(engine):
    """LEFT JOIN misses fill None and sort last; duplicate right keys
    and duplicate CTE names are rejected, not silently mis-joined."""
    eng, cols = engine
    r = eng.execute(
        "WITH a AS (SELECT ip, Count(*) AS n FROM flows GROUP BY ip), "
        "b AS (SELECT ip, Count(*) AS m FROM flows WHERE ip = 2 "
        "GROUP BY ip) "
        "SELECT a.ip, b.m AS m FROM a LEFT JOIN b ON a.ip = b.ip "
        "ORDER BY m DESC")
    by_ip = {row[0]: row[1] for row in r.values}
    assert by_ip[2] is not None
    assert all(v is None for ip, v in by_ip.items() if ip != 2)
    # None rows sort LAST even descending
    assert r.values[0][0] == 2 and r.values[-1][1] is None
    with pytest.raises(ValueError, match="duplicate key"):
        eng.execute(
            "WITH a AS (SELECT ip, Count(*) AS n FROM flows GROUP BY ip),"
            " b AS (SELECT ip, bytes FROM flows) "
            "SELECT a.ip, b.bytes FROM a JOIN b ON a.ip = b.ip")
    with pytest.raises(ValueError, match="duplicate CTE"):
        eng.execute(
            "WITH q AS (SELECT ip FROM flows GROUP BY ip), "
            "q AS (SELECT ip FROM flows GROUP BY ip) "
            "SELECT q.ip FROM q JOIN q ON q.ip = q.ip")


def test_promql_topk_bottomk_quantile(prom):
    eng, _, _ = prom
    # api=19, web=109 at t=1090
    out = eng.query('topk(1, rps)', at=1090)
    assert len(out) == 1 and out[0]["metric"]["job"] == "web"
    assert float(out[0]["value"][1]) == 109.0
    out = eng.query('bottomk(1, rps)', at=1090)
    assert len(out) == 1 and out[0]["metric"]["job"] == "api"
    out = eng.query('quantile(0.5, rps)', at=1090)
    assert len(out) == 1
    assert float(out[0]["value"][1]) == pytest.approx((19 + 109) / 2)


def test_sql_limit_offset(engine):
    eng, _ = engine
    full = eng.execute("SELECT ip, Count(*) AS n FROM flows "
                       "GROUP BY ip ORDER BY ip")
    page = eng.execute("SELECT ip, Count(*) AS n FROM flows "
                       "GROUP BY ip ORDER BY ip LIMIT 2 OFFSET 1")
    assert page.values == full.values[1:3]


def test_show_tag_values(engine):
    """The Grafana variable-dropdown query (clickhouse.go:53)."""
    eng, cols = engine
    r = eng.execute("SHOW TAG ip VALUES FROM flows")
    assert r.columns == ["ip"]
    assert [v[0] for v in r.values] == sorted(set(cols["ip"].tolist()))
    r2 = eng.execute("SHOW TAG ip VALUES FROM flows LIMIT 2")
    assert len(r2.values) == 2
    with pytest.raises(ValueError, match="not a tag"):
        eng.execute("SHOW TAG nope VALUES FROM flows")
    # metric columns are NOT tags: float values would truncate-merge
    with pytest.raises(ValueError, match="not a tag"):
        eng.execute("SHOW TAG bytes VALUES FROM flows")


def test_promql_without_modifier(prom):
    eng, _, _ = prom
    # dropping the only label collapses both series into one sum
    out = eng.query('sum without (job) (rps)', at=1090)
    assert len(out) == 1 and out[0]["metric"] == {}
    assert float(out[0]["value"][1]) == 19.0 + 109.0
    # dropping a non-existent label keeps per-series identity
    out = eng.query('sum without (zone) (rps)', at=1090)
    assert {r["metric"]["job"] for r in out} == {"api", "web"}


def test_promql_math_functions(prom):
    eng, _, _ = prom
    out = eng.query('sqrt(rps{job="api"})', at=1090)
    assert float(out[0]["value"][1]) == pytest.approx(np.sqrt(19.0))
    out = eng.query('clamp_max(rps, 50)', at=1090)
    vals = {r["metric"]["job"]: float(r["value"][1]) for r in out}
    assert vals == {"api": 19.0, "web": 50.0}
    out = eng.query('ln(rps{job="api"}) + ln(rps{job="api"})', at=1090)
    assert float(out[0]["value"][1]) == pytest.approx(2 * np.log(19.0))


def test_promql_round_and_negative_bounds(prom):
    eng, store, dicts = prom
    t = store.table("ext_metrics", "ext_samples")
    mh = dicts.get("metric_name").encode_one("halfs")
    lh = dicts.get("label_set").encode_one("job=h")
    t.append({"timestamp": np.array([1100], np.uint32),
              "metric": np.array([mh], np.uint32),
              "labels": np.array([lh], np.uint32),
              "value": np.array([2.5], np.float32)})
    # upstream round(): ties round UP, not half-to-even
    out = eng.query('round(halfs)', at=1100)
    assert float(out[0]["value"][1]) == 3.0
    # negative clamp bounds parse (unary minus)
    out = eng.query('clamp_min(halfs - 10, -5)', at=1100)
    assert float(out[0]["value"][1]) == -5.0


def test_promql_stddev_and_quantile_over_time(prom):
    eng, _, _ = prom
    # across-series stddev at t=1090: values {19, 109}
    out = eng.query('stddev(rps)', at=1090)
    assert float(out[0]["value"][1]) == pytest.approx(np.std([19, 109]))
    out = eng.query('stdvar without (job) (rps)', at=1090)
    assert float(out[0]["value"][1]) == pytest.approx(np.var([19, 109]))
    # over-time: window (1030, 1090] holds samples 14..19
    win = np.array([14, 15, 16, 17, 18, 19], float)
    out = eng.query('stddev_over_time(rps{job="api"}[1m])', at=1090)
    assert float(out[0]["value"][1]) == pytest.approx(win.std())
    out = eng.query('quantile_over_time(0.5, rps{job="api"}[1m])',
                    at=1090)
    assert float(out[0]["value"][1]) == pytest.approx(
        np.quantile(win, 0.5))


def test_stddev_over_time_large_values(prom):
    """Catastrophic-cancellation guard: a huge-valued gauge with tiny
    variance must report the true stddev, not 0."""
    eng, store, dicts = prom
    t = store.table("ext_metrics", "ext_samples")
    mh = dicts.get("metric_name").encode_one("big_gauge")
    lh = dicts.get("label_set").encode_one("job=g")
    # the largest magnitude whose +-1 structure survives the f32 value
    # column (ints <= 2^24 are exact); the old cumsum-of-squares form
    # loses most of the variance here, the two-pass form is exact
    base = 16_000_000.0
    vals = np.array([base - 1, base + 1, base - 1, base + 1], np.float64)
    t.append({"timestamp": np.array([1060, 1070, 1080, 1090], np.uint32),
              "metric": np.full(4, mh, np.uint32),
              "labels": np.full(4, lh, np.uint32),
              "value": vals.astype(np.float32)})
    out = eng.query('stddev_over_time(big_gauge[1m])', at=1090)
    assert float(out[0]["value"][1]) == pytest.approx(1.0, rel=1e-9)
    out = eng.query('stdvar_over_time(big_gauge[1m])', at=1090)
    assert float(out[0]["value"][1]) == pytest.approx(1.0, rel=1e-9)


def test_promql_delta(prom):
    """delta(): non-counter difference over the window, extrapolated —
    no counter-reset correction (a drop stays negative)."""
    eng, store, dicts = prom
    t = store.table("ext_metrics", "ext_samples")
    mh = dicts.get("metric_name").encode_one("gauge_drop")
    lh = dicts.get("label_set").encode_one("job=d")
    t.append({"timestamp": np.array([1000, 1030, 1060], np.uint32),
              "metric": np.full(3, mh, np.uint32),
              "labels": np.full(3, lh, np.uint32),
              "value": np.array([100.0, 60.0, 20.0], np.float32)})
    out = eng.query('delta(gauge_drop[1m])', at=1060)
    # window == sampled span exactly: delta = 20 - 100 = -80
    assert float(out[0]["value"][1]) == pytest.approx(-80.0)


def test_promql_on_ignoring_matching(prom):
    eng, store, dicts = prom
    t = store.table("ext_metrics", "ext_samples")
    mh = dicts.get("metric_name").encode_one("capacity")
    # capacity carries an extra 'tier' label the rps series lacks
    for job, cap in (("api", 100.0), ("web", 200.0)):
        lh = dicts.get("label_set").encode_one(f"job={job},tier=gold")
        t.append({"timestamp": np.array([1090], np.uint32),
                  "metric": np.array([mh], np.uint32),
                  "labels": np.array([lh], np.uint32),
                  "value": np.array([cap], np.float32)})
    # default 1:1 match fails to join (label sets differ) -> empty
    assert eng.query('rps / capacity', at=1090) == []
    # on(job) joins them
    out = eng.query('rps / on (job) capacity', at=1090)
    vals = {r["metric"]["job"]: float(r["value"][1]) for r in out}
    assert vals == {"api": 19.0 / 100.0, "web": 109.0 / 200.0}
    # ignoring(tier) is the equivalent exclusion form
    out2 = eng.query('rps / ignoring (tier) capacity', at=1090)
    vals2 = {r["metric"]["job"]: float(r["value"][1]) for r in out2}
    assert vals2 == vals
    # ambiguous match is loud, not arbitrary
    with pytest.raises(ValueError, match="many-to-many"):
        eng.query('rps / on (tier) capacity', at=1090)


def test_promql_matching_edge_semantics(prom):
    eng, store, dicts = prom
    t = store.table("ext_metrics", "ext_samples")
    mh = dicts.get("metric_name").encode_one("one_cap")
    lh = dicts.get("label_set").encode_one("tier=gold")
    t.append({"timestamp": np.array([1090], np.uint32),
              "metric": np.array([mh], np.uint32),
              "labels": np.array([lh], np.uint32),
              "value": np.array([50.0], np.float32)})
    # empty on(): joins single series on the empty key
    out = eng.query('rps{job="api"} / on () one_cap', at=1090)
    assert len(out) == 1 and float(out[0]["value"][1]) == 19.0 / 50.0
    # on-labels absent from both sides never fabricate empty labels
    assert out[0]["metric"] == {}
    # duplicate left keys that MATCH one right sample: genuine
    # many-to-one, loud error (group_left unsupported)
    with pytest.raises(ValueError, match="many-to-one"):
        eng.query('rps / on (nope) one_cap', at=1090)
    # duplicate left keys that match NOTHING just drop (upstream
    # semantics): on (tier) folds both rps series to the empty key but
    # one_cap's key carries tier=gold, so nothing joins and no error
    assert eng.query('rps / on (tier) one_cap', at=1090) == []
    # scalar operands reject matching modifiers loudly
    with pytest.raises(ValueError, match="instant vectors"):
        eng.query('1 + on (job) rps', at=1090)


# -- round-3b PromQL surface: comparisons, set ops, function library ------
def test_promql_comparison_filter_and_bool(prom):
    eng, _, _ = prom
    # filter: only series whose value passes survive, value unchanged
    out = eng.query('rps > 50', at=1100)
    assert len(out) == 1
    assert out[0]["metric"]["job"] == "web"
    assert float(out[0]["value"][1]) == 109.0
    # filter keeps the metric name upstream
    assert out[0]["metric"].get("__name__") == "rps"
    # bool: every series yields 0/1 and drops the name
    out = eng.query('rps > bool 50', at=1100)
    got = {r["metric"]["job"]: float(r["value"][1]) for r in out}
    assert got == {"api": 0.0, "web": 1.0}
    # vector-vector comparison with bool
    out = eng.query('rps == bool rps', at=1100)
    assert sorted(float(r["value"][1]) for r in out) == [1.0, 1.0]
    # <= and != round out the operator set
    out = eng.query('rps <= 19', at=1100)
    assert len(out) == 1 and out[0]["metric"]["job"] == "api"
    out = eng.query('rps != bool 19', at=1100)
    got = {r["metric"]["job"]: float(r["value"][1]) for r in out}
    assert got == {"api": 0.0, "web": 1.0}


def test_promql_set_ops(prom):
    eng, _, _ = prom
    out = eng.query('rps and rps{job="api"}', at=1100)
    assert len(out) == 1 and out[0]["metric"]["job"] == "api"
    out = eng.query('rps unless rps{job="api"}', at=1100)
    assert len(out) == 1 and out[0]["metric"]["job"] == "web"
    out = eng.query('rps{job="api"} or rps', at=1100)
    got = {r["metric"]["job"]: float(r["value"][1]) for r in out}
    assert got == {"api": 19.0, "web": 109.0}
    # on() restricting the set-op key
    out = eng.query('rps and on (job) rps{job="web"}', at=1100)
    assert len(out) == 1 and out[0]["metric"]["job"] == "web"


def test_promql_mod_pow_arith(prom):
    eng, _, _ = prom
    out = eng.query('rps{job="api"} % 4', at=1100)
    assert float(out[0]["value"][1]) == 3.0              # 19 % 4
    out = eng.query('rps{job="api"} ^ 2', at=1100)
    assert float(out[0]["value"][1]) == 361.0
    # ^ is right-associative: 2^(3^2) would be 512 on scalars; probe
    # via a vector: v ^ 1 ^ 2 = v ^ (1^2) = v
    out = eng.query('rps{job="api"} ^ 1 ^ 2', at=1100)
    assert float(out[0]["value"][1]) == 19.0
    # fmod semantics: dividend sign (Go math.Mod), not python %
    out = eng.query('(0 - rps{job="api"}) % 4', at=1100)
    assert float(out[0]["value"][1]) == -3.0


def test_promql_scalar_bridges(prom):
    eng, _, _ = prom
    out = eng.query('rps{job="api"} - time()', at=1100)
    assert float(out[0]["value"][1]) == 19.0 - 1100.0
    out = eng.query('rps{job="web"} - scalar(rps{job="api"})', at=1100)
    assert float(out[0]["value"][1]) == 90.0
    # scalar() of a 2-series vector is NaN -> empty result
    assert eng.query('rps{job="web"} - scalar(rps)', at=1100) == []
    # vector(): scalar into an empty-labeled series
    out = eng.query('vector(7)', at=1100)
    assert out[0]["metric"] == {} and float(out[0]["value"][1]) == 7.0


def test_promql_absent_and_present(prom):
    eng, _, _ = prom
    out = eng.query('absent(rps{job="nope"})', at=1100)
    assert len(out) == 1
    assert float(out[0]["value"][1]) == 1.0
    # labels derive from the equality matchers
    assert out[0]["metric"] == {"job": "nope"}
    assert eng.query('absent(rps{job="api"})', at=1100) == []
    out = eng.query('present_over_time(rps{job="api"}[1m])', at=1100)
    assert float(out[0]["value"][1]) == 1.0


def test_promql_changes_resets_deriv_predict(prom):
    eng, store, dicts = prom
    t = store.table("ext_metrics", "ext_samples")
    mh = dicts.get("metric_name").encode_one("wig")
    lh = dicts.get("label_set").encode_one("job=w")
    ts = np.array([1000, 1010, 1020, 1030, 1040], np.uint32)
    vs = np.array([10.0, 30.0, 30.0, 3.0, 13.0], np.float32)
    t.append({"timestamp": ts, "metric": np.full(5, mh, np.uint32),
              "labels": np.full(5, lh, np.uint32),
              "value": vs})
    # the (t-range, t] window is LEFT-OPEN (modern upstream): the
    # sample AT 1000 is excluded, so in-window values are 30,30,3,13
    out = eng.query('changes(wig[40s])', at=1040)
    assert float(out[0]["value"][1]) == 2.0     # 30->3, 3->13
    out = eng.query('resets(wig[40s])', at=1040)
    assert float(out[0]["value"][1]) == 1.0     # only 30->3
    # rps{job=api} climbs exactly 0.1/s
    out = eng.query('deriv(rps{job="api"}[1m])', at=1100)
    assert float(out[0]["value"][1]) == pytest.approx(0.1)
    out = eng.query('predict_linear(rps{job="api"}[1m], 60)', at=1100)
    # the fitted line v(t) = 0.1*(t-1000) + 10 evaluates to 20 AT the
    # grid point 1100 (upstream's intercept perspective), +60s*0.1 = 26
    assert float(out[0]["value"][1]) == pytest.approx(26.0)


def test_promql_label_functions(prom):
    eng, _, _ = prom
    out = eng.query(
        'label_replace(rps, "env", "x-$1", "job", "(a.*)")', at=1100)
    envs = {r["metric"]["job"]: r["metric"].get("env") for r in out}
    assert envs == {"api": "x-api", "web": None}   # web: regex no match
    out = eng.query(
        'label_join(rps, "combo", "-", "job", "job")', at=1100)
    combos = sorted(r["metric"]["combo"] for r in out)
    assert combos == ["api-api", "web-web"]


def test_promql_sort_and_timestamp(prom):
    eng, _, _ = prom
    out = eng.query('sort(rps)', at=1100)
    assert [r["metric"]["job"] for r in out] == ["api", "web"]
    out = eng.query('sort_desc(rps)', at=1100)
    assert [r["metric"]["job"] for r in out] == ["web", "api"]
    out = eng.query('timestamp(rps{job="api"})', at=1100)
    assert float(out[0]["value"][1]) == 1090.0  # last sample's own ts
    out = eng.query('sgn(rps{job="api"} - 100)', at=1100)
    assert float(out[0]["value"][1]) == -1.0
    out = eng.query('clamp(rps, 20, 105)', at=1100)
    got = sorted(float(r["value"][1]) for r in out)
    assert got == [20.0, 105.0]
    # upstream: min > max yields empty, not a swap
    assert eng.query('clamp(rps, 105, 20)', at=1100) == []


def test_promql_group_left(prom):
    eng, store, dicts = prom
    t = store.table("ext_metrics", "ext_samples")
    mh = dicts.get("metric_name").encode_one("build_info")
    lh = dicts.get("label_set").encode_one("job=api,ver=2.1")
    t.append({"timestamp": np.array([1090], np.uint32),
              "metric": np.array([mh], np.uint32),
              "labels": np.array([lh], np.uint32),
              "value": np.array([1.0], np.float32)})
    # many-to-one: both rps series could match a shared key; with
    # on(job) only api joins, and group_left(ver) copies the version
    out = eng.query('rps * on (job) group_left (ver) build_info',
                    at=1100)
    assert len(out) == 1
    assert out[0]["metric"]["job"] == "api"
    assert out[0]["metric"]["ver"] == "2.1"
    assert float(out[0]["value"][1]) == 19.0
    # group_right mirrors: one-side on the left
    out = eng.query('build_info * on (job) group_right (ver) rps',
                    at=1100)
    assert len(out) == 1 and float(out[0]["value"][1]) == 19.0


def test_promql_group_left_filter_keeps_group_labels(prom):
    eng, store, dicts = prom
    t = store.table("ext_metrics", "ext_samples")
    mh = dicts.get("metric_name").encode_one("gi")
    lh = dicts.get("label_set").encode_one("job=api,ver=9")
    t.append({"timestamp": np.array([1090], np.uint32),
              "metric": np.array([mh], np.uint32),
              "labels": np.array([lh], np.uint32),
              "value": np.array([1.0], np.float32)})
    # filter-mode comparison with group_left still copies the group
    # labels (upstream resultMetric semantics)
    out = eng.query('rps > on (job) group_left (ver) gi', at=1100)
    assert len(out) == 1
    assert out[0]["metric"]["ver"] == "9"
    assert out[0]["metric"].get("__name__") == "rps"


def test_promql_set_op_on_scalars_is_loud(prom):
    eng, _, _ = prom
    import pytest as _pt
    with _pt.raises(ValueError):
        eng.query('vector(1 and 2)', at=1100)


# -- round-3b SQL: boolean WHERE trees, LIKE, Percentile, PerSecond -------
def test_sql_or_not_parens(engine):
    eng, cols = engine
    res = eng.execute("SELECT Count(*) AS n FROM flows WHERE "
                      "ip = 1 OR ip = 2")
    m = (cols["ip"] == 1) | (cols["ip"] == 2)
    assert res.values[0][0] == int(m.sum())
    res = eng.execute("SELECT Count(*) AS n FROM flows WHERE "
                      "NOT (ip = 1 OR ip = 2)")
    assert res.values[0][0] == int((~m).sum())
    # mixed precedence: AND binds tighter than OR
    res = eng.execute("SELECT Count(*) AS n FROM flows WHERE "
                      "ip = 1 AND proto = 6 OR ip = 2 AND proto = 17")
    m = ((cols["ip"] == 1) & (cols["proto"] == 6)) | \
        ((cols["ip"] == 2) & (cols["proto"] == 17))
    assert res.values[0][0] == int(m.sum())
    # time pruning still applies with an OR residual alongside
    res = eng.execute("SELECT Count(*) AS n FROM flows WHERE "
                      "timestamp >= 10 AND timestamp < 20 AND "
                      "(ip = 1 OR proto = 6)")
    m = (cols["timestamp"] >= 10) & (cols["timestamp"] < 20) & \
        ((cols["ip"] == 1) | (cols["proto"] == 6))
    assert res.values[0][0] == int(m.sum())


def test_sql_not_in(engine):
    eng, cols = engine
    res = eng.execute("SELECT Count(*) AS n FROM flows WHERE "
                      "ip NOT IN (1, 2)")
    assert res.values[0][0] == int((~np.isin(cols["ip"], [1, 2])).sum())


def test_sql_percentile(engine):
    eng, cols = engine
    res = eng.execute("SELECT Percentile(rtt, 95) AS p FROM flows")
    assert res.values[0][0] == pytest.approx(
        float(np.percentile(cols["rtt"], 95)))
    res = eng.execute("SELECT ip, Percentile(rtt, 50) AS p FROM flows "
                      "GROUP BY ip ORDER BY ip")
    for ip, p in res.values:
        assert p == pytest.approx(
            float(np.percentile(cols["rtt"][cols["ip"] == ip], 50)))


def test_sql_persecond(engine):
    eng, cols = engine
    # bounded WHERE span: 40s
    res = eng.execute("SELECT PerSecond(Sum(bytes)) AS r FROM flows "
                      "WHERE timestamp >= 10 AND timestamp < 50")
    m = (cols["timestamp"] >= 10) & (cols["timestamp"] < 50)
    assert res.values[0][0] == pytest.approx(
        cols["bytes"][m].sum() / 40.0)
    # under interval grouping the bucket width is the divisor
    res = eng.execute("SELECT time(20), PerSecond(Sum(bytes)) AS r "
                      "FROM flows GROUP BY time(20) ORDER BY time")
    for tb, r in res.values:
        m = (cols["timestamp"] // 20) * 20 == tb
        assert r == pytest.approx(cols["bytes"][m].sum() / 20.0)
    # unbounded + unbucketed is a loud error
    with pytest.raises(ValueError, match="PerSecond"):
        eng.execute("SELECT PerSecond(Sum(bytes)) AS r FROM flows")


def test_sql_like_regexp(tmp_path):
    """LIKE/REGEXP widen to dictionary-id membership (the reference's
    dictGet lowering for auto-tags)."""
    from deepflow_tpu.querier.engine import DICT_COLUMNS
    store = Store(str(tmp_path / "s"))
    dicts = TagDictRegistry(str(tmp_path / "s"))
    schema = TableSchema(
        name="l7",
        columns=(
            ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
            ColumnSpec("endpoint_hash", np.dtype(np.uint32), AggKind.KEY),
            ColumnSpec("n", np.dtype(np.uint32), AggKind.SUM),
        ))
    t = store.create_table("flow_log", schema)
    d_name = DICT_COLUMNS.get("endpoint_hash")
    assert d_name, "endpoint_hash should be dictionary-backed"
    d = dicts.get(d_name[0])
    eps = ["GET /api/users", "GET /api/orders", "POST /login"]
    hs = [d.encode_one(s) for s in eps]
    t.append({"timestamp": np.array([1, 2, 3], np.uint32),
              "endpoint_hash": np.array(hs, np.uint32),
              "n": np.ones(3, np.uint32)})
    eng = QueryEngine(store, dicts)
    res = eng.execute("SELECT Count(*) AS c FROM l7 WHERE "
                      "endpoint_hash LIKE 'GET /api/%'")
    assert res.values[0][0] == 2
    res = eng.execute("SELECT Count(*) AS c FROM l7 WHERE "
                      "endpoint_hash NOT LIKE 'GET %'")
    assert res.values[0][0] == 1
    res = eng.execute("SELECT Count(*) AS c FROM l7 WHERE "
                      "endpoint_hash REGEXP '(GET|POST) /(api/)?[a-z]+'")
    assert res.values[0][0] == 3


def test_sql_regexp_is_unanchored(tmp_path):
    """REGEXP searches (ClickHouse match()); LIKE stays anchored."""
    from deepflow_tpu.querier.engine import DICT_COLUMNS
    store = Store(str(tmp_path / "s"))
    dicts = TagDictRegistry(str(tmp_path / "s"))
    schema = TableSchema(
        name="l7",
        columns=(
            ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
            ColumnSpec("endpoint_hash", np.dtype(np.uint32), AggKind.KEY),
            ColumnSpec("n", np.dtype(np.uint32), AggKind.SUM),
        ))
    t = store.create_table("flow_log", schema)
    d = dicts.get(DICT_COLUMNS["endpoint_hash"][0])
    hs = [d.encode_one(s) for s in
          ["GET /api/users", "GET /api/orders", "POST /login"]]
    t.append({"timestamp": np.array([1, 2, 3], np.uint32),
              "endpoint_hash": np.array(hs, np.uint32),
              "n": np.ones(3, np.uint32)})
    eng = QueryEngine(store, dicts)
    res = eng.execute("SELECT Count(*) AS c FROM l7 WHERE "
                      "endpoint_hash REGEXP 'api'")     # substring
    assert res.values[0][0] == 2
    res = eng.execute("SELECT Count(*) AS c FROM l7 WHERE "
                      "endpoint_hash LIKE 'api'")       # anchored: none
    assert res.values[0][0] == 0


def test_sql_persecond_needs_both_bounds(engine):
    eng, _ = engine
    # only an upper bound: the implicit lo=0 would make an epoch-sized
    # divisor; must be loud instead
    with pytest.raises(ValueError, match="both sides"):
        eng.execute("SELECT PerSecond(Sum(bytes)) AS r FROM flows "
                    "WHERE timestamp < 50")


# -- sketch datasource (ISSUE 7 serving read path) -------------------------
def test_parse_qualified_func():
    from deepflow_tpu.querier.sql import QualifiedFunc
    s = parse_sql("SELECT sketch.topk(10) FROM sketch "
                  "WHERE time >= 100 AND time < 200 LIMIT 5")
    assert s.table == "sketch" and s.limit == 5
    assert s.items[0].expr == QualifiedFunc("sketch.topk", (10,))
    s = parse_sql("SELECT sketch.hll_card() FROM sketch")
    assert s.items[0].expr == QualifiedFunc("sketch.hll_card", ())
    # bare dotted idents stay plain columns (rollup tables etc.)
    s = parse_sql("SELECT sketch.entropy FROM sketch")
    from deepflow_tpu.querier.sql import Column
    assert s.items[0].expr == Column("sketch.entropy")


@pytest.fixture
def sketch_served(tmp_path):
    from deepflow_tpu.models import flow_suite
    from deepflow_tpu.runtime.tpu_sketch import TpuSketchExporter
    from deepflow_tpu.serving import SketchTables, SnapshotCache

    cfg = flow_suite.FlowSuiteConfig(cms_log2_width=12, ring_size=256,
                                     hll_groups=32, hll_precision=8,
                                     entropy_log2_buckets=8)
    exp = TpuSketchExporter(cfg=cfg, store=None, batch_rows=2048,
                            window_seconds=3600, wire="lanes")
    tables = SketchTables(SnapshotCache(exp.snapshot_bus,
                                        max_staleness_s=1e9))
    from deepflow_tpu.batch.schema import L4_SCHEMA
    rng = np.random.default_rng(11)
    base = {
        "ip_src": rng.integers(0, 1 << 30, 64).astype(np.uint32),
        "ip_dst": rng.integers(0, 1 << 30, 64).astype(np.uint32),
        "port_src": rng.integers(0, 1 << 16, 64).astype(np.uint32),
        "port_dst": rng.integers(0, 1 << 16, 64).astype(np.uint32),
        "proto": rng.integers(0, 255, 64).astype(np.uint32),
    }
    for w, now in ((1, 1000.0), (2, 1001.0)):
        picks = rng.integers(0, 64, 8000)
        cols = {}
        for name, dt in L4_SCHEMA.columns:
            cols[name] = (base[name][picks].astype(dt) if name in base
                          else rng.integers(0, 1 << 10, 8000).astype(dt))
        exp.process([("l4_flow_log", 0, cols)])
        exp.flush_window(now=now)
    yield exp, tables
    exp.close()


def test_sketch_sql_roundtrip_through_engine(tmp_path, sketch_served):
    exp, tables = sketch_served
    eng = QueryEngine(Store(str(tmp_path / "qs")), TagDictRegistry(None),
                      sketch=tables)
    res = eng.execute("SELECT sketch.topk(5) FROM sketch")
    assert res.columns == ["time", "window", "rank", "flow_key", "count"]
    assert res.values and res.values[0][1] == 2      # latest window
    assert res.values[0][4] >= res.values[-1][4]     # rank order
    key = res.values[0][3]
    res = eng.execute(f"SELECT sketch.cms_point({key}) FROM sketch")
    assert res.values[0][3] > 0                      # estimate column
    res = eng.execute("SELECT sketch.entropy FROM sketch "
                      "WHERE time >= 999 AND time < 1002")
    assert [r[1] for r in res.values] == [1, 2]      # both windows
    res = eng.execute("SELECT sketch.hll_card() FROM sketch")
    assert res.values[0][3] > 0
    # without serving wired, the table is unknown like any other
    bare = QueryEngine(Store(str(tmp_path / "qs2")), TagDictRegistry(None))
    with pytest.raises(KeyError):
        bare.execute("SELECT sketch.topk(5) FROM sketch")


def test_sketch_promql_functions(tmp_path, sketch_served):
    exp, tables = sketch_served
    store = Store(str(tmp_path / "ps"))
    dicts = TagDictRegistry(None)
    eng = PromEngine(store, dicts, sketch=tables)
    out = eng.query("sketch_topk(3)", at=1001)
    assert 0 < len(out) <= 3
    assert all("flow_key" in r["metric"] for r in out)
    key = int(out[0]["metric"]["flow_key"])
    out = eng.query(f"sketch_cms_point({key})", at=1001)
    assert float(out[0]["value"][1]) > 0
    out = eng.query("sketch_hll_card()", at=1001)
    assert float(out[0]["value"][1]) > 0
    # range query: the entropy timeline across both windows
    out = eng.query_range("sketch_entropy()", start=1000, end=1001, step=1)
    feats = {r["metric"]["feature"] for r in out}
    assert feats == {"ip_src", "ip_dst", "port_src", "port_dst"}
    assert all(len(r["values"]) == 2 for r in out)
    # sketch functions compose with the normal evaluator
    out = eng.query("sum(sketch_topk(3))", at=1001)
    assert len(out) == 1
    # unwired engine: crisp error, not a silent empty vector
    with pytest.raises(ValueError, match="sketch datasource"):
        PromEngine(store, dicts).query("sketch_topk(3)", at=1001)


def test_sketch_http_routes(sketch_served, tmp_path):
    exp, tables = sketch_served
    store = Store(str(tmp_path / "hs"))
    srv = QuerierServer(store, TagDictRegistry(None), port=0,
                        sketch=tables)
    srv.start()
    try:
        body = "sql=" + urllib.parse.quote(
            "SELECT sketch.topk(3) FROM sketch")
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/query", data=body.encode(),
            headers={"Content-Type": "application/x-www-form-urlencoded"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            payload = json.load(resp)
        assert payload["result"]["columns"][2] == "rank"
        assert payload["result"]["values"]
        qs = urllib.parse.urlencode({"query": "sketch_entropy()",
                                     "time": 1001})
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/api/v1/query?{qs}",
                timeout=5) as resp:
            out = json.load(resp)
        assert out["status"] == "success"
        assert len(out["data"]["result"]) == 4
    finally:
        srv.close()

"""ISSUE 20: the feed autotuner's control law, observed synchronously.

`FeedAutotuner.tick()` is the exact step the supervised thread runs, so
every property — convergence, hysteresis damping, idle skips, safe
fallback — is tested with fake metrics and explicit dt, no sleeps. The
last test checks the real knob surface against a live dict-wire
exporter and the PR 2 supervision of the control thread."""

import pytest

from deepflow_tpu.runtime.autotune import (AUTOTUNE_GAUGE_HELP,
                                           FeedAutotuner, autotune_gauges)
from deepflow_tpu.runtime.supervisor import default_supervisor


class _FakeStager:
    def __init__(self, group_batches=1):
        self.group_batches = group_batches

    def set_group_batches(self, n):
        # the real stager defers to the next group boundary; the fake
        # applies immediately — the controller under test is the same
        self.group_batches = max(1, int(n))


class _FakeFeed:
    def __init__(self):
        self.depth = 2
        self.coalesce = 1


class _FakePool:
    def __init__(self, active=2):
        self.active = active

    def resize(self, n):
        self.active = max(1, int(n))


class _FakeExporter:
    def __init__(self):
        self._stager = _FakeStager()
        self._feed = _FakeFeed()
        self._pack_pool = _FakePool()


class _Plant:
    """Fake metrics: device busy peaks at (coalesce=4, depth=2,
    workers=2) and every tick moves rows. The controller only ever
    sees this dict — exactly what `metrics=` is for."""

    def __init__(self, exp):
        self.exp = exp
        self.rows = 0
        self.device_errors = 0
        self.crash_recoveries = 0
        self.degraded = 0.0

    def __call__(self):
        self.rows += 1000
        busy = (1.0
                - 0.10 * abs(self.exp._stager.group_batches - 4)
                - 0.05 * abs(self.exp._feed.depth - 2)
                - 0.05 * abs(self.exp._pack_pool.active - 2))
        return {"busy": busy, "stall_s": 0.0, "dwell_s": 0.0,
                "dwell_batches": 0, "rows_in": self.rows,
                "device_errors": self.device_errors,
                "crash_recoveries": self.crash_recoveries,
                "degraded": self.degraded}


def _tuner(exp, plant, **kw):
    kw.setdefault("interval_s", 1.0)
    return FeedAutotuner(exp, metrics=plant, **kw)


def _knob(at, name):
    return next(k for k in at.knobs if k.name == name)


def test_converges_to_objective_optimum():
    """Bounded hill-climbing finds the plant's optimum (coalesce 4)
    from the static config (coalesce 1) and HOLDS the other knobs at
    their already-optimal statics; trials past the peak revert and
    geometrically damp (cooldown_base doubles per revert)."""
    exp = _FakeExporter()
    plant = _Plant(exp)
    at = _tuner(exp, plant)
    try:
        for _ in range(60):
            at.tick(dt=1.0)
        while at._trial is not None:        # let an in-flight trial judge
            at.tick(dt=1.0)
        assert exp._stager.group_batches == 4
        assert exp._feed.depth == 2
        assert exp._pack_pool.active == 2
        assert at.decisions >= 3            # 1 -> 2 -> 3 -> 4 committed
        assert at.reverts >= 3              # overshoots + flat knobs
        assert _knob(at, "coalesce_batches").cooldown_base > 1  # damped
        # the last score may be a reverted probe's, one step off-peak
        assert at.objective >= 0.89
        assert at.enabled and at.fallbacks == 0
    finally:
        at.close()


def test_idle_intervals_never_judge():
    """A quiet pipe says nothing about a knob: with rows frozen the
    controller neither starts nor judges trials, so the knobs hold."""
    exp = _FakeExporter()
    plant = _Plant(exp)
    at = _tuner(exp, plant)
    try:
        at.tick(dt=1.0)                     # seed baselines
        plant.rows -= 1000                  # freeze rows_in from here on

        def frozen():
            m = plant()
            plant.rows -= 1000
            return m

        at._metrics = frozen
        for _ in range(10):
            at.tick(dt=1.0)
        assert at.decisions == 0 and at.reverts == 0
        assert exp._stager.group_batches == 1
        assert exp._feed.depth == 2
    finally:
        at.close()


@pytest.mark.parametrize("incident", ["device_errors",
                                      "crash_recoveries", "degraded"])
def test_fallback_restores_static_config(incident):
    """Any device incident mid-tune restores every knob to its static
    config value and disables the controller — an incident must meet
    the exact pipeline the operator configured."""
    exp = _FakeExporter()
    plant = _Plant(exp)
    at = _tuner(exp, plant)
    try:
        for _ in range(8):                  # move some knobs first
            at.tick(dt=1.0)
        assert exp._stager.group_batches > 1
        setattr(plant, incident, 1 if incident != "degraded" else 1.0)
        at.tick(dt=1.0)
        assert not at.enabled and at.fallbacks == 1
        assert exp._stager.group_batches == 1      # statics restored
        assert exp._feed.depth == 2
        assert exp._pack_pool.active == 2
        g = at.gauges()
        assert g["tpu_autotune_enabled"] == 0.0
        assert g["tpu_autotune_fallbacks"] == 1.0
        ticks = at.ticks
        at.tick(dt=1.0)                     # disabled: a no-op forever
        assert at.ticks == ticks
    finally:
        at.close()


def test_gauges_help_registry_and_exposition():
    """Every gauge carries HELP text, counters() is the same family
    minus the prefix, and promexpo renders the live controller's
    gauges as valid exposition — gone again after close()."""
    from deepflow_tpu.runtime.promexpo import (render_metrics,
                                               validate_exposition)

    exp = _FakeExporter()
    at = _tuner(exp, _Plant(exp))
    try:
        g = at.gauges()
        assert set(g) == set(AUTOTUNE_GAUGE_HELP)
        assert set(at.counters()) == {k[len("tpu_autotune_"):] for k in g}
        assert autotune_gauges()["tpu_autotune_enabled"] == 1.0
        text = render_metrics(None, None)
        assert "# TYPE deepflow_tpu_autotune_enabled gauge" in text
        assert "deepflow_tpu_autotune_coalesce_batches" in text
        assert validate_exposition(text) == []
    finally:
        at.close()
    assert "tpu_autotune_enabled" not in autotune_gauges()
    assert "deepflow_tpu_autotune" not in render_metrics(None, None)


def test_real_exporter_knob_surface_and_supervision():
    """Against a live dict-wire exporter: the knob surface is exactly
    (stager coalesce, feed depth, pool routing width), statics capture
    the config, set routes through the boundary-deferred stager setter,
    and the control thread rides the supervision tree."""
    from deepflow_tpu.runtime.tpu_sketch import TpuSketchExporter

    e = TpuSketchExporter(store=None, window_seconds=3600,
                          batch_rows=1024, wire="dict",
                          prefetch_depth=2, coalesce_batches=2,
                          pack_workers=2)
    at = FeedAutotuner(e, interval_s=0.1)
    try:
        assert [k.name for k in at.knobs] == [
            "coalesce_batches", "prefetch_depth", "pack_workers"]
        assert [k.static for k in at.knobs] == [2, 2, 2]
        _knob(at, "coalesce_batches").set(3)
        assert e._stager._pending_group == 3   # applied at next boundary
        _knob(at, "pack_workers").set(3)
        assert e._pack_pool.active == 3
        at.start()
        names = {t["name"] for t in default_supervisor().threads()}
        assert "feed-autotune" in names
    finally:
        at.close()
        e.close()
    assert not at.enabled

"""Derived-metric library: named metrics that expand to expressions.

Reference: server/querier/engine/clickhouse/metrics/ — a per-table
registry where e.g. `rtt` expands to AVGIf(rtt_sum/rtt_count, ...) in
generated ClickHouse SQL, so dashboards ask for semantic metric names
rather than raw column math. Here each derived metric is a DeepFlow-SQL
expression string parsed once through the normal grammar; the engine
substitutes it when a SELECT item names a derived metric (real columns
always win over library names), and SHOW METRICS lists the ones whose
underlying columns the table actually carries.
"""

from __future__ import annotations

from typing import Dict, Optional, Set, Tuple

from deepflow_tpu.querier import sql as Q

# name -> (expression, unit, description)
DERIVED_METRICS: Dict[str, Tuple[str, str, str]] = {
    "byte": ("Sum(byte_tx) + Sum(byte_rx)", "B", "total bytes both ways"),
    "packet": ("Sum(packet_tx) + Sum(packet_rx)", "",
               "total packets both ways"),
    "rtt_avg": ("Sum(rtt_sum) / Sum(rtt_count)", "us",
                "mean TCP handshake RTT"),
    "srt_avg": ("Sum(srt_sum) / Sum(srt_count)", "us",
                "mean system response time"),
    "art_avg": ("Sum(art_sum) / Sum(art_count)", "us",
                "mean application response time"),
    "rrt_avg": ("Sum(rrt_sum) / Sum(rrt_count)", "us",
                "mean L7 request-response time"),
    "cit_avg": ("Sum(cit_sum) / Sum(cit_count)", "us",
                "mean client idle time"),
    "retrans": ("Sum(retrans_tx) + Sum(retrans_rx)", "",
                "total retransmissions"),
    "retrans_ratio": (
        "(Sum(retrans_tx) + Sum(retrans_rx)) / "
        "(Sum(packet_tx) + Sum(packet_rx))", "",
        "retransmitted fraction of packets"),
    "l7_error": ("Sum(l7_client_error) + Sum(l7_server_error)", "",
                 "total L7 errors"),
    "l7_error_ratio": (
        "(Sum(l7_client_error) + Sum(l7_server_error)) / Sum(l7_response)",
        "", "errored fraction of L7 responses"),
    "new_flow": ("Sum(new_flow)", "", "new flows"),
    "closed_flow": ("Sum(closed_flow)", "", "closed flows"),
}

_parsed: Dict[str, Q.Expr] = {}


def expression(name: str) -> Optional[Q.Expr]:
    """Parsed expression for a derived metric name, or None."""
    spec = DERIVED_METRICS.get(name)
    if spec is None:
        return None
    expr = _parsed.get(name)
    if expr is None:
        stmt = Q.parse_sql(f"SELECT {spec[0]} FROM _")
        expr = stmt.items[0].expr
        _parsed[name] = expr
    return expr


def required_columns(name: str) -> Set[str]:
    expr = expression(name)
    return Q.expr_columns(expr) if expr is not None else set()


def available_for(column_names: Set[str]) -> Dict[str, Tuple[str, str, str]]:
    """Derived metrics whose every underlying column the table carries."""
    return {n: spec for n, spec in DERIVED_METRICS.items()
            if required_columns(n) <= column_names}


"""AppRedExporter: per-service RED windows from the l7 stream.

Role: the reference answers service rate/error/latency from ClickHouse
(vtap_app_* meter sums + `quantile()` over l7_flow_log.rrt at query
time). Here the l7 firehose drives models/app_suite on device — request
and error histograms plus a DDSketch per hashed service — and each
window writes one row per active service group into
`tpu_sketch.app_red` (requests, error_ratio, p50/p95/p99 rrt), which
the querier reads like any other table. Same exporter shape as
tpu_sketch.TpuSketchExporter: QueueWorkerExporter subscription,
host-side batching to static shapes, windowed flush, donated state.
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional

import numpy as np

from deepflow_tpu.batch.batcher import Batcher
from deepflow_tpu.batch.schema import Schema
from deepflow_tpu.models import app_suite
from deepflow_tpu.runtime.exporters import QueueWorkerExporter
from deepflow_tpu.runtime.stats import StatsRegistry
from deepflow_tpu.runtime.supervisor import default_supervisor
from deepflow_tpu.store.db import Store
from deepflow_tpu.store.table import AggKind, ColumnSpec, TableSchema
from deepflow_tpu.store.writer import StoreWriter

APP_RED_DB = "tpu_sketch"


def quantile_column(q: float) -> str:
    """0.95 -> rrt_p95_us, 0.995 -> rrt_p99_5_us, 0.999 -> rrt_p99_9_us
    — exact, so no two distinct quantiles can share a column name."""
    return "rrt_p" + f"{q * 100:g}".replace(".", "_") + "_us"


def app_red_table(quantiles=(0.5, 0.95, 0.99)) -> TableSchema:
    """Schema follows the configured quantile set (one column per
    quantile) — a non-default AppSuiteConfig.quantiles must not
    silently land in wrong columns."""
    names = [quantile_column(q) for q in quantiles]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate quantile columns: {names}")
    qcols = tuple(ColumnSpec(nm, np.dtype(np.float32), AggKind.MAX)
                  for nm in names)
    return TableSchema(
        name="app_red",
        columns=(
            ColumnSpec("timestamp", np.dtype(np.uint32), AggKind.KEY),
            ColumnSpec("service_group", np.dtype(np.uint32), AggKind.KEY),
            # counts, not ratios: ratios cannot aggregate across windows
            # (the repo convention — querier derived metrics divide SUMs
            # at query time, querier/metrics.py l7_error_ratio)
            ColumnSpec("requests", np.dtype(np.uint32), AggKind.SUM),
            ColumnSpec("errors", np.dtype(np.uint32), AggKind.SUM),
        ) + qcols,
    )


APP_RED_TABLE = app_red_table()

# the l7 columns the suite consumes, batched to static shapes
_RED_SCHEMA = Schema(name="l7_red", columns=(
    ("ip_dst", np.dtype(np.uint32)),
    ("port_dst", np.dtype(np.uint32)),
    ("protocol", np.dtype(np.uint32)),
    ("status", np.dtype(np.uint32)),
    ("rrt_us", np.dtype(np.uint32)),
))


class AppRedExporter(QueueWorkerExporter):
    """l7_flow_log -> AppSuite windows -> app_red rows."""

    def __init__(self, store: Optional[Store] = None,
                 cfg: Optional[app_suite.AppSuiteConfig] = None,
                 batch_rows: int = 1 << 14,
                 window_seconds: float = 1.0,
                 stats: Optional[StatsRegistry] = None,
                 tag_dicts=None,
                 prom_bucket_stride: int = 0,
                 prom_bucket_metric: str = "app_rrt_bucket") -> None:
        """prom_bucket_stride > 0 additionally surfaces each window's
        DDSketch as cumulative Prometheus `le` buckets in the
        ext_metrics.ext_samples table (one sample per active service per
        retained gamma-bucket boundary, every stride-th boundary plus
        +Inf), as RUNNING counters — so Grafana's canonical
        `histogram_quantile(0.95, rate(app_rrt_bucket[5m]))` works over
        real sketch windows (the DDSketch IS a histogram; its gamma
        boundaries are just log-spaced `le` bounds). Needs tag_dicts for
        the metric/label-set dictionaries."""
        super().__init__("app_red", ["l7_flow_log"], n_workers=1,
                         batch=64, stats=stats)
        import jax
        import jax.numpy as jnp

        self._jnp = jnp
        self.cfg = cfg or app_suite.AppSuiteConfig()
        self.window_seconds = window_seconds
        self.batcher = Batcher(_RED_SCHEMA, capacity=batch_rows)
        self.state = app_suite.init(self.cfg)
        self.rows_in = 0
        self.windows = 0
        self.last_output: Optional[app_suite.AppWindowOutput] = None
        self._update = jax.jit(
            lambda s, c, m: app_suite.update(s, c, m, self.cfg),
            donate_argnums=0)
        self._flush_fn = jax.jit(
            lambda s: app_suite.flush(s, self.cfg), donate_argnums=0)
        self.writer = None
        if store is not None:
            self.writer = StoreWriter(
                store.create_table(APP_RED_DB,
                                   app_red_table(self.cfg.quantiles)),
                batch_rows=4096, flush_interval=5.0)
        self.bucket_writer = None
        if prom_bucket_stride > 0:
            if store is None or tag_dicts is None:
                raise ValueError("prom_bucket_stride needs store and "
                                 "tag_dicts")
            from deepflow_tpu.ops import ddsketch as _dd
            from deepflow_tpu.pipelines.ext_metrics import (EXT_METRICS_DB,
                                                            SAMPLE_TABLE)
            self.bucket_writer = StoreWriter(
                store.create_table(EXT_METRICS_DB, SAMPLE_TABLE),
                batch_rows=4096, flush_interval=5.0)
            g = _dd.gamma(self.cfg.dd)
            # retained boundaries: every stride-th bucket upper edge,
            # always ending in +Inf (Prometheus requires the Inf bucket)
            idx = np.arange(prom_bucket_stride - 1, self.cfg.dd.buckets,
                            prom_bucket_stride)
            if len(idx) == 0 or idx[-1] != self.cfg.dd.buckets - 1:
                idx = np.append(idx, self.cfg.dd.buckets - 1)
            self._bucket_idx = idx
            # sketch bucket i covers (min*g^(i-1), min*g^i] (ddsketch
            # bucket_index is ceil-based), so cumsum through bucket i
            # counts values <= min*g^i — that IS the le bound
            les = self.cfg.dd.min_value * g ** idx.astype(np.float64)
            self._bucket_les = [f"{v:.6g}" for v in les[:-1]] + ["+Inf"]
            self._bucket_metric_h = tag_dicts.get("metric_name").encode_one(
                prom_bucket_metric)
            self._label_dict = tag_dicts.get("label_set")
            self._label_rows: dict = {}   # group -> uint32 label hashes
            # running cumulative counters per (group, retained bucket):
            # Prometheus histograms are counters, rate() recovers
            # windows. float64 here; the f32 value column caps exact
            # integer counts at 2^24, so counters RESET to the window's
            # own counts past 2^23 — a legal Prometheus counter reset
            # that rate()'s reset correction absorbs.
            self._bucket_cum = np.zeros(
                (self.cfg.groups, len(idx)), np.float64)
        self._state_lock = threading.Lock()
        self._window_stop = threading.Event()
        self._window_thread = None     # supervisor ThreadHandle

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self.writer is not None:
            self.writer.start()
        if self.bucket_writer is not None:
            self.bucket_writer.start()
        super().start()
        # supervised (crash capture + restart), deadman disabled: the
        # loop legitimately blocks a full window_seconds between beats
        # (same policy as the tpu_sketch window thread)
        self._window_thread = default_supervisor().spawn(
            "app-red-window", self._window_loop, deadman_s=None)

    def close(self) -> None:
        self._window_stop.set()
        if self._window_thread is not None:
            self._window_thread.stop()
            self._window_thread.join(timeout=5)
        super().close()
        self.flush_window()
        if self.writer is not None:
            self.writer.close()
        if self.bucket_writer is not None:
            self.bucket_writer.close()

    def _window_loop(self) -> None:
        while not self._window_stop.wait(self.window_seconds):
            self.flush_window()

    # -- data path ---------------------------------------------------------
    def process(self, chunks: List[Any]) -> None:
        for stream, _idx, cols, *_ in chunks:
            schema_cols = self.coerce_to_schema(cols, _RED_SCHEMA)
            n = len(next(iter(schema_cols.values())))
            with self._state_lock:
                # not an emission: the batcher is private state guarded
                # BY this lock (the window thread flushes it under the
                # same lock); no other thread can block on it
                for tb in self.batcher.put(schema_cols):  # lint: disable=emit-under-lock
                    self._run_batch_locked(tb)
                self.rows_in += n

    def _run_batch_locked(self, tb) -> None:
        jnp = self._jnp
        cols_d = {k: jnp.asarray(v) for k, v in tb.columns.items()}
        self.state = self._update(self.state, cols_d,
                                  jnp.asarray(tb.mask()))

    def flush_window(self, now: Optional[float] = None
                     ) -> Optional[app_suite.AppWindowOutput]:
        now = time.time() if now is None else now
        with self._state_lock:
            for tb in self.batcher.flush():
                self._run_batch_locked(tb)
            self.windows += 1
            self.state, out = self._flush_fn(self.state)
        self.last_output = out
        self._write_output(out, int(now))
        return out

    def _write_output(self, out: app_suite.AppWindowOutput,
                      second: int) -> None:
        if self.writer is None:
            return
        reqs = np.asarray(out.requests)
        active = np.nonzero(reqs > 0)[0]
        if len(active) == 0:
            return
        qs = np.asarray(out.rrt_quantiles)[:, active]
        row = {
            "timestamp": np.full(len(active), second, np.uint32),
            "service_group": active.astype(np.uint32),
            "requests": reqs[active].astype(np.uint32),
            "errors": np.asarray(out.errors)[active].astype(np.uint32),
        }
        for i, q in enumerate(self.cfg.quantiles):
            row[quantile_column(q)] = qs[i].astype(np.float32)
        self.writer.put(row)
        self._write_buckets(out, active, second)

    def _write_buckets(self, out, active: np.ndarray, second: int) -> None:
        if self.bucket_writer is None:
            return
        # fetch only the active groups' sketch rows (device gather first
        # — the full [groups, buckets] plane would be a 2MB D2H per
        # window)
        hist = np.asarray(out.rrt_hist[self._jnp.asarray(active)])
        zeros = np.asarray(out.rrt_zeros[self._jnp.asarray(active)])
        # cumulative over buckets (le semantics: count of samples <=
        # bound; the below-min zeros count is <= every retained bound),
        # then accumulated over windows (counter semantics)
        cum = np.cumsum(hist, axis=1)[:, self._bucket_idx] \
            + zeros[:, None]
        # f32-precision guard: reset a group's counter to this window's
        # counts before its total exceeds the f32 exact-integer range
        # (rate() absorbs the reset like any counter restart)
        over = self._bucket_cum[active, -1] > float(1 << 23)
        self._bucket_cum[active] = np.where(
            over[:, None], cum, self._bucket_cum[active] + cum)
        # one label-hash row per group, dictionary-encoded once; the
        # emit itself is pure array ops (this runs on the 1s window
        # thread — a per-(group, bucket) Python loop would stall it)
        n_le = len(self._bucket_les)
        lh_rows = []
        for g in active.tolist():
            row = self._label_rows.get(g)
            if row is None:
                row = np.asarray(
                    [self._label_dict.encode_one(
                        f"le={le},service_group={g}")
                     for le in self._bucket_les], np.uint32)
                self._label_rows[g] = row
            lh_rows.append(row)
        k = len(active) * n_le
        self.bucket_writer.put({
            "timestamp": np.full(k, second, np.uint32),
            "metric": np.full(k, self._bucket_metric_h, np.uint32),
            "labels": np.concatenate(lh_rows),
            "value": self._bucket_cum[active].ravel().astype(np.float32),
        })

    def flush(self) -> None:
        """Drain pending RED rows to disk (Ingester.flush)."""
        if self.writer is not None:
            self.writer.flush()
        if self.bucket_writer is not None:
            self.bucket_writer.flush()

    def counters(self) -> dict:
        c = super().counters()   # keep the queue's observable-loss stats
        c.update({"rows_in": self.rows_in, "windows": self.windows})
        return c

"""Agent orchestrator (reference: agent/src/trident.rs + rpc/synchronizer).

Builds the capture-side pipeline — packet decode, policy labeler, flow
map, L7 session parsing, quadruple generator, uniform senders — and runs
the control loops: a controller sync heartbeat that registers the agent,
hot-applies pushed config (reference: ConfigHandler diff/apply), follows
ingester reassignment, and escapes to safe defaults when the controller
goes silent; plus the 1s tick that flushes flows and metric documents
onto the firehose.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from deepflow_tpu.agent.flow_map import FlowMap
from deepflow_tpu.agent.guard import EscapeTimer, Guard
from deepflow_tpu.agent.l7 import (MSG_REQUEST, SessionAggregator,
                                   parse_payload)
# AFTER l7: the l7 <-> l7_ext pair registers extended parsers at
# import time, and l7 must win the import race (importing l7_ext
# first leaves it partially initialized when l7 calls back into it)
from deepflow_tpu.agent.l7_ext import L7_TLS
from deepflow_tpu.agent.packet import PROTO_TCP, PROTO_UDP
from deepflow_tpu.agent.policy import (PolicyEnforcer,
                                       PolicyLabeler)
from deepflow_tpu.agent.quadruple import (documents_to_records,
                                          flows_to_documents)
from deepflow_tpu.agent.sender import UniformSender
from deepflow_tpu.wire.framing import MessageType
from deepflow_tpu.wire.gen import flow_log_pb2


@dataclass
class AgentConfig:
    ctrl_ip: str = "127.0.0.1"
    host: str = "agent-host"
    controller_url: Optional[str] = None      # None = standalone mode
    ingester_addr: str = "127.0.0.1:30033"
    sync_interval_s: float = 60.0
    escape_after_s: float = 300.0
    revision: str = "deepflow-tpu-agent"
    l7_enabled: bool = True
    # "columnar" ships tick flows as planar COLUMNAR_FLOW frames (the
    # TPU-native wire: vectorized encode, memcpy decode); "protobuf"
    # emits per-row TaggedFlow records for reference-compatible servers
    wire_mode: str = "columnar"
    # platform sync (agent/platform.py): interface report cadence, and an
    # optional k8s resource file to watch (api_watcher analogue)
    platform_sync_interval_s: float = 60.0
    k8s_resource_file: Optional[str] = None
    k8s_cluster_domain: str = "k8s-cluster"
    # live apiserver list/watch (agent/k8s_watch.py); takes precedence
    # over the file lister when set
    k8s_apiserver_url: Optional[str] = None
    k8s_apiserver_token: Optional[str] = None
    # KVM host: libvirt qemu domain-XML directory to extract guest
    # NICs from (reference: libvirt_xml_extractor.rs); None = off
    libvirt_xml_dir: Optional[str] = None
    # shared-object L7 plugins (agent/plugin.py): .so paths loaded at
    # startup and hot-loadable via pushed config (reference: rpc Plugin)
    so_plugins: tuple = ()
    # sandboxed wasm L7 plugins (agent/wasm_plugin.py): .wasm paths,
    # same lifecycle as so_plugins but fuel/memory-confined
    wasm_plugins: tuple = ()
    # packet-sequence collection (agent/packet_sequence.py): per-packet
    # TCP headers -> l4_packet rows. Off by default like the reference's
    # packet_sequence_flag=0 (config.rs:519)
    packet_sequence: bool = False
    # l4 flow-log aggregation interval (agent/flow_aggr.py, the
    # collector/flow_aggr.rs role): 0 ships every 1s tick row; 60
    # matches the reference's 1m l4_flow_log granularity. The metrics
    # fork (quadruple documents) always stays at 1s either way.
    l4_log_aggr_s: int = 0
    # agent-side L7 session rate cap per second (reference:
    # l7_log_collect_nps_threshold, default 10000); 0 = uncapped
    l7_log_rate: int = 10_000
    # continuous OnCPU profiling (agent/profiler.py, the perf_profiler.c
    # role): pids to sample (0 = the agent's own process). Each cycle
    # samples `profile_duration_s` at `profile_freq_hz` and ships the
    # folded stacks as Profile records on the firehose. Empty = off.
    profile_pids: tuple = ()
    profile_interval_s: float = 10.0
    profile_duration_s: float = 1.0
    profile_freq_hz: int = 99
    # agent-side UDP debug server (reference: agent/src/debug/ serving
    # per-subsystem dumps to deepflow-ctl). None disables; 0 = ephemeral
    debug_port: Optional[int] = None
    # where controller-pushed upgrade packages are staged (rpc Upgrade
    # role); None = /tmp
    upgrade_dir: Optional[str] = None
    # ship the agent's own counters as DFSTATS onto the firehose
    # (reference: utils/stats.rs -> ingester deepflow_system DB)
    self_telemetry: bool = True
    # dispatcher (agent/dispatcher.py): capture mode + policy actions
    dispatcher_mode: str = "local"
    local_macs: tuple = ()
    npb_addr: Optional[str] = None            # NPB action target
    npb_tunnel: str = "raw"                   # "raw" | "vxlan" encap
    pcap_policy_dir: Optional[str] = None     # PCAP action sink


def columns_to_l4_schema(cols: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Vectorized tick-columns -> L4_SCHEMA planar columns, the payload of
    the columnar wire mode. Matches the server decoders' unit contract
    (timestamp s, duration us, 4-byte planes) without any per-row work."""
    from deepflow_tpu.batch.schema import L4_SCHEMA

    out: Dict[str, np.ndarray] = {}
    for name, dt in L4_SCHEMA.columns:
        if name == "timestamp":
            out[name] = (cols["start_time"]
                         // np.uint64(1_000_000_000)).astype(dt)
        elif name == "duration_us":
            out[name] = np.minimum(cols["duration"] // np.uint64(1000),
                                   np.uint64(0xFFFFFFFF)).astype(dt)
        elif name in cols:
            out[name] = cols[name].astype(dt, copy=False)
        else:
            out[name] = np.zeros(len(cols["ip_src"]), dt)
    return out


def columns_to_l4_records(cols: Dict[str, np.ndarray]) -> List[bytes]:
    """Serialize tick flow columns as TaggedFlow wire records."""
    out: List[bytes] = []
    for i in range(len(cols["ip_src"])):
        m = flow_log_pb2.TaggedFlow()
        f = m.flow
        k = f.flow_key
        k.vtap_id = int(cols["vtap_id"][i])
        k.ip_src = int(cols["ip_src"][i])
        k.ip_dst = int(cols["ip_dst"][i])
        k.port_src = int(cols["port_src"][i])
        k.port_dst = int(cols["port_dst"][i])
        k.proto = int(cols["proto"][i])
        src = f.metrics_peer_src
        src.byte_count = int(cols["byte_tx"][i])
        src.packet_count = int(cols["packet_tx"][i])
        src.l3_epc_id = int(cols["l3_epc_id"][i])
        dst = f.metrics_peer_dst
        dst.byte_count = int(cols["byte_rx"][i])
        dst.packet_count = int(cols["packet_rx"][i])
        f.flow_id = int(cols["flow_id"][i])
        f.start_time = int(cols["start_time"][i])
        f.duration = int(cols["duration"][i])
        f.end_time = f.start_time + f.duration
        f.close_type = int(cols["close_type"][i])
        f.tap_side = int(cols["tap_side"][i])
        f.is_new_flow = int(cols["is_new_flow"][i])
        f.eth_type = 0x0800
        has_perf = cols["rtt"][i] or cols["retrans"][i]
        if not has_perf:
            # any engine signal warrants the stats block: a mid-stream
            # capture can have zero-window/CIT/continuous-RTT data with
            # no handshake rtt and no retransmissions
            for name in ("srt_count", "art_count", "cit_count",
                         "zero_win_tx", "zero_win_rx", "syn_count",
                         "synack_count", "rtt_client", "rtt_server"):
                if name in cols and cols[name][i]:
                    has_perf = True
                    break
        if has_perf:
            f.has_perf_stats = 1
            f.perf_stats.l4_protocol = 1
            t = f.perf_stats.tcp
            t.rtt = int(cols["rtt"][i])
            t.total_retrans_count = int(cols["retrans"][i])
            for name in ("srt_sum", "srt_count", "srt_max", "art_sum",
                         "art_count", "art_max", "cit_sum", "cit_count",
                         "cit_max", "syn_count", "synack_count"):
                if name in cols:
                    setattr(t, name, int(cols[name][i]))
            if "rtt_client" in cols:
                t.rtt_client_max = int(cols["rtt_client"][i])
                t.rtt_server_max = int(cols["rtt_server"][i])
                t.counts_peer_tx.retrans_count = int(cols["retrans_tx"][i])
                t.counts_peer_rx.retrans_count = int(cols["retrans_rx"][i])
                t.counts_peer_tx.zero_win_count = \
                    int(cols["zero_win_tx"][i])
                t.counts_peer_rx.zero_win_count = \
                    int(cols["zero_win_rx"][i])
        out.append(m.SerializeToString())
    return out


def l7_session_message(flow, rec_dict: dict, ts_ns: int,
                       vtap_id: int) -> "flow_log_pb2.AppProtoLogsData":
    """Merged l7 session -> AppProtoLogsData message. ONE builder for
    every front end (packet capture here, syscall records in
    agent/ebpf_source.py) so session orientation and wire fields cannot
    drift between sources. ts_ns is the merge (response) time; start
    backs off by the measured round trip."""
    m = flow_log_pb2.AppProtoLogsData()
    b = m.base
    b.start_time = max(ts_ns - rec_dict["rrt_us"] * 1000, 0)
    b.end_time = ts_ns
    b.vtap_id = vtap_id
    b.ip_src, b.ip_dst = int(flow[0]), int(flow[1])
    b.port_src, b.port_dst = int(flow[2]), int(flow[3])
    b.protocol = int(flow[4])
    b.head.proto = rec_dict["proto"]
    b.head.msg_type = 2                # merged session (LogMessageType)
    b.head.rrt = rec_dict["rrt_us"] * 1000
    m.req.endpoint = rec_dict["endpoint"]
    m.resp.status = rec_dict["status"]
    m.req_len = rec_dict["req_len"]
    m.resp_len = rec_dict["resp_len"]
    # instrumented-app trace context + request detail (parsers stamp
    # these when present; empty strings hash to 0 = reference NULL)
    m.version = rec_dict.get("version", "")
    m.req.req_type = rec_dict.get("req_type", "")
    m.req.domain = rec_dict.get("domain", "")
    m.req.resource = rec_dict.get("resource", "")
    m.trace_info.trace_id = rec_dict.get("trace_id", "")
    m.trace_info.span_id = rec_dict.get("span_id", "")
    m.ext_info.x_request_id_0 = rec_dict.get("x_request_id_0", "")
    m.ext_info.x_request_id_1 = rec_dict.get("x_request_id_1", "")
    m.ext_info.client_ip = rec_dict.get("client_ip", "")
    m.ext_info.http_user_agent = rec_dict.get("user_agent", "")
    m.ext_info.http_referer = rec_dict.get("referer", "")
    # packet-path TLS detection: a session the TLS parser recognized
    # (handshake metadata — SNI/version; the payload itself stays
    # encrypted) carries the same is_tls bit the uprobe sources set,
    # so "WHERE is_tls = 1" covers both observation modes
    if rec_dict["proto"] == L7_TLS:
        m.flags = m.flags | 1
    return m


def _l7_record_bytes(flow, rec_dict: dict, ts_ns: int,
                     vtap_id: int) -> bytes:
    return l7_session_message(flow, rec_dict, ts_ns,
                              vtap_id).SerializeToString()


class Agent:
    """Standalone or managed capture agent."""

    def __init__(self, cfg: AgentConfig) -> None:
        self.cfg = cfg
        self.vtap_id = 0
        self.flow_map = FlowMap()
        self.policy = PolicyLabeler()
        from deepflow_tpu.agent.dispatcher import (Dispatcher,
                                                   DispatcherConfig)
        self.enforcer = PolicyEnforcer(self.policy, npb_addr=cfg.npb_addr,
                                       pcap_dir=cfg.pcap_policy_dir,
                                       npb_tunnel=cfg.npb_tunnel)
        self.dispatcher = Dispatcher(
            DispatcherConfig(mode=cfg.dispatcher_mode,
                             local_macs=set(cfg.local_macs)),
            policy=self.policy, enforcer=self.enforcer)
        self.sessions = SessionAggregator()
        self.flow_aggr = None
        self._pending_aggr = None     # stash drained on interval change
        self.aggr_schema_errors = 0   # divergent hot-switch column sets
        self.last_aggr_schema_error = ""
        if cfg.l4_log_aggr_s:
            from deepflow_tpu.agent.flow_aggr import FlowAggr
            self.flow_aggr = FlowAggr(cfg.l4_log_aggr_s)
        self.guard = Guard()
        self.escape = EscapeTimer(cfg.escape_after_s, self._on_escape)
        sender_types = [MessageType.TAGGEDFLOW, MessageType.METRICS,
                        MessageType.PROTOCOLLOG, MessageType.COLUMNAR_FLOW,
                        MessageType.PROC_EVENT]
        self.pseq = None
        self._pseq_pending: List[bytes] = []
        if cfg.packet_sequence:
            from deepflow_tpu.agent.packet_sequence import \
                PacketSequenceCollector
            self.pseq = PacketSequenceCollector()
            self.flow_map.want_packet_context = True
            sender_types.append(MessageType.PACKETSEQUENCE)
        self.profiles_sent = 0
        self.profile_errors = 0
        self.gpid_map: Dict[int, int] = {}
        self.upgrades_applied = 0
        self.upgrade_errors = 0
        self.sync_errors = 0
        self.plugin_fetch_errors = 0
        self.staged_package: Optional[str] = None
        # real deployments exec the staged binary here; None = revision
        # swap in place (process and firehose sockets stay up)
        self.on_upgrade = None
        if cfg.profile_pids:
            sender_types.append(MessageType.PROFILE)
        self.senders: Dict[MessageType, UniformSender] = {
            mt: UniformSender(mt, cfg.ingester_addr)
            for mt in sender_types
        }
        self._stop = threading.Event()
        self._threads: list = []   # supervisor ThreadHandles
        self._lock = threading.Lock()
        self._l7_out: List[bytes] = []
        self.escaped = False
        self.config_version = 0
        self.platform_watcher = None
        self.k8s_watcher = None
        self.api_watcher = None
        self.ntp_offset_ns = 0
        self._capture_source = None   # set via attach_source()
        self._l7_rate_sec = -1        # L7 rate-cap window (epoch second)
        self._l7_rate_used = 0
        self.l7_throttled = 0
        self.so_plugins: Dict[str, object] = {}
        for path in cfg.so_plugins:
            self._load_plugin(path)
        self.wasm_plugins: Dict[str, object] = {}
        for path in cfg.wasm_plugins:
            self._load_wasm(path)
        # one Countable registry for BOTH the debug surface and the
        # DFSTATS self-telemetry loop (reference: utils/stats.rs — the
        # agent monitors itself with the same pipeline it feeds)
        from deepflow_tpu.runtime.stats import StatsRegistry

        self.stats = StatsRegistry()
        self.stats.register("agent.flow_map", self.flow_map.counters)
        # closure, not a bound method: the aggregator hot-swaps when a
        # pushed config changes l4_log_aggr_s
        self.stats.register(
            "agent.flow_aggr",
            lambda: (self.flow_aggr.counters() if self.flow_aggr
                     is not None else {"rows_in": 0, "rows_out": 0,
                                       "stashed": 0, "enabled": 0}))
        self.stats.register("agent.dispatcher", self.dispatcher.counters)
        self.stats.register("agent.enforcer", self.enforcer.counters)
        self.stats.register("agent.guard", self.guard.counters)
        if self.pseq is not None:
            self.stats.register("agent.packet_sequence",
                                self.pseq.counters)
        self.stats_shipper = None
        self.debug = None
        if cfg.debug_port is not None:
            self._build_debug(cfg.debug_port)

    def _build_debug(self, port: int) -> None:
        """Agent-side debug protocol (reference: agent/src/debug/ —
        per-subsystem dumps over UDP for deepflow-ctl). Shares the
        server-side protocol/CLI plumbing (runtime/debug.py)."""
        from deepflow_tpu.runtime.debug import DebugServer

        self.debug = DebugServer(self.stats, port=port)
        self.debug.register("policy", lambda req: {
            "rules": [vars(r) for r in self.policy.rules],
            "enforcer": self.enforcer.counters()})
        self.debug.register("rpc", lambda req: {
            "vtap_id": self.vtap_id,
            "config_version": self.config_version,
            "escaped": self.escaped,
            "ntp_offset_ns": self.ntp_offset_ns,
            "controller_url": self.cfg.controller_url})
        from deepflow_tpu.agent.platform import local_interfaces
        self.debug.register("platform", lambda req: {
            "interfaces": local_interfaces(),
            "k8s_watcher": (self.k8s_watcher.counters()
                            if self.k8s_watcher is not None else None)})
        self.debug.register("plugins", lambda req: {
            "so": [p.counters() for p in self.so_plugins.values()],
            "wasm": [p.counters() for p in self.wasm_plugins.values()]})

        def _ebpf_dump(req: dict) -> dict:
            # the reference's `deepflow-ctl agent ebpf` dump: what the
            # kernel side is doing — loader availability, attached
            # capture filters (kernel verdict counters), and the
            # syscall-tracer state machine if one is wired
            from deepflow_tpu.agent import bpf as bpf_mod
            from deepflow_tpu.agent import socket_trace as st_mod
            from deepflow_tpu.agent import uprobe_trace as up_mod
            attach_ok, attach_why = st_mod.attach_available()
            up_ok, up_why = up_mod.attach_available()
            out: dict = {"bpf_available": bpf_mod.available(),
                         # CAPABILITY of the in-tree socket_trace
                         # kprobe suite: could programs attach on this
                         # host (and why not). The agent currently
                         # sources syscall records from the replay path
                         # either way — this flag is the prerequisite,
                         # not the switch.
                         "socket_trace_attach_capable": attach_ok,
                         "socket_trace_attach_reason": attach_why,
                         # TLS uprobe suite: live when the uprobe PMU
                         # is visible AND enable_tls_uprobes ran
                         "tls_uprobe_attach_capable": up_ok,
                         "tls_uprobe_attach_reason": up_why}
            tls = getattr(self, "tls_uprobes", None)
            if tls is not None:
                out["tls_uprobes"] = tls.counters()
            tracer = getattr(self, "ebpf_tracer", None)
            if tracer is not None:
                out["tracer"] = tracer.counters()
            src = self._capture_source
            filt = getattr(src, "bpf", None) if src is not None else None
            if filt is not None:
                out["capture_filter"] = {**filt.counters(), **filt.spec}
            return out
        self.debug.register("ebpf", _ebpf_dump)

    def _load_plugin(self, path: str) -> bool:
        """dlopen + register one L7 plugin; a broken .so must not take
        the agent down (reference: load_plugin error path just logs)."""
        from deepflow_tpu.agent.plugin import load_so_plugin
        if path in self.so_plugins:
            return True
        try:
            self.so_plugins[path] = load_so_plugin(path)
            return True
        except (OSError, ValueError):
            return False

    def _load_wasm(self, path: str) -> bool:
        """Instantiate + register one sandboxed wasm parser; a broken
        module must not take the agent down."""
        from deepflow_tpu.agent.wasm_plugin import load_wasm_plugin
        if path in self.wasm_plugins:
            return True
        try:
            self.wasm_plugins[path] = load_wasm_plugin(path)
            return True
        except Exception:
            # hostile bytes can fail in arbitrary ways before the
            # sandbox's own trap conversion is armed; none of them may
            # take the agent down
            return False

    def attach_source(self, source) -> None:
        """Declare the live capture source feeding this agent (the
        CaptureLoop's source) so the debug surface can introspect it
        (ebpf dump: attached filter spec + kernel verdict counters)."""
        self._capture_source = source

    def set_vtap_id(self, vtap_id: int) -> None:
        """Fan the assigned id out to every component that stamps it:
        flow rows, and each sender's wire FlowHeader."""
        self.vtap_id = vtap_id
        self.flow_map.vtap_id = vtap_id
        for s in self.senders.values():
            s.vtap_id = vtap_id
        if self.stats_shipper is not None:
            self.stats_shipper.sender.vtap_id = vtap_id

    # -- control plane -----------------------------------------------------
    def sync_once(self) -> bool:
        """One controller round trip (reference: Synchronizer.Sync)."""
        if self.cfg.controller_url is None:
            return True
        body = json.dumps({"ctrl_ip": self.cfg.ctrl_ip,
                           "host": self.cfg.host,
                           "revision": self.cfg.revision,
                           "boot": self.vtap_id == 0,
                           # GPIDSync leg: processes this agent observes
                           # (its own + eBPF-seen); the controller
                           # returns globally-unique gprocess ids
                           "processes": self._local_processes()}).encode()
        req = urllib.request.Request(
            f"{self.cfg.controller_url}/v1/sync", data=body,
            headers={"Content-Type": "application/json"})
        t0 = time.time_ns()
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                r = json.load(resp)
        except Exception:
            return False
        t1 = time.time_ns()
        if "server_time_ns" in r:
            # classic NTP midpoint estimate: offset = server - local at
            # the round-trip middle (reference: rpc/ntp.rs). Tracked and
            # surfaced, NOT silently applied to packet timestamps — a
            # step-change mid-window would corrupt flow durations; the
            # operator sees the drift in counters/df-ctl and fixes the
            # clock (the reference's agent likewise reports and gates).
            self.ntp_offset_ns = int(r["server_time_ns"]) - (t0 + t1) // 2
        self.set_vtap_id(r["vtap_id"])
        if r.get("ingester"):
            for s in self.senders.values():
                s.set_target(r["ingester"])
            if self.stats_shipper is not None:
                # self-telemetry follows the reassignment too
                self.stats_shipper.sender.set_target(r["ingester"])
        if r["config_version"] != self.config_version:
            self._apply_config(r["config"])
            self.config_version = r["config_version"]
        if r.get("gpids"):
            self.gpid_map = {int(k): int(v)
                             for k, v in r["gpids"].items()}
            tracer = getattr(self, "ebpf_tracer", None)
            if tracer is not None:
                tracer.gpid_map = self.gpid_map
        if r.get("upgrade"):
            self._apply_upgrade(r["upgrade"])
        self.escape.on_sync_ok()
        self.escaped = False
        return True

    def _local_processes(self) -> list:
        """Processes this agent reports for GPIDSync: itself plus any
        pids the eBPF tracer has seen records from."""
        procs = [{"pid": os.getpid(), "name": "deepflow-agent",
                  "start_time": self._self_start_time()}]
        tracer = getattr(self, "ebpf_tracer", None)
        if tracer is not None:
            procs.extend(tracer.seen_processes())
        return procs

    @staticmethod
    def _self_start_time() -> int:
        try:
            with open("/proc/self/stat") as f:
                # field 22 (starttime, clock ticks since boot); fields
                # after the parenthesized comm, which may contain spaces
                return int(f.read().rsplit(")", 1)[1].split()[19])
        except (OSError, IndexError, ValueError):
            return 0

    def _apply_upgrade(self, upg: dict) -> None:
        """Staged agent upgrade (reference: rpc Upgrade + the agent's
        upgrade task): fetch the package from the controller, verify
        the checksum, stage it to disk, flush in-flight data, then
        restart into the new revision. Here "restart" = the on_upgrade
        callback (a real deployment execs the staged binary there); the
        default keeps the process and its sender sockets alive, so the
        firehose never drops a tick."""
        import base64
        import hashlib
        if upg.get("revision") == self.cfg.revision:
            return
        try:
            url = (f"{self.cfg.controller_url}/v1/upgrade-package?name="
                   + urllib.parse.quote(upg["package"]))
            with urllib.request.urlopen(url, timeout=30) as resp:
                doc = json.load(resp)
            data = base64.b64decode(doc["data_b64"])
        except Exception:
            self.upgrade_errors += 1
            return
        digest = hashlib.sha256(data).hexdigest()
        if digest != upg.get("sha256"):
            # corrupt/tampered package: refuse, stay on the old revision
            self.upgrade_errors += 1
            return
        staged = os.path.join(self.cfg.upgrade_dir or "/tmp",
                              f"deepflow-agent-{upg['revision']}")
        try:
            with open(staged + ".tmp", "wb") as f:
                f.write(data)
            os.replace(staged + ".tmp", staged)
        except OSError:
            self.upgrade_errors += 1
            return
        self.tick()                      # flush before the restart
        if self.on_upgrade is not None:
            # the restart hook (a deployment execs the staged binary
            # here) runs BEFORE the revision flips: if it fails, the
            # agent keeps reporting the old revision so the controller
            # keeps retrying (and eventually quarantines it) instead of
            # recording a converged agent that never restarted. The
            # except also keeps the synchronizer thread alive.
            try:
                self.on_upgrade(staged, upg["revision"])
            except Exception:
                self.upgrade_errors += 1
                return
        self.cfg.revision = upg["revision"]
        self.upgrades_applied += 1
        self.staged_package = staged

    def _apply_config(self, cfg: dict) -> None:
        """Hot-apply pushed RuntimeConfig (reference: ConfigHandler)."""
        self.guard.set_limits(cfg.get("max_memory_mb", 768),
                              cfg.get("max_cpus", 1))
        self.cfg.l7_enabled = bool(cfg.get("l7_log_enabled", True))
        self.cfg.sync_interval_s = cfg.get("sync_interval_s", 60)
        if "l7_log_rate" in cfg:
            self.cfg.l7_log_rate = int(cfg["l7_log_rate"] or 0)
        # flow-log aggregation interval is hot-switchable; turning it
        # OFF flushes the stash so no merged rows strand. Under the
        # agent lock: tick() (flow-tick thread) reads/advances the
        # same aggregator.
        if "l4_log_aggr_s" in cfg:
            want = int(cfg["l4_log_aggr_s"] or 0)
            with self._lock:
                have = (self.flow_aggr.interval_s
                        if self.flow_aggr is not None else 0)
                if want != have:
                    if self.flow_aggr is not None:
                        out = self.flow_aggr.flush()
                        if out is not None:
                            # stash drains through the NEXT tick; a
                            # second switch before that tick must
                            # APPEND, not clobber
                            if self._pending_aggr is not None:
                                if self._aggr_sets_match(
                                        self._pending_aggr, out):
                                    out = {k: np.concatenate(
                                        [self._pending_aggr[k], out[k]])
                                        for k in out}
                                # diverged: keep only the fresh flush —
                                # counted in aggr_schema_errors, never
                                # silently intersected
                            self._pending_aggr = out
                    if want:
                        from deepflow_tpu.agent.flow_aggr import FlowAggr
                        self.flow_aggr = FlowAggr(want)
                    else:
                        self.flow_aggr = None
                    self.cfg.l4_log_aggr_s = want
        # trace-context header extraction config (reference proxy config
        # http_log_trace_id / http_log_span_id / ...): hot-swapped into
        # the process-global parser registry's extraction config.
        # configure() accepts a list or the reference's comma-joined
        # string form for every field.
        if any(k in cfg for k in ("http_log_trace_id", "http_log_span_id",
                                  "http_log_x_request_id",
                                  "http_log_proxy_client")):
            from deepflow_tpu.agent import trace_context
            trace_context.configure(
                trace_types=cfg.get("http_log_trace_id"),
                span_types=cfg.get("http_log_span_id"),
                x_request_id=cfg.get("http_log_x_request_id"),
                proxy_client=cfg.get("http_log_proxy_client"))
        # pushed policy (reference: FlowAcl push -> policy compile):
        # absent/None = unmanaged; a LIST is authoritative (pushing []
        # must clear the rule set). Versioned like the reference's
        # version_acls so an unchanged push is a no-op.
        if cfg.get("flow_acls") is not None:
            from deepflow_tpu.agent.policy import rules_from_flow_acls
            self.policy.update(rules_from_flow_acls(cfg["flow_acls"]),
                               int(cfg.get("acl_version", 0) or 0)
                               or self.policy.version + 1)
        # absent or None = plugins not managed by this push; a LIST is
        # authoritative (pushing [] must actually stop a plugin)
        if cfg.get("so_plugins") is not None:
            self._sync_plugins(cfg["so_plugins"])
        if cfg.get("wasm_plugins") is not None:
            self._sync_wasm_plugins(cfg["wasm_plugins"])

    def _resolve_plugin_path(self, entry: str) -> Optional[str]:
        """A pushed plugin entry is a local path, or `pkg://<name>` —
        a controller-DISTRIBUTED binary (the reference's rpc Plugin
        stream role): fetched from the upgrade-package store, sha256-
        verified, cached under upgrade_dir/plugins. A cache hit is
        validated against the store's metadata (a re-uploaded package
        under the same name must reach every agent, not just fresh
        ones); when the controller is unreachable the cache is trusted
        (offline tolerance). Returns the local path to load, or None
        on failure (counted)."""
        if not entry.startswith("pkg://"):
            return entry
        import base64
        import hashlib
        name = entry[len("pkg://"):]
        if not name or "/" in name or name.startswith("."):
            self.plugin_fetch_errors += 1
            return None
        cache_dir = os.path.join(self.cfg.upgrade_dir or "/tmp",
                                 "plugins")
        cached = os.path.join(cache_dir, name)
        base = (f"{self.cfg.controller_url}/v1/upgrade-package?name="
                + urllib.parse.quote(name)
                ) if self.cfg.controller_url else None
        if os.path.exists(cached):
            if base is None:
                return cached
            try:
                with urllib.request.urlopen(base + "&meta=1",
                                            timeout=10) as resp:
                    meta = json.load(resp)
                with open(cached, "rb") as f:
                    local = hashlib.sha256(f.read()).hexdigest()
                if local == meta.get("sha256"):
                    return cached
                # stale: fall through to refetch
            except Exception:
                return cached           # controller unreachable: trust
        if base is None:
            self.plugin_fetch_errors += 1
            return None
        try:
            with urllib.request.urlopen(base, timeout=30) as resp:
                doc = json.load(resp)
            data = base64.b64decode(doc["data_b64"])
            if hashlib.sha256(data).hexdigest() != doc.get("sha256"):
                raise ValueError("package sha256 mismatch")
            os.makedirs(cache_dir, exist_ok=True)
            with open(cached + ".tmp", "wb") as f:
                f.write(data)
            os.replace(cached + ".tmp", cached)
            return cached
        except Exception:
            self.plugin_fetch_errors += 1
            return None

    def _converge_plugins(self, paths, loaded: dict, load_fn,
                          unload_fn) -> None:
        """ONE converge discipline for .so and wasm plugin sets: resolve
        (local or pkg://), unload what's no longer wanted (pushing []
        must actually stop a plugin), load the rest."""
        resolved = [p for p in (self._resolve_plugin_path(e)
                                for e in paths) if p is not None]
        want = set(resolved)
        for path in list(loaded):
            if path not in want:
                unload_fn(loaded.pop(path))
        for path in resolved:
            load_fn(path)

    def _sync_plugins(self, paths) -> None:
        from deepflow_tpu.agent.plugin import unload_so_plugin
        self._converge_plugins(paths, self.so_plugins,
                               self._load_plugin, unload_so_plugin)

    def _sync_wasm_plugins(self, paths) -> None:
        from deepflow_tpu.agent.wasm_plugin import unload_wasm_plugin
        self._converge_plugins(paths, self.wasm_plugins,
                               self._load_wasm, unload_wasm_plugin)

    def _on_escape(self) -> None:
        """Controller silent too long: fall back to conservative defaults
        (reference: escape timer -> safe RuntimeConfig)."""
        self.escaped = True
        self.cfg.l7_enabled = False

    # -- data plane --------------------------------------------------------
    def feed(self, frames: List[bytes],
             timestamps_ns: Optional[np.ndarray] = None) -> int:
        """Ingest one capture batch; returns valid packets."""
        pkt = self.dispatcher.dispatch(frames, timestamps_ns)
        with self._lock:
            # collector state is shared with the tick thread's flush:
            # both run under the same lock (the _l7_out pattern)
            ctx = self.flow_map.inject(pkt)
            if self.pseq is not None and ctx is not None:
                self._collect_pseq(ctx)
        if self.cfg.l7_enabled:
            self._parse_l7(frames, pkt)
        return int(pkt["valid"].sum())

    def _collect_pseq(self, ctx: dict) -> None:
        """Per-packet TCP headers into the sequence collector; `ctx` is
        flow_map.inject's per-valid-packet context (cols/flow_id/
        initiator-relative direction — one masking+orientation pass,
        owned by the flow map). Caller holds self._lock."""
        cols = ctx["cols"]
        tcp = np.nonzero(cols["proto"] == PROTO_TCP)[0]
        if not len(tcp):
            return
        zeros = np.zeros(len(cols["proto"]), np.uint32)
        blocks = self.pseq.observe(
            ctx["flow_id"][tcp], cols["timestamp_ns"][tcp],
            cols["tcp_seq"][tcp], cols.get("tcp_ack", zeros)[tcp],
            cols["tcp_flags"][tcp], cols.get("tcp_win", zeros)[tcp],
            cols["payload_len"][tcp], ctx["direction"][tcp])
        if blocks:
            self._pseq_pending.extend(blocks)

    def _parse_l7(self, frames: List[bytes],
                  pkt: Dict[str, np.ndarray]) -> None:
        candidates = np.nonzero(
            pkt["valid"] & (pkt["payload_len"] > 0)
            & ((pkt["proto"] == PROTO_TCP) | (pkt["proto"] == PROTO_UDP))
        )[0]
        for i in candidates:
            payload = frames[i][int(pkt["payload_off"][i]):]
            rec = parse_payload(payload, proto=int(pkt["proto"][i]),
                                port_src=int(pkt["port_src"][i]),
                                port_dst=int(pkt["port_dst"][i]),
                                ts_ns=int(pkt["timestamp_ns"][i]),
                                ip_src=int(pkt["ip_src"][i]),
                                ip_dst=int(pkt["ip_dst"][i]),
                                ip_version=int(pkt["ip_version"][i]))
            if rec is None:
                continue
            # session key is direction-agnostic
            key = tuple(sorted([(int(pkt["ip_src"][i]),
                                 int(pkt["port_src"][i])),
                                (int(pkt["ip_dst"][i]),
                                 int(pkt["port_dst"][i]))]))
            # the merged record is emitted on the RESPONSE packet, whose
            # src is the server — orient the log client->server
            if rec.msg_type == MSG_REQUEST:
                flow = (pkt["ip_src"][i], pkt["ip_dst"][i],
                        pkt["port_src"][i], pkt["port_dst"][i],
                        pkt["proto"][i])
            else:
                flow = (pkt["ip_dst"][i], pkt["ip_src"][i],
                        pkt["port_dst"][i], pkt["port_src"][i],
                        pkt["proto"][i])
            merged = self.sessions.offer((key, int(pkt["proto"][i])), rec,
                                         int(pkt["timestamp_ns"][i]))
            if merged is not None:
                with self._lock:
                    # agent-side L7 rate cap (reference: the LeakyBucket
                    # throttle on PROTOCOLLOG sends,
                    # l7_log_collect_nps_threshold): sessions past this
                    # second's budget drop HERE, before serialization,
                    # and the drop is a Countable
                    sec = int(pkt["timestamp_ns"][i]) // 1_000_000_000
                    # monotonic window roll: an out-of-order EARLIER
                    # stamp must count against the current budget, not
                    # reset it (a != test would refill on every
                    # boundary-straddling interleave)
                    if sec > self._l7_rate_sec:
                        self._l7_rate_sec = sec
                        self._l7_rate_used = 0
                    if self.cfg.l7_log_rate and \
                            self._l7_rate_used >= self.cfg.l7_log_rate:
                        self.l7_throttled += 1
                        continue
                    self._l7_rate_used += 1
                    self._l7_out.append(_l7_record_bytes(
                        flow, merged, int(pkt["timestamp_ns"][i]),
                        self.vtap_id))

    def enable_tls_uprobes(self, paths: Optional[List[str]] = None,
                           pids: Optional[List[int]] = None) -> dict:
        """Live encrypted-traffic capture (reference: the ssl/go
        tracer lifecycles): load the uprobe suite, attach the given
        libssl/Go-binary images and/or discover per-pid, and pump
        captured plaintext records through the EbpfTracer into the
        normal l7 export every tick. Raises OSError where the uprobe
        PMU is masked (callers gate on
        uprobe_trace.attach_available)."""
        from deepflow_tpu.agent.ebpf_source import (EbpfTracer,
                                                    ProcFdResolver)
        from deepflow_tpu.agent.uprobe_trace import (TlsUprobeSource,
                                                     go_version)
        if getattr(self, "ebpf_tracer", None) is None:
            self.ebpf_tracer = EbpfTracer(vtap_id=self.vtap_id)
            self.ebpf_tracer.gpid_map = self.gpid_map
        if getattr(self, "tls_uprobes", None) is None:
            self.tls_uprobes = TlsUprobeSource()
            self._fd_resolver = ProcFdResolver()
        src = self.tls_uprobes
        for p in paths or []:
            if go_version(p):
                src.attach_go(p)
            else:
                src.attach_ssl(p)
        for pid in pids or []:
            src.attach_pid(pid)
        return src.counters()

    def _pump_tls_uprobes(self) -> int:
        """Kernel ring -> EbpfTracer -> _l7_out (ships with the next
        tick's PROTOCOLLOG batch like every other l7 record)."""
        src = getattr(self, "tls_uprobes", None)
        if src is None:
            return 0
        tracer = self.ebpf_tracer

        def _feed(raw: bytes) -> None:
            rec = tracer.feed_raw(raw, resolver=self._fd_resolver)
            if rec:
                with self._lock:
                    self._l7_out.append(rec)

        return src.pump(_feed)

    def tick(self, now_ns: Optional[int] = None,
             final: bool = False) -> dict:
        """1s flush: flows -> TAGGEDFLOW, documents -> METRICS,
        sessions -> PROTOCOLLOG. `final` force-flushes the
        packet-sequence collector (shutdown: blocks younger than the
        5s budget must not be dropped)."""
        now_ns = int(time.time() * 1e9) if now_ns is None else now_ns
        self._pump_tls_uprobes()
        pseq_blocks: List[bytes] = []
        with self._lock:
            # vectorized tick: oriented wire-ready columns, no per-flow
            # Python (flow_map.tick_columns)
            cols = self.flow_map.tick_columns(now_ns)
            cols["vtap_id"][:] = self.vtap_id
            l7_records, self._l7_out = self._l7_out, []
            if self.pseq is not None:
                pseq_blocks = self._pseq_pending \
                    + self.pseq.flush(now_ns, force=final)
                self._pseq_pending = []
        sent = {"flows": 0, "documents": 0, "l7": 0}
        # flow-log fork: optionally aggregated to l4_log_aggr_s buckets
        # (flow_aggr.rs); the metrics fork below always sees the 1s
        # cols. Under the agent lock: _apply_config (synchronizer
        # thread) flushes/swaps the aggregator on hot-switch, and the
        # stash's slot bookkeeping is not safe against that interleave.
        flow_cols = cols
        with self._lock:
            if self.flow_aggr is not None:
                agg = self.flow_aggr.add(cols, now_ns)
                if final:
                    fin = self.flow_aggr.flush()
                    if fin is not None:
                        agg = fin if agg is None else {
                            k: np.concatenate([agg[k], fin[k]])
                            for k in agg}
                flow_cols = agg
            if self._pending_aggr is not None:
                # rows flushed by an interval hot-switch ride this tick
                pend, self._pending_aggr = self._pending_aggr, None
                if flow_cols is None or not len(
                        flow_cols.get("ip_src", ())):
                    flow_cols = pend
                elif self._aggr_sets_match(pend, flow_cols):
                    flow_cols = {
                        k: np.concatenate([flow_cols[k], pend[k]])
                        for k in pend}
                # else: column sets diverged (schema change between the
                # hot-switch flush and this tick). The stale pending rows
                # are DROPPED — visibly, via aggr_schema_errors — rather
                # than intersect-merged into a malformed batch or raised
                # into the unsupervised flow-tick thread (which would
                # stop all exports for the rest of the process).
        if flow_cols is not None and len(flow_cols["ip_src"]):
            if self.cfg.wire_mode == "columnar":
                from deepflow_tpu.batch.schema import L4_SCHEMA
                sent["flows"] = self.senders[
                    MessageType.COLUMNAR_FLOW].send_columns(
                        columns_to_l4_schema(flow_cols), L4_SCHEMA)
            else:
                records = columns_to_l4_records(flow_cols)
                sent["flows"] = self.senders[
                    MessageType.TAGGEDFLOW].send(records)
        if len(cols["ip_src"]):
            docs = flows_to_documents(cols, now_ns // 1_000_000_000)
            doc_records = documents_to_records(docs)
            sent["documents"] = self.senders[MessageType.METRICS].send(
                doc_records)
        if l7_records:
            sent["l7"] = self.senders[MessageType.PROTOCOLLOG].send(
                l7_records)
        tracer = getattr(self, "ebpf_tracer", None)
        if tracer is not None and tracer.io_events:
            # slow file-IO spans the tracer's IO gate extracted
            # (reference: io_event -> PROC_EVENT -> perf_event table)
            evs, tracer.io_events = tracer.io_events, []
            sent["proc_events"] = self.senders[
                MessageType.PROC_EVENT].send(evs)
        if pseq_blocks:
            # packet-sequence blocks are self-delimited by their
            # leading u32 block_size (l4_packet.go's decoder reads
            # exactly that), so frames carry blocks concatenated RAW —
            # no per-record varint prefixes
            sent["packet_blocks"] = self.senders[
                MessageType.PACKETSEQUENCE].send_raw_batch(pseq_blocks)
        self.sessions.expire(now_ns)
        return sent

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.guard.start()
        if self.debug is not None:
            self.debug.start()
        if self.cfg.self_telemetry and self.cfg.ingester_addr:
            from deepflow_tpu.runtime.stats import StatsShipper
            self.stats_shipper = StatsShipper(
                self.stats, self.cfg.ingester_addr, vtap_id=self.vtap_id)
            self.stats.start(interval_s=10.0)
        # worker threads ride the supervision tree (ISSUE 14 baseline
        # burn-down): crash capture + backoff restart instead of a
        # silently dead synchronizer/ticker
        from deepflow_tpu.runtime.supervisor import default_supervisor
        sup = default_supervisor()
        if self.cfg.controller_url is not None:
            self._threads.append(sup.spawn(
                "synchronizer", self._sync_loop,
                beat_period_s=self.cfg.sync_interval_s))
            # platform sync: interface report on change + optional k8s
            # cluster watch (agent/platform.py — api_watcher analogue)
            from deepflow_tpu.agent.platform import (file_lister,
                                                     interface_reporter,
                                                     k8s_watcher,
                                                     libvirt_lister,
                                                     local_interfaces)
            lister = None
            if self.cfg.libvirt_xml_dir:
                # KVM host: guest NICs from the domain XML definitions
                # ride the same genesis report as the host's own NICs
                lv = libvirt_lister(self.cfg.libvirt_xml_dir)
                lister = (lambda: local_interfaces() + lv())
            self.platform_watcher = interface_reporter(
                self.cfg.controller_url, self.cfg.host, self.cfg.ctrl_ip,
                lister=lister,
                interval_s=self.cfg.platform_sync_interval_s)
            self.platform_watcher.start()
            if self.cfg.k8s_apiserver_url:
                # the real list/watch protocol: the live cache is the
                # lister, SnapshotWatcher pushes it on change
                from deepflow_tpu.agent.k8s_watch import ApiWatcher
                self.api_watcher = ApiWatcher(
                    self.cfg.k8s_apiserver_url,
                    token=self.cfg.k8s_apiserver_token)
                self.api_watcher.start()
                self.k8s_watcher = k8s_watcher(
                    self.cfg.controller_url,
                    self.cfg.k8s_cluster_domain,
                    self.api_watcher.snapshot,
                    interval_s=self.cfg.platform_sync_interval_s)
                self.k8s_watcher.start()
            elif self.cfg.k8s_resource_file:
                self.k8s_watcher = k8s_watcher(
                    self.cfg.controller_url,
                    self.cfg.k8s_cluster_domain,
                    file_lister(self.cfg.k8s_resource_file),
                    interval_s=self.cfg.platform_sync_interval_s)
                self.k8s_watcher.start()
        self._threads.append(sup.spawn("flow-tick", self._tick_loop))
        if self.cfg.profile_pids:
            from deepflow_tpu.agent import profiler as prof_mod
            if prof_mod.available():
                # deadman off: a sampling cycle legitimately blocks for
                # profile_duration_s at a stretch
                self._threads.append(sup.spawn(
                    "oncpu-profiler", self._profile_loop,
                    deadman_s=None))

    def close(self) -> None:
        self._stop.set()
        for w in (self.platform_watcher, self.k8s_watcher,
                  self.api_watcher):
            if w is not None:
                w.close()
        for t in self._threads:
            t.stop()           # cancel any in-progress restart backoff
        for t in self._threads:
            t.join(timeout=2)
        self.tick(final=True)  # final flush incl. young pseq blocks
        tls = getattr(self, "tls_uprobes", None)
        if tls is not None:    # detach probes + perf rings + maps
            tls.close()
            self.tls_uprobes = None
        if self.debug is not None:
            self.debug.close()
        if self.stats_shipper is not None:
            # final scrape: an agent shorter-lived than the 10s cadence
            # (or counters updated since the last tick) must still land
            self.stats.collect()
            self.stats_shipper.close()   # removes sink, flushes, closes
        self.stats.stop()
        self.enforcer.close()
        self.guard.close()
        for s in self.senders.values():
            s.close()
        # unregister our plugins from the process-global parser set: a
        # successor Agent in this process would otherwise double-register
        self._sync_plugins(())
        self._sync_wasm_plugins(())

    def _sync_loop(self) -> None:
        from deepflow_tpu.runtime.supervisor import default_supervisor
        sup = default_supervisor()
        while True:
            sup.beat()
            # the synchronizer thread must survive any single round's
            # exception (a bad pushed config, an upgrade hook error):
            # a dead sync loop means no config pushes, no escape
            # checks, and no recovery — forever
            try:
                self.sync_once()
                self.escape.check()
            except Exception:
                self.sync_errors += 1
            if self._stop.wait(self.cfg.sync_interval_s):
                return

    def _tick_loop(self) -> None:
        from deepflow_tpu.runtime.supervisor import default_supervisor
        sup = default_supervisor()
        while not self._stop.wait(1.0):
            sup.beat()
            self.tick()

    def _profile_loop(self) -> None:
        """Continuous OnCPU profiling cycle: sample each configured pid
        for profile_duration_s, ship folded stacks on the firehose. A
        target that exits or refuses perf is counted, never fatal."""
        from deepflow_tpu.agent.profiler import (OnCpuProfiler, Symbolizer,
                                                 folded_to_profile_records)
        # symbolizers cached per pid across cycles, invalidated when the
        # process's mappings change — re-parsing every mapped ELF's
        # symtab each 10s cycle would burn steady multi-MB IO for maps
        # that almost never change
        sym_cache: Dict[int, tuple] = {}
        while not self._stop.wait(self.cfg.profile_interval_s):
            for pid in self.cfg.profile_pids:
                target = int(pid) or os.getpid()
                try:
                    with open(f"/proc/{target}/maps") as f:
                        maps_txt = f.read()
                    cached = sym_cache.get(target)
                    if cached is None or cached[0] != maps_txt:
                        cached = (maps_txt, Symbolizer(target))
                        sym_cache[target] = cached
                    prof = OnCpuProfiler(target,
                                         freq_hz=self.cfg.profile_freq_hz)
                    try:
                        folded = prof.run(self.cfg.profile_duration_s,
                                          symbolizer=cached[1])
                    finally:
                        prof.close()
                except OSError:
                    self.profile_errors += 1
                    sym_cache.pop(target, None)   # e.g. target exited
                    continue
                if not folded:
                    continue
                recs = folded_to_profile_records(
                    folded, app_service=self.cfg.host, pid=target,
                    vtap_id=self.vtap_id)
                self.profiles_sent += self.senders[
                    MessageType.PROFILE].send(recs)

    def _aggr_sets_match(self, a: dict, b: dict) -> bool:
        """True when two aggregated-column dicts share an identical key
        set; on divergence, records it (visible in counters + debug)."""
        if set(a) == set(b):
            return True
        self.aggr_schema_errors += 1
        self.last_aggr_schema_error = (
            f"only_a={sorted(set(a) - set(b))} "
            f"only_b={sorted(set(b) - set(a))}")
        return False

    def counters(self) -> dict:
        c = self.flow_map.counters()
        c["escaped"] = int(self.escaped)
        c["aggr_schema_errors"] = self.aggr_schema_errors
        c["profiles_sent"] = self.profiles_sent
        c["profile_errors"] = self.profile_errors
        c["upgrades_applied"] = self.upgrades_applied
        c["upgrade_errors"] = self.upgrade_errors
        c["ntp_offset_ns"] = self.ntp_offset_ns
        c["sessions_merged"] = self.sessions.merged
        c["l7_throttled"] = self.l7_throttled
        for mt, s in self.senders.items():
            c[f"sent_{mt.name.lower()}"] = s.sent_records
        return c

"""Tencent Cloud client: API 3.0 (TC3-HMAC-SHA256) from scratch.

Reference: server/controller/cloud/tencent/ — tencent.go wraps the
vendor SDK's CommonClient per (service, region) and pages every
Describe* with Offset/Limit until TotalCount is exhausted
(tencent.go:206-240); region.go/az.go/vpc.go/network.go/vm.go pull
DescribeRegions/DescribeZones/DescribeVpcs/DescribeSubnets/
DescribeInstances and normalize. This client implements the vendor
wire protocol directly (same discipline as cloud_aws.py /
cloud_aliyun.py — no vendored SDK), making it the THIRD auth scheme
the one platform interface carries:

- TC3-HMAC-SHA256 signed POST: canonical request over the JSON body
  (content-type;host signed headers, hex-sha256 payload), a dated
  credential scope, and the derived-key chain
  TC3{secret} -> date -> service -> "tc3_request" -> signature
  (vs AWS's SigV4 scope/derivation details and Aliyun's single-step
  HMAC-SHA1 nonce signature);
- service-global endpooints with the region in the X-TC-Region
  header (vs per-region hosts);
- Offset/Limit + Response.TotalCount pagination (vs nextToken and
  PageNumber).

Emits the same normalized region/az/vpc/subnet/vm rows as the other
vendors, so recorder/tagrecorder/platform-compiler are untouched.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import time
import urllib.parse
import urllib.request
from typing import List, Optional, Sequence, Tuple

from deepflow_tpu.controller.cloud import (ResourceBuilder,
                                           add_vm_public_addresses)
from deepflow_tpu.controller.model import Resource

CVM_VERSION = "2017-03-12"
VPC_VERSION = "2017-03-12"
CLB_VERSION = "2018-03-17"
PAGE_LIMIT = 100

# actions whose Offset/Limit are Integer-typed; every OTHER paged
# action takes them as STRINGS (tencent.go:47-55 pagesIntControl —
# the FULL reference set, mirrored exactly — + :209-213's strconv
# branch for the rest)
_INT_PAGED_ACTIONS = {
    "DescribeInstances", "DescribeNatGateways",
    "DescribeLoadBalancers", "DescribeNetworkInterfaces",
    "DescribeVpcPeerConnections",
    "DescribeNatGatewayDestinationIpPortTranslationNatRules",
}


def tc3_signature(secret_key: str, service: str, payload: bytes,
                  host: str, timestamp: int) -> Tuple[str, str]:
    """(authorization-ready signature hex, credential date) per the
    documented TC3 process: canonical request -> string-to-sign ->
    derived key chain."""
    date = time.strftime("%Y-%m-%d", time.gmtime(timestamp))
    ct = "application/json; charset=utf-8"
    canonical = ("POST\n/\n\n"
                 f"content-type:{ct}\nhost:{host}\n\n"
                 "content-type;host\n"
                 + hashlib.sha256(payload).hexdigest())
    scope = f"{date}/{service}/tc3_request"
    sts = ("TC3-HMAC-SHA256\n" + str(timestamp) + "\n" + scope + "\n"
           + hashlib.sha256(canonical.encode()).hexdigest())

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k_date = _hmac(("TC3" + secret_key).encode(), date)
    k_service = _hmac(k_date, service)
    k_signing = _hmac(k_service, "tc3_request")
    return hmac.new(k_signing, sts.encode(),
                    hashlib.sha256).hexdigest(), date


def tc3_authorization(secret_id: str, secret_key: str, service: str,
                      payload: bytes, host: str,
                      timestamp: int) -> str:
    sig, date = tc3_signature(secret_key, service, payload, host,
                              timestamp)
    return ("TC3-HMAC-SHA256 "
            f"Credential={secret_id}/{date}/{service}/tc3_request, "
            "SignedHeaders=content-type;host, "
            f"Signature={sig}")


class TencentPlatform:
    """Same duck type as the other vendor drivers (check_auth +
    get_cloud_data); endpoint_template carries {service} (hosts are
    service-global; the region rides the X-TC-Region header)."""

    def __init__(self, domain: str, secret_id: str, secret_key: str,
                 endpoint_template: str =
                 "https://{service}.tencentcloudapi.com",
                 regions: Optional[Sequence[str]] = None) -> None:
        self.domain = domain
        self.secret_id = secret_id
        self.secret_key = secret_key
        self.endpoint_template = endpoint_template
        self.include_regions = tuple(regions) if regions else ()

    # -- wire --------------------------------------------------------------
    def _call(self, service: str, version: str, action: str,
              region: str, body: Optional[dict] = None) -> dict:
        url = self.endpoint_template.format(service=service)
        host = urllib.parse.urlparse(url).netloc
        payload = json.dumps(body or {}).encode()
        ts = int(time.time())
        headers = {
            "Content-Type": "application/json; charset=utf-8",
            "Host": host,
            "X-TC-Action": action,
            "X-TC-Version": version,
            "X-TC-Timestamp": str(ts),
            "Authorization": tc3_authorization(
                self.secret_id, self.secret_key, service, payload,
                host, ts),
        }
        if region:
            headers["X-TC-Region"] = region
        req = urllib.request.Request(url, data=payload,
                                     headers=headers)
        with urllib.request.urlopen(req, timeout=30) as r:
            doc = json.load(r)
        resp = doc.get("Response", {})
        if "Error" in resp:
            raise RuntimeError(
                f"tencent {action}: {resp['Error'].get('Code')}")
        return resp

    def _paged(self, service: str, version: str, action: str,
               region: str, result_key: str) -> List[dict]:
        """Offset/Limit until TotalCount rows collected
        (tencent.go:206-240's loop; hard page cap as a lying-total
        guard)."""
        out: List[dict] = []
        offset = 0
        for _ in range(1000):
            if action in _INT_PAGED_ACTIONS:
                page: dict = {"Limit": PAGE_LIMIT, "Offset": offset}
            else:
                page = {"Limit": str(PAGE_LIMIT),
                        "Offset": str(offset)}
            resp = self._call(service, version, action, region, page)
            rows = resp.get(result_key, [])
            out.extend(rows)
            total = int(resp.get("TotalCount", len(out)))
            if not rows or len(out) >= total:
                break
            offset += len(rows)
        return out

    # -- api ---------------------------------------------------------------
    def check_auth(self) -> None:
        self._call("cvm", CVM_VERSION, "DescribeRegions", "")

    def _regions(self) -> List[str]:
        resp = self._call("cvm", CVM_VERSION, "DescribeRegions", "")
        names = [r.get("Region", "")
                 for r in resp.get("RegionSet", [])
                 if r.get("RegionState", "AVAILABLE") == "AVAILABLE"]
        names = [n for n in names if n]
        if self.include_regions:
            names = [n for n in names if n in self.include_regions]
        return names

    def get_cloud_data(self) -> List[Resource]:
        b = ResourceBuilder(self.domain)
        add = b.add

        for region in self._regions():
            region_id = add("region", region, region)
            zones = self._call("cvm", CVM_VERSION, "DescribeZones",
                               region)
            for z in zones.get("ZoneSet", []):
                zid = z.get("Zone", "")
                if zid:
                    add("az", zid, z.get("ZoneName") or zid,
                        region_id=region_id)
            for vpc in self._paged("vpc", VPC_VERSION, "DescribeVpcs",
                                   region, "VpcSet"):
                vid = vpc.get("VpcId", "")
                if not vid:
                    continue
                add("vpc", vid, vpc.get("VpcName") or vid,
                    region_id=region_id,
                    cidr=vpc.get("CidrBlock", ""))
            for sn in self._paged("vpc", VPC_VERSION,
                                  "DescribeSubnets", region,
                                  "SubnetSet"):
                sid = sn.get("SubnetId", "")
                if not sid:
                    continue
                epc = b.get("vpc", sn.get("VpcId", ""))
                add("subnet", sid, sn.get("SubnetName") or sid,
                    epc_id=epc, cidr=sn.get("CidrBlock", ""),
                    az=sn.get("Zone", ""))
            for inst in self._paged("cvm", CVM_VERSION,
                                    "DescribeInstances", region,
                                    "InstanceSet"):
                iid = inst.get("InstanceId", "")
                if not iid:
                    continue
                vpc_id = inst.get("VirtualPrivateCloud",
                                  {}).get("VpcId", "")
                epc = b.get("vpc", vpc_id)
                ips = inst.get("PrivateIpAddresses") or []
                vm_rid = add("vm", iid,
                             inst.get("InstanceName") or iid,
                             epc_id=epc, vpc_id=epc,
                             ip=ips[0] if ips else "",
                             az=inst.get("Placement",
                                         {}).get("Zone", ""))
                add_vm_public_addresses(
                    b, iid, vm_rid, epc,
                    [(p_, "") for p_ in
                     inst.get("PublicIpAddresses") or []])
            # NAT gateways + their floating ips (nat_gateway.go:35-80:
            # NatGatewaySet rows carry PublicIpAddressSet)
            for nat in self._paged("vpc", VPC_VERSION,
                                   "DescribeNatGateways", region,
                                   "NatGatewaySet"):
                nid = nat.get("NatGatewayId", "")
                if not nid:
                    continue
                epc = b.get("vpc", nat.get("VpcId", ""))
                nat_rid = add("nat_gateway", nid,
                              nat.get("NatGatewayName") or nid,
                              vpc_id=epc, region_id=region_id)
                for ip_e in nat.get("PublicIpAddressSet") or []:
                    ip = ip_e.get("PublicIpAddress", "")
                    if ip:
                        add("floating_ip", f"{nid}/{ip}", ip,
                            vpc_id=epc, ip=ip,
                            nat_gateway_id=nat_rid)
            # CLB load balancers + listeners (lb.go:42-108)
            for lb in self._paged("clb", CLB_VERSION,
                                  "DescribeLoadBalancers", region,
                                  "LoadBalancerSet"):
                lid = lb.get("LoadBalancerId", "")
                if not lid:
                    continue
                epc = b.get("vpc", lb.get("VpcId", ""))
                vips = lb.get("LoadBalancerVips") or []
                lb_rid = add("lb", lid,
                             lb.get("LoadBalancerName") or lid,
                             vpc_id=epc, region_id=region_id,
                             ip=vips[0] if vips else "",
                             lb_model=lb.get("LoadBalancerType", ""))
                lst = self._call("clb", CLB_VERSION,
                                 "DescribeListeners", region,
                                 {"LoadBalancerId": lid})
                for ln in lst.get("Listeners", []):
                    lnid = ln.get("ListenerId", "")
                    if lnid:
                        add("lb_listener", lnid,
                            ln.get("ListenerName") or lnid,
                            lb_id=lb_rid,
                            port=int(ln.get("Port", 0)),
                            protocol=ln.get("Protocol", ""))
        return b.rows()

import numpy as np

import jax
import jax.numpy as jnp

from deepflow_tpu.ops import entropy


def test_uniform_vs_concentrated(rng):
    n = 50_000
    uniform = rng.integers(0, 2**32, size=n, dtype=np.uint32)
    concentrated = np.full(n, 42, dtype=np.uint32)
    cols = jnp.asarray(np.stack([uniform, concentrated]))
    state = entropy.init(features=2, log2_buckets=12)
    state = jax.jit(entropy.update)(state, cols)
    ents = np.asarray(entropy.entropies(state))
    assert ents[0] > 0.9          # many distinct values -> near max entropy
    assert ents[1] < 0.01         # single value -> near zero


def test_entropy_matches_exact_histogram(rng):
    # Few distinct values, no hash collisions expected at 2^14 buckets.
    n = 20_000
    vals = rng.integers(0, 16, size=n, dtype=np.uint32)
    state = entropy.init(features=1, log2_buckets=14)
    state = entropy.update(state, jnp.asarray(vals[None, :]))
    got = float(entropy.entropies(state)[0])
    counts = np.bincount(vals)
    p = counts[counts > 0] / n
    want = -(p * np.log(p)).sum() / np.log(1 << 14)
    assert abs(got - want) < 1e-3


def test_weights_mask_merge_reset(rng):
    vals = np.array([1, 1, 2, 3], dtype=np.uint32)
    w = np.array([2, 2, 4, 100], dtype=np.int32)
    m = np.array([1, 1, 1, 0], dtype=bool)
    s = entropy.init(1, 10)
    s = entropy.update(s, jnp.asarray(vals[None, :]), jnp.asarray(w), jnp.asarray(m))
    assert int(np.asarray(s.hist).sum()) == 8
    merged = entropy.merge(s, s)
    assert int(np.asarray(merged.hist).sum()) == 16
    assert int(np.asarray(entropy.reset(s).hist).sum()) == 0

import numpy as np

import jax
import jax.numpy as jnp

from deepflow_tpu.ops import cms, topk


def test_topk_recall_vs_exact_groupby(rng):
    """North-star harness in miniature: recall vs exact GROUP BY (<1% loss
    target from BASELINE.md, measured at 100% here on a small universe)."""
    n, k = 200_000, 50
    keys = rng.zipf(1.2, size=n).clip(max=200_000).astype(np.uint32)
    sketch = cms.init(depth=4, log2_width=16)
    ring = topk.init(ring_size=512)

    step = jax.jit(lambda s, r, b: (
        lambda s2: (s2, topk.offer(r, b, s2)))(cms.update_conservative(s, b)))
    for i in range(0, n, 20_000):
        batch = jnp.asarray(keys[i:i + 20_000])
        sketch, ring = step(sketch, ring, batch)

    got_keys, got_counts = topk.result(ring, k)
    got = set(np.asarray(got_keys).tolist())
    uniq, counts = np.unique(keys, return_counts=True)
    want = set(uniq[np.argsort(counts)[::-1][:k]].tolist())
    recall = len(got & want) / k
    assert recall >= 0.99, recall
    # counts of returned keys are CMS overestimates of truth
    truth = dict(zip(uniq.tolist(), counts.tolist()))
    for key, est in zip(np.asarray(got_keys).tolist(),
                        np.asarray(got_counts).tolist()):
        if key in truth:
            assert est >= truth[key]


def test_offer_dedups_standing_candidates(rng):
    sketch = cms.init(depth=4, log2_width=12)
    ring = topk.init(ring_size=8)
    batch = jnp.asarray(np.array([5, 5, 5, 6], np.uint32))
    sketch = cms.update(sketch, batch)
    ring = topk.offer(ring, batch, sketch)
    ring = topk.offer(ring, batch, sketch)   # same keys again
    keys = np.asarray(ring.keys)
    real = keys[keys != 0xFFFFFFFF]
    assert len(np.unique(real)) == len(real)  # no duplicate candidates


def test_mask_excludes_padding():
    sketch = cms.init(depth=4, log2_width=12)
    ring = topk.init(ring_size=8)
    batch = jnp.asarray(np.array([1, 2, 3, 999], np.uint32))
    mask = jnp.asarray(np.array([1, 1, 1, 0], bool))
    sketch = cms.update(sketch, batch, mask=mask)
    ring = topk.offer(ring, batch, sketch, mask=mask)
    keys, counts = topk.result(ring, 8)
    keys = np.asarray(keys)[np.asarray(counts) > 0]
    assert 999 not in keys.tolist()


def test_sampled_admission_recall_production_path(rng):
    """Recall harness for the production-style path: plain MXU-histogram CMS
    + 1/16 stride-sampled, phase-rotated ring admission (the flow_suite
    mechanism, at test-scale width). Admission is sampled but scores are
    full-sketch and standing candidates are rescored each batch, so hot keys
    rank correctly once admitted."""
    n, k, batch = 400_000, 100, 40_000
    keys = rng.zipf(1.2, size=n).clip(max=200_000).astype(np.uint32)
    sketch = cms.init(depth=4, log2_width=16)
    ring = topk.init(ring_size=1024)

    step = jax.jit(lambda s, r, b, ph: (
        lambda s2: (s2, topk.offer(r, b, s2, sample_log2=4, phase=ph))
    )(cms.update(s, b)))
    for j, i in enumerate(range(0, n, batch)):
        sketch, ring = step(sketch, ring, jnp.asarray(keys[i:i + batch]),
                            jnp.int32(j))

    got_keys, _ = topk.result(ring, k)
    got = set(np.asarray(got_keys).tolist())
    uniq, counts = np.unique(keys, return_counts=True)
    want = set(uniq[np.argsort(counts)[::-1][:k]].tolist())
    recall = len(got & want) / k
    assert recall >= 0.98, recall


def test_staged_update_equals_fused():
    """flow_suite.make_staged_update (the transfer-safe four-program
    pipeline the tpu_sketch exporter uses on tunneled backends) produces
    bit-identical state to the fused update."""
    import jax
    import jax.numpy as jnp

    from deepflow_tpu.batch.schema import SKETCH_L4_SCHEMA
    from deepflow_tpu.models import flow_suite

    cfg = flow_suite.FlowSuiteConfig(cms_log2_width=12, ring_size=256,
                                     hll_groups=32, hll_precision=6,
                                     entropy_log2_buckets=6)
    rng = np.random.default_rng(11)
    staged = flow_suite.make_staged_update(cfg)
    fused = jax.jit(lambda s, c, m: flow_suite.update(s, c, m, cfg))
    s_f, s_s = flow_suite.init(cfg), flow_suite.init(cfg)
    n = 4096
    for i in range(4):
        cols = {nm: jnp.asarray(rng.integers(0, 1 << 16, n).astype(d))
                for nm, d in SKETCH_L4_SCHEMA.columns}
        mask = jnp.asarray(rng.random(n) < 0.9)
        s_f = fused(s_f, cols, mask)
        s_s = staged(s_s, cols, mask)
    for a, b in zip(jax.tree.leaves(s_f), jax.tree.leaves(s_s)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

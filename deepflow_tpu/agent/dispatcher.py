"""Dispatcher: capture-mode shaping + policy enforcement for one batch.

Reference: agent/src/dispatcher/ — three dispatcher flavors share a base:
local_mode (capturing the host's own interfaces: direction and l2_end
derive from the host's MAC set), mirror_mode (a mirror port carries many
VMs' traffic; per-VM MAC tables orient each packet), analyzer_mode (an
aggregated TAP feed: outer VLAN is the tap id and is stripped, tunnels
always decapped). The columnar re-design keeps one vectorized decode and
expresses each mode as column post-processing over the whole batch —
there is no per-packet mode branch.

The dispatcher also runs the policy stage (labeler + NPB/PCAP/DROP
enforcement) so `dispatch()` hands the flow map a batch that is already
oriented, labeled, and filtered — the reference's
dispatcher->labeler->flow_generator order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Set

import numpy as np

from deepflow_tpu.agent.packet import decode_packets
from deepflow_tpu.agent.policy import PolicyEnforcer, PolicyLabeler

MODE_LOCAL = "local"
MODE_MIRROR = "mirror"
MODE_ANALYZER = "analyzer"

# tap_side values (reference: TapSide — client/server observation point)
SIDE_CLIENT = 0
SIDE_SERVER = 1


@dataclass
class DispatcherConfig:
    mode: str = MODE_LOCAL
    # local mode: this host's MACs; mirror mode: all monitored VM MACs
    local_macs: Set[int] = field(default_factory=set)
    decap_vxlan: bool = True


class Dispatcher:
    def __init__(self, cfg: DispatcherConfig,
                 policy: Optional[PolicyLabeler] = None,
                 enforcer: Optional[PolicyEnforcer] = None) -> None:
        if cfg.mode not in (MODE_LOCAL, MODE_MIRROR, MODE_ANALYZER):
            raise ValueError(f"unknown dispatcher mode {cfg.mode!r}")
        self.cfg = cfg
        self.policy = policy
        self.enforcer = enforcer
        self.batches = 0
        self.kept = 0

    def dispatch(self, frames: Sequence[bytes],
                 timestamps_ns: Optional[np.ndarray] = None
                 ) -> Dict[str, np.ndarray]:
        """frames -> decoded, mode-stamped, policy-filtered MetaPacket
        columns (the flow map's input contract)."""
        self.batches += 1
        # analyzer mode always decapsulates: the TAP aggregates overlay
        # traffic from many hypervisors
        decap = self.cfg.decap_vxlan or self.cfg.mode == MODE_ANALYZER
        pkt = decode_packets(list(frames), timestamps_ns, decap_vxlan=decap)
        n = len(pkt["valid"])

        if self.cfg.mode in (MODE_LOCAL, MODE_MIRROR) and \
                self.cfg.local_macs:
            # direction from the MAC table: a packet whose src MAC is
            # ours/monitored was SENT here (client side observation);
            # dst MAC ours = received (server side). l2_end marks the
            # side that terminates on a known MAC.
            macs = np.asarray(sorted(self.cfg.local_macs), np.uint64)
            src_local = np.isin(pkt["mac_src"], macs)
            dst_local = np.isin(pkt["mac_dst"], macs)
            pkt["tap_side"] = np.where(src_local, SIDE_CLIENT,
                                       SIDE_SERVER).astype(np.uint32)
            pkt["l2_end_0"] = src_local
            pkt["l2_end_1"] = dst_local
            if self.cfg.mode == MODE_MIRROR:
                # mirror feed carries unrelated traffic too: keep only
                # packets touching a monitored MAC
                pkt["valid"] &= src_local | dst_local
        elif self.cfg.mode == MODE_ANALYZER:
            # outer VLAN is the tap id on aggregated TAPs
            pkt["tap_type"] = pkt["vlan_id"].astype(np.uint32)
            pkt["tap_side"] = np.zeros(n, np.uint32)
        else:
            pkt["tap_side"] = np.zeros(n, np.uint32)

        if self.policy is not None:
            rule_ids = self.policy.lookup(pkt)
            # actions must never fire on packets already rejected (non-IP
            # frames decode garbage ip columns that can spuriously match
            # prefix rules; mirror mode has just filtered unmonitored MACs)
            rule_ids[~pkt["valid"]] = 0
            pkt["policy_id"] = rule_ids
            if self.enforcer is not None:
                keep = self.enforcer.apply(frames, pkt["timestamp_ns"],
                                           rule_ids)
                pkt["valid"] &= keep
        self.kept += int(pkt["valid"].sum())
        return pkt

    def counters(self) -> dict:
        c = {"mode": self.cfg.mode, "batches": self.batches,
             "kept": self.kept}
        if self.enforcer is not None:
            c.update(self.enforcer.counters())
        return c

"""Persistent string<->u32 dictionaries: the SmartEncoding reverse map.

Strings (metric names, label sets, endpoints, folded stacks) become u32
hashes before entering the columnar/device domain; this dictionary makes
them recoverable at query time. It plays the role of the reference's
flow_tag database (server/ingester/flow_tag/flow_tag.go: per-batch dedup'd
tag name/value writes that the querier joins for display) and of the
tagrecorder dimension tables — but keyed by content hash, so encoding
needs no controller round-trip.

Durability: append-only JSONL journal, replayed on open; entries are
content-addressed so replay order and duplicate appends are harmless.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Iterable, List, Optional

import numpy as np


def fnv1a32(data: bytes) -> int:
    h = 0x811C9DC5
    for b in data:
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


def fold_ipv6(addr16: bytes) -> int:
    """THE system-wide IPv6 -> u32 fold: FNV-1a confined to the class-E
    range (240.0.0.0/4, reserved and unrouted), so a folded v6 address
    can never collide with a real v4 interface/CIDR in platform joins or
    policy prefixes while keeping 28 bits of key entropy. Capture
    (agent/packet.py), platform compilation, and enrichment all use this
    one function."""
    return fnv1a32(addr16) | 0xF0000000


class TagDict:
    """One named dictionary (e.g. 'metric_name', 'app_stack')."""

    def __init__(self, path: Optional[str] = None) -> None:
        self.path = path
        self._fwd: Dict[str, int] = {}
        self._rev: Dict[int, str] = {}
        self._lock = threading.Lock()
        self._fh = None
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            if os.path.exists(path):
                with open(path) as f:
                    for line in f:
                        try:
                            e = json.loads(line)
                        except ValueError:
                            continue  # torn tail write from a crash
                        self._fwd[e["s"]] = e["h"]
                        self._rev[e["h"]] = e["s"]
            self._fh = open(path, "a")

    def encode_one(self, s: str) -> int:
        with self._lock:
            h = self._fwd.get(s)
            if h is not None:
                return h
            h = fnv1a32(s.encode())
            # linear-probe past collisions so decode stays unambiguous
            while h in self._rev and self._rev[h] != s:
                h = (h + 1) & 0xFFFFFFFF
            self._fwd[s] = h
            self._rev[h] = s
            if self._fh is not None:
                self._fh.write(json.dumps({"h": h, "s": s}) + "\n")
            return h

    def encode(self, strings: Iterable[str]) -> np.ndarray:
        return np.fromiter((self.encode_one(s) for s in strings),
                           dtype=np.uint32)

    def lookup(self, s: str) -> Optional[int]:
        """Read-only encode: the query path must not grow the dictionary
        (unbounded journal growth from probing WHERE literals)."""
        with self._lock:
            return self._fwd.get(s)

    def values(self) -> List[str]:
        """All known strings (one locked copy) — series/label discovery
        (the Prometheus /api/v1/labels surface)."""
        with self._lock:
            return list(self._fwd)

    def decode(self, h: int) -> Optional[str]:
        return self._rev.get(int(h))

    def decode_many(self, hs: Iterable[int]) -> List[Optional[str]]:
        return [self._rev.get(int(h)) for h in hs]

    def flush(self) -> None:
        if self._fh is not None:
            self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __len__(self) -> int:
        return len(self._fwd)


class TagDictRegistry:
    """All dictionaries under <root>/flow_tag/<name>.jsonl."""

    def __init__(self, root: Optional[str]) -> None:
        self.root = root
        self._dicts: Dict[str, TagDict] = {}
        self._lock = threading.Lock()

    def get(self, name: str) -> TagDict:
        with self._lock:
            d = self._dicts.get(name)
            if d is None:
                path = None if self.root is None else \
                    os.path.join(self.root, "flow_tag", f"{name}.jsonl")
                d = self._dicts[name] = TagDict(path)
            return d

    def flush(self) -> None:
        with self._lock:
            dicts = list(self._dicts.values())
        for d in dicts:
            d.flush()

    def close(self) -> None:
        with self._lock:
            dicts = list(self._dicts.values())
        for d in dicts:
            d.close()

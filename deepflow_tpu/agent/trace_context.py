"""Trace-context extraction from instrumented-app request headers.

Reference: agent/src/flow_generator/protocol_logs/http.rs:1120-1240 —
`decode_id` dispatches on TraceType (traceparent / SkyWalking sw3/sw6/
sw8 / X-B3 / uber-trace-id / customized keys) and stamps trace_id /
span_id into the l7 log. These ids are what link eBPF/packet spans to
OTel spans in one distributed trace; without them tempo assembly rests
solely on syscall ids.

All decoders are written from the public wire formats:
- W3C trace context (https://www.w3.org/TR/trace-context/):
  `traceparent: 00-<32hex trace-id>-<16hex parent-id>-<flags>`
- SkyWalking sw6/sw8: `-`-separated, base64 segments:
  `<sample>-<trace-id b64>-<segment-id b64>-<span-id>-...`
- SkyWalking sw3: `|`-separated:
  `SEGMENTID|SPANID|100|100|...|TRACEID|SAMPLING` (trace at index 7,
  span shown as SEGMENTID-SPANID)
- Zipkin B3 single/multi: `X-B3-TraceId` / `X-B3-SpanId` raw values
- Jaeger: `uber-trace-id: TRACEID:SPANID:PARENTSPAN:FLAGS`
- anything else (customized key): the raw header value

The key *list* is pushed agent config (the reference's
`http_log_trace_id` / `http_log_span_id` proxy config fields,
trident.proto Config) and hot-swappable.
"""

from __future__ import annotations

import base64
import binascii
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

TRACE_ID = 0
SPAN_ID = 1


def _b64(seg: str) -> str:
    try:
        return base64.b64decode(seg + "=" * (-len(seg) % 4)).decode(
            "utf-8", "replace")
    except (binascii.Error, ValueError):
        return seg


def _decode_traceparent(value: str, id_type: int) -> Optional[str]:
    segs = value.strip().split("-")
    if id_type == TRACE_ID and len(segs) > 1:
        return segs[1]
    if id_type == SPAN_ID and len(segs) > 2:
        return segs[2]
    return None


def _decode_sw8(value: str, id_type: int) -> Optional[str]:
    segs = value.strip().split("-")
    if id_type == TRACE_ID and len(segs) > 2:
        return _b64(segs[1])
    if id_type == SPAN_ID and len(segs) > 4:
        return f"{_b64(segs[2])}-{segs[3]}"
    return None


def _decode_sw3(value: str, id_type: int) -> Optional[str]:
    segs = value.strip().split("|")
    if len(segs) > 7:
        if id_type == TRACE_ID:
            return segs[7]
        if id_type == SPAN_ID:
            return f"{segs[0]}-{segs[1]}"
    return None


def _decode_uber(value: str, id_type: int) -> Optional[str]:
    segs = value.strip().split(":")
    if id_type == TRACE_ID and len(segs) > 0 and segs[0]:
        return segs[0]
    if id_type == SPAN_ID and len(segs) > 2:
        return segs[2]
    return None


def _decode_raw(value: str, id_type: int) -> Optional[str]:
    return value.strip() or None


# header key (lowercase) -> decoder; anything not listed decodes raw
# (the reference's TraceType::Customize / XB3 behavior)
_DECODERS = {
    "traceparent": _decode_traceparent,
    "sw8": _decode_sw8,
    "sw6": _decode_sw8,          # same layout as sw8 for ids
    "sw3": _decode_sw3,
    "uber-trace-id": _decode_uber,
}


def decode_id(key: str, value: str, id_type: int) -> Optional[str]:
    """Extract trace or span id from one header, by the key's format."""
    return _DECODERS.get(key.lower(), _decode_raw)(value, id_type)


@dataclass
class HttpLogConfig:
    """Pushed, hot-swappable header-extraction config (the reference's
    l7-protocol-advanced-features / http_log_* proxy fields). Key lists
    are ordered: first present header wins."""
    trace_types: Tuple[str, ...] = ("traceparent", "sw8")
    span_types: Tuple[str, ...] = ("traceparent", "sw8")
    x_request_id: Tuple[str, ...] = ("x-request-id",)
    proxy_client: Tuple[str, ...] = ("x-forwarded-for", "x-real-ip")


_CONFIG = HttpLogConfig()
_LOCK = threading.Lock()


def _norm(v) -> Tuple[str, ...]:
    """Key list from pushed config: a list/tuple, or the reference's
    comma-joined string form."""
    if isinstance(v, str):
        v = v.split(",")
    return tuple(s.strip().lower() for s in v if s.strip())


def configure(trace_types=None, span_types=None,
              x_request_id=None, proxy_client=None) -> None:
    """Swap the process-global extraction config (parsers are a
    process-global registry; the agent applies pushed config here).
    Every field accepts an iterable of keys or a comma-joined string."""
    global _CONFIG
    with _LOCK:
        cur = _CONFIG
        _CONFIG = HttpLogConfig(
            trace_types=_norm(trace_types)
            if trace_types is not None else cur.trace_types,
            span_types=_norm(span_types)
            if span_types is not None else cur.span_types,
            x_request_id=_norm(x_request_id)
            if x_request_id is not None else cur.x_request_id,
            proxy_client=_norm(proxy_client)
            if proxy_client is not None else cur.proxy_client)


def config() -> HttpLogConfig:
    return _CONFIG


def extract(headers: Dict[str, str]) -> Dict[str, str]:
    """headers (lowercase names) -> {trace_id, span_id, x_request_id,
    client_ip}; empty strings where absent. Shared by HTTP/1 and
    HTTP/2+gRPC so the two stamp identical columns."""
    cfg = _CONFIG
    out = {"trace_id": "", "span_id": "", "x_request_id": "",
           "client_ip": ""}
    for key in cfg.trace_types:
        v = headers.get(key)
        if v:
            got = decode_id(key, v, TRACE_ID)
            if got:
                out["trace_id"] = got
                break
    for key in cfg.span_types:
        v = headers.get(key)
        if v:
            got = decode_id(key, v, SPAN_ID)
            if got:
                out["span_id"] = got
                break
    for key in cfg.x_request_id:
        v = headers.get(key)
        if v:
            out["x_request_id"] = v.strip()
            break
    for key in cfg.proxy_client:
        v = headers.get(key)
        if v:
            # first address of a comma-joined proxy chain = the client
            out["client_ip"] = v.split(",")[0].strip()
            break
    return out

"""Device-side heavy-hitter top-K over a CMS-estimated candidate ring.

Exact top-K needs the full key universe (the reference gets it for free from
ClickHouse GROUP BY at query time). On device we instead keep a fixed-size
candidate ring: every batch, the batch's (deduped) keys are scored against
the Count-Min sketch, merged with the standing candidates, and compacted back
to ring size with `lax.top_k` — all static shapes, fully jittable.

Recall loss vs exact comes from (a) CMS overestimation (mitigated by
conservative update) and (b) ring evictions (mitigated by ring_size >> K).
tests/test_topk.py scores recall against an exact numpy GROUP BY, the
in-repo stand-in for the reference exactness harness (SURVEY.md §4).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from deepflow_tpu.ops import cms

# np scalar, NOT jnp: a jnp.uint32() here is a device array committed to
# the default backend at import, and any program that embeds such a
# device-resident constant trips the tunnel's persistent h2d slow mode
# when COMPILED (bisected 2026-07-30: `jit(lambda b: SENTINEL * b)` alone
# degrades h2d 569 -> 94 MB/s with the jnp form; identical code with an
# inline/np constant stays >1.2 GB/s). Earlier "compare-free" theories
# were chasing a confounder — every tripping program referenced this
# constant, every clean one didn't.
SENTINEL = np.uint32(0xFFFFFFFF)


class TopKState(NamedTuple):
    keys: jnp.ndarray    # [ring] uint32, SENTINEL = empty
    counts: jnp.ndarray  # [ring] int32 CMS estimates


def init(ring_size: int) -> TopKState:
    return TopKState(
        keys=jnp.full((ring_size,), SENTINEL, dtype=jnp.uint32),
        counts=jnp.full((ring_size,), -1, dtype=jnp.int32),
    )


def _nonzero_u32(x: jnp.ndarray) -> jnp.ndarray:
    """[n] uint32 1 where x != 0, else 0, via (x | -x) >> 31 — pure
    arithmetic, no compare/select/minimum op at all."""
    return (x | (jnp.uint32(0) - x)) >> jnp.uint32(31)


def _not_sentinel(keys: jnp.ndarray) -> jnp.ndarray:
    """[n] int32 1 where key != SENTINEL, else 0 — WITHOUT a compare op.

    Load-bearing on the remote-TPU runtime: merely COMPILING a program
    where a compare-class elementwise op (==, where, even jnp.minimum)
    sits between data-movement ops (gather/sort/roll/strided-slice)
    trips a persistent slow mode in the tunnel's transfer layer — every
    later host->device copy runs ~15-30x slow for the process (verified
    by bisection; compile alone suffices; movement-only and
    compare-on-inputs-only programs are fine). The ring path is exactly
    such a program, so every predicate on moved data here is pure
    arithmetic: SENTINEL is u32 max, so SENTINEL - k is 0 iff k is the
    sentinel, and _nonzero_u32 turns that into a 0/1 lane."""
    return _nonzero_u32(SENTINEL - keys).astype(jnp.int32)


def _dedup_sorted(k: jnp.ndarray, c: jnp.ndarray):
    """Dedup ALREADY-SORTED (key, count) pairs: within an equal-key run
    counts sort ascending, so the run's LAST lane already holds the max —
    no segment-max scatter, no cumsum. Run boundaries are detected
    arithmetically (sorted ascending => k[i+1] - k[i] is 0 iff equal),
    never with a compare: see _not_sentinel."""
    diff = _nonzero_u32(k[1:] - k[:-1])
    last_u = jnp.concatenate([diff, jnp.ones((1,), jnp.uint32)])
    last_i = last_u.astype(jnp.int32) * _not_sentinel(k)
    # k where last-of-run, SENTINEL elsewhere; c where kept, -1 elsewhere
    k = k * last_u + SENTINEL * (jnp.uint32(1) - last_u)
    c = last_i * (c + 1) - 1
    return k, c


def _dedup_keep_max(keys: jnp.ndarray, counts: jnp.ndarray):
    """Sort by key; on equal runs keep the max count on one lane, -1 on
    rest (one two-key sort + arithmetic boundary detect)."""
    k, c = jax.lax.sort((keys, counts), num_keys=2)
    return _dedup_sorted(k, c)


def candidate_keys(state_keys: jnp.ndarray, batch_keys: jnp.ndarray,
                   mask: jnp.ndarray | None = None, sample_log2: int = 0,
                   phase: jnp.ndarray | int = 0) -> jnp.ndarray:
    """Standing ring keys + (sampled) batch keys — the movement half of
    admission, shared by offer() and the staged pipeline.

    The mask is applied arithmetically (bool -> u32 - 1 = all-ones where
    dead, OR'd in = SENTINEL), not with jnp.where: a select whose output
    feeds roll+strided-slice in the same program is by itself enough to
    trip the tunnel h2d slow mode (bisected 2026-07-30: where->roll->
    slice->concat degrades 539->102 MB/s; the same chain with the OR mask
    or with movement/select alone stays >1.2 GB/s)."""
    bk = batch_keys.astype(jnp.uint32)
    if mask is not None:
        bk = bk | (mask.astype(jnp.uint32) - jnp.uint32(1))
    if sample_log2 > 0:
        bk = jnp.roll(bk, -(jnp.asarray(phase) % (1 << sample_log2)))
        bk = bk[:: 1 << sample_log2]
    return jnp.concatenate([state_keys, bk])


def blend_counts(all_keys: jnp.ndarray, est: jnp.ndarray) -> jnp.ndarray:
    """est where the key is live, -1 at sentinels — compare-free."""
    live = _not_sentinel(all_keys)
    return live * (est.astype(jnp.int32) + 1) - 1


def sort_pairs(all_keys: jnp.ndarray, all_counts: jnp.ndarray):
    """Two-key lexicographic sort (movement only, no compares)."""
    return jax.lax.sort((all_keys, all_counts), num_keys=2)


def select_ring(k: jnp.ndarray, c: jnp.ndarray,
                ring_size: int) -> TopKState:
    """Dedup (last-of-run on the sorted pairs) + top_k compaction.
    Compares here touch only this function's inputs — the staged pipeline
    relies on that (see flow_suite.make_staged_update)."""
    k2, c2 = _dedup_sorted(k, c)
    top_c, top_i = jax.lax.top_k(c2, ring_size)
    return TopKState(keys=k2[top_i], counts=top_c)


def offer(state: TopKState, batch_keys: jnp.ndarray, sketch: cms.CMSState,
          mask: jnp.ndarray | None = None, sample_log2: int = 0,
          phase: jnp.ndarray | int = 0) -> TopKState:
    """Merge a batch of keys (scored via `sketch`) into the candidate ring.

    `sample_log2 > 0` admits only a 1/2^s stride-sample of lanes. Admission
    is sampled; *scores* always come from the full Count-Min sketch, and
    standing candidates are rescored every batch, so a hot key only has to be
    sampled once per window to be ranked with its true (full-stream) estimate.
    This cuts the per-batch gather + sort from O(n) to O(n/2^s), bounding
    per-batch work the way the reference's throttler bounds per-second writes
    (server/ingester/flow_log/throttler/throttling_queue.go:98).

    `phase` rotates which residue class (mod 2^s) gets sampled — pass a
    per-batch counter so lane positions correlated with the stride (e.g.
    round-robin packers upstream) still get admitted over a window.
    """
    # Standing candidates get re-scored too (their CMS estimates only
    # grow), in the SAME query as the batch keys: one concat + one gather
    # instead of a separate ring-sized pass.
    all_keys = candidate_keys(state.keys, batch_keys, mask, sample_log2,
                              phase)
    est = cms.query(sketch, all_keys)
    k, c = sort_pairs(all_keys, blend_counts(all_keys, est))
    return select_ring(k, c, state.keys.shape[0])


def result(state: TopKState, k: int):
    """(keys, counts) of the current top-k, count-descending."""
    top_c, top_i = jax.lax.top_k(state.counts, k)
    return state.keys[top_i], top_c


def reset(state: TopKState) -> TopKState:
    return init(state.keys.shape[0])

"""External-APM tracing adapter: pull third-party traces into the
DeepFlow span model.

Reference: server/querier/app/tracing-adapter/ — a TraceAdapter
registry (`service/base.go Register`, skywalking + packet services),
an ExSpan normalization model (`model/tracing.go`), per-APM endpoint
config (`config ExternalAPM {name, addr, timeout, extra_config}`), and
one route (`router/router.go GET /api/v1/adapter/tracing?traceid=`)
that fans the trace id out to every configured APM and merges the
normalized spans. The flagship adapter speaks the SkyWalking GraphQL
query protocol (`service/skywalking.go query_trace`, v8+).

Here the same shape in Python: `TraceAdapter.get_trace`, an
`ADAPTERS` registry, `ExternalAPM` config rows (yaml `external_apm:`
under `querier:`), and the `SkyWalkingAdapter` speaking the public
skywalking-query-protocol over urllib. Spans normalize into the
dataclass below, which serializes to the reference's ExSpan JSON so
existing consumers of that API shape can switch backends.
"""

from __future__ import annotations

import base64
import json
import logging
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from deepflow_tpu.store.dict_store import fnv1a32

log = logging.getLogger(__name__)

# span_kind (model/tracing.go ExSpan.SpanKind, OTel numbering)
KIND_INTERNAL, KIND_SERVER, KIND_CLIENT = 1, 2, 3
_KIND_TAP_SIDE = {KIND_SERVER: "s-app", KIND_CLIENT: "c-app",
                  KIND_INTERNAL: "app"}

# SkyWalking span `type` values (skywalking-query-protocol trace.graphqls)
_SW_TYPE_KIND = {"Entry": KIND_SERVER, "Exit": KIND_CLIENT,
                 "Local": KIND_INTERNAL}

# SkyWalking `layer` -> deepflow l7_protocol family label. The adapter
# only knows the layer, not the concrete protocol, so these map to the
# display string; the numeric id stays 0 (unknown) like the reference
# does for non-HTTP layers.
_SW_LAYER_PROTO = {"Http": (20, "HTTP"), "Database": (0, "SQL"),
                   "Cache": (0, "Cache"), "MQ": (0, "MQ"),
                   "RPCFramework": (0, "RPC"), "Unknown": (0, "")}

_SW_QUERY = """query queryTrace($traceId: ID!) {
  trace: queryTrace(traceId: $traceId) {
    spans {
      traceId segmentId spanId parentSpanId
      refs { traceId parentSegmentId parentSpanId type }
      serviceCode serviceInstanceName startTime endTime endpointName
      type peer component isError layer
      tags { key value }
    }
  }
}"""


@dataclass
class ExSpan:
    """Normalized external span (reference model/tracing.go ExSpan)."""

    name: str = ""
    _id: int = 0
    start_time_us: int = 0
    end_time_us: int = 0
    tap_side: str = "app"
    l7_protocol: int = 0
    l7_protocol_str: str = ""
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: str = ""
    span_kind: int = KIND_INTERNAL
    endpoint: str = ""
    request_type: str = ""
    request_resource: str = ""
    response_status: int = 0
    app_service: str = ""
    app_instance: str = ""
    service_uname: str = ""
    attribute: Dict[str, str] = field(default_factory=dict)

    def to_json(self) -> dict:
        return asdict(self)


@dataclass
class ExternalAPM:
    """One configured APM endpoint (reference config.ExternalAPM)."""

    name: str
    addr: str                       # e.g. http://host:port
    timeout_s: float = 60.0
    extra_config: Dict[str, str] = field(default_factory=dict)


class SkyWalkingAdapter:
    """SkyWalking v8+ query-protocol adapter (reference
    service/skywalking.go): POST the queryTrace GraphQL document to
    {addr}/graphql, normalize segments/spans/refs into ExSpans."""

    def get_trace(self, trace_id: str, apm: ExternalAPM) -> List[ExSpan]:
        body = json.dumps({"query": _SW_QUERY,
                           "variables": {"traceId": trace_id}}).encode()
        req = urllib.request.Request(
            apm.addr.rstrip("/") + "/graphql", data=body,
            headers={"Content-Type": "application/json"})
        auth = apm.extra_config.get("auth")
        if auth:
            req.add_header("Authorization", "Basic "
                           + base64.b64encode(auth.encode()).decode())
        with urllib.request.urlopen(req, timeout=apm.timeout_s) as resp:
            doc = json.load(resp)
        trace = (doc.get("data") or {}).get("trace") or {}
        return [self._to_exspan(s, trace_id)
                for s in trace.get("spans") or []]

    @staticmethod
    def _span_uid(segment_id: str, span_id) -> str:
        # spans are unique per (segment, spanId); refs address parents
        # the same way, so the composite is the cross-segment link key
        return f"{segment_id}-{span_id}"

    def _to_exspan(self, s: dict, trace_id: str) -> ExSpan:
        tags = {t.get("key", ""): t.get("value") or ""
                for t in s.get("tags") or []}
        kind = _SW_TYPE_KIND.get(s.get("type", ""), KIND_INTERNAL)
        proto_id, proto_str = _SW_LAYER_PROTO.get(s.get("layer") or
                                                  "Unknown", (0, ""))
        span_uid = self._span_uid(s.get("segmentId", ""),
                                  s.get("spanId", 0))
        # parent: same-segment spanId unless -1, else the cross-segment
        # ref (CROSS_PROCESS/CROSS_THREAD both carry parentSegmentId)
        parent = ""
        if int(s.get("parentSpanId", -1)) >= 0:
            parent = self._span_uid(s.get("segmentId", ""),
                                    s["parentSpanId"])
        else:
            refs = s.get("refs") or []
            if refs:
                parent = self._span_uid(refs[0].get("parentSegmentId", ""),
                                        refs[0].get("parentSpanId", 0))
        status = 0
        for k in ("http.status_code", "http.status.code"):
            v = tags.get(k, "")
            if v.isascii() and v.isdigit():
                status = int(v)
                break
        if not status and s.get("isError"):
            status = 500
        endpoint = s.get("endpointName") or ""
        uid = f"{trace_id}/{span_uid}".encode()
        return ExSpan(
            name=endpoint,
            # deterministic 64-bit id (hash() is seed-randomized)
            _id=(fnv1a32(uid) << 32) | fnv1a32(uid[::-1]),
            start_time_us=int(s.get("startTime", 0)) * 1000,
            end_time_us=int(s.get("endTime", 0)) * 1000,
            tap_side=_KIND_TAP_SIDE[kind],
            l7_protocol=proto_id,
            l7_protocol_str=proto_str,
            trace_id=trace_id,
            span_id=span_uid,
            parent_span_id=parent,
            span_kind=kind,
            endpoint=endpoint,
            request_type=tags.get("http.method", ""),
            request_resource=tags.get("url") or tags.get("db.statement")
            or tags.get("cache.key") or endpoint,
            response_status=status,
            app_service=s.get("serviceCode") or "",
            app_instance=s.get("serviceInstanceName") or "",
            service_uname=s.get("serviceCode") or "",
            attribute={k: v for k, v in tags.items()},
        )


# adapter registry (reference service/base.go Register); custom
# adapters register here by protocol name
ADAPTERS: Dict[str, object] = {"skywalking": SkyWalkingAdapter()}


def register_adapter(name: str, adapter) -> None:
    if not hasattr(adapter, "get_trace"):
        raise TypeError("adapter lacks .get_trace")
    ADAPTERS[name] = adapter


class TracingAdapterService:
    """Fan a trace id out to every configured APM and merge the
    normalized spans (reference tracing_adapter TraceHandler)."""

    def __init__(self, apms: Optional[List[ExternalAPM]] = None) -> None:
        self.apms = apms or []

    @classmethod
    def from_config(cls, rows: List[dict]) -> "TracingAdapterService":
        """yaml rows: [{name, addr, timeout_s?, extra_config?}]."""
        apms = []
        for r in rows:
            if r.get("name") not in ADAPTERS:
                log.warning("external_apm %r: no adapter registered",
                            r.get("name"))
                continue
            if not r.get("addr"):
                # a malformed optional-feature row must not prevent the
                # querier from starting
                log.warning("external_apm %r: addr missing; skipped",
                            r.get("name"))
                continue
            apms.append(ExternalAPM(
                name=r["name"], addr=r["addr"],
                timeout_s=float(r.get("timeout_s", 60.0)),
                extra_config=dict(r.get("extra_config") or {})))
        return cls(apms)

    def get_trace(self, trace_id: str) -> List[ExSpan]:
        def one(apm: ExternalAPM) -> List[ExSpan]:
            adapter = ADAPTERS.get(apm.name)
            if adapter is None:
                return []
            try:
                return adapter.get_trace(trace_id, apm)
            except Exception as e:
                # one unreachable APM must not fail the whole query
                # (reference: logs and continues per adapter)
                log.warning("external apm %s trace %s failed: %s",
                            apm.name, trace_id, e)
                return []

        if not self.apms:
            return []
        if len(self.apms) == 1:
            return one(self.apms[0])
        # concurrent fan-out: response latency is the slowest single
        # APM, not the sum of every timeout
        with ThreadPoolExecutor(max_workers=len(self.apms)) as pool:
            results = list(pool.map(one, self.apms))
        spans: List[ExSpan] = []
        for got in results:
            spans.extend(got)
        return spans

"""Resource model: the controller's source-of-truth tables.

Reference: server/controller/recorder/ reconciles cloud-API and
genesis-reported snapshots into MySQL resource tables (region/az/host/
vpc/subnet/pod_node/pod_ns/pod_group/pod/service), and emits change
events. Here the model is in-memory dataclass tables persisted as one
JSON document, with the same diff-on-update discipline: update_domain()
reconciles a full snapshot per domain and reports created/deleted ids so
resource events and dictionary syncs stay incremental.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

# Ordered PARENTS-FIRST: the recorder sorts created lists by this index
# so a subscriber never sees a child before its parent. The set mirrors
# the reference's updater fleet (server/controller/recorder/updater/ —
# region.go, az.go, sub_domain.go, host.go, vm.go, vpc.go, network.go,
# vrouter.go, routing_table.go, vinterface.go, wan_ip.go, lan_ip.go,
# floating_ip.go, security_group(+_rule).go, nat_gateway.go,
# nat_rule.go, nat_vm_connection.go, lb.go, lb_listener.go,
# lb_target_server.go, lb_vm_connection.go, peer_connection.go, cen.go,
# rds_instance.go, redis_instance.go, pod_cluster.go, pod_node.go,
# vm_pod_node_connection.go, pod_namespace.go, pod_ingress(+rule,
# +rule_backend).go, pod_service(+port).go, pod_group(+port).go,
# pod_replica_set.go, pod.go, process.go).
RESOURCE_TYPES = (
    "region", "az", "sub_domain", "host", "vpc", "vm", "subnet",
    "vrouter", "routing_table", "vinterface", "wan_ip", "lan_ip",
    "security_group", "security_group_rule",
    "nat_gateway", "nat_rule", "nat_vm_connection",
    "floating_ip",      # links vpc+vm+nat_gateway: after all three
    "lb", "lb_listener", "lb_target_server", "lb_vm_connection",
    "peer_connection", "cen", "rds_instance", "redis_instance",
    "pod_cluster", "pod_node", "vm_pod_node_connection",
    "pod_ns", "pod_ingress", "pod_ingress_rule",
    "pod_ingress_rule_backend", "service", "pod_service_port",
    "pod_group", "pod_group_port", "pod_replica_set", "pod",
    "process",
)


@dataclass(frozen=True)
class Resource:
    """One row of any resource table."""

    type: str
    id: int
    name: str
    domain: str = "default"
    # type-specific links (epc_id for subnets/pods, ip/port for services...)
    attrs: Tuple[Tuple[str, object], ...] = ()

    def attr(self, key: str, default=None):
        for k, v in self.attrs:
            if k == key:
                return v
        return default


def make_resource(type: str, id: int, name: str, domain: str = "default",
                  **attrs) -> Resource:
    return Resource(type, id, name, domain,
                    tuple(sorted(attrs.items())))


@dataclass
class DomainDiff:
    created: List[Resource] = field(default_factory=list)
    deleted: List[Resource] = field(default_factory=list)
    updated: List[Resource] = field(default_factory=list)

    @property
    def changed(self) -> bool:
        return bool(self.created or self.deleted or self.updated)


class ResourceModel:
    """All resource tables + version counter + change subscribers."""

    def __init__(self, path: Optional[str] = None) -> None:
        self.path = path
        self._rows: Dict[Tuple[str, int], Resource] = {}
        self._lock = threading.Lock()
        self.version = 1
        self._subscribers: List[Callable[[DomainDiff], None]] = []
        if path is not None and os.path.exists(path):
            self._load()

    # -- persistence -------------------------------------------------------
    def _load(self) -> None:
        with open(self.path) as f:
            doc = json.load(f)
        self.version = doc.get("version", 1)
        for r in doc.get("resources", []):
            res = Resource(r["type"], r["id"], r["name"], r["domain"],
                           tuple((k, v) for k, v in r["attrs"]))
            self._rows[(res.type, res.id)] = res

    def _save(self) -> None:
        if self.path is None:
            return
        doc = {
            "version": self.version,
            "resources": [
                {"type": r.type, "id": r.id, "name": r.name,
                 "domain": r.domain, "attrs": [list(a) for a in r.attrs]}
                for r in self._rows.values()
            ],
        }
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.path)

    # -- queries -----------------------------------------------------------
    def list(self, type: Optional[str] = None,
             domain: Optional[str] = None) -> List[Resource]:
        with self._lock:
            return [r for r in self._rows.values()
                    if (type is None or r.type == type)
                    and (domain is None or r.domain == domain)]

    def get(self, type: str, id: int) -> Optional[Resource]:
        with self._lock:
            return self._rows.get((type, id))

    # -- updates -----------------------------------------------------------
    def upsert(self, resource: Resource) -> bool:
        """Atomic single-row create/update (no deletion scope at all —
        unlike update_domain this can never remove anything). Returns
        True when the row changed; subscribers see a one-row diff.
        Exists for hot-path upserts (per-sync sub_domain rows) where a
        whole-domain reconcile would be an O(domain) read-modify-write
        race against concurrent syncs."""
        with self._lock:
            old = self._rows.get((resource.type, resource.id))
            if old == resource:
                return False
            if old is not None and old.domain != resource.domain:
                raise ValueError(
                    f"resource {(resource.type, resource.id)} is owned "
                    f"by domain {old.domain!r}")
            self._rows[(resource.type, resource.id)] = resource
            self.version += 1
            self._save()
        diff = DomainDiff(created=[resource] if old is None else [],
                          updated=[resource] if old is not None else [])
        for fn in self._subscribers:
            fn(diff)
        return True

    def subscribe(self, fn: Callable[[DomainDiff], None]) -> None:
        """Called after each update_domain with the diff (reference:
        recorder/pubsub feeding tagrecorder + resource-event emit)."""
        self._subscribers.append(fn)

    def update_domain(self, domain: str, snapshot: List[Resource],
                      sub_domain_id: Optional[int] = None) -> DomainDiff:
        """Reconcile the full snapshot for one domain (reference:
        recorder.Refresh diff engines, recorder/updater/).

        `sub_domain_id` narrows the reconciliation scope to ONE
        sub-domain's rows (reference: cloud/sub_domain.go — an attached
        k8s cluster refreshes independently of its owning cloud
        domain): only rows carrying that sub_domain_id attr are
        eligible for deletion, and every snapshot row must carry it —
        a sub-domain refresh can never delete the parent domain's own
        resources, and a full-domain refresh (None) owns only the
        un-scoped rows."""
        for r in snapshot:   # validate before any mutation
            if r.domain != domain:
                raise ValueError(f"resource {r} not in domain {domain}")
            # scope symmetry: a sub-domain refresh must carry ITS id on
            # every row, and a full-domain refresh must carry none — a
            # scoped row upserted by the full-domain path would be
            # deletable by NO refresh (each side's deletion scope would
            # skip it), i.e. an immortal stale resource
            if r.attr("sub_domain_id", 0) != (sub_domain_id or 0):
                raise ValueError(
                    f"resource {(r.type, r.id)} sub_domain scope "
                    f"mismatch (refresh scope: {sub_domain_id})")
        diff = DomainDiff()
        with self._lock:
            new_keys = {(r.type, r.id) for r in snapshot}
            for key, old in list(self._rows.items()):
                if old.domain != domain or key in new_keys:
                    continue
                if old.attr("sub_domain_id", 0) != (sub_domain_id or 0):
                    continue         # outside this refresh's scope
                del self._rows[key]
                diff.deleted.append(old)
            for r in snapshot:
                old = self._rows.get((r.type, r.id))
                if old is None:
                    diff.created.append(r)
                elif old != r:
                    diff.updated.append(r)
                self._rows[(r.type, r.id)] = r
            if diff.changed:
                self.version += 1
                self._save()
        if diff.changed:
            for fn in self._subscribers:
                fn(diff)
        return diff

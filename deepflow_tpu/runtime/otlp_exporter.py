"""OTLP exporter: l7_flow_log chunks -> OTLP/HTTP trace exports.

Reference: server/ingester/flow_log/exporters/otlp_exporter/ — queue
workers convert L7FlowLog rows to OTLP spans and push them over gRPC to
a collector. Here the conversion targets the same public OTLP wire shape
(wire/protos/otel.proto) shipped as protobuf over HTTP POST /v1/traces
(the OTLP/HTTP binary flavor), with the SmartEncoded endpoint hash
reverse-translated to the span name when the dictionary knows it.
"""

from __future__ import annotations

import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

import numpy as np

from deepflow_tpu.runtime.exporters import QueueWorkerExporter
from deepflow_tpu.runtime.stats import StatsRegistry
from deepflow_tpu.store.dict_store import TagDictRegistry
from deepflow_tpu.wire.gen import otel_pb2


def l7_chunk_to_otlp(cols: Dict[str, np.ndarray],
                     endpoint_dict=None) -> otel_pb2.ExportTraceServiceRequest:
    req = otel_pb2.ExportTraceServiceRequest()
    rs = req.resource_spans.add()
    ss = rs.scope_spans.add()
    n = len(next(iter(cols.values())))
    for i in range(n):
        span = ss.spans.add()
        eh = int(cols["endpoint_hash"][i])
        name = None
        if endpoint_dict is not None:
            name = endpoint_dict.decode(eh)
        span.name = name if name else f"endpoint-{eh:08x}"
        span.kind = 2  # server
        start_ns = int(cols["timestamp"][i]) * 1_000_000_000
        span.start_time_unix_nano = start_ns
        span.end_time_unix_nano = start_ns + int(cols["rrt_us"][i]) * 1000
        span.status.code = 2 if int(cols["status"][i]) else 1
        kv = span.attributes.add()
        kv.key = "df.l7_protocol"
        kv.value.int_value = int(cols["l7_protocol"][i])
        kv = span.attributes.add()
        kv.key = "net.peer.port"
        kv.value.int_value = int(cols["port_dst"][i])
    return req


class OtlpExporter(QueueWorkerExporter):
    """Exporter-contract OTLP/HTTP pusher for l7 streams."""

    def __init__(self, endpoint: str,
                 tag_dicts: Optional[TagDictRegistry] = None,
                 n_workers: int = 2, queue_size: int = 1 << 14,
                 stats: Optional[StatsRegistry] = None) -> None:
        super().__init__("otlp", ["l7_flow_log"], queue_size=queue_size,
                         n_workers=n_workers, batch=16, stats=stats)
        self.endpoint = endpoint.rstrip("/") + "/v1/traces"
        self.endpoint_dict = None if tag_dicts is None else \
            tag_dicts.get("l7_endpoint")
        self.spans_sent = 0
        self.send_errors = 0

    def process(self, chunks: List[Any]) -> None:
        for _stream, _idx, cols, *_ in chunks:
            req = l7_chunk_to_otlp(cols, self.endpoint_dict)
            body = req.SerializeToString()
            http_req = urllib.request.Request(
                self.endpoint, data=body,
                headers={"Content-Type": "application/x-protobuf"})
            try:
                with urllib.request.urlopen(http_req, timeout=10):
                    pass
                self.spans_sent += sum(
                    len(ss.spans) for rs in req.resource_spans
                    for ss in rs.scope_spans)
            except (urllib.error.URLError, OSError):
                self.send_errors += 1

    def counters(self) -> dict:
        c = super().counters()
        c.update({"spans_sent": self.spans_sent,
                  "send_errors": self.send_errors})
        return c

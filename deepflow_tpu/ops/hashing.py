"""Multiply-shift hash families on uint32 lanes.

All sketch kernels share this family. Widths are powers of two so bucket
selection is a top-bits shift (multiply-shift universal hashing), never a
modulo — TPU-friendly and avalanche-tested in tests/test_hashing.py.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from deepflow_tpu.utils.u32 import as_u32, mix32, splitmix32_seeds

_U32 = np.uint32


def make_seeds(depth: int, seed: int = 0xDEC0DE) -> jnp.ndarray:
    """[depth, 2] odd uint32 (multiplier, xor-salt) pairs."""
    raw = splitmix32_seeds(2 * depth, seed)
    return jnp.asarray(raw.reshape(depth, 2))


def bucket(keys: jnp.ndarray, mult: jnp.ndarray, salt: jnp.ndarray, log2_width: int) -> jnp.ndarray:
    """h(x) = top log2_width bits of (mult * mix32(x ^ salt)); shape of keys."""
    x = mix32(as_u32(keys) ^ salt)
    return ((mult * x) >> _U32(32 - log2_width)).astype(jnp.int32)


def multi_bucket(keys: jnp.ndarray, seeds: jnp.ndarray, log2_width: int) -> jnp.ndarray:
    """[depth, n] bucket indices for each of the `depth` hash rows.

    Plays the role of the d independent hash rows of a Count-Min sketch; the
    reference's exact GROUP BY has no analogue — this is where the TPU design
    trades exactness for a fixed-shape, device-resident state.
    """
    mult = seeds[:, 0][:, None]  # [d, 1]
    salt = seeds[:, 1][:, None]
    x = mix32(as_u32(keys)[None, :] ^ salt)
    return ((mult * x) >> _U32(32 - log2_width)).astype(jnp.int32)


def fingerprint(keys: jnp.ndarray, salt: int = 0xF1A9E12) -> jnp.ndarray:
    """Secondary 32-bit fingerprint, independent of bucket hashes."""
    return mix32(as_u32(keys) ^ _U32(salt))

"""Trace-context header extraction + deep HTTP/1 parsing.

Reference behavior: agent/src/flow_generator/protocol_logs/http.rs
decode_id (TraceType dispatch) and the HttpInfo header extraction —
trace ids from instrumented-app headers are what link packet/eBPF spans
to OTel spans in one distributed trace.
"""

import numpy as np
import pytest

from deepflow_tpu.agent import trace_context
from deepflow_tpu.agent.l7 import (MSG_REQUEST, MSG_RESPONSE, HttpParser,
                                   SessionAggregator, http_body_len,
                                   parse_http_headers)
from deepflow_tpu.agent.trace_context import SPAN_ID, TRACE_ID, decode_id


@pytest.fixture(autouse=True)
def _default_config():
    """Each test starts from the default extraction config."""
    trace_context.configure(trace_types=("traceparent", "sw8"),
                            span_types=("traceparent", "sw8"),
                            x_request_id="x-request-id",
                            proxy_client=("x-forwarded-for", "x-real-ip"))
    yield


# -- decoder formats -------------------------------------------------------
def test_traceparent_decode():
    v = "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"
    assert decode_id("traceparent", v, TRACE_ID) == \
        "4bf92f3577b34da6a3ce929d0e0e4736"
    assert decode_id("traceparent", v, SPAN_ID) == "00f067aa0ba902b7"


def test_sw8_decode_base64_segments():
    # sample-TRACEID(b64)-SEGMENTID(b64)-SPANID-...
    import base64
    tid = base64.b64encode(b"trace-123").decode()
    seg = base64.b64encode(b"seg-9").decode()
    v = f"1-{tid}-{seg}-3-c2Vydmlj-aW5zdA==-L2FwaQ==-MTAuMC4wLjE6ODA="
    assert decode_id("sw8", v, TRACE_ID) == "trace-123"
    assert decode_id("sw8", v, SPAN_ID) == "seg-9-3"


def test_sw3_decode():
    v = "seg1|4|100|100|#10.0.0.1:80|#/parent|#/api|TRACE9|1"
    assert decode_id("sw3", v, TRACE_ID) == "TRACE9"
    assert decode_id("sw3", v, SPAN_ID) == "seg1-4"


def test_uber_decode():
    v = "abcdef123:span77:parent0:1"
    assert decode_id("uber-trace-id", v, TRACE_ID) == "abcdef123"
    assert decode_id("uber-trace-id", v, SPAN_ID) == "parent0"


def test_custom_key_decodes_raw():
    assert decode_id("x-company-trace", " raw-id ", TRACE_ID) == "raw-id"


def test_extract_priority_order_and_custom_config():
    hdrs = {"sw8": "1-" + "dHJhY2U=" + "-c2Vn-1-a-b-c-d",
            "x-mytrace": "custom-id"}
    # default order: traceparent absent -> sw8 wins
    assert trace_context.extract(hdrs)["trace_id"] == "trace"
    # pushed config: a customize key takes priority
    trace_context.configure(trace_types=("x-mytrace", "sw8"))
    assert trace_context.extract(hdrs)["trace_id"] == "custom-id"


def test_extract_proxy_client_first_hop():
    hdrs = {"x-forwarded-for": "203.0.113.9, 10.0.0.1, 10.0.0.2"}
    assert trace_context.extract(hdrs)["client_ip"] == "203.0.113.9"
    hdrs = {"x-real-ip": "198.51.100.7"}
    assert trace_context.extract(hdrs)["client_ip"] == "198.51.100.7"


# -- deep HTTP/1 -----------------------------------------------------------
REQ = (b"GET /api/users?id=7 HTTP/1.1\r\n"
       b"Host: api.example.com\r\n"
       b"User-Agent: curl/8.0\r\n"
       b"Referer: https://example.com/home\r\n"
       b"X-Request-Id: req-42\r\n"
       b"X-Forwarded-For: 203.0.113.9, 10.0.0.1\r\n"
       b"traceparent: 00-4bf92f3577b34da6a3ce929d0e0e4736-"
       b"00f067aa0ba902b7-01\r\n"
       b"\r\n")


def test_http1_request_full_headers():
    rec = HttpParser().parse(REQ)
    assert rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "GET /api/users"
    assert rec.resource == "/api/users?id=7"
    assert rec.req_type == "GET"
    assert rec.domain == "api.example.com"
    assert rec.version == "1.1"
    assert rec.user_agent == "curl/8.0"
    assert rec.referer == "https://example.com/home"
    assert rec.x_request_id == "req-42"
    assert rec.client_ip == "203.0.113.9"
    assert rec.trace_id == "4bf92f3577b34da6a3ce929d0e0e4736"
    assert rec.span_id == "00f067aa0ba902b7"


def test_http1_response_content_length():
    resp = (b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: 512\r\n\r\n" + b"x" * 16)
    rec = HttpParser().parse(resp)
    assert rec.msg_type == MSG_RESPONSE and rec.status == 200
    assert rec.resp_len == 512          # framing truth, not capture size


def test_http1_chunked_body_accounting():
    body = (b"4\r\nWiki\r\n"
            b"5\r\npedia\r\n"
            b"0\r\n\r\n")
    resp = (b"HTTP/1.1 200 OK\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n" + body)
    rec = HttpParser().parse(resp)
    assert rec.resp_len == 9            # 4 + 5, terminator excluded
    # a lying chunk size is capped at the bytes actually present
    liar = (b"HTTP/1.1 200 OK\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n"
            b"FFFF\r\nonly-14-bytes!\r\n")
    assert HttpParser().parse(liar).resp_len == 16  # 14 + CRLF present


def test_parse_http_headers_first_value_wins_and_bounded():
    payload = (b"GET / HTTP/1.1\r\n"
               b"X-Dup: first\r\nX-Dup: second\r\n\r\n")
    h = parse_http_headers(payload)
    assert h["x-dup"] == "first"
    flood = b"GET / HTTP/1.1\r\n" + b"".join(
        b"H%d: v\r\n" % i for i in range(500)) + b"\r\n"
    assert len(parse_http_headers(flood)) <= 64


def test_http_body_len_no_framing_headers():
    assert http_body_len(b"POST /x HTTP/1.1\r\nHost: a\r\n\r\nhello",
                         {"host": "a"}) == 5


# -- session merge carries the detail -------------------------------------
def test_session_merge_carries_trace_context():
    agg = SessionAggregator()
    req = HttpParser().parse(REQ)
    resp = HttpParser().parse(b"HTTP/1.1 200 OK\r\n"
                              b"Content-Length: 2\r\n"
                              b"X-Request-Id: resp-43\r\n\r\nok")
    assert agg.offer(("f",), req, 1_000) is None
    merged = agg.offer(("f",), resp, 2_000)
    assert merged["trace_id"] == "4bf92f3577b34da6a3ce929d0e0e4736"
    assert merged["span_id"] == "00f067aa0ba902b7"
    assert merged["domain"] == "api.example.com"
    assert merged["user_agent"] == "curl/8.0"
    assert merged["client_ip"] == "203.0.113.9"
    assert merged["x_request_id_0"] == "req-42"
    assert merged["x_request_id_1"] == "resp-43"


# -- HTTP/2: same extraction through HPACK --------------------------------
def test_http2_request_trace_headers():
    import struct

    from deepflow_tpu.agent import l7_ext

    def lit(name: bytes, value: bytes) -> bytes:
        return (b"\x00" + bytes([len(name)]) + name
                + bytes([len(value)]) + value)

    tp = b"00-aaaabbbbccccddddeeeeffff00001111-2222333344445555-01"
    block = (b"\x82"                                    # :method GET
             + lit(b":path", b"/v2/users?x=1")
             + lit(b":authority", b"svc.example.com")
             + lit(b"traceparent", tp)
             + lit(b"x-request-id", b"h2-req-1")
             + lit(b"user-agent", b"grpc-go/1.50"))
    payload = l7_ext._H2_PREFACE + len(block).to_bytes(3, "big") + \
        bytes([0x1, 0x4]) + struct.pack(">I", 1) + block
    rec = l7_ext.Http2Parser().parse(payload)
    assert rec.msg_type == MSG_REQUEST
    assert rec.endpoint == "GET /v2/users"
    assert rec.resource == "/v2/users?x=1"
    assert rec.domain == "svc.example.com"
    assert rec.version == "2"
    assert rec.trace_id == "aaaabbbbccccddddeeeeffff00001111"
    assert rec.span_id == "2222333344445555"
    assert rec.x_request_id == "h2-req-1"
    assert rec.user_agent == "grpc-go/1.50"


# -- the wire carries it: session dict -> protobuf -> columns -------------
def test_l7_wire_roundtrip_stamps_trace_columns(tmp_path):
    from deepflow_tpu.agent.trident import _l7_record_bytes
    from deepflow_tpu.decode.columnar import decode_l7_records
    from deepflow_tpu.store.dict_store import TagDictRegistry

    agg = SessionAggregator()
    agg.offer(("f",), HttpParser().parse(REQ), 1_000)
    merged = agg.offer(("f",), HttpParser().parse(
        b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nok"), 2_000)
    raw = _l7_record_bytes((0x0A000001, 0x0A000002, 555, 80, 6),
                           merged, 2_000, vtap_id=3)
    dicts = TagDictRegistry(str(tmp_path))
    d = dicts.get("l7_endpoint")
    cols = decode_l7_records([raw], endpoint_dict=d)
    tid = "4bf92f3577b34da6a3ce929d0e0e4736"
    assert cols["trace_id_hash"][0] == np.uint32(d.encode_one(tid))
    assert cols["span_id_hash"][0] != 0
    assert cols["request_domain_hash"][0] == np.uint32(
        d.encode_one("api.example.com"))
    assert cols["user_agent_hash"][0] != 0
    assert cols["x_request_id_0_hash"][0] == np.uint32(
        d.encode_one("req-42"))
    # the dict reverses the hash back to the trace id (tempo lookup path)
    assert d.decode(int(cols["trace_id_hash"][0])) == tid
    dicts.close()


def test_configure_accepts_comma_strings_and_lists():
    trace_context.configure(trace_types="X-MyTrace, sw8",
                            x_request_id=["X-Req-A", "x-req-b"])
    cfg = trace_context.config()
    assert cfg.trace_types == ("x-mytrace", "sw8")
    assert cfg.x_request_id == ("x-req-a", "x-req-b")
    got = trace_context.extract({"x-req-b": "id-9"})
    assert got["x_request_id"] == "id-9"


def test_chunked_rejects_hostile_size_tokens():
    for tok in (b"-2", b"+3", b"1_0", b"0x10", b""):
        payload = (b"HTTP/1.1 200 OK\r\n"
                   b"Transfer-Encoding: chunked\r\n\r\n"
                   + tok + b"\r\nAAAA\r\n")
        assert http_body_len(payload, {"transfer-encoding": "chunked"}) == 0


def test_http2_duplicate_header_first_wins():
    import struct

    from deepflow_tpu.agent import l7_ext

    def lit(name: bytes, value: bytes) -> bytes:
        return (b"\x00" + bytes([len(name)]) + name
                + bytes([len(value)]) + value)

    block = (b"\x82" + lit(b":path", b"/")
             + lit(b"x-forwarded-for", b"1.1.1.1")
             + lit(b"x-forwarded-for", b"2.2.2.2"))
    payload = l7_ext._H2_PREFACE + len(block).to_bytes(3, "big") + \
        bytes([0x1, 0x4]) + struct.pack(">I", 1) + block
    rec = l7_ext.Http2Parser().parse(payload)
    assert rec.client_ip == "1.1.1.1"       # same as HTTP/1 semantics


def test_parser_surface_never_raises_on_fuzz():
    """The new header/trace parsing surface is attacker-facing payload
    handling: random and structured-corrupt inputs must never raise
    (the reference fuzzes its protocol_logs the same way)."""
    import random

    from deepflow_tpu.agent.l7 import HttpParser

    rng = random.Random(0xFEED)
    p = HttpParser()
    seeds = [
        REQ,
        b"HTTP/1.1 200 OK\r\nTransfer-Encoding: chunked\r\n\r\n5\r\nhi",
        b"GET / HTTP/1.1\r\n" + b"traceparent: " + b"-" * 300 + b"\r\n\r\n",
        b"GET / HTTP/1.1\r\nHost: " + bytes(range(256)) + b"\r\n\r\n",
    ]
    for _ in range(300):
        base = bytearray(rng.choice(seeds))
        for _ in range(rng.randrange(1, 8)):
            base[rng.randrange(len(base))] = rng.randrange(256)
        payload = bytes(base)
        if p.check(payload):
            p.parse(payload)                    # must not raise
        parse_http_headers(payload)
        http_body_len(payload, parse_http_headers(payload))
    for _ in range(200):
        blob = bytes(rng.randrange(256)
                     for _ in range(rng.randrange(0, 400)))
        if p.check(blob):
            p.parse(blob)


def test_decoders_never_raise_on_fuzz():
    import random

    rng = random.Random(0xD00D)
    keys = ["traceparent", "sw8", "sw6", "sw3", "uber-trace-id", "x-any"]
    for _ in range(500):
        key = rng.choice(keys)
        value = "".join(rng.choice("-|:.abc0123\x00 ￿")
                        for _ in range(rng.randrange(0, 60)))
        decode_id(key, value, TRACE_ID)
        decode_id(key, value, SPAN_ID)
        trace_context.extract({key: value})


def test_http_log_keys_push_through_group_config():
    """The controller accepts the http_log_* keys and a managed agent
    hot-applies them (the ops-documented flow end to end)."""
    from deepflow_tpu.agent.trident import Agent, AgentConfig
    from deepflow_tpu.controller.registry import VTapRegistry

    reg = VTapRegistry()
    reg.set_config("default",
                   {"http_log_trace_id": "x-corp-trace, traceparent"})
    agent = Agent(AgentConfig())
    try:
        agent._apply_config(reg.get_config("default"))
        assert trace_context.config().trace_types == \
            ("x-corp-trace", "traceparent")
        # unmanaged keys keep their values
        assert trace_context.config().proxy_client == \
            ("x-forwarded-for", "x-real-ip")
    finally:
        agent.close()


def test_bad_http_log_values_rejected_at_the_controller():
    """A non-string value must 400 at set_config, not raise inside
    every managed agent's hot-apply forever."""
    from deepflow_tpu.controller.registry import VTapRegistry

    reg = VTapRegistry()
    for bad in (5, True, [1, 2], {"a": 1}):
        with pytest.raises(ValueError):
            reg.set_config("default", {"http_log_trace_id": bad})
    reg.set_config("default", {"http_log_trace_id": "a, b"})     # ok
    reg.set_config("default", {"http_log_trace_id": ["a", "b"]})  # ok

#!/usr/bin/env bash
# CI entry point: tests + entry-point checks + per-kernel microbenches.
#
# Everything runs on the virtual 8-device CPU mesh (no TPU needed), the
# same environment tests/conftest.py pins, so this script is safe on any
# box with the baked-in Python env. SURVEY.md §4: the new framework's CI
# bar is "do better than the reference" — the reference gates on
# unit+integration; this also compile-checks the driver entry points and
# keeps kernel microbenches runnable in one command.
#
# Usage: ./ci.sh [quick]   ("quick" skips the microbenches)

set -euo pipefail
cd "$(dirname "$0")"

export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
export PALLAS_AXON_POOL_IPS=   # never claim the TPU tunnel from CI
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"

echo "== kernel capability probes =="
# verdict r4 #8: every CI log states which datapath mode ran — live
# kernel attach (PMU visible) or verifier-load + replay (masked)
python - <<'EOF'
from deepflow_tpu.agent import bpf, btf, socket_trace, uprobe_trace
print("bpf(2):", bpf.available())
print("kprobe attach:", socket_trace.attach_available())
print("uprobe attach:", uprobe_trace.attach_available())
print("kernel BTF (stack-ABI goid keying):",
      btf.fsbase_offset() or "unavailable")
EOF

echo "== deepflow-lint: static invariants =="
# ISSUE 3 + ISSUE 11: the pipeline's concurrency / trace-safety /
# metrics / conservation / twin disciplines checked mechanically
# (deepflow_tpu/analysis/). The gate is "no findings beyond the
# committed baseline" — paying down debt shrinks .lint-baseline.json;
# any NEW violation (including a twin fingerprint drifting from
# .lint-twins.json without --ack-twin) fails CI here. SARIF rides to
# artifacts/lint.sarif for annotation surfaces, and the wall-clock
# budget (<30s, memoized ProjectIndex) keeps the gate honest as the
# rule set grows.
mkdir -p artifacts
lint_t0=$(date +%s)
python -m deepflow_tpu.cli lint --baseline .lint-baseline.json \
    --sarif artifacts/lint.sarif
lint_t1=$(date +%s)
lint_dt=$((lint_t1 - lint_t0))
echo "lint self-scan: ${lint_dt}s (budget 30s)"
if [ "$lint_dt" -ge 30 ]; then
    echo "FAIL: lint self-scan blew the 30s runtime budget" >&2
    exit 1
fi
python - <<'EOF'
import json
doc = json.load(open("artifacts/lint.sarif"))
assert doc["version"] == "2.1.0", doc.get("version")
rules = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
for need in ("lock-order-cycle", "unlocked-shared-write",
             "silent-drop", "twin-drift", "model-conform",
             "doc-drift",
             # ISSUE 18: the device-plane rules must be registered
             "donation-use-after-donate", "retrace-hazard",
             "u32-overflow", "pytree-schema-drift"):
    assert need in rules, f"SARIF rule table missing {need}"
print(f"lint.sarif: {len(rules)} rules, "
      f"{len(doc['runs'][0]['results'])} gated result(s)")
# the device-plane gate only has teeth while both stores are
# committed (deleting one disarms it silently — fail loudly here)
for path, key, floor in ((".lint-programs.json", "programs", 20),
                         (".lint-schemas.json", "schemas", 14)):
    store = json.load(open(path))
    assert store["version"] == 1, path
    n = len(store[key])
    assert n >= floor, f"{path}: {n} {key} < {floor}"
    print(f"{path}: {n} acknowledged {key}")
EOF

echo "== deepflow-model: exhaustive protocol verification =="
# ISSUE 14: the pod epoch / spill-drain / sender-ring protocols
# checked over ALL interleavings (N=3 shards, <= 2 concurrent faults),
# the mutation self-test (every seeded mutant must die with a
# counterexample), and one LIVE mutant demo: inject a bug, watch the
# checker produce a readable schedule, revert, re-prove clean. The
# whole gate fits a 60s budget; an unfinished sweep exits 2 and fails
# here — a partial sweep is not a proof. Verdicts + the demo
# counterexample land in artifacts/ beside lint.sarif.
verify_t0=$(date +%s)
python -m deepflow_tpu.cli verify --budget-s 45 \
    --trace-out artifacts/verify-verdicts.txt
python -m deepflow_tpu.cli verify --mutants --budget-s 45
# live demo: inject -> counterexample -> revert -> clean
set +e
python -m deepflow_tpu.cli verify --protocol pod \
    --mutant double-merge-late \
    --trace-out artifacts/verify-trace.txt > /dev/null
mut_rc=$?
set -e
if [ "$mut_rc" -ne 1 ]; then
    echo "FAIL: injected pod mutant was not killed (rc=$mut_rc)" >&2
    exit 1
fi
grep -q "schedule (shortest):" artifacts/verify-trace.txt
grep -q "conservation" artifacts/verify-trace.txt
python -m deepflow_tpu.cli verify --protocol pod --budget-s 45 \
    > /dev/null   # revert (the mutation is parametric): clean again
verify_t1=$(date +%s)
verify_dt=$((verify_t1 - verify_t0))
echo "deepflow-model: 3 protocols proven, mutants killed, demo trace" \
     "captured (${verify_dt}s, budget 60s)"
if [ "$verify_dt" -ge 60 ]; then
    echo "FAIL: verify gate blew the 60s budget" >&2
    exit 1
fi

echo "== twin-drift gate trips on an unacked edit =="
# ISSUE 11 acceptance: prove IN CI that editing one side of a
# registered twin pair without `--ack-twin` fails the gate — on a
# throwaway copy of the fixture shape, never the real tree
python - <<'EOF'
import json, os, pathlib, subprocess, sys, tempfile

with tempfile.TemporaryDirectory() as td:
    td = pathlib.Path(td)
    (td / "analysis").mkdir()
    (td / "analysis" / "twins.py").write_text(
        'TWIN_TABLE = [\n'
        '    ("demo", "host.py:mix_np", "dev.py:mix"),\n'
        ']\n')
    (td / "host.py").write_text("def mix_np(x):\n    return x * 3\n")
    (td / "dev.py").write_text("def mix(x):\n    return x * 3\n")
    store = td / "twins.json"
    run = lambda *a: subprocess.run(
        [sys.executable, "-m", "deepflow_tpu.cli", "lint", str(td),
         "--rules", "twin-drift", "--twins", str(store), *a],
        capture_output=True, text=True)
    ack = subprocess.run(
        [sys.executable, "-m", "deepflow_tpu.cli", "lint", str(td),
         "--twins", str(store), "--ack-twin"],
        capture_output=True, text=True)
    assert ack.returncode == 0, ack.stderr + ack.stdout
    clean = run()
    assert clean.returncode == 0, clean.stdout
    # edit the device side WITHOUT re-acking: the gate must trip
    (td / "dev.py").write_text("def mix(x):\n    return x * 5\n")
    tripped = run()
    assert tripped.returncode == 1 and "twin-drift" in tripped.stdout, \
        tripped.stdout
    # ack makes it green again
    ack2 = subprocess.run(
        [sys.executable, "-m", "deepflow_tpu.cli", "lint", str(td),
         "--twins", str(store), "--ack-twin"],
        capture_output=True, text=True)
    assert ack2.returncode == 0, ack2.stderr
    assert run().returncode == 0
print("twin gate: ack -> clean, edit -> trip, re-ack -> clean")
EOF

echo "== device-plane gate: donated reuse trips live =="
# ISSUE 18 acceptance: the PR-15 bug class — a donated state buffer
# read after the donating dispatch — must fail the gate on a live
# throwaway tree, cross-file through a jit-returning factory; and a
# jit cache-key edit without --ack-programs must name the callable
python - <<'EOF'
import pathlib, subprocess, sys, tempfile

with tempfile.TemporaryDirectory() as td:
    td = pathlib.Path(td)
    (td / "detectors.py").write_text(
        "import jax\n"
        "def make_window_step(cfg):\n"
        "    return jax.jit(lambda s, rows: s, donate_argnums=0)\n")
    (td / "alerts.py").write_text(
        "import detectors\n"
        "class Engine:\n"
        "    def __init__(self, cfg):\n"
        "        self._step = detectors.make_window_step(cfg)\n"
        "    def feed(self, state, rows):\n"
        "        out = self._step(state, rows)\n"
        "        return state\n")     # <- read after donation
    run = lambda *a: subprocess.run(
        [sys.executable, "-m", "deepflow_tpu.cli", "lint", str(td), *a],
        capture_output=True, text=True)
    tripped = run("--rules", "donation-use-after-donate")
    assert tripped.returncode == 1, tripped.stdout
    assert "donated" in tripped.stdout and "alerts.py" in tripped.stdout
    # the sanctioned shape — rebind the result over the donated name
    (td / "alerts.py").write_text((td / "alerts.py").read_text().replace(
        "        out = self._step(state, rows)\n",
        "        state = self._step(state, rows)\n"))
    assert run("--rules", "donation-use-after-donate").returncode == 0
    # cache-key edits go through --ack-programs, like twin edits
    store = td / "programs.json"
    ack = run("--programs", str(store), "--ack-programs")
    assert ack.returncode == 0, ack.stderr + ack.stdout
    assert run("--programs", str(store),
               "--rules", "retrace-hazard").returncode == 0
    (td / "detectors.py").write_text(
        (td / "detectors.py").read_text().replace(
            "donate_argnums=0", "donate_argnums=0, static_argnums=1"))
    drift = run("--programs", str(store), "--rules", "retrace-hazard")
    assert drift.returncode == 1, drift.stdout
    assert "make_window_step" in drift.stdout \
        and "--ack-programs" in drift.stdout, drift.stdout
print("device gate: donated reuse trips, rebind clean, "
      "key edit needs --ack-programs")
EOF

echo "== pytest =="
python -m pytest tests/ -q

echo "== prometheus exposition smoke =="
# flight recorder + /metrics listener against a live ingester: the
# text exposition format is a contract with real scrapers, so the
# strict checker failing ANY line fails CI (ISSUE 1 observability)
python - <<'EOF'
import socket, time, urllib.request
import numpy as np
from deepflow_tpu.batch.schema import L4_SCHEMA
from deepflow_tpu.enrich.platform_data import PlatformDataManager
from deepflow_tpu.pipelines import Ingester, IngesterConfig
from deepflow_tpu.runtime.promexpo import validate_exposition
from deepflow_tpu.runtime.tracing import default_tracer
from deepflow_tpu.wire import columnar_wire
from deepflow_tpu.wire.framing import FlowHeader, MessageType, encode_frame

ing = Ingester(IngesterConfig(listen_port=0, prom_port=0,
                              tpu_sketch_window_s=0.2),
               platform=PlatformDataManager())
ing.start()
r = np.random.default_rng(0)
cols = {name: (r.integers(-100, 100, 1000).astype(dt)
               if np.dtype(dt) == np.int32
               else r.integers(0, 1 << 20, 1000).astype(dt))
        for name, dt in L4_SCHEMA.columns}
frame = encode_frame(MessageType.COLUMNAR_FLOW,
                     columnar_wire.encode_columnar(cols),
                     FlowHeader(sequence=1, vtap_id=3))
with socket.create_connection(("127.0.0.1", ing.port), timeout=5) as s:
    for _ in range(4):
        s.sendall(frame)
needed = {"receiver", "decode", "export", "kernel", "window"}
deadline = time.time() + 60
while time.time() < deadline:
    if needed <= set(default_tracer().latency()):
        break
    time.sleep(0.2)
with urllib.request.urlopen(
        f"http://127.0.0.1:{ing.prom_port}/metrics", timeout=10) as resp:
    text = resp.read().decode()
ing.close()
problems = validate_exposition(text)
assert not problems, problems[:10]
missing = needed - set(default_tracer().latency())
assert not missing, f"stages never recorded: {missing}"
for stage in needed:
    assert f'stage="{stage}"' in text, f"{stage} absent from exposition"
# ISSUE 6: the accuracy observatory's Countable family and the
# continuous occupancy gauges ride every scrape of a live ingester
for needle in ("deepflow_tpu_sketch_accuracy_windows",
               "tpu_device_busy_fraction", "tpu_feed_stall_seconds"):
    assert needle in text, f"{needle} absent from exposition"
print("exposition OK:", len(text.splitlines()), "lines,",
      len(default_tracer().latency()), "stages")
EOF

echo "== chaos smoke: breaker + supervisor + degraded sketch =="
# Deterministic fault injection (runtime/faults.py, fixed seed) against a
# live ingester: one exporter raises 100% for 5s then heals, and the
# tpu_sketch device path is killed once. The process must stay up, the
# breaker must open and re-close via its half-open probe, zero exceptions
# may reach the decode stage, the sketch lane must restore from its
# checkpoint, and every loss must be visible as Countables on /metrics.
python - <<'EOF'
import socket, tempfile, time, urllib.request
import numpy as np
from deepflow_tpu.batch.schema import L4_SCHEMA
from deepflow_tpu.enrich.platform_data import PlatformDataManager
from deepflow_tpu.pipelines import Ingester, IngesterConfig
from deepflow_tpu.runtime.promexpo import validate_exposition
from deepflow_tpu.wire import columnar_wire
from deepflow_tpu.wire.framing import FlowHeader, MessageType, encode_frame

class Flaky:
    name = "flaky"
    def start(self): pass
    def close(self): pass
    def is_export_data(self, stream, cols): return stream == "l4_flow_log"
    def put(self, stream, idx, cols): pass

store = tempfile.mkdtemp(prefix="chaos_store_")
ing = Ingester(IngesterConfig(
    listen_port=0, prom_port=0, tpu_sketch_window_s=0.5, store_path=store,
    breaker_min_calls=2, breaker_open_s=1.5, breaker_half_open_probes=1,
    fault_spec=("exporter.raise:p=1.0,for_s=5,match=flaky;"
                "tpu.device_error:count=1,after=2;seed=7")),
    platform=PlatformDataManager())
ing.exporters.register(Flaky())
ing.start()
r = np.random.default_rng(0)
cols = {name: r.integers(0, 1 << 8, 500).astype(dt)
        for name, dt in L4_SCHEMA.columns}
frame = encode_frame(MessageType.COLUMNAR_FLOW,
                     columnar_wire.encode_columnar(cols),
                     FlowHeader(sequence=1, vtap_id=3))
states_seen, sent = set(), 0
deadline = time.time() + 9.0
with socket.create_connection(("127.0.0.1", ing.port), timeout=5) as s:
    while time.time() < deadline:
        s.sendall(frame); sent += 500
        states_seen.add(ing.exporters.breakers()["flaky"]["state"])
        if ("open" in states_seen and "closed" in states_seen
                and ing.tpu_sketch.device_errors >= 1
                and ing.exporters.breakers()["flaky"]["closes"] >= 1):
            break
        time.sleep(0.1)

br = ing.exporters.breakers()["flaky"]
assert br["trips"] >= 1, f"breaker never opened: {br}"
assert br["closes"] >= 1 and br["state"] == "closed", \
    f"breaker never re-closed via half-open probe: {br}"
assert ing.exporters.put_errors >= 2 and ing.exporters.shed_count >= 1, \
    "loss must be counted (put_errors/shed)"
# zero exceptions reached the decode stage: every decoder alive, zero crashes
dec = [t for t in ing.supervisor.threads() if t["name"].startswith("decode-")]
assert dec and all(t["alive"] and t["crashes"] == 0 for t in dec), dec
deadline = time.time() + 10.0
while time.time() < deadline:
    decoded = sum(d.records for d in ing.flow_log.decoders)
    if decoded >= sent:
        break
    time.sleep(0.1)
assert decoded >= sent, f"decode stalled: {decoded} < {sent}"
# the killed device path restored from checkpoint, <=1 window lost
sk = ing.tpu_sketch
assert sk.device_errors >= 1 and sk.lost_windows <= 1, sk.counters()
assert sk.checkpointer.counters()["restores"] >= 1, sk.checkpointer.counters()
assert not sk.degraded
with urllib.request.urlopen(
        f"http://127.0.0.1:{ing.prom_port}/metrics", timeout=10) as resp:
    text = resp.read().decode()
assert not validate_exposition(text)
for needle in ("deepflow_breaker_flaky_trips", "deepflow_breaker_flaky_closes",
               "deepflow_exporters_put_errors", "deepflow_supervisor_crashes",
               "deepflow_supervisor_restarts",
               "deepflow_exporter_tpu_sketch_device_errors",
               "deepflow_exporter_tpu_sketch_lost_windows",
               "deepflow_faults_armed"):
    assert needle in text, f"{needle} absent from /metrics"
ing.close()
print(f"chaos OK: {sent} records sent, {decoded} decoded, breaker {br['trips']}"
      f" trip(s)/{br['closes']} close(s), sketch restored "
      f"{sk.checkpointer.counters()['restores']}x, {sk.lost_windows} window lost")
EOF

echo "== durability smoke: kill-and-restart spill replay + retransmit =="
# ISSUE 4: the conservation invariant end-to-end. Ingester A's l4 decoder
# is wedged by a seeded stall while a real UniformSender (retransmit ring,
# seeded disconnects) blasts records: overflow spills to CRC segment
# files, /metrics shows the spill + dedup counters, and close() runs the
# drain ladder — deadline, then park the backlog on disk. Ingester B on
# the same spill_dir replays the segments; every record must be decoded
# exactly once or attributed to a named loss counter. Zero silent loss.
python - <<'EOF'
import tempfile, time, urllib.request
import numpy as np
from deepflow_tpu.agent.sender import UniformSender
from deepflow_tpu.batch.schema import L4_SCHEMA
from deepflow_tpu.enrich.platform_data import PlatformDataManager
from deepflow_tpu.pipelines import Ingester, IngesterConfig
from deepflow_tpu.runtime.promexpo import validate_exposition
from deepflow_tpu.wire.framing import MessageType

spill_dir = tempfile.mkdtemp(prefix="durability_spill_")
ROWS, FRAMES = 50, 60
cfg = dict(listen_port=0, prom_port=0, n_decoders=1, queue_size=64,
           spill_dir=spill_dir, drain_deadline_s=0.6)
ing_a = Ingester(IngesterConfig(
    fault_spec=("queue.stall:p=1.0,delay_s=5,match=ingest.l4_flow_log;"
                "sender.disconnect:count=3,after=5;seed=11"), **cfg),
    platform=PlatformDataManager())
ing_a.start()
r = np.random.default_rng(0)
cols = {name: r.integers(0, 1 << 8, ROWS).astype(dt)
        for name, dt in L4_SCHEMA.columns}
sender = UniformSender(MessageType.COLUMNAR_FLOW,
                       f"127.0.0.1:{ing_a.port}", vtap_id=3,
                       reconnect_interval=0.01)
sent = 0
for _ in range(FRAMES):
    sent += sender.send_columns(cols, L4_SCHEMA)
assert sender.flush(5.0) == 0, "retransmit ring failed to drain"
assert sender.disconnects >= 1 and sender.retransmitted_frames >= 1
deadline = time.time() + 10
while time.time() < deadline:
    if ing_a.spill.counters()["spilled_records"] > 0:
        break
    time.sleep(0.1)
with urllib.request.urlopen(
        f"http://127.0.0.1:{ing_a.prom_port}/metrics", timeout=10) as resp:
    text = resp.read().decode()
assert not validate_exposition(text)
for needle in ("deepflow_spill_spilled_records",
               "deepflow_spill_pending_segments",
               "deepflow_receiver_rx_duplicate"):
    assert needle in text, f"{needle} absent from /metrics"
dup = ing_a.receiver.counters()["rx_duplicate"]
assert dup >= 1, "retransmit dedup never engaged"
t0 = time.time()
ing_a.close()                      # the "kill": wedged decoder, short drain
took = time.time() - t0
assert took < 15, f"drain ladder hung: {took:.1f}s"
assert ing_a.health()["drain"] == "drained"
a_spill = ing_a.spill.counters()
a_decoded = sum(d.records for d in ing_a.flow_log.decoders)
assert a_spill["spilled_records"] > 0, a_spill

ing_b = Ingester(IngesterConfig(**cfg), platform=PlatformDataManager())
ing_b.start()                      # restart: replay the parked segments
deadline = time.time() + 20
while time.time() < deadline:
    if (ing_b.spill.pending_segments() == 0
            and all(len(q) == 0 for q in ing_b._own_queues().values())):
        break
    time.sleep(0.1)
time.sleep(0.5)
b_decoded = sum(d.records for d in ing_b.flow_log.decoders)
assert ing_b.spill.counters()["replayed"] > 0
with urllib.request.urlopen(
        f"http://127.0.0.1:{ing_b.prom_port}/metrics", timeout=10) as resp:
    text_b = resp.read().decode()
assert "deepflow_spill_replayed" in text_b
q_a = ing_a.flow_log._streams[0][1].counters()
q_b = ing_b.flow_log._streams[0][1].counters()
lost_frames = (a_spill["spill_evicted"] + q_a["overwritten"]
               + q_a["closed_dropped"] + q_b["overwritten"]
               + q_b["closed_dropped"]
               + ing_b.spill.counters()["spill_evicted"])
delivered = a_decoded + b_decoded
assert delivered + lost_frames * ROWS + \
    sender.counters()["retransmit_shed"] == sent, (
        f"silent loss: sent={sent} delivered={delivered} "
        f"lost_frames={lost_frames} a={a_spill} qa={q_a} qb={q_b}")
ing_b.close()
print(f"durability OK: {sent} records, {a_decoded} decoded pre-kill, "
      f"{a_spill['spilled_records']} frames spilled, {b_decoded} decoded "
      f"after restart replay, {dup} duplicate(s) suppressed, "
      f"{lost_frames} frame(s) counted lost")
EOF

echo "== feed smoke: coalesced+prefetch bit-identical, fewer dispatches =="
# ISSUE 5: the overlapped device feed on the CPU backend. Prefetch
# on/off must land the exact same sketch state; the coalesced path must
# provably ship fewer, bigger transfers (one device_put per group
# instead of one per plane) — asserted through the exporter's transfer/
# dispatch counters AND the tracer's kernel span counts (one span per
# fused group vs one per batch). The feed thread rides the supervision
# tree and the lint gate above already proved no host sync leaked into
# the async device path.
python - <<'EOF'
import numpy as np
import jax
from deepflow_tpu.batch.schema import L4_SCHEMA
from deepflow_tpu.runtime.supervisor import default_supervisor
from deepflow_tpu.runtime.tpu_sketch import TpuSketchExporter
from deepflow_tpu.runtime.tracing import default_tracer

tr = default_tracer()
tr.enable()
rng = np.random.default_rng(5)
pool = {name: rng.integers(0, 1 << 12, 512).astype(dt)
        for name, dt in L4_SCHEMA.columns}
chunks = [{k: v[rng.integers(0, 512, 3000)] for k, v in pool.items()}
          for _ in range(6)]
base = TpuSketchExporter(store=None, window_seconds=3600, batch_rows=1024,
                         wire="lanes", prefetch_depth=0)
# zero_copy pinned OFF: this smoke proves the ISSUE 5 TensorBatch feed
# (the bit-identity REFERENCE); the decode smoke below proves the
# ISSUE 9 zero-copy stager against it
feed = TpuSketchExporter(store=None, window_seconds=3600, batch_rows=1024,
                         wire="lanes", prefetch_depth=2, coalesce_batches=2,
                         zero_copy=False)
for c in chunks:
    base.process([("l4_flow_log", 0, c)])
    feed.process([("l4_flow_log", 0, c)])
assert feed._feed.drain(30), "feed never drained"
for a, b in zip(jax.tree.leaves(base.state), jax.tree.leaves(feed.state)):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
batches = base.batcher.emitted_batches
assert batches and batches == feed.batcher.emitted_batches
# dispatches-per-batch dropped: tracer kernel spans = inline batches +
# fused feed groups, and the fused groups undercut the batch count
kernel_spans = tr.counters()["kernel_count"]
assert kernel_spans == batches + feed._feed.groups, \
    (kernel_spans, batches, feed._feed.groups)
assert feed._feed.groups < batches
assert base.h2d_transfers == 5 * batches      # mask + 4 planes, per batch
assert feed.h2d_transfers <= batches, "coalesced path must be <= 1/batch"
assert feed.dispatches < base.dispatches
assert feed.batcher.pool_hits > 0, "recycle pool never engaged"
sup = [t for t in default_supervisor().threads()
       if t["name"] == "tpu-sketch-feed"]
assert sup and all(t["crashes"] == 0 for t in sup), sup
base.close()
feed.close()
tr.disable()
print(f"feed OK: {batches} batches, transfers {base.h2d_transfers} -> "
      f"{feed.h2d_transfers}, dispatches {base.dispatches} -> "
      f"{feed.dispatches}, state bit-identical")
EOF

echo "== decode smoke: zero-copy staging bit-identical, host floor, busy gauge =="
# ISSUE 9: the zero-copy decode->staging path. Zero-copy on/off (and the
# flow-hash sharded pack pool) must land the exact same sketch state;
# the host staging floor must be measured and the zero-copy path must
# not regress the TensorBatch reference; and a live lanes-wire ingester
# must serve tpu_device_busy_fraction and the decode hash-cache
# counters off /metrics.
python - <<'EOF'
import socket, time, urllib.request
import numpy as np
import jax
from deepflow_tpu.batch.schema import L4_SCHEMA, SKETCH_L4_SCHEMA
from deepflow_tpu.batch.staging import LaneStager, PackPool
from deepflow_tpu.batch.batcher import Batcher
from deepflow_tpu.enrich.platform_data import PlatformDataManager
from deepflow_tpu.models import flow_suite
from deepflow_tpu.pipelines import Ingester, IngesterConfig
from deepflow_tpu.runtime.promexpo import validate_exposition
from deepflow_tpu.runtime.tpu_sketch import TpuSketchExporter
from deepflow_tpu.wire import columnar_wire
from deepflow_tpu.wire.framing import FlowHeader, MessageType, encode_frame

# -- zero-copy on/off (and sharded pack) state equality ------------------
rng = np.random.default_rng(9)
pool = {name: rng.integers(0, 1 << 12, 512).astype(dt)
        for name, dt in L4_SCHEMA.columns}
chunks = [{k: v[rng.integers(0, 512, 3000)] for k, v in pool.items()}
          for _ in range(6)]
mk = lambda **kw: TpuSketchExporter(
    store=None, window_seconds=3600, batch_rows=1024, wire="lanes",
    prefetch_depth=2, coalesce_batches=2, **kw)
ref, zc, zcp = mk(zero_copy=False), mk(), mk(pack_workers=2)
assert zc.zero_copy and zcp.zero_copy and not ref.zero_copy
# compare at the WINDOW boundary (the consistency contract): the stager
# may park complete slots in its open group buffer mid-stream, but every
# flush ships the prefix — identical batch partition, identical output
for c in chunks:
    for e in (ref, zc, zcp):
        e.process([("l4_flow_log", 0, c)])
outs = [e.flush_window() for e in (ref, zc, zcp)]
for o in outs[1:]:
    for a, b in zip(jax.tree.leaves(outs[0]), jax.tree.leaves(o)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
zc_counters = zcp.counters()
assert zc_counters["staged_rows"] == 6 * 3000, zc_counters
assert zc_counters["pack_tasks"] > 0 and zc_counters["pack_task_errors"] == 0
for e in (ref, zc, zcp):
    e.close()

# -- host decode->staging floor: zero-copy must not regress --------------
C = 4096
sk_chunks = [{name: rng.integers(0, 1 << 12, 10_000).astype(dt)
              for name, dt in SKETCH_L4_SCHEMA.columns} for _ in range(4)]

def rate(fn):
    rows = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 0.5:
        for c in sk_chunks:
            fn(c)
            rows += 10_000
    return rows / (time.perf_counter() - t0)

flat = np.empty(flow_suite.coalesced_lanes_words(1, C), np.uint32)
b = Batcher(SKETCH_L4_SCHEMA, capacity=C)
def tb_path(c):
    for tb in b.put(c):
        flat[0] = tb.valid
        flow_suite.pack_lanes_into(tb.columns, flow_suite.slot_plane(flat, 0, C))
        b.recycle(tb)
st = LaneStager(C, group_batches=1, pool_cap=4)
def zc_path(c):
    for sg in st.put(c):
        sg.wait_ready(timeout=30.0)
        st.recycle(sg)
tb_rate, zc_rate = rate(tb_path), rate(zc_path)
assert zc_rate > 1_000_000, f"zero-copy staging floor: {zc_rate:.0f} rec/s"
assert zc_rate > 0.8 * tb_rate, \
    f"zero-copy regressed the TensorBatch pack: {zc_rate:.0f} vs {tb_rate:.0f}"

# -- live lanes-wire ingester: busy gauge + hash-cache on /metrics -------
ing = Ingester(IngesterConfig(
    listen_port=0, prom_port=0, tpu_sketch_window_s=0.5,
    tpu_sketch_wire="lanes", pack_workers=2),
    platform=PlatformDataManager())
assert ing.tpu_sketch.zero_copy, "lanes-wire ingester must stage zero-copy"
ing.start()
cols = {name: rng.integers(0, 1 << 8, 500).astype(dt)
        for name, dt in L4_SCHEMA.columns}
frame = encode_frame(MessageType.COLUMNAR_FLOW,
                     columnar_wire.encode_columnar(cols),
                     FlowHeader(sequence=1, vtap_id=3))
sent = 0
deadline = time.time() + 6.0
with socket.create_connection(("127.0.0.1", ing.port), timeout=5) as s:
    while time.time() < deadline and sent < 50_000:
        s.sendall(frame); sent += 500
deadline = time.time() + 10.0
while time.time() < deadline:
    if ing.tpu_sketch.rows_in >= sent:
        break
    time.sleep(0.1)
assert ing.tpu_sketch.rows_in >= sent, \
    f"sketch lane stalled: {ing.tpu_sketch.rows_in} < {sent}"
with urllib.request.urlopen(
        f"http://127.0.0.1:{ing.prom_port}/metrics", timeout=10) as resp:
    text = resp.read().decode()
assert not validate_exposition(text)
for needle in ("tpu_device_busy_fraction",
               "deepflow_decode_hash_cache_hash_cache_hits",
               "deepflow_exporter_tpu_sketch_staged_rows",
               "deepflow_exporter_tpu_sketch_pack_tasks"):
    assert needle in text, f"{needle} absent from /metrics"
ing.close()
print(f"decode OK: state bit-identical (zero-copy, sharded pack), host floor "
      f"TensorBatch {tb_rate/1e6:.1f}M -> zero-copy {zc_rate/1e6:.1f}M rec/s, "
      f"{sent} records through the live lanes ingester, busy gauge served")
EOF

echo "== autotune smoke: controller moves the feed, state stays bit-identical =="
# ISSUE 20: (a) a deterministic bursty-diurnal replay through two
# dict-wire exporters — one live-tuned (the same tick() the supervised
# thread runs), one controller-off — must land bit-identical sketch AND
# dict-table state at the window flush: every knob the controller
# touches changes only grouping/transfer shape, never the batch
# partition. (b) a LIVE ingester with cfg.autotune on must show the
# controller visibly moving coalesce_batches on /metrics while bursty
# replay traffic flows, with both gauge families valid exposition.
python - <<'EOF'
import socket, time, urllib.request
import numpy as np
import jax
from deepflow_tpu.enrich.platform_data import PlatformDataManager
from deepflow_tpu.pipelines import Ingester, IngesterConfig
from deepflow_tpu.replay.generator import bursty_diurnal
from deepflow_tpu.runtime.autotune import FeedAutotuner
from deepflow_tpu.runtime.promexpo import validate_exposition
from deepflow_tpu.runtime.tpu_sketch import TpuSketchExporter

# -- (a) bit-identity vs the controller-off twin -------------------------
ramp = bursty_diurnal(seed=3, rows_per_window=2048)
mk = lambda: TpuSketchExporter(store=None, window_seconds=3600,
                               batch_rows=1024, wire="dict",
                               prefetch_depth=2, coalesce_batches=2)
tuned, plain = mk(), mk()
assert tuned.zero_copy and plain.zero_copy
tuner = FeedAutotuner(tuned, interval_s=0.05)
for _w, _name, cols in ramp.windows():
    tuned.process([("l4_flow_log", 0, cols)])
    plain.process([("l4_flow_log", 0, cols)])
    assert tuned._feed.drain(30)
    tuner.tick(dt=0.05)
assert plain._feed.drain(30)
# compare at the WINDOW flush (the open k<K prefix ships there): the
# tuned stager may park more complete slots mid-stream at a wider
# group width, but the flush boundary is the consistency contract
outs = [e.flush_window() for e in (tuned, plain)]
for a, b in zip(jax.tree.leaves((outs[0], tuned.state, tuned._dict_state)),
                jax.tree.leaves((outs[1], plain.state, plain._dict_state))):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
assert tuner.ticks >= 10 and tuner.fallbacks == 0
ticks, trials = tuner.ticks, tuner.decisions + tuner.reverts
tuner.close(); tuned.close(); plain.close()

# -- (b) live ingester: the controller visibly moves coalesce_batches ----
ing = Ingester(IngesterConfig(
    listen_port=0, prom_port=0, tpu_sketch_window_s=5.0,
    tpu_sketch_wire="dict", autotune=True, autotune_interval_s=0.2),
    platform=PlatformDataManager())
assert ing.autotuner is not None, "cfg.autotune did not arm the controller"
ing.start()
ramp = bursty_diurnal(seed=5, rows_per_window=2048)
frames = []
for w in range(6):
    frames += ramp.l4_frames(w, per_frame=256)

def scrape():
    with urllib.request.urlopen(
            f"http://127.0.0.1:{ing.prom_port}/metrics", timeout=10) as r:
        return r.read().decode()

seen = set()
deadline = time.time() + 30.0
with socket.create_connection(("127.0.0.1", ing.port), timeout=5) as s:
    i = 0
    while time.time() < deadline:
        s.sendall(frames[i % len(frames)]); i += 1
        if i % 20 == 0:
            for line in scrape().splitlines():
                if line.startswith("deepflow_tpu_autotune_coalesce_batches "):
                    seen.add(float(line.split()[-1]))
            if len(seen) > 1:
                break
assert len(seen) > 1, f"controller never moved coalesce_batches: {seen}"
text = scrape()
assert not validate_exposition(text)
assert "# TYPE deepflow_tpu_autotune_coalesce_batches gauge" in text
assert "# TYPE deepflow_tpu_autotune_enabled gauge" in text
enabled = [ln for ln in text.splitlines()
           if ln.startswith("deepflow_tpu_autotune_enabled ")]
assert enabled and float(enabled[0].split()[-1]) == 1.0, enabled
# the stats-registered family: same series names the timeline samples
assert "deepflow_exporter_tpu_autotune_decisions" in text
assert "deepflow_exporter_tpu_autotune_coalesce_batches" in text
ing.close()
print(f"autotune OK: twin bit-identical over {ticks} ticks "
      f"({trials} trials), live coalesce values seen {sorted(seen)}")
EOF

echo "== audit smoke: exact-shadow recall + degraded conservation =="
# ISSUE 6: the accuracy observatory against a fixed-seed heavy-hitter
# replay. The full-rate exact shadow must score the live sketch's top-K
# recall >= 0.9 and hold every error inside its theoretical bound; then
# an injected tpu.device_error pushes the lane through a degraded
# (host-fallback) window, which must still be audited — tagged, kept
# out of the alarm — with the audit's row conservation intact
# (rows observed by the shadow == rows_in, loss included). The
# occupancy profiler must export a Perfetto-loadable timeline.
python - <<'EOF'
import json
import numpy as np
from deepflow_tpu.replay.generator import SyntheticAgent
from deepflow_tpu.runtime.faults import default_faults
from deepflow_tpu.runtime.profiler import default_profiler
from deepflow_tpu.runtime.tpu_sketch import TpuSketchExporter
from deepflow_tpu.runtime.tracing import default_tracer

tr = default_tracer(); tr.enable()
agent = SyntheticAgent(seed=0xC0FFEE)
cols = agent.l4_columns_pooled(60000, pool=512)
exp = TpuSketchExporter(store=None, window_seconds=3600, batch_rows=4096,
                        wire="lanes", prefetch_depth=2,
                        coalesce_batches=2, audit_rate=1.0)
for i in range(0, 60000, 10000):
    exp.process([("l4_flow_log", 0,
                  {k: v[i:i+10000] for k, v in cols.items()})])
exp.flush_window()
a = exp._audit
snap = a.last_window
assert snap["topk_recall"] >= 0.9, snap
assert tr.gauges()["tpu_audit_topk_recall"] >= 0.9
assert not snap["violation"] and not a.alarm, snap
assert snap["cms_rel_error"] <= a.cms_eps_theory, snap
assert a.rows_seen_total == exp.rows_in == 60000

# degraded window: inject device errors, lane falls to the host
# fallback; the audit keeps counting every row and tags the window
f = default_faults()
sites = f.arm_spec("tpu.device_error:count=2;seed=3")
exp.degrade_after = 1
more = agent.l4_columns_pooled(30000, pool=512)
for i in range(0, 30000, 10000):
    exp.process([("l4_flow_log", 0,
                  {k: v[i:i+10000] for k, v in more.items()})])
assert exp._feed.drain(30)
assert exp.device_errors >= 1 and exp.degraded, exp.counters()
exp.flush_window()
for s in sites:
    f.disarm(s)
assert a.degraded_windows >= 1 and a.last_window["degraded"]
assert not a.alarm and a._violations == 0      # tagged, never alarmed
assert a.rows_seen_total == exp.rows_in == 90000, (
    f"audit conservation broken: {a.rows_seen_total} != {exp.rows_in}")
trace = default_profiler().to_chrome_trace()
xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
assert xs and all({"ts", "dur", "pid", "tid"} <= set(e) for e in xs)
json.dumps(trace)
busy = default_profiler().busy_fraction()
exp.close(); tr.disable()
print(f"audit OK: recall {snap['topk_recall']}, cms_err "
      f"{snap['cms_rel_error']:.2e} (eps {a.cms_eps_theory:.2e}), "
      f"hll_err {snap['hll_rel_error']:.4f}, {a.degraded_windows} "
      f"degraded window(s) audited, conservation 90000/90000, "
      f"{len(xs)} trace events, device busy {busy:.2f}")
EOF

echo "== serving smoke: sketch read path vs live ingest =="
# ISSUE 7: the sketch-serving read plane against a live ingester at the
# chaos-smoke rate. A QuerierServer (supervised accept thread) mounts
# the SnapshotCache-backed sketch datasource; a concurrent query loop
# hammers SQL + PromQL + direct point reads WHILE frames flow. Gates:
# answers come back non-empty, the serving gauges land on /metrics with
# staleness <= max_staleness_s, the datasource listing shows the sketch
# tables, and the strict exposition checker stays green.
python - <<'EOF'
import json, socket, tempfile, threading, time, urllib.parse, urllib.request
import numpy as np
from deepflow_tpu.batch.schema import L4_SCHEMA
from deepflow_tpu.enrich.platform_data import PlatformDataManager
from deepflow_tpu.pipelines import Ingester, IngesterConfig
from deepflow_tpu.querier.server import QuerierServer
from deepflow_tpu.runtime.promexpo import validate_exposition
from deepflow_tpu.runtime.supervisor import default_supervisor
from deepflow_tpu.serving import SketchTables, SnapshotCache
from deepflow_tpu.wire import columnar_wire
from deepflow_tpu.wire.framing import FlowHeader, MessageType, encode_frame

MAX_STALE = 3.0
store = tempfile.mkdtemp(prefix="serving_store_")
ing = Ingester(IngesterConfig(listen_port=0, prom_port=0,
                              tpu_sketch_window_s=0.3, store_path=store),
               platform=PlatformDataManager())
ing.start()
cache = SnapshotCache(ing.tpu_sketch.snapshot_bus, max_staleness_s=MAX_STALE)
tables = SketchTables(cache)
tables.register_datasource()
q = QuerierServer(ing.store, ing.tag_dicts, port=0, sketch=tables)
q.start()
sup = [t for t in default_supervisor().threads()
       if t["name"] == "querier-http"]
assert sup and sup[0]["alive"] and sup[0]["crashes"] == 0, sup

r = np.random.default_rng(0)
cols = {name: r.integers(0, 1 << 8, 500).astype(dt)
        for name, dt in L4_SCHEMA.columns}
frame = encode_frame(MessageType.COLUMNAR_FLOW,
                     columnar_wire.encode_columnar(cols),
                     FlowHeader(sequence=1, vtap_id=3))

results = {"sql": 0, "prom": 0, "direct": 0, "errors": []}
stop = threading.Event()

def _query_loop():
    base = f"http://127.0.0.1:{q.port}"
    while not stop.is_set():
        try:
            body = urllib.parse.urlencode(
                {"sql": "SELECT sketch.topk(5) FROM sketch"}).encode()
            req = urllib.request.Request(f"{base}/v1/query", data=body)
            with urllib.request.urlopen(req, timeout=5) as resp:
                out = json.load(resp)
            if out.get("result", {}).get("values"):
                results["sql"] += 1
            qs = urllib.parse.urlencode({"query": "sketch_hll_card()"})
            with urllib.request.urlopen(f"{base}/api/v1/query?{qs}",
                                        timeout=5) as resp:
                out = json.load(resp)
            if out.get("status") == "success" and out["data"]["result"]:
                results["prom"] += 1
            for _ in range(200):    # the dashboard-QPS shape: point reads
                tables.cms_point(0xBEEF)
                results["direct"] += 1
        except Exception as e:      # noqa: BLE001 — smoke must report
            results["errors"].append(repr(e))
            time.sleep(0.05)

qt = threading.Thread(target=_query_loop, daemon=True)
qt.start()
sent = 0
deadline = time.time() + 5.0
with socket.create_connection(("127.0.0.1", ing.port), timeout=5) as s:
    while time.time() < deadline:
        s.sendall(frame); sent += 500
        time.sleep(0.02)
# let the last window flush + the query loop observe it, then scrape
time.sleep(0.7)
with urllib.request.urlopen(
        f"http://127.0.0.1:{ing.prom_port}/metrics", timeout=10) as resp:
    text = resp.read().decode()
stop.set(); qt.join(timeout=5)
problems = validate_exposition(text)
assert not problems, problems[:10]
assert results["sql"] > 0 and results["prom"] > 0, results
assert not results["errors"], results["errors"][:3]
for needle in ("deepflow_trace_querier_read_qps",
               "deepflow_trace_querier_read_p99_s",
               "deepflow_trace_sketch_snapshot_staleness_s"):
    assert needle in text, f"{needle} absent from /metrics"
stale = [float(line.split()[-1]) for line in text.splitlines()
         if line.startswith("deepflow_trace_sketch_snapshot_staleness_s ")]
assert stale and stale[0] <= MAX_STALE, \
    f"staleness bound violated: {stale} > {MAX_STALE}"
ds = ing.flow_metrics.rollups.list_datasources()
assert any(row.get("table") == "sketch.topk" for row in ds), ds
q.close()
tables.unregister_datasource()
ing.close()
print(f"serving OK: {sent} records ingested, {results['sql']} SQL + "
      f"{results['prom']} PromQL + {results['direct']} direct reads, "
      f"staleness {stale[0]:.2f}s <= {MAX_STALE}s")
EOF

echo "== pod chaos smoke: shard fault domains + epoch merges =="
# ISSUE 10: the pod fault-domain layer against a LIVE 8-device simulated
# mesh ingest. Seeded chaos kills one shard's device path until it
# degrades and stalls another shard's epoch contribution past the merge
# deadline. Gates: ingest on the surviving shards never blocks, /healthz
# names the degraded shard, the straggler's epoch closes without it
# (counted on /metrics), the shard pool recovers to 8/8, pod-wide
# conservation `sent == delivered + host + lost` holds off /metrics, and
# a serving sketch.topk answer carries the reduced shard participation.
python - <<'EOF'
import re, socket, time, urllib.request
import numpy as np
from deepflow_tpu.batch.schema import L4_SCHEMA
from deepflow_tpu.enrich.platform_data import PlatformDataManager
from deepflow_tpu.pipelines import Ingester, IngesterConfig
from deepflow_tpu.runtime.promexpo import validate_exposition
from deepflow_tpu.serving import SketchTables, SnapshotCache
from deepflow_tpu.wire import columnar_wire
from deepflow_tpu.wire.framing import FlowHeader, MessageType, encode_frame

def scrape(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as resp:
        return resp.read().decode()

def counter(text, name):
    m = re.search(rf"^{re.escape(name)} ([0-9.e+-]+)$", text, re.M)
    return None if m is None else float(m.group(1))

def healthz(port):
    import json
    req = urllib.request.Request(f"http://127.0.0.1:{port}/healthz")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:           # 503 carries the body
        import json as _j
        return e.code, _j.load(e)

ing = Ingester(IngesterConfig(
    listen_port=0, prom_port=0, tpu_sketch_window_s=0.6,
    tpu_sketch_pod_shards=8, pod_merge_deadline_s=1.0,
    fault_spec=("shard.device_error:count=3,match=shard2;"
                "merge.stall:count=1,delay_s=3.0,match=shard5;seed=13")),
    platform=PlatformDataManager())
assert ing.tpu_sketch.pod is not None
ing.start()
r = np.random.default_rng(0)
cols = {name: r.integers(0, 1 << 8, 500).astype(dt)
        for name, dt in L4_SCHEMA.columns}
frame = encode_frame(MessageType.COLUMNAR_FLOW,
                     columnar_wire.encode_columnar(cols),
                     FlowHeader(sequence=1, vtap_id=3))
cache = SnapshotCache(ing.tpu_sketch.snapshot_bus, max_staleness_s=3600)
tables = SketchTables(cache)
sent = 0
saw_degraded = saw_missed = False
deadline = time.time() + 45.0
with socket.create_connection(("127.0.0.1", ing.port), timeout=5) as s:
    while time.time() < deadline:
        s.sendall(frame); sent += 500
        code, h = healthz(ing.prom_port)
        if h.get("pod_shards_degraded") or h.get("pod_shards_lost"):
            saw_degraded = True
            assert code == 503 and not h["ok"], h   # probe sees it
        c = ing.tpu_sketch.counters()
        if c["pod_merge_missed"] >= 1:
            saw_missed = True
        if (saw_degraded and saw_missed
                and c["pod_shards_active"] == 8
                and c["pod_rows_delivered"] > 0
                and c["pod_device_errors"] >= 2):
            break
        time.sleep(0.05)
assert saw_degraded, "healthz never reported the degraded shard"
assert saw_missed, "the straggler was never excluded at the deadline"
# ingest on the surviving shards never blocked: everything sent was
# decoded and accounted (delivered/host/lost/pending), nothing wedged
deadline = time.time() + 15.0
while time.time() < deadline and ing.tpu_sketch.rows_in < sent:
    time.sleep(0.1)
assert ing.tpu_sketch.rows_in >= sent, \
    f"ingest stalled: {ing.tpu_sketch.rows_in} < {sent}"
# recovery: the shard pool is back to 8/8 on /healthz
deadline = time.time() + 20.0
while time.time() < deadline:
    code, h = healthz(ing.prom_port)
    if h.get("pod_shards_active") == 8 and h["ok"]:
        break
    time.sleep(0.2)
assert h["pod_shards_active"] == 8 and h["ok"], h
# conservation + exclusion counters off /metrics (one scrape)
text = scrape(ing.prom_port)
assert not validate_exposition(text)
P = "deepflow_exporter_tpu_sketch_"
sent_c = counter(text, P + "pod_rows_sent")
delivered = counter(text, P + "pod_rows_delivered")
host = counter(text, P + "pod_rows_host")
lost = counter(text, P + "pod_rows_lost")
pending = counter(text, P + "pod_rows_pending")
missed = counter(text, P + "pod_merge_missed")
assert None not in (sent_c, delivered, host, lost, pending, missed), \
    "pod counters absent from /metrics"
assert sent_c == delivered + host + lost + pending, \
    f"conservation broken: {sent_c} != {delivered}+{host}+{lost}+{pending}"
assert missed >= 1 and counter(text, P + "pod_late_merges") >= 1
for needle in ("deepflow_trace_pod_shards_active",
               "deepflow_trace_pod_merge_epoch_s",
               "deepflow_trace_pod_merge_missed"):
    assert needle in text, f"{needle} absent from /metrics"
# serving answers carry shard participation honestly
rows = tables.topk(5)
assert rows and "shards_active" in rows[0], rows[:1]
assert any(s.tags.get("pod_shards_participated", 8) < 8
           for s in cache.window_range(None, None)), \
    "no reduced-participation snapshot was ever published"
cache.close()
ing.close()
c = ing.tpu_sketch.counters()
assert c["pod_rows_pending"] == 0
assert c["pod_rows_sent"] == (c["pod_rows_delivered"] + c["pod_rows_host"]
                              + c["pod_rows_lost"])
print(f"pod OK: {sent} records, 8 shards, {int(c['pod_device_errors'])} "
      f"device error(s), {int(c['pod_merge_missed'])} missed "
      f"contribution(s), {int(c['pod_late_merges'])} late merge(s), "
      f"{int(c['pod_rows_lost'])} rows counted lost, conservation exact")
EOF

echo "== multihost chaos smoke: DCN partition + host kill + rejoin =="
# ISSUE 17: the cross-host pod against a LIVE 2-host simulated-DCN
# ingest. Seeded chaos severs host 1's DCN link at the first epoch
# marker (held, auto-healed after 2s) and kills the host on the first
# marker it DOES receive post-heal; the boundary rejoin brings it back.
# Gates: /healthz names the missing host (503), ingest never blocks,
# the partitioned epoch excludes the host counted, the kill rejoins to
# 2/2 hosts, pod-wide conservation `sent == delivered + host + lost +
# pending` holds off ONE /metrics scrape mid-chaos, and serving topk
# answers carry the reduced host participation.
python - <<'EOF'
import re, socket, time, urllib.request
import numpy as np
from deepflow_tpu.batch.schema import L4_SCHEMA
from deepflow_tpu.enrich.platform_data import PlatformDataManager
from deepflow_tpu.pipelines import Ingester, IngesterConfig
from deepflow_tpu.runtime.promexpo import validate_exposition
from deepflow_tpu.serving import SketchTables, SnapshotCache
from deepflow_tpu.wire import columnar_wire
from deepflow_tpu.wire.framing import FlowHeader, MessageType, encode_frame

def scrape(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as resp:
        return resp.read().decode()

def counter(text, name):
    m = re.search(rf"^{re.escape(name)} ([0-9.e+-]+)$", text, re.M)
    return None if m is None else float(m.group(1))

def healthz(port):
    import json
    req = urllib.request.Request(f"http://127.0.0.1:{port}/healthz")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:           # 503 carries the body
        import json as _j
        return e.code, _j.load(e)

ing = Ingester(IngesterConfig(
    listen_port=0, prom_port=0, tpu_sketch_window_s=0.6,
    tpu_sketch_pod_shards=2, pod_hosts=2, dcn_transport="sim",
    dcn_marker_deadline_s=1.0, dcn_heal_after_s=2.0,
    fault_spec=("dcn.partition:count=1,match=host1;"
                "host.lost:count=1,match=host1;seed=13")),
    platform=PlatformDataManager())
pod = ing.tpu_sketch.pod
assert pod is not None and hasattr(pod, "host_status")
ing.start()
r = np.random.default_rng(0)
cols = {name: r.integers(0, 1 << 8, 500).astype(dt)
        for name, dt in L4_SCHEMA.columns}
frame = encode_frame(MessageType.COLUMNAR_FLOW,
                     columnar_wire.encode_columnar(cols),
                     FlowHeader(sequence=1, vtap_id=3))
cache = SnapshotCache(ing.tpu_sketch.snapshot_bus, max_staleness_s=3600)
tables = SketchTables(cache)
sent = 0
saw_missing = saw_link_down = mid_chaos_conserved = False
deadline = time.time() + 60.0
with socket.create_connection(("127.0.0.1", ing.port), timeout=5) as s:
    while time.time() < deadline:
        s.sendall(frame); sent += 500
        code, h = healthz(ing.prom_port)
        if h.get("pod_hosts_lost"):
            saw_missing = True
            assert code == 503 and not h["ok"], h   # probe names it
            assert h["pod_hosts_lost"] == [1], h
        if h.get("pod_links_down"):
            saw_link_down = True
        c = ing.tpu_sketch.counters()
        if not mid_chaos_conserved and c["pod_hosts_missed"] >= 1:
            # conservation off ONE scrape while the chaos is live
            text = scrape(ing.prom_port)
            P = "deepflow_exporter_tpu_sketch_"
            terms = [counter(text, P + k) for k in
                     ("pod_rows_sent", "pod_rows_delivered",
                      "pod_rows_host", "pod_rows_lost",
                      "pod_rows_pending")]
            assert None not in terms, "pod host counters absent"
            assert terms[0] == sum(terms[1:]), \
                f"mid-chaos conservation broken: {terms}"
            mid_chaos_conserved = True
        if (saw_missing and mid_chaos_conserved
                and c["pod_host_rejoins"] >= 1
                and c["pod_hosts_active"] == 2
                and c["pod_rows_delivered"] > 0):
            break
        time.sleep(0.05)
assert saw_missing, "healthz never reported the lost host"
assert saw_link_down, "healthz never reported the severed DCN link"
assert mid_chaos_conserved, "the host was never excluded at the deadline"
# ingest never blocked on the dead/partitioned host
deadline = time.time() + 15.0
while time.time() < deadline and ing.tpu_sketch.rows_in < sent:
    time.sleep(0.1)
assert ing.tpu_sketch.rows_in >= sent, \
    f"ingest stalled: {ing.tpu_sketch.rows_in} < {sent}"
# recovery: both hosts active on /healthz
deadline = time.time() + 20.0
while time.time() < deadline:
    code, h = healthz(ing.prom_port)
    if h.get("pod_hosts_active") == 2 and h["ok"]:
        break
    time.sleep(0.2)
assert h["pod_hosts_active"] == 2 and h["ok"], h
# the full host ledger off /metrics (one scrape)
text = scrape(ing.prom_port)
assert not validate_exposition(text)
P = "deepflow_exporter_tpu_sketch_"
assert counter(text, P + "pod_hosts_missed") >= 1
assert counter(text, P + "dcn_partitions") >= 1
assert counter(text, P + "dcn_heals") >= 1
assert counter(text, P + "pod_hosts_killed") >= 1
assert counter(text, P + "pod_host_rejoins") >= 1
assert counter(text, P + "dcn_markers_sent") >= 1
# serving answers carry host participation honestly
rows = tables.topk(5)
assert rows and "hosts_active" in rows[0], rows[:1]
assert any(s.tags.get("pod_hosts_participated", 2) < 2
           for s in cache.window_range(None, None)), \
    "no reduced-host-participation snapshot was ever published"
cache.close()
ing.close()
c = ing.tpu_sketch.counters()
assert c["pod_rows_pending"] == 0
assert c["pod_rows_sent"] == (c["pod_rows_delivered"] + c["pod_rows_host"]
                              + c["pod_rows_lost"])
print(f"multihost OK: {sent} records, 2 hosts, "
      f"{int(c['dcn_partitions'])} partition(s), "
      f"{int(c['pod_hosts_killed'])} host kill(s), "
      f"{int(c['pod_host_rejoins'])} rejoin(s), "
      f"{int(c['pod_hosts_missed'])} missed epoch(s), "
      f"{int(c['pod_rows_lost'])} rows counted lost, conservation exact")
EOF

echo "== anomaly smoke: DDoS ramp detection + mid-attack device fault =="
# ISSUE 15: the anomaly plane against a LIVE ingester. The ddos_ramp
# profile streams over the socket window-by-window; a tpu.device_error
# is armed at attack onset (fires mid-attack on the next batch). Gates:
# the ramp is detected within <= 2 windows of onset, the detection
# lane's rows_seen == rows_in conservation holds through the fault, the
# faulted window is tagged (lossy/degraded), alerts are durable npz AND
# queryable through SQL + PromQL + the /metrics gauges, and the strict
# exposition checker stays green.
python - <<'EOF'
import json, socket, tempfile, time, urllib.parse, urllib.request
import numpy as np
from deepflow_tpu.enrich.platform_data import PlatformDataManager
from deepflow_tpu.pipelines import Ingester, IngesterConfig
from deepflow_tpu.querier.server import QuerierServer
from deepflow_tpu.replay.generator import ddos_ramp
from deepflow_tpu.runtime.faults import default_faults
from deepflow_tpu.runtime.promexpo import validate_exposition
from deepflow_tpu.serving import AnomalyTables, SnapshotCache
from deepflow_tpu.wire import columnar_wire
from deepflow_tpu.wire.framing import FlowHeader, MessageType, encode_frame
from deepflow_tpu.batch.schema import L4_SCHEMA

store = tempfile.mkdtemp(prefix="anomaly_store_")
# 1s windows: the default-config window close costs ~0.75s on a CPU
# box (full-width partial-slot flush — the bench anomaly phase numbers
# it), so a 0.3s cadence would lag and smear ramp windows together
WIN = 1.0
ing = Ingester(IngesterConfig(
    listen_port=0, prom_port=0, store_path=store,
    tpu_sketch_window_s=WIN, tpu_sketch_wire="lanes",
    anomaly_enabled=True, anomaly_warmup_windows=6),
    platform=PlatformDataManager())
ing.start()
plane = ing.tpu_sketch.anomaly
assert plane is not None

# collect alert windows + tags straight off the anomaly bus
alert_events, lossy_windows = [], []
def _collect(snap):
    if snap.tags.get("alerts"):
        alert_events.append((snap.step, snap.tags["alerts"]))
    if snap.tags.get("lossy") or snap.tags.get("degraded"):
        lossy_windows.append(snap.step)
plane.bus.subscribe(_collect)

cache = SnapshotCache(plane.bus, max_staleness_s=5.0)
tables = AnomalyTables(cache)
tables.register_datasource()
q = QuerierServer(ing.store, ing.tag_dicts, port=0, anomaly=tables)
q.start()

ramp = ddos_ramp(seed=7, rows_per_window=2048)
onset_plane_window = None
seq = 0
with socket.create_connection(("127.0.0.1", ing.port), timeout=5) as s:
    for w, phase, cols in ramp.windows():
        if w == ramp.onset_window:
            onset_plane_window = plane.windows
            # mid-attack chaos: the next sketch batch dies on device
            default_faults().arm("tpu.device_error", count=1)
        n = len(cols["ip_src"])
        wire_cols = {name: cols[name].astype(dt) if name in cols
                     else np.zeros(n, dt)
                     for name, dt in L4_SCHEMA.columns}
        for lo in range(0, n, 500):     # frame-size cap: 500 rows/frame
            chunk = {k: v[lo:lo + 500] for k, v in wire_cols.items()}
            seq += 1
            s.sendall(encode_frame(
                MessageType.COLUMNAR_FLOW,
                columnar_wire.encode_columnar(chunk),
                FlowHeader(sequence=seq, vtap_id=3)))
        time.sleep(WIN)
        if alert_events and w > ramp.onset_window + 1:
            break
time.sleep(2 * WIN)               # let the last windows flush

assert alert_events, "DDoS ramp never detected"
first_alert_window = alert_events[0][0]
latency = first_alert_window - onset_plane_window
assert 0 <= latency <= 2, (first_alert_window, onset_plane_window)
dets = {a["detector"] for _, alerts in alert_events for a in alerts}
assert "entropy_ddos" in dets, dets

# the injected device error really fired, was tagged, never silent
fc = default_faults().counters()
assert fc.get("tpu_device_error_fired", 0) == 1, fc
assert ing.tpu_sketch.lost_rows > 0
assert lossy_windows, "faulted window never tagged on the bus"
# conservation through the detection lane, exact at this instant
assert plane.rows_seen == ing.tpu_sketch.rows_in, \
    (plane.rows_seen, ing.tpu_sketch.rows_in)
assert plane.windows_unscored == 0 or plane.score_errors > 0
assert plane.alerts_shed == 0

# queryable: SQL + PromQL through the live querier routes
base = f"http://127.0.0.1:{q.port}"
body = urllib.parse.urlencode(
    {"sql": "SELECT * FROM anomaly"}).encode()
with urllib.request.urlopen(
        urllib.request.Request(f"{base}/v1/query", data=body),
        timeout=5) as resp:
    out = json.load(resp)
rows = out["result"]["values"]
assert any(r[2] == "entropy_ddos" and r[5] == 1 for r in rows), rows
qs = urllib.parse.urlencode(
    {"query": 'anomaly_score{detector="entropy_ddos"}'})
with urllib.request.urlopen(f"{base}/api/v1/query?{qs}",
                            timeout=5) as resp:
    out = json.load(resp)
assert out["status"] == "success" and out["data"]["result"], out
score = float(out["data"]["result"][0]["value"][1])

# durable: alert windows are fsynced npz under the anomaly checkpoint
import glob, os
npz = glob.glob(os.path.join(store, "anomaly_ckpt", "anomaly-*.npz"))
assert npz, "no durable alert snapshots on disk"

# gauges on /metrics, strict exposition
with urllib.request.urlopen(
        f"http://127.0.0.1:{ing.prom_port}/metrics", timeout=10) as resp:
    text = resp.read().decode()
problems = validate_exposition(text)
assert not problems, problems[:10]
for needle in ("deepflow_trace_anomaly_score",
               "deepflow_trace_anomaly_alerts_total",
               "deepflow_trace_anomaly_detect_latency_windows",
               "deepflow_trace_anomaly_active_flows"):
    assert needle in text, f"{needle} absent from /metrics"

q.close()
tables.unregister_datasource()
ing.close()
default_faults().disarm()
print(f"anomaly OK: detected in {latency} window(s) of onset "
      f"(score {score:.1f}), device fault tagged at windows "
      f"{sorted(set(lossy_windows))[:3]}, {len(npz)} durable alert "
      f"snapshot(s), conservation exact", flush=True)
# every gate above passed and everything is closed; interpreter-exit
# teardown of the XLA CPU client under this many wound-down threads
# intermittently aborts (std::terminate with no active exception) and
# is not what this smoke gates — exit hard on the verdict
import os as _os
_os._exit(0)
EOF

echo "== blackbox smoke: timeline + SLO burn + incident flight recorder =="
# ISSUE 16 end-to-end: a live ingester self-samples into the timeline
# while a seeded exporter.raise fault trips the flaky breaker; the
# trigger must capture EXACTLY ONE durable incident bundle whose
# manifest is valid and whose timeline window covers the trigger
# instant; PromQL (rate over a sketch counter, query_range over the
# device-busy gauge) and SQL (FROM timeline / FROM incidents) must
# answer over the live self-metrics through the QuerierServer HTTP
# routes; and /metrics must carry the slo_burn_rate family with HELP,
# strictly valid.
python - <<'EOF'
import json, os, socket, tempfile, time, urllib.parse, urllib.request
import numpy as np
from deepflow_tpu.batch.schema import L4_SCHEMA
from deepflow_tpu.enrich.platform_data import PlatformDataManager
from deepflow_tpu.pipelines import Ingester, IngesterConfig
from deepflow_tpu.querier.server import QuerierServer
from deepflow_tpu.runtime.faults import default_faults
from deepflow_tpu.runtime.promexpo import validate_exposition
from deepflow_tpu.wire import columnar_wire
from deepflow_tpu.wire.framing import FlowHeader, MessageType, encode_frame

class Flaky:
    name = "flaky"
    def start(self): pass
    def close(self): pass
    def is_export_data(self, stream, cols): return stream == "l4_flow_log"
    def put(self, stream, idx, cols): pass

store = tempfile.mkdtemp(prefix="blackbox_store_")
ing = Ingester(IngesterConfig(
    listen_port=0, prom_port=0, tpu_sketch_window_s=0.5, store_path=store,
    timeline_sample_s=0.1, breaker_min_calls=2, breaker_open_s=60.0,
    fault_spec="exporter.raise:p=1.0,for_s=5,match=flaky;seed=7"),
    platform=PlatformDataManager())
ing.exporters.register(Flaky())
ing.start()
q = QuerierServer(ing.store, ing.tag_dicts, port=0,
                  timeline=ing.timeline, incidents=ing.incidents)
q.start()

r = np.random.default_rng(0)
cols = {name: r.integers(0, 1 << 8, 500).astype(dt)
        for name, dt in L4_SCHEMA.columns}
frame = encode_frame(MessageType.COLUMNAR_FLOW,
                     columnar_wire.encode_columnar(cols),
                     FlowHeader(sequence=1, vtap_id=3))
sent = 0
deadline = time.time() + 12.0
with socket.create_connection(("127.0.0.1", ing.port), timeout=5) as s:
    while time.time() < deadline:
        s.sendall(frame); sent += 500
        if (ing.exporters.breakers()["flaky"]["trips"] >= 1
                and ing.incidents.counters()["captured"] >= 1
                and ing.timeline.ticks >= 70):  # >= 7s of 0.1s samples
                                                # for the range query
            break
        time.sleep(0.1)

# the seeded fault tripped the breaker and the watcher captured
# EXACTLY ONE durable bundle (the global rate limit collapses the
# correlated edges of this one bad moment)
br = ing.exporters.breakers()["flaky"]
assert br["trips"] >= 1, f"breaker never opened: {br}"
inc = ing.incidents.counters()
assert inc["captured"] == 1, inc
assert inc["capture_errors"] == 0 and inc["bundles"] == 1, inc
listing = ing.incidents.list()
assert len(listing) == 1, listing
m = listing[0]
assert m["version"] == 1 and m["kind"] == "breaker_open", m
bundle = m["path"]
for fname, size in m["files"].items():
    p = os.path.join(bundle, fname)
    assert os.path.getsize(p) == size, (fname, size)
# the bundle's timeline window covers the trigger instant, and the
# captured window actually carries self-metric series
lo, hi = m["window"]
assert lo <= m["wall_time"] <= hi, m
tj = json.load(open(os.path.join(bundle, "timeline.json")))
tl_metrics = {s["metric"] for s in tj["series"]}
assert "receiver_rx_frames" in tl_metrics, sorted(tl_metrics)[:20]
trg = json.load(open(os.path.join(bundle, "trigger.json")))
assert trg["kind"] == "breaker_open" and \
    trg["detail"]["breaker"] == "flaky", trg

base = f"http://127.0.0.1:{q.port}"
# PromQL over live self-metrics: rate() over the sketch-lane counter
qs = urllib.parse.urlencode({"query": "rate(tpu_sketch_rows_in[1m])"})
with urllib.request.urlopen(f"{base}/api/v1/query?{qs}", timeout=10) as resp:
    out = json.load(resp)
assert out["status"] == "success" and out["data"]["result"], out
assert float(out["data"]["result"][0]["value"][1]) > 0, out
# query_range over the profiler gauge: >= 5 grid points answered
now = int(time.time())
qs = urllib.parse.urlencode({"query": "tpu_device_busy_fraction",
                             "start": now - 5, "end": now, "step": 1})
with urllib.request.urlopen(f"{base}/api/v1/query_range?{qs}",
                            timeout=10) as resp:
    out = json.load(resp)
assert out["status"] == "success" and out["data"]["result"], out
vals = out["data"]["result"][0]["values"]
assert len(vals) >= 5, vals
# SQL over the rings and the bundle directory (POST /v1/query)
def sql(stmt):
    body = urllib.parse.urlencode({"sql": stmt}).encode()
    with urllib.request.urlopen(
            urllib.request.Request(f"{base}/v1/query", data=body),
            timeout=10) as resp:
        return json.load(resp)["result"]
rows = sql("SELECT * FROM timeline LIMIT 50")
assert rows["columns"] == ["time", "metric", "labels", "value", "tier"]
assert len(rows["values"]) == 50, len(rows["values"])
rows = sql("SELECT * FROM incidents")
assert len(rows["values"]) == 1 and rows["values"][0][2] == "breaker_open"
# /metrics: burn-rate family with HELP + staleness count, strictly valid
with urllib.request.urlopen(
        f"http://127.0.0.1:{ing.prom_port}/metrics", timeout=10) as resp:
    text = resp.read().decode()
assert not validate_exposition(text)
for needle in ("# HELP deepflow_slo_burn_rate",
               'deepflow_slo_burn_rate{slo="ingest_availability",window="fast"}',
               "deepflow_selfmetric_stale",
               "deepflow_timeline_samples",
               "deepflow_incidents_captured"):
    assert needle in text, f"{needle} absent from /metrics"
ticks = ing.timeline.ticks
q.close()
ing.close()
default_faults().disarm()
print(f"blackbox OK: {sent} records sent, {ticks} sampler ticks, "
      f"breaker {br['trips']} trip(s), 1 incident bundle "
      f"({len(m['files'])} files), query_range {len(vals)} samples",
      flush=True)
import os as _os
_os._exit(0)
EOF

# the offline CLI over the same bundle directory (capture, then grep:
# grep -q on a live pipe EPIPEs the CLI under pipefail)
BB_STORE=$(ls -dt /tmp/blackbox_store_* | head -1)
BB_LIST=$(python -m deepflow_tpu.cli incident list --dir "$BB_STORE/incidents")
echo "$BB_LIST" | grep -q breaker_open
BB_ID=$(echo "$BB_LIST" | grep -o 'inc-[a-z0-9_-]*' | head -1)
python -m deepflow_tpu.cli incident show --dir "$BB_STORE/incidents" \
  --id "$BB_ID" > /tmp/bb_show.json
grep -q '"kind": "breaker_open"' /tmp/bb_show.json
python -m deepflow_tpu.cli incident export --dir "$BB_STORE/incidents" \
  --id "$BB_ID" --out /tmp/bb_incident.tar.gz
tar -tzf /tmp/bb_incident.tar.gz | grep -q manifest.json
echo "incident CLI OK: $BB_ID listed, shown, exported"

echo "== driver entry points =="
python - <<'EOF'
import jax
import __graft_entry__ as g
g.dryrun_multichip(8)
fn, args = g.entry()
jax.jit(fn)(*args)
print("entry + 8-device dryrun ok")
EOF

if [ "${1:-}" != "quick" ]; then
  echo "== TSAN: native decoder MT path =="
  # the one native component with real concurrency; any data race aborts
  # with ThreadSanitizer's report (SURVEY.md §4: beat the reference's
  # go -race bar on the ported hot path)
  if ! command -v g++ >/dev/null; then
    echo "(g++ unavailable; TSAN step skipped)"
  else
    # a real compile failure must FAIL CI, not silently skip the gate
    g++ -O1 -g -fsanitize=thread -std=c++17 \
      deepflow_tpu/decode/native_src/tsan_harness.cc \
      -o /tmp/tsan_decoder -lpthread
    python - <<'PYEOF'
from deepflow_tpu.replay.generator import SyntheticAgent
from deepflow_tpu.wire.codec import pack_pb_records
agent = SyntheticAgent()
cols, records = agent.l4_batch(50000)
records = list(records)
# corrupt a scattered subset so every worker's region has gaps: the MT
# decoder's memmove compaction (decoder.cc df_decode_l4_mt) only runs
# when bad records leave regions sparse — a clean payload would let a
# compaction race pass TSAN vacuously. Two failure shapes: garbage wire
# bytes, and a well-formed record with no Flow field.
for i in range(0, len(records), 97):
    records[i] = b"\xff" * len(records[i])
for i in range(31, len(records), 193):
    records[i] = b"\x08\x01"
open("/tmp/tsan_payload.bin", "wb").write(pack_pb_records(records))
PYEOF
    /tmp/tsan_decoder /tmp/tsan_payload.bin 500
  fi

  echo "== kernel microbenches (CPU shapes) =="
  python benches/kernel_bench.py --batch 262144 --iters 6

  echo "== headline bench smoke (small shapes, CPU) =="
  # the scoreboard harness itself is product surface: a regression in
  # the window/selection/pipelined-decode machinery must fail CI, not
  # the end-of-round driver run
  DEEPFLOW_BENCH_SMALL=1 python bench.py > /tmp/bench_smoke.json
  python - <<'PYEOF'
import json
d = json.load(open("/tmp/bench_smoke.json"))
assert d["value"] > 0 and d["topk_recall_vs_exact"] >= 0.99, d
assert d["lane_windows"] and d["headline_window"] is not None
# per-lane transfer/kernel attribution must always be present and
# non-zero for BOTH wire lanes (the dict-lane chip measurement)
for lane in ("packed", "dict"):
    sb = d["stage_breakdown"][lane]
    assert sb["h2d_mb_s"] > 0 and sb["kernel_records_per_sec"] > 0, sb
# the degraded-mode floor must be measured, not asserted by docstring
assert d["stage_breakdown"]["host_fallback"]["records_per_sec"] > 0
# the audit overhead must be measured too (ISSUE 6 acceptance: <5% on
# TPU at the default rate; CPU smoke only asserts the measurement runs)
audit = d["stage_breakdown"]["audit"]
assert audit["records_per_sec"] > 0 and 0 <= audit["overhead_frac"] <= 1
# the host decode->staging floor (ISSUE 9): both paths measured, the
# feed phase runs zero-copy with the TensorBatch reference beside it
dec = d["stage_breakdown"]["decode"]
assert dec["tensorbatch_records_per_sec"] > 0, dec
assert dec["zero_copy_records_per_sec"] > 0, dec
assert dec["zero_copy_pooled_records_per_sec"] > 0, dec
fo = d["stage_breakdown"]["feed_overlap"]
assert fo["zero_copy"] == 1 and fo["records_per_sec_tensorbatch"] > 0, fo
# dict-wire zero-copy parity (ISSUE 20): the DEFAULT wire runs staged
# (one coalesced h2d per group, so <= 1 transfer/batch — a backend-
# independent structural property) with the inline reference measured
# beside it; the >= 1.5x speedup bar is the dev-box (TPU) acceptance,
# CPU smoke asserts the measurement runs and the transfer ceiling holds
dzc = d["stage_breakdown"]["dict_zero_copy"]
assert dzc["zero_copy"] == 1 and dzc["records_per_sec"] > 0, dzc
assert dzc["records_per_sec_inline"] > 0 and dzc["zero_copy_speedup"] > 0, dzc
assert dzc["transfers_per_batch"] <= 1.0, dzc
# the self-tuning feed (ISSUE 20): within ~10% of the best static
# config at every phase is the dev-box acceptance; CPU small shapes
# are noisy, so the smoke gates every phase measured, a looser ratio
# floor, and that the controller never took its safe fallback
at = d["stage_breakdown"]["autotune"]
assert set(at["phases"]) == {"trough", "rise", "peak", "burst",
                             "fall", "night"}, at
assert all(p["autotuned_records_per_sec"] > 0
           for p in at["phases"].values()), at
assert at["min_ratio_vs_best_static"] >= 0.5, at
assert at["fallbacks"] == 0, at
# the pod merge-epoch phase (ISSUE 10): clean epochs merge with full
# participation, and one injected straggler provably bounds the merge
# at the deadline (excluded + counted) instead of stalling the pod
pm = d["stage_breakdown"]["pod_merge"]
assert pm["shards"] >= 2 and pm["clean"]["records_per_sec"] > 0, pm
assert pm["clean"]["shards_participated"] == pm["shards"], pm
assert pm["clean"]["merge_missed"] == 0, pm
assert pm["clean"]["delivered_frac"] == 1.0, pm
assert pm["one_straggler"]["merge_missed"] >= 1, pm
# deadline-bounded: the epoch closed at ~the 10s deadline, nowhere
# near the injected 60s stall
assert pm["one_straggler"]["merge_epoch_s"] < 30.0, pm
assert pm["one_straggler"]["delivered_frac"] < 1.0, pm
assert pm["topk_recall_vs_exact"] >= 0.9, pm
# the cross-host DCN merge (ISSUE 17 acceptance): 2 simulated hosts
# merge clean at full participation, and one injected marker loss
# excludes the host at ~the marker deadline (counted) instead of
# stalling the pod — the close stays deadline-bounded
mh = d["stage_breakdown"]["multihost_merge"]
assert mh["hosts"] == 2 and mh["clean"]["records_per_sec"] > 0, mh
assert mh["clean"]["hosts_participated"] == 2, mh
assert mh["clean"]["hosts_missed"] == 0, mh
assert mh["clean"]["delivered_frac"] == 1.0, mh
assert mh["one_marker_loss"]["markers_lost"] >= 1, mh
assert mh["one_marker_loss"]["hosts_missed"] >= 1, mh
assert mh["one_marker_loss"]["hosts_participated"] == 1, mh
assert mh["one_marker_loss"]["delivered_frac"] < 1.0, mh
assert mh["one_marker_loss"]["epoch_close_s"] < 30.0, mh
# the anomaly plane (ISSUE 15 acceptance): the detection lane adds
# < 5% to window-close latency at the default config, the ramp is
# detected within <= 2 windows of onset, and the detection lane's
# row ledger conserves
an = d["stage_breakdown"]["anomaly"]
assert an["window_close_ms_on"] > 0 and an["window_close_ms_off"] > 0, an
assert an["overhead_frac"] < 0.05, an
assert an["detect_latency_windows"] is not None \
    and an["detect_latency_windows"] <= 2, an
assert an["rows_conserved"] is True, an
# the self-telemetry sampler (ISSUE 16 acceptance): one tick of the
# production-shaped rule set costs < 1% of the window close it rides
# beside, with the series actually populated
tl = d["stage_breakdown"]["timeline"]
assert tl["window_close_ms"] > 0 and tl["sampler_tick_ms"] > 0, tl
assert tl["overhead_frac"] < 0.01, tl
assert tl["series"] >= 5 and tl["samples"] > 0, tl
# the serving read path (ISSUE 7 acceptance): >= 50k point-query QPS
# against a live ingest, with the read-hammered run's sketch state
# bit-identical to the no-readers twin
srv = d["stage_breakdown"]["serving"]
assert srv["point_query_qps"] >= 50_000, srv
assert srv["bit_identical_vs_no_readers"] is True, srv
assert srv["read_p99_s"] > 0 and srv["reads"] > 0, srv
print("bench smoke OK:", d["value"], "rec/s (CPU small),",
      "dict kernel", d["stage_breakdown"]["dict"]["kernel_records_per_sec"],
      "rec/s")
PYEOF
fi

echo "CI OK"

"""FlowAggr: 1m aggregation of per-tick flow rows before the wire.

Reference: `agent/src/collector/flow_aggr.rs` — the flow-log fork of
the hot path aggregates the FlowMap's 1s TaggedFlows per flow over a
minute and ships ONE l4_flow_log row per flow per minute (the 1s
stream keeps feeding the metrics fork untouched). 60x fewer rows hit
the ingester for long-lived flows; short flows still emit promptly on
close.

Columnar redesign: the stash is a slot-indexed column table (exactly
the FlowMap discipline — flow_id -> slot dict is the only per-flow
Python), and each tick's output batch merges in one vectorized pass
per column class:

  sum:   byte/packet/retrans counters, perf *_sum/*_count,
         zero-window + handshake counters
  max:   perf *_max, one-shot rtt estimates, close_type, is_new_flow
  min:   start_time
  first: identity columns (5-tuple, ids, tap_side, ...)

`add(cols, now_ns)` returns the columns to EMIT NOW: rows that closed
this tick (merged with their stashed history) plus every stashed flow
whose aggregation bucket just ended (forced report, close_type 0 —
the same semantics tick_columns itself uses). `duration` is
recomputed as max(start+duration) - min(start) across merged rows.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

_SUM_KEYS = ("byte_tx", "byte_rx", "packet_tx", "packet_rx", "retrans",
             "retrans_tx", "retrans_rx", "rtt_client_sum",
             "rtt_client_count", "rtt_server_sum", "rtt_server_count",
             "srt_sum", "srt_count", "art_sum", "art_count", "cit_sum",
             "cit_count", "zero_win_tx", "zero_win_rx", "syn_count",
             "synack_count", "retrans_syn", "retrans_synack")
_MAX_KEYS = ("rtt", "rtt_client", "rtt_server", "srt_max", "art_max",
             "cit_max", "close_type", "is_new_flow", "status")
_MIN_KEYS = ("start_time",)
# everything else: first value wins (identity columns)


class FlowAggr:
    """Per-flow interval aggregation with columnar stash."""

    def __init__(self, interval_s: int = 60, capacity: int = 1024) -> None:
        if interval_s <= 0:
            raise ValueError("interval_s must be positive")
        self.interval_s = interval_s
        self._capacity = max(capacity, 16)
        self._slot: Dict[int, int] = {}
        self._free: List[int] = []
        self._cols: Dict[str, np.ndarray] = {}
        self._end: Optional[np.ndarray] = None    # max(start+duration)
        self._live = np.zeros(0, np.bool_)
        self._bucket = -1
        self.rows_in = 0
        self.rows_out = 0

    # -- internals ---------------------------------------------------------
    def _ensure_layout(self, cols: Dict[str, np.ndarray]) -> None:
        if self._cols:
            return
        n = self._capacity
        for k, v in cols.items():
            self._cols[k] = np.zeros(n, v.dtype)
        self._end = np.zeros(n, np.uint64)
        self._live = np.zeros(n, np.bool_)

    def _grow(self) -> None:
        n = len(self._live)
        for k, v in self._cols.items():
            nv = np.zeros(n * 2, v.dtype)
            nv[:n] = v
            self._cols[k] = nv
        ne = np.zeros(n * 2, np.uint64)
        ne[:n] = self._end
        self._end = ne
        nl = np.zeros(n * 2, np.bool_)
        nl[:n] = self._live
        self._live = nl

    def _allocate(self, fid: int) -> int:
        if self._free:
            s = self._free.pop()
        else:
            s = len(self._slot)
            while s < len(self._live) and self._live[s]:
                s += 1
            while s >= len(self._live):
                self._grow()
        self._slot[fid] = s
        self._live[s] = True
        return s

    def _emit(self, slots: np.ndarray) -> Dict[str, np.ndarray]:
        out = {k: v[slots].copy() for k, v in self._cols.items()}
        out["duration"] = (self._end[slots]
                           - out["start_time"]).astype(np.uint64)
        self.rows_out += len(slots)
        for s in slots.tolist():
            self._live[s] = False
            self._free.append(s)
        fids = out["flow_id"].tolist()
        for f in fids:
            self._slot.pop(int(f), None)
        return out

    # -- API ---------------------------------------------------------------
    def add(self, cols: Dict[str, np.ndarray],
            now_ns: Optional[int] = None) -> Optional[Dict[str, np.ndarray]]:
        """Fold one tick's flow columns in; returns columns to emit now
        (None when nothing is due). The input batch has at most one row
        per flow_id (tick_columns emits each flow once)."""
        now_ns = int(time.time() * 1e9) if now_ns is None else now_ns
        emit_parts: List[Dict[str, np.ndarray]] = []

        # bucket boundary FIRST: stashed flows from the previous bucket
        # flush as forced reports before this tick's rows merge in
        bucket = now_ns // (self.interval_s * 1_000_000_000)
        if bucket != self._bucket:
            if self._bucket >= 0 and self._live.any():
                emit_parts.append(self._emit(np.nonzero(self._live)[0]))
            self._bucket = bucket

        n = len(cols.get("flow_id", ()))
        if n:
            self.rows_in += n
            self._ensure_layout(cols)
            fids = cols["flow_id"].astype(np.uint64)
            get = self._slot.get
            known = np.fromiter((get(int(f), -1) for f in fids),
                                dtype=np.int64, count=n)
            fresh = known < 0
            # fresh flows: allocate + assign every column verbatim
            fresh_idx = np.nonzero(fresh)[0]
            if len(fresh_idx):
                slots = np.fromiter(
                    (self._allocate(int(f)) for f in fids[fresh_idx]),
                    dtype=np.int64, count=len(fresh_idx))
                for k, v in cols.items():
                    self._cols[k][slots] = v[fresh_idx]
                self._end[slots] = (
                    cols["start_time"][fresh_idx].astype(np.uint64)
                    + cols["duration"][fresh_idx].astype(np.uint64))
                known[fresh_idx] = slots
            # known flows: merge per column class
            old_idx = np.nonzero(~fresh)[0]
            if len(old_idx):
                slots = known[old_idx]
                for k, v in cols.items():
                    dst = self._cols.get(k)
                    if dst is None:
                        continue
                    nv = v[old_idx]
                    if k in _SUM_KEYS:
                        dst[slots] += nv.astype(dst.dtype)
                    elif k in _MAX_KEYS:
                        dst[slots] = np.maximum(dst[slots],
                                                nv.astype(dst.dtype))
                    elif k in _MIN_KEYS:
                        dst[slots] = np.minimum(dst[slots],
                                                nv.astype(dst.dtype))
                    # else: identity — first value stands
                self._end[slots] = np.maximum(
                    self._end[slots],
                    cols["start_time"][old_idx].astype(np.uint64)
                    + cols["duration"][old_idx].astype(np.uint64))
            # rows that closed THIS tick leave immediately, merged
            closed = cols["close_type"].astype(np.int64) > 0
            if closed.any():
                emit_parts.append(self._emit(known[np.nonzero(closed)[0]]))

        if not emit_parts:
            return None
        if len(emit_parts) == 1:
            return emit_parts[0]
        return {k: np.concatenate([p[k] for p in emit_parts])
                for k in emit_parts[0]}

    def flush(self) -> Optional[Dict[str, np.ndarray]]:
        """Force-emit everything (shutdown: the final tick must not
        strand stashed flows)."""
        if not self._live.any():
            return None
        return self._emit(np.nonzero(self._live)[0])

    def counters(self) -> dict:
        # same key set as the agent's disabled-state fallback, so the
        # DFSTATS column shape is stable across hot-switches
        return {"rows_in": self.rows_in, "rows_out": self.rows_out,
                "stashed": int(self._live.sum()), "enabled": 1}

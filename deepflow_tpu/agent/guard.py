"""Guard: self-imposed resource limits + escape-to-safe behavior.

Reference: agent/src/utils/guard.rs — a watchdog thread enforces the
controller-set cpu/memory limits (graceful self-termination on breach,
:174,:205-312) and the synchronizer's escape timer reverts to a safe
config when the controller goes silent. Here breach and escape invoke
callbacks so the orchestrator decides (stop capture / shrink batches)
instead of killing the process outright.
"""

from __future__ import annotations

import resource
import threading
import time
from typing import Callable, List, Optional


class Guard:
    def __init__(self, max_memory_mb: int = 768,
                 max_cpu_fraction: float = 1.0,
                 check_interval: float = 10.0) -> None:
        self.max_memory_mb = max_memory_mb
        self.max_cpu_fraction = max_cpu_fraction
        self.check_interval = check_interval
        self.on_breach: List[Callable[[str], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_cpu = 0.0
        self._last_wall = 0.0
        self.breaches = 0

    def set_limits(self, max_memory_mb: int,
                   max_cpu_fraction: float) -> None:
        """Hot-applied from pushed config (reference: ConfigHandler)."""
        self.max_memory_mb = max_memory_mb
        self.max_cpu_fraction = max_cpu_fraction

    @staticmethod
    def current_rss_mb() -> float:
        """Live RSS (not ru_maxrss, whose high-water mark never drops —
        one transient spike would latch a permanent breach)."""
        try:
            with open("/proc/self/statm") as f:
                pages = int(f.read().split()[1])
            return pages * resource.getpagesize() / (1024 * 1024)
        except (OSError, ValueError, IndexError):
            ru = resource.getrusage(resource.RUSAGE_SELF)
            return ru.ru_maxrss / 1024  # fallback: peak (linux KiB)

    def check_once(self) -> Optional[str]:
        """Returns a breach description or None."""
        ru = resource.getrusage(resource.RUSAGE_SELF)
        rss_mb = self.current_rss_mb()
        if rss_mb > self.max_memory_mb:
            return f"memory {rss_mb:.0f}MiB > limit {self.max_memory_mb}MiB"
        cpu = ru.ru_utime + ru.ru_stime
        wall = time.monotonic()
        if self._last_wall:
            dw = wall - self._last_wall
            if dw > 0:
                frac = (cpu - self._last_cpu) / dw
                if frac > self.max_cpu_fraction:
                    self._last_cpu, self._last_wall = cpu, wall
                    return (f"cpu {frac:.2f} cores > limit "
                            f"{self.max_cpu_fraction:.2f}")
        self._last_cpu, self._last_wall = cpu, wall
        return None

    def start(self) -> None:
        # supervised (ISSUE 14 baseline burn-down): a raising breach
        # callback used to kill the guard silently — no RSS ceiling, no
        # CPU cap, forever; now it's crash-captured and restarted
        from deepflow_tpu.runtime.supervisor import default_supervisor
        self._thread = default_supervisor().spawn(
            "guard", self._run, beat_period_s=self.check_interval)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.stop()
            self._thread.join(timeout=2)

    def _run(self) -> None:
        from deepflow_tpu.runtime.supervisor import default_supervisor
        sup = default_supervisor()
        while not self._stop.wait(self.check_interval):
            sup.beat()
            breach = self.check_once()
            if breach is not None:
                self.breaches += 1
                for fn in self.on_breach:
                    fn(breach)

    def counters(self) -> dict:
        ru = resource.getrusage(resource.RUSAGE_SELF)
        return {"rss_mb": ru.ru_maxrss / 1024, "breaches": self.breaches}


class EscapeTimer:
    """Revert to safe defaults when controller sync goes silent
    (reference: synchronizer.rs escape timer)."""

    def __init__(self, escape_after_s: float,
                 on_escape: Callable[[], None]) -> None:
        self.escape_after_s = escape_after_s
        self.on_escape = on_escape
        self._last_sync = time.monotonic()
        self._escaped = False

    def on_sync_ok(self) -> None:
        self._last_sync = time.monotonic()
        self._escaped = False

    def check(self) -> bool:
        if not self._escaped and \
                time.monotonic() - self._last_sync > self.escape_after_s:
            self._escaped = True
            self.on_escape()
        return self._escaped

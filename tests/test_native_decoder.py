"""Native C++ decoder: parity with the Python oracle + robustness."""

import numpy as np
import pytest

from deepflow_tpu.decode import columnar, native
from deepflow_tpu.replay.generator import SyntheticAgent
from deepflow_tpu.wire.codec import pack_pb_records

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native decoder unavailable: {native.build_error()}")


def test_parity_with_python_decoder():
    agent = SyntheticAgent()
    _, records = agent.l4_batch(500)
    want = columnar.decode_l4_records(records)
    got, bad = native.decode_l4_payload(pack_pb_records(records))
    assert bad == 0
    for name in want:
        assert got[name].dtype == want[name].dtype, name
        np.testing.assert_array_equal(got[name], want[name], err_msg=name)


def test_capacity_chunking():
    agent = SyntheticAgent()
    _, records = agent.l4_batch(300)
    got, bad = native.decode_l4_payload(pack_pb_records(records),
                                        capacity=64)
    assert bad == 0
    assert len(got["ip_src"]) == 300
    want = columnar.decode_l4_records(records)
    np.testing.assert_array_equal(got["byte_tx"], want["byte_tx"])


def test_bad_records_skipped():
    agent = SyntheticAgent()
    _, records = agent.l4_batch(10)
    records[3] = b"\xff\xff\xff garbage"
    got, bad = native.decode_l4_payload(pack_pb_records(records))
    assert bad == 1
    assert len(got["ip_src"]) == 9


def test_truncated_payload():
    agent = SyntheticAgent()
    _, records = agent.l4_batch(5)
    payload = pack_pb_records(records)
    got, bad = native.decode_l4_payload(payload[:-7])
    assert bad == 1
    assert len(got["ip_src"]) == 4


def test_empty_payload():
    got, bad = native.decode_l4_payload(b"")
    assert bad == 0 and len(got["ip_src"]) == 0


def test_v6_fold_agrees_across_paths():
    """Capture, the Python wire decoder, and the C++ decoder must all
    produce the SAME class-E-confined u32 for one v6 address."""
    import struct

    import numpy as np

    from deepflow_tpu.agent.packet import decode_packets
    from deepflow_tpu.store.dict_store import fold_ipv6

    src16 = bytes(range(100, 116))
    dst16 = bytes(range(116, 132))
    tcp = struct.pack(">HHIIBBHHH", 443, 55000, 7, 0, 0x50, 0x10,
                      8192, 0, 0)
    ip6 = struct.pack(">IHBB", 0x60000000, len(tcp), 6, 64) \
        + src16 + dst16
    frame = b"\x02" * 6 + b"\x04" * 6 + b"\x86\xdd" + ip6 + tcp
    cap = decode_packets([frame])
    assert cap["ip_src"][0] == fold_ipv6(src16)

    from deepflow_tpu.decode import native
    from deepflow_tpu.decode.columnar import decode_l4_records
    from deepflow_tpu.wire.codec import pack_pb_records
    from deepflow_tpu.wire.gen import flow_log_pb2

    d = flow_log_pb2.TaggedFlow()
    d.flow.flow_key.ip6_src = src16
    d.flow.flow_key.ip6_dst = dst16
    d.flow.flow_key.port_src = 443
    d.flow.flow_key.port_dst = 55000
    rec = d.SerializeToString()
    py = decode_l4_records([rec])
    assert py["ip_src"][0] == fold_ipv6(src16)
    assert py["ip_dst"][0] == fold_ipv6(dst16)
    if native.available():
        payload = pack_pb_records([rec])
        n32 = len(native.L4_COLS32)
        n64 = len(native.L4_COLS64)
        buf32 = np.empty((n32, 8), np.uint32)
        buf64 = np.empty((n64, 8), np.uint64)
        rows, bad, _ = native.decode_l4_into(payload, buf32, buf64)
        assert rows == 1
        names32 = [n for n, _ in native.L4_COLS32]
        assert buf32[names32.index("ip_src"), 0] == fold_ipv6(src16)
        assert buf32[names32.index("ip_dst"), 0] == fold_ipv6(dst16)


def test_round3_column_goldens():
    """New round-3 columns: tunnel MACs, acl_gids, derived status /
    retrans_syn[ack] / l7_error — exact values through BOTH decoders
    (the reference derivations: l4_flow_log.go :857 getStatus, :960
    handshake retrans, :926 l7_error)."""
    from deepflow_tpu.wire.gen import flow_log_pb2

    def rec(close_type, proto, syn=0, synack=0, gids=(),
            cli_err=0, srv_err=0):
        m = flow_log_pb2.TaggedFlow()
        f = m.flow
        f.flow_key.proto = proto
        f.flow_key.ip_src = 1
        f.flow_key.ip_dst = 2
        f.close_type = close_type
        f.start_time = 1_000_000_000
        f.end_time = 2_000_000_000
        t = f.tunnel
        t.tx_mac0, t.tx_mac1 = 0x0000AABB, 0xCCDDEEFF
        t.rx_mac0, t.rx_mac1 = 0x00001122, 0x33445566
        f.acl_gids.extend(gids)
        if syn or synack or cli_err or srv_err:
            f.has_perf_stats = 1
            f.perf_stats.tcp.syn_count = syn
            f.perf_stats.tcp.synack_count = synack
            f.perf_stats.l7.err_client_count = cli_err
            f.perf_stats.l7.err_server_count = srv_err
        return m.SerializeToString()

    records = [
        rec(1, 6, syn=3, synack=2, gids=(7, 9)),   # FIN -> status 0
        rec(3, 6),                                 # TCP timeout -> 3
        rec(3, 17),                                # UDP timeout -> 0
        rec(2, 6, cli_err=2, srv_err=5),           # RST -> 3
    ]
    want = columnar.decode_l4_records(records)
    got, bad = native.decode_l4_payload(pack_pb_records(records))
    assert bad == 0
    for name in want:
        np.testing.assert_array_equal(got[name], want[name], err_msg=name)
    assert got["status"].tolist() == [0, 3, 0, 3]
    assert got["retrans_syn"].tolist() == [2, 0, 0, 0]
    assert got["retrans_synack"].tolist() == [1, 0, 0, 0]
    assert got["acl_gids"].tolist() == [7, 0, 0, 0]
    assert got["l7_error"].tolist() == [0, 0, 0, 7]
    assert got["tunnel_tx_mac"].tolist() == [0x0000AABBCCDDEEFF] * 4
    assert got["tunnel_rx_mac"].tolist() == [0x0000112233445566] * 4


def test_fuzz_hostile_payloads_never_crash():
    """Deterministic fuzz: random bytes, truncated/corrupted real
    records, and pathological length prefixes must never crash the C++
    walker or overrun buffers (bad counts rise instead), across both
    ST and MT paths, matching the python oracle's row count."""
    rng = np.random.default_rng(0xFADE)
    agent = SyntheticAgent()
    _, real = agent.l4_batch(64)
    payloads = []
    # pure garbage
    for n in (0, 1, 3, 4, 5, 64, 4096):
        payloads.append(rng.bytes(n))
    # length prefix pointing past the end
    payloads.append((1 << 20).to_bytes(4, "little") + b"x" * 32)
    # real records with random corruption
    for _ in range(20):
        recs = list(real)
        for _ in range(8):
            i = int(rng.integers(0, len(recs)))
            b = bytearray(recs[i])
            if len(b):
                j = int(rng.integers(0, len(b)))
                b[j] = int(rng.integers(0, 256))
            recs[i] = bytes(b)
        payloads.append(pack_pb_records(recs))
    # truncations of a valid payload
    whole = pack_pb_records(real)
    for cut in (1, 7, len(whole) // 3, len(whole) - 1):
        payloads.append(whole[:cut])

    for payload in payloads:
        for threads in (1, 4):
            got, bad = native.decode_l4_payload(payload,
                                                n_threads=threads)
            rows = len(got["ip_src"])
            assert rows + bad >= 0           # no crash is the real assert
            # oracle agreement on well-formed-record COUNT: the python
            # decoder skips exactly the records the walker rejects,
            # except byte-corrupted ones that remain valid protobuf
            # with unknown fields — so only assert bounds
            assert rows <= 64


def test_pipelined_decoder_matches_serial():
    """PipelinedDecoder (feeder-thread overlap) yields byte-identical
    column data to serial decode_l4_into across a payload stream long
    enough to cycle every ring slot several times, and consuming slowly
    never lets the feeder overwrite a buffer still held."""
    agent = SyntheticAgent()
    base = agent.l4_columns(512)
    recs = [agent.l4_record(base, i) for i in range(512)]
    payloads = [pack_pb_records(recs[i::8]) for i in range(8)] * 3
    n32, n64 = len(native.L4_COLS32), len(native.L4_COLS64)
    want = []
    b32 = np.empty((n32, 64), np.uint32)
    b64 = np.empty((n64, 64), np.uint64)
    for p in payloads:
        rows, bad, _ = native.decode_l4_into(p, b32, b64)
        assert bad == 0
        want.append((rows, b32[:, :rows].copy(), b64[:, :rows].copy()))

    dec = native.PipelinedDecoder(capacity=64, n_bufs=3)
    got_n = 0
    import time as _t
    for (rows, g32, g64), (wr, w32, w64) in zip(
            dec.stream(iter(payloads)), want):
        _t.sleep(0.002)      # slow consumer: feeder runs ahead, must
        assert rows == wr    # still respect the ring discipline
        np.testing.assert_array_equal(g32[:, :rows], w32)
        np.testing.assert_array_equal(g64[:, :rows], w64)
        got_n += 1
    assert got_n == len(payloads)


def test_pipelined_decoder_propagates_feeder_errors():
    dec = native.PipelinedDecoder(capacity=64)

    def gen():
        yield b"\x00\x01ok-this-will-decode-to-nothing"
        raise RuntimeError("payload source exploded")

    with pytest.raises(RuntimeError, match="payload source exploded"):
        for _ in dec.stream(gen()):
            pass


def test_pipelined_decoder_reusable_after_abort_and_error():
    """An early consumer break or a feeder error must not poison the
    NEXT stream on the same decoder (per-call queues + stop flag)."""
    agent = SyntheticAgent()
    base = agent.l4_columns(128)
    recs = [agent.l4_record(base, i) for i in range(128)]
    payloads = [pack_pb_records(recs[i::4]) for i in range(4)]
    dec = native.PipelinedDecoder(capacity=128, n_bufs=2)
    # 1) abort mid-stream
    for n, _ in enumerate(dec.stream(iter(payloads))):
        if n == 1:
            break
    # 2) feeder error mid-stream
    def gen():
        yield payloads[0]
        raise RuntimeError("boom")
    with pytest.raises(RuntimeError):
        for _ in dec.stream(gen()):
            pass
    # 3) a fresh stream still yields every payload with correct counts
    got = [rows for rows, _, _ in dec.stream(iter(payloads))]
    assert got == [32, 32, 32, 32]

"""Ingester assembly: build receiver + every pipeline from one config.

Reference: server/ingester/ingester/ingester.go:67-224 — loads per-module
configs, builds Receiver + PlatformDataManager, starts all pipelines,
returns closers. Storage can be disabled (the reference's StorageDisabled
mode, ingester.go:132) which leaves decode + export live — the mode the
pure-TPU sketch deployment runs in.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Optional

from deepflow_tpu.enrich.platform_data import PlatformDataManager
from deepflow_tpu.pipelines.droplet import DropletPipeline
from deepflow_tpu.pipelines.event import EventPipeline
from deepflow_tpu.pipelines.ext_metrics import ExtMetricsPipeline
from deepflow_tpu.pipelines.flow_log import FlowLogPipeline
from deepflow_tpu.pipelines.flow_metrics import FlowMetricsPipeline
from deepflow_tpu.pipelines.profile import ProfilePipeline
from deepflow_tpu.runtime.exporters import Exporters
from deepflow_tpu.runtime.receiver import Receiver
from deepflow_tpu.runtime.stats import StatsRegistry
from deepflow_tpu.store.db import Store
from deepflow_tpu.store.dict_store import TagDictRegistry
from deepflow_tpu.store.monitor import DiskMonitor


@dataclass
class IngesterConfig:
    """Mirrors the reference's per-module config blocks
    (flow_log/config/config.go defaults)."""

    listen_port: int = 30033
    listen_host: str = "127.0.0.1"
    debug_port: Optional[int] = None     # None disables the UDP debug server
    store_path: Optional[str] = None     # None = StorageDisabled mode
    n_decoders: int = 2
    queue_size: int = 16384
    throttle_per_s: int = 50_000
    store_max_bytes: int = 100 << 30
    rollup_intervals: tuple = (60,)
    # enable the TPU sketch analytics exporter (BASELINE.json's
    # tpu_sketch plugin); None disables, a float sets window seconds
    tpu_sketch_window_s: Optional[float] = None
    # which wire the sketch lane batches on: "dict" (SmartEncoded
    # news/hits planes, the smallest bytes-per-record) or "lanes"
    # (packed 4-plane batches — the wire the ISSUE 9 zero-copy stager
    # and fused kernel ride)
    tpu_sketch_wire: str = "dict"
    # -- overlapped device feed (runtime/feed.py, ISSUE 5) ------------
    # double-buffered host->device prefetch for the tpu_sketch lane: a
    # supervised feed thread packs + transfers batch N+1 (one coalesced
    # device_put per batch) while batch N's donated-state update runs
    # async on device. 0 = the inline unoverlapped path (bit-identical
    # sketch state either way — asserted in tests/test_feed.py).
    prefetch_depth: int = 2
    # stack K TensorBatches into one lax.scan-fused device step,
    # amortizing per-dispatch overhead that dominates at small
    # batch_rows; 1 = one dispatch per batch (still coalesced)
    coalesce_batches: int = 1
    # -- zero-copy decode->staging (batch/staging.py, ISSUE 9) --------
    # pack decoded chunk columns DIRECTLY into the recycled coalesced
    # staging buffer — no intermediate TensorBatch copy on the lanes
    # feed path. Bit-identical sketch state either way (the TensorBatch
    # path stays as the reference; tests/test_staging.py). Only takes
    # effect with wire="lanes" and prefetch_depth > 0.
    zero_copy: bool = True
    # > 0: shard the staging pack across this many supervised worker
    # threads by flow hash, so host packing keeps prefetch_depth full
    # on multi-core hosts; 0 packs on the exporter worker thread
    pack_workers: int = 0
    # -- self-tuning device feed (runtime/autotune.py, ISSUE 20) ------
    # True spawns the feedback controller: a supervised thread that
    # bounded-hill-climbs coalesce_batches / prefetch_depth /
    # pack_workers live from tpu_device_busy_fraction,
    # tpu_feed_stall_seconds and the feed's queue dwell — the static
    # values above become the starting point (and the safe-fallback
    # target on any device error). Bit-invisible to sketch state
    # either way (ci.sh diffs an autotuned run against its
    # controller-off twin). Requires prefetch_depth > 0.
    autotune: bool = False
    # seconds between control ticks; one knob trial spans two ticks
    # (step, then judge against the occupancy deltas)
    autotune_interval_s: float = 2.0
    # hill-climb bounds: the controller never leaves [1, max]
    autotune_max_coalesce: int = 8
    autotune_max_depth: int = 8
    # -- pod fault domains (parallel/pod.py, ISSUE 10) ----------------
    # >= 2 runs the tpu_sketch lane as an epoch-merged pod of
    # single-device shard fault domains (one per jax device): each
    # window flush closes a deadline-bounded merge epoch, a straggler
    # past pod_merge_deadline_s is excluded (counted) instead of
    # awaited, a failing shard degrades/rejoins on its own, and the
    # POD-MERGED state is published with shard-participation tags.
    # 0 keeps the single-chip lane.
    tpu_sketch_pod_shards: int = 0
    pod_merge_deadline_s: float = 5.0
    # -- cross-host pod (parallel/multihost.py, ISSUE 17) -------------
    # >= 2 stacks a HOST fault-domain ladder on top of the shard pod:
    # each host runs its own PodFlowSuite, epoch markers and host
    # contributions cross the DCN (real jax.distributed collectives in
    # a multiprocess run, an in-process simulated DCN with seeded
    # marker-loss/partition/host-kill injection otherwise), a host past
    # dcn_marker_deadline_s is EXCLUDED (counted) instead of awaited,
    # and a killed host rejoins at an epoch boundary from its snapbus
    # snapshots. 0 keeps the single-host lane.
    pod_hosts: int = 0
    dcn_marker_deadline_s: float = 5.0
    # DCN transport: "auto" picks real collectives when the process
    # joined a jax.distributed run, the simulated DCN otherwise;
    # "sim"/"jax" force one.
    dcn_transport: str = "auto"
    # > 0: a simulated-DCN partition self-heals after this many seconds
    # (chaos runs drive partition + heal without an in-process hook)
    dcn_heal_after_s: float = 0.0
    # -- accuracy observatory (runtime/audit.py, ISSUE 6) -------------
    # deterministic flow-hash sampled exact shadow of the tpu_sketch
    # lane: exact per-key counts / distinct count / entropy for the
    # sampled slice, compared against the device sketch at every window
    # close — observed error, epsilon headroom and top-K recall land on
    # /metrics as gauges plus the tpu_sketch_accuracy Countable family,
    # and a sustained bound violation trips an alarm on /healthz.
    # Host-side only, bit-invisible to the sketch path. 0 disables.
    audit_sample_rate: float = 1.0 / 64
    # -- anomaly plane (deepflow_tpu/anomaly/, ISSUE 15) --------------
    # run the detection lane beside the tpu_sketch lane: per-window
    # entropy-DDoS scoring over a device-resident active-flow working
    # set, streaming-PCA residuals and matrix-profile discords over
    # the golden-signal window series, alert records durable on the
    # anomaly snapshot bus and queryable through serving/ (SQL
    # `SELECT * FROM anomaly`, PromQL `anomaly_score{detector=...}`).
    # Requires the tpu_sketch lane; False leaves detection off.
    anomaly_enabled: bool = False
    # entropy-DDoS alert threshold in z units (EWMA-standardized
    # feature-entropy deviation; src dispersion up / dst collapse)
    anomaly_entropy_z: float = 4.0
    # streaming-PCA residual threshold in z units (residual deviation
    # against its own EWMA history)
    anomaly_pca_z: float = 4.0
    # matrix-profile discord threshold (z-normalized subsequence
    # distance of the newest window against all history)
    anomaly_mp_threshold: float = 3.0
    # active-flow working-set size as log2 slots (2^n-entry device
    # table, LRU-by-window eviction); 0 disables the table (the
    # entropy detector still runs off the suite entropies)
    anomaly_active_log2: int = 14
    # windows before any detector may alert (EWMA baselines warm up
    # on a running average over these)
    anomaly_warmup_windows: int = 8
    # per-service RED windows from the l7 stream (runtime/app_red.py);
    # None disables, a float sets window seconds
    app_red_window_s: Optional[float] = None
    # > 0: surface app_red's DDSketch windows as Prometheus `le` bucket
    # counters (every Nth gamma boundary) so histogram_quantile works
    app_red_prom_buckets: int = 0
    # this ingester's id inside a multi-analyzer deployment: the 10
    # analyzer bits of every row _id (l4_flow_log.go genID) — distinct
    # per process or ids collide across ingesters
    analyzer_id: int = 0
    # geo-IP province stamping (enrich/geo.py): a JSON data file path,
    # or None for the built-in synthetic sample ranges; geo_enabled
    # False leaves the province columns zero
    geo_db_path: Optional[str] = None
    geo_enabled: bool = True
    # flight recorder (runtime/tracing.py): span timing through the hot
    # path, queryable via the trace CLI / debug commands. True enables
    # the process tracer; False leaves it as-is (another ingester or a
    # test may own it). Tracing costs ~one histogram add per batch
    # stage; the explicit transfer/kernel drains that detailed
    # attribution needs are SAMPLED (every 16th batch + cold
    # compiles), so the async device pipeline keeps its shape
    trace_enabled: bool = True
    # Prometheus text-exposition listener (runtime/promexpo.py) serving
    # the Countable registry + flight-recorder histograms; None
    # disables, 0 binds an ephemeral port (reference: the :9526
    # stats/pprof listener)
    prom_port: Optional[int] = None
    # -- resilience (runtime/supervisor.py, breaker.py, faults.py) ----
    # deadman watchdog: a supervised worker whose last heartbeat is
    # older than this is counted stale (detection only; the `stacks`
    # debug command shows where it sits). 0 disables.
    supervisor_deadman_s: float = 60.0
    # crash-restart backoff base (doubles per consecutive crash, capped
    # at 100x base, deterministic jitter)
    supervisor_backoff_s: float = 0.05
    # per-exporter circuit breakers around the decode->export fan-out;
    # False runs unwrapped (errors still contained, never quarantined)
    breaker_enabled: bool = True
    breaker_failure_rate: float = 0.5   # window fraction that trips
    breaker_min_calls: int = 4          # outcomes before a trip decision
    breaker_open_s: float = 5.0         # quarantine before half-open
    breaker_half_open_probes: int = 2   # probes that must all succeed
    # a put() slower than this counts as a failure; None disables
    breaker_latency_budget_s: Optional[float] = None
    # deterministic fault injection (runtime/faults.py spec string,
    # e.g. "exporter.raise:p=1,for_s=5;seed=7"); also read from the
    # DEEPFLOW_FAULTS env var — config wins when both are set
    fault_spec: Optional[str] = None
    # -- durability (runtime/spill.py, ISSUE 4) -----------------------
    # disk-spill for the ingest queues: overflow past the watermark is
    # serialized to CRC-framed segment files and replayed when headroom
    # (or the next process) returns. None disables — overload falls
    # back to overwrite-oldest. Segments found at start() are replayed.
    spill_dir: Optional[str] = None
    spill_segment_bytes: int = 1 << 20    # roll (fsync) cadence
    spill_budget_bytes: int = 64 << 20    # oldest-segment eviction past this
    spill_watermark: float = 0.75         # ring fraction that starts spilling
    # drain ladder (close()): how long to wait for queues + exporters
    # to flush before spilling the remainder to disk
    drain_deadline_s: float = 5.0
    # -- self-telemetry timeline (runtime/timeline.py, ISSUE 16) ------
    # sampler cadence of the bounded in-process TSDB over every
    # registered Countable + gauge surface: a Supervisor-spawned
    # thread snapshots at this cadence into fixed-size per-series
    # rings, and PromQL/SQL answer over them through the querier.
    # 0 disables the timeline (and with it the SLO burn-rate rules
    # and the incident recorder, which both ride the sampler tick)
    timeline_sample_s: float = 1.0
    # hot per-series ring capacity (samples); the oldest sample past
    # this either graduates to the coarse tier or is dropped counted
    timeline_hot_samples: int = 600
    # every Nth evicted hot sample joins the coarse tier (same
    # capacity -> Nx the lookback at 1/N resolution); 0 disables it
    timeline_coarse_every: int = 10
    # -- SLO burn-rate rules (evaluated on the sampler tick) ----------
    # shared objective for the declared SLOs (ingest availability off
    # the conservation-ledger loss counters; serving p99; detection
    # latency); burn rate = error fraction / (1 - objective)
    slo_objective: float = 0.999
    # serving p99 bound (seconds) the querier-read SLO holds against
    slo_serving_p99_s: float = 0.05
    # detection-latency bound (windows behind live) for the anomaly SLO
    slo_detect_latency_windows: float = 2.0
    # fast-window (5m) burn rate that counts as fast-burning — feeds
    # health()["slo_burning"] and the incident trigger (14.4 burns a
    # 0.999 objective's monthly budget in about two days)
    slo_fast_burn: float = 14.4
    # -- incident flight recorder (runtime/incident.py) ---------------
    # bundle directory; None derives <store_path>/incidents, and with
    # store_path also None the recorder is off (nowhere durable)
    incident_dir: Optional[str] = None
    incident_budget_bytes: int = 64 << 20  # oldest bundles evicted past
    incident_min_interval_s: float = 30.0  # global capture rate limit
    incident_window_s: float = 120.0       # timeline lookback per bundle


class Ingester:
    """One-call construction of the full receive->store data plane."""

    def __init__(self, cfg: IngesterConfig,
                 platform: Optional[PlatformDataManager] = None,
                 stats: Optional[StatsRegistry] = None) -> None:
        self.cfg = cfg
        self.stats = stats or StatsRegistry()
        from deepflow_tpu.runtime.tracing import default_tracer
        self.tracer = default_tracer()
        if cfg.trace_enabled:
            self.tracer.enable()
        self.stats.register("tracer", self.tracer.counters)
        # supervision tree: every worker thread below (receiver loops,
        # decoders, exporter workers) spawns through the process
        # supervisor — crash capture, backoff restart, deadman watchdog
        from deepflow_tpu.runtime.supervisor import default_supervisor
        self.supervisor = default_supervisor()
        # 0/None disables the watchdog (workers spawn with deadman None)
        self.supervisor.deadman_s = cfg.supervisor_deadman_s or None
        self.supervisor.backoff_base_s = cfg.supervisor_backoff_s
        self.supervisor.backoff_cap_s = 100 * cfg.supervisor_backoff_s
        self.stats.register("supervisor", self.supervisor.counters)
        # deterministic chaos: arm fault sites from config/env so a
        # chaos smoke replays the same schedule every run
        from deepflow_tpu.runtime.faults import default_faults
        self.faults = default_faults()
        self._armed_sites: list = []
        spec = cfg.fault_spec or os.environ.get("DEEPFLOW_FAULTS")
        if spec:
            # remembered so close() disarms exactly what THIS instance
            # armed — chaos must not leak into a successor ingester
            self._armed_sites = self.faults.arm_spec(spec)
            self.stats.register("faults", self.faults.counters)
        from deepflow_tpu.runtime.breaker import BreakerConfig
        breaker_cfg = None
        if cfg.breaker_enabled:
            breaker_cfg = BreakerConfig(
                failure_rate=cfg.breaker_failure_rate,
                min_calls=cfg.breaker_min_calls,
                open_s=cfg.breaker_open_s,
                half_open_probes=cfg.breaker_half_open_probes,
                latency_budget_s=cfg.breaker_latency_budget_s)
        self.platform = platform or PlatformDataManager(stats=self.stats)
        self.exporters = Exporters(stats=self.stats,
                                   breaker_cfg=breaker_cfg)
        self.store: Optional[Store] = None
        self.monitor: Optional[DiskMonitor] = None
        if cfg.store_path is not None:
            os.makedirs(cfg.store_path, exist_ok=True)
            self.store = Store(cfg.store_path)
            self.monitor = DiskMonitor(self.store, cfg.store_max_bytes,
                                       stats=self.stats)
        self.tag_dicts = TagDictRegistry(cfg.store_path)
        # a caller-supplied PlatformDataManager keeps its own geo choice
        # (incl. geo=None meaning "leave the columns zero")
        if platform is None and cfg.geo_enabled:
            from deepflow_tpu.enrich.geo import load_geo_table
            self.platform.geo = load_geo_table(cfg.geo_db_path,
                                               self.tag_dicts)
        self.tpu_sketch = None
        self.autotuner = None
        if cfg.tpu_sketch_window_s is not None:
            from deepflow_tpu.runtime.tpu_sketch import TpuSketchExporter
            ckpt_dir = None if cfg.store_path is None else \
                os.path.join(cfg.store_path, "sketch_ckpt")
            anomaly = None
            anomaly_dir = None
            if cfg.anomaly_enabled:
                from deepflow_tpu.anomaly import AnomalyConfig
                anomaly = AnomalyConfig(
                    active_log2=cfg.anomaly_active_log2,
                    entropy_z=cfg.anomaly_entropy_z,
                    pca_z=cfg.anomaly_pca_z,
                    mp_threshold=cfg.anomaly_mp_threshold,
                    warmup_windows=cfg.anomaly_warmup_windows)
                anomaly_dir = None if cfg.store_path is None else \
                    os.path.join(cfg.store_path, "anomaly_ckpt")
            self.tpu_sketch = TpuSketchExporter(
                store=self.store, window_seconds=cfg.tpu_sketch_window_s,
                checkpoint_dir=ckpt_dir, stats=self.stats,
                wire=cfg.tpu_sketch_wire,
                prefetch_depth=cfg.prefetch_depth,
                coalesce_batches=cfg.coalesce_batches,
                zero_copy=cfg.zero_copy,
                pack_workers=cfg.pack_workers,
                pod_shards=cfg.tpu_sketch_pod_shards,
                pod_merge_deadline_s=cfg.pod_merge_deadline_s,
                pod_hosts=cfg.pod_hosts,
                dcn_marker_deadline_s=cfg.dcn_marker_deadline_s,
                dcn_transport=cfg.dcn_transport,
                dcn_heal_after_s=cfg.dcn_heal_after_s,
                audit_rate=cfg.audit_sample_rate,
                anomaly=anomaly, anomaly_dir=anomaly_dir)
            self.exporters.register(self.tpu_sketch)
            # self-tuning feed (runtime/autotune.py, ISSUE 20): the
            # controller holds the decode-plane knobs from here on;
            # cfg's values are its starting point and fallback target
            if cfg.autotune and self.tpu_sketch._feed is not None:
                from deepflow_tpu.runtime.autotune import FeedAutotuner
                self.autotuner = FeedAutotuner(
                    self.tpu_sketch,
                    interval_s=cfg.autotune_interval_s,
                    max_coalesce=cfg.autotune_max_coalesce,
                    max_depth=cfg.autotune_max_depth)
                self.stats.register("exporter.tpu_autotune",
                                    self.autotuner.counters)
            if self.tpu_sketch.anomaly is not None:
                # alerts ride the breaker-wrapped fan-out on stream
                # "anomaly" (third-party exporters can subscribe; the
                # put itself is contained + counted like every other)
                self.tpu_sketch.anomaly.attach_exporters(self.exporters)
        self.app_red = None
        if cfg.app_red_window_s is not None:
            from deepflow_tpu.runtime.app_red import AppRedExporter
            self.app_red = AppRedExporter(
                store=self.store, window_seconds=cfg.app_red_window_s,
                stats=self.stats, tag_dicts=self.tag_dicts,
                prom_bucket_stride=cfg.app_red_prom_buckets)
            self.exporters.register(self.app_red)
        self.receiver = Receiver(port=cfg.listen_port, host=cfg.listen_host,
                                 stats=self.stats)
        self.flow_log = FlowLogPipeline(
            self.receiver, self.store, self.platform, self.exporters,
            n_decoders=cfg.n_decoders, queue_size=cfg.queue_size,
            throttle_per_s=cfg.throttle_per_s, stats=self.stats,
            tag_dicts=self.tag_dicts, analyzer_id=cfg.analyzer_id)
        self.flow_metrics = FlowMetricsPipeline(
            self.receiver, self.store, self.exporters,
            n_unmarshallers=cfg.n_decoders, queue_size=cfg.queue_size,
            rollup_intervals=cfg.rollup_intervals, stats=self.stats)
        self.ext_metrics = ExtMetricsPipeline(
            self.receiver, self.store, self.tag_dicts, stats=self.stats)
        self.event = EventPipeline(
            self.receiver, self.store, self.tag_dicts, stats=self.stats)
        self.profile = ProfilePipeline(
            self.receiver, self.store, self.tag_dicts, stats=self.stats)
        droplet_dir = None if cfg.store_path is None else \
            os.path.join(cfg.store_path, "droplet")
        self.droplet = DropletPipeline(
            self.receiver, self.store, self.tag_dicts, droplet_dir,
            stats=self.stats)
        self._pipelines = (self.flow_log, self.flow_metrics, self.ext_metrics,
                           self.event, self.profile, self.droplet)
        # durability: arm disk-spill on every ingest queue; segments a
        # previous process left behind replay once start() runs
        self.spill = None
        self._drain_state = "running"
        if cfg.spill_dir is not None:
            from deepflow_tpu.runtime.spill import SpillGroup
            self.spill = SpillGroup(
                self._own_queues(), cfg.spill_dir,
                segment_bytes=cfg.spill_segment_bytes,
                budget_bytes=cfg.spill_budget_bytes,
                watermark=cfg.spill_watermark)
            self.stats.register("spill", self.spill.counters)
        # self-telemetry timeline + SLO burn rates + incident recorder
        # (ISSUE 16): the sampler snapshots every Countable and gauge
        # surface into per-series rings, SLO rules burn-rate on its
        # tick, and the watcher captures one correlated fsynced bundle
        # per trigger edge. Host-side only — bit-invisible to the
        # sketch/anomaly device state (asserted in tests).
        self.timeline = None
        self.incidents = None
        self._incident_watcher = None
        if cfg.timeline_sample_s > 0:
            from deepflow_tpu.runtime.profiler import default_profiler
            from deepflow_tpu.runtime.timeline import (RecordingRule,
                                                       SloRule, Timeline)
            self.timeline = Timeline(
                sample_s=cfg.timeline_sample_s,
                hot_samples=cfg.timeline_hot_samples,
                coarse_every=cfg.timeline_coarse_every,
                stats=self.stats, tracer=self.tracer,
                profiler=default_profiler(),
                fast_burn_threshold=cfg.slo_fast_burn)
            # recording rules: the derived lane rates item 2's feedback
            # controller conditions on, materialized as first-class
            # series (rate window = 10 ticks, the staleness horizon)
            rate_win = 10.0 * cfg.timeline_sample_s

            def _per_s(metric):
                def fn(tl, now):
                    d = tl._window_delta(metric, now - rate_win, now)
                    return d / rate_win
                return fn

            self.timeline.add_rule(RecordingRule(
                "ingest_frames_per_s", _per_s("receiver_rx_frames")))
            self.timeline.add_rule(RecordingRule(
                "sketch_rows_per_s", _per_s("tpu_sketch_rows_in")))
            # declared SLOs: availability off the conservation-ledger
            # loss counters, serving p99, detection latency
            self.timeline.add_slo(SloRule(
                "ingest_availability", objective=cfg.slo_objective,
                kind="ratio",
                bad=("receiver_rx_dropped", "exporters_put_errors",
                     "exporters_shed"),
                total=("receiver_rx_frames",)))
            self.timeline.add_slo(SloRule(
                "serving_p99", objective=cfg.slo_objective,
                kind="threshold", series="querier_read_p99_s",
                bound=cfg.slo_serving_p99_s))
            self.timeline.add_slo(SloRule(
                "detection_latency", objective=cfg.slo_objective,
                kind="threshold",
                series="anomaly_detect_latency_windows",
                bound=cfg.slo_detect_latency_windows))
            self.stats.register("timeline", self.timeline.counters)
            incident_dir = cfg.incident_dir
            if incident_dir is None and cfg.store_path is not None:
                incident_dir = os.path.join(cfg.store_path, "incidents")
            if incident_dir is not None:
                from deepflow_tpu.runtime.incident import (
                    IncidentRecorder, IncidentWatcher)
                buses = {}
                if self.tpu_sketch is not None:
                    buses["sketch"] = self.tpu_sketch.snapshot_bus
                    if self.tpu_sketch.anomaly is not None:
                        buses["anomaly"] = self.tpu_sketch.anomaly.bus
                self.incidents = IncidentRecorder(
                    incident_dir, timeline=self.timeline,
                    profiler=default_profiler(), stats=self.stats,
                    snapbuses=buses,
                    budget_bytes=cfg.incident_budget_bytes,
                    min_interval_s=cfg.incident_min_interval_s,
                    window_s=cfg.incident_window_s)
                self.stats.register("incidents", self.incidents.counters)
                anomaly = None if self.tpu_sketch is None \
                    else self.tpu_sketch.anomaly
                self._incident_watcher = IncidentWatcher(
                    self.incidents, health_fn=self.health,
                    breakers_fn=self.exporters.breakers,
                    alerts_fn=None if anomaly is None else
                    (lambda: float(sum(anomaly.alerts_total))),
                    timeline=self.timeline)
                self.timeline.add_tick_hook(self._incident_watcher.tick)
        self.prom = None
        if cfg.prom_port is not None:
            from deepflow_tpu.runtime.promexpo import PrometheusExporter
            self.prom = PrometheusExporter(stats=self.stats,
                                           tracer=self.tracer,
                                           port=cfg.prom_port,
                                           health=self.health,
                                           timeline=self.timeline)
        self.debug = None
        if cfg.debug_port is not None:
            from deepflow_tpu.runtime.debug import DebugServer
            self.debug = DebugServer(self.stats, port=cfg.debug_port,
                                     tracer=self.tracer)
            self.debug.register(
                "vtap-status",
                lambda req: {f"{v}:{t}": vars(st) for (v, t), st
                             in self.receiver.status().items()})
            self.debug.register("artifacts", self._artifact_listing)
            self.debug.register("datasource", self._datasource_cmd)
            self.debug.register("queues", self._queues_cmd)
            self.debug.register("queue-tap", self._queue_tap_cmd)
            # `supervisor` rides DebugServer's built-in handler (the
            # supervision tree is process-scoped, like the tracer)
            self.debug.register("breakers",
                                lambda req: self.exporters.breakers())
            self.debug.register("spill", self._spill_cmd)

    def health(self) -> dict:
        """Liveness verdict for the /healthz endpoint: not-ok when any
        supervised worker is deadman-stale, any exporter breaker is
        open (quarantined), or the tpu_sketch lane is running degraded
        on the host fallback. The supervision tree is process-scoped
        (like the flight recorder), so in the rare several-ingesters-
        per-process deployment the stale/crash numbers aggregate across
        all of them — breakers and the degraded flag stay per-instance.
        `drain` is the shutdown-ladder verdict: "running" in steady
        state, "draining" while close() flushes under its deadline,
        "drained" once everything landed (store/segments) — a probe
        sees the ladder instead of a silently-vanishing endpoint."""
        sup = self.supervisor.counters()
        open_breakers = [n for n, c in self.exporters.breakers().items()
                         if c["state"] == "open"]
        degraded = bool(self.tpu_sketch is not None
                        and self.tpu_sketch.degraded)
        # accuracy observatory (ISSUE 6): sustained observed-error-
        # over-bound windows trip a breaker-style alarm — the lane is
        # up but its ANSWERS are suspect, which a probe must see
        accuracy_alarm = bool(self.tpu_sketch is not None
                              and self.tpu_sketch.audit_alarm)
        draining = self._drain_state != "running"
        out = {
            "ok": not (sup["stale"] or open_breakers or degraded
                       or accuracy_alarm or draining),
            "drain": self._drain_state,
            "stale_threads": sup["stale"],
            "crashes": sup["crashes"],
            "restarts": sup["restarts"],
            "open_breakers": open_breakers,
            "degraded_tpu_sketch": degraded,
            "accuracy_alarm": accuracy_alarm,
        }
        # SLO fast-burn verdict (ISSUE 16): informational — which
        # declared objectives are burning budget past the fast-window
        # threshold. Deliberately NOT folded into `ok`: burn lags its
        # cause (the loss that burned the budget already flipped a
        # breaker or loss counter above), and a 5m-window burn keeping
        # /healthz 503 long after recovery would fight the probes
        if self.timeline is not None:
            out["slo_burning"] = self.timeline.fast_burning()
        # pod fault domains (ISSUE 10): per-shard states on the probe
        # surface — a degraded or lost shard is a reduced-capacity pod
        # (not-ok, like the single-chip degraded lane) and the probe
        # names WHICH shard, not just "something is wrong"
        pod = None if self.tpu_sketch is None else self.tpu_sketch.pod
        if pod is not None:
            status = pod.shard_status()
            out["pod_shards"] = pod.n_shards
            out["pod_shards_active"] = sum(
                1 for s in status if s["status"] == "active")
            out["pod_shards_degraded"] = [
                s["shard"] for s in status if s["status"] == "degraded"]
            out["pod_shards_lost"] = [
                s["shard"] for s in status if s["status"] == "lost"]
            if out["pod_shards_active"] < pod.n_shards:
                out["ok"] = False
            # cross-host pod (ISSUE 17): the probe names WHICH host is
            # missing, same contract one fault-domain level up
            if hasattr(pod, "host_status"):
                hosts = pod.host_status()
                out["pod_hosts"] = len(hosts)
                out["pod_hosts_active"] = sum(
                    1 for h in hosts if h["status"] == "active")
                out["pod_hosts_lost"] = [
                    h["host"] for h in hosts if h["status"] == "lost"]
                out["pod_links_down"] = [
                    h["host"] for h in hosts if not h["link_up"]]
                if out["pod_hosts_active"] < len(hosts):
                    out["ok"] = False
        return out

    def _spill_cmd(self, req: dict) -> dict:
        """Per-queue disk-spill accounting (the `spill` debug command):
        segments/bytes pending plus the spilled/replayed/evicted flow."""
        if self.spill is None:
            return {"enabled": False}
        want = req.get("module") or ""
        return {"enabled": True, "drain": self._drain_state,
                "queues": {name: c
                           for name, c in sorted(
                               self.spill.per_queue().items())
                           if want in name}}

    def _own_queues(self) -> dict:
        """THIS ingester's inter-stage MultiQueues by name. Scoped to
        the instance — a process can host several ingesters, and a
        debug command must never reach into another's pipelines."""
        out = {}
        for _, q in self.flow_log._streams:
            out[q.name] = q
        for p in (self.flow_metrics, self.ext_metrics, self.event,
                  self.profile, self.droplet):
            q = getattr(p, "queues", None)
            if q is not None:
                out[q.name] = q
        return out

    def _queues_cmd(self, req: dict) -> dict:
        """Every inter-stage queue with in/out/overwritten/pending
        (reference: queue-tap listing in deepflow-ctl)."""
        want = req.get("module") or ""
        return {name: q.counters()
                for name, q in sorted(self._own_queues().items())
                if want in name}

    def _queue_tap_cmd(self, req: dict) -> dict:
        """Sample up to `count` in-flight items from a named queue
        (reference: queue::bounded_with_debug taps). Arms the tap, lets
        traffic flow briefly, returns item summaries. The wait is
        clamped: the debug loop is single-threaded, so a handler must
        return well inside the client's 2s datagram timeout."""
        import time as _time

        name = req.get("module") or ""
        q = self._own_queues().get(name)
        if q is None:
            return {"error": f"unknown queue {name!r} "
                             "(list with the queues command)"}
        count = min(int(req.get("count", 3)), 20)
        wait_s = min(max(float(req.get("wait_s", 1.0)), 0.0), 1.5)
        q.tap(count)
        try:
            deadline = _time.time() + wait_s
            items: list = []
            while _time.time() < deadline:
                items.extend(q.tap_take())
                if len(items) >= count:
                    break
                _time.sleep(0.05)
            items.extend(q.tap_take())
        finally:
            q.untap()
        return {"queue": name, "sampled": items[:count]}

    def _datasource_cmd(self, req: dict) -> dict:
        """Runtime rollup-tier CRUD over the debug socket (the
        reference's `deepflow-ctl domain datasource` ->
        datasource/handle.go Handle). op: list | add | del | retention;
        add/del/retention take interval (seconds, whole minutes), add
        and retention take ttl (seconds, 0 = keep forever)."""
        rollups = self.flow_metrics.rollups
        if rollups is None:
            return {"error": "storage disabled: no rollup tiers"}
        op = req.get("op", "list")
        if op not in ("list", "add", "del", "retention"):
            return {"error": f"unknown op {op!r}"}
        try:
            if op == "list":
                return {"datasources": rollups.list_datasources()}
            interval = int(req["interval"])
            if op == "add":
                ttl = req.get("ttl")
                # absent ttl = derive the tier default; 0 = keep forever
                from deepflow_tpu.store.rollup import TTL_DERIVE
                return rollups.add_interval(
                    interval, TTL_DERIVE if ttl is None else int(ttl))
            if op == "del":
                ok = rollups.remove_interval(interval,
                                             drop_data=bool(
                                                 req.get("drop", True)))
                return {"deleted": ok, "interval": interval}
            # retention: an explicit ttl is REQUIRED (a forgotten --ttl
            # must not silently mean keep-forever); 0 = keep forever
            ttl = req.get("ttl")
            if ttl is None:
                return {"error": "retention requires ttl "
                                 "(seconds; 0 = keep forever)"}
            ok = rollups.set_retention(interval,
                                       None if int(ttl) == 0 else int(ttl))
            return {"updated": ok, "interval": interval}
        except KeyError as e:
            return {"error": f"missing field {e}"}
        except ValueError as e:
            return {"error": str(e)}

    def _artifact_listing(self, req: dict) -> dict:
        """Stored droplet artifacts (per-vtap pcaps, syslog files) —
        the deepflow-ctl pcap listing role. Names + sizes only; the
        files live beside the store for direct retrieval. `module`
        substring-filters names, and the listing truncates to the
        debug protocol's single-datagram budget (truncated count
        reported) so a busy ingester still answers."""
        out_dir = self.droplet.out_dir
        if out_dir is None or not os.path.isdir(out_dir):
            return {"dir": out_dir, "files": []}
        want = req.get("module") or ""
        names = [n for n in sorted(os.listdir(out_dir)) if want in n]
        files = []
        for name in names[:500]:      # ~70B/entry << 65000B datagram
            p = os.path.join(out_dir, name)
            if os.path.isfile(p):
                files.append({"name": name,
                              "bytes": os.path.getsize(p)})
        out = {"dir": out_dir, "files": files}
        if len(names) > 500:
            out["truncated"] = len(names) - 500
        return out

    def start(self) -> None:
        self.exporters.start()
        for p in self._pipelines:
            p.start()
        if self.monitor is not None:
            self.monitor.start()
        if self.debug is not None:
            self.debug.start()
        if self.prom is not None:
            self.prom.start()
        # throttle-bucket janitor: rolls idle reservoir buckets on wall
        # clock so a quiet stream's rows reach the writer within one
        # bucket width instead of waiting for the next record
        self._janitor_stop = threading.Event()

        def _janitor():
            while not self._janitor_stop.wait(1.0):
                self.supervisor.beat()
                for p in self._pipelines:
                    tick = getattr(p, "tick", None)
                    if tick is not None:
                        tick()
        # supervised under the ingester's own tree: a crashed janitor
        # (one pipeline's tick raising) restarts instead of leaving
        # every quiet stream's rows stranded until the next record
        self._janitor = self.supervisor.spawn(
            "throttle-janitor", _janitor, beat_period_s=1.0)
        if self.spill is not None:
            # replay-before-receive: drain threads start re-injecting
            # any segments a previous process left behind while the
            # listener below is still coming up
            self.spill.start()
        if self.timeline is not None:
            self.timeline.register_datasource()
            if self.incidents is not None:
                self.incidents.register_datasource()
            self.timeline.start(self.supervisor)
        if self.autotuner is not None:
            self.autotuner.start()
        self.receiver.start()  # last, like the reference (ingester.go:220)

    def flush(self) -> None:
        """Drain throttlers/writers to disk (tests and shutdown)."""
        for p in self._pipelines:
            p.flush()
        if self.tpu_sketch is not None:
            self.tpu_sketch.flush()
        if self.app_red is not None:
            self.app_red.flush()
        self.tag_dicts.flush()

    def _drain_wait(self, deadline: float) -> bool:
        """Wait (bounded) for ingest queues, then exporter queues, to
        empty — decoders and exporter workers are still running at this
        point, so 'wait' means 'let them finish'. True = fully drained."""
        import time as _time

        queues = list(self._own_queues().values())

        def drained() -> bool:
            return (all(len(q) == 0 for q in queues)
                    and self.exporters.pending() == 0
                    and (self.spill is None
                         or self.spill.pending_segments() == 0))

        while _time.monotonic() < deadline:
            if drained():
                return True
            _time.sleep(0.05)
        return drained()

    def close(self) -> None:
        """The drain ladder (ISSUE 4): stop accepting -> let decoders/
        exporters flush under `drain_deadline_s` -> final sketch
        checkpoint -> spill whatever never drained to segment files for
        the next start -> tear down. /healthz reports the rung via the
        `drain` verdict for as long as the listener is up."""
        import time as _time

        self._drain_state = "draining"
        # sampler first: its tick hooks read health()/breakers, and the
        # surfaces below are about to be torn down under it
        if self.timeline is not None:
            self.timeline.stop()
            self.timeline.unregister_datasource()
            if self.incidents is not None:
                self.incidents.unregister_datasource()
        # controller before the drain: knob moves during teardown would
        # race the drain ladder's own barriers for no benefit
        if self.autotuner is not None:
            self.autotuner.close()
        janitor_stop = getattr(self, "_janitor_stop", None)
        if janitor_stop is not None:
            janitor_stop.set()
            self._janitor.stop()
            self._janitor.join(timeout=2)
        # rung 1: stop accepting — close the listener, let established
        # connections dispatch their in-flight kernel-buffered bytes
        # (bounded), THEN stop the readers
        started = getattr(self, "_janitor", None) is not None
        if started:
            self.receiver.quiesce(
                deadline_s=max(0.5, self.cfg.drain_deadline_s / 4))
        self.receiver.close()
        # rung 2: bounded flush — pipelines and exporters still live
        drained = True
        if started:
            drained = self._drain_wait(
                _time.monotonic() + self.cfg.drain_deadline_s)
            self.flush()               # throttle buckets + writers to disk
        # rung 3: final sketch checkpoint (the flush in exporter close
        # can still fail; the snapshot bounds that loss to zero windows)
        if self.tpu_sketch is not None:
            self.tpu_sketch.checkpoint_now()
        # rung 4: park the undrained remainder on disk, counted, for
        # the next start's replay (spill_remaining drains the rings)
        if self.spill is not None:
            self.spill.close(spill_remaining=not drained)
        for p in self._pipelines:
            p.close()
        if self.monitor is not None:
            self.monitor.close()
        self.exporters.close()
        self._drain_state = "drained"
        if self.debug is not None:
            self.debug.close()
        if self.prom is not None:
            self.prom.close()
        self.tag_dicts.close()
        self.stats.deregister("tracer")
        self.stats.deregister("supervisor")
        if self.autotuner is not None:
            self.stats.deregister("exporter.tpu_autotune")
        if self.timeline is not None:
            self.stats.deregister("timeline")
        if self.incidents is not None:
            self.stats.deregister("incidents")
        if self.spill is not None:
            self.stats.deregister("spill")
        for site in self._armed_sites:
            self.faults.disarm(site)
        if self._armed_sites:
            self.stats.deregister("faults")
            self._armed_sites = []

    @property
    def port(self) -> int:
        return self.receiver.bound_port

    @property
    def prom_port(self) -> Optional[int]:
        """Bound metrics-endpoint port (ephemeral-port aware), or None
        when exposition is disabled."""
        return None if self.prom is None else self.prom.port

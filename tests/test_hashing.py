import numpy as np

import jax.numpy as jnp

from deepflow_tpu.ops import hashing
from deepflow_tpu.utils import fold_columns, mix32, splitmix32_seeds


def test_mix32_bijective_sample(rng):
    xs = rng.integers(0, 2**32, size=100_000, dtype=np.uint32)
    ys = np.asarray(mix32(jnp.asarray(xs)))
    assert len(np.unique(ys)) == len(np.unique(xs))


def test_mix32_avalanche(rng):
    """Flipping one input bit flips ~half the output bits on average."""
    xs = rng.integers(0, 2**32, size=4096, dtype=np.uint32)
    base = np.asarray(mix32(jnp.asarray(xs)))
    for bit in (0, 7, 16, 31):
        flipped = np.asarray(mix32(jnp.asarray(xs ^ np.uint32(1 << bit))))
        hamming = np.unpackbits((base ^ flipped).view(np.uint8)).mean() * 32
        assert 13.0 < hamming < 19.0, f"bit {bit}: {hamming}"


def test_seeds_deterministic_and_odd():
    a = splitmix32_seeds(64)
    b = splitmix32_seeds(64)
    assert np.array_equal(a, b)
    assert np.all(a % 2 == 1)
    assert len(np.unique(a)) == 64


def test_bucket_uniformity(rng):
    keys = jnp.asarray(rng.integers(0, 2**32, size=200_000, dtype=np.uint32))
    seeds = hashing.make_seeds(4)
    idx = np.asarray(hashing.multi_bucket(keys, seeds, 10))
    assert idx.shape == (4, 200_000)
    assert idx.min() >= 0 and idx.max() < 1024
    for row in idx:
        counts = np.bincount(row, minlength=1024)
        # chi2 ~ buckets for uniform; allow generous slack
        chi2 = ((counts - counts.mean()) ** 2 / counts.mean()).sum()
        assert chi2 < 1400, chi2


def test_rows_independent(rng):
    keys = jnp.asarray(rng.integers(0, 2**32, size=50_000, dtype=np.uint32))
    seeds = hashing.make_seeds(4)
    idx = np.asarray(hashing.multi_bucket(keys, seeds, 12))
    for i in range(4):
        for j in range(i + 1, 4):
            match = (idx[i] == idx[j]).mean()
            assert match < 0.01, (i, j, match)


def test_fold_columns_sensitivity(rng):
    a = rng.integers(0, 2**32, size=10_000, dtype=np.uint32)
    b = rng.integers(0, 2**16, size=10_000, dtype=np.uint32)
    k1 = np.asarray(fold_columns([jnp.asarray(a), jnp.asarray(b)]))
    k2 = np.asarray(fold_columns([jnp.asarray(a), jnp.asarray(b ^ np.uint32(1))]))
    assert (k1 != k2).mean() > 0.999

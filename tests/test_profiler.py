"""Continuous OnCPU profiler: perf sampling, ELF symbolization, and the
full produce->wire->store->flame loop (reference:
agent/src/ebpf/kernel/perf_profiler.c + user/profile/stringifier.c).

These tests run the REAL perf_event_open sampler against a compiled C
burner whose hot function is known — the round-3 verdict's acceptance
test: "spin a CPU loop, profile it, assert its function dominates the
flame"."""

import os
import socket
import subprocess
import time

import pytest

from deepflow_tpu.agent import profiler
from deepflow_tpu.agent.profiler import (OnCpuProfiler, Symbolizer,
                                         elf_function_symbols,
                                         folded_to_profile_records)

pytestmark = pytest.mark.skipif(not profiler.available(),
                                reason="perf_event_open unsupported")

_BURNER_C = r"""
#include <stdint.h>
#include <stdio.h>
volatile uint64_t sink;
__attribute__((noinline)) uint64_t burn_cycles(uint64_t n) {
    uint64_t acc = 1;
    for (uint64_t i = 0; i < n; i++)
        acc = acc * 2862933555777941757ULL + 3037000493ULL;
    return acc;
}
int main(void) {
    fprintf(stderr, "ready\n");
    /* volatile-dependent arg: the call must not be hoisted out of the
       loop as loop-invariant, or the hot function never runs */
    for (;;) sink += burn_cycles((1 << 20) + (sink & 1));
    return 0;
}
"""


@pytest.fixture(scope="module")
def burner(tmp_path_factory):
    d = tmp_path_factory.mktemp("prof")
    src = d / "burner.c"
    src.write_text(_BURNER_C)
    exe = d / "burner"
    try:
        subprocess.run(["gcc", "-O1", "-fno-omit-frame-pointer",
                        "-no-pie", "-o", str(exe), str(src)],
                       check=True, capture_output=True)
    except (subprocess.CalledProcessError, FileNotFoundError):
        pytest.skip("no working C toolchain")
    p = subprocess.Popen([str(exe)], stderr=subprocess.PIPE)
    p.stderr.readline()                       # "ready"
    try:
        yield p, str(exe)
    finally:
        p.kill()
        p.wait()


def _sample(pid, duration=0.8):
    try:
        prof = OnCpuProfiler(pid, freq_hz=199)
    except OSError as e:
        pytest.skip(f"perf_event_open refused: {e}")
    try:
        return prof.run(duration)
    finally:
        prof.close()


def test_elf_function_symbols(burner):
    _, exe = burner
    addrs, names, is_pie = elf_function_symbols(exe)
    assert "burn_cycles" in names and "main" in names
    assert not is_pie                          # -no-pie => ET_EXEC
    assert addrs == sorted(addrs)


def test_symbolizer_resolves_burner(burner):
    p, _ = burner
    sym = Symbolizer(p.pid)
    addrs, names, _ = elf_function_symbols(f"/proc/{p.pid}/exe")
    ip = addrs[names.index("burn_cycles")] + 4
    assert sym.resolve(ip) == "burn_cycles"
    assert sym.resolve(0x10) == "[unknown]"


def test_oncpu_sampler_hot_function_dominates(burner):
    p, _ = burner
    folded = _sample(p.pid)
    total = sum(folded.values())
    assert total >= 30, f"too few samples ({total}) for a 199Hz/0.8s run"
    hot = sum(v for k, v in folded.items() if "burn_cycles" in k)
    assert hot / total >= 0.8, folded


def test_e2e_profile_to_flame(burner, tmp_path):
    """The whole loop the reference ships: sampler -> folded stacks ->
    Profile wire records -> firehose -> profile pipeline -> store ->
    querier flame, asserting the burner's function dominates the
    rendered flame graph."""
    from deepflow_tpu.pipelines import Ingester, IngesterConfig
    from deepflow_tpu.querier.profile import ProfileQuery
    from deepflow_tpu.wire.codec import pack_pb_records
    from deepflow_tpu.wire.framing import (FlowHeader, MessageType,
                                           encode_frame)

    p, _ = burner
    folded = _sample(p.pid)
    assert folded
    records = folded_to_profile_records(folded, app_service="burner",
                                        pid=p.pid, vtap_id=7)
    ing = Ingester(IngesterConfig(listen_port=0,
                                  store_path=str(tmp_path)))
    ing.start()
    try:
        frame = encode_frame(MessageType.PROFILE,
                             pack_pb_records(records),
                             FlowHeader(sequence=1, vtap_id=7))
        with socket.create_connection(("127.0.0.1", ing.port),
                                      timeout=5) as s:
            s.sendall(frame)
        deadline = time.time() + 10
        while time.time() < deadline and ing.profile.profiles < len(
                records):
            time.sleep(0.05)
        assert ing.profile.profiles >= len(records)
        ing.flush()
        q = ProfileQuery(ing.store, ing.tag_dicts)
        flame = q.flame(app_service="burner", event_type="on-cpu")
        assert flame["total_value"] == sum(folded.values())

        def find(node, name):
            if node["name"] == name:
                return node
            for c in node["children"]:
                got = find(c, name)
                if got is not None:
                    return got
            return None

        hot = find(flame, "burn_cycles")
        assert hot is not None, flame
        assert hot["total_value"] / flame["total_value"] >= 0.8
        top = q.top_functions(app_service="burner")
        assert top and any(t["name"] == "burn_cycles" for t in top[:2])
    finally:
        ing.close()


def test_agent_profile_loop_ships_to_ingester(tmp_path):
    """Agent-side integration: profile_pids config turns on the
    continuous profiling loop, which samples the agent's own process
    and ships Profile records over the firehose into the ingester's
    profile table."""
    from deepflow_tpu.agent.trident import Agent, AgentConfig
    from deepflow_tpu.pipelines import Ingester, IngesterConfig

    ing = Ingester(IngesterConfig(listen_port=0,
                                  store_path=str(tmp_path)))
    ing.start()
    agent = None
    try:
        cfg = AgentConfig(ingester_addr=f"127.0.0.1:{ing.port}",
                          host="prof-agent",
                          profile_pids=(0,),       # 0 = self
                          profile_interval_s=0.2,
                          profile_duration_s=0.3,
                          profile_freq_hz=199)
        agent = Agent(cfg)
        agent.start()
        # generous deadline: one sample cycle is ~0.5s, but this box
        # has ONE core and background load (the TPU bench retry loop's
        # probes) can starve the agent's sampler thread for long
        # stretches — 15s flaked twice under a concurrent probe
        deadline = time.time() + 45
        while time.time() < deadline and ing.profile.profiles == 0:
            # keep the target's CPU busy so the sampler sees stacks
            sum(i * i for i in range(20000))
            time.sleep(0.01)
        if agent.profile_errors and ing.profile.profiles == 0:
            pytest.skip("perf refused inside agent loop")
        if ing.profile.profiles == 0 and agent.profiles_sent == 0:
            # sampler ran without errors yet captured nothing: the
            # kernel throttles perf sampling under CPU pressure
            # (perf_cpu_time_max_percent), which happens when another
            # heavy process shares this single core (observed twice
            # with a concurrent TPU bench/probe). Degradation, not a
            # product bug — skip LOUDLY rather than flake.
            pytest.skip("perf sampler starved (co-load on 1 core): "
                        "0 samples in 45s with no errors")
        assert ing.profile.profiles >= 1, (
            f"no profiles in 45s: sent={agent.profiles_sent} "
            f"errors={agent.profile_errors}")
        assert agent.profiles_sent >= 1
        ing.flush()
        rows = ing.store.table("profile", "in_process_profile").scan()
        assert len(rows["value"]) >= 1
        svc = ing.tag_dicts.get("profile_name").decode(
            int(rows["app_service"][0]))
        assert svc == "prof-agent"
    finally:
        if agent is not None:
            agent.close()
        ing.close()


_MT_BURNER_C = r"""
#include <stdint.h>
#include <stdio.h>
#include <pthread.h>
volatile uint64_t sink;
__attribute__((noinline)) uint64_t burn_cycles(uint64_t n) {
    uint64_t acc = 1;
    for (uint64_t i = 0; i < n; i++)
        acc = acc * 2862933555777941757ULL + 3037000493ULL;
    return acc;
}
static void *worker(void *arg) {
    for (;;) sink += burn_cycles((1 << 20) + (sink & 1));
    return 0;
}
int main(void) {
    pthread_t t1, t2;
    pthread_create(&t1, 0, worker, 0);
    pthread_create(&t2, 0, worker, 0);
    fprintf(stderr, "ready\n");
    /* main thread sleeps: ALL cpu burns on workers — a single-task
       sampler would see nothing */
    for (;;) pthread_join(t1, 0);
    return 0;
}
"""


def test_sampler_sees_worker_threads(tmp_path):
    """inherit=1 refuses ring mmap on this kernel class, so the
    profiler opens one event per task — worker-thread CPU (where real
    services burn) must be visible even when the main thread sleeps."""
    d = tmp_path
    src = d / "mt_burner.c"
    src.write_text(_MT_BURNER_C)
    exe = d / "mt_burner"
    try:
        subprocess.run(["gcc", "-O1", "-fno-omit-frame-pointer",
                        "-no-pie", "-pthread", "-o", str(exe), str(src)],
                       check=True, capture_output=True)
    except (subprocess.CalledProcessError, FileNotFoundError):
        pytest.skip("no working C toolchain")
    p = subprocess.Popen([str(exe)], stderr=subprocess.PIPE)
    p.stderr.readline()
    try:
        time.sleep(0.2)
        prof = OnCpuProfiler(p.pid, freq_hz=199)
        try:
            assert prof.task_count >= 3        # main + 2 workers
            folded = prof.run(0.8)
        finally:
            prof.close()
        total = sum(folded.values())
        assert total >= 30
        hot = sum(v for k, v in folded.items() if "burn_cycles" in k)
        assert hot / total >= 0.8, folded
    finally:
        p.kill()
        p.wait()

"""Fleet monitor: vtap liveness + agent->ingester rebalancing.

Reference: server/controller/monitor/ — marks agents offline when their
sync heartbeats stop and rebalances agents across analyzer (ingester)
replicas. Rebalancing here is rendezvous hashing: each agent reports to
the ingester with the highest hash(agent, ingester) weight, so adding or
removing one ingester moves only its own share of agents.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Dict, List, Optional

from deepflow_tpu.controller.registry import VTapRegistry


def _weight(vtap_key: str, ingester: str) -> int:
    h = hashlib.blake2s(f"{vtap_key}|{ingester}".encode(),
                        digest_size=8).digest()
    return int.from_bytes(h, "big")


class FleetMonitor:
    def __init__(self, registry: VTapRegistry,
                 offline_after_s: float = 120.0) -> None:
        self.registry = registry
        self.offline_after_s = offline_after_s
        self._ingesters: List[str] = []
        self._lock = threading.Lock()

    # -- ingester membership ----------------------------------------------
    def set_ingesters(self, addrs: List[str]) -> None:
        with self._lock:
            self._ingesters = sorted(addrs)

    def ingesters(self) -> List[str]:
        with self._lock:
            return list(self._ingesters)

    # -- assignment --------------------------------------------------------
    def assign(self, ctrl_ip: str, host: str) -> Optional[str]:
        """The ingester this agent should ship its firehose to."""
        with self._lock:
            if not self._ingesters:
                return None
            key = f"{ctrl_ip}|{host}"
            return max(self._ingesters, key=lambda a: _weight(key, a))

    def assignments(self) -> Dict[str, List[str]]:
        with self._lock:
            ingesters = list(self._ingesters)  # one consistent snapshot
        out: Dict[str, List[str]] = {a: [] for a in ingesters}
        if not ingesters:
            return out
        for vt in self.registry.list():
            key = f"{vt.ctrl_ip}|{vt.host}"
            a = max(ingesters, key=lambda addr: _weight(key, addr))
            out[a].append(key)
        return out

    # -- liveness ----------------------------------------------------------
    def check(self, now: Optional[float] = None) -> Dict[str, List[str]]:
        now = time.time() if now is None else now
        alive, offline = [], []
        for vt in self.registry.list():
            key = f"{vt.ctrl_ip}|{vt.host}"
            if now - vt.last_seen > self.offline_after_s:
                offline.append(key)
            else:
                alive.append(key)
        return {"alive": alive, "offline": offline}

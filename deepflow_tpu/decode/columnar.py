"""Reference Python columnar decoders: pb records -> schema columns.

This is the correctness oracle and fallback; the line-rate path is the C++
decoder (deepflow_tpu.decode.native), which walks the protobuf wire format
directly into the same column layout. Mirrors the reference decode stage
(server/ingester/flow_log/decoder/decoder.go:176-192 TaggedFlow ->
L4FlowLog), but emits structure-of-arrays instead of row structs.
"""

from __future__ import annotations

import zlib
from typing import Dict, Iterable, List

import numpy as np

from deepflow_tpu.batch.schema import L4_SCHEMA, L7_SCHEMA, METRIC_SCHEMA
from deepflow_tpu.wire.gen import flow_log_pb2, metric_pb2, otel_pb2

# L7Protocol ids (reference: agent l7_protocol enum)
L7_PROTO_HTTP1 = 20
L7_PROTO_GRPC = 41
L7_PROTO_UNKNOWN = 0

_NS_PER_S = 1_000_000_000


def _fnv1a32(data: bytes) -> int:
    h = 0x811C9DC5
    for b in data:
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


def _u32(v: int) -> int:
    return v & 0xFFFFFFFF


def decode_l4_records(records: Iterable[bytes]) -> Dict[str, np.ndarray]:
    """Parse TaggedFlow records into L4_SCHEMA columns."""
    rows: List[tuple] = []
    for raw in records:
        m = flow_log_pb2.TaggedFlow()
        try:
            m.ParseFromString(raw)
        except Exception:
            continue  # skip the one bad record, keep the batch
        f = m.flow
        k = f.flow_key
        tcp = f.perf_stats.tcp
        rows.append((
            k.ip_src, k.ip_dst, k.port_src, k.port_dst, k.proto,
            k.vtap_id, f.tap_side, _u32(f.metrics_peer_src.l3_epc_id),
            _u32(f.metrics_peer_src.byte_count),
            _u32(f.metrics_peer_dst.byte_count),
            _u32(f.metrics_peer_src.packet_count),
            _u32(f.metrics_peer_dst.packet_count),
            tcp.rtt, tcp.total_retrans_count, f.close_type,
            _u32(f.start_time // _NS_PER_S),
            _u32(min(f.duration // 1000, 0xFFFFFFFF)),
        ))
    cols = L4_SCHEMA.alloc(len(rows))
    if rows:
        arr = np.array(rows, dtype=np.uint64)
        for i, (name, dt) in enumerate(L4_SCHEMA.columns):
            if dt == np.dtype(np.int32):
                cols[name][:] = arr[:, i].astype(np.uint32).view(np.int32)
            else:
                cols[name][:] = arr[:, i].astype(dt)
    return cols


def decode_l7_records(records: Iterable[bytes],
                      endpoint_dict=None) -> Dict[str, np.ndarray]:
    """Parse AppProtoLogsData records into L7_SCHEMA columns.

    String endpoints are hashed to uint32 on the host, matching the
    SmartEncoding philosophy: strings become integers before they reach the
    columnar/device domain (reference: the tagrecorder dictionary approach,
    SURVEY.md §2.3). With `endpoint_dict` (a TagDict) the hash is recorded
    reversibly; without, a raw FNV-1a is used.
    """
    rows: List[tuple] = []
    for raw in records:
        m = flow_log_pb2.AppProtoLogsData()
        try:
            m.ParseFromString(raw)
        except Exception:
            continue
        b = m.base
        endpoint = m.req.endpoint or m.req.resource or m.req.domain
        eh = endpoint_dict.encode_one(endpoint) if endpoint_dict is not None \
            else _fnv1a32(endpoint.encode())
        rows.append((
            b.ip_src, b.ip_dst, b.port_src, b.port_dst, b.protocol,
            b.head.proto, b.head.msg_type, b.vtap_id,
            eh, m.resp.status,
            _u32(b.head.rrt // 1000), _u32(m.req_len), _u32(m.resp_len),
            _u32(b.start_time // _NS_PER_S),
        ))
    cols = L7_SCHEMA.alloc(len(rows))
    if rows:
        arr = np.array(rows, dtype=np.uint64)
        for i, (name, dt) in enumerate(L7_SCHEMA.columns):
            if dt == np.dtype(np.int32):
                cols[name][:] = arr[:, i].astype(np.uint32).view(np.int32)
            else:
                cols[name][:] = arr[:, i].astype(dt)
    return cols


def decode_otel_frames(payloads: Iterable[bytes],
                       compressed: bool = False, vtap_id: int = 0,
                       endpoint_dict=None):
    """OTLP trace exports -> (L7_SCHEMA columns, bad_payload_count)
    (reference: flow_log decoder.go:219 zlib+pb decode ->
    log_data/otel.go span mapping).

    Each payload is one ExportTraceServiceRequest. Spans map like the
    reference's: name -> endpoint, duration -> rrt, OTLP status code ->
    response status (0 ok, 1 error), rpc.system/http.* attributes pick
    the l7 protocol; network peers come from net.* attributes when
    present, else 0.
    """
    rows: List[tuple] = []
    bad = 0
    for payload in payloads:
        if compressed:
            try:
                payload = zlib.decompress(payload)
            except zlib.error:
                bad += 1
                continue
        req = otel_pb2.ExportTraceServiceRequest()
        try:
            req.ParseFromString(payload)
        except Exception:
            bad += 1
            continue
        for rs in req.resource_spans:
            for ss in rs.scope_spans:
                for span in ss.spans:
                    attrs = {kv.key: kv.value for kv in span.attributes}
                    l7 = L7_PROTO_UNKNOWN
                    if "rpc.system" in attrs and \
                            attrs["rpc.system"].string_value == "grpc":
                        l7 = L7_PROTO_GRPC
                    elif any(k.startswith("http.") for k in attrs):
                        l7 = L7_PROTO_HTTP1
                    port = (int(attrs["net.peer.port"].int_value)
                            & 0xFFFF) if "net.peer.port" in attrs else 0
                    dur_us = max(span.end_time_unix_nano
                                 - span.start_time_unix_nano, 0) // 1000
                    # record the name in the endpoint dictionary so the
                    # hash is reversible at query/export time (its probing
                    # also resolves collisions, unlike a raw fnv)
                    eh = endpoint_dict.encode_one(span.name) \
                        if endpoint_dict is not None \
                        else _fnv1a32(span.name.encode())
                    rows.append((
                        0, 0, 0, port, 6, l7,
                        3,                       # msg_type: session
                        vtap_id,
                        eh,
                        1 if span.status.code == 2 else 0,
                        _u32(dur_us),
                        0, 0,
                        _u32(span.start_time_unix_nano // _NS_PER_S),
                    ))
    cols = L7_SCHEMA.alloc(len(rows))
    if rows:
        arr = np.array(rows, dtype=np.uint64)
        for i, (name, dt) in enumerate(L7_SCHEMA.columns):
            if dt == np.dtype(np.int32):
                cols[name][:] = arr[:, i].astype(np.uint32).view(np.int32)
            else:
                cols[name][:] = arr[:, i].astype(dt)
    return cols, bad


def decode_metric_records(records: Iterable[bytes]) -> Dict[str, np.ndarray]:
    """Parse metric Document records into METRIC_SCHEMA columns."""
    rows: List[tuple] = []
    for raw in records:
        d = metric_pb2.Document()
        try:
            d.ParseFromString(raw)
        except Exception:
            continue
        fld = d.tag.field
        ip = int.from_bytes(fld.ip, "big") if fld.ip else 0
        t = d.meter.flow.traffic
        p = d.meter.flow.performance
        lat = d.meter.flow.latency
        rows.append((
            d.timestamp, _u32(ip), fld.server_port, fld.vtap_id, fld.protocol,
            _u32(t.packet_tx), _u32(t.packet_rx),
            _u32(t.byte_tx), _u32(t.byte_rx),
            _u32(t.new_flow), _u32(t.closed_flow), t.syn, t.synack,
            _u32(p.retrans_tx), _u32(p.retrans_rx),
            _u32(lat.rtt_sum), lat.rtt_count,
        ))
    cols = METRIC_SCHEMA.alloc(len(rows))
    if rows:
        arr = np.array(rows, dtype=np.uint64)
        for i, (name, dt) in enumerate(METRIC_SCHEMA.columns):
            cols[name][:] = arr[:, i].astype(dt)
    return cols
